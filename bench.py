"""Headline benchmark: batched wildcard route matching on one chip.

Reproduces BASELINE.json config 3 by default: ~1M mixed `+`/`#` wildcard
subscriptions, Zipf-skewed publish stream, batch-matched on the device.
North star (BASELINE.md): 1M publishes/s routed with p99 match < 1 ms.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
Extra detail goes to BENCH_DETAILS.json, never stdout.
"""

import json
import os
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    import numpy as np

    import jax

    from emqx_tpu import topic as T
    from emqx_tpu.ops.automaton import build_automaton
    from emqx_tpu.ops.dictionary import TokenDict, encode_topics
    from emqx_tpu.ops.match_kernel import match_batch

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)
    n_subs = int(os.environ.get("BENCH_SUBS", 1_000_000 if on_tpu else 50_000))
    batch = int(os.environ.get("BENCH_BATCH", 4096))
    iters = int(os.environ.get("BENCH_ITERS", 50 if on_tpu else 10))
    f_width = int(os.environ.get("BENCH_F", 16))
    m_cap = int(os.environ.get("BENCH_M", 128))
    max_levels = 16
    rng = np.random.default_rng(0)

    log(f"platform={platform} subs={n_subs} batch={batch} iters={iters}")

    # --- subscription set: fleet-telemetry-style mixed wildcards -------
    t0 = time.perf_counter()
    n_vehicles = max(n_subs // 2, 1)
    filters = []
    for i in range(n_subs):
        kind = i % 10
        if kind < 5:  # vehicles/<id>/sensors/#
            filters.append((i, ("vehicles", f"v{i % n_vehicles}", "sensors", "#")))
        elif kind < 7:
            filters.append((i, ("dev", f"g{i % 997}", "+", f"d{i % 4999}")))
        elif kind < 9:
            filters.append((i, ("site", "+", "floor", f"f{i % 331}", "#")))
        else:
            filters.append((i, ("alerts", f"z{i % 53}", "+", "+")))
    gen_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    tdict = TokenDict()
    aut = build_automaton(filters, tdict, max_levels=max_levels)
    build_s = time.perf_counter() - t0
    log(
        f"built automaton: nodes={aut.n_nodes} buckets={len(aut.ht_rows)} "
        f"probes={aut.probes} kernel_levels={aut.kernel_levels} "
        f"in {build_s:.2f}s (gen {gen_s:.2f}s)"
    )

    # --- publish stream: Zipf-skewed over the vehicle fleet ------------
    zipf = rng.zipf(1.3, size=batch * iters) % n_vehicles
    streams = []
    for it in range(iters):
        topics = []
        for j in range(batch):
            i = it * batch + j
            k = i % 10
            if k < 6:
                topics.append(("vehicles", f"v{zipf[i]}", "sensors", "temp"))
            elif k < 8:
                topics.append(("dev", f"g{i % 997}", "x", f"d{i % 4999}"))
            elif k < 9:
                topics.append(("site", f"s{i % 7}", "floor", f"f{i % 331}", "a"))
            else:
                topics.append(("nomatch", f"q{i}"))
        streams.append(encode_topics(tdict, topics, aut.kernel_levels))

    dev_tables = tuple(jax.device_put(a) for a in aut.device_arrays())

    def run(tokens, lengths, dollar):
        return match_batch(
            *dev_tables,
            tokens,
            lengths,
            dollar,
            probes=aut.probes,
            f_width=f_width,
            m_cap=m_cap,
        )

    # warmup / compile
    t0 = time.perf_counter()
    codes, counts, ovf = run(*streams[0])
    counts.block_until_ready()
    log(f"compile+first batch: {time.perf_counter() - t0:.2f}s; "
        f"ovf={int(np.asarray(ovf).sum())} "
        f"mean_matches={float(np.asarray(counts).mean()):.2f}")

    lat = []
    t_start = time.perf_counter()
    for s in streams:
        t0 = time.perf_counter()
        codes, counts, ovf = run(*s)
        counts.block_until_ready()
        lat.append(time.perf_counter() - t0)
    elapsed = time.perf_counter() - t_start

    total_topics = batch * iters
    rate = total_topics / elapsed
    lat_ms = np.array(lat) * 1e3
    p50, p99 = np.percentile(lat_ms, [50, 99])
    per_topic_p99_us = p99 * 1e3 / batch
    details = {
        "platform": platform,
        "n_subs": n_subs,
        "batch": batch,
        "iters": iters,
        "build_s": build_s,
        "nodes": aut.n_nodes,
        "probes": aut.probes,
        "rate_topics_per_s": rate,
        "batch_latency_ms_p50": float(p50),
        "batch_latency_ms_p99": float(p99),
        "per_topic_amortized_us_p99": float(per_topic_p99_us),
        "overflow_frac": float(np.asarray(ovf).mean()),
        "mean_matches_per_topic": float(np.asarray(counts).mean()),
    }
    with open(os.path.join(os.path.dirname(__file__) or ".", "BENCH_DETAILS.json"), "w") as f:
        json.dump(details, f, indent=2)
    log(json.dumps(details))

    print(
        json.dumps(
            {
                "metric": "wildcard_topic_matches_per_sec_per_chip",
                "value": round(rate, 1),
                "unit": f"topics/s @ {n_subs} wildcard subs (batch p99 {p99:.2f} ms)",
                "vs_baseline": round(rate / 1_000_000, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
