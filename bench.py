"""Headline benchmark: batched wildcard route matching on one chip.

Reproduces BASELINE.json configs 3-4: up to 10M mixed `+`/`#` wildcard
subscriptions, Zipf-skewed fan-out-heavy publish stream.  North star
(BASELINE.md): 1M publishes/s routed with p99 match < 1 ms.

Honest full-path timing (VERDICT r1 weak #2): the clock covers
topic-string tokenization, device match, device-side CSR expansion to
filter positions, and materializing host-visible fid arrays — i.e.
everything `emqx_router:match_routes/1` does per publish
(/root/reference/apps/emqx/src/emqx_router.erl:205-212), batched.

Also reports InsertRps measured concurrently with matching (the
reference's own micro-bench shape, apps/emqx/src/emqx_broker_bench.erl:
25-35) against a MatchEngine with background rebuild.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
Extra detail goes to BENCH_DETAILS.json, never stdout.
"""

import asyncio
import json
import os
import sys
import time


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def make_filters(n_subs, fanout):
    """Fleet-telemetry-style wildcard set with ~`fanout` subscribers per
    matched topic across every filter family (fan-out heavy per VERDICT
    r1, but with distinctness scaling with n_subs so fan-out stays at
    the configured level instead of exploding at 10M)."""
    n_vehicles = max(n_subs // (2 * fanout), 1)
    n_dev = max(n_subs // (5 * fanout), 1)
    n_site = max(n_subs // (5 * fanout), 1)
    n_alert = max(n_subs // (10 * fanout), 1)
    filters = []
    for i in range(n_subs):
        kind = i % 10
        if kind < 5:  # fanout x subscribers share each vehicle
            filters.append((i, ("vehicles", f"v{i % n_vehicles}", "sensors", "#")))
        elif kind < 7:
            filters.append((i, ("dev", f"g{i % n_dev}", "+", f"d{i % 7}")))
        elif kind < 9:
            filters.append((i, ("site", "+", "floor", f"f{i % n_site}", "#")))
        else:
            filters.append((i, ("alerts", f"z{i % n_alert}", "+", "+")))
    return filters, (n_vehicles, n_dev, n_site, n_alert)


def make_topics(rng, n, pops):
    n_vehicles, n_dev, n_site, n_alert = pops
    zipf = rng.zipf(1.3, size=n) % max(n_vehicles, 1)
    topics = []
    for i in range(n):
        k = i % 10
        if k < 6:
            topics.append(f"vehicles/v{zipf[i]}/sensors/temp")
        elif k < 8:
            topics.append(f"dev/g{i % n_dev}/x/d{i % 7}")
        elif k < 9:
            topics.append(f"site/s{i % 7}/floor/f{i % n_site}/a")
        else:
            topics.append(f"nomatch/q{i}")
    return topics


def measure_insert_rps(base_filters, n_insert, log):
    """InsertRps into a live MatchEngine (background rebuild on) while a
    match stream keeps running — no stop-the-world allowed."""
    from emqx_tpu.engine import MatchEngine

    eng = MatchEngine(
        max_levels=16,
        rebuild_threshold=65536,
        background_rebuild=True,
        use_device=True,
    )
    for fid, ws in base_filters:
        eng._wild.insert("/".join(ws), fid)
        eng._by_fid[fid] = "/".join(ws)
    eng.rebuild()
    probe = [f"vehicles/v{i}/sensors/temp" for i in range(16)]
    eng.match_batch(probe)  # compile the base kernel
    # warm every delta-automaton shape class the timed run will touch
    # (first folds + XLA compiles are one-time costs a live broker pays
    # at boot, not steady churn): insert as many dummies as the run
    # will, matching at geometric points so each capacity class compiles
    n_warm = min(n_insert, 120_000)
    step = max(n_warm // 8, 1)
    for i in range(n_warm):
        eng.insert(f"warm/{i % 31}/+/w{i}", -1 - i)
        if i % step == step - 1:
            eng.match_batch(probe)
    eng.match_batch(probe)
    for i in range(n_warm):
        eng.delete(-1 - i)
    eng.rebuild()  # reset to a clean base; delta tier re-warms from hot cache
    eng.match_batch(probe)

    # the 10M-sub phases leave gigabytes of static Python objects;
    # gen-2 collections rescanning them mid-churn cost 100+ ms pauses
    # (the reference tunes BEAM GC for the same reason — fullsweep /
    # emqx_gc policies).  Freeze the static heap for the timed region.
    import gc

    gc.collect()
    gc.freeze()

    nxt = len(base_filters)
    t0 = time.perf_counter()
    match_time = 0.0
    match_lat = []
    # route ops arrive in windows, as the reference's router syncer
    # batches them (?MAX_BATCH_SIZE 1000, emqx_router_syncer.erl:58):
    # insert_many is the engine's equivalent of one syncer batch
    window = 512
    for w0 in range(0, n_insert, window):
        eng.insert_many([
            (f"ins/{i % 4099}/+/x{i}", nxt + i)
            for i in range(w0, min(w0 + window, n_insert))
        ])
        if (w0 // window) % 4 == 3:  # match stream stays hot mid-churn
            m0 = time.perf_counter()
            eng.match_batch(probe)
            dt = time.perf_counter() - m0
            match_time += dt
            match_lat.append(dt)
    el = time.perf_counter() - t0 - match_time
    gc.unfreeze()
    rps = n_insert / el
    import numpy as _np

    lat_ms = _np.array(match_lat or [0.0]) * 1e3
    p50, p99 = _np.percentile(lat_ms, [50, 99])
    log(
        f"insert: {n_insert} inserts in {el:.2f}s -> {rps:,.0f}/s "
        f"(interleaved {len(match_lat)} match batches, p50 {p50:.1f} ms "
        f"p99 {p99:.1f} ms, stats={eng.index_stats()})"
    )
    # drain the engine's background build/fold threads: leaking them
    # into the next bench phase steals GIL from its measurement
    for tname in ("_build_thread", "_fold_thread"):
        t = getattr(eng, tname, None)
        if t is not None and t.is_alive():
            t.join(120)
    eng._poll_swap()
    return rps, float(p50), float(p99)


def run_dispatch_fanout_bench(log):
    """Dispatch-half microbench: fixed fan-out sweep (1 / 16 / 256
    subscribers per message) through the REAL window pipeline —
    publish_many → CSR expansion → per-client grouping →
    single-encode → corked per-connection write — with wire encode +
    write counted (each channel's send serializes every packet and
    appends to a sink, exactly Connection._send_packets minus the
    socket).  Host matching (the match half has its own benches);
    QoS 0 subscribers so the clock sees fan-out, not ack windows.

    Reports routed msg/s per fan-out level as
    ``dispatch_fanout_msgs_per_s``."""
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.broker.session import SubOpts
    from emqx_tpu.codec import mqtt as C
    from emqx_tpu.config import BrokerConfig
    from emqx_tpu.message import Message

    window = 64
    n_for = {1: 20000, 16: 4000, 256: 500}
    out = {}

    def setup(fanout, qos, label, max_inflight=None):
        """One broker + `fanout` subscribed channels writing into a
        byte/write-count sink."""
        cfg = BrokerConfig()
        cfg.engine.use_device = False
        b = Broker(config=cfg)
        sink = [0, 0]  # bytes written, write calls

        def send(pkts):
            data = b"".join(C.serialize(p, C.MQTT_V5) for p in pkts)
            sink[0] += len(data)
            sink[1] += 1

        flt = f"fan/{label}"
        kw = {} if max_inflight is None else {
            "max_inflight": max_inflight
        }
        for i in range(fanout):
            ch = Channel(b, send=send, close=lambda r: None)
            cid = f"f{label}-{i}"
            session, _ = b.cm.open_session(True, cid, ch, **kw)
            session.subscribe(flt, SubOpts(qos=qos))
            b.subscribe(cid, flt, SubOpts(qos=qos))
        return b, sink, flt

    def pump(b, flt, fanout, qos):
        """Warm, then route n_for windows; returns (rate, stages)."""
        n = n_for[fanout] if fanout in n_for else n_for[256]
        msgs = [Message(topic=flt, payload=b"x" * 64, qos=qos)
                for _ in range(n)]
        b.publish_many(msgs[:window])  # warm
        t0 = time.perf_counter()
        total = 0
        for w0 in range(window, n, window):
            w = msgs[w0:w0 + window]
            # stamp at "ingress": pre-built messages would otherwise
            # age across the run and trip the slow-subs scan on every
            # delivery — a harness artifact production never pays
            now = time.time()
            for m in w:
                m.timestamp = now
            total += sum(b.publish_many(w))
        dt = time.perf_counter() - t0
        routed = n - window
        assert total == routed * fanout, (total, routed * fanout)
        # the profiler rides the instrumented hot path (its shipping
        # default): per-stage p50/p99 says WHERE window time goes, not
        # just msg/s.  "e2e" is excluded: this harness constructs all
        # messages (timestamp-stamped) BEFORE the timed loop, so its
        # e2e samples measure time-since-bench-start, not delivery
        # latency — the broker e2e bench stamps at ingest and reports
        # the real number
        stages = {}
        for name, snap in b.profiler.snapshots().items():
            if snap.count and name != "e2e":
                stages[name] = {
                    "count": snap.count,
                    "p50_us": round(snap.percentile(50), 1),
                    "p99_us": round(snap.percentile(99), 1),
                }
        return routed / dt, routed, dt, stages

    def report(tag, fanout, rate, routed, dt, stages, sink):
        stage_str = " ".join(
            f"{k}={v['p50_us']:.0f}us"
            for k, v in sorted(stages.items())
            if k in ("expand", "decide", "deliver", "assemble",
                     "flush", "match_submit")
        )
        log(
            f"dispatch fanout {tag}: {rate:,.0f} msg/s "
            f"({routed * fanout / dt:,.0f} deliveries/s, "
            f"{sink[1]} writes, {sink[0] / (1 << 20):.1f} MiB; "
            f"stage p50 {stage_str})"
        )

    for fanout in (1, 16, 256):
        b, sink, flt = setup(fanout, qos=0, label=str(fanout))
        rate, routed, dt, stages = pump(b, flt, fanout, qos=0)
        out[f"fanout_{fanout}"] = rate
        out[f"fanout_{fanout}_stages"] = stages
        report(str(fanout), fanout, rate, routed, dt, stages, sink)

    # QoS1 row: the per-delivery session bookkeeping (packet-id
    # alloc, inflight insert, pid splice into the shared body) that
    # QoS0 fan-out never exercises — the half PR 5's native assembly
    # + block bookkeeping attack.  Unbounded inflight (the clients
    # never ack): the clock sees assembly, not window backpressure.
    # Since PR 9 this row registers a no-op `message.delivered` hook:
    # it measures the HOOK-CONSUMER case (per-run delivery lists
    # materialized for the callback), directly comparable to the
    # always-materializing pre-PR9 path.
    b, sink, flt = setup(256, qos=1, label="256q1", max_inflight=0)
    b.hooks.add("message.delivered", lambda cid, ds: None)
    rate, routed, dt, stages = pump(b, flt, 256, qos=1)
    out["fanout_256_qos1"] = rate
    out["fanout_256_qos1_stages"] = stages
    report("256 qos1", 256, rate, routed, dt, stages, sink)

    # the no-hooks twin: nothing consumes per-delivery lists, so the
    # window skips the hook walk AND the delivery-tuple
    # materialization — the lazy-deliveries win shows up as the gap
    # between this row and fanout_256_qos1
    b, sink, flt = setup(256, qos=1, label="256q1nh", max_inflight=0)
    rate, routed, dt, stages = pump(b, flt, 256, qos=1)
    out["fanout_256_qos1_nohooks"] = rate
    out["fanout_256_qos1_nohooks_stages"] = stages
    report("256 qos1 nohooks", 256, rate, routed, dt, stages, sink)
    out["note"] = (
        "publish_many windows of 64, QoS0, 64 B payloads stamped at "
        "ingress, host matching; encode+write counted (every packet "
        "serialized into a per-connection sink).  Pre-PR3 "
        "per-subscriber dispatch on this harness: fanout 1 -> "
        "33,314, 16 -> 4,709, 256 -> 267 msg/s (one transport write "
        "per delivery); PR3's window path (CSR expand -> encode-once "
        "-> corked flush) must hold fanout 256 at >= 3x that 267 "
        "baseline, and PR5's native assemble path (per-run decision "
        "scan -> GIL-released arena splice, the 'assemble' sub-stage) "
        "must hold >= 2x the PR4 number on the same box.  PR9 adds "
        "the 'decide' stage (window decision columns) and the "
        "fanout_256_qos1_nohooks row (lazy delivery lists: "
        "fanout_256_qos1 registers a no-op delivered hook, the "
        "nohooks row does not)."
    )
    return out


def run_replay_bench(log, n_sessions=256, n_backlog=64,
                     storm_sessions=2000):
    """Durable-replay bench (the mass-reconnect scenario): N
    checkpointed sessions, each owed an M-message QoS1 backlog from
    shared streams, reconnect and drain through the resume scheduler.

    ``replay_sessions_per_s``: scalar (per-session mqueue bake +
    per-packet encode) vs windowed (batched multi-session DS reads +
    dispatch windows through decide columns / encode-once / native
    splice) on identical worlds — run interleaved by the caller for
    A/B medians.  Encode+write counted exactly like the fanout bench
    (every packet serialized into a per-connection sink).

    ``reconnect_storm``: a larger storm with live publishes
    interleaved between scheduler rounds — drain wall time, live
    delivery p50/p99 while draining, and the max parked depth."""
    import shutil
    import tempfile

    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.broker.session import SubOpts
    from emqx_tpu.codec import mqtt as C
    from emqx_tpu.config import BrokerConfig
    from emqx_tpu.ds.persist import DurableSessions
    from emqx_tpu.message import Message

    def seed(data_dir, n_sess, n_msgs):
        ds = DurableSessions(str(data_dir))
        t0 = time.time() - 60.0
        for i in range(n_sess):
            ds.save(f"r{i}", {"r/#": {"qos": 1}}, 7200.0, now=t0)
        ds.add_filter("r/#")
        # shared streams: every session replays the SAME backlog (the
        # broadcast-outage shape where windowed reads coalesce)
        ds.persist([
            Message(topic=f"r/{k % 8}/x", qos=1, payload=b"x" * 64,
                    timestamp=time.time())
            for k in range(n_msgs)
        ])
        ds.sync()
        ds.close()

    def drain(data_dir, n_sess, mode):
        """``scalar`` = the pre-scheduler shape (per-session
        `replay_chunk` reads, no sharing, mqueue bake + per-packet
        encode — what the resume loop did before this subsystem);
        ``sched_scalar`` = the scheduler pacing the SAME mqueue path
        with batched reads; ``windowed`` = batched reads + dispatch
        windows through decide columns / encode-once / native
        splice."""
        cfg = BrokerConfig()
        cfg.engine.use_device = False
        cfg.durable.enable = True
        cfg.durable.data_dir = str(data_dir)
        cfg.durable.resume.windowed = mode == "windowed"
        cfg.durable.resume.max_concurrent = 64
        cfg.durable.resume.park_queue_cap = n_sess
        b = Broker(config=cfg)
        scheduled = mode != "scalar"
        if scheduled:
            b.resume.running = True
        sink = [0, 0]

        def send(pkts):
            data = b"".join(C.serialize(p, C.MQTT_V5) for p in pkts)
            sink[0] += len(data)
            sink[1] += 1

        cids = [f"r{i}" for i in range(n_sess)]
        t0 = time.perf_counter()
        for cid in cids:
            ch = Channel(b, send=send, close=lambda r: None)
            ch.version = C.MQTT_V5
            session, present = b.open_session(
                False, cid, ch, expiry_interval=7200.0, max_inflight=0
            )
            assert present
            if not scheduled:
                # the legacy flow: replay filled the mqueue inside
                # open_session; CONNACK is followed by resume()
                ch.send_packets(session.resume())
        rounds = 0
        if scheduled:
            while any(b.resume.pending(c) for c in cids):
                b.resume.drain_once()
                rounds += 1
        dt = time.perf_counter() - t0
        sent = b.metrics.all().get("messages.sent", 0)
        stages = {}
        for name, snap in b.profiler.snapshots().items():
            if snap.count and name in (
                "replay_read", "expand", "decide", "deliver",
                "assemble", "flush",
            ):
                stages[name] = {
                    "count": snap.count,
                    "p50_us": round(snap.percentile(50), 1),
                    "p99_us": round(snap.percentile(99), 1),
                }
        b.durable.close()
        return n_sess / dt, sent, dt, rounds, stages, sink

    out = {}
    for tag in ("scalar", "sched_scalar", "windowed"):
        d = tempfile.mkdtemp(prefix=f"replay_{tag}_")
        try:
            seed(d, n_sessions, n_backlog)
            rate, sent, dt, rounds, stages, sink = drain(
                d, n_sessions, tag
            )
            assert sent >= n_sessions * n_backlog, (sent, tag)
            out[f"replay_sessions_per_s_{tag}"] = rate
            out[f"replay_{tag}_stages"] = stages
            log(
                f"replay {tag}: {rate:,.1f} sessions/s "
                f"({n_sessions} sessions x {n_backlog} qos1 msgs in "
                f"{dt:.2f}s, {rounds} rounds, {sent:,} deliveries, "
                f"{sink[0] / (1 << 20):.1f} MiB wire)"
            )
        finally:
            shutil.rmtree(d, ignore_errors=True)
    if out.get("replay_sessions_per_s_scalar"):
        out["replay_windowed_vs_scalar"] = (
            out["replay_sessions_per_s_windowed"]
            / out["replay_sessions_per_s_scalar"]
        )

    # reconnect storm: drain a big park queue while live publishes
    # measure event-loop availability between scheduler rounds
    d = tempfile.mkdtemp(prefix="replay_storm_")
    try:
        seed(d, storm_sessions, 8)
        cfg = BrokerConfig()
        cfg.engine.use_device = False
        cfg.durable.enable = True
        cfg.durable.data_dir = d
        cfg.durable.resume.max_concurrent = 64
        cfg.durable.resume.park_queue_cap = storm_sessions
        b = Broker(config=cfg)
        b.resume.running = True
        sink = [0]

        def send2(pkts):
            sink[0] += sum(
                len(C.serialize(p, C.MQTT_V5)) for p in pkts
            )

        cids = [f"r{i}" for i in range(storm_sessions)]
        for cid in cids:
            ch = Channel(b, send=send2, close=lambda r: None)
            ch.version = C.MQTT_V5
            b.open_session(False, cid, ch, expiry_interval=7200.0,
                           max_inflight=0)
        parked_max = b.resume.info()["parked"]
        live_ch = Channel(b, send=send2, close=lambda r: None)
        live_ch.version = C.MQTT_V5
        ls, _ = b.cm.open_session(True, "live", live_ch)
        ls.subscribe("live/x", SubOpts(qos=0))
        b.subscribe("live", "live/x", SubOpts(qos=0))
        live_lat = []
        pending = set(cids)
        t0 = time.perf_counter()
        rounds = 0
        while pending:
            b.resume.drain_once()
            rounds += 1
            if rounds % 5 == 0:
                t1 = time.perf_counter()
                b.publish_many([Message(
                    topic="live/x", qos=0, payload=b"hb",
                    timestamp=time.time(),
                )])
                live_lat.append(time.perf_counter() - t1)
            if rounds % 50 == 0 or len(pending) < 128:
                pending = {c for c in pending
                           if b.resume.pending(c)}
        storm_dt = time.perf_counter() - t0
        live_lat.sort()
        out["reconnect_storm"] = {
            "sessions": storm_sessions,
            "backlog_per_session": 8,
            "drain_s": storm_dt,
            "sessions_per_s": storm_sessions / storm_dt,
            "parked_max": parked_max,
            "live_publish_p50_ms": (
                live_lat[len(live_lat) // 2] * 1e3 if live_lat else 0
            ),
            "live_publish_p99_ms": (
                live_lat[int(len(live_lat) * 0.99)] * 1e3
                if live_lat else 0
            ),
        }
        log(
            f"reconnect storm: {storm_sessions} sessions drained in "
            f"{storm_dt:.2f}s "
            f"({storm_sessions / storm_dt:,.0f} sessions/s), "
            f"parked_max={parked_max}, live publish p99 "
            f"{out['reconnect_storm']['live_publish_p99_ms']:.1f} ms"
        )
        b.durable.close()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return out


def run_durability_bench(log, iters=None, n_msgs=None,
                         recovery_msgs=None, write_json=True):
    """Durability A/B (BENCH_r12, the PR 15 tentpole): persistent-
    session QoS1 publish throughput under the four fsync disciplines —

      * ``never``      no fsync anywhere (the pre-PR hot path);
      * ``interval``   periodic group flush off the tick (acks free);
      * ``always``     group-commit: ONE fsync amortized per dispatch
                       window before the window's acks release;
      * ``naive``      the counterfactual the group commit exists to
                       beat: fsync per MESSAGE (window size 1).

    Interleaved iterations, medians reported.  The acceptance bar:
    ``always`` >= 5x ``naive`` and ``interval`` within ~10% of
    ``never`` (no robustness tax on the default).

    Plus cold-recovery numbers on a >=1M-message store: native
    segment-scan reopen (index rebuild) and the full census rebuild
    after metadata loss (the log-is-source-of-truth path).
    """
    import shutil
    import statistics
    import tempfile

    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.config import BrokerConfig
    from emqx_tpu.ds.builtin_local import LocalStorage
    from emqx_tpu.ds.native import DsLog
    from emqx_tpu.message import Message

    iters = iters or int(os.environ.get("BENCH_DUR_ITERS", "5"))
    n_msgs = n_msgs or int(os.environ.get("BENCH_DUR_MSGS", "2048"))
    recovery_msgs = recovery_msgs or int(
        os.environ.get("BENCH_DUR_RECOVERY_MSGS", "1000000")
    )
    window = 64

    def one_run(mode):
        """One measured pass: a detached persistent subscriber's
        filter arms the gate, the publisher pushes QoS1 windows
        through publish_many (the loop-less group-commit path: in
        `always` mode each window ends with its covering flush, the
        contract a socketed PUBACK rides)."""
        d = tempfile.mkdtemp(prefix=f"dur_{mode}_")
        try:
            cfg = BrokerConfig()
            cfg.engine.use_device = False
            cfg.durable.enable = True
            cfg.durable.data_dir = d
            cfg.durable.fsync = "always" if mode == "naive" else mode
            b = Broker(config=cfg)
            b.durable.save(
                "psub", {"bench/#": {"qos": 1}}, 7200.0,
                now=time.time() - 30.0,
            )
            b.durable.add_filter("bench/#")
            win = 1 if mode == "naive" else window
            payload = b"x" * 64
            msgs = [
                Message(
                    topic=f"bench/{i % 128}/t", qos=1,
                    payload=payload, timestamp=time.time(),
                )
                for i in range(n_msgs)
            ]
            t0 = time.perf_counter()
            for off in range(0, n_msgs, win):
                b.publish_many(msgs[off:off + win])
            dt = time.perf_counter() - t0
            syncs = b.durable.gate.sync_count
            stored = b.durable.storage.stats()["messages"]
            assert stored == n_msgs, (mode, stored)
            if mode in ("always", "naive"):
                assert not b.durable.gate.dirty  # acked => flushed
                assert syncs >= (n_msgs // win)
            b.durable.close()
            return n_msgs / dt, syncs
        finally:
            shutil.rmtree(d, ignore_errors=True)

    modes = ("never", "interval", "always", "naive")
    rates = {m: [] for m in modes}
    syncs = {m: 0 for m in modes}
    for it in range(iters):
        for m in modes:  # interleaved: drift hits every mode equally
            r, s = one_run(m)
            rates[m].append(r)
            syncs[m] = s
        log(
            f"durability iter {it}: " + ", ".join(
                f"{m}={rates[m][-1]:,.0f}/s" for m in modes
            )
        )
    med = {m: statistics.median(rates[m]) for m in modes}
    out = {
        "publish_qos1_msgs_per_s": {m: med[m] for m in modes},
        "syncs_per_run": syncs,
        "always_vs_naive": med["always"] / med["naive"],
        "interval_vs_never": med["interval"] / med["never"],
        "window": window,
        "n_msgs": n_msgs,
        "iters": iters,
    }
    log(
        f"durability medians: never={med['never']:,.0f} "
        f"interval={med['interval']:,.0f} always={med['always']:,.0f} "
        f"naive={med['naive']:,.0f} msg/s; always/naive="
        f"{out['always_vs_naive']:.1f}x (>=5x bar), interval/never="
        f"{out['interval_vs_never']:.2f} (~0.9+ bar)"
    )

    # ---- cold recovery on a >=1M-message store (log scan + census
    # rebuild after metadata loss)
    d = tempfile.mkdtemp(prefix="dur_recovery_")
    try:
        store = LocalStorage(d, n_streams=16)
        payload = b"r" * 16
        t_fill0 = time.perf_counter()
        batch = 4096
        msgs = [
            Message(
                topic=f"f/{i % 512}/t", qos=1, payload=payload,
                timestamp=1e9 + i,
            )
            for i in range(batch)
        ]
        filled = 0
        while filled < recovery_msgs:
            store.store_batch(msgs[: min(batch, recovery_msgs - filled)])
            filled += batch
        store.sync()
        store.close()
        fill_dt = time.perf_counter() - t_fill0
        size_mb = sum(
            os.path.getsize(os.path.join(d, f))
            for f in os.listdir(d)
        ) / (1 << 20)
        # clean reopen: native segment scan rebuilds the (stream, ts)
        # index; the census cache is valid and skips the decode pass
        t0 = time.perf_counter()
        store = LocalStorage(d, n_streams=16)
        open_clean_s = time.perf_counter() - t0
        n = store.stats()["messages"]
        store.close()
        # metadata loss: census gone — the log is the source of truth
        # and the census rebuild decodes every record (it runs in the
        # background now; rebuild_now() joins so the decode pass is
        # what the timer sees)
        os.unlink(os.path.join(d, "census.json"))
        t0 = time.perf_counter()
        store = LocalStorage(d, n_streams=16)
        store.rebuild_now()
        rebuild_s = time.perf_counter() - t0
        assert store.stats()["messages"] == n >= recovery_msgs
        store.close()
        # native-only recovery floor (no census logic at all)
        t0 = time.perf_counter()
        lg = DsLog(d)
        native_open_s = time.perf_counter() - t0
        lg.close()
        out["cold_recovery"] = {
            "messages": int(n),
            "store_mb": round(size_mb, 1),
            "fill_s": round(fill_dt, 2),
            "native_open_s": round(native_open_s, 3),
            "open_clean_s": round(open_clean_s, 3),
            "census_rebuild_s": round(rebuild_s, 2),
        }
        log(
            f"cold recovery: {n:,} msgs ({size_mb:.0f} MiB) — native "
            f"open {native_open_s:.2f}s, clean open {open_clean_s:.2f}s, "
            f"census rebuild after meta loss {rebuild_s:.1f}s"
        )
    finally:
        shutil.rmtree(d, ignore_errors=True)

    if write_json:
        path = os.path.join(
            os.path.dirname(__file__) or ".", "BENCH_r12.json"
        )
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
    return out


def run_ds_shard_bench(log, iters=None, n_msgs=None,
                       recovery_msgs=None, write_json=True):
    """Sharded DS store A/B (BENCH_r13, the PR 16 tentpole): three
    measurements —

      * APPEND THROUGHPUT at 1/2/4 shards with ``always`` semantics
        (every window fsynced before the next): four writer threads
        drive the segment engine directly — the layer sharding
        changes.  One shard = every writer serializes on ONE store
        mutex, which is held ACROSS the fsync (dslog.cpp), so appends
        stall for the whole flush; N shards = N independent mutexes
        and fsync barriers whose IO waits overlap.  Two interleaved
        configs, medians of interleaved iterations:

          - ``io_bound`` (the acceptance row): 4 KiB records, window
            2 — commit wait dominates, so the per-shard barrier
            independence is what the clock sees.  Bar: 4 shards >=
            2x one shard.
          - ``cpu_bound`` (the honest counterpoint): 96 B records,
            window 16 — per-record CPU dominates, and THE BENCH BOX
            HAS ONE CORE, so the only parallelism sharding can add
            is fsync-wait/append overlap; the ratio compresses
            toward 1x as CPU share grows.  On a multi-core box this
            row scales too (the flushes run truly in parallel); a
            1-core box bounds any workload's speedup by
            (cpu + io) / max(cpu, io).

        The session layer above the engine (encode, census journal,
        gate bookkeeping) is shard-independent CPU and identical in
        both columns; driving it here would only dilute the A/B with
        a constant.
      * RESTART-TO-SERVING on a 1M-message 4-shard store, three
        metadata states: intact (snapshot folded, journal empty — the
        O(1)-ish fast path, bar: < 2 s), journal-replay (crash after
        a flush, before the fold: snapshot + journal + per-stream
        delta scan from the watermark — O(delta)), and full rebuild
        after metadata loss (every record decoded; runs in the
        background, so both time-to-serving and time-to-complete are
        reported).
      * GC RECLAIM RATE under live appends: retention passes
        interleave with an appending writer; reclaimed records/s plus
        proof the writer never stalls.
    """
    import concurrent.futures
    import shutil
    import statistics
    import tempfile
    import threading

    from emqx_tpu.ds.native import DsLog
    from emqx_tpu.ds.sharded import ShardedStorage
    from emqx_tpu.message import Message

    iters = iters or int(os.environ.get("BENCH_SHARD_ITERS", "9"))
    n_msgs = n_msgs or int(os.environ.get("BENCH_SHARD_MSGS", "4096"))
    recovery_msgs = recovery_msgs or int(
        os.environ.get("BENCH_SHARD_RECOVERY_MSGS", "1000000")
    )
    n_threads = 4

    def one_run(n_shards, window, recsize, total):
        d = tempfile.mkdtemp(prefix=f"shard{n_shards}_")
        try:
            logs = [
                DsLog(os.path.join(d, f"shard-{i:02d}"))
                for i in range(n_shards)
            ]
            per = total // n_threads
            rec = b"x" * recsize

            def writer(tid):
                lg = logs[tid % n_shards]
                for i in range(0, per, window):
                    for j in range(window):
                        lg.append(tid, 1_000_000 + i + j, rec)
                    lg.sync()  # the always-mode fsync barrier

            t0 = time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(n_threads) as ex:
                list(ex.map(writer, range(n_threads)))
            dt = time.perf_counter() - t0
            for lg in logs:
                lg.close()
            return total / dt
        finally:
            shutil.rmtree(d, ignore_errors=True)

    shard_counts = (1, 2, 4)
    configs = {
        "io_bound": dict(window=2, recsize=4096, total=n_msgs),
        "cpu_bound": dict(window=16, recsize=96, total=n_msgs * 2),
    }
    rates = {c: {n: [] for n in shard_counts} for c in configs}
    for it in range(iters):
        for cfg, kw in configs.items():
            for n in shard_counts:  # interleaved: drift hits all
                rates[cfg][n].append(one_run(n, **kw))
        log(
            f"ds_shard iter {it}: " + "; ".join(
                cfg + " " + ", ".join(
                    f"{n}={rates[cfg][n][-1]:,.0f}/s"
                    for n in shard_counts
                )
                for cfg in configs
            )
        )
    out = {"writer_threads": n_threads, "iters": iters}
    for cfg, kw in configs.items():
        med = {n: statistics.median(rates[cfg][n]) for n in shard_counts}
        out["append_" + cfg] = {
            **{str(n) + "_shard_msgs_per_s": med[n]
               for n in shard_counts},
            "shards4_vs_1": med[4] / med[1],
            **kw,
        }
        log(
            f"ds_shard {cfg} medians: 1={med[1]:,.0f} "
            f"2={med[2]:,.0f} 4={med[4]:,.0f} msg/s; "
            f"4/1={med[4] / med[1]:.2f}x"
            + (" (>=2x bar)" if cfg == "io_bound" else "")
        )

    # ---- restart-to-serving at 1M messages, three metadata states
    d = tempfile.mkdtemp(prefix="shard_recovery_")
    try:
        n_shards = 4
        st = ShardedStorage(d, n_shards=n_shards, layout="hash")
        payload = b"r" * 16
        batch = 4096
        t_fill0 = time.perf_counter()
        filled = 0
        while filled < recovery_msgs:
            n = min(batch, recovery_msgs - filled)
            st.store_batch([
                Message(topic=f"f/{(filled + i) % 512}/t", qos=1,
                        payload=payload, timestamp=1e9 + filled + i)
                for i in range(n)
            ])
            filled += n
        st.sync_data()
        st.save_meta()
        fill_dt = time.perf_counter() - t_fill0
        st.close()  # folds every shard's journal into its snapshot

        # 1: metadata intact — snapshot + empty journal, delta scan
        # finds nothing (the < 2 s acceptance bar)
        t0 = time.perf_counter()
        st = ShardedStorage(d, n_shards=n_shards, layout="hash")
        open_intact_s = time.perf_counter() - t0
        total = st.stats()["messages"]
        assert total >= recovery_msgs, total

        # 2: journal-replay — append a delta tail, flush the journal,
        # then drop the handles WITHOUT the close-time fold (the
        # crash-after-flush state): reopen pays snapshot + journal
        # replay + delta scan from the watermark
        delta = recovery_msgs // 100
        st.store_batch([
            Message(topic=f"g/{i % 64}/t", qos=1, payload=payload,
                    timestamp=2e9 + i)
            for i in range(delta)
        ])
        st.sync_data()
        st.save_meta()  # journal append, NO fold
        for inner in st.stores:
            inner._log.close()  # crash: no close-time fold
        t0 = time.perf_counter()
        st = ShardedStorage(d, n_shards=n_shards, layout="hash")
        open_journal_s = time.perf_counter() - t0
        assert st.stats()["messages"] == total + delta
        st.close()

        # 3: full rebuild after metadata loss — serving starts
        # immediately (reads go unpruned to the log); completion is
        # the background decode pass over every record
        for i in range(n_shards):
            sub = os.path.join(d, f"shard-{i:02d}")
            for f in ("census.json", "census.journal"):
                p = os.path.join(sub, f)
                if os.path.exists(p):
                    os.unlink(p)
        t0 = time.perf_counter()
        st = ShardedStorage(d, n_shards=n_shards, layout="hash")
        open_rebuild_serving_s = time.perf_counter() - t0
        st.rebuild_now()
        open_rebuild_complete_s = time.perf_counter() - t0
        assert st.stats()["messages"] == total + delta
        st.close()
        out["restart_to_serving"] = {
            "messages": int(total + delta),
            "shards": n_shards,
            "fill_s": round(fill_dt, 2),
            "intact_s": round(open_intact_s, 3),
            "journal_replay_s": round(open_journal_s, 3),
            "journal_delta_msgs": delta,
            "rebuild_serving_s": round(open_rebuild_serving_s, 3),
            "rebuild_complete_s": round(open_rebuild_complete_s, 2),
        }
        log(
            f"restart-to-serving @ {total + delta:,} msgs x "
            f"{n_shards} shards: intact {open_intact_s:.3f}s "
            f"(< 2 s bar), journal replay ({delta:,} delta) "
            f"{open_journal_s:.3f}s, rebuild serving "
            f"{open_rebuild_serving_s:.3f}s / complete "
            f"{open_rebuild_complete_s:.1f}s"
        )
    finally:
        shutil.rmtree(d, ignore_errors=True)

    # ---- GC reclaim rate under live appends
    d = tempfile.mkdtemp(prefix="shard_gc_")
    try:
        st = ShardedStorage(
            d, n_shards=4, layout="hash", seg_bytes=1 << 16
        )
        payload = b"g" * 128
        base_ts = 1e9
        st.store_batch([
            Message(topic=f"f/{i % 64}/t", qos=1, payload=payload,
                    timestamp=base_ts + i)
            for i in range(50_000)
        ], sync=True)
        stop = threading.Event()
        appended = [0]

        def appender():
            i = 0
            while not stop.is_set():
                st.store_batch([
                    Message(topic=f"f/{(i + j) % 64}/t", qos=1,
                            payload=payload,
                            timestamp=base_ts + 100_000 + i + j)
                    for j in range(256)
                ])
                i += 256
                appended[0] = i

        th = threading.Thread(target=appender, daemon=True)
        th.start()
        reclaimed = 0
        t0 = time.perf_counter()
        # advancing cutoff: each pass releases another slice of the
        # backlog while the writer keeps appending
        for cut in range(10):
            cutoff = int((base_ts + (cut + 1) * 5_000) * 1e6)
            reclaimed += st.gc_pinned(cutoff, {})
            time.sleep(0.02)
        gc_dt = time.perf_counter() - t0
        stop.set()
        th.join()
        st.close()
        out["gc_under_load"] = {
            "reclaimed_records": int(reclaimed),
            "reclaim_records_per_s": round(reclaimed / gc_dt, 1),
            "live_appends_during_gc": int(appended[0]),
        }
        log(
            f"gc under load: {reclaimed:,} records reclaimed in "
            f"{gc_dt:.2f}s ({reclaimed / gc_dt:,.0f}/s) while "
            f"{appended[0]:,} live appends landed"
        )
    finally:
        shutil.rmtree(d, ignore_errors=True)

    if write_json:
        path = os.path.join(
            os.path.dirname(__file__) or ".", "BENCH_r13.json"
        )
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
    return out


def run_cluster_forward_bench(log, n_msgs=None, iters=None,
                              write_json=True):
    """Cluster window forwarding A/B (BENCH_r09): batched scatter
    throughput and per-message forward latency across a 2-node
    in-process cluster — one publisher on node A, one QoS1 wildcard
    subscriber on node B, every message crossing the inter-node link
    as sequenced at-least-once window frames.

    Rows: ``tcp`` (the stock PeerLink), ``quic`` (the in-repo QUIC
    peer transport, PSK profile), and ``quic_loss1`` (QUIC under
    seeded 1% datagram loss on both quic seams — the robustness case
    TCP byte streams handle with head-of-line stalls).  Interleaved
    iterations; medians carry the signal.  Acceptance: QUIC lossless
    throughput >= the TCP baseline (no robustness tax on the happy
    path)."""
    import asyncio

    from emqx_tpu import failpoints as fpmod
    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.cluster import ClusterNode
    from emqx_tpu.codec import mqtt as C
    from emqx_tpu.config import BrokerConfig, ListenerConfig

    n_msgs = n_msgs or int(os.environ.get("BENCH_CF_MSGS", 3000))
    iters = iters or int(os.environ.get("BENCH_CF_ITERS", 5))
    payload = b"x" * int(os.environ.get("BENCH_CF_PAYLOAD", 200))

    async def once(mode, loss=0.0, seed=0):
        def mk_cfg():
            cfg = BrokerConfig()
            cfg.listeners = [ListenerConfig(port=0)]
            cfg.engine.use_device = False  # measure the wire, not XLA
            # unbounded-ish session windows: the clock must see the
            # forward pipeline, not the subscriber's ack window (same
            # rationale as run_replay_bench)
            cfg.mqtt.max_inflight = 4096
            cfg.mqtt.max_mqueue_len = 1_000_000
            return cfg

        sa = BrokerServer(mk_cfg())
        await sa.start()
        sb = BrokerServer(mk_cfg())
        await sb.start()
        fast = dict(
            heartbeat_interval=0.2, down_after=5.0,
            flush_interval=0.002, consensus="lww",
            transport_mode=mode,
        )
        a = ClusterNode("bfa", sa.broker, **fast)
        await a.start()
        b = ClusterNode("bfb", sb.broker, **fast)
        await b.start(seeds=[("bfa", "127.0.0.1", a.port)])
        lat = []
        try:
            loop = asyncio.get_running_loop()

            async def open_conn(port, cid):
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(C.serialize(
                    C.Connect(client_id=cid, proto_ver=C.MQTT_V5),
                    C.MQTT_V5,
                ))
                await w.drain()
                p = C.StreamParser(version=C.MQTT_V5)
                while True:
                    data = await r.read(1 << 16)
                    assert data, "closed during CONNECT"
                    if list(p.feed(data)):
                        break
                return r, w, p

            sr, sw, sp = await open_conn(
                sb.listeners[0].port, "cf-sub"
            )
            sw.write(C.serialize(
                C.Subscribe(packet_id=1, subscriptions=[
                    C.Subscription(topic_filter="cf/#", qos=1)
                ]),
                C.MQTT_V5,
            ))
            await sw.drain()
            while True:
                data = await sr.read(1 << 16)
                assert data
                if any(p.type == C.SUBACK for p in sp.feed(data)):
                    break
            await asyncio.sleep(0.4)  # route delta -> node A

            pr, pw, pp = await open_conn(
                sa.listeners[0].port, "cf-pub"
            )

            async def drain_pub():  # eat PUBACKs to the publisher
                while True:
                    data = await pr.read(1 << 16)
                    if not data:
                        return
                    list(pp.feed(data))

            drainer = loop.create_task(drain_pub())
            if loss > 0.0:
                fpmod.configure("cluster.quic.send", "drop",
                                prob=loss, seed=seed)
                fpmod.configure("cluster.quic.recv", "drop",
                                prob=loss, seed=seed + 1)
            sent_at = {}
            got = set()
            done = loop.create_future()

            async def consume():
                while len(got) < n_msgs:
                    data = await sr.read(1 << 16)
                    assert data, "subscriber link died"
                    now = time.perf_counter()
                    acks = []
                    for pkt in sp.feed(data):
                        if pkt.type != C.PUBLISH:
                            continue
                        if pkt.topic not in got:
                            got.add(pkt.topic)
                            lat.append(now - sent_at[pkt.topic])
                        if pkt.qos:
                            acks.append(C.serialize(
                                C.Puback(packet_id=pkt.packet_id),
                                C.MQTT_V5,
                            ))
                    if acks:
                        sw.write(b"".join(acks))
                        await sw.drain()
                done.set_result(None)

            eater = loop.create_task(consume())
            # flow-controlled publisher: a bounded outstanding window
            # keeps the measure steady-state (and off this sandbox
            # kernel's zero-window pathology on single-connection
            # multi-hundred-KB bursts)
            window = 256
            t0 = time.perf_counter()
            for i in range(n_msgs):
                while i - len(got) >= window:
                    await asyncio.sleep(0.001)
                topic = f"cf/{i}"
                sent_at[topic] = time.perf_counter()
                pw.write(C.serialize(
                    C.Publish(topic=topic, payload=payload, qos=1,
                              packet_id=(i % 60000) + 1),
                    C.MQTT_V5,
                ))
                if i % 64 == 63:
                    await pw.drain()
            await pw.drain()
            await asyncio.wait_for(done, timeout=120)
            dt = time.perf_counter() - t0
            eater.cancel()
            drainer.cancel()
            assert len(got) == n_msgs, (
                f"forwarded loss: {n_msgs - len(got)} missing"
            )
            lat.sort()
            return {
                "msgs_per_s": n_msgs / dt,
                "fwd_p50_ms": lat[len(lat) // 2] * 1e3,
                "fwd_p99_ms": lat[int(len(lat) * 0.99)] * 1e3,
            }
        finally:
            fpmod.clear()
            await b.stop()
            await sb.stop()
            await a.stop()
            await sa.stop()

    rows = [
        ("tcp", "tcp", 0.0),
        ("quic", "quic", 0.0),
        ("quic_loss1", "quic", 0.01),
    ]
    runs = {name: [] for name, _, _ in rows}
    for it in range(iters):  # interleaved A/B: noise hits all rows
        for name, mode, loss in rows:
            r = asyncio.run(once(mode, loss, seed=20260804 + it))
            runs[name].append(r)
            log(
                f"cluster_forward[{name}] iter {it}: "
                f"{r['msgs_per_s']:,.0f} msg/s, p50 "
                f"{r['fwd_p50_ms']:.1f} ms, p99 {r['fwd_p99_ms']:.1f} ms"
            )

    def med(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2]

    summary = {
        name: {
            k: round(med([r[k] for r in rs]), 2)
            for k in ("msgs_per_s", "fwd_p50_ms", "fwd_p99_ms")
        }
        for name, rs in runs.items()
    }
    log(f"cluster_forward medians: {json.dumps(summary)}")
    if write_json:
        out = {
            "pr": 11,
            "metric": "cluster_forward_msgs_per_s",
            "methodology": (
                "Interleaved A/B, {it} iterations each, same box "
                "(bench.py run_cluster_forward_bench): 2-node "
                "in-process cluster (lww), one publisher on node A "
                "bursting {n} QoS1 publishes ({p}B payloads) that all "
                "forward to node B's wildcard subscriber as sequenced "
                "at-least-once window frames; throughput clocks first "
                "publish to last delivery, latency is per-message "
                "publish->delivery on one clock.  'tcp' = the stock "
                "PeerLink; 'quic' = the in-repo QUIC peer transport "
                "(PSK profile, control+forward streams, selective-ACK "
                "recovery); 'quic_loss1' = QUIC under seeded 1% "
                "datagram loss on cluster.quic.send AND .recv (the "
                "failpoint seams) — zero-loss is asserted in-run.  "
                "Medians reported; ratios carry the signal."
            ).format(it=iters, n=n_msgs, p=len(payload)),
            "runs": runs,
            "medians": summary,
            "criteria": {
                "quic_vs_tcp_lossless_throughput": round(
                    summary["quic"]["msgs_per_s"]
                    / summary["tcp"]["msgs_per_s"], 3,
                ),
                "quic_loss1_p99_vs_lossless": round(
                    summary["quic_loss1"]["fwd_p99_ms"]
                    / max(summary["quic"]["fwd_p99_ms"], 1e-9), 3,
                ),
            },
        }
        path = os.path.join(
            os.path.dirname(__file__) or ".", "BENCH_r09.json"
        )
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
    return summary


def run_rules_bench(log, iters=None, write_json=True):
    """Rule-engine WHERE evaluation A/B (BENCH_r10): N registered
    rules x a fanout dispatch window through the REAL pipeline
    (publish_many -> trie match of rule topic filters -> rule sink ->
    apply_batch), on identical worlds:

      * ``scalar`` — RuleEngine.eval_force="scalar": the per-rule
        interpreter referee (per-message eval_where over lazy envs);
      * ``host``   — the stacked rules x window matrix on the numpy
        twin (matched-row slice);
      * ``dev``    — the fused rules_eval_batch JAX kernel.

    Registries of 1k and 10k lowerable rules partitioned over 16
    topic groups (each message matches ~N/16 rules), predicates a
    rotating mix of numeric/string/IN/presence shapes at ~1/8 pass
    rate so action dispatch stays off the clock.  Interleaved
    iterations; medians carry the signal; per-stage attribution
    (extract vs eval) from the profiler's ``rules`` lap +
    ``rules_extract``/``rules_eval`` sub-stages."""
    import numpy as _np  # noqa: F401  (env sanity: numpy present)

    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.config import BrokerConfig
    from emqx_tpu.message import Message

    from emqx_tpu.rules.runtime import (
        build_env, eval_select, eval_where,
    )

    iters = iters or int(os.environ.get("BENCH_RULES_ITERS", 5))
    window = 64
    n_groups = 16

    _PREDS = [
        "payload.v = {k}",
        "payload.v > 29 AND payload.s = 'x'",
        "payload.s IN ('q', 'z{k}')",
        "is_null(payload.w) AND payload.v >= 30",
        "payload.v IN ({k}, 31)",
        "NOT (payload.v < 30) AND payload.s != 'y'",
    ]

    def prepr_apply_batch(eng):
        """The PRE-PR `RuleEngine.apply_batch`, verbatim (the
        acceptance baseline): full `build_env` per matched message,
        one Python pass per rule with per-rule PredicateProgram
        column extraction, per-hit metrics."""

        def apply_batch(items, rec=None):
            if not items:
                return 0
            if len(items) == 1:
                return eng.apply(items[0][0], items[0][1])
            msgs = [m for m, _ in items]
            env_cache = [None] * len(items)

            def env(i):
                e = env_cache[i]
                if e is None:
                    e = env_cache[i] = build_env(msgs[i])
                return e

            by_rule = {}
            for i, (_, rids) in enumerate(items):
                for rid in rids:
                    by_rule.setdefault(rid, []).append(i)
            hits = 0
            for rid, idxs in by_rule.items():
                rule = eng.rules.get(rid)
                if rule is None or not rule.enabled:
                    continue
                rule.matched += len(idxs)
                if rule.program is not None and len(idxs) > 1:
                    mask = rule.program.eval_batch(
                        [env(i) for i in idxs]
                    )
                    passed = [
                        i for i, ok in zip(idxs, mask.tolist()) if ok
                    ]
                else:
                    passed = [
                        i for i in idxs
                        if eval_where(rule.parsed.where, env(i))
                    ]
                rule.failed += len(idxs) - len(passed)
                rule.passed += len(passed)
                hits += len(passed)
                for i in passed:
                    selected = eval_select(rule.parsed, env(i))
                    eng._run_actions(rule, selected, msgs[i])
            if eng.broker is not None and hits:
                eng.broker.metrics.inc("rules.matched", hits)
            return hits

        return apply_batch

    def build(mode, n_rules):
        cfg = BrokerConfig()
        cfg.engine.use_device = False  # match half: host trie
        b = Broker(config=cfg)
        if mode == "prepr":
            b.rules.apply_batch = prepr_apply_batch(b.rules)
        elif mode == "referee":
            b.rules.eval_force = "scalar"
        else:
            b.router.engine.rules_force = mode
        for i in range(n_rules):
            pred = _PREDS[i % len(_PREDS)].format(k=24 + i % 8)
            b.rules.add_rule(
                f"r{i}",
                f'SELECT * FROM "bench/{i % n_groups}/#" '
                f"WHERE {pred}",
            )
        return b

    def pump(b, n_msgs):
        msgs = [
            Message(
                topic=f"bench/{j % n_groups}/x",
                payload=(
                    '{"v": %d, "s": "%s"}' % (j % 32, "xyq"[j % 3])
                ).encode(),
                qos=0,
            )
            for j in range(n_msgs)
        ]
        b.publish_many(msgs[:window])  # warm (JIT compile off-clock)
        t0 = time.perf_counter()
        for w0 in range(window, n_msgs, window):
            w = msgs[w0:w0 + window]
            now = time.time()
            for m in w:
                m.timestamp = now
            b.publish_many(w)
        dt = time.perf_counter() - t0
        return (n_msgs - window) / dt

    results = {}
    for n_rules in (1000, 10000):
        n_msgs = window * (33 if n_rules == 1000 else 9)
        brokers = {
            mode: build(mode, n_rules)
            for mode in ("prepr", "referee", "host", "dev")
        }
        runs = {m: [] for m in brokers}
        for it in range(iters):
            for mode, b in brokers.items():
                runs[mode].append(pump(b, n_msgs))

        def med(xs):
            return sorted(xs)[len(xs) // 2]

        stages = {}
        for mode, b in brokers.items():
            snap = {}
            for name, s in b.profiler.snapshots().items():
                if s.count and name in (
                    "rules", "rules_extract", "rules_eval",
                ):
                    snap[name] = {
                        "count": s.count,
                        "p50_us": round(s.percentile(50), 1),
                        "p99_us": round(s.percentile(99), 1),
                    }
            snap["engine"] = {
                k: v for k, v in b.rules.stats().items()
                if isinstance(v, (int, float)) and v is not None
            }
            stages[mode] = snap
        medians = {m: round(med(rs), 1) for m, rs in runs.items()}
        key = f"rules_{n_rules}"
        # rule-match throughput isolated to the rules STAGE (the part
        # this PR vectorizes): pre-PR rules-lap p50 / matrix rules-lap
        # p50 — the end-to-end msg/s ratio additionally carries the
        # match/expand floor both paths share
        try:
            stage_ratio = round(
                stages["prepr"]["rules"]["p50_us"]
                / stages["host"]["rules"]["p50_us"], 2,
            )
        except (KeyError, ZeroDivisionError):
            stage_ratio = None
        results[key] = {
            "runs": {m: [round(r, 1) for r in rs]
                     for m, rs in runs.items()},
            "medians_msgs_per_s": medians,
            "speedup_host_vs_prepr": round(
                medians["host"] / medians["prepr"], 2
            ),
            "speedup_dev_vs_prepr": round(
                medians["dev"] / medians["prepr"], 2
            ),
            "speedup_host_vs_referee": round(
                medians["host"] / medians["referee"], 2
            ),
            "stage_speedup_host_vs_prepr": stage_ratio,
            "stages": stages,
        }
        log(
            f"rules bench {n_rules}: prepr {medians['prepr']:,.0f} "
            f"referee {medians['referee']:,.0f} "
            f"host {medians['host']:,.0f} dev {medians['dev']:,.0f} "
            f"msg/s (host "
            f"{results[key]['speedup_host_vs_prepr']}x vs pre-PR, "
            f"{results[key]['speedup_host_vs_referee']}x vs referee)"
        )
    if write_json:
        out = {
            "pr": 12,
            "metric": "rules_match_msgs_per_s",
            "methodology": (
                "Interleaved A/B, {it} iterations each, same box "
                "(bench.py run_rules_bench): one broker per path, N "
                "lowerable rules over 16 topic groups (each 64-msg "
                "publish window matches ~N/16 rules; predicates mix "
                "numeric/string/IN/presence shapes at ~2-3% pass "
                "rate), no subscribers, host topic matching.  "
                "'prepr' = the pre-PR apply_batch verbatim (full "
                "build_env per message, one Python pass + per-rule "
                "PredicateProgram extraction per rule — the "
                "acceptance baseline); 'referee' = the per-pair "
                "interpreter oracle the property suite pins "
                "bit-identical (it already benefits from this PR's "
                "lazy envs); 'host' = numpy rules x window matrix "
                "over shared window columns (matched-row slice); "
                "'dev' = fused rules_eval_batch JAX kernel (this box "
                "is CPU-only: the dev row rides CPU XLA; ratios, not "
                "absolutes, carry the signal).  Medians reported.  "
                "Stage attribution: profiler 'rules' lap with "
                "rules_extract/rules_eval sub-stages."
            ).format(it=iters),
            **results,
        }
        path = os.path.join(
            os.path.dirname(__file__) or ".", "BENCH_r10.json"
        )
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
    return results


def run_rule_egress_bench(log, iters=None, write_json=True):
    """Rule-engine OUTPUT half A/B (BENCH_r16, the PR 20 tentpole):
    1k registered rules x 64-msg publish windows through the REAL
    end-to-end action pipeline — SELECT materialization, payload
    templating, buffered sink worker, and an actual TCP round-trip to
    an in-process loopback sink server per delivery:

      * ``scalar``  — select_force="scalar" (the per-row interpreter
        referee) + a max_batch=1 sink worker: one eval_select + one
        template render + ONE sink round-trip per action row (the
        pre-PR shape);
      * ``batched`` — select_force="batched" + micro-batching worker
        + ``on_query_batch``: one `materialize_rows` pass per (rule,
        window), one `render_rows` per action, ONE sink round-trip
        per flushed micro-batch.

    Both sides run the SAME WHERE matrix (the PR 12 half stays on) so
    the ratio isolates the output half.  An iteration clocks publish
    -> last action ACKED by the sink server.  Interleaved iterations,
    medians."""
    import struct as _struct

    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.config import BrokerConfig
    from emqx_tpu.message import Message
    from emqx_tpu.resources import Resource

    iters = iters or int(os.environ.get("BENCH_EGRESS_ITERS", 5))
    window = 64
    n_groups = 16
    n_rules = 1000
    n_windows = int(os.environ.get("BENCH_EGRESS_WINDOWS", 6))

    class TcpSink(Resource):
        """Length-framed loopback sink: each frame carries N
        newline-joined records, the server acks with the count — so
        every ``on_query`` is one real RTT and every
        ``on_query_batch`` amortizes the window into one."""

        max_batch = 1

        def __init__(self, port: int) -> None:
            self.port = port
            self._r = self._w = None

        async def on_start(self) -> None:
            self._r, self._w = await asyncio.open_connection(
                "127.0.0.1", self.port
            )

        async def on_stop(self) -> None:
            if self._w is not None:
                self._w.close()

        async def _send(self, records) -> int:
            body = b"\n".join(
                r.encode() if isinstance(r, str) else r
                for r in records
            )
            self._w.write(_struct.pack(">I", len(body)) + body)
            await self._w.drain()
            hdr = await self._r.readexactly(4)
            return _struct.unpack(">I", hdr)[0]

        async def on_query(self, query) -> None:
            await self._send([query])

        async def on_query_batch(self, queries) -> int:
            return await self._send(queries)

    _TMPL = (
        '{"t":"${topic}","v":${v},"v2":${v2},"s":"${s}"}'
    )

    async def build(mode, port):
        cfg = BrokerConfig()
        cfg.engine.use_device = False  # match half: host trie
        b = Broker(config=cfg)
        from emqx_tpu.rules.engine import SinkAction

        sink = TcpSink(port)
        if mode == "scalar":
            b.rules.select_force = "scalar"
            sink.max_batch = 1
            worker = await b.resources.create(
                "bench_sink", sink, max_buffer=1_000_000
            )
        else:
            b.rules.select_force = "batched"
            sink.max_batch = 4096
            worker = await b.resources.create(
                "bench_sink", sink, max_buffer=1_000_000,
                batch_records=512, batch_age=0.002,
            )
        for i in range(n_rules):
            b.rules.add_rule(
                f"r{i}",
                f"SELECT payload.v AS v, topic, "
                f"payload.v * 2 + {i % 8} AS v2, payload.s AS s "
                f'FROM "bench/{i % n_groups}/#" '
                f"WHERE payload.v >= 16",
                actions=[SinkAction("bench_sink", payload=_TMPL)],
            )
        return b, worker

    def make_msgs(n_msgs):
        return [
            Message(
                topic=f"bench/{j % n_groups}/x",
                payload=(
                    '{"v": %d, "s": "%s"}' % (j % 32, "xyq"[j % 3])
                ).encode(),
                qos=0,
            )
            for j in range(n_msgs)
        ]

    async def pump(b, worker, received):
        """One timed iteration: publish every window, then wait for
        the LAST enqueued action's sink ack."""
        msgs = make_msgs(window * n_windows)
        base_matched = worker.stats["matched"]
        base_dropped = worker.stats["dropped"]
        base_rcvd = received["n"]
        t0 = time.perf_counter()
        for w0 in range(0, len(msgs), window):
            w = msgs[w0:w0 + window]
            now = time.time()
            for m in w:
                m.timestamp = now
            b.publish_many(w)
            # yield so the drain loop overlaps with publish (the
            # broker's event loop does this for free)
            await asyncio.sleep(0)
        expect = (
            worker.stats["matched"] - base_matched
            - (worker.stats["dropped"] - base_dropped)
        )
        while received["n"] - base_rcvd < expect:
            await asyncio.sleep(0.0005)
        dt = time.perf_counter() - t0
        return expect / dt

    async def main():
        received = {"n": 0}

        async def handle(reader, writer):
            try:
                while True:
                    hdr = await reader.readexactly(4)
                    (ln,) = _struct.unpack(">I", hdr)
                    body = await reader.readexactly(ln)
                    cnt = body.count(b"\n") + 1 if body else 0
                    received["n"] += cnt
                    writer.write(_struct.pack(">I", cnt))
                    await writer.drain()
            except (
                asyncio.IncompleteReadError, ConnectionResetError
            ):
                pass

        server = await asyncio.start_server(
            handle, "127.0.0.1", 0
        )
        port = server.sockets[0].getsockname()[1]
        sides = {}
        for mode in ("scalar", "batched"):
            sides[mode] = await build(mode, port)
        runs = {m: [] for m in sides}
        # warm both sides off-clock (imports, JIT, template cache)
        for mode, (b, worker) in sides.items():
            await pump(b, worker, received)
        for _ in range(iters):
            for mode, (b, worker) in sides.items():
                runs[mode].append(await pump(b, worker, received))
        stats = {}
        for mode, (b, worker) in sides.items():
            snap = worker.batch_hist.snapshot()
            stats[mode] = {
                "engine": {
                    k: v for k, v in b.rules.stats().items()
                    if isinstance(v, (int, float)) and v is not None
                },
                "sink": {
                    **{
                        k: v for k, v in worker.stats.items()
                        if isinstance(v, (int, float))
                    },
                    "batch_p50": round(snap.percentile(50), 1),
                    "batch_p99": round(snap.percentile(99), 1),
                },
            }
            await b.resources.stop_all()
        server.close()
        await server.wait_closed()
        return runs, stats

    runs, stats = asyncio.run(main())

    def med(xs):
        return sorted(xs)[len(xs) // 2]

    medians = {m: round(med(rs), 1) for m, rs in runs.items()}
    speedup = round(medians["batched"] / medians["scalar"], 2)
    results = {
        "runs": {m: [round(r, 1) for r in rs]
                 for m, rs in runs.items()},
        "medians_actions_per_s": medians,
        "speedup_batched_vs_scalar": speedup,
        "stages": stats,
    }
    log(
        f"rule egress bench {n_rules} rules: "
        f"scalar {medians['scalar']:,.0f} "
        f"batched {medians['batched']:,.0f} actions/s "
        f"({speedup}x)"
    )
    if write_json:
        out = {
            "pr": 20,
            "metric": "rule_action_throughput_actions_per_s",
            "methodology": (
                "Interleaved A/B, {it} iterations each, same box "
                "(bench.py run_rule_egress_bench): 1k lowerable "
                "SELECT rules over 16 topic groups (each 64-msg "
                "window matches ~62 rules, WHERE pass rate 1/2), "
                "every action a templated-payload sink delivery to "
                "an in-process loopback TCP server that acks each "
                "frame (a REAL per-delivery round-trip).  'scalar' "
                "= per-row eval_select + per-record sink RTT "
                "(max_batch=1, the pre-PR shape); 'batched' = "
                "windowed SELECT lowering (materialize_rows + "
                "render_rows) + micro-batched worker flushes "
                "(batch_records=512, batch_age=2ms) + one RTT per "
                "flushed batch.  Both sides run the same WHERE "
                "matrix; an iteration clocks publish -> last action "
                "ACK.  Medians reported."
            ).format(it=iters),
            "rules_1000": results,
        }
        path = os.path.join(
            os.path.dirname(__file__) or ".", "BENCH_r16.json"
        )
        with open(path, "w") as f:
            json.dump(out, f, indent=2)
    return results


def run_overload_bench(log, iters=None, write_json=True):
    """Overload-protection A/B (BENCH_r11): the PR 13 acceptance
    counterfactual.  Two halves:

    * **steady state** — fanout-256 QoS1 windows with the olp ladder
      ENABLED AT LEVEL 0 vs disabled (disabled == pre-PR behavior;
      the byte-identity is property-tested), paired interleaved —
      the "overhead within noise" criterion;
    * **flood + slow-subscriber storm** — real sockets: QoS0
      flooders at well over dispatch capacity, a slow subscriber
      that stops reading, a steady QoS1 publisher and a PINGREQ
      control plane, run with OLP ON vs OFF (interleaved).  Reports
      live QoS1 publish→PUBACK p50/p99, control-ping p99, peak RSS
      delta, shed counters, max ladder level, recovery time back to
      level 0, and asserts ZERO acked-QoS1 loss in every run.
    """
    import asyncio
    import statistics

    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.broker.session import SubOpts
    from emqx_tpu.codec import mqtt as C
    from emqx_tpu.config import BrokerConfig, ListenerConfig
    from emqx_tpu.message import Message
    from emqx_tpu.sysmon import _rss_bytes

    iters = int(
        os.environ.get("BENCH_OVERLOAD_ITERS", iters or 3)
    )
    flood_s = float(os.environ.get("BENCH_OVERLOAD_FLOOD_S", 4.0))
    out = {}

    # ---------------------------------------- steady-state fanout A/B

    def fanout_once(olp_on):
        cfg = BrokerConfig()
        cfg.engine.use_device = False
        cfg.olp.enable = olp_on
        b = Broker(config=cfg)
        sink = [0]

        def send(pkts):
            sink[0] += sum(
                len(C.serialize(p, C.MQTT_V5)) for p in pkts
            )

        flt = "fan/olp"
        for i in range(256):
            ch = Channel(b, send=send, close=lambda r: None)
            cid = f"o{i}"
            session, _ = b.cm.open_session(
                True, cid, ch, max_inflight=0
            )
            session.subscribe(flt, SubOpts(qos=1))
            b.subscribe(cid, flt, SubOpts(qos=1))
        n = 500
        msgs = [Message(topic=flt, payload=b"x" * 64, qos=1)
                for _ in range(n)]
        b.publish_many(msgs[:64])  # warm
        t0 = time.perf_counter()
        for w0 in range(64, n, 64):
            w = msgs[w0:w0 + 64]
            now = time.time()
            for m in w:
                m.timestamp = now
            b.publish_many(w)
        return (n - 64) / (time.perf_counter() - t0)

    on_rates, off_rates = [], []
    for _ in range(5):  # paired interleaved
        off_rates.append(fanout_once(False))
        on_rates.append(fanout_once(True))
    off_med = statistics.median(off_rates)
    on_med = statistics.median(on_rates)
    out["steady_fanout256_qos1_olp_off_msgs_per_s"] = off_med
    out["steady_fanout256_qos1_olp_on_msgs_per_s"] = on_med
    out["steady_overhead_ratio"] = on_med / off_med
    log(
        f"overload steady-state fanout-256 qos1: olp-off "
        f"{off_med:,.0f} msg/s vs olp-on(level 0) {on_med:,.0f} "
        f"({on_med / off_med:.3f}x — must be within noise)"
    )

    # ------------------------------------------- flood counterfactual

    async def flood_run(olp_on):
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.engine.batch_max = 128
        cfg.olp.enable = olp_on
        cfg.olp.sample_interval = 0.05
        cfg.olp.min_hold = 0.3
        cfg.olp.batcher_fill = [0.3, 0.6, 50.0]
        # pin the machine-state signals inert: the flood signal
        # (batcher fill) is the one this scenario exercises
        cfg.olp.loop_lag_ms = [1e6, 1e6, 1e6]
        cfg.olp.e2e_p99_ms = [1e6, 1e6, 1e6]
        cfg.olp.mqueue_backlog = [1e9, 1e9, 1e9]
        cfg.olp.sysmem = [0.999, 0.9995, 0.9999]
        cfg.olp.procmem = [0.97, 0.98, 0.99]
        cfg.olp.cpu = [1e6, 1e6, 1e6]
        srv = BrokerServer(cfg)
        await srv.start()
        broker = srv.broker
        port = srv.listeners[0].port
        loop = asyncio.get_running_loop()
        rss0 = _rss_bytes()
        peak_rss = rss0
        max_level = 0
        stop = asyncio.Event()

        async def sampler():
            nonlocal peak_rss, max_level
            while not stop.is_set():
                broker.olp.tick(time.time())
                max_level = max(max_level, broker.olp.level)
                peak_rss = max(peak_rss, _rss_bytes())
                await asyncio.sleep(0.02)

        async def conn(cid):
            r, w = await asyncio.open_connection("127.0.0.1", port)
            w.write(C.serialize(
                C.Connect(client_id=cid, proto_ver=C.MQTT_V5),
                C.MQTT_V5,
            ))
            await w.drain()
            p = C.StreamParser(version=C.MQTT_V5)
            while True:
                data = await r.read(1 << 16)
                assert data
                if any(pk.type == C.CONNACK for pk in p.feed(data)):
                    return r, w, p

        sam = loop.create_task(sampler())
        # live subscriber: qos1 live traffic + the qos0 flood
        sr, sw, sp = await conn("live_sub")
        sw.write(C.serialize(C.Subscribe(
            packet_id=1,
            subscriptions=[C.Subscription("live/#", qos=1),
                           C.Subscription("flood/#", qos=0)],
        ), C.MQTT_V5))
        await sw.drain()
        got = set()
        flood_got = [0]
        done = asyncio.Event()

        async def sub_loop():
            while not done.is_set():
                data = await sr.read(1 << 16)
                if not data:
                    return
                acks = []
                for pk in sp.feed(data):
                    if pk.type != C.PUBLISH:
                        continue
                    if pk.topic.startswith("live/"):
                        got.add(bytes(pk.payload))
                        if pk.qos:
                            acks.append(C.serialize(
                                C.Puback(packet_id=pk.packet_id),
                                C.MQTT_V5,
                            ))
                    else:
                        flood_got[0] += 1
                if acks:
                    sw.write(b"".join(acks))

        sub_task = loop.create_task(sub_loop())
        # slow-subscriber storm: subscribe the flood, then stop reading
        slow_ws = []
        for i in range(2):
            _zr, zw, _zp = await conn(f"slow{i}")
            zw.write(C.serialize(C.Subscribe(
                packet_id=1,
                subscriptions=[C.Subscription("flood/#", qos=0)],
            ), C.MQTT_V5))
            await zw.drain()
            slow_ws.append(zw)
        flood_on = True
        flood_sent = [0]

        async def flooder(i):
            _r, w, _p = await conn(f"flood{i}")
            payload = b"f" * 2048
            k = 0
            while flood_on:
                burst = b"".join(
                    C.serialize(C.Publish(
                        topic=f"flood/{i}/{k + j}", qos=0,
                        payload=payload,
                    ), C.MQTT_V5)
                    for j in range(64)
                )
                k += 64
                flood_sent[0] += 64
                w.write(burst)
                try:
                    await asyncio.wait_for(w.drain(), 1.0)
                except asyncio.TimeoutError:
                    await asyncio.sleep(0.05)
            w.close()

        flooders = [loop.create_task(flooder(i)) for i in range(3)]
        # steady qos1 publisher + control pings
        pr, pw, pp = await conn("steady")
        cr, cw, cp = await conn("control")
        ack_lat = []
        ping_lat = []
        pending = {}
        acked = set()

        async def pub_reader():
            while not done.is_set():
                data = await pr.read(1 << 14)
                if not data:
                    return
                for pk in pp.feed(data):
                    if pk.type == C.PUBACK:
                        t0 = pending.pop(pk.packet_id, None)
                        if t0 is not None:
                            ack_lat.append(
                                (time.perf_counter() - t0) * 1e3
                            )
                        acked.add(pk.packet_id)

        pub_rd = loop.create_task(pub_reader())
        sent = []
        t_end = time.time() + flood_s
        seq = 0
        while time.time() < t_end:
            seq += 1
            pid = (seq % 60000) + 1
            pending[pid] = time.perf_counter()
            sent.append(seq)
            pw.write(C.serialize(C.Publish(
                topic="live/x", qos=1, packet_id=pid,
                payload=b"s%d" % seq,
            ), C.MQTT_V5))
            await pw.drain()
            t0 = time.perf_counter()
            cw.write(C.serialize(C.Pingreq(), C.MQTT_V5))
            await cw.drain()
            try:
                data = await asyncio.wait_for(cr.read(1 << 10), 10.0)
                if any(pk.type == C.PINGRESP for pk in cp.feed(data)):
                    ping_lat.append(
                        (time.perf_counter() - t0) * 1e3
                    )
            except asyncio.TimeoutError:
                ping_lat.append(10_000.0)
            await asyncio.sleep(0.05)
        flood_on = False
        await asyncio.gather(*flooders, return_exceptions=True)
        # drain: every acked QoS1 must arrive (zero-loss assertion)
        want = {b"s%d" % s for s in sent}
        deadline = time.time() + 15.0
        while time.time() < deadline and not want <= got:
            await asyncio.sleep(0.1)
        lost = len(want - got)
        assert lost == 0, f"acked-QoS1 loss with olp_on={olp_on}"
        recovery_s = None
        if olp_on:
            t0 = time.time()
            while time.time() - t0 < 15.0 and broker.olp.level:
                await asyncio.sleep(0.05)
            recovery_s = round(time.time() - t0, 2)
        m = broker.metrics
        shed_total = (
            m.val("delivery.dropped.olp_shed")
            + m.val("messages.dropped.olp_shed")
            + m.val("delivery.dropped.out_buffer")
        )
        res = {
            "publish_ack_p50_ms": statistics.median(ack_lat or [0]),
            "publish_ack_p99_ms": sorted(ack_lat or [0])[
                max(0, int(len(ack_lat) * 0.99) - 1)
            ],
            "ping_p99_ms": sorted(ping_lat or [0])[
                max(0, int(len(ping_lat) * 0.99) - 1)
            ],
            "peak_rss_delta_mb": round(
                (peak_rss - rss0) / (1 << 20), 1
            ),
            "max_level": max_level,
            "recovery_s": recovery_s,
            "qos1_sent": len(sent),
            "qos1_lost": lost,
            "flood_published": flood_sent[0],
            "flood_delivered_to_live_sub": flood_got[0],
            "shed_total": shed_total,
        }
        done.set()
        stop.set()
        for w in (sw, pw, cw, *slow_ws):
            w.close()
        sub_task.cancel()
        pub_rd.cancel()
        await asyncio.gather(
            sub_task, pub_rd, sam, return_exceptions=True
        )
        await srv.stop()
        return res

    runs = {"olp_on": [], "olp_off": []}
    for i in range(iters):
        # interleaved A/B, off first (the counterfactual baseline)
        runs["olp_off"].append(asyncio.run(flood_run(False)))
        runs["olp_on"].append(asyncio.run(flood_run(True)))

    def med(mode, key):
        vals = [r[key] for r in runs[mode] if r[key] is not None]
        return statistics.median(vals) if vals else None

    for mode in ("olp_off", "olp_on"):
        out[mode] = {
            k: med(mode, k)
            for k in ("publish_ack_p50_ms", "publish_ack_p99_ms",
                      "ping_p99_ms", "peak_rss_delta_mb",
                      "max_level", "recovery_s", "qos1_lost",
                      "flood_delivered_to_live_sub", "shed_total")
        }
        out[mode]["runs"] = runs[mode]
        log(
            f"overload flood [{mode}]: publish-ack p99 "
            f"{out[mode]['publish_ack_p99_ms']:.1f} ms, ping p99 "
            f"{out[mode]['ping_p99_ms']:.1f} ms, peak RSS delta "
            f"{out[mode]['peak_rss_delta_mb']:.1f} MB, max level "
            f"{out[mode]['max_level']}, shed {out[mode]['shed_total']}"
            + (f", recovery {out[mode]['recovery_s']}s"
               if out[mode]["recovery_s"] is not None else "")
        )
    out["note"] = (
        "flood: 3 QoS0 flooder connections (2 KiB payloads, 64-msg "
        "bursts) + 2 slow subscribers that stop reading + a steady "
        "QoS1 publisher and a PINGREQ control plane, for "
        f"{flood_s:.0f}s per run, interleaved OFF/ON x{iters}, "
        "medians; batcher batch_max=128 so the batcher-fill signal "
        "drives the ladder (L1@0.3, L2@0.6).  Zero acked-QoS1 loss "
        "asserted in EVERY run.  olp_on must keep ping/publish p99 "
        "bounded via L2 QoS0-delivery shedding and step back to "
        "level 0 after the flood (recovery_s); olp_off is the "
        "counterfactual the ladder prevents.  Steady-state: "
        "fanout-256 QoS1 with olp enabled at level 0 vs disabled "
        "(disabled == pre-PR dispatch byte-for-byte), paired "
        "interleaved x5."
    )
    if write_json:
        with open(os.path.join(
            os.path.dirname(__file__) or ".", "BENCH_r11.json"
        ), "w") as f:
            json.dump(out, f, indent=2)
    return out


def run_flightrec_bench(log, iters=None, write_json=True):
    """Flight-recorder overhead A/B (BENCH_r15, the flight-recorder
    tentpole's acceptance criterion): fanout-256 QoS1 windows with the
    always-on recorder ARMED (one ring append per committed window via
    Profiler.commit, plus a tick — SLO delta check, samplers — inside
    the timed region) vs disabled (``flight.enable=false``: the
    recorder object exists but ``armed`` is False and the profiler
    hook is None — the pre-PR dispatch byte-for-byte, which the
    property suite pins bit-identical).  Paired interleaved on one
    box; medians.  The criterion: armed-vs-off median throughput
    within 2%."""
    import statistics

    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.broker.session import SubOpts
    from emqx_tpu.codec import mqtt as C
    from emqx_tpu.config import BrokerConfig
    from emqx_tpu.message import Message

    iters = int(os.environ.get("BENCH_FLIGHT_ITERS", iters or 5))

    def fanout_once(armed):
        cfg = BrokerConfig()
        cfg.engine.use_device = False
        cfg.flight.enable = armed
        b = Broker(config=cfg)
        sink = [0]

        def send(pkts):
            sink[0] += sum(
                len(C.serialize(p, C.MQTT_V5)) for p in pkts
            )

        flt = "fan/flight"
        for i in range(256):
            ch = Channel(b, send=send, close=lambda r: None)
            cid = f"f{i}"
            session, _ = b.cm.open_session(
                True, cid, ch, max_inflight=0
            )
            session.subscribe(flt, SubOpts(qos=1))
            b.subscribe(cid, flt, SubOpts(qos=1))
        n = 500
        msgs = [Message(topic=flt, payload=b"x" * 64, qos=1)
                for _ in range(n)]
        b.publish_many(msgs[:64])  # warm
        t0 = time.perf_counter()
        for w0 in range(64, n, 64):
            w = msgs[w0:w0 + 64]
            now = time.time()
            for m in w:
                m.timestamp = now
            b.publish_many(w)
        # the recorder's 1 Hz housekeeping, charged to the armed side
        # (production runs it from the broker tick)
        b.flight.tick(profiler=b.profiler)
        dt = time.perf_counter() - t0
        b.flight.stop()
        return (n - 64) / dt

    on_rates, off_rates = [], []
    for _ in range(iters):  # paired interleaved
        off_rates.append(fanout_once(False))
        on_rates.append(fanout_once(True))
    off_med = statistics.median(off_rates)
    on_med = statistics.median(on_rates)
    ratio = on_med / off_med
    results = {
        "fanout256_qos1_flight_off_msgs_per_s": off_med,
        "fanout256_qos1_flight_on_msgs_per_s": on_med,
        "armed_over_off_ratio": ratio,
        "within_2pct": bool(ratio >= 0.98),
        "iters": iters,
    }
    log(
        f"flightrec fanout-256 qos1: recorder-off {off_med:,.0f} "
        f"msg/s vs armed {on_med:,.0f} ({ratio:.3f}x — criterion "
        f">= 0.98)"
    )
    if write_json:
        out = {
            "schema": "flight-recorder overhead A/B",
            "note": (
                "Interleaved A/B, {it} iteration pairs, same box "
                "(bench.py run_flightrec_bench): fanout-256 QoS1, "
                "500 msgs in 64-msg windows per iteration, fresh "
                "broker per run.  'armed' = always-on flight "
                "recorder (ring append per committed window + one "
                "tick with SLO delta check inside the timed "
                "region); 'off' = flight.enable=false (the pre-PR "
                "dispatch — the property suite pins the armed wire "
                "bit-identical to it).  Medians; acceptance is "
                "armed/off >= 0.98."
            ).format(it=iters),
            **results,
        }
        with open(os.path.join(
            os.path.dirname(__file__) or ".", "BENCH_r15.json"
        ), "w") as f:
            json.dump(out, f, indent=2)
    return results


def run_broker_bench(log, mode="auto"):
    """End-to-end socket benchmark (BASELINE config 1 shape, the
    emqtt_bench workload): N publishers / M wildcard subscribers over
    real TCP + the full codec → channel → batcher → match → dispatch
    path, in-process, against an engine PRELOADED with
    BENCH_BROKER_BG_SUBS background wildcard subscriptions (default
    1M) so the match step does production-scale work.

    ``mode``: "host" pins use_device=False (the reference-equivalent
    CPU trie per window); "auto" is the SHIPPING default (per-window
    adaptive host/device policy); "device" pins every window through
    the device — over the axon tunnel that documents the ~100 ms RTT
    floor, co-located it is the ms-scale path.  Reports routed msg/s
    and delivery latency percentiles (publish write → subscriber read,
    same clock)."""
    import asyncio
    import struct

    import numpy as np

    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.codec import mqtt as C
    from emqx_tpu.config import BrokerConfig, ListenerConfig

    n_subs = int(os.environ.get("BENCH_BROKER_SUBS", 100))
    n_pubs = int(os.environ.get("BENCH_BROKER_PUBS", 100))
    n_msgs = int(os.environ.get("BENCH_BROKER_MSGS", 300))
    n_bg = int(os.environ.get("BENCH_BROKER_BG_SUBS", 1_000_000))
    inflight = int(os.environ.get("BENCH_BROKER_INFLIGHT", 256))
    device = mode == "device"
    if device:
        # the pinned-device variant is host↔device-RTT-bound (on the
        # axon tunnel ~100 ms/window); fewer messages keep it quick
        n_msgs = int(os.environ.get("BENCH_BROKER_MSGS_DEVICE", 50))
    total = n_pubs * n_msgs
    lat: list = []

    async def bench():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.engine.batch_window_ms = float(
            os.environ.get("BENCH_BROKER_WINDOW_MS", 1.0)
        )
        cfg.engine.use_device = {
            "host": False, "auto": None, "device": True
        }[mode]
        if device and n_bg == 0:
            # force even a tiny live set onto the device automaton
            cfg.engine.rebuild_threshold = min(n_subs, 64)
        srv = BrokerServer(cfg)
        await srv.start()

        if n_bg:
            # background wildcard set: the fleet-telemetry families at
            # scale (distinct fids over shared patterns — the standalone
            # bench's fan-out shape) + per-live-sub matching filters so
            # every bench topic fans out ~9x in the MATCH step.  fids
            # are ints: no subscriber sessions, so dispatch skips them
            # after lookup — the measured cost is routing, as intended.
            t_bg = time.perf_counter()
            bg_filters, _pops = make_filters(n_bg, 8)

            def preload():
                eng = srv.broker.router.engine
                for fid, ws in bg_filters:
                    eng._wild.insert("/".join(ws), 1_000_000_000 + fid)
                    eng._by_fid[1_000_000_000 + fid] = "/".join(ws)
                for i in range(n_subs):
                    for k in range(8):
                        flt = f"bench/{i}/+"
                        eng._wild.insert(flt, 2_000_000_000 + i * 8 + k)
                        eng._by_fid[2_000_000_000 + i * 8 + k] = flt
                if mode != "host":
                    eng.rebuild()
                    eng.warmup(4096)

            await asyncio.get_running_loop().run_in_executor(
                None, preload
            )
            log(
                f"preloaded {n_bg + n_subs * 8} background wildcard "
                f"subs in {time.perf_counter() - t_bg:.1f}s (mode={mode})"
            )
        port = srv.listeners[0].port
        loop = asyncio.get_running_loop()
        received = 0
        all_done = loop.create_future()
        sub_ready = [asyncio.Event() for _ in range(n_subs)]

        async def open_conn(cid):
            r, w = await asyncio.open_connection("127.0.0.1", port)
            w.write(
                C.serialize(
                    C.Connect(client_id=cid, proto_ver=C.MQTT_V5), C.MQTT_V5
                )
            )
            await w.drain()
            p = C.StreamParser(version=C.MQTT_V5)
            while True:
                data = await r.read(1 << 16)
                assert data, "connection closed during CONNECT"
                pkts = list(p.feed(data))
                if pkts:
                    assert pkts[0].type == C.CONNACK
                    break
            return r, w, p

        async def subscriber(i):
            nonlocal received
            r, w, p = await open_conn(f"bs{i}")
            w.write(
                C.serialize(
                    C.Subscribe(
                        packet_id=1,
                        subscriptions=[
                            C.Subscription(
                                topic_filter=f"bench/{i}/#", qos=0
                            )
                        ],
                    ),
                    C.MQTT_V5,
                )
            )
            await w.drain()
            while True:
                data = await r.read(1 << 16)
                if not data:
                    return
                for pkt in p.feed(data):
                    if pkt.type == C.SUBACK:
                        sub_ready[i].set()
                    elif pkt.type == C.PUBLISH:
                        lat.append(
                            loop.time()
                            - struct.unpack_from("d", pkt.payload)[0]
                        )
                        received += 1
                        if received >= total and not all_done.done():
                            all_done.set_result(None)

        async def publisher(j):
            r, w, p = await open_conn(f"bp{j}")
            acked = 0
            ack_evt = asyncio.Event()

            async def ack_reader():
                nonlocal acked
                while acked < n_msgs:
                    data = await r.read(1 << 16)
                    if not data:
                        return
                    for pkt in p.feed(data):
                        if pkt.type == C.PUBACK:
                            acked += 1
                            ack_evt.set()

            t = loop.create_task(ack_reader())
            pid = 0
            for k in range(n_msgs):
                sub_i = (j + k * 7) % n_subs
                pid = (pid % 65535) + 1
                w.write(
                    C.serialize(
                        C.Publish(
                            topic=f"bench/{sub_i}/v",
                            payload=struct.pack("d", loop.time()),
                            qos=1,
                            packet_id=pid,
                        ),
                        C.MQTT_V5,
                    )
                )
                if (k & 31) == 0:
                    await w.drain()
                while k - acked >= inflight:
                    ack_evt.clear()
                    await ack_evt.wait()
            await w.drain()
            await t
            w.close()

        probe_lat: list = []

        async def probe():
            """Low-rate probe: delivery latency under load without the
            queueing delay a saturating publisher measures (its own
            number is just backlog depth)."""
            r, w, p = await open_conn("bprobe")
            w.write(
                C.serialize(
                    C.Subscribe(
                        packet_id=1,
                        subscriptions=[
                            C.Subscription(topic_filter="probe/#", qos=0)
                        ],
                    ),
                    C.MQTT_V5,
                )
            )
            await w.drain()

            async def reader():
                while True:
                    data = await r.read(1 << 16)
                    if not data:
                        return
                    for pkt in p.feed(data):
                        if pkt.type == C.PUBLISH:
                            probe_lat.append(
                                loop.time()
                                - struct.unpack_from("d", pkt.payload)[0]
                            )

            rt = loop.create_task(reader())
            try:
                while True:
                    w.write(
                        C.serialize(
                            C.Publish(
                                topic="probe/t",
                                payload=struct.pack("d", loop.time()),
                                qos=0,
                            ),
                            C.MQTT_V5,
                        )
                    )
                    await w.drain()
                    await asyncio.sleep(0.005)
            except asyncio.CancelledError:
                rt.cancel()
                raise

        sub_tasks = [loop.create_task(subscriber(i)) for i in range(n_subs)]
        await asyncio.gather(*(e.wait() for e in sub_ready))
        if device and n_bg == 0:
            t_warm = time.perf_counter()

            def build_and_warm():
                # the threshold crossing kicked a BACKGROUND rebuild;
                # force a synchronous one (joins the builder) so the
                # automaton exists before warming the batch buckets
                eng = srv.broker.router.engine
                eng.rebuild()
                return eng.warmup(4096)

            warmed = await loop.run_in_executor(None, build_and_warm)
            log(
                f"warmed {warmed} kernel batch buckets in "
                f"{time.perf_counter() - t_warm:.1f}s"
            )
        probe_task = loop.create_task(probe())
        t0 = time.perf_counter()
        await asyncio.gather(*(publisher(j) for j in range(n_pubs)))
        await asyncio.wait_for(all_done, 120)
        elapsed = time.perf_counter() - t0
        loaded_probe = list(probe_lat)
        # quiet phase: pipeline latency with the backlog drained — the
        # number comparable to the reference's sub-ms delivery claim
        probe_lat.clear()
        await asyncio.sleep(1.5)
        quiet_probe = list(probe_lat)
        probe_task.cancel()
        for t in sub_tasks:
            t.cancel()
        stats = srv.broker.router.engine.index_stats()
        stages = {
            name: {
                "count": snap.count,
                "p50_us": round(snap.percentile(50), 1),
                "p99_us": round(snap.percentile(99), 1),
            }
            for name, snap in srv.broker.profiler.snapshots().items()
            if snap.count
        }
        await srv.stop()
        return elapsed, loaded_probe, quiet_probe, stats, stages

    (
        elapsed, loaded_probe, quiet_probe, eng_stats, window_stages
    ) = asyncio.run(bench())
    lat_ms = np.array(lat) * 1e3
    quiet_ms = np.array(quiet_probe or [0.0]) * 1e3
    loaded_ms = np.array(loaded_probe or [0.0]) * 1e3
    out = {
        "mode": mode,
        "msgs_per_s": total / elapsed,
        "delivery_p50_ms": float(np.percentile(quiet_ms, 50)),
        "delivery_p99_ms": float(np.percentile(quiet_ms, 99)),
        "loaded_probe_p50_ms": float(np.percentile(loaded_ms, 50)),
        "loaded_probe_p99_ms": float(np.percentile(loaded_ms, 99)),
        "saturated_sojourn_p50_ms": float(np.percentile(lat_ms, 50)),
        "pubs": n_pubs,
        "subs": n_subs,
        "bg_subs": n_bg,
        "total_msgs": total,
        "engine_stats": eng_stats,
        # per-stage window-pipeline percentiles from the profiler:
        # WHERE the window milliseconds live, not just the rate
        "window_stages_us": window_stages,
        "used_device_path": eng_stats.get("auto_dev_windows", 0) > 0
        or (mode == "device" and eng_stats.get("base", 0) > 0),
        "note": "in-process harness: clients share the broker's "
        "event loop; QoS1 publishers, 256 inflight, wildcard subs + "
        "bg_subs preloaded background wildcard set, full codec both "
        "directions; delivery p50/p99 from a 200 Hz probe after the "
        "flood drains (pipeline latency); loaded_probe = same probe "
        "during the flood (includes bounded queueing); "
        "saturated_sojourn = the flood's own messages (backlog depth, "
        "not pipeline).  mode=device pins every window through the "
        "device: over the axon tunnel its latency floor is the "
        "tunnel RTT (~100 ms, BENCH_DETAILS.tunnel_rtt_ms) — "
        "co-located hardware pays ~1-2 ms.  mode=auto is the shipping "
        "default: per-window measured-cost policy (host for shallow "
        "windows, device offload under congestion).",
    }
    log(
        f"broker e2e[{mode}]: {out['msgs_per_s']:,.0f} msg/s routed "
        f"({n_pubs}p/{n_subs}s+{n_bg}bg, qos1), delivery p50 "
        f"{out['delivery_p50_ms']:.1f} ms p99 "
        f"{out['delivery_p99_ms']:.1f} ms "
        f"(loaded probe p99 {out['loaded_probe_p99_ms']:.0f} ms, "
        f"saturated sojourn p50 "
        f"{out['saturated_sojourn_p50_ms']:.0f} ms, "
        f"auto={eng_stats.get('auto_host_windows')}h/"
        f"{eng_stats.get('auto_dev_windows')}d)"
    )
    return out


def main():
    import numpy as np

    import jax

    from emqx_tpu import topic as T
    from emqx_tpu.ops.automaton import (build_automaton, expand_codes_dedup,
                                        expand_codes_flat)
    from emqx_tpu.engine import _pad_batch
    from emqx_tpu.ops.dictionary import PAD_TOK, TokenDict, encode_topics
    from emqx_tpu.ops.match_kernel import match_batch, match_batch_compact

    from emqx_tpu.engine import enable_compile_cache

    enable_compile_cache()  # shape-class compiles persist across runs

    platform = jax.devices()[0].platform
    on_tpu = platform not in ("cpu",)
    n_subs = int(
        os.environ.get("BENCH_SUBS", 10_000_000 if on_tpu else 50_000)
    )
    batch = int(
        os.environ.get("BENCH_BATCH", 32768 if on_tpu else 4096)
    )
    iters = int(os.environ.get("BENCH_ITERS", 50 if on_tpu else 10))
    f_width = int(os.environ.get("BENCH_F", 4))
    m_cap = int(os.environ.get("BENCH_M", 16))
    depth = int(os.environ.get("BENCH_DEPTH", 8))  # batches in flight
    fanout = int(os.environ.get("BENCH_FANOUT", 8))
    n_insert = int(os.environ.get("BENCH_INSERTS", 100_000 if on_tpu else 20_000))
    max_levels = 16
    rng = np.random.default_rng(0)

    log(f"platform={platform} subs={n_subs} batch={batch} iters={iters} "
        f"fanout~{fanout}")

    t0 = time.perf_counter()
    filters, pops = make_filters(n_subs, fanout)
    gen_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    tdict = TokenDict()
    aut = build_automaton(filters, tdict, max_levels=max_levels)
    build_s = time.perf_counter() - t0
    fid_arr = np.arange(n_subs, dtype=np.int64)  # position == fid here
    log(
        f"built automaton: nodes={aut.n_nodes} buckets={len(aut.fp_rows)} "
        f"salt={aut.salt} kernel_levels={aut.kernel_levels} "
        f"in {build_s:.2f}s (gen {gen_s:.2f}s)"
    )

    streams = [
        make_topics(rng, batch, pops) for _ in range(iters)
    ]

    dev = tuple(jax.device_put(a) for a in aut.device_arrays())

    # per-topic MATRIX encode cache: live publish streams are
    # Zipf-heavy, so a hot topic is one dict hit yielding a row index
    # and the batch materializes as one fancy-index gather (the
    # engine's production path uses the same scheme,
    # engine._encode_rows).  Invalidated on dictionary growth, same
    # as the engine's generation check.
    levels = aut.kernel_levels
    enc_index = {}
    enc_mat = np.full((65536, levels), PAD_TOK, np.int32)
    enc_len = np.zeros(65536, np.int32)
    enc_dol = np.zeros(65536, bool)
    enc_state = [len(tdict), 0]  # [dict generation, rows used]

    nat = tdict.native()

    def submit(topic_strings):
        """Tokenize + dispatch one batch; returns device arrays without
        blocking (JAX async dispatch keeps `depth` batches in flight so
        host<->device latency amortizes away, as the broker's pipelined
        publish path does).  Tokenize = one C-speed map() over the row
        cache + a native (GIL-released) batch encode of the misses —
        the production engine's _encode_rows scheme."""
        nonlocal enc_mat, enc_len, enc_dol
        b = len(topic_strings)
        if len(tdict) != enc_state[0]:
            enc_index.clear()
            enc_state[:] = [len(tdict), 0]
        used = enc_state[1]
        if used >= 524288:  # reset only at a batch boundary (aliasing)
            enc_index.clear()
            used = 0
        js = list(map(enc_index.get, topic_strings))
        if None in js:
            miss_rows = {}
            miss_ts = []
            for i, j in enumerate(js):
                if j is None:
                    t = topic_strings[i]
                    r = miss_rows.get(t)
                    if r is None:
                        r = miss_rows[t] = used + len(miss_ts)
                        miss_ts.append(t)
                    js[i] = r
            need = used + len(miss_ts)
            while need > len(enc_len):
                cap = len(enc_len) * 2
                m2 = np.full((cap, levels), PAD_TOK, np.int32)
                m2[: len(enc_len)] = enc_mat
                enc_mat = m2
                enc_len = np.resize(enc_len, cap)
                enc_dol = np.resize(enc_dol, cap)
            if nat is not None:
                nat.encode_topics_into(
                    miss_ts, levels, enc_mat[used:need],
                    enc_len[used:need], enc_dol[used:need],
                )
            else:
                get = tdict.get
                for k, t in enumerate(miss_ts):
                    ws = T.words(t)
                    n = min(len(ws), levels)
                    row = enc_mat[used + k]
                    row[:] = PAD_TOK
                    for j2 in range(n):
                        row[j2] = get(ws[j2])
                    enc_len[used + k] = n
                    enc_dol[used + k] = bool(ws) and ws[0].startswith("$")
            enc_index.update(miss_rows)
            used = need
        idx = np.fromiter(js, np.int64, count=b)
        enc_state[1] = used
        # dedup the window: Zipf streams repeat hot topics (~2x here),
        # and each unique topic needs only one device row + one slot in
        # the device->host code transfer (the production engine dedups
        # the same way, engine._flat_dispatch)
        uniq, inv = np.unique(idx, return_inverse=True)
        tokens, lengths, dollar = _pad_batch(
            enc_mat[uniq], enc_len[uniq], enc_dol[uniq]
        )
        # COMPACT output layout: the dense [B, m_cap] code matrix at a
        # few-percent fill was 1 MB/batch of mostly -1 — the full-path
        # bottleneck through the ~10 MB/s axon tunnel (profiled: 114 of
        # 143 ms/batch was this transfer)
        out = match_batch_compact(
            *dev,
            tokens,
            lengths,
            dollar,
            f_width=f_width,
            m_cap=m_cap,
            c_cap=tokens.shape[0],
        )
        # start the device->host copies immediately so transfers overlap
        # with the next batches' compute instead of serializing on the
        # (tunnel-inflated) round-trip at drain time
        out[0].copy_to_host_async()
        out[1].copy_to_host_async()
        out[2].copy_to_host_async()
        return out, len(uniq), inv, (tokens, lengths, dollar)

    def drain(pending):
        """Transfer the compact code form and expand to per-topic fid
        lists with vectorized host CSR — the full route-lookup result
        (`emqx_router:match_routes` per topic), fanned back from the
        deduplicated device batch to every original topic row."""
        out, n_uniq, inv, enc = pending
        flat, counts, total = out
        if int(np.asarray(total)[0]) > len(flat):
            # compact buffer clipped: dense-kernel fallback (correct at
            # any fill; the c_cap sizing makes this rare)
            codes, _, ovf = match_batch(
                *dev, *enc, f_width=f_width, m_cap=m_cap
            )
            rows, pos = expand_codes_dedup(
                aut.code_off, aut.code_idx, np.asarray(codes)[:n_uniq], inv
            )
            return rows, fid_arr[pos], np.asarray(ovf)[:n_uniq][inv]
        counts = np.asarray(counts).astype(np.int64)
        ovf_u = counts < 0
        rows, pos = expand_codes_flat(
            aut.code_off, aut.code_idx, np.asarray(flat),
            np.where(ovf_u, -counts - 1, counts), inv,
        )
        fids = fid_arr[pos]  # flat (topic_row, fid) pairs
        return rows, fids, ovf_u[:n_uniq][inv]

    # warmup / compile
    t0 = time.perf_counter()
    rows, fids, ovf = drain(submit(streams[0]))
    log(f"compile+first batch: {time.perf_counter() - t0:.2f}s; "
        f"ovf={int(ovf.sum())} mean_fanout={len(fids) / batch:.2f}")

    # (a) device-only throughput: batches pre-encoded so the clock sees
    # only dispatch + device compute (host tokenize cost is excluded
    # here and included in the full-path phase below)
    encoded = [
        encode_topics(tdict, [T.words(t) for t in s], aut.kernel_levels)
        for s in streams
    ]
    # warm the full-batch shape (the pipelined phase above runs the
    # DEDUPED batch shape, so this one may not be compiled yet)
    match_batch(*dev, *encoded[0], f_width=f_width, m_cap=m_cap)[
        1
    ].block_until_ready()
    t0 = time.perf_counter()
    outs = [
        match_batch(
            *dev, *e, f_width=f_width, m_cap=m_cap
        )
        for e in encoded
    ]
    outs[-1][1].block_until_ready()
    device_rate = batch * iters / (time.perf_counter() - t0)
    del encoded, outs
    log(f"device-only match rate: {device_rate:,.0f} topics/s")

    # (b) full path, pipelined: submit keeps `depth` batches in flight,
    # drain produces host-visible fid lists for every batch
    from collections import deque

    total_matches = 0
    ovf_total = 0
    inflight = deque()
    t_start = time.perf_counter()
    for s in streams:
        inflight.append(submit(s))
        if len(inflight) >= depth:
            rows, fids, ovf = drain(inflight.popleft())
            total_matches += len(fids)
            ovf_total += int(ovf.sum())
    while inflight:
        rows, fids, ovf = drain(inflight.popleft())
        total_matches += len(fids)
        ovf_total += int(ovf.sum())
    elapsed = time.perf_counter() - t_start

    # (c) single-batch synchronous latency (includes host<->device
    # round-trip; on the axon tunnel this is dominated by ~100 ms RTT,
    # see BENCH_DETAILS.tunnel_rtt_ms)
    lat = []
    for s in streams[: min(iters, 10)]:
        t0 = time.perf_counter()
        drain(submit(s))
        lat.append(time.perf_counter() - t0)
    lat_ms = np.array(lat) * 1e3
    p50, p99 = np.percentile(lat_ms, [50, 99])

    # measure the bare dispatch round-trip to attribute latency fairly
    tiny = jax.jit(lambda a: a + 1)
    ta = jax.device_put(np.zeros(8, np.int32))
    np.asarray(tiny(ta))
    t0 = time.perf_counter()
    for _ in range(5):
        np.asarray(tiny(ta))
    tunnel_rtt_ms = (time.perf_counter() - t0) / 5 * 1e3

    # (c2) small-window sync latency: a production publish window is
    # ~1-4k topics, not 32k; this is the per-window match latency the
    # broker's pipeline hides, reported net of the link RTT so the
    # compute+transfer cost is visible separately from the (env-
    # specific) tunnel floor.
    small = [s[:1024] for s in streams[: min(iters, 10)]]
    drain(submit(small[0]))  # warm the 1024 shape
    lat_small = []
    for s in small:
        t0 = time.perf_counter()
        drain(submit(s))
        lat_small.append(time.perf_counter() - t0)
    small_ms = np.array(lat_small) * 1e3
    small_p50, small_p99 = np.percentile(small_ms, [50, 99])

    # host-trie rate at full scale: the reference-equivalent per-topic
    # CPU path against the SAME 10M-sub set — the honest at-scale
    # comparison for the device's batched full path
    host_rate = 0.0
    if os.environ.get("BENCH_HOST_RATE", "1") != "0":
        from emqx_tpu.ops.trie_native import make_trie

        t0 = time.perf_counter()
        htrie = make_trie()
        for fid, ws in filters:
            htrie.insert("/".join(ws), fid, ws)
        host_build_s = time.perf_counter() - t0
        sample = [T.words(t) for t in streams[0][:20000]]
        for ws in sample[:200]:
            htrie.match_words(ws)
        t0 = time.perf_counter()
        for ws in sample:
            htrie.match_words(ws)
        host_rate = len(sample) / (time.perf_counter() - t0)
        log(
            f"host trie @ {n_subs} subs: {host_rate:,.0f} topics/s "
            f"(build {host_build_s:.1f}s)"
        )
        del htrie

    total_topics = batch * iters
    rate = total_topics / elapsed

    insert_rps, churn_p50, churn_p99 = measure_insert_rps(
        filters[: min(n_subs, 1_000_000)], n_insert, log
    )

    def sub_bench(label: str, script: str, timeout: float,
                  env=None) -> dict:
        """One tool-subprocess bench phase: runs `tools/<script>`,
        parses its one-line JSON, logs the child's stderr tail when it
        fails (a swallowed traceback made every child failure read as
        'list index out of range')."""
        import subprocess

        log(f"{label} (subprocess {script})...")
        try:
            out = subprocess.run(
                [sys.executable,
                 os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "tools", script)],
                capture_output=True, text=True, timeout=timeout,
                env=env,
            )
            if out.returncode != 0 or not out.stdout.strip():
                log(f"{label} failed rc={out.returncode}: "
                    f"{out.stderr[-2000:]}")
                return {}
            stats = json.loads(out.stdout.strip().splitlines()[-1])
            log(f"{label}: {stats}")
            return stats
        except Exception as exc:
            log(f"{label} failed: {exc}")
            return {}

    sharded_stats = {}
    if os.environ.get("BENCH_SHARDED", "1") != "0":
        # the sharded engine runs on the driver's virtual 8-device CPU
        # mesh in a SUBPROCESS (this process must keep seeing the TPU)
        sharded_stats.update(sub_bench(
            "sharded mesh bench", "bench_sharded.py", 420
        ))
    if os.environ.get("BENCH_DS", "1") != "0":
        # DS layout: LTS learned-structure replay vs flat hash shards
        sharded_stats.update(sub_bench(
            "ds layout bench", "bench_ds.py", 420,
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        ))
    if os.environ.get("BENCH_CLUSTER_SHARDED", "1") != "0":
        # cluster-sharded route index: 2 OS-process nodes, the filter
        # set partitioned by rendezvous hash (~1/N each), scatter-
        # gather matching checked against the full-knowledge oracle
        sharded_stats.update(sub_bench(
            "cluster-sharded bench", "bench_cluster_sharded.py", 600,
            env=dict(os.environ, BENCH_SHARD_FILTERS=os.environ.get(
                "BENCH_SHARD_FILTERS", "1000000")),
        ))
    if os.environ.get("BENCH_MC", "1") != "0":
        # multi-core broker: worker processes + loadgen processes (the
        # whole phase lives outside this TPU-holding process)
        sharded_stats.update(sub_bench(
            "multicore broker bench", "bench_multicore.py", 540
        ))

    fanout_stats = {}
    if os.environ.get("BENCH_FANOUT_DISPATCH", "1") != "0":
        # the dispatch half of the pipeline (BENCH_r06+ tracks the
        # PR 3 tentpole): fixed fan-out sweep, encode+write counted
        fanout_stats = run_dispatch_fanout_bench(log)

    replay_stats = {}
    if os.environ.get("BENCH_REPLAY", "1") != "0":
        # mass-reconnect durable replay (BENCH_r08 tracks the resume
        # scheduler): scalar vs windowed sessions/s + storm drain
        replay_stats = run_replay_bench(log)

    durability_stats = {}
    if os.environ.get("BENCH_DURABILITY", "1") != "0":
        # fsync-mode A/B + naive per-message-fsync counterfactual +
        # cold recovery (BENCH_r12 tracks the PR 15 tentpole)
        durability_stats = run_durability_bench(log)

    ds_shard_stats = {}
    if os.environ.get("BENCH_DS_SHARD", "1") != "0":
        # sharded DS store: 1/2/4-shard fsynced append throughput,
        # restart-to-serving at 1M msgs (intact / journal-replay /
        # full rebuild), GC reclaim under live appends (BENCH_r13
        # tracks the PR 16 tentpole)
        ds_shard_stats = run_ds_shard_bench(log)

    cluster_fwd_stats = {}
    if os.environ.get("BENCH_CLUSTER_FORWARD", "1") != "0":
        # at-least-once window forwarding over tcp vs quic vs quic@1%
        # datagram loss (BENCH_r09 tracks the PR 11 tentpole)
        cluster_fwd_stats = run_cluster_forward_bench(log)

    overload_stats = {}
    if os.environ.get("BENCH_OVERLOAD", "1") != "0":
        # overload ladder on/off counterfactual + steady-state A/B
        # (BENCH_r11 tracks the PR 13 tentpole)
        overload_stats = run_overload_bench(log)

    flight_stats = {}
    if os.environ.get("BENCH_FLIGHT", "1") != "0":
        # always-on flight recorder armed vs off (BENCH_r15 tracks
        # the flight-recorder tentpole's <=2% overhead criterion)
        flight_stats = run_flightrec_bench(log)

    rules_stats = {}
    if os.environ.get("BENCH_RULES", "1") != "0":
        # rule-engine WHERE matrix vs the scalar interpreter referee
        # at 1k/10k registered rules (BENCH_r10 tracks the PR 12
        # tentpole)
        rules_stats = run_rules_bench(log)

    rule_egress_stats = {}
    if os.environ.get("BENCH_RULE_EGRESS", "1") != "0":
        # rule OUTPUT half: batched SELECT + micro-batched sink
        # egress vs the per-row scalar referee with per-record sink
        # round-trips (BENCH_r16 tracks the PR 20 tentpole)
        rule_egress_stats = run_rule_egress_bench(log)

    broker_stats = {}
    if os.environ.get("BENCH_BROKER", "1") != "0":
        # three rows at >=1M background subs: host-pinned (the
        # reference-equivalent per-window CPU trie), the SHIPPING
        # default (device on, adaptive per-window policy — must beat
        # host on throughput AND p99 or the policy has failed), and
        # device-pinned (documents the tunnel-RTT floor)
        host = run_broker_bench(log, "host")
        broker_stats = {"broker_" + k: v for k, v in host.items()}
        auto = run_broker_bench(log, "auto")
        broker_stats.update(
            {"broker_device_" + k: v for k, v in auto.items()}
        )
        forced = run_broker_bench(log, "device")
        broker_stats.update(
            {"broker_device_forced_" + k: v for k, v in forced.items()}
        )

    details = {
        "platform": platform,
        "n_subs": n_subs,
        "batch": batch,
        "iters": iters,
        "build_s": build_s,
        "nodes": aut.n_nodes,
        "salt": aut.salt,
        "rate_topics_per_s": rate,
        "device_only_rate_topics_per_s": device_rate,
        "sync_batch_latency_ms_p50": float(p50),
        "sync_batch_latency_ms_p99": float(p99),
        "sync_1k_window_ms_p50": float(small_p50),
        "sync_1k_window_ms_p99": float(small_p99),
        "sync_1k_window_net_of_rtt_ms_p50": float(
            max(small_p50 - tunnel_rtt_ms, 0.0)
        ),
        "sync_1k_window_net_of_rtt_ms_p99": float(
            max(small_p99 - tunnel_rtt_ms, 0.0)
        ),
        "host_trie_rate_topics_per_s": float(host_rate),
        "tunnel_rtt_ms": float(tunnel_rtt_ms),
        "pipeline_depth": depth,
        "overflow_frac": ovf_total / total_topics,
        "mean_matches_per_topic": total_matches / total_topics,
        "insert_rps": insert_rps,
        "churn_match_p50_ms": churn_p50,
        "churn_match_p99_ms": churn_p99,
        "timing_covers": "cached tokenize (per-topic encode rows, "
        "Zipf-hit-rate dependent — matches the production engine's "
        "cache) + device match + async compact-code transfer + "
        "vectorized host CSR expand to per-topic fid lists",
        "dispatch_fanout_msgs_per_s": fanout_stats,
        "replay": replay_stats,
        "durability": durability_stats,
        "ds_shard": ds_shard_stats,
        "cluster_forward": cluster_fwd_stats,
        "rules": rules_stats,
        "rule_egress": rule_egress_stats,
        "overload": overload_stats,
        "flightrec": flight_stats,
        **sharded_stats,
        **broker_stats,
    }
    with open(
        os.path.join(os.path.dirname(__file__) or ".", "BENCH_DETAILS.json"),
        "w",
    ) as f:
        json.dump(details, f, indent=2)
    log(json.dumps(details))

    print(
        json.dumps(
            {
                "metric": "wildcard_topic_matches_per_sec_per_chip",
                "value": round(rate, 1),
                "unit": (
                    f"topics/s full-path @ {n_subs} wildcard subs, "
                    f"fanout {total_matches / total_topics:.1f} "
                    f"({insert_rps:,.0f} inserts/s; device-only "
                    f"{device_rate:,.0f}/s; broker e2e "
                    f"{broker_stats.get('broker_msgs_per_s', 0):,.0f} "
                    f"msg/s qos1 p99 "
                    f"{broker_stats.get('broker_delivery_p99_ms', 0):.0f}"
                    f" ms)"
                ),
                "vs_baseline": round(rate / 1_000_000, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
