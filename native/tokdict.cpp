// Native token dictionary + batch filter encoder.
//
// The engine's encode arenas re-encode subscription deltas on every
// fold/rebuild; the per-filter Python loop (dict.add per word) holds
// the GIL for the whole burst and steals ~half the insert thread's
// throughput under sustained churn.  This encoder does the same work
// in one ctypes call (GIL released): split each filter on '/', map
// words to dense ids ('+' -> PLUS_TOK, trailing '#' -> is_hash), and
// fill the caller's numpy arrays in place.
//
// Token-id semantics mirror emqx_tpu/ops/dictionary.py exactly:
// sequential non-negative ids in first-seen order; PLUS_TOK = -3,
// PAD_TOK = -4.  The Python TokenDict stays the fast-path lookup map:
// every word NEW to this call is reported back as (id, offset, length)
// into the input blob so the caller can mirror it into its dict —
// both maps always hold the identical word -> id relation.
//
// Thread safety: none here; callers serialize mutations (the engine's
// _enc_lock), same contract as the Python dict it mirrors.

#include <cstdint>
#include <string>
#include <string_view>
#include <unordered_map>

namespace {

constexpr int32_t PLUS_TOK = -3;
constexpr int32_t PAD_TOK = -4;
constexpr int32_t UNKNOWN_TOK = -2;

struct TokDict {
    std::unordered_map<std::string, int32_t> ids;
};

}  // namespace

extern "C" {

void* td_new() { return new TokDict(); }

void td_free(void* h) { delete static_cast<TokDict*>(h); }

int64_t td_len(void* h) {
    return static_cast<int64_t>(static_cast<TokDict*>(h)->ids.size());
}

int32_t td_add(void* h, const char* w, int64_t len) {
    auto* d = static_cast<TokDict*>(h);
    std::string key(w, static_cast<size_t>(len));
    auto it = d->ids.find(key);
    if (it != d->ids.end()) return it->second;
    int32_t id = static_cast<int32_t>(d->ids.size());
    d->ids.emplace(std::move(key), id);
    return id;
}

// Bulk-seed the mirror from an existing Python dict: word i =
// blob[starts[i], starts[i]+lens[i]) gets id i (insertion order ==
// id order for the Python dict being mirrored).
void td_seed(void* h, const char* blob, const int64_t* starts,
             const int64_t* lens, int64_t n) {
    auto* d = static_cast<TokDict*>(h);
    d->ids.reserve(static_cast<size_t>(n) * 2);
    for (int64_t i = 0; i < n; i++) {
        d->ids.emplace(
            std::string(blob + starts[i], static_cast<size_t>(lens[i])),
            static_cast<int32_t>(i));
    }
}

int32_t td_get(void* h, const char* w, int64_t len) {
    auto* d = static_cast<TokDict*>(h);
    auto it = d->ids.find(std::string(w, static_cast<size_t>(len)));
    return it == d->ids.end() ? -2 /* UNKNOWN_TOK */ : it->second;
}

// Encode `n` filters out of `blob` (filter i = blob[starts[i],
// starts[i]+lens[i])), writing mat[i*max_levels ..], blen[i], ish[i].
// New words are reported as new_ids[k] / new_spans[2k]=offset /
// new_spans[2k+1]=len.  Returns the count of new words (>= 0) —
// ALWAYS, including on failure, because words inserted before the
// failing filter are already in this map and the caller's Python
// mirror must learn them or the two dictionaries diverge for good.
// *err_i reports the first filter whose body exceeds max_levels (the
// call stops there; its arena rows are not usable), or -1 on success.
int64_t td_encode_filters(void* h, const char* blob, const int64_t* starts,
                          const int64_t* lens, int64_t n,
                          int32_t max_levels, int32_t* mat,
                          int32_t* blen, uint8_t* ish, int32_t* new_ids,
                          int64_t* new_spans, int64_t new_cap,
                          int64_t* err_i) {
    auto* d = static_cast<TokDict*>(h);
    int64_t n_new = 0;
    *err_i = -1;
    for (int64_t i = 0; i < n; i++) {
        const char* s = blob + starts[i];
        const int64_t len = lens[i];
        int32_t* row = mat + i * max_levels;
        for (int32_t k = 0; k < max_levels; k++) row[k] = PAD_TOK;
        // trailing '#' level => hash terminal, stripped from the body
        int64_t body_len = len;
        bool hash = false;
        if (len >= 1 && s[len - 1] == '#' &&
            (len == 1 || s[len - 2] == '/')) {
            hash = true;
            body_len = len >= 2 ? len - 2 : 0;  // drop "#" and its '/'
        }
        ish[i] = hash ? 1 : 0;
        int32_t nlev = 0;
        if (!(body_len == 0 && hash && len == 1)) {
            // split body on '/'; an empty body with hash ("#") has no
            // levels at all, but "a//#" keeps its empty middle level
            int64_t start = 0;
            for (int64_t p = 0; p <= body_len; p++) {
                if (p == body_len || s[p] == '/') {
                    if (nlev >= max_levels) {
                        *err_i = i;
                        return n_new;
                    }
                    const char* w = s + start;
                    const int64_t wl = p - start;
                    if (wl == 1 && w[0] == '+') {
                        row[nlev++] = PLUS_TOK;
                    } else {
                        std::string key(w, static_cast<size_t>(wl));
                        auto it = d->ids.find(key);
                        int32_t id;
                        if (it != d->ids.end()) {
                            id = it->second;
                        } else {
                            id = static_cast<int32_t>(d->ids.size());
                            d->ids.emplace(std::move(key), id);
                            if (n_new < new_cap) {
                                new_ids[n_new] = id;
                                new_spans[2 * n_new] = starts[i] + start;
                                new_spans[2 * n_new + 1] = wl;
                            }
                            n_new++;
                        }
                        row[nlev++] = id;
                    }
                    start = p + 1;
                }
            }
        }
        blen[i] = nlev;
    }
    return n_new;
}

// Topic-row encode (the publish-path tokenizer's MISS path): topic
// i = blob[starts[i], starts[i]+lens[i]) fills row i of the caller's
// mat/lens/dollar slices — get-only lookups (UNKNOWN for words no
// filter ever used), truncation at `levels`, '$'-flag from the first
// byte.  The caller owns the hit cache (a Python dict keyed on the
// topic string, invalidated when the dictionary grows).
void td_encode_topics_into(void* h, const char* blob,
                           const int64_t* starts, const int64_t* lens,
                           int64_t n, int32_t levels, int32_t* mat,
                           int32_t* out_lens, uint8_t* dollar) {
    auto* d = static_cast<TokDict*>(h);
    for (int64_t i = 0; i < n; i++) {
        const char* s = blob + starts[i];
        const int64_t len = lens[i];
        int32_t* mrow = mat + i * levels;
        for (int32_t k = 0; k < levels; k++) mrow[k] = PAD_TOK;
        dollar[i] = (len > 0 && s[0] == '$') ? 1 : 0;
        int32_t nlev = 0;
        int64_t start = 0;
        for (int64_t p = 0; p <= len && nlev < levels; p++) {
            if (p == len || s[p] == '/') {
                auto wit = d->ids.find(
                    std::string(s + start, static_cast<size_t>(p - start)));
                mrow[nlev++] =
                    wit == d->ids.end() ? UNKNOWN_TOK : wit->second;
                start = p + 1;
            }
        }
        out_lens[i] = nlev;
    }
}

}  // extern "C"
