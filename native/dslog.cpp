// dslog: append-only stream log with (stream, time) ordered index.
//
// The native storage engine under emqx_tpu.ds.builtin_local — the slot
// the reference fills with RocksDB via erlang-rocksdb
// (/root/reference/rebar.config:85; apps/emqx_durable_storage/src/
// emqx_ds_storage_layer.erl).  Scope-matched to what the DS layer
// actually needs from its KV store: append message batches under a
// (stream-id, timestamp) key, replay a stream from a timestamp in
// order, survive restart (log is the source of truth; the index
// rebuilds on open), and recover from damage without silent loss.
//
// Layout: <dir>/seg-<n>.log, records are
//   [u32 len][u32 crc32(payload)][u32 stream][u64 ts][u64 seq][payload]
// A segment rolls at seg_bytes.  Readers use pread on the segment fd,
// so appends and iteration don't contend.
//
// Crash/corruption contract (the PR 15 durability tentpole):
//
//   * TORN TAIL — a record whose extent reaches EOF but fails its CRC
//     (or an incomplete header/payload at EOF) is the artifact of an
//     append cut by a crash: it is truncated away, exactly as before.
//   * INTERIOR CORRUPTION — a record that fails its CRC but whose
//     extent ends BEFORE EOF was once intact and got flipped on disk
//     (bit rot, a misdirected write).  The segment's suffix from that
//     record on is QUARANTINED: never indexed, never truncated (the
//     bytes are preserved on disk for forensics), never appended into
//     (a quarantined final segment rolls to a fresh one on open), and
//     never reclaimed by gc.  The intact prefix keeps serving.  The
//     walkable-record estimate of the suffix accumulates in
//     `corrupt_records` so the binding can raise the
//     `ds_storage_corruption` alarm instead of losing data silently —
//     the old behavior (truncate at first CRC break) destroyed the
//     whole suffix with no trace.
//   * fsync ordering — rolling fsyncs the outgoing segment before
//     closing it and fsyncs the directory after creating a segment
//     file, so one dslog_sync on the current fd covers every record
//     appended since the previous sync, across rolls.
//
// C ABI (ctypes-friendly): all functions return >=0 on success,
// negative errno-style codes on failure.

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <sys/uio.h>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint32_t kHeaderLen = 4 + 4 + 4 + 8 + 8;
constexpr uint64_t kDefaultSegBytes = 64ull << 20;
constexpr uint32_t kMaxRecordLen = 128u << 20;

uint32_t crc32_table[256];
struct CrcInit {
  CrcInit() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      crc32_table[i] = c;
    }
  }
} crc_init;

uint32_t crc32(const uint8_t* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++)
    c = crc32_table[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Entry {
  uint64_t ts;
  uint64_t seq;
  uint32_t seg;
  uint64_t off;   // offset of payload within segment
  uint32_t len;
};

struct Db {
  std::string dir;
  uint64_t seg_bytes = kDefaultSegBytes;
  // per-stream ordered index: (ts, seq) -> location
  std::map<uint32_t, std::map<std::pair<uint64_t, uint64_t>, Entry>> index;
  std::map<uint32_t, int> seg_fds;  // read fds per segment
  uint32_t cur_seg = 0;
  int cur_fd = -1;
  uint64_t cur_size = 0;
  uint64_t next_seq = 1;
  // interior-corruption quarantine (see header comment)
  int64_t corrupt_records = 0;
  std::set<uint32_t> quarantined;
  std::mutex mu;

  ~Db() {
    if (cur_fd >= 0) close(cur_fd);
    for (auto& kv : seg_fds)
      if (kv.second >= 0 && kv.second != cur_fd) close(kv.second);
  }
};

struct Iter {
  Db* db;
  uint32_t stream;
  // resume key: strictly-greater-than cursor
  uint64_t ts = 0;
  uint64_t seq = 0;
  bool first = true;
};

std::string seg_path(const Db& db, uint32_t seg) {
  char buf[32];
  snprintf(buf, sizeof buf, "/seg-%06u.log", seg);
  return db.dir + buf;
}

int open_segment_fd(Db& db, uint32_t seg) {
  auto it = db.seg_fds.find(seg);
  if (it != db.seg_fds.end()) return it->second;
  int fd = open(seg_path(db, seg).c_str(), O_RDONLY);
  db.seg_fds[seg] = fd;
  return fd;
}

// walk the unreadable suffix by its length fields to estimate how
// many records it holds (>= 1; trailing unwalkable garbage counts 1).
int64_t count_suffix_records(int fd, uint64_t off, uint64_t size) {
  int64_t n = 0;
  uint64_t o = off;
  while (o + kHeaderLen <= size) {
    uint32_t len;
    if (pread(fd, &len, 4, o) != 4) break;
    if (len > kMaxRecordLen || o + kHeaderLen + len > size) break;
    n++;
    o += kHeaderLen + len;
  }
  if (o < size || n == 0) n++;
  return n;
}

// scan one segment, filling the index.  A torn TAIL (partial append
// cut by a crash: damage reaching EOF) truncates as before; damage
// with intact bytes written after it is interior corruption and
// quarantines the suffix (kept on disk, not served) instead of
// silently destroying it.
int recover_segment(Db& db, uint32_t seg) {
  std::string path = seg_path(db, seg);
  int fd = open(path.c_str(), O_RDWR);
  if (fd < 0) return -errno;
  struct stat st;
  if (fstat(fd, &st) != 0) { int e = -errno; close(fd); return e; }
  uint64_t size = (uint64_t)st.st_size, off = 0;
  std::vector<uint8_t> buf;
  bool quarantine = false;
  while (off + kHeaderLen <= size) {
    uint8_t head[kHeaderLen];
    if (pread(fd, head, kHeaderLen, off) != (ssize_t)kHeaderLen) {
      // the header lies within the file (loop guard) yet could not
      // be read: an IO error (bad sector), not a torn write —
      // truncating would destroy whatever intact data follows, so
      // quarantine conservatively
      quarantine = true;
      break;
    }
    uint32_t len, crc, stream;
    uint64_t ts, seq;
    memcpy(&len, head, 4);
    memcpy(&crc, head + 4, 4);
    memcpy(&stream, head + 8, 4);
    memcpy(&ts, head + 12, 8);
    memcpy(&seq, head + 20, 8);
    if (len > kMaxRecordLen) {
      // a complete header with an implausible length was flipped on
      // disk (writev writes the header atomically enough that a torn
      // append leaves a prefix, not garbage); bytes beyond the bare
      // header mean data follows it — interior corruption
      quarantine = size - off > kHeaderLen;
      break;
    }
    if (off + kHeaderLen + len > size) break;  // extends past EOF: torn
    buf.resize(len);
    if (pread(fd, buf.data(), len, off + kHeaderLen) != (ssize_t)len) {
      // extent is fully inside the file: a short/failed read is a
      // bad sector, not a crash artifact — quarantine, never truncate
      quarantine = true;
      break;
    }
    if (crc32(buf.data(), len) != crc) {
      // extent ends before EOF -> something intact was written after
      // this record, so it was once valid: interior corruption.  At
      // EOF it is the torn tail of the crashed append.
      quarantine = off + kHeaderLen + len < size;
      break;
    }
    db.index[stream][{ts, seq}] =
        Entry{ts, seq, seg, off + kHeaderLen, len};
    if (seq >= db.next_seq) db.next_seq = seq + 1;
    off += kHeaderLen + len;
  }
  if (off < size) {
    if (quarantine) {
      db.corrupt_records += count_suffix_records(fd, off, size);
      db.quarantined.insert(seg);
    } else if (ftruncate(fd, (off_t)off) != 0) {
      int e = -errno;
      close(fd);
      return e;
    }
  }
  close(fd);
  return 0;
}

int fsync_dir(const std::string& dir) {
  int dfd = open(dir.c_str(), O_RDONLY);
  if (dfd < 0) return -errno;
  int rc = fsync(dfd) != 0 ? -errno : 0;
  close(dfd);
  return rc;
}

int roll_segment(Db& db) {
  if (db.cur_fd >= 0) {
    // sync-ordering invariant: the outgoing segment is fully durable
    // before it becomes unreachable from dslog_sync (which only
    // fsyncs cur_fd) — a group-commit sync after a roll must cover
    // the records appended before it.  A FAILED flush here must fail
    // the roll (and so the append): swallowing it would let a later
    // dslog_sync on the fresh segment report success over un-flushed
    // records — the "acked means durable" contract broken silently.
    // State stays consistent for a retry: cur_fd remains the old
    // segment and cur_size still exceeds seg_bytes.
    if (fsync(db.cur_fd) != 0) return -errno;
    close(db.cur_fd);
    // also close any cached READ fd for the rolled segment (distinct
    // from cur_fd) before dropping it from the map — else it leaks
    auto it = db.seg_fds.find(db.cur_seg);
    if (it != db.seg_fds.end()) {
      if (it->second >= 0 && it->second != db.cur_fd) close(it->second);
      db.seg_fds.erase(it);
    }
    db.cur_seg++;
  }
  std::string path = seg_path(db, db.cur_seg);
  bool fresh = access(path.c_str(), F_OK) != 0;
  db.cur_fd = open(path.c_str(), O_CREAT | O_WRONLY | O_APPEND, 0644);
  if (db.cur_fd < 0) return -errno;
  if (fresh) fsync_dir(db.dir);  // the dir entry must survive too
  struct stat st;
  fstat(db.cur_fd, &st);
  db.cur_size = (uint64_t)st.st_size;
  return 0;
}

}  // namespace

extern "C" {

// open (and recover) a db directory; returns handle or null.
void* dslog_open(const char* dir, uint64_t seg_bytes) {
  Db* db = new Db;
  db->dir = dir;
  if (seg_bytes) db->seg_bytes = seg_bytes;
  mkdir(dir, 0755);
  // find existing segments
  std::vector<uint32_t> segs;
  if (DIR* d = opendir(dir)) {
    while (dirent* e = readdir(d)) {
      unsigned n;
      if (sscanf(e->d_name, "seg-%06u.log", &n) == 1) segs.push_back(n);
    }
    closedir(d);
  }
  uint32_t max_seg = 0;
  for (uint32_t s : segs) {
    if (recover_segment(*db, s) != 0) { delete db; return nullptr; }
    if (s > max_seg) max_seg = s;
  }
  db->cur_seg = segs.empty() ? 0 : max_seg;
  if (db->quarantined.count(db->cur_seg)) {
    // the final segment carries a quarantined suffix: appends must
    // never land after unreadable bytes (recovery would quarantine
    // them too) — start a fresh segment instead
    db->cur_seg = max_seg + 1;
  }
  // open current segment for append (without rolling past it)
  {
    std::string path = seg_path(*db, db->cur_seg);
    bool fresh = access(path.c_str(), F_OK) != 0;
    db->cur_fd = open(path.c_str(), O_CREAT | O_WRONLY | O_APPEND, 0644);
    if (db->cur_fd < 0) { delete db; return nullptr; }
    if (fresh) fsync_dir(db->dir);
    struct stat st;
    fstat(db->cur_fd, &st);
    db->cur_size = (uint64_t)st.st_size;
  }
  return db;
}

void dslog_close(void* h) { delete static_cast<Db*>(h); }

// append one record; returns assigned seq (>0) or negative error.
int64_t dslog_append(void* h, uint32_t stream, uint64_t ts,
                     const uint8_t* data, uint32_t len) {
  Db& db = *static_cast<Db*>(h);
  std::lock_guard<std::mutex> lock(db.mu);
  if (db.cur_size >= db.seg_bytes) {
    int rc = roll_segment(db);
    if (rc != 0) return rc;
  }
  uint64_t seq = db.next_seq++;
  uint8_t head[kHeaderLen];
  uint32_t crc = crc32(data, len);
  memcpy(head, &len, 4);
  memcpy(head + 4, &crc, 4);
  memcpy(head + 8, &stream, 4);
  memcpy(head + 12, &ts, 8);
  memcpy(head + 20, &seq, 8);
  struct iovec iov[2] = {{head, kHeaderLen}, {(void*)data, len}};
  ssize_t n = writev(db.cur_fd, iov, 2);
  if (n != (ssize_t)(kHeaderLen + len)) {
    // a short write (ENOSPC/EINTR) left stray bytes at EOF: truncate
    // back so later appends land where the index says they do
    if (n > 0) ftruncate(db.cur_fd, (off_t)db.cur_size);
    db.next_seq--;  // seq was not durably consumed
    return -EIO;
  }
  uint64_t payload_off = db.cur_size + kHeaderLen;
  db.index[stream][{ts, seq}] =
      Entry{ts, seq, db.cur_seg, payload_off, len};
  db.cur_size += kHeaderLen + len;
  return (int64_t)seq;
}

int dslog_sync(void* h) {
  Db& db = *static_cast<Db*>(h);
  std::lock_guard<std::mutex> lock(db.mu);
  return db.cur_fd >= 0 && fsync(db.cur_fd) != 0 ? -errno : 0;
}

// list distinct stream ids; out_cap in elements. returns count stored.
int dslog_streams(void* h, uint32_t* out, int out_cap) {
  Db& db = *static_cast<Db*>(h);
  std::lock_guard<std::mutex> lock(db.mu);
  int n = 0;
  for (auto& kv : db.index) {
    if (n < out_cap) out[n] = kv.first;
    n++;
  }
  return n;
}

void* dslog_iter_new(void* h, uint32_t stream, uint64_t ts_from) {
  Iter* it = new Iter;
  it->db = static_cast<Db*>(h);
  it->stream = stream;
  it->ts = ts_from;
  it->seq = 0;
  it->first = true;
  return it;
}

void dslog_iter_free(void* itp) { delete static_cast<Iter*>(itp); }

// next record: fills buf (cap bytes), ts/seq out. returns payload len,
// 0 at end, negative on error; -E2BIG when cap is too small (record is
// NOT consumed — retry with a bigger buffer).
int64_t dslog_iter_next(void* itp, uint8_t* buf, uint32_t cap,
                        uint64_t* ts_out, uint64_t* seq_out) {
  Iter& it = *static_cast<Iter*>(itp);
  Db& db = *it.db;
  Entry e;
  {
    std::lock_guard<std::mutex> lock(db.mu);
    auto sit = db.index.find(it.stream);
    if (sit == db.index.end()) return 0;
    auto& m = sit->second;
    // first call: >= (ts_from, 0); afterwards strictly greater
    auto mit = it.first ? m.lower_bound({it.ts, 0})
                        : m.upper_bound({it.ts, it.seq});
    if (mit == m.end()) return 0;
    e = mit->second;
  }
  if (e.len > cap) return -E2BIG;
  int fd;
  {
    std::lock_guard<std::mutex> lock(db.mu);
    fd = open_segment_fd(db, e.seg);
  }
  if (fd < 0) return -EIO;
  if (pread(fd, buf, e.len, (off_t)e.off) != (ssize_t)e.len) return -EIO;
  it.ts = e.ts;
  it.seq = e.seq;
  it.first = false;
  *ts_out = e.ts;
  *seq_out = e.seq;
  return (int64_t)e.len;
}

// retention GC: unlink whole segments whose every record is older than
// cutoff_ts (the current segment is never dropped).  Returns the number
// of records reclaimed.  Segment-granular like RocksDB generation drops
// — cheap, no rewrite.  A segment id doubles as the store's GENERATION:
// `pin_floor` is the lowest generation some live replay cursor still
// needs — segments at or above it are never reclaimed, whatever their
// age (pass UINT32_MAX for "nothing pinned").
int64_t dslog_gc2(void* h, uint64_t cutoff_ts, uint32_t pin_floor) {
  Db& db = *static_cast<Db*>(h);
  std::lock_guard<std::mutex> lock(db.mu);
  // per-segment max ts + record count
  std::map<uint32_t, std::pair<uint64_t, int64_t>> seg_stat;
  for (auto& skv : db.index)
    for (auto& ekv : skv.second) {
      auto& st = seg_stat[ekv.second.seg];
      if (ekv.second.ts > st.first) st.first = ekv.second.ts;
      st.second++;
    }
  int64_t reclaimed = 0;
  for (auto& kv : seg_stat) {
    uint32_t seg = kv.first;
    if (seg == db.cur_seg || kv.second.first >= cutoff_ts) continue;
    if (seg >= pin_floor) continue;  // generation pinned by a cursor
    // a quarantined segment is preserved for forensics: its suffix's
    // timestamps are unknowable, so age-based reclaim never applies
    if (db.quarantined.count(seg)) continue;
    auto fdit = db.seg_fds.find(seg);
    if (fdit != db.seg_fds.end()) {
      if (fdit->second >= 0) close(fdit->second);
      db.seg_fds.erase(fdit);
    }
    unlink(seg_path(db, seg).c_str());
    for (auto& skv : db.index) {
      auto& m = skv.second;
      for (auto it = m.begin(); it != m.end();)
        it = it->second.seg == seg ? m.erase(it) : std::next(it);
    }
    reclaimed += kv.second.second;
  }
  return reclaimed;
}

int64_t dslog_gc(void* h, uint64_t cutoff_ts) {
  return dslog_gc2(h, cutoff_ts, UINT32_MAX);
}

// generation (= segment id) of the first record of `stream` strictly
// after cursor (ts, seq): the generation a resuming session's replay
// cursor pins.  -1 when the cursor is exhausted (nothing left to read,
// so nothing to pin).
int64_t dslog_seg_for(void* h, uint32_t stream, uint64_t ts,
                      uint64_t seq) {
  Db& db = *static_cast<Db*>(h);
  std::lock_guard<std::mutex> lock(db.mu);
  auto sit = db.index.find(stream);
  if (sit == db.index.end()) return -1;
  auto mit = sit->second.upper_bound({ts, seq});
  if (mit == sit->second.end()) return -1;
  return (int64_t)mit->second.seg;
}

// the current generation (the segment new appends land in)
int64_t dslog_cur_seg(void* h) {
  Db& db = *static_cast<Db*>(h);
  std::lock_guard<std::mutex> lock(db.mu);
  return (int64_t)db.cur_seg;
}

// estimated record count across quarantined suffixes (corruption the
// recovery detected and preserved instead of serving or destroying)
int64_t dslog_corrupt_records(void* h) {
  Db& db = *static_cast<Db*>(h);
  std::lock_guard<std::mutex> lock(db.mu);
  return db.corrupt_records;
}

// number of segments carrying a quarantined suffix
int dslog_quarantined_count(void* h) {
  Db& db = *static_cast<Db*>(h);
  std::lock_guard<std::mutex> lock(db.mu);
  return (int)db.quarantined.size();
}

// record count for a stream (for stats/tests)
int64_t dslog_stream_count(void* h, uint32_t stream) {
  Db& db = *static_cast<Db*>(h);
  std::lock_guard<std::mutex> lock(db.mu);
  auto sit = db.index.find(stream);
  return sit == db.index.end() ? 0 : (int64_t)sit->second.size();
}

}  // extern "C"
