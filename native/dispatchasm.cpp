// GIL-released per-run packet assembly for the dispatch fan-out.
//
// The dispatch window encoder (codec.mqtt.DispatchEncoder) serializes
// each unique PUBLISH body once per window into a contiguous arena and
// records, per body, the head span (fixed header .. topic) and tail
// span (properties + payload) around the 2-byte packet-id slot.  The
// Python hot loop used to splice those per subscriber (one bytes join
// + one Packet object per delivery); this kernel does the whole run —
// every delivery for ONE client — in a single ctypes call: head
// splice, big-endian pid patch, tail splice, straight into one
// caller-sized output buffer that becomes the connection's corked
// write.  ctypes releases the GIL for the duration, so a large run's
// memcpy work overlaps the batcher's executor threads.

#include <cstdint>
#include <cstring>

extern "C" {

// Assemble one client's delivery run into `out` (caller-allocated to
// the exact total size).  Per delivery i: body[i] indexes the arena
// span tables; pid[i] >= 0 means a QoS>0 frame whose 2-byte packet id
// is spliced between head and tail, pid[i] < 0 a QoS 0 frame whose
// head span IS the whole frame (tail_len 0).  Returns bytes written
// (the caller asserts it equals the precomputed total).
int64_t da_assemble_run(const uint8_t* arena,
                        const int64_t* head_off, const int64_t* head_len,
                        const int64_t* tail_off, const int64_t* tail_len,
                        const int64_t* body, const int64_t* pid,
                        int64_t n, uint8_t* out) {
    uint8_t* w = out;
    for (int64_t i = 0; i < n; i++) {
        const int64_t b = body[i];
        const int64_t hl = head_len[b];
        std::memcpy(w, arena + head_off[b], (size_t)hl);
        w += hl;
        const int64_t p = pid[i];
        if (p >= 0) {
            *w++ = (uint8_t)((p >> 8) & 0xFF);
            *w++ = (uint8_t)(p & 0xFF);
        }
        const int64_t tl = tail_len[b];
        if (tl) {
            std::memcpy(w, arena + tail_off[b], (size_t)tl);
            w += tl;
        }
    }
    return (int64_t)(w - out);
}

// Assemble an entire dispatch window — EVERY client's run — in one
// GIL-released call.  The caller concatenates the per-run (body, pid)
// columns into window-wide arrays and precomputes each run's byte
// offset into the shared output buffer (the "splice plan"): run j
// covers deliveries [run_start[j], run_start[j+1]) and its bytes must
// land exactly at out + run_out_off[j], so each client's slice of the
// window buffer becomes that connection's corked write with zero
// re-copy.  The per-run offset is re-checked at every run boundary:
// one corrupt span table mis-sizing run j returns -(j+1) immediately
// instead of silently shifting every later client's wire bytes.
int64_t da_assemble_window(const uint8_t* arena,
                           const int64_t* head_off, const int64_t* head_len,
                           const int64_t* tail_off, const int64_t* tail_len,
                           const int64_t* body, const int64_t* pid,
                           const int64_t* run_start,
                           const int64_t* run_out_off,
                           int64_t n_runs, int64_t n_total, uint8_t* out) {
    uint8_t* w = out;
    for (int64_t j = 0; j < n_runs; j++) {
        if (w != out + run_out_off[j]) return -(j + 1);
        const int64_t end = (j + 1 < n_runs) ? run_start[j + 1] : n_total;
        for (int64_t i = run_start[j]; i < end; i++) {
            const int64_t b = body[i];
            const int64_t hl = head_len[b];
            std::memcpy(w, arena + head_off[b], (size_t)hl);
            w += hl;
            const int64_t p = pid[i];
            if (p >= 0) {
                *w++ = (uint8_t)((p >> 8) & 0xFF);
                *w++ = (uint8_t)(p & 0xFF);
            }
            const int64_t tl = tail_len[b];
            if (tl) {
                std::memcpy(w, arena + tail_off[b], (size_t)tl);
                w += tl;
            }
        }
    }
    return (int64_t)(w - out);
}

}  // extern "C"
