// GIL-released sort/unique primitives for the automaton assembler.
//
// numpy's argsort/unique hold the GIL; the assembler runs them over
// million-row edge arrays in a BACKGROUND builder thread, which
// froze the insert/publish thread for tens of milliseconds per
// rebuild under churn.  ctypes calls release the GIL, so routing the
// two dominant kernels here lets the builder run truly parallel.

#include <algorithm>
#include <cstdint>
#include <numeric>

extern "C" {

// Stable argsort of int64 keys: order_out[k] = index of k-th smallest.
void su_argsort_i64(const int64_t* in, int64_t n, int64_t* order_out) {
    std::iota(order_out, order_out + n, int64_t{0});
    std::stable_sort(order_out, order_out + n,
                     [in](int64_t a, int64_t b) { return in[a] < in[b]; });
}

// unique + inverse (np.unique(..., return_inverse=True) semantics):
// uniq_out gets the sorted distinct values, inv_out[i] the position of
// in[i] within them.  Returns the distinct count.  uniq_out needs
// capacity n; scratch needs capacity n.
int64_t su_unique_inverse_i64(const int64_t* in, int64_t n,
                              int64_t* uniq_out, int64_t* inv_out,
                              int64_t* scratch) {
    if (n == 0) return 0;
    su_argsort_i64(in, n, scratch);
    int64_t m = 0;
    int64_t prev = 0;
    for (int64_t k = 0; k < n; k++) {
        const int64_t i = scratch[k];
        const int64_t v = in[i];
        if (k == 0 || v != prev) {
            uniq_out[m++] = v;
            prev = v;
        }
        inv_out[i] = m - 1;
    }
    return m;
}

}  // extern "C"
