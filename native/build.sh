#!/usr/bin/env bash
# Rebuild every native helper .so with the exact flags the checked-in
# binaries (and the on-demand rebuilders in emqx_tpu/ops/*_native.py /
# dispatchasm.py) use.  Each loader also rebuilds its own lib lazily
# when the source is newer than the binary, so running this script is
# only needed for a clean rebuild or a toolchain bump.
#
# A lib that fails to build is reported and SKIPPED: every native lib
# has a pure-Python fallback, and tier-1 skips the native parity tests
# when the lib is absent (mirroring tests/test_tokdict_native.py).
# All sources are C++17-only by design (hosttrie's old heterogeneous
# unordered_map lookup needed GCC >= 11 and was rewritten), so any
# toolchain this repo meets builds every lib.

set -u
cd "$(dirname "$0")"
mkdir -p build

FLAGS="-O3 -fPIC -shared -std=c++17 -Wall"
status=0

for src in sortutil tokdict dslog hosttrie dispatchasm; do
    out="build/lib${src}.so"
    if g++ $FLAGS -o "$out" "${src}.cpp"; then
        echo "built $out"
    else
        echo "SKIPPED $out (build failed; pure-Python fallback will serve)" >&2
        status=1
    fi
done

exit $status
