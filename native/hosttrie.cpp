// Native host-side wildcard-filter trie: the hot-path twin of
// emqx_tpu/ops/trie_host.py (same MQTT matching semantics: '+'/'#'
// per level, '#' also matches its parent, root wildcards excluded for
// '$'-topics — the reference rules from emqx_trie_search.erl:260-348).
//
// Python's per-insert cost (~20 us: node allocation, dict walks) caps
// subscription churn at ~20k inserts/s; this engine's 100k+/s target
// needs the index mutations native.  Exposed through a C ABI for
// ctypes (pybind11 is not available in this environment); the Python
// wrapper (emqx_tpu/ops/trie_native.py) interns arbitrary Python fid
// objects to dense int64 handles.
//
// Levels are the '/'-separated byte strings of the filter, stored
// verbatim (UTF-8 passthrough, empty levels preserved).

#include <cstdint>
#include <cstring>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

// C++17-portable child lookup: heterogeneous unordered_map find()
// (P0919) needs libstdc++ >= 11, so lookups go through one
// thread_local std::string key buffer instead — assign() reuses its
// capacity, so the hot path still allocates no level strings after
// warm-up, on every toolchain this repo meets.
using ChildMap = std::unordered_map<std::string, int32_t>;

thread_local std::string tl_key;

static inline ChildMap::iterator find_sv(ChildMap& ch,
                                         std::string_view sv) {
    tl_key.assign(sv.data(), sv.size());
    return ch.find(tl_key);
}

struct Node {
    ChildMap children;
    // fid -> insertion sequence number; the seq tags let one trie
    // serve both the full set (ht_match) and the "inserted since the
    // last fold watermark" residual view (ht_match_since) without a
    // second structure or a rebuild at fold time
    std::unordered_map<int64_t, int64_t> exact;  // filters ending here
    std::unordered_map<int64_t, int64_t> hash;   // filters '<path>/#'
    // max insert seq anywhere in this node's subtree (monotone upper
    // bound; deletes leave it stale, which only costs pruning power).
    // ht_match_since skips whole subtrees below the watermark, so the
    // residual walk is O(residual-touched paths), not O(full trie).
    int64_t max_seq = 0;
    bool empty() const {
        return children.empty() && exact.empty() && hash.empty();
    }
};

struct Trie {
    std::vector<Node> nodes;      // index 0 = root
    std::vector<int32_t> free_;   // pruned node slots for reuse
    // fid -> its filter string (needed for delete + replace semantics)
    std::unordered_map<int64_t, std::string> filters;
    int64_t seq = 0;              // monotonically increasing insert tag
    Trie() { nodes.emplace_back(); }

    int32_t alloc() {
        if (!free_.empty()) {
            int32_t i = free_.back();
            free_.pop_back();
            nodes[i] = Node();
            return i;
        }
        nodes.emplace_back();
        return (int32_t)nodes.size() - 1;
    }
};

// split on '/', preserving empty levels ("a//b" -> ["a", "", "b"]);
// "" -> [""] (one empty level), matching emqx_tpu.topic.words.
// string_views into the caller's buffer: zero allocations.
static void split_levels(const char* s, std::vector<std::string_view>& out) {
    out.clear();
    const char* start = s;
    const char* p = s;
    for (;; ++p) {
        if (*p == '/' || *p == '\0') {
            out.emplace_back(start, (size_t)(p - start));
            if (*p == '\0') break;
            start = p + 1;
        }
    }
}

thread_local std::vector<std::string_view> tl_ws;

static void remove_path(Trie* t, const std::string& flt, int64_t fid) {
    std::vector<std::string_view> ws;
    split_levels(flt.c_str(), ws);
    bool terminal_hash = !ws.empty() && ws.back() == "#";
    size_t body = terminal_hash ? ws.size() - 1 : ws.size();
    std::vector<int32_t> path;  // nodes along the walk (excluding root)
    int32_t node = 0;
    for (size_t i = 0; i < body; ++i) {
        auto it = find_sv(t->nodes[node].children, ws[i]);
        if (it == t->nodes[node].children.end()) return;
        path.push_back(node);
        node = it->second;
    }
    if (terminal_hash)
        t->nodes[node].hash.erase(fid);
    else
        t->nodes[node].exact.erase(fid);
    // prune now-empty nodes bottom-up
    for (size_t i = body; i-- > 0;) {
        int32_t parent = path[i];
        auto it = find_sv(t->nodes[parent].children, ws[i]);
        if (it == t->nodes[parent].children.end()) break;
        int32_t child = it->second;
        if (!t->nodes[child].empty()) break;
        t->nodes[parent].children.erase(it);
        t->free_.push_back(child);
        node = parent;
    }
}

}  // namespace

extern "C" {

void* ht_new() { return new Trie(); }

void ht_free(void* h) { delete static_cast<Trie*>(h); }

int64_t ht_len(void* h) {
    return (int64_t)static_cast<Trie*>(h)->filters.size();
}

// Insert `flt` under `fid`; re-inserting the same fid replaces its
// previous filter.  Returns the assigned sequence tag (> 0), or 0 when
// the set did not change (same fid, same filter).
int64_t ht_insert(void* h, const char* flt, int64_t fid) {
    Trie* t = static_cast<Trie*>(h);
    auto it = t->filters.find(fid);
    if (it != t->filters.end()) {
        if (it->second == flt) return 0;
        remove_path(t, it->second, fid);
    }
    auto& ws = tl_ws;
    split_levels(flt, ws);
    bool terminal_hash = !ws.empty() && ws.back() == "#";
    size_t body = terminal_hash ? ws.size() - 1 : ws.size();
    int32_t node = 0;
    for (size_t i = 0; i < body; ++i) {
        auto& ch = t->nodes[node].children;
        auto cit = find_sv(ch, ws[i]);
        if (cit == ch.end()) {
            int32_t nn = t->alloc();
            // alloc() may reallocate nodes; re-find the child map
            t->nodes[node].children.emplace(std::string(ws[i]), nn);
            node = nn;
        } else {
            node = cit->second;
        }
    }
    int64_t seq = ++t->seq;
    if (terminal_hash)
        t->nodes[node].hash[fid] = seq;
    else
        t->nodes[node].exact[fid] = seq;
    t->filters[fid] = flt;
    // refresh subtree max along the inserted path (root included)
    node = 0;
    t->nodes[0].max_seq = seq;
    for (size_t i = 0; i < body; ++i) {
        node = find_sv(t->nodes[node].children, ws[i])->second;
        t->nodes[node].max_seq = seq;
    }
    return seq;
}

// Batch insert (the emqx_router_syncer batching shape: route ops
// arrive in windows, and one GIL-released call amortizes the ctypes
// boundary).  Filter i = blob[starts[i], starts[i]+lens[i]); seqs_out
// gets each insert's sequence tag (0 when unchanged).
void ht_insert_batch(void* h, const char* blob, const int64_t* starts,
                     const int64_t* lens, const int64_t* fids,
                     int64_t n, int64_t* seqs_out) {
    for (int64_t i = 0; i < n; i++) {
        std::string f(blob + starts[i], static_cast<size_t>(lens[i]));
        seqs_out[i] = ht_insert(h, f.c_str(), fids[i]);
    }
}

// Latest assigned sequence tag (the fold watermark source).
int64_t ht_seq(void* h) { return static_cast<Trie*>(h)->seq; }

int32_t ht_delete(void* h, int64_t fid) {
    Trie* t = static_cast<Trie*>(h);
    auto it = t->filters.find(fid);
    if (it == t->filters.end()) return 0;
    remove_path(t, it->second, fid);
    t->filters.erase(it);
    return 1;
}

// Match a concrete topic.  Fills `out` (capacity `cap`) with matching
// fids and returns the TOTAL match count (callers grow the buffer and
// retry when the return exceeds cap).
int64_t ht_match(void* h, const char* topic, int64_t* out, int64_t cap) {
    Trie* t = static_cast<Trie*>(h);
    std::vector<std::string_view> name;
    split_levels(topic, name);
    bool dollar = !name.empty() && !name[0].empty() && name[0][0] == '$';
    int64_t n = 0;
    auto emit = [&](const std::unordered_map<int64_t, int64_t>& ids) {
        for (auto& kv : ids) {
            if (n < cap) out[n] = kv.first;
            ++n;
        }
    };
    std::vector<std::pair<int32_t, size_t>> stack;
    stack.emplace_back(0, 0);
    const size_t len = name.size();
    while (!stack.empty()) {
        auto [node, i] = stack.back();
        stack.pop_back();
        // root '#' never matches '$'-topics
        if (!(dollar && node == 0)) emit(t->nodes[node].hash);
        if (i == len) {
            emit(t->nodes[node].exact);
            continue;
        }
        auto& ch = t->nodes[node].children;
        auto lit = find_sv(ch, name[i]);
        if (lit != ch.end()) stack.emplace_back(lit->second, i + 1);
        if (!(dollar && i == 0)) {
            auto plus = find_sv(ch, std::string_view("+", 1));
            if (plus != ch.end()) stack.emplace_back(plus->second, i + 1);
        }
    }
    return n;
}

// Like ht_match, but only filters whose insertion tag is >= min_seq —
// the residual ("inserted since the last fold") view used by the match
// engine's overlay.  Same walk, filtered emit.
int64_t ht_match_since(void* h, const char* topic, int64_t min_seq,
                       int64_t* out, int64_t cap) {
    Trie* t = static_cast<Trie*>(h);
    std::vector<std::string_view> name;
    split_levels(topic, name);
    bool dollar = !name.empty() && !name[0].empty() && name[0][0] == '$';
    int64_t n = 0;
    auto emit = [&](const std::unordered_map<int64_t, int64_t>& ids) {
        for (auto& kv : ids) {
            if (kv.second < min_seq) continue;
            if (n < cap) out[n] = kv.first;
            ++n;
        }
    };
    std::vector<std::pair<int32_t, size_t>> stack;
    if (t->nodes[0].max_seq >= min_seq) stack.emplace_back(0, 0);
    const size_t len = name.size();
    while (!stack.empty()) {
        auto [node, i] = stack.back();
        stack.pop_back();
        if (!(dollar && node == 0)) emit(t->nodes[node].hash);
        if (i == len) {
            emit(t->nodes[node].exact);
            continue;
        }
        auto& ch = t->nodes[node].children;
        auto lit = find_sv(ch, name[i]);
        if (lit != ch.end() && t->nodes[lit->second].max_seq >= min_seq)
            stack.emplace_back(lit->second, i + 1);
        if (!(dollar && i == 0)) {
            auto plus = find_sv(ch, std::string_view("+", 1));
            if (plus != ch.end() && t->nodes[plus->second].max_seq >= min_seq)
                stack.emplace_back(plus->second, i + 1);
        }
    }
    return n;
}

}  // extern "C"
