"""exproto gateway: a toy line-protocol implemented in an external
gRPC ConnectionUnaryHandler drives real broker sessions through the
ConnectionAdapter service (emqx_gateway_exproto parity, full loop over
real sockets + real gRPC)."""

import asyncio
import threading
from concurrent import futures

import grpc
import pytest

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.gateway.exproto import (
    ADAPTER_SERVICE,
    HANDLER_SERVICE,
    pb,
)
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


class LineHandler:
    """The 'external protocol service': a newline-framed protocol —
    CONNECT <id> / SUB <topic> / PUB <topic> <payload> — answering OK,
    and turning broker deliveries into 'MSG <topic> <payload>' lines."""

    def __init__(self):
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers((
            grpc.method_handlers_generic_handler(
                HANDLER_SERVICE, self._handlers()
            ),
        ))
        self.port = self._server.add_insecure_port("127.0.0.1:0")
        self._adapter = None
        self._adapter_lock = threading.Lock()
        self.events = []

    def connect_adapter(self, port):
        chan = grpc.insecure_channel(f"127.0.0.1:{port}")

        def stub(name, req_cls):
            return chan.unary_unary(
                f"/{ADAPTER_SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=pb.CodeResponse.FromString,
            )

        self._adapter = {
            "Send": stub("Send", pb.SendBytesRequest),
            "Authenticate": stub("Authenticate", pb.AuthenticateRequest),
            "Subscribe": stub("Subscribe", pb.SubscribeRequest),
            "Publish": stub("Publish", pb.PublishRequest),
            "StartTimer": stub("StartTimer", pb.TimerRequest),
        }

    def start(self):
        self._server.start()

    def stop(self):
        self._server.stop(0.2).wait()

    def _handlers(self):
        E = pb.EmptySuccess

        def unary(fn, req_cls):
            def call(request, context):
                fn(request)
                return E()

            return grpc.unary_unary_rpc_method_handler(
                call,
                request_deserializer=req_cls.FromString,
                response_serializer=E.SerializeToString,
            )

        return {
            "OnSocketCreated": unary(
                lambda r: self.events.append(("created", r.conn)),
                pb.SocketCreatedRequest,
            ),
            "OnSocketClosed": unary(
                lambda r: self.events.append(("closed", r.conn)),
                pb.SocketClosedRequest,
            ),
            "OnReceivedBytes": unary(
                self._on_bytes, pb.ReceivedBytesRequest
            ),
            "OnTimerTimeout": unary(
                lambda r: self.events.append(("timeout", r.conn)),
                pb.TimerTimeoutRequest,
            ),
            "OnReceivedMessages": unary(
                self._on_messages, pb.ReceivedMessagesRequest
            ),
        }

    def _reply(self, conn, text):
        self._adapter["Send"](pb.SendBytesRequest(
            conn=conn, bytes=(text + "\n").encode()
        ))

    def _on_bytes(self, r):
        for line in bytes(r.bytes).decode().splitlines():
            parts = line.strip().split(" ", 2)
            if not parts or not parts[0]:
                continue
            cmd = parts[0]
            if cmd == "CONNECT":
                rsp = self._adapter["Authenticate"](pb.AuthenticateRequest(
                    conn=r.conn,
                    clientinfo=pb.ClientInfo(
                        proto_name="line", proto_ver="1",
                        clientid=parts[1],
                    ),
                ))
                self._adapter["StartTimer"](pb.TimerRequest(
                    conn=r.conn, type=pb.KEEPALIVE, interval=30
                ))
                self._reply(r.conn, "OK" if rsp.code == 0 else "ERR")
            elif cmd == "SUB":
                rsp = self._adapter["Subscribe"](pb.SubscribeRequest(
                    conn=r.conn, topic=parts[1], qos=1
                ))
                self._reply(r.conn, "OK" if rsp.code == 0 else "ERR")
            elif cmd == "PUB":
                rsp = self._adapter["Publish"](pb.PublishRequest(
                    conn=r.conn, topic=parts[1], qos=1,
                    payload=parts[2].encode(),
                ))
                self._reply(r.conn, "OK" if rsp.code == 0 else "ERR")

    def _on_messages(self, r):
        for m in r.messages:
            self._reply(
                r.conn, f"MSG {m.topic} {m.payload.decode()}"
            )


class LineClient:
    def __init__(self, port):
        self.port = port

    async def start(self):
        self.r, self.w = await asyncio.open_connection("127.0.0.1", self.port)
        return self

    async def cmd(self, line, expect="OK"):
        self.w.write((line + "\n").encode())
        await self.w.drain()
        got = (await asyncio.wait_for(self.r.readline(), 5)).decode().strip()
        assert got == expect, (line, got)

    async def readline(self):
        return (await asyncio.wait_for(self.r.readline(), 5)).decode().strip()

    def close(self):
        self.w.close()


def test_exproto_line_protocol_roundtrip():
    async def t():
        handler = LineHandler()
        handler.start()

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.gateways = [{
            "type": "exproto", "bind": "127.0.0.1", "port": 0,
            "handler": f"127.0.0.1:{handler.port}",
        }]
        srv = BrokerServer(cfg)
        await srv.start()
        gw = srv.broker.gateways.get("exproto")
        handler.connect_adapter(gw.adapter_port)

        mqtt = TestClient(srv.listeners[0].port, "m-obs")
        await mqtt.connect()
        await mqtt.subscribe("line/#", qos=1)

        lc = await LineClient(gw.port).start()
        await lc.cmd("CONNECT dev-7")
        await lc.cmd("SUB alerts/#")
        await lc.cmd("PUB line/up hello-from-line")

        # line client's publish reaches the MQTT subscriber
        pub = await mqtt.recv_publish()
        assert pub.topic == "line/up" and pub.payload == b"hello-from-line"

        # MQTT publish reaches the line client as a MSG line
        await mqtt.publish("alerts/fire", b"evacuate", qos=1)
        got = await lc.readline()
        assert got == "MSG alerts/fire evacuate"

        # gateway session is visible to the broker core
        assert srv.broker.cm.lookup("dev-7") is not None

        lc.close()
        await asyncio.sleep(0.2)
        assert ("closed", handler.events[0][1]) in handler.events

        await mqtt.close()
        await srv.stop()
        handler.stop()

    run(t())
