"""exhook graft server: a gRPC client drives the HookProvider service
over a real loopback channel (the reference contract an external EMQX
speaks, apps/emqx_exhook/priv/protos/exhook.proto)."""

import grpc
import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.config import BrokerConfig
from emqx_tpu.exhook import pb
from emqx_tpu.exhook.server import SERVICE, ExhookServer
from emqx_tpu.rules.engine import FunctionAction


def rpc(channel, method, req, resp_cls):
    fn = channel.unary_unary(
        f"/{SERVICE}/{method}",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=resp_cls.FromString,
    )
    return fn(req, timeout=5)


@pytest.fixture()
def served():
    broker = Broker(BrokerConfig())
    srv = ExhookServer(broker=broker, bind="127.0.0.1:0")
    srv.start()
    chan = grpc.insecure_channel(f"127.0.0.1:{srv.port}")
    yield broker, srv, chan
    chan.close()
    srv.stop()


def test_provider_loaded_lists_hooks(served):
    broker, srv, chan = served
    resp = rpc(
        chan,
        "OnProviderLoaded",
        pb.ProviderLoadedRequest(
            broker=pb.BrokerInfo(version="5.8.0"),
            meta=pb.RequestMeta(node="emqx@remote", cluster_name="cl1"),
        ),
        pb.LoadedResponse,
    )
    names = {h.name for h in resp.hooks}
    assert "message.publish" in names and "client.authenticate" in names
    pub = next(h for h in resp.hooks if h.name == "message.publish")
    assert list(pub.topics) == ["#"]
    assert broker.metrics.val("exhook.provider.loaded") == 1


def test_message_publish_verdicts(served):
    broker, srv, chan = served

    from emqx_tpu.hooks import STOP_WITH

    # a hook that drops secret topics and rewrites others
    def gate(msg):
        if msg.topic.startswith("secret/"):
            return STOP_WITH(None)
        if msg.topic == "rewrite/me":
            msg.topic = "rewritten/you"
        return msg

    broker.hooks.add("message.publish", gate)

    def publish(topic, payload=b"x"):
        return rpc(
            chan,
            "OnMessagePublish",
            pb.MessagePublishRequest(
                message=pb.Message(topic=topic, payload=payload, qos=1)
            ),
            pb.ValuedResponse,
        )

    ok = publish("plain/topic")
    assert ok.type == pb.ValuedResponse.IGNORE

    dropped = publish("secret/launch-codes")
    assert dropped.type == pb.ValuedResponse.STOP_AND_RETURN
    assert dropped.message.headers["allow_publish"] == "false"

    moved = publish("rewrite/me")
    assert moved.type == pb.ValuedResponse.CONTINUE
    assert moved.message.topic == "rewritten/you"


def test_message_publish_runs_rules(served):
    broker, srv, chan = served
    hits = []
    broker.rules.add_rule(
        "r1",
        'SELECT payload.v AS v FROM "metrics/#" WHERE payload.v > 10',
        actions=[FunctionAction(fn=lambda sel, msg: hits.append(sel["v"]))],
    )
    for v, topic in ((5, "metrics/a"), (42, "metrics/b"), (9, "other/c")):
        rpc(
            chan,
            "OnMessagePublish",
            pb.MessagePublishRequest(
                message=pb.Message(topic=topic, payload=b'{"v": %d}' % v)
            ),
            pb.ValuedResponse,
        )
    assert hits == [42]


def test_authenticate_and_authorize(served):
    broker, srv, chan = served
    from emqx_tpu.access import DictAuthenticator

    broker.access.allow_anonymous = False
    authn = DictAuthenticator()
    authn.add_user("alice", "wonder")
    broker.access.authenticators.append(authn)

    def auth(clientid, username, password):
        return rpc(
            chan,
            "OnClientAuthenticate",
            pb.ClientAuthenticateRequest(
                clientinfo=pb.ClientInfo(
                    clientid=clientid, username=username, password=password
                )
            ),
            pb.ValuedResponse,
        )

    assert auth("c1", "alice", "wonder").bool_result is True
    assert auth("c1", "alice", "nope").bool_result is False
    assert auth("c2", "mallory", "x").bool_result is False

    resp = rpc(
        chan,
        "OnClientAuthorize",
        pb.ClientAuthorizeRequest(
            clientinfo=pb.ClientInfo(clientid="c1", username="alice"),
            type=pb.ClientAuthorizeRequest.PUBLISH,
            topic="t/1",
        ),
        pb.ValuedResponse,
    )
    assert resp.bool_result is True  # default authz allow


def test_notification_hooks_fan_into_local_chain(served):
    broker, srv, chan = served
    seen = []
    broker.hooks.add(
        "session.subscribed", lambda cid, topic: seen.append((cid, topic))
    )
    rpc(
        chan,
        "OnSessionSubscribed",
        pb.SessionSubscribedRequest(
            clientinfo=pb.ClientInfo(clientid="dev-1"),
            topic="fleet/+/pos",
            subopts=pb.SubOpts(qos=1),
        ),
        pb.EmptySuccess,
    )
    assert seen == [("dev-1", "fleet/+/pos")]
    assert broker.metrics.val("exhook.session.subscribed") == 1
