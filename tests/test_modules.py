"""Broker modules: delayed publish ($delayed/), topic rewrite,
exclusive subscriptions ($exclusive/), auto-subscribe
(emqx_modules/emqx_delayed.erl, emqx_rewrite.erl,
emqx_exclusive_subscription.erl, emqx_auto_subscribe)."""

import asyncio
import time

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.modules import RewriteRule
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def make_server(**cfg_fn):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    for k, v in cfg_fn.items():
        setattr(cfg, k, v)
    return BrokerServer(cfg)


def test_delayed_publish():
    async def t():
        srv = make_server()
        await srv.start()
        port = srv.listeners[0].port
        sub = TestClient(port, "s")
        await sub.connect()
        await sub.subscribe("job/#", qos=1)
        pub = TestClient(port, "p")
        await pub.connect()
        t0 = time.monotonic()
        await pub.publish("$delayed/1/job/run", b"later", qos=1)
        assert len(srv.broker.delayed) == 1
        # nothing delivered before the delay elapses
        try:
            got_early = await sub.recv(timeout=0.3)
            assert got_early is None or got_early.type != 3
        except asyncio.TimeoutError:
            pass  # exactly what we want: nothing arrived
        srv.broker.delayed.tick(time.time() + 2)  # fast-forward
        pkt = await sub.recv_publish()
        assert pkt.topic == "job/run" and pkt.payload == b"later"
        # malformed delay drops
        await pub.publish("$delayed/notanum", b"x", qos=1)
        assert len(srv.broker.delayed) == 0
        await pub.disconnect()
        await sub.disconnect()
        await srv.stop()

    run(t())


def test_topic_rewrite_pub_and_sub():
    async def t():
        srv = make_server()
        await srv.start()
        srv.broker.rewrite.add_rule(
            RewriteRule(
                action="all",
                source="x/#",
                pattern=r"^x/y/(.+)$",
                dest=r"z/y/\1",
            )
        )
        port = srv.listeners[0].port
        sub = TestClient(port, "s")
        await sub.connect()
        # subscribing x/y/+ actually lands on z/y/+
        await sub.subscribe("x/y/+", qos=1)
        pub = TestClient(port, "p")
        await pub.connect()
        await pub.publish("z/y/direct", b"d", qos=1)
        assert (await sub.recv_publish()).payload == b"d"
        # publishing x/y/1 is rewritten to z/y/1
        await pub.publish("x/y/1", b"r", qos=1)
        pkt = await sub.recv_publish()
        assert pkt.topic == "z/y/1" and pkt.payload == b"r"
        await pub.disconnect()
        await sub.disconnect()
        await srv.stop()

    run(t())


def test_exclusive_subscription():
    async def t():
        srv = make_server()
        srv.broker.config.mqtt.exclusive_subscription = True
        await srv.start()
        port = srv.listeners[0].port
        a = TestClient(port, "a")
        await a.connect()
        ack = await a.subscribe("$exclusive/lock/1", qos=1)
        assert ack.reason_codes[0] <= 2
        b = TestClient(port, "b")
        await b.connect()
        ack_b = await b.subscribe("$exclusive/lock/1", qos=1)
        assert ack_b.reason_codes[0] == 0x97  # already held
        # holder receives messages on the REAL topic
        pub = TestClient(port, "p")
        await pub.connect()
        await pub.publish("lock/1", b"m", qos=1)
        assert (await a.recv_publish()).payload == b"m"
        # release on disconnect frees the lock
        await a.disconnect()
        await asyncio.sleep(0.05)
        ack_b2 = await b.subscribe("$exclusive/lock/1", qos=1)
        assert ack_b2.reason_codes[0] <= 2
        await b.disconnect()
        await pub.disconnect()
        await srv.stop()

    run(t())


def test_exclusive_disabled_by_default():
    async def t():
        srv = make_server()
        await srv.start()
        a = TestClient(srv.listeners[0].port, "a")
        await a.connect()
        ack = await a.subscribe("$exclusive/q/1", qos=1)
        assert ack.reason_codes[0] >= 0x80
        await a.disconnect()
        await srv.stop()

    run(t())


def test_auto_subscribe():
    async def t():
        srv = make_server(
            auto_subscribe=[{"topic": "inbox/%c", "qos": 1}]
        )
        await srv.start()
        port = srv.listeners[0].port
        c = TestClient(port, "dev9")
        await c.connect()
        pub = TestClient(port, "p")
        await pub.connect()
        await pub.publish("inbox/dev9", b"auto", qos=1)
        pkt = await c.recv_publish()
        assert pkt.payload == b"auto"
        await pub.disconnect()
        await c.disconnect()
        await srv.stop()

    run(t())


def test_topic_metrics_counters_and_rate():
    """emqx_modules topic-metrics: registered filters count matching
    publishes per qos and deliveries; rates refresh on tick; the cap
    and double-registration guard hold."""
    import time as _time

    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.config import BrokerConfig
    from emqx_tpu.message import Message
    from tests_fakes import FakeChannel

    broker = Broker(BrokerConfig())
    tm = broker.topic_metrics
    assert tm.register("metrics/+/t")
    assert not tm.register("metrics/+/t")  # duplicate

    ch = FakeChannel()
    broker.cm.open_session(True, "watcher", ch)
    from emqx_tpu.broker.session import SubOpts

    broker.subscribe("watcher", "metrics/#", SubOpts(qos=0))

    broker.publish(Message(topic="metrics/a/t", payload=b"1", qos=1))
    broker.publish(Message(topic="metrics/a/t", payload=b"2", qos=0))
    broker.publish(Message(topic="other/x", payload=b"3", qos=0))

    (entry,) = tm.info()
    assert entry["topic"] == "metrics/+/t"
    assert entry["messages.in"] == 2
    assert entry["messages.qos1.in"] == 1
    assert entry["messages.out"] == 2  # delivered to the watcher

    tm.tick(_time.time() + 2.0)
    (entry,) = tm.info()
    assert entry["rate.in"] > 0

    assert tm.unregister("metrics/+/t")
    assert not tm.unregister("metrics/+/t")

    # invalid filters are rejected at registration
    import pytest as _pytest

    with _pytest.raises(ValueError):
        tm.register("bad/#/middle")
