"""Multi-core broker: worker processes share one SO_REUSEPORT listener
and cluster over loopback — a client landing on any worker reaches
subscribers owned by any other (the esockd-acceptor-pool +
broker-pool role, emqx_broker.erl:539-540, as processes)."""

import asyncio
import json
import os
import subprocess
import sys

from emqx_tpu.broker.multicore import (free_ports, spawn_workers,
                                       worker_configs)
from mqtt_client import TestClient


def test_worker_configs_shape():
    cfgs = worker_configs(3, 1883)
    assert len(cfgs) == 3
    for i, cfg in enumerate(cfgs):
        assert cfg["listeners"][0]["port"] == 1883
        assert cfg["listeners"][0]["reuse_port"] is True
        assert cfg["node_name"] == f"worker{i}"
        assert cfg["engine"]["use_device"] is False
        seeds = cfg["cluster"]["seeds"]
        assert len(seeds) == 2 and all(
            s[0] != f"worker{i}" for s in seeds
        )
    # all workers agree on each other's cluster ports
    ports = {c["node_name"]: c["cluster"]["port"] for c in cfgs}
    for cfg in cfgs:
        for name, _h, p in cfg["cluster"]["seeds"]:
            assert ports[name] == p


def test_cross_worker_pubsub():
    port = free_ports(1)[0]
    pool = spawn_workers(3, port, bind="127.0.0.1")
    try:
        pool.wait_ready(port, timeout=120)

        async def t():
            await asyncio.sleep(2.0)  # cluster mesh settles
            # many clients spread across workers by the kernel; every
            # subscriber must receive regardless of worker placement
            subs = []
            for i in range(6):
                c = TestClient(port, f"mcs{i}")
                await c.connect()
                await c.subscribe(f"mc/{i}/#", qos=1)
                subs.append(c)
            await asyncio.sleep(1.0)  # route replication
            pub = TestClient(port, "mcp")
            await pub.connect()
            for i in range(6):
                await pub.publish(f"mc/{i}/x", str(i).encode(), qos=1,
                                  timeout=10)
            for i, c in enumerate(subs):
                m = await c.recv_publish(timeout=10)
                assert m.topic == f"mc/{i}/x"
                assert m.payload == str(i).encode()
            await pub.close()
            for c in subs:
                await c.close()

        asyncio.run(t())
        assert pool.alive() == 3
    finally:
        pool.stop()


def test_worker_configs_shard_durable_homes(tmp_path):
    """Durable multicore pools shard their session homes: per-worker
    data dirs + the crc32 shard rule in every worker's resume config
    (no two workers may hold rival checkpoints for one client)."""
    base = {"durable": {"enable": True, "data_dir": str(tmp_path)}}
    cfgs = worker_configs(2, 1883, base_config=base,
                          service_socket="/tmp/svc.sock")
    for i, cfg in enumerate(cfgs):
        assert cfg["durable"]["data_dir"] == str(tmp_path / f"worker{i}")
        assert cfg["durable"]["resume"]["shard_index"] == i
        assert cfg["durable"]["resume"]["shard_count"] == 2
        assert cfg["multicore"] == {
            "n_workers": 2, "worker_id": i,
            "service_socket": "/tmp/svc.sock",
        }


def test_worker_configs_merge_olp(tmp_path):
    cfgs = worker_configs(
        2, 1883, base_config={"olp": {"hwm_backlog": 9}},
        olp={"enable": True},
    )
    for cfg in cfgs:
        assert cfg["olp"] == {"hwm_backlog": 9, "enable": True}


def test_bench_smoke_mode():
    """The tier-1 liveness gate: `bench_multicore --smoke` boots the
    full 2-worker + match-service topology, pushes one pubsub round,
    shuts down cleanly, and lints the multicore modules clean."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable,
         os.path.join(repo, "tools", "bench_multicore.py"), "--smoke"],
        capture_output=True, text=True, timeout=240, cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    res = json.loads(proc.stdout.strip().splitlines()[-1])
    assert res["mc_smoke"] == "ok"
    assert res["mc_alive"] == 2
    assert res["mc_service_alive"] is True
    assert res["mc_stopped_clean"] is True
    assert res["lint_findings"] == 0
