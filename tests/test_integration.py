"""End-to-end integration: a real broker on a real TCP socket, driven
by the codec-level test client — the M2 'minimum end-to-end slice'
(SURVEY §7): CONNECT/SUBSCRIBE/PUBLISH/deliver across connections,
QoS 0/1/2, wildcard + shared subs, retained, wills, session resume."""

import asyncio

import pytest

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig, ListenerConfig

from mqtt_client import TestClient


@pytest.fixture
def server_port(request):
    """Run a broker server in a dedicated event loop via asyncio.run
    per test (pytest-asyncio is not available; tests drive their own
    loop through `run`)."""
    return None


def run(coro):
    return asyncio.run(coro)


def make_server(**cfg_kw) -> BrokerServer:
    cfg = BrokerConfig(**cfg_kw)
    cfg.listeners = [ListenerConfig(port=0)]  # ephemeral port
    return BrokerServer(cfg)


async def start(server):
    await server.start()
    return server.listeners[0].port


def test_connect_ping_disconnect():
    async def t():
        server = make_server()
        port = await start(server)
        try:
            cli = TestClient(port, "c1")
            ack = await cli.connect()
            assert ack.reason_code == 0 and not ack.session_present
            await cli.ping()
            await cli.disconnect()
        finally:
            await server.stop()

    run(t())


def test_pub_sub_roundtrip_qos0():
    async def t():
        server = make_server()
        port = await start(server)
        try:
            sub = TestClient(port, "sub")
            await sub.connect()
            await sub.subscribe("a/+/c", qos=0)
            pub = TestClient(port, "pub")
            await pub.connect()
            await pub.publish("a/b/c", b"hello")
            msg = await sub.recv_publish()
            assert msg.topic == "a/b/c" and msg.payload == b"hello"
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await server.stop()

    run(t())


def test_qos1_and_qos2_delivery():
    async def t():
        server = make_server()
        port = await start(server)
        try:
            sub = TestClient(port, "sub")
            await sub.connect()
            await sub.subscribe("q/#", qos=2)
            pub = TestClient(port, "pub")
            await pub.connect()

            ack = await pub.publish("q/1", b"one", qos=1)
            assert ack.reason_code == 0
            m1 = await sub.recv_publish()
            assert m1.qos == 1 and m1.payload == b"one"

            comp = await pub.publish("q/2", b"two", qos=2)
            assert comp is not None
            m2 = await sub.recv_publish()
            assert m2.qos == 2 and m2.payload == b"two"
            broker = server.broker
            assert broker.metrics.val("messages.qos2.received") == 1
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await server.stop()

    run(t())


def test_qos1_no_subscribers_reason_code():
    async def t():
        server = make_server()
        port = await start(server)
        try:
            pub = TestClient(port, "pub")
            await pub.connect()
            ack = await pub.publish("void", b"x", qos=1)
            assert ack.reason_code == 0x10  # no matching subscribers
            await pub.disconnect()
        finally:
            await server.stop()

    run(t())


def test_retained_message_replay():
    async def t():
        server = make_server()
        port = await start(server)
        try:
            pub = TestClient(port, "pub")
            await pub.connect()
            # qos=1: the PUBACK resolves only after the publish window
            # flushed (retain store included), so the later subscribe
            # deterministically sees the retained copy.  A qos0 retained
            # publish racing a foreign subscribe is unordered, as in the
            # reference (cross-client ordering is not an MQTT guarantee).
            await pub.publish("state/light", b"on", retain=True, qos=1)
            await pub.disconnect()

            sub = TestClient(port, "sub")
            await sub.connect()
            await sub.subscribe("state/+")
            msg = await sub.recv_publish()
            assert msg.topic == "state/light" and msg.payload == b"on"
            assert msg.retain
            await sub.disconnect()
        finally:
            await server.stop()

    run(t())


def test_shared_subscription_balancing():
    async def t():
        server = make_server()
        port = await start(server)
        server.broker.router.shared.strategy = "round_robin"
        try:
            c1 = TestClient(port, "c1")
            c2 = TestClient(port, "c2")
            await c1.connect()
            await c2.connect()
            await c1.subscribe("$share/g/work")
            await c2.subscribe("$share/g/work")
            pub = TestClient(port, "pub")
            await pub.connect()
            for i in range(4):
                await pub.publish("work", str(i).encode())
            got1 = [await c1.recv_publish() for _ in range(2)]
            got2 = [await c2.recv_publish() for _ in range(2)]
            assert {m.payload for m in got1} | {m.payload for m in got2} == {
                b"0", b"1", b"2", b"3"
            }
            await c1.disconnect()
            await c2.disconnect()
            await pub.disconnect()
        finally:
            await server.stop()

    run(t())


def test_will_message_on_abnormal_disconnect():
    async def t():
        server = make_server()
        port = await start(server)
        try:
            watcher = TestClient(port, "watcher")
            await watcher.connect()
            await watcher.subscribe("wills/#")

            doomed = TestClient(port, "doomed")
            await doomed.connect(
                will=C.Will(topic="wills/doomed", payload=b"gone", qos=1)
            )
            # abrupt socket close => will fires
            await doomed.close()
            msg = await watcher.recv_publish()
            assert msg.topic == "wills/doomed" and msg.payload == b"gone"

            # graceful disconnect => no will
            polite = TestClient(port, "polite")
            await polite.connect(
                will=C.Will(topic="wills/polite", payload=b"bye")
            )
            await polite.disconnect()
            with pytest.raises(asyncio.TimeoutError):
                await watcher.recv_publish(timeout=0.3)
            await watcher.disconnect()
        finally:
            await server.stop()

    run(t())


def test_session_resume_redelivers_queued():
    async def t():
        server = make_server()
        port = await start(server)
        try:
            sub = TestClient(port, "persist")
            await sub.connect(
                clean_start=False,
                properties={"session_expiry_interval": 300},
            )
            await sub.subscribe("inbox/persist", qos=1)
            await sub.close()  # drop without DISCONNECT; session persists
            await asyncio.sleep(0.05)

            pub = TestClient(port, "pub")
            await pub.connect()
            await pub.publish("inbox/persist", b"offline-msg", qos=1)
            await pub.disconnect()

            sub2 = TestClient(port, "persist")
            ack = await sub2.connect(
                clean_start=False,
                properties={"session_expiry_interval": 300},
            )
            assert ack.session_present
            msg = await sub2.recv_publish()
            assert msg.payload == b"offline-msg" and msg.qos == 1
            await sub2.disconnect()
        finally:
            await server.stop()

    run(t())


def test_takeover_closes_old_connection():
    async def t():
        server = make_server()
        port = await start(server)
        try:
            first = TestClient(port, "dup")
            await first.connect(
                clean_start=False,
                properties={"session_expiry_interval": 60},
            )
            second = TestClient(port, "dup")
            ack = await second.connect(
                clean_start=False,
                properties={"session_expiry_interval": 60},
            )
            assert ack.session_present
            # old connection gets DISCONNECT(0x8E) then EOF
            pkt = await first.recv(timeout=2.0)
            assert pkt is not None and pkt.type == C.DISCONNECT
            assert pkt.reason_code == 0x8E
            await second.disconnect()
            await first.close()
        finally:
            await server.stop()

    run(t())


def test_auth_denied_connect():
    async def t():
        server = make_server()
        port = await start(server)
        server.broker.access.allow_anonymous = False
        try:
            cli = TestClient(port, "nope")
            ack = await cli.connect()
            assert ack.reason_code == 0x86  # bad user name or password
            assert await cli.recv(timeout=1.0) is None  # closed
        finally:
            await server.stop()

    run(t())


def test_acl_denied_publish_qos1():
    async def t():
        from emqx_tpu.access import AclProvider, AclRule, DENY

        server = make_server()
        port = await start(server)
        server.broker.access.authz_sources.append(
            AclProvider([AclRule(DENY, "all", "publish", ["secret/#"])])
        )
        try:
            cli = TestClient(port, "c")
            await cli.connect()
            await cli.send(
                C.Publish(topic="secret/x", payload=b"x", qos=1, packet_id=7)
            )
            ack = await cli.expect(C.PUBACK)
            assert ack.reason_code == 0x87  # not authorized
            await cli.disconnect()
        finally:
            await server.stop()

    run(t())


def test_mqtt_v311_client():
    async def t():
        server = make_server()
        port = await start(server)
        try:
            sub = TestClient(port, "v4sub", version=C.MQTT_V4)
            ack = await sub.connect()
            assert ack.reason_code == 0
            await sub.subscribe("old/+", qos=1)
            pub = TestClient(port, "v4pub", version=C.MQTT_V4)
            await pub.connect()
            await pub.publish("old/school", b"341", qos=1)
            msg = await sub.recv_publish()
            assert msg.payload == b"341"
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await server.stop()

    run(t())


def test_unsubscribe_stops_delivery():
    async def t():
        server = make_server()
        port = await start(server)
        try:
            sub = TestClient(port, "sub")
            await sub.connect()
            await sub.subscribe("u/t")
            pub = TestClient(port, "pub")
            await pub.connect()
            await pub.publish("u/t", b"1")
            assert (await sub.recv_publish()).payload == b"1"
            unack = await sub.unsubscribe("u/t")
            assert unack.reason_codes == [0]
            await pub.publish("u/t", b"2")
            with pytest.raises(asyncio.TimeoutError):
                await sub.recv_publish(timeout=0.3)
            # unsubscribing again reports no-subscription-existed
            unack2 = await sub.unsubscribe("u/t")
            assert unack2.reason_codes == [0x11]
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await server.stop()

    run(t())
