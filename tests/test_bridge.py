"""MQTT bridge + client: two local brokers connected by an egress +
ingress bridge (emqx_bridge_mqtt semantics over the package's own
client, which also gets its reconnect behavior exercised)."""

import asyncio

from emqx_tpu.bridge_mqtt import MqttBridge
from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.client import MqttClient
from emqx_tpu.config import BrokerConfig, ListenerConfig
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


async def make_server():
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    srv = BrokerServer(cfg)
    await srv.start()
    return srv


def test_client_pubsub_and_reconnect():
    async def t():
        srv = await make_server()
        port = srv.listeners[0].port
        got = []
        sub = MqttClient("127.0.0.1", port, "cl-sub", reconnect_min=0.05)
        sub.on_message = lambda m: got.append((m.topic, m.payload))
        await sub.start()
        await asyncio.wait_for(sub.connected.wait(), 5)
        await sub.subscribe("c/#", qos=1)

        pub = MqttClient("127.0.0.1", port, "cl-pub", reconnect_min=0.05)
        await pub.start()
        await asyncio.wait_for(pub.connected.wait(), 5)
        await pub.publish("c/1", b"one", qos=1)
        for _ in range(100):
            if got:
                break
            await asyncio.sleep(0.02)
        assert got == [("c/1", b"one")]

        # server kicks the subscriber: it reconnects and resubscribes
        srv.broker.cm.kick("cl-sub")
        await asyncio.sleep(0.3)
        await asyncio.wait_for(sub.connected.wait(), 5)
        await pub.publish("c/2", b"two", qos=1)
        for _ in range(100):
            if len(got) >= 2:
                break
            await asyncio.sleep(0.02)
        assert ("c/2", b"two") in got

        await pub.stop()
        await sub.stop()
        await srv.stop()

    run(t())


def test_bridge_egress_and_ingress():
    async def t():
        local = await make_server()
        remote = await make_server()
        lport = local.listeners[0].port
        rport = remote.listeners[0].port

        bridge = MqttBridge(
            local.broker,
            "up",
            "127.0.0.1",
            rport,
            egress=["tele/#"],
            ingress=["cmd/#"],
        )
        await bridge.start()
        await asyncio.wait_for(
            bridge._resource.client.connected.wait(), 5
        )
        if bridge._ingress_client is not None:
            await asyncio.wait_for(
                bridge._ingress_client.connected.wait(), 5
            )
        await asyncio.sleep(0.1)

        # remote watcher sees local telemetry (egress)
        watcher = TestClient(rport, "w")
        await watcher.connect()
        await watcher.subscribe("tele/#", qos=1)
        lpub = TestClient(lport, "lp")
        await lpub.connect()
        await lpub.publish("tele/v1/temp", b"20.1", qos=1)
        pkt = await watcher.recv_publish(timeout=5)
        assert pkt.topic == "tele/v1/temp" and pkt.payload == b"20.1"

        # local subscriber receives remote commands (ingress)
        lsub = TestClient(lport, "ls")
        await lsub.connect()
        await lsub.subscribe("cmd/#", qos=1)
        rpub = TestClient(rport, "rp")
        await rpub.connect()
        await rpub.publish("cmd/v1/go", b"north", qos=1)
        pkt2 = await lsub.recv_publish(timeout=5)
        assert pkt2.topic == "cmd/v1/go" and pkt2.payload == b"north"

        # egress survives a remote outage: buffered and replayed
        await remote.stop()
        await asyncio.sleep(0.1)
        await lpub.publish("tele/v1/late", b"queued", qos=1)
        worker = local.broker.resources.get("bridge:up")
        assert len(worker) >= 1  # buffered while the remote is down

        await bridge.stop()
        await lpub.disconnect()
        await lsub.disconnect()
        await watcher.close()
        await rpub.close()
        await local.stop()

    run(t())
