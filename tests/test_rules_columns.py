"""Columnar rule-engine WHERE evaluation: the rules x window matrix.

The referee suite for the three rule-eval paths:

  * device       — ``engine.rules_force = "dev"`` runs the stacked
    program through ops.match_kernel.rules_eval_batch (JAX);
  * host-vectorized — ``"host"`` pins the numpy twin;
  * scalar referee  — ``RuleEngine.eval_force = "scalar"`` pins the
    per-rule interpreter walk over the same lazy envs (the oracle).

All three must produce identical matched sets, per-rule
matched/passed/failed counters, and action invocation ORDER over
random rule sets (lowerable + interpreter-fallback, overlapping topic
filters, numeric/string/presence predicates, absent fields, malformed
JSON payloads) x random windows.  Plus kernel-vs-twin equality over
random padded columns, ``rules_rev`` cache-invalidation churn,
per-RULE (not per-window) fallback degradation, the lazy-env
allocation bound, and the chaos criterion: 100% device rules-eval
failure mid-stream still fires the correct actions via the host path,
trips the shared breaker, stops device attempts, and the background
probe re-closes it."""

import json
import random
import time

import numpy as np
import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.broker.broker import Broker
from emqx_tpu.config import BrokerConfig
from emqx_tpu.engine import MatchEngine
from emqx_tpu.message import Message
from emqx_tpu.ops.match_kernel import rules_eval_host
from emqx_tpu.rules.columns import WindowColumns
from emqx_tpu.rules.engine import FunctionAction, RuleEngine
from emqx_tpu.rules.predicate import build_stack, lower_where
from emqx_tpu.rules.runtime import LazyEnv, build_env, eval_where
from emqx_tpu.rules.sql import parse_sql


@pytest.fixture(autouse=True)
def _clear_failpoints():
    fp.clear()
    yield
    fp.clear()


def wait_until(cond, timeout=5.0, what="condition"):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < timeout, f"timeout: {what}"
        time.sleep(0.01)


# ------------------------------------------------ random rule worlds

# lowerable, no arithmetic, integer-valued fields: device-eligible
# under the f32 gate
_LOW_NOARITH = [
    "payload.a > 2",
    "payload.a >= payload.b",
    "payload.a = 3",
    "payload.s = 'x'",
    "payload.s != 'y'",
    "payload.s IN ('x', 'q')",
    "qos IN (1, 2)",
    "retain != 1",
    "is_null(payload.a)",
    "is_not_null(payload.s) AND payload.s != 'z'",
    "NOT (payload.a > 0) AND payload.b <= 2",
    "payload.missing = payload.gone",
    "payload.s > payload.s2",
    "topic > clientid",
    "payload.a = 1 OR payload.missing > 1",
    "payload.x != 1",
    "clientid = 'c1'",
    "payload.obj = payload.obj2",
]

# lowerable with arithmetic (float64 host twin territory)
_LOW_ARITH = [
    "payload.a + 1 >= payload.b * 2",
    "payload.a div 2 = 1",
    "payload.a mod 2 = 0",
    "payload.a / payload.b > 1",
    "payload.a - 0.5 < payload.b",
]

# non-lowerable: per-RULE interpreter fallback
_FALLBACK = [
    "regex_match(payload.s, 'x.*')",
    "lower(clientid) = 'c1'",
    "CASE WHEN qos = 0 THEN true ELSE false END",
    "topic LIKE 't/%'",
]

_FILTERS = ["t/#", "t/+/x", "t/1/x", "t/2/#", "s/only"]
_TOPICS = ["t/1/x", "t/2/x", "t/2/y", "s/only", "q/none"]


def _rand_payload(rng, ints_only=False):
    payload = {}
    if rng.random() < 0.8:
        payload["a"] = (
            rng.randint(-5, 5) if ints_only or rng.random() < 0.7
            else round(rng.uniform(-5, 5), 2)
        )
    if rng.random() < 0.7:
        payload["b"] = rng.randint(0, 3)
    if rng.random() < 0.6:
        payload["s"] = rng.choice(["x", "y", "z", "xq"])
    if rng.random() < 0.5:
        payload["s2"] = rng.choice(["x", "y"])
    if rng.random() < 0.3:
        payload["x"] = rng.choice([1, "y"])
    if rng.random() < 0.3:
        payload["obj"] = rng.choice([{"k": 1}, {"k": 2}, [1, 2]])
    if rng.random() < 0.3:
        payload["obj2"] = rng.choice([{"k": 1}, [1, 2]])
    body = json.dumps(payload).encode()
    if rng.random() < 0.08:
        body = b"not json {"
    return body


def _build_world(seed, preds):
    rng = random.Random(seed)
    rules = []
    for i in range(rng.randint(6, 14)):
        flt = rng.choice(_FILTERS)
        pred = rng.choice(preds)
        rules.append((f"r{i}", f'SELECT * FROM "{flt}" WHERE {pred}'))
    windows = []
    ints_only = preds is _LOW_NOARITH
    for _ in range(5):
        win = []
        for _ in range(rng.randint(1, 10)):
            win.append(Message(
                topic=rng.choice(_TOPICS),
                payload=_rand_payload(rng, ints_only=ints_only),
                qos=rng.randint(0, 2),
                retain=bool(rng.getrandbits(1)),
                from_client=rng.choice(["c1", "c2"]),
                timestamp=1.7e9,
            ))
        windows.append(win)
    return rules, windows


def _run_world(rules, windows, mode):
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    b = Broker(config=cfg)
    if mode == "scalar":
        b.rules.eval_force = "scalar"
    else:
        b.router.engine.rules_force = mode
    fired = []
    for rid, sql in rules:
        b.rules.add_rule(
            rid, sql,
            actions=[FunctionAction(
                lambda sel, msg, rid=rid: fired.append(
                    (rid, msg.topic, bytes(msg.payload))
                )
            )],
        )
    for win in windows:
        b.publish_many([
            Message(
                topic=m.topic, payload=m.payload, qos=m.qos,
                retain=m.retain, from_client=m.from_client,
                timestamp=m.timestamp,
            )
            for m in win
        ])
    counters = {
        rid: (r.matched, r.passed, r.failed)
        for rid, r in b.rules.rules.items()
    }
    return (
        fired,
        counters,
        b.metrics.val("rules.matched"),
        b.rules.stats(),
        b.router.engine.stats(),
    )


@pytest.mark.parametrize("seed", [1, 2, 7, 23, 41, 97])
def test_three_paths_identical_mixed_rules(seed):
    """Mixed lowerable/arith/fallback registries: matched sets,
    per-rule counters and action order identical across scalar
    referee / host columns / device."""
    rules, windows = _build_world(
        seed, _LOW_NOARITH + _LOW_ARITH + _FALLBACK
    )
    scalar = _run_world(rules, windows, "scalar")
    host = _run_world(rules, windows, "host")
    dev = _run_world(rules, windows, "dev")
    for other, label in ((host, "host"), (dev, "dev")):
        assert scalar[0] == other[0], (label, "action order")
        assert scalar[1] == other[1], (label, "rule counters")
        assert scalar[2] == other[2], (label, "rules.matched")
    # the pinned paths really ran where they claim
    assert scalar[3]["scalar_windows"] > 0
    assert scalar[3]["matrix_windows"] == 0
    assert host[3]["matrix_windows"] > 0
    assert host[4]["rules_host_windows"] > 0
    assert host[4]["rules_dev_windows"] == 0


@pytest.mark.parametrize("seed", [3, 11, 29, 43, 61, 83])
def test_three_paths_identical_device_eligible(seed):
    """Arith-free integer worlds pass the f32 gate: the dev pin must
    actually reach the device kernel and stay bit-identical."""
    rules, windows = _build_world(seed, _LOW_NOARITH)
    scalar = _run_world(rules, windows, "scalar")
    dev = _run_world(rules, windows, "dev")
    assert scalar[0] == dev[0]
    assert scalar[1] == dev[1]
    assert dev[4]["rules_dev_windows"] > 0


# ------------------------------------------------- kernel vs twin

def test_kernel_vs_twin_over_random_padded_columns():
    """The padded-bucket device path (engine._rules_device) must equal
    the unpadded host twin over random programs x random windows."""
    rng = random.Random(5)
    preds = [rng.choice(_LOW_NOARITH) for _ in range(23)]
    wheres = [
        parse_sql(f'SELECT * FROM "t" WHERE {p}').where for p in preds
    ]
    stack = build_stack([(str(i), w) for i, w in enumerate(wheres)])
    assert not stack.fallback
    eng = MatchEngine(use_device=False)
    for rev in range(3):  # cache re-keys per rev
        msgs = [
            Message(
                topic=rng.choice(_TOPICS),
                payload=_rand_payload(rng, ints_only=True),
                qos=rng.randint(0, 2),
                retain=bool(rng.getrandbits(1)),
                from_client="c1",
            )
            for _ in range(rng.randint(1, 70))
        ]
        cols = WindowColumns(msgs, stack.paths, stack.lit_strings)
        host = rules_eval_host(
            stack.code, stack.a0, stack.a1, stack.a2, stack.a3,
            stack.litn, cols.lit_ranks, stack.last,
            cols.num, cols.sid, cols.err, cols.prs,
        )
        dev = eng._rules_device(stack, rev, cols)
        assert np.array_equal(host, dev)
        # and both equal the interpreter oracle (rules sharing a
        # deduped program row share its matrix row)
        for i, w in enumerate(wheres):
            want = [eval_where(w, build_env(m)) for m in msgs]
            row = stack.row_of[str(i)]
            assert host[row].tolist() == want, preds[i]


def test_host_twin_block_chunking_and_program_dedup():
    """Registries past RULES_HOST_BLOCK evaluate in slabs (distinct
    literals defeat dedup), and identical programs share one row."""
    n_rules = 2048 + 37
    stack = build_stack([
        (
            str(i),
            parse_sql(
                f'SELECT * FROM "t" WHERE payload.a > {i}'
            ).where,
        )
        for i in range(n_rules)
    ])
    assert stack.n_rules == n_rules  # all distinct: no dedup
    msgs = [
        Message(topic="t", payload=b'{"a": %d}' % a, qos=0)
        for a in (0, 1, 500, 2090)
    ]
    cols = WindowColumns(msgs, stack.paths, stack.lit_strings)
    mat = rules_eval_host(
        stack.code, stack.a0, stack.a1, stack.a2, stack.a3,
        stack.litn, cols.lit_ranks, stack.last,
        cols.num, cols.sid, cols.err, cols.prs,
    )
    assert mat.shape == (n_rules, 4)
    for i in (0, 1, 1000, 2048, 2084):
        assert mat[i].tolist() == [0 > i, 1 > i, 500 > i, 2090 > i]
    # identical programs dedup to ONE matrix row, counters stay exact
    w = parse_sql('SELECT * FROM "t" WHERE payload.a > 1').where
    dedup = build_stack([(str(i), w) for i in range(500)])
    assert dedup.n_lowered == 500 and dedup.n_rules == 1
    assert all(v == 0 for v in dedup.row_of.values())


# --------------------------------------------- registry churn / rev

def test_rules_rev_invalidates_stack_and_device_cache():
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    b = Broker(config=cfg)
    b.router.engine.rules_force = "dev"
    hits = []
    b.rules.add_rule(
        "r1", 'SELECT * FROM "t/#" WHERE payload.v > 1',
        actions=[FunctionAction(lambda s, m: hits.append("r1"))],
    )
    rev1 = b.rules.rules_rev
    stack1 = b.rules._stacked()
    assert b.rules._stacked() is stack1  # cached within a rev
    b.publish(Message(topic="t/a", payload=b'{"v": 5}'))
    assert hits == ["r1"]
    # churn: add, remove, disable — each bumps rules_rev
    b.rules.add_rule(
        "r2", 'SELECT * FROM "t/#" WHERE payload.v > 10',
        actions=[FunctionAction(lambda s, m: hits.append("r2"))],
    )
    assert b.rules.rules_rev > rev1
    assert b.rules._stacked() is not stack1
    b.publish(Message(topic="t/b", payload=b'{"v": 50}'))
    assert hits == ["r1", "r1", "r2"]
    b.rules.enable_rule("r1", False)
    b.publish(Message(topic="t/c", payload=b'{"v": 50}'))
    assert hits == ["r1", "r1", "r2", "r2"]
    b.rules.remove_rule("r2")
    b.rules.enable_rule("r1", True)
    b.publish(Message(topic="t/d", payload=b'{"v": 50}'))
    assert hits == ["r1", "r1", "r2", "r2", "r1"]
    # the device program cache re-keyed on every rev it saw
    assert b.router.engine._rul_prog_cache is not None


def test_single_regex_rule_degrades_per_rule_not_per_window():
    """Acceptance: one non-lowerable rule must not push the whole
    registry off the matrix path."""
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    b = Broker(config=cfg)
    fired = []
    for i in range(20):
        b.rules.add_rule(
            f"low{i}", f'SELECT * FROM "t/#" WHERE payload.v > {i}',
            actions=[FunctionAction(
                lambda s, m, i=i: fired.append(f"low{i}")
            )],
        )
    b.rules.add_rule(
        "rx", "SELECT * FROM \"t/#\" WHERE regex_match(payload.s, 'ab.*')",
        actions=[FunctionAction(lambda s, m: fired.append("rx"))],
    )
    st = b.rules.stats()
    assert st["lowered"] == 20 and st["fallback"] == 1
    b.publish(Message(topic="t/1", payload=b'{"v": 10, "s": "abc"}'))
    st = b.rules.stats()
    assert st["matrix_windows"] == 1  # window stayed on the matrix
    assert st["scalar_windows"] == 0
    assert st["fallback_rule_evals"] == 1  # only rx walked the envs
    assert sorted(fired) == sorted(
        [f"low{i}" for i in range(10)] + ["rx"]
    )


# ------------------------------------------------------- lazy envs

def test_lazy_env_materializes_only_referenced_fields():
    """Satellite: a 1-field fallback rule over a wide payload must
    materialize one env field (payload), decode its JSON once, and
    never build the full 13-field env."""
    eng = RuleEngine()  # standalone: no broker
    eng.add_rule(
        "rx", "SELECT payload.f1 AS v FROM \"w/#\" "
        "WHERE regex_match(payload.f1, 'x.*')",
    )
    wide = {f"f{k}": "x%d" % k for k in range(100)}
    decodes = []
    orig_loads = json.loads

    def counting_loads(s, *a, **kw):
        decodes.append(1)
        return orig_loads(s, *a, **kw)

    json.loads = counting_loads
    try:
        msgs = [
            Message(topic="w/1", payload=json.dumps(wide).encode())
            for _ in range(4)
        ]
        hits = eng.apply_batch([(m, ["rx"]) for m in msgs])
    finally:
        json.loads = orig_loads
    assert hits == 4
    assert len(decodes) == 4  # one decode per message, window-wide
    rule = eng.rules["rx"]
    assert rule.passed == 4


def test_lazy_env_entry_count_regression():
    """The env dict itself stays thin: len(env) counts materialized
    fields, and a single-field predicate stays at 1."""
    m = Message(
        topic="w/1",
        payload=json.dumps(
            {f"f{k}": k for k in range(200)}
        ).encode(),
        qos=1,
    )
    env = LazyEnv(m)
    w = parse_sql('SELECT * FROM "w" WHERE payload.f7 > 3').where
    assert eval_where(w, env)
    assert len(env) == 1  # payload only — not the 13-field build_env
    assert set(env) == {"payload"}
    # full build_env for comparison materializes everything
    assert len(build_env(m)) == 13


# --------------------------------------------------- chaos: breaker

def test_device_rules_failure_midstream_breaker_and_probe():
    """Acceptance chaos criterion (FP301 seam dispatch.rules.device):
    100% device rules-eval failure mid-stream still fires the correct
    actions via the host path, trips the shared PR 1 breaker, stops
    device attempts, and the background probe re-closes it once the
    fault clears."""
    # use_device stays AUTO (the shipping default): unmeasured small
    # match windows serve on host — so a device-match success cannot
    # reset the consecutive-failure count between rules windows —
    # while the heal probe can still force the device path
    cfg = BrokerConfig()
    b = Broker(config=cfg)
    eng = b.router.engine
    eng.rules_force = "dev"
    eng.breaker_probe_interval = 3600.0
    fired = []
    for i in range(6):
        b.rules.add_rule(
            f"r{i}", f'SELECT * FROM "t/#" WHERE payload.v >= {i}',
            actions=[FunctionAction(
                lambda s, m, i=i: fired.append(i)
            )],
        )
    # fold the rule filters into the base automaton: the heal probe
    # re-tries DEVICE MATCHING, which needs a non-empty device table
    eng.rebuild()

    def pub(k):
        b.publish_many([Message(
            topic=f"t/{k}", payload=b'{"v": 3}', qos=0,
        )])

    pub(0)
    assert eng._rul_stats["dev_windows"] >= 1
    assert sorted(fired) == [0, 1, 2, 3]  # v=3 passes rules 0..3
    trips = []
    eng.on_breaker_trip = lambda info: trips.append(info)
    fp.configure("dispatch.rules.device", "error", prob=1.0)
    fired.clear()
    for k in range(4):  # breaker_threshold is 3
        pub(k)
    # every window still fired the correct actions via host columns
    assert sorted(fired) == sorted([0, 1, 2, 3] * 4)
    assert eng.breaker_open is True
    assert trips and trips[0]["reason"] == "rules"
    assert eng._rul_stats["dev_errors"] >= 3
    # breaker open: no further device attempts, still firing
    errs = eng._rul_stats["dev_errors"]
    fired.clear()
    pub(9)
    assert sorted(fired) == [0, 1, 2, 3]
    assert eng._rul_stats["dev_errors"] == errs
    # fault clears: a rules window schedules the probe, which
    # re-closes the shared breaker
    fp.clear("dispatch.rules.device")
    eng.breaker_probe_interval = 0.0
    pub(10)
    wait_until(lambda: not eng.breaker_open, what="breaker re-close")
    dev_before = eng._rul_stats["dev_windows"]
    pub(11)
    assert eng._rul_stats["dev_windows"] > dev_before


# -------------------------------------------------- policy / knobs

def test_rules_auto_first_device_window_warms_not_records():
    """EWMA hygiene: the first device rules window pays the JIT
    compile and must not seed the cost estimate."""
    where = parse_sql('SELECT * FROM "t" WHERE payload.v > 1').where
    stack = build_stack([(str(i), where) for i in range(8)])
    msgs = [
        Message(topic="t", payload=b'{"v": 2}') for _ in range(4)
    ]
    cols = WindowColumns(msgs, stack.paths, stack.lit_strings)
    eng = MatchEngine(use_device=False)
    eng.rules_force = "dev"
    _, path1 = eng.rules_eval_window(stack, 1, cols)
    assert path1 == "dev"
    assert eng._rul_dev_us is None  # compile window not recorded
    _, path2 = eng.rules_eval_window(stack, 1, cols)
    assert path2 == "dev"
    assert eng._rul_dev_us is not None


def test_matrix_env_kill_switch(monkeypatch):
    monkeypatch.setenv("EMQX_TPU_NO_RULES_MATRIX", "1")
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    b = Broker(config=cfg)
    hits = []
    b.rules.add_rule(
        "r", 'SELECT * FROM "t/#" WHERE payload.v > 1',
        actions=[FunctionAction(lambda s, m: hits.append(1))],
    )
    b.publish(Message(topic="t/a", payload=b'{"v": 2}'))
    assert hits == [1]
    st = b.rules.stats()
    assert st["matrix_enabled"] is False
    assert st["scalar_windows"] == 1 and st["matrix_windows"] == 0


def test_arith_and_f32_unsafe_windows_stay_on_host_twin():
    """The f32 gate binds even under a dev pin: arith programs and
    f32-lossy columns take the float64 host twin."""
    eng = MatchEngine(use_device=False)
    eng.rules_force = "dev"
    # arith program
    w = parse_sql('SELECT * FROM "t" WHERE payload.a + 1 > 2').where
    stack = build_stack([("r", w)])
    msgs = [Message(topic="t", payload=b'{"a": 5}')]
    cols = WindowColumns(msgs, stack.paths, stack.lit_strings)
    mat, path = eng.rules_eval_window(stack, 1, cols)
    assert path == "host" and mat[0, 0]
    # f32-lossy column (millisecond timestamp)
    w2 = parse_sql(
        'SELECT * FROM "t" WHERE timestamp > 1753000000100'
    ).where
    stack2 = build_stack([("r", w2)])
    m = Message(topic="t", payload=b"{}")
    m.timestamp = 1753000000.2
    cols2 = WindowColumns([m], stack2.paths, stack2.lit_strings)
    mat2, path2 = eng.rules_eval_window(stack2, 2, cols2)
    assert path2 == "host" and mat2[0, 0]


def _standalone_parity(sql, payloads):
    """One rule x given payloads through the matrix path AND the
    scalar referee; both must agree with the interpreter."""
    got = {}
    for force in ("scalar", None):
        eng = RuleEngine()
        eng.eval_force = force
        eng.add_rule("r", sql)
        msgs = [Message(topic="w/1", payload=p) for p in payloads]
        got[force] = eng.apply_batch([(m, ["r"]) for m in msgs])
        counters = eng.rules["r"]
        got[(force, "ctr")] = (counters.matched, counters.passed)
    assert got["scalar"] == got[None], sql
    assert got[("scalar", "ctr")] == got[(None, "ctr")], sql
    return got[None]


def test_review_no_var_path_registry_does_not_crash():
    """Code-review r1: a registry whose only lowered predicate
    references ZERO var paths (constant compound equality) must not
    IndexError on the zero-path err plane."""
    hits = _standalone_parity(
        'SELECT * FROM "w/#" WHERE 1 + 1 = 2', [b"{}", b"{}"]
    )
    assert hits == 2


def test_review_string_concat_plus_falls_back_per_rule():
    """Code-review r1: '+' over two could-be-string operands CONCATS
    in the interpreter — such rules must degrade to the interpreter,
    while single-var arithmetic stays lowerable."""
    w = parse_sql(
        'SELECT * FROM "w" WHERE payload.a + payload.b = payload.c'
    ).where
    assert lower_where(w) is None
    assert lower_where(
        parse_sql('SELECT * FROM "w" WHERE payload.a + 1 > 2').where
    ) is not None
    hits = _standalone_parity(
        'SELECT * FROM "w/#" WHERE payload.a + payload.b = payload.c',
        [b'{"a": "2", "b": "3", "c": "23"}', b'{"a": 1, "b": 2, "c": 3}'],
    )
    assert hits == 2  # concat match AND numeric match
    _standalone_parity(
        'SELECT * FROM "w/#" WHERE payload.a + payload.b != 5',
        [b'{"a": "2", "b": "3"}'],
    )


def test_review_literal_nan_payload_degrades_window():
    """Code-review r1: json.loads accepts a literal NaN, which would
    alias the num lane's sentinel — the window degrades to the
    interpreter and stays bit-identical (NOT(nan > 0) is True)."""
    hits = _standalone_parity(
        'SELECT * FROM "w/#" WHERE NOT (payload.a > 0)',
        [b'{"a": NaN}', b'{"a": 1}', b'{"a": -1}'],
    )
    assert hits == 2  # NaN row matches via NOT, like the interpreter


def test_review_nested_bool_number_term_equality():
    """Code-review r1: Python container equality has True == 1; the
    canonical term encoding must agree."""
    hits = _standalone_parity(
        'SELECT * FROM "w/#" WHERE payload.a = payload.b',
        [b'{"a": [true], "b": [1]}', b'{"a": [true], "b": [2]}'],
    )
    assert hits == 1


def test_lowering_rejects_non_lowerable_shapes():
    for src in (
        "lower(clientid) = 'c1'",
        "CASE WHEN qos = 0 THEN true ELSE false END",
        "topic LIKE 't/%'",
        "payload.s > 'abc'",  # string ordering vs literal
    ):
        w = parse_sql(f'SELECT * FROM "t" WHERE {src}').where
        assert lower_where(w) is None, src
    # and WHERE-less rules lower to an always-true row
    prog = lower_where(None)
    assert prog is not None and len(prog.steps) == 1
