"""OTLP export (emqx_opentelemetry parity) and structured logging
(emqx_logger / emqx_log_throttler parity)."""

import asyncio
import json
import logging

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.logger import JsonFormatter, LogThrottler
from emqx_tpu.otel import OtelExporter


def run(coro):
    return asyncio.run(coro)


def test_otel_metrics_payload_shape():
    """The payload must be valid OTLP/JSON: resourceMetrics ->
    scopeMetrics -> metrics with sum (counters) and gauge (stats)."""

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        srv = BrokerServer(cfg)
        await srv.start()
        srv.broker.metrics.inc("messages.received", 5)
        srv.broker.stats.set("connections.count", 2)
        exp = OtelExporter(srv.broker, "http://127.0.0.1:0")
        body = json.loads(exp.metrics_payload(1000.0))
        rm = body["resourceMetrics"][0]
        attrs = {a["key"]: a["value"]["stringValue"]
                 for a in rm["resource"]["attributes"]}
        assert attrs["service.name"] == "emqx_tpu"
        metrics = {m["name"]: m for m in rm["scopeMetrics"][0]["metrics"]}
        recv = metrics["emqx_messages_received"]
        assert recv["sum"]["isMonotonic"] is True
        assert recv["sum"]["dataPoints"][0]["asInt"] == "5"
        conn = metrics["emqx_connections_count"]
        assert conn["gauge"]["dataPoints"][0]["asInt"] == "2"
        await srv.stop()

    run(t())


def test_otel_end_to_end_collector():
    """Full push: broker -> OtelExporter -> local HTTP collector."""

    async def t():
        from aiohttp import web

        received = []

        async def collect(request):
            received.append(await request.json())
            return web.Response(status=200)

        app = web.Application()
        async def head(request):
            return web.Response()

        app.router.add_post("/v1/metrics", collect)
        app.router.add_route("HEAD", "/v1/metrics", head)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.otel.enable = True
        cfg.otel.endpoint = f"http://127.0.0.1:{port}"
        cfg.otel.interval = 0.0  # every housekeeping tick
        srv = BrokerServer(cfg)
        await srv.start()
        assert srv.otel is not None
        srv.otel.tick()  # force an immediate export
        for _ in range(100):
            if received:
                break
            await asyncio.sleep(0.05)
        assert received, "collector never received an OTLP push"
        assert "resourceMetrics" in received[0]
        await srv.stop()
        await runner.cleanup()

    run(t())


def test_json_formatter_fields_and_extras():
    fmt = JsonFormatter()
    rec = logging.LogRecord(
        "emqx_tpu.test", logging.WARNING, __file__, 1,
        "client %s kicked", ("c1",), None,
    )
    rec.clientid = "c1"
    out = json.loads(fmt.format(rec))
    assert out["level"] == "warning"
    assert out["logger"] == "emqx_tpu.test"
    assert out["msg"] == "client c1 kicked"
    assert out["clientid"] == "c1"
    assert isinstance(out["ts"], float)


def test_log_throttler_windows_and_summary(caplog):
    throttler = LogThrottler(window_s=0.2)
    logger = logging.getLogger("emqx_tpu.throttle_test")
    logger.addFilter(throttler)
    logger.setLevel(logging.INFO)
    try:
        with caplog.at_level(logging.INFO, "emqx_tpu.throttle_test"):
            for _ in range(10):
                logger.info("socket error from %s", "1.2.3.4")
        assert len(caplog.records) == 1  # first passes, rest swallowed

        caplog.clear()
        import time as _t
        _t.sleep(0.25)
        with caplog.at_level(logging.INFO, "emqx_tpu.throttle_test"):
            logger.info("socket error from %s", "1.2.3.4")
        assert len(caplog.records) == 1
        assert "throttled: 9 similar events" in caplog.records[0].getMessage()

        # errors always pass
        caplog.clear()
        with caplog.at_level(logging.INFO, "emqx_tpu.throttle_test"):
            for _ in range(3):
                logger.error("disk full")
        assert len(caplog.records) == 3
    finally:
        logger.removeFilter(throttler)
