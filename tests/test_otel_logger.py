"""OTLP export (emqx_opentelemetry parity) and structured logging
(emqx_logger / emqx_log_throttler parity)."""

import asyncio
import json
import logging

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.logger import JsonFormatter, LogThrottler
from emqx_tpu.otel import OtelExporter


def run(coro):
    return asyncio.run(coro)


def test_otel_metrics_payload_shape():
    """The payload must be valid OTLP/JSON: resourceMetrics ->
    scopeMetrics -> metrics with sum (counters) and gauge (stats)."""

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        srv = BrokerServer(cfg)
        await srv.start()
        srv.broker.metrics.inc("messages.received", 5)
        srv.broker.stats.set("connections.count", 2)
        exp = OtelExporter(srv.broker, "http://127.0.0.1:0")
        body = json.loads(exp.metrics_payload(1000.0))
        rm = body["resourceMetrics"][0]
        attrs = {a["key"]: a["value"]["stringValue"]
                 for a in rm["resource"]["attributes"]}
        assert attrs["service.name"] == "emqx_tpu"
        metrics = {m["name"]: m for m in rm["scopeMetrics"][0]["metrics"]}
        recv = metrics["emqx_messages_received"]
        assert recv["sum"]["isMonotonic"] is True
        assert recv["sum"]["dataPoints"][0]["asInt"] == "5"
        conn = metrics["emqx_connections_count"]
        assert conn["gauge"]["dataPoints"][0]["asInt"] == "2"
        await srv.stop()

    run(t())


def test_otel_end_to_end_collector():
    """Full push: broker -> OtelExporter -> local HTTP collector."""

    async def t():
        from aiohttp import web

        received = []

        async def collect(request):
            received.append(await request.json())
            return web.Response(status=200)

        app = web.Application()
        async def head(request):
            return web.Response()

        app.router.add_post("/v1/metrics", collect)
        app.router.add_route("HEAD", "/v1/metrics", head)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.otel.enable = True
        cfg.otel.endpoint = f"http://127.0.0.1:{port}"
        cfg.otel.interval = 0.0  # every housekeeping tick
        srv = BrokerServer(cfg)
        await srv.start()
        assert srv.otel is not None
        srv.otel.tick()  # force an immediate export
        for _ in range(100):
            if received:
                break
            await asyncio.sleep(0.05)
        assert received, "collector never received an OTLP push"
        assert "resourceMetrics" in received[0]
        await srv.stop()
        await runner.cleanup()

    run(t())


def test_json_formatter_fields_and_extras():
    fmt = JsonFormatter()
    rec = logging.LogRecord(
        "emqx_tpu.test", logging.WARNING, __file__, 1,
        "client %s kicked", ("c1",), None,
    )
    rec.clientid = "c1"
    out = json.loads(fmt.format(rec))
    assert out["level"] == "warning"
    assert out["logger"] == "emqx_tpu.test"
    assert out["msg"] == "client c1 kicked"
    assert out["clientid"] == "c1"
    assert isinstance(out["ts"], float)


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__(logging.DEBUG)
        self.records = []

    def emit(self, record):
        self.records.append(record)


def test_log_throttler_windows_and_summary():
    # the throttler is a handler filter (configure() wires it that
    # way); the summary line goes to ITS handler only, on a copied
    # record — sibling handlers must see the original untouched
    ours = _Capture()
    sibling = _Capture()  # e.g. the OTel log handler
    throttler = LogThrottler(window_s=0.2, handler=ours)
    ours.addFilter(throttler)
    logger = logging.getLogger("emqx_tpu.throttle_test")
    logger.addHandler(ours)
    logger.addHandler(sibling)
    logger.setLevel(logging.INFO)
    logger.propagate = False
    try:
        for _ in range(10):
            logger.info("socket error from %s", "1.2.3.4")
        assert len(ours.records) == 1  # first passes, rest swallowed
        assert len(sibling.records) == 10  # unthrottled sibling

        ours.records.clear()
        sibling.records.clear()
        import time as _t
        _t.sleep(0.25)
        logger.info("socket error from %s", "1.2.3.4")
        assert len(ours.records) == 1
        assert ("throttled: 9 similar events"
                in ours.records[0].getMessage())
        # the shared record instance was NOT mutated: the sibling
        # handler sees the plain message
        assert len(sibling.records) == 1
        assert sibling.records[0].getMessage() == "socket error from 1.2.3.4"

        # errors always pass
        ours.records.clear()
        for _ in range(3):
            logger.error("disk full")
        assert len(ours.records) == 3
    finally:
        logger.removeHandler(ours)
        logger.removeHandler(sibling)


def test_otel_trace_spans_capture():
    """Distributed trace spans (emqx_otel_trace / emqx_external_trace
    role): a publish produces a message.publish span with one
    message.deliver child per receiving client, the publisher's W3C
    traceparent user property is honored as the parent AND propagated
    to subscribers, and the OTLP/JSON payload lands on a collector."""

    async def t():
        from aiohttp import web

        from emqx_tpu.message import Message
        from mqtt_client import TestClient

        received = []

        async def collect(request):
            received.append(await request.json())
            return web.Response(status=200)

        async def head(request):
            return web.Response()

        app = web.Application()
        for path in ("/v1/metrics", "/v1/traces"):
            app.router.add_post(path, collect if path.endswith(
                "traces") else head)
            app.router.add_route("HEAD", path, head)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.otel.enable = True
        cfg.otel.endpoint = f"http://127.0.0.1:{port}"
        cfg.otel.export_traces = True
        srv = BrokerServer(cfg)
        await srv.start()
        assert srv.broker.tracer is not None

        sub = TestClient(srv.listeners[0].port, "tsub")
        await sub.connect()
        await sub.subscribe("traced/#", qos=0)

        upstream = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        srv.broker.publish(Message(
            topic="traced/x", payload=b"hi",
            properties={"user_property": [("traceparent", upstream)]},
        ))
        # the subscriber receives the CONTINUED trace context
        pkt = await sub.recv_publish(timeout=5)
        ups = dict(pkt.properties.get("user_property", ()))
        assert "traceparent" in ups
        assert ups["traceparent"].split("-")[1] == "ab" * 16

        srv.broker.tracer.flush()
        for _ in range(100):
            if received:
                break
            await asyncio.sleep(0.05)
        assert received, "collector never received spans"
        spans = received[0]["resourceSpans"][0]["scopeSpans"][0]["spans"]
        names = [s["name"] for s in spans]
        assert "message.publish" in names and "message.deliver" in names
        pub = next(s for s in spans if s["name"] == "message.publish")
        dlv = next(s for s in spans if s["name"] == "message.deliver")
        assert pub["traceId"] == "ab" * 16  # upstream trace honored
        assert pub["parentSpanId"] == "cd" * 8
        assert dlv["traceId"] == pub["traceId"]
        assert dlv["parentSpanId"] == pub["spanId"]
        attrs = {a["key"]: a["value"] for a in dlv["attributes"]}
        assert attrs["messaging.client_id"]["stringValue"] == "tsub"

        await sub.disconnect()
        await srv.stop()
        await runner.cleanup()

    run(t())
