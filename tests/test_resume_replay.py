"""Crash-safe mass reconnect: batched durable-session replay through
the window pipeline, with resume admission control.

The referee suite for the resume scheduler (broker/resume.py):

  * windowed replay — resume backlogs batched across sessions
    (`DurableSessions.replay_chunk_many`) and dispatched through the
    SAME pipeline as live fan-out (decision columns, encode-once
    slots, the native window splice) — must put bit-identical bytes
    on every resuming connection's wire vs the scalar per-session
    mqueue resume path, with identical per-qos sent metrics and
    (pid, qos) inflight windows, over random subs / QoS /
    overlapping-filter / shared-group / no_local / RAP / subid /
    upgrade_qos / v4-v5 / inflight-pressure worlds (the
    test_decide_columns referee pattern applied to resume);

  * admission control — max_concurrent replay slots, park FIFO,
    CONNACK server-busy past park_queue_cap, parked sessions
    self-draining as slots free;

  * crash safety — the boot checkpoint survives until the
    ``session.resume.commit`` seam fires AFTER the last window's
    inflight/mqueue handoff; ``ds.replay.read`` faults (error, drop,
    kill-mid-replay via panic + broker restart in-test) never lose a
    persisted QoS1 message — duplicates only within at-least-once
    bounds;

  * the reconnect storm — 10k resuming sessions with QoS1 backlogs
    plus concurrent live publishes: bounded live latency, bounded
    per-round replay bytes, parked depth observable;

  * the PR 8 "for free" claim — a lifecycle-sampled replayed message
    gets spans through the replay window, delivering clients named.
"""

import json
import random
import time

import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.resume import ResumeBusy
from emqx_tpu.ds import atomicio
from emqx_tpu.broker.session import SubOpts
from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig, check_config
from emqx_tpu.message import Message


class WireChannel(Channel):
    def __init__(self, broker, version=C.MQTT_V5):
        self.writes = []

        def send(pkts):
            self.writes.append(
                b"".join(C.serialize(p, self.version) for p in pkts)
            )

        super().__init__(broker, send=send, close=lambda r: None)
        self.version = version

    def wire(self) -> bytes:
        return b"".join(bytes(x) for x in self.writes)


def _cfg(data_dir, windowed=True, **resume_kw):
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.durable.enable = True
    cfg.durable.data_dir = str(data_dir)
    cfg.durable.resume.windowed = windowed
    for k, v in resume_kw.items():
        setattr(cfg.durable.resume, k, v)
    return cfg


# --------------------------------------------------- world generator

def _build_world(seed):
    rng = random.Random(seed)
    clients = []
    for i in range(10):
        subs = []
        for f in range(rng.randint(1, 3)):
            flt = rng.choice(
                ["t/#", "t/+/x", f"t/{f}/x", "s/only",
                 "$share/g1/t/+/x"]
            )
            subs.append({
                "flt": flt,
                "qos": rng.randint(0, 2),
                "rap": rng.random() < 0.4,
                "no_local": rng.random() < 0.3,
                "subid": rng.randint(1, 9)
                if rng.random() < 0.2 else None,
            })
        clients.append({
            "cid": f"c{i}",
            "version": rng.choice([C.MQTT_V4, C.MQTT_V5]),
            "upgrade": rng.random() < 0.3,
            "max_inflight": rng.choice([2, 4, 32]),
            "subs": subs,
        })
    msgs = []
    for j in range(rng.randint(10, 40)):
        msgs.append({
            "topic": rng.choice(
                ["t/1/x", "t/2/x", "t/0/x", "s/only", "t/deep/x"]
            ),
            "qos": rng.randint(0, 2),
            "retain": rng.random() < 0.3,
            "payload": bytes(
                rng.randrange(256)
                for _ in range(rng.randint(0, 120))
            ),
            "from": rng.choice(["c0", "c1", "pub"]),
        })
    return clients, msgs


def _seed_dir(data_dir, clients, msgs):
    """Subscribe + checkpoint every client, then persist the backlog
    while they are away (the outage interval)."""
    b = Broker(config=_cfg(data_dir))
    chans = {}
    for c in clients:
        ch = WireChannel(b, version=c["version"])
        session, _ = b.cm.open_session(
            False, c["cid"], ch, expiry_interval=3600.0
        )
        session.upgrade_qos = c["upgrade"]
        for s in c["subs"]:
            opts = SubOpts(
                qos=s["qos"], retain_as_published=s["rap"],
                no_local=s["no_local"], subid=s["subid"],
            )
            session.subscribe(s["flt"], opts)
            b.subscribe(c["cid"], s["flt"], opts)
        chans[c["cid"]] = ch
    for c in clients:
        b.cm.disconnect(c["cid"], chans[c["cid"]])
        b.channel_disconnected(c["cid"])
    out = [
        Message(
            topic=m["topic"], qos=m["qos"], retain=m["retain"],
            payload=m["payload"], from_client=m["from"],
            timestamp=time.time(),
        )
        for m in msgs
    ]
    b.publish_many(out)
    b.durable.sync()
    b.durable.close()


def _drain_resume(b, clients):
    rounds = 0
    while any(b.resume.pending(c["cid"]) for c in clients):
        b.resume.drain_once()
        rounds += 1
        assert rounds < 10_000, "resume never completed"
        if b.resume.drain_once.__self__ is not b.resume:  # pragma: no cover
            break
    return rounds


def _ack_until_quiet(b, clients, chans, sessions):
    """Client side of the ack dance: decode every connection's new
    wire bytes, answer PUBACK/PUBREC+PUBCOMP through the session
    (which drains the mqueue into the window), repeat to fixpoint."""
    parsers = {
        c["cid"]: C.StreamParser(version=c["version"]) for c in clients
    }
    seen = {c["cid"]: 0 for c in clients}
    progress = True
    while progress:
        progress = False
        for c in clients:
            cid = c["cid"]
            ch = chans[cid]
            wire = ch.wire()
            new = wire[seen[cid]:]
            if not new:
                continue
            seen[cid] = len(wire)
            session = sessions[cid]
            for pkt in parsers[cid].feed(new):
                if pkt.type != C.PUBLISH or pkt.packet_id is None:
                    continue
                progress = True
                if pkt.qos == 1:
                    _ok, follow = session.puback(pkt.packet_id)
                    ch.send_packets(follow)
                elif pkt.qos == 2:
                    _ok, follow = session.pubrec(pkt.packet_id)
                    ch.send_packets(follow)
                    _ok, follow = session.pubcomp(pkt.packet_id)
                    ch.send_packets(follow)


def _resume_run(data_dir, clients, windowed, ack=True):
    """Reconnect every client against a fresh broker on ``data_dir``
    and drain the whole resume through the scheduler; returns
    (per-connection wire bytes, per-qos sent metrics, (pid, qos)
    inflight windows, broker)."""
    b = Broker(config=_cfg(data_dir, windowed=windowed,
                           max_concurrent=3, chunk_msgs=16))
    b.resume.running = True
    b.router.shared._rng.seed(1234)
    chans = {}
    sessions = {}
    for c in clients:
        ch = WireChannel(b, version=c["version"])
        session, present = b.open_session(
            False, c["cid"], ch, expiry_interval=3600.0,
            max_inflight=c["max_inflight"],
        )
        assert present, c["cid"]
        session.upgrade_qos = c["upgrade"]
        ch.session = session
        ch.send_packets(session.resume())  # post-CONNACK redelivery
        chans[c["cid"]] = ch
        sessions[c["cid"]] = session
    _drain_resume(b, clients)
    if ack:
        _ack_until_quiet(b, clients, chans, sessions)
    wires = {c["cid"]: chans[c["cid"]].wire() for c in clients}
    sent = {
        k: b.metrics.all().get(k, 0)
        for k in ("messages.sent", "messages.qos0.sent",
                  "messages.qos1.sent", "messages.qos2.sent")
    }
    inflight = {
        c["cid"]: sorted(
            (pid, e.qos)
            for pid, e in sessions[c["cid"]].inflight.items()
        )
        for c in clients
    }
    b.durable.close()
    return wires, sent, inflight, b


# ------------------------------------------------ bit-identity referee

@pytest.mark.parametrize("seed", range(6))
def test_windowed_replay_bit_identical_to_scalar(tmp_path, seed):
    """The acceptance referee: windowed replay (batched DS reads +
    dispatch windows through decide columns / encode-once / native
    splice) vs the scalar per-session mqueue resume — bit-identical
    per-connection wire bytes, per-qos sent metrics, and (pid, qos)
    inflight windows, including the ack-driven drain of backlogs
    larger than the inflight window."""
    clients, msgs = _build_world(seed)
    d_win = tmp_path / "win"
    d_sca = tmp_path / "sca"
    _seed_dir(d_win, clients, msgs)
    _seed_dir(d_sca, clients, msgs)
    w_wire, w_sent, w_inf, _ = _resume_run(d_win, clients, True)
    s_wire, s_sent, s_inf, _ = _resume_run(d_sca, clients, False)
    assert w_sent == s_sent
    assert w_inf == s_inf
    for c in clients:
        cid = c["cid"]
        assert w_wire[cid] == s_wire[cid], (
            seed, cid, len(w_wire[cid]), len(s_wire[cid])
        )


def test_windowed_replay_matches_legacy_inline_resume(tmp_path):
    """The windowed path must also agree with the LEGACY shape: no
    scheduler running, the whole interval replayed synchronously
    inside open_session (the pre-scheduler behavior unit tests and
    loop-less embedders still get).  upgrade_qos pinned off: it is
    broker-level config in production, but the harness sets it on the
    session object AFTER open_session returns — too late for the
    in-line replay to see (a harness artifact, not a path
    difference)."""
    clients, msgs = _build_world(99)
    for c in clients:
        c["upgrade"] = False
    d_win = tmp_path / "win"
    d_leg = tmp_path / "leg"
    _seed_dir(d_win, clients, msgs)
    _seed_dir(d_leg, clients, msgs)
    w_wire, w_sent, w_inf, _ = _resume_run(d_win, clients, True)

    b = Broker(config=_cfg(d_leg))  # resume.running stays False
    b.router.shared._rng.seed(1234)
    chans = {}
    sessions = {}
    for c in clients:
        ch = WireChannel(b, version=c["version"])
        session, present = b.open_session(
            False, c["cid"], ch, expiry_interval=3600.0,
            max_inflight=c["max_inflight"],
        )
        assert present
        session.upgrade_qos = c["upgrade"]
        ch.send_packets(session.resume())
        chans[c["cid"]] = ch
        sessions[c["cid"]] = session
    _ack_until_quiet(b, clients, chans, sessions)
    l_sent = {
        k: b.metrics.all().get(k, 0)
        for k in ("messages.sent", "messages.qos0.sent",
                  "messages.qos1.sent", "messages.qos2.sent")
    }
    assert w_sent == l_sent
    for c in clients:
        assert w_wire[c["cid"]] == chans[c["cid"]].wire(), c["cid"]
    b.durable.close()


# --------------------------------------------------- admission control

def _seed_simple(data_dir, cids, n_msgs=6, topic_of=None, qos=1):
    """One filter per client, ``n_msgs`` QoS1 backlog each."""
    from emqx_tpu.ds.persist import DurableSessions

    ds = DurableSessions(str(data_dir))
    t0 = time.time() - 30.0
    for cid in cids:
        ds.save(cid, {"q/" + cid + "/#": {"qos": 1}}, 3600.0, now=t0)
        ds.add_filter("q/" + cid + "/#")
    msgs = []
    for cid in cids:
        for j in range(n_msgs):
            msgs.append(Message(
                topic=(topic_of(cid, j) if topic_of
                       else f"q/{cid}/{j}"),
                qos=qos, payload=f"{cid}-{j}".encode(),
                timestamp=time.time(),
            ))
    ds.persist(msgs)
    ds.sync()
    ds.close()


def test_admission_caps_park_fifo_and_busy(tmp_path):
    cids = [f"a{i}" for i in range(4)]
    _seed_simple(tmp_path / "ds", cids)
    b = Broker(config=_cfg(tmp_path / "ds", max_concurrent=1,
                           park_queue_cap=2, chunk_msgs=4))
    b.resume.running = True
    chans = {}
    for cid in cids[:3]:
        ch = WireChannel(b)
        _s, present = b.open_session(
            False, cid, ch, expiry_interval=3600.0
        )
        assert present
        chans[cid] = ch
    info = b.resume.info()
    assert info["active"] == 1 and info["parked"] == 2
    assert b.metrics.all()["session.resume.parked"] == 2
    # saturated: the 4th reconnect is refused BEFORE any state exists
    with pytest.raises(ResumeBusy):
        b.open_session(False, cids[3], WireChannel(b),
                       expiry_interval=3600.0)
    assert b.metrics.all()["session.resume.busy"] == 1
    assert b.cm.lookup(cids[3]) is None
    assert b.durable.has_checkpoint(cids[3])  # nothing was lost
    # parked sessions self-drain in FIFO order as slots free
    rounds = 0
    while any(b.resume.pending(c) for c in cids[:3]):
        b.resume.drain_once()
        assert b.resume.info()["active"] <= 1
        rounds += 1
        assert rounds < 500
    assert b.metrics.all()["session.resumed"] == 3
    assert b.metrics.all()["session.replay.windows"] >= 3
    for cid in cids[:3]:
        assert not b.durable.has_checkpoint(cid)  # committed
        assert chans[cid].wire()  # backlog arrived
    # the refused client retries and is admitted now
    ch = WireChannel(b)
    _s, present = b.open_session(False, cids[3], ch,
                                 expiry_interval=3600.0)
    assert present
    while b.resume.pending(cids[3]):
        b.resume.drain_once()
    assert ch.wire()
    b.durable.close()


def test_disconnect_mid_replay_keeps_checkpoint_then_resumes(tmp_path):
    """Disconnect while the backlog is still draining: the boot
    checkpoint must NOT be overwritten (its on-disk cursors cover the
    un-replayed tail — the crash-recovery story), and the next
    reconnect continues the replay where it stopped."""
    _seed_simple(tmp_path / "ds", ["m0"], n_msgs=40)
    b = Broker(config=_cfg(tmp_path / "ds", chunk_msgs=5))
    b.resume.running = True
    ch1 = WireChannel(b)
    session, present = b.open_session(
        False, "m0", ch1, expiry_interval=3600.0, max_inflight=1000
    )
    assert present
    ch1.send_packets(session.resume())
    b.resume.drain_once()  # partial: 5 of 40
    assert b.resume.pending("m0")
    state_path = b.durable._state_path("m0")
    before = atomicio.load_json(state_path)
    b.cm.disconnect("m0", ch1)
    b.channel_disconnected("m0")
    # checkpoint NOT overwritten with a fresh disconnected_at (that
    # would skip the un-replayed tail after a restart)
    after = atomicio.load_json(state_path)
    assert after == before
    assert b.durable.has_checkpoint("m0")
    info = b.resume.info()
    assert info["paused"] == 1 and info["active"] == 0
    # reconnect: the detached session takes the new channel and the
    # scheduler picks the job back up
    ch2 = WireChannel(b)
    session2, present = b.open_session(
        False, "m0", ch2, expiry_interval=3600.0
    )
    assert present and session2 is session
    ch2.send_packets(session2.resume())
    while b.resume.pending("m0"):
        b.resume.drain_once()
    assert not b.durable.has_checkpoint("m0")  # committed
    got = set()
    for ch, ver in ((ch1, C.MQTT_V5), (ch2, C.MQTT_V5)):
        parser = C.StreamParser(version=ver)
        for pkt in parser.feed(ch.wire()):
            if pkt.type == C.PUBLISH:
                got.add(bytes(pkt.payload))
    assert got == {f"m0-{j}".encode() for j in range(40)}
    b.durable.close()


# ------------------------------------------------------- chaos: seams

def _collect_payloads(ch, version=C.MQTT_V5):
    out = []
    parser = C.StreamParser(version=version)
    for pkt in parser.feed(ch.wire()):
        if pkt.type == C.PUBLISH:
            out.append(bytes(pkt.payload))
    return out


def test_replay_read_fault_backoff_and_self_drain(tmp_path):
    """``ds.replay.read`` error: the session backs off, keeps its
    checkpoint, and self-drains to a complete backlog once the fault
    clears — zero loss."""
    _seed_simple(tmp_path / "ds", ["e0"], n_msgs=20)
    b = Broker(config=_cfg(tmp_path / "ds", chunk_msgs=4))
    b.resume.running = True
    fp.configure("ds.replay.read", "error", times=3)
    try:
        ch = WireChannel(b)
        session, present = b.open_session(
            False, "e0", ch, expiry_interval=3600.0, max_inflight=1000
        )
        assert present
        ch.send_packets(session.resume())
        deadline = time.time() + 10.0
        while b.resume.pending("e0"):
            b.resume.drain_once()
            assert time.time() < deadline, "fault never self-drained"
            time.sleep(0.01)  # let the backoff deadline pass
        got = _collect_payloads(ch)
        assert sorted(got) == sorted(
            f"e0-{j}".encode() for j in range(20)
        )
        assert not b.durable.has_checkpoint("e0")
    finally:
        fp.clear()
        b.durable.close()


def test_replay_read_drop_never_skips_the_interval(tmp_path):
    """``drop`` answers a replay read with nothing — which must read
    as "retry later", NEVER as stream exhaustion: the interval behind
    a dropped read would otherwise be silently skipped (QoS1 loss)."""
    _seed_simple(tmp_path / "ds", ["d0"], n_msgs=24)
    b = Broker(config=_cfg(tmp_path / "ds", chunk_msgs=6))
    b.resume.running = True
    fp.configure("ds.replay.read", "drop", times=4)
    try:
        ch = WireChannel(b)
        session, present = b.open_session(
            False, "d0", ch, expiry_interval=3600.0, max_inflight=1000
        )
        assert present
        ch.send_packets(session.resume())
        deadline = time.time() + 10.0
        while b.resume.pending("d0"):
            b.resume.drain_once()
            assert time.time() < deadline
        got = _collect_payloads(ch)
        # complete coverage — dups allowed (at-least-once), loss not
        assert set(got) == {f"d0-{j}".encode() for j in range(24)}
    finally:
        fp.clear()
        b.durable.close()


def test_resume_commit_fault_keeps_checkpoint_until_it_clears(tmp_path):
    """``session.resume.commit`` error: the backlog is delivered but
    the checkpoint SURVIVES (a crash now re-replays — at-least-once;
    dropping it early would be loss); when the fault clears the
    commit lands, the checkpoint is discarded and session.resumed
    fires."""
    _seed_simple(tmp_path / "ds", ["k0"], n_msgs=8)
    b = Broker(config=_cfg(tmp_path / "ds", chunk_msgs=50))
    b.resume.running = True
    fp.configure("session.resume.commit", "error", times=2)
    try:
        ch = WireChannel(b)
        session, present = b.open_session(
            False, "k0", ch, expiry_interval=3600.0, max_inflight=1000
        )
        assert present
        ch.send_packets(session.resume())
        b.resume.drain_once()  # reads all + delivery + failed commit
        assert sorted(_collect_payloads(ch)) == sorted(
            f"k0-{j}".encode() for j in range(8)
        )
        assert b.durable.has_checkpoint("k0")  # commit blocked
        assert b.metrics.all().get("session.resumed", 0) == 0
        deadline = time.time() + 10.0
        while b.resume.pending("k0"):
            b.resume.drain_once()
            assert time.time() < deadline
            time.sleep(0.02)
        assert not b.durable.has_checkpoint("k0")
        assert b.metrics.all()["session.resumed"] == 1
    finally:
        fp.clear()
        b.durable.close()


def test_kill_mid_replay_zero_qos1_loss_on_restart(tmp_path):
    """THE crash-safety acceptance: the broker dies (failpoint panic —
    BaseException, absorbed by no recovery path) in the middle of a
    windowed mass replay; a fresh broker on the same data directory
    re-resumes, and every QoS1 message persisted before the outage is
    delivered — duplicates allowed (at-least-once), loss not."""
    cids = ["v0", "v1", "v2"]
    _seed_simple(tmp_path / "ds", cids, n_msgs=30)
    b1 = Broker(config=_cfg(tmp_path / "ds", chunk_msgs=5,
                            max_concurrent=2))
    b1.resume.running = True
    chans1 = {}
    for cid in cids:
        ch = WireChannel(b1)
        session, present = b1.open_session(
            False, cid, ch, expiry_interval=3600.0, max_inflight=1000
        )
        assert present
        ch.send_packets(session.resume())
        chans1[cid] = ch
    # a few windows land, then the "process dies" mid-replay
    fp.configure("ds.replay.read", "panic", after=4)
    died = False
    try:
        for _ in range(200):
            b1.resume.drain_once()
    except fp.FailpointPanic:
        died = True
    finally:
        fp.clear()
    assert died, "panic failpoint never fired"
    delivered_before = {
        cid: set(_collect_payloads(chans1[cid])) for cid in cids
    }
    # b1 is abandoned exactly as a dead process would be: no commit,
    # no checkpoint write, no close.  The restart boots from disk.
    b2 = Broker(config=_cfg(tmp_path / "ds", chunk_msgs=7,
                            max_concurrent=3))
    b2.resume.running = True
    for cid in cids:
        assert b2.durable.has_checkpoint(cid)  # survived the crash
    chans2 = {}
    for cid in cids:
        ch = WireChannel(b2)
        session, present = b2.open_session(
            False, cid, ch, expiry_interval=3600.0, max_inflight=1000
        )
        assert present
        ch.send_packets(session.resume())
        chans2[cid] = ch
    while any(b2.resume.pending(cid) for cid in cids):
        b2.resume.drain_once()
    for cid in cids:
        want = {f"{cid}-{j}".encode() for j in range(30)}
        got = delivered_before[cid] | set(
            _collect_payloads(chans2[cid])
        )
        assert got >= want, (cid, sorted(want - got)[:5])
    b2.durable.close()


def test_scalar_inline_resume_survives_dropped_read(tmp_path):
    """The loop-less fallback (no scheduler running): a chaos-dropped
    read stops the in-line replay WITHOUT discarding the checkpoint,
    so the next reconnect (or restart) replays the blocked tail
    instead of losing it — and without spinning the caller forever."""
    _seed_simple(tmp_path / "ds", ["s0"], n_msgs=12)
    b = Broker(config=_cfg(tmp_path / "ds"))
    fp.configure("ds.replay.read", "drop", after=1)
    try:
        ch = WireChannel(b)
        session, present = b.open_session(
            False, "s0", ch, expiry_interval=3600.0, max_inflight=1000
        )
        assert present
        ch.send_packets(session.resume())
        # blocked mid-interval: the checkpoint MUST survive, and the
        # session must NOT count as resumed (backlog never handed off)
        assert b.durable.has_checkpoint("s0")
        assert b.metrics.all().get("session.resumed", 0) == 0
    finally:
        fp.clear()
        b.durable.close()
    b2 = Broker(config=_cfg(tmp_path / "ds"))
    ch2 = WireChannel(b2)
    session2, present = b2.open_session(
        False, "s0", ch2, expiry_interval=3600.0, max_inflight=1000
    )
    assert present
    ch2.send_packets(session2.resume())
    got = set(_collect_payloads(ch)) | set(_collect_payloads(ch2))
    assert got == {f"s0-{j}".encode() for j in range(12)}
    assert not b2.durable.has_checkpoint("s0")
    b2.durable.close()


def test_read_fault_after_partial_progress_loses_nothing(tmp_path):
    """A fault on a LATER storage read of the same round must not
    poison the dedup set: the already-read prefix is delivered, the
    faulted cursor stays put, and the retry re-reads exactly the
    unread region — the full 600-message backlog arrives.  (The
    broken shape: raising past the mutated seen-set made the retry
    skip the discarded prefix's region as 'seen' and marked the
    session done — silent QoS1 loss.)"""
    _seed_simple(tmp_path / "ds", ["p0"], n_msgs=600)
    b = Broker(config=_cfg(tmp_path / "ds", chunk_msgs=600))
    b.resume.running = True
    # first read (256 msgs) succeeds, the second FAULTS, mid-round
    fp.configure("ds.replay.read", "error", after=1, times=1)
    try:
        ch = WireChannel(b)
        session, present = b.open_session(
            False, "p0", ch, expiry_interval=3600.0, max_inflight=0
        )
        assert present
        ch.send_packets(session.resume())
        deadline = time.time() + 15.0
        while b.resume.pending("p0"):
            b.resume.drain_once()
            assert time.time() < deadline
            time.sleep(0.01)
        got = set(_collect_payloads(ch))
        assert got == {f"p0-{j}".encode() for j in range(600)}, (
            len(got)
        )
    finally:
        fp.clear()
        b.durable.close()


def test_persistent_drop_backs_off_instead_of_spinning(tmp_path):
    """A PERSISTENT dropped read (prob=1, no times cap) must read as
    a fault — backoff, no progress — not as an empty-chunk success
    that busy-spins the drive loop at 100% CPU."""
    _seed_simple(tmp_path / "ds", ["z0"], n_msgs=10)
    b = Broker(config=_cfg(tmp_path / "ds", chunk_msgs=4))
    b.resume.running = True
    fp.configure("ds.replay.read", "drop")
    try:
        ch = WireChannel(b)
        _s, present = b.open_session(
            False, "z0", ch, expiry_interval=3600.0, max_inflight=1000
        )
        assert present
        assert b.resume.drain_once() == 0  # blocked, not "progress"
        assert b.resume.drain_once() == 0  # backoff holds
        assert b.resume.pending("z0")
        assert b.durable.has_checkpoint("z0")
    finally:
        fp.clear()
        b.durable.close()


def test_mid_replay_subscribe_survives_in_checkpoint(tmp_path):
    """A filter subscribed DURING the live mid-replay window must
    reach the kept checkpoint (subs refreshed, original
    disconnected_at and virgin cursors preserved) — or a restart
    would rebuild the session without it and lose every QoS1 message
    the new filter gated into storage."""
    _seed_simple(tmp_path / "ds", ["w0"], n_msgs=40)
    b = Broker(config=_cfg(tmp_path / "ds", chunk_msgs=5))
    b.resume.running = True
    ch = WireChannel(b)
    session, present = b.open_session(
        False, "w0", ch, expiry_interval=3600.0, max_inflight=1000
    )
    assert present
    ch.send_packets(session.resume())
    b.resume.drain_once()  # partial
    before = atomicio.load_json(b.durable._state_path("w0"))
    opts = SubOpts(qos=1)
    session.subscribe("extra/#", opts)
    b.subscribe("w0", "extra/#", opts)
    b.cm.disconnect("w0", ch)
    b.channel_disconnected("w0")
    after = atomicio.load_json(b.durable._state_path("w0"))
    assert "extra/#" in after["subs"]  # the live change persisted
    assert after["disconnected_at"] == before["disconnected_at"]
    assert "iters" not in after  # never the advanced in-memory cursors
    b.durable.close()


def test_expiry_zero_termination_mid_replay_drops_job(tmp_path):
    """A session that ends with expiry 0 mid-replay abandoned its
    state by protocol: the replay job AND the boot checkpoint go with
    it — a later reconnect starts clean instead of resurrecting it."""
    _seed_simple(tmp_path / "ds", ["x0"], n_msgs=40)
    b = Broker(config=_cfg(tmp_path / "ds", chunk_msgs=5))
    b.resume.running = True
    ch = WireChannel(b)
    session, present = b.open_session(
        False, "x0", ch, expiry_interval=3600.0, max_inflight=1000
    )
    assert present
    b.resume.drain_once()  # partial
    assert b.resume.pending("x0")
    session.expiry_interval = 0.0  # MQTT5 DISCONNECT lowered it
    b.cm.disconnect("x0", ch)
    b.session_terminated("x0", session)
    assert not b.resume.pending("x0")
    assert not b.durable.has_checkpoint("x0")
    b.durable.close()


# ----------------------------------------------------- reconnect storm

class SinkChannel:
    """Minimal ChannelLike for the storm: counts packets/bytes, takes
    the native wire path (cork/send_wire), encodes nothing."""

    version = C.MQTT_V5

    __slots__ = ("n_pub", "n_bytes")

    def __init__(self):
        self.n_pub = 0
        self.n_bytes = 0

    def cork(self):
        pass

    def uncork(self):
        pass

    def send_packets(self, pkts):
        self.n_pub += sum(
            1 for p in pkts if getattr(p, "type", None) == C.PUBLISH
            or isinstance(p, C.Publish)
        )

    def send_wire(self, data, npub, count=True):
        self.n_bytes += len(data)
        self.n_pub += sum(npub)
        return True

    def close(self, reason):
        pass


def test_reconnect_storm_bounded_latency_and_memory(tmp_path):
    """The storm acceptance: >= 10k resuming sessions with QoS1
    backlogs + concurrent live publishes.  Asserts the degradation
    CONTRACT: active replay slots never exceed max_concurrent, each
    round's DS reads stay under the byte budget, parked depth is
    observable while the queue drains, live publish windows stay
    fast while the storm drains, and every session's full backlog
    arrives (zero loss)."""
    n_sessions = 10_000
    n_backlog = 5
    from emqx_tpu.ds.persist import DurableSessions

    ds = DurableSessions(str(tmp_path / "ds"))
    t0 = time.time() - 60.0
    cids = [f"s{i}" for i in range(n_sessions)]
    for cid in cids:
        ds.save(cid, {"storm/#": {"qos": 1}}, 7200.0, now=t0)
    ds.add_filter("storm/#")
    ds.persist([
        Message(topic=f"storm/{k}", qos=1, payload=b"x" * 96,
                timestamp=time.time())
        for k in range(n_backlog)
    ])
    ds.sync()
    ds.close()

    budget = 256 * 1024
    b = Broker(config=_cfg(tmp_path / "ds", max_concurrent=64,
                           park_queue_cap=n_sessions,
                           replay_byte_budget=budget,
                           chunk_msgs=64))
    b.resume.running = True
    # spy on the read layer: every round's byte pull must respect the
    # budget (+ one session's chunk of slack — cursor granularity)
    rounds_bytes = []
    orig = b.durable.replay_chunk_many

    def spy(states, max_msgs=1024, byte_budget=None):
        out = orig(states, max_msgs=max_msgs, byte_budget=byte_budget)
        rounds_bytes.append(out[2])
        return out

    b.durable.replay_chunk_many = spy
    chans = {}
    for cid in cids:
        ch = SinkChannel()
        _s, present = b.open_session(
            False, cid, ch, expiry_interval=7200.0, max_inflight=1000
        )
        assert present
        chans[cid] = ch
    m = b.metrics.all()
    assert m["session.resume.parked"] == n_sessions - 64
    assert b.resume.info()["parked"] == n_sessions - 64

    # one live subscriber rides along; live publishes must stay fast
    # while the storm drains
    live = SinkChannel()
    ls, _ = b.cm.open_session(True, "live-sub", live)
    ls.subscribe("live/x", SubOpts(qos=0))
    b.subscribe("live-sub", "live/x", SubOpts(qos=0))
    live_lat = []
    pending = set(cids)
    rounds = 0
    while pending:
        b.resume.drain_once()
        rounds += 1
        assert rounds < 20_000
        assert b.resume.info()["active"] <= 64
        if rounds % 10 == 0:
            t_live = time.perf_counter()
            b.publish_many([Message(topic="live/x", qos=0,
                                    payload=b"hb",
                                    timestamp=time.time())])
            live_lat.append(time.perf_counter() - t_live)
        if rounds % 50 == 0 or len(pending) < 256:
            pending = {c for c in pending if b.resume.pending(c)}
    assert rounds_bytes and max(rounds_bytes) <= budget + 64 * 1024
    # live traffic stayed bounded while 10k sessions drained: p99 of
    # a 1-message live window under 200 ms is loose enough for CI
    # noise while catching event-loop starvation outright
    live_lat.sort()
    assert live_lat, "no live publishes interleaved"
    assert live_lat[int(len(live_lat) * 0.99)] < 0.2
    assert live.n_pub == len(live_lat)
    # zero loss: every session received its whole backlog
    short = [c for c in cids if chans[c].n_pub < n_backlog]
    assert not short, (len(short), short[:5])
    assert b.metrics.all()["session.resumed"] == n_sessions
    assert b.metrics.all()["session.replay.windows"] >= (
        n_sessions // 64
    )
    b.durable.close()


# ------------------------------------------- lifecycle spans for free

def test_replayed_sampled_message_gets_lifecycle_spans(tmp_path):
    """The PR 8 'for free' claim, proven: replay windows ride the
    dispatch pipeline, so a lifecycle-sampled REPLAYED message gets a
    span cut from the replay window's flight record — source tagged,
    delivering clients named."""
    _seed_simple(tmp_path / "ds", ["t0", "t1"], n_msgs=3,
                 topic_of=lambda cid, j: f"q/{cid}/{j}")
    cfg = _cfg(tmp_path / "ds", chunk_msgs=50)
    cfg.tracing.enable = True
    cfg.tracing.sample_rate = 1.0
    cfg.tracing.seed = 7
    b = Broker(config=cfg)
    b.resume.running = True
    chans = {}
    for cid in ("t0", "t1"):
        ch = WireChannel(b)
        session, present = b.open_session(
            False, cid, ch, expiry_interval=3600.0, max_inflight=1000
        )
        assert present
        ch.send_packets(session.resume())
        chans[cid] = ch
    while b.resume.pending("t0") or b.resume.pending("t1"):
        b.resume.drain_once()
    store = b.lifecycle.store
    assert len(store) >= 1
    spans = [s for t in store.traces(limit=64)
             for s in store.get(t["trace_id"])]
    replay_spans = [
        s for s in spans if s["attrs"].get("source") == "replay"
    ]
    assert replay_spans, "no replay-window spans were cut"
    for s in replay_spans:
        assert s["attrs"]["deliveries"] >= 1
        # the delivering client is named on the span (decision-column
        # attribution, exactly as for live fan-out)
        assert s["attrs"].get("clients"), s
        assert s["attrs"]["clients"][0] in ("t0", "t1")
        # stage events from the replay window's flight record,
        # including the new replay_read stage
        names = {e["name"] for e in s["events"]}
        assert "stage.replay_read" in names
    # and the wire still carried NO trace context (the property the
    # rate-0 suite proves for live traffic holds for replay too: the
    # context never reaches a subscriber wire)
    for cid, ch in chans.items():
        parser = C.StreamParser(version=C.MQTT_V5)
        for pkt in parser.feed(ch.wire()):
            if pkt.type == C.PUBLISH:
                assert "emqx-tp-trace" not in (
                    pkt.properties.get("user_properties") or {}
                )
    b.durable.close()


# --------------------------------------------------- config + surfaces

def test_resume_config_bounds():
    cfg = BrokerConfig()
    cfg.durable.resume.max_concurrent = 0
    cfg.durable.resume.replay_byte_budget = 16
    cfg.durable.resume.park_queue_cap = -1
    cfg.durable.resume.chunk_msgs = 0
    problems = check_config(cfg)
    assert any("max_concurrent" in p for p in problems)
    assert any("replay_byte_budget" in p for p in problems)
    assert any("park_queue_cap" in p for p in problems)
    assert any("chunk_msgs" in p for p in problems)
    assert not check_config(BrokerConfig())


def test_resume_counters_in_metrics_registry():
    from emqx_tpu.metrics import METRICS

    for name in ("session.resume.parked", "session.resume.busy",
                 "session.replay.windows", "session.replay.messages"):
        assert name in METRICS  # fixed slot => /metrics exposition


def test_profiler_has_replay_read_stage():
    from emqx_tpu.observability import Profiler

    assert "replay_read" in Profiler.STAGES
