"""Window decision columns (PR 9): vectorized per-delivery QoS /
no-local / body-slot decisions, fused into the window pipeline.

The referee suite for the three dispatch paths:

  * device-fused   — `engine.decide_force = "dev"` runs the packed
    column through ops.match_kernel.decide_batch (JAX);
  * host-vectorized — `"host"` pins the numpy twin;
  * scalar fallback — `Broker._decide_columns = False` takes the
    pre-columns per-run path (`_dispatch_scalar` → deliver_run_native
    / Session.deliver).

All three must put bit-identical bytes on every connection's wire,
with identical delivery counts, per-qos sent metrics, and (pid, qos)
inflight windows, over random worlds mixing qos / no_local / RAP /
subid / upgrade_qos / v4-v5 / inflight pressure.  Plus: the lazy
delivery-list materialization (zero per-delivery tuples for windows
nobody consumes), the sampled-run tracer guard, the router attribute
columns staying in sync under churn, and the chaos criterion — 100%
device decide failure mid-stream still delivers QoS1 through the PR 1
circuit breaker.
"""

import random

import numpy as np
import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.session import SubOpts
from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig
from emqx_tpu.message import Message
from emqx_tpu.ops import dispatchasm, match_kernel
from emqx_tpu.router import Router

_native = dispatchasm.load()


def _broker(decide=None, columns=True):
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    b = Broker(config=cfg)
    b._decide_columns = columns
    if decide is not None:
        b.router.engine.decide_force = decide
    return b


class WireChannel(Channel):
    def __init__(self, broker, version=C.MQTT_V5):
        self.writes = []

        def send(pkts):
            self.writes.append(
                b"".join(C.serialize(p, self.version) for p in pkts)
            )

        super().__init__(broker, send=send, close=lambda r: None)
        self.version = version


# ------------------------------------------------ three-path parity

def _build_world(seed):
    rng = random.Random(seed)
    clients = []
    for i in range(12):
        subs = []
        for f in range(rng.randint(1, 3)):
            flt = rng.choice(
                ["t/#", "t/+/x", f"t/{f}/x", "s/only",
                 "$share/g1/t/+/x"]
            )
            subs.append({
                "flt": flt,
                "qos": rng.randint(0, 2),
                "rap": rng.random() < 0.4,
                "no_local": rng.random() < 0.3,
                "subid": rng.randint(1, 9)
                if rng.random() < 0.2 else None,
            })
        clients.append({
            "cid": f"c{i}",
            "version": rng.choice([C.MQTT_V4, C.MQTT_V5]),
            "upgrade": rng.random() < 0.3,
            "max_inflight": rng.choice([2, 4, 32]),
            "subs": subs,
        })
    windows = []
    for _ in range(4):
        win = []
        for _ in range(rng.randint(1, 12)):
            win.append({
                "topic": rng.choice(
                    ["t/1/x", "t/2/x", "t/0/x", "s/only", "t/deep/x"]
                ),
                "qos": rng.randint(0, 2),
                "retain": rng.random() < 0.3,
                "payload": bytes(
                    rng.randrange(256)
                    for _ in range(rng.randint(0, 200))
                ),
                "from": rng.choice(["c0", "c1", "pub"]),
            })
        windows.append(win)
    return clients, windows


def _run_world(clients, windows, mode):
    b = _broker(
        decide=mode if mode in ("host", "dev") else None,
        columns=mode != "scalar",
    )
    # deterministic shared-group picks so all three runs pick the
    # same member for every message
    b.router.shared._rng.seed(1234)
    chans = {}
    for c in clients:
        ch = WireChannel(b, version=c["version"])
        session, _ = b.cm.open_session(
            True, c["cid"], ch, max_inflight=c["max_inflight"]
        )
        session.upgrade_qos = c["upgrade"]
        for s in c["subs"]:
            opts = SubOpts(
                qos=s["qos"], retain_as_published=s["rap"],
                no_local=s["no_local"], subid=s["subid"],
            )
            session.subscribe(s["flt"], opts)
            b.subscribe(c["cid"], s["flt"], opts)
        chans[c["cid"]] = ch
    counts = []
    ts = 1.0e9
    for win in windows:
        msgs = [
            Message(
                topic=w["topic"], qos=w["qos"], retain=w["retain"],
                payload=w["payload"], from_client=w["from"],
                timestamp=ts,
            )
            for w in win
        ]
        counts.append(b.publish_many(msgs))
    wires = {
        cid: b"".join(bytes(x) for x in ch.writes)
        for cid, ch in chans.items()
    }
    sent = {
        k: b.metrics.val(k)
        for k in ("messages.sent", "messages.qos0.sent",
                  "messages.qos1.sent", "messages.qos2.sent",
                  "packets.publish.sent", "messages.delivered")
    }
    inflights = {
        c["cid"]: sorted(
            (pid, e.qos)
            for pid, e in b.cm.lookup(c["cid"]).inflight.items()
        )
        for c in clients
    }
    stats = b.router.engine.stats()
    return counts, wires, sent, inflights, stats


@pytest.mark.skipif(_native is None, reason="native dispatchasm unavailable")
@pytest.mark.parametrize("seed", [1, 2, 7, 23, 41])
def test_three_paths_bit_identical(seed):
    clients, windows = _build_world(seed)
    scalar = _run_world(clients, windows, "scalar")
    host = _run_world(clients, windows, "host")
    dev = _run_world(clients, windows, "dev")
    for other, label in ((host, "host"), (dev, "dev")):
        assert scalar[0] == other[0], (label, "counts")
        for cid in scalar[1]:
            assert scalar[1][cid] == other[1][cid], (label, cid)
        assert scalar[2] == other[2], (label, "sent metrics")
        assert scalar[3] == other[3], (label, "inflight")
    # the pinned paths really ran where they claim
    assert host[4]["decide_host_windows"] > 0
    assert host[4]["decide_dev_windows"] == 0
    assert dev[4]["decide_dev_windows"] > 0
    # and the parity run exercised every decoded byte stream
    for cid, wire in dev[1].items():
        version = next(
            c["version"] for c in clients if c["cid"] == cid
        )
        for pkt in C.StreamParser(version=version).feed(wire):
            assert pkt.type == C.PUBLISH


def test_decide_kernel_twins_bit_identical():
    """decide_batch (device) vs decide_batch_host (numpy) over random
    columns, including the padded-bucket path the engine uses."""
    rng = np.random.default_rng(7)
    for _ in range(20):
        r, n, b = 64, int(rng.integers(1, 700)), int(rng.integers(1, 40))
        cols = (
            rng.integers(0, 3, r).astype(np.int8),
            rng.random(r) < 0.3,
            rng.random(r) < 0.4,
            rng.random(r) < 0.2,
        )
        orows = rng.integers(0, r, n)
        crows = rng.integers(0, 100, n)
        midx = rng.integers(0, b, n)
        mq = rng.integers(0, 3, b).astype(np.int8)
        mr = rng.random(b) < 0.5
        mf = rng.integers(-1, 100, b).astype(np.int32)
        host = match_kernel.decide_batch_host(
            *cols, orows, crows, midx, mq, mr, mf
        )
        from emqx_tpu.engine import MatchEngine

        eng = MatchEngine(use_device=False)
        dev = eng._decide_device(
            cols, 0, orows, crows, midx, mq, mr, mf
        )
        assert np.array_equal(host, dev)


# --------------------------------------------- router attribute table

def test_router_opts_columns_track_churn():
    """Random subscribe/refresh/unsubscribe churn (direct + shared):
    the numpy attribute columns must mirror the opts table exactly."""
    rng = random.Random(5)
    r = Router()
    live = {}
    for step in range(400):
        cid = f"c{rng.randrange(8)}"
        flt = rng.choice(
            ["a/#", "b/+", "c/d", "$share/g/a/#", "$share/h/b/+"]
        )
        if (cid, flt) in live and rng.random() < 0.4:
            r.unsubscribe(cid, flt)
            del live[(cid, flt)]
        else:
            opts = SubOpts(
                qos=rng.randint(0, 2),
                no_local=rng.random() < 0.5,
                retain_as_published=rng.random() < 0.5,
                subid=rng.randint(1, 5)
                if rng.random() < 0.3 else None,
            )
            r.subscribe(cid, flt, opts)
            live[(cid, flt)] = opts
    qos, nl, rap, sid = r.opts_columns()
    checked = 0
    for slot, opts in enumerate(r._opts_table):
        if opts is None:
            continue
        checked += 1
        assert qos[slot] == opts.qos
        assert nl[slot] == opts.no_local
        assert rap[slot] == opts.retain_as_published
        assert sid[slot] == (opts.subid is not None)
    assert checked == len(
        [o for o in r._opts_table if o is not None]
    ) and checked > 0


# --------------------------------------------------- lazy deliveries

def _fanout_broker(n=8, qos=1, **kw):
    b = _broker(**kw)
    for i in range(n):
        cid = f"f{i}"
        ch = WireChannel(b)
        s, _ = b.cm.open_session(True, cid, ch)
        s.subscribe("t/#", SubOpts(qos=qos))
        b.subscribe(cid, "t/#", SubOpts(qos=qos))
    return b


def test_no_consumer_materializes_zero_delivery_tuples(monkeypatch):
    """No hook, no batch sink, no tracer: a whole fanout window must
    allocate ZERO per-delivery (msg, opts) tuples."""
    b = _fanout_broker(8)
    calls = []
    orig = Broker._materialize_run

    def spy(msgs, router, sm_l, so_a, k, e):
        calls.append((k, e))
        return orig(msgs, router, sm_l, so_a, k, e)

    monkeypatch.setattr(Broker, "_materialize_run", staticmethod(spy))
    counts = b.publish_many(
        [Message(topic=f"t/{i}", qos=1) for i in range(6)]
    )
    assert counts == [8] * 6
    assert calls == []


def test_delivered_hook_still_gets_per_run_lists():
    """Satellite 1 must not change the hook contract: with a callback
    registered, `message.delivered` fires once per (window, client)
    with the full delivery list."""
    b = _fanout_broker(3)
    got = []
    b.hooks.add(
        "message.delivered",
        lambda cid, ds: got.append((cid, len(ds), ds[0][0].topic)),
    )
    b.publish_many([Message(topic="t/a", qos=0)] * 2)
    assert sorted(got) == [
        ("f0", 2, "t/a"), ("f1", 2, "t/a"), ("f2", 2, "t/a")
    ]


def test_empty_hook_registry_skips_hook_walk(monkeypatch):
    """Satellite 1: with nothing registered, the window never calls
    hooks.run("message.delivered", ...) at all."""
    b = _fanout_broker(4)
    names = []
    orig_run = b.hooks.run

    def spy(name, *a):
        names.append(name)
        return orig_run(name, *a)

    monkeypatch.setattr(b.hooks, "run", spy)
    b.publish_many([Message(topic="t/x", qos=0)] * 3)
    assert "message.delivered" not in names


# ------------------------------------------- sampled-run tracer guard

def _tracing_broker(rate, n=6, filters=()):
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.tracing.enable = True
    cfg.tracing.sample_rate = rate
    cfg.tracing.topic_filters = list(filters)
    b = Broker(config=cfg)
    for i in range(n):
        cid = f"f{i}"
        ch = WireChannel(b)
        s, _ = b.cm.open_session(True, cid, ch)
        s.subscribe("t/#", SubOpts(qos=1))
        b.subscribe(cid, "t/#", SubOpts(qos=1))
    return b


def test_unsampled_window_materializes_nothing(monkeypatch):
    """Lifecycle tracing ACTIVE but nothing sampled (rate 0): the
    fanout window still allocates zero per-delivery tuples — the
    OBS601 sampled-guard idiom applied to materialization."""
    b = _tracing_broker(rate=0.0)
    calls = []
    orig = Broker._materialize_run
    monkeypatch.setattr(
        Broker, "_materialize_run",
        staticmethod(lambda *a: calls.append(a) or orig(*a)),
    )
    assert b.lifecycle.active
    counts = b.publish_many(
        [Message(topic=f"t/{i}", qos=1) for i in range(6)]
    )
    assert counts == [6] * 6
    assert calls == []


def test_sampled_message_materializes_only_its_runs(monkeypatch):
    """A pinned-topic sample mid-window materializes the delivery
    lists ONLY for runs that carry the sampled message, and its
    lifecycle span names the delivering clients."""
    b = _tracing_broker(rate=0.0, n=0, filters=["hot/#"])
    # two disjoint subscriber groups: only g* receive the sampled topic
    for i in range(3):
        cid = f"g{i}"
        ch = WireChannel(b)
        s, _ = b.cm.open_session(True, cid, ch)
        s.subscribe("hot/#", SubOpts(qos=1))
        b.subscribe(cid, "hot/#", SubOpts(qos=1))
    for i in range(3):
        cid = f"h{i}"
        ch = WireChannel(b)
        s, _ = b.cm.open_session(True, cid, ch)
        s.subscribe("cold/#", SubOpts(qos=1))
        b.subscribe(cid, "cold/#", SubOpts(qos=1))
    runs = []
    orig = Broker._materialize_run
    monkeypatch.setattr(
        Broker, "_materialize_run",
        staticmethod(lambda *a: runs.append(a[-2:]) or orig(*a)),
    )
    counts = b.publish_many([
        Message(topic="hot/x", qos=1),
        Message(topic="cold/x", qos=1),
    ])
    assert counts == [3, 3]
    # exactly the three hot-subscriber runs materialized (1 delivery
    # each); the three cold runs allocated nothing
    assert len(runs) == 3
    assert all(e - k == 1 for k, e in runs)
    (span,) = b.lifecycle.store.spans()
    assert sorted(span["attrs"]["clients"]) == ["g0", "g1", "g2"]
    assert span["attrs"]["clients_total"] == 3


# --------------------------------------------------- chaos: breaker

@pytest.fixture(autouse=True)
def _clear_failpoints():
    fp.clear()
    yield
    fp.clear()


def test_device_decide_failure_midstream_still_delivers_qos1():
    """Acceptance chaos criterion: 100% device decide failure
    mid-stream — every QoS1 window still delivers (host columns), and
    enough consecutive faults trip the shared PR 1 breaker, after
    which the decide step stops even trying the device."""
    b = _fanout_broker(4, decide="dev")
    eng = b.router.engine
    assert b.publish_many(
        [Message(topic="t/ok", qos=1)] * 2
    ) == [4, 4]
    assert eng.stats()["decide_dev_windows"] >= 1
    trips = []
    eng.on_breaker_trip = lambda info: trips.append(info)
    fp.configure("dispatch.decide.device", "error", prob=1.0)
    for i in range(4):  # breaker_threshold is 3
        assert b.publish_many(
            [Message(topic=f"t/{i}", qos=1)] * 2
        ) == [4, 4]
    stats = eng.stats()
    assert stats["decide_dev_errors"] >= 3
    assert stats["breaker_open"] is True
    assert trips and trips[0]["reason"] == "decide"
    # breaker open: no further device attempts, still delivering
    errs = stats["decide_dev_errors"]
    assert b.publish_many([Message(topic="t/z", qos=1)]) == [4]
    assert eng.stats()["decide_dev_errors"] == errs


# ------------------------------------------------ columns plumbing

def test_columns_path_engages_and_records_decide_stage():
    b = _fanout_broker(4)
    counts = b.publish_many(
        [Message(topic=f"t/{i}", qos=1) for i in range(8)]
    )
    assert counts == [4] * 8
    (win,) = b.profiler.windows(1)
    assert "decide" in win["stages_us"]
    if _native is not None:
        assert "assemble" in win["stages_us"]
    assert b.profiler.summary()["decide"]["count"] >= 1


def test_scalar_env_kill_switch(monkeypatch):
    monkeypatch.setenv("EMQX_TPU_NO_DECIDE", "1")
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    b = Broker(config=cfg)
    assert b._decide_columns is False
    ch = WireChannel(b)
    s, _ = b.cm.open_session(True, "c1", ch)
    s.subscribe("t/#", SubOpts(qos=1))
    b.subscribe("c1", "t/#", SubOpts(qos=1))
    assert b.publish(Message(topic="t/a", qos=1)) == 1
    (win,) = b.profiler.windows(1)
    assert "decide" not in win["stages_us"]


def test_shared_sub_single_delivery_through_columns():
    """One shared group member gets each message; group opts ride the
    interned opts-table slots."""
    b = _broker()
    for cid in ("s1", "s2"):
        ch = WireChannel(b)
        sess, _ = b.cm.open_session(True, cid, ch)
        opts = SubOpts(qos=1)
        sess.subscribe("$share/g/t/#", opts)
        b.subscribe(cid, "$share/g/t/#", opts)
    counts = b.publish_many(
        [Message(topic=f"t/{i}", qos=1) for i in range(10)]
    )
    assert counts == [1] * 10
    total = sum(
        len(b.cm.lookup(cid).inflight) for cid in ("s1", "s2")
    )
    assert total == 10


def test_closing_channel_run_not_counted_as_sent():
    """A channel that started closing mid-window drops its blob; the
    window-level sent flush must not count it (parity with the scalar
    path, which checks _closing before bumping)."""
    b = _fanout_broker(2)
    b.cm.channel("f0")._closing = True
    before = b.metrics.val("messages.sent")
    b.publish_many([Message(topic="t/a", qos=1)])
    assert b.metrics.val("messages.sent") - before == 1
    assert b.metrics.val("messages.qos1.sent") == 1


def test_decide_auto_first_device_window_warms_not_records():
    """Auto policy hygiene: the first device decide window pays the
    JIT compile and must not seed the cost EWMA (which would pin the
    policy to host forever); the second window records."""
    from emqx_tpu.engine import MatchEngine

    eng = MatchEngine(use_device=None)
    rng = np.random.default_rng(3)
    r, n, bsz = 64, 4096, 16
    cols = (
        rng.integers(0, 3, r).astype(np.int8),
        rng.random(r) < 0.3, rng.random(r) < 0.3, rng.random(r) < 0.1,
    )
    args = (
        rng.integers(0, r, n), rng.integers(0, 50, n),
        rng.integers(0, bsz, n),
        rng.integers(0, 3, bsz).astype(np.int8),
        rng.random(bsz) < 0.5,
        rng.integers(-1, 50, bsz).astype(np.int32),
    )
    _, path1 = eng.decide_window(cols, 1, *args)
    assert path1 == "dev"  # unmeasured big window probes the device
    assert eng._dec_dev_us is None  # compile window not recorded
    _, path2 = eng.decide_window(cols, 1, *args)
    assert path2 == "dev"
    assert eng._dec_dev_us is not None


def test_sampled_span_clients_exclude_no_local_drops():
    """The span's delivering-clients list must not name a client whose
    only delivery was no-local-dropped."""
    b = _tracing_broker(rate=0.0, n=0, filters=["hot/#"])
    for cid, nl in (("gx", True), ("gy", False)):
        ch = WireChannel(b)
        s, _ = b.cm.open_session(True, cid, ch)
        opts = SubOpts(qos=1, no_local=nl)
        s.subscribe("hot/#", opts)
        b.subscribe(cid, "hot/#", opts)
    # published BY gx: gx's no_local subscription drops it on gx only
    assert b.publish(Message(topic="hot/x", qos=1, from_client="gx")) == 2
    (span,) = b.lifecycle.store.spans()
    assert span["attrs"]["clients"] == ["gy"]
