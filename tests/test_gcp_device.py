"""GCP IoT-Core compat devices (emqx_gcp_device parity): registry
CRUD over REST, and JWT-per-connect authentication with the device's
registered RS256/ES256 public key."""

import asyncio
import base64
import json
import tempfile
import time

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from mqtt_client import TestClient

_MGMT_TMP = tempfile.TemporaryDirectory(prefix="emqx-gcp-")

CLIENTID = (
    "projects/p1/locations/us-central1/registries/reg1/devices/dev1"
)


def run(coro):
    return asyncio.run(coro)


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _make_keypair():
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import rsa

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pub_pem = key.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo,
    ).decode()
    return key, pub_pem


def _rs256_jwt(key, claims) -> str:
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding

    head = _b64url(json.dumps({"alg": "RS256", "typ": "JWT"}).encode())
    body = _b64url(json.dumps(claims).encode())
    sig = key.sign(
        f"{head}.{body}".encode(), padding.PKCS1v15(), hashes.SHA256()
    )
    return f"{head}.{body}.{_b64url(sig)}"


def test_deviceid_parse():
    from emqx_tpu.gcp_device import deviceid_from_clientid

    assert deviceid_from_clientid(CLIENTID) == "dev1"
    assert deviceid_from_clientid("ordinary-client") is None
    assert deviceid_from_clientid("projects/p/devices/d") is None
    assert deviceid_from_clientid(
        "projects/p/locations/l/registries/r/devices/"
    ) is None


def test_gcp_device_jwt_connect():
    """A registered device connects with a fresh RS256 JWT; a wrong
    key or an expired JWT is rejected (authn.erl's decision ladder)."""
    key, pub_pem = _make_keypair()
    wrong_key, _ = _make_keypair()

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.auth.allow_anonymous = False
        cfg.gcp_device_enable = True
        cfg.gcp_device_file = tempfile.mktemp(
            suffix=".json", dir=_MGMT_TMP.name
        )
        srv = BrokerServer(cfg)
        await srv.start()
        port = srv.listeners[0].port
        srv.broker.gcp_devices.put_device({
            "deviceid": "dev1",
            "keys": [{"key_type": "RSA_PEM", "key": pub_pem,
                      "expires_at": 0}],
            "project": "p1", "location": "us-central1",
            "registry": "reg1",
        })

        good = _rs256_jwt(key, {"aud": "p1",
                                "exp": int(time.time()) + 300})
        c = TestClient(port, CLIENTID)
        ack = await c.connect(password=good.encode())
        assert ack.reason_code == 0
        await c.disconnect()

        # wrong key -> rejected
        bad = _rs256_jwt(wrong_key, {"exp": int(time.time()) + 300})
        c2 = TestClient(port, CLIENTID)
        ack2 = await c2.connect(password=bad.encode())
        assert ack2.reason_code != 0
        await c2.close()

        # expired JWT -> rejected even with the right key
        stale = _rs256_jwt(key, {"exp": int(time.time()) - 300})
        c3 = TestClient(port, CLIENTID)
        ack3 = await c3.connect(password=stale.encode())
        assert ack3.reason_code != 0
        await c3.close()

        # expired KEY -> rejected (actual_keys filters it out)
        srv.broker.gcp_devices.put_device({
            "deviceid": "dev1",
            "keys": [{"key_type": "RSA_PEM", "key": pub_pem,
                      "expires_at": time.time() - 10}],
        })
        c4 = TestClient(port, CLIENTID)
        ack4 = await c4.connect(password=good.encode())
        assert ack4.reason_code != 0
        await c4.close()
        await srv.stop()

    run(t())


def test_gcp_device_registry_persistence_and_rest():
    key, pub_pem = _make_keypair()

    async def t():
        import aiohttp

        from api_helper import auth_session

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.api.enable = True
        cfg.api.port = 0
        cfg.api.data_dir = tempfile.mkdtemp(dir=_MGMT_TMP.name)
        cfg.gcp_device_enable = True
        cfg.gcp_device_file = tempfile.mktemp(
            suffix=".json", dir=_MGMT_TMP.name
        )
        srv = BrokerServer(cfg)
        await srv.start()
        http, api = await auth_session(srv)
        async with http:
            async with http.post(api + "/api/v5/gcp_devices", json=[
                {"deviceid": "d1",
                 "keys": [{"key": pub_pem, "expires_at": 0}]},
                {"deviceid": "d2", "keys": []},
                {"keys": "not-a-device"},
            ]) as r:
                out = await r.json()
                # bad entries are skipped, not aborting the batch
                assert out["imported"] == 2 and out["errors"] == 1
            # malformed key objects are a 400, not a 500
            async with http.put(
                api + "/api/v5/gcp_devices/dX",
                json={"keys": ["bare-string"]},
            ) as r:
                assert r.status == 400
            async with http.get(api + "/api/v5/gcp_devices") as r:
                assert (await r.json())["meta"]["count"] == 2
            async with http.put(
                api + "/api/v5/gcp_devices/d3",
                json={"keys": [{"key": pub_pem}]},
            ) as r:
                assert (await r.json())["deviceid"] == "d3"
            async with http.delete(
                api + "/api/v5/gcp_devices/d2"
            ) as r:
                assert r.status == 204
            async with http.get(
                api + "/api/v5/gcp_devices/d2"
            ) as r:
                assert r.status == 404
        await srv.stop()

        # the registry file survives a restart
        srv2 = BrokerServer(cfg)
        await srv2.start()
        assert srv2.broker.gcp_devices.get_device("d1") is not None
        assert srv2.broker.gcp_devices.get_device("d3") is not None
        assert srv2.broker.gcp_devices.get_device("d2") is None
        await srv2.stop()

    run(t())
