"""File transfer over $file/ topics + plugin loading + dashboard
(emqx_ft / emqx_plugins / emqx_dashboard parity)."""

import asyncio
import tempfile

# auto-cleaned parent for per-test mgmt stores (finalized at interpreter exit)
_MGMT_TMP = tempfile.TemporaryDirectory(prefix="emqx-mgmt-")
import json

import aiohttp

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from api_helper import auth_session
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def test_file_transfer_assembly(tmp_path):
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.ft.enable = True
        cfg.ft.storage_dir = str(tmp_path / "ft")
        srv = BrokerServer(cfg)
        await srv.start()
        port = srv.listeners[0].port

        c = TestClient(port, "uploader")
        await c.connect()
        await c.subscribe("$file/f1/response")
        data = bytes(range(256)) * 40  # 10240 bytes
        await c.publish(
            "$file/f1/init",
            json.dumps({"name": "blob.bin", "size": len(data)}).encode(),
        )
        resp = await c.recv_publish()
        assert json.loads(resp.payload)["result"] == "ok"
        # segments out of order
        await c.publish("$file/f1/5120", data[5120:])
        await c.publish("$file/f1/0", data[:5120])
        await c.publish("$file/f1/fin", b"")
        resp2 = await c.recv_publish()
        body = json.loads(resp2.payload)
        assert body["result"] == "ok", body
        with open(body["detail"], "rb") as f:
            assert f.read() == data

        # size mismatch is rejected
        await c.subscribe("$file/f2/response")
        await c.publish(
            "$file/f2/init", json.dumps({"size": 10}).encode()
        )
        await c.publish("$file/f2/0", b"short")
        await c.publish("$file/f2/fin", b"")
        msgs = [await c.recv_publish() for _ in range(2)]
        results = [json.loads(m.payload)["result"] for m in msgs]
        assert "error" in results
        await c.disconnect()
        await srv.stop()

    run(t())


def test_plugin_loading(tmp_path):
    plugin_dir = tmp_path / "plugins"
    plugin_dir.mkdir()
    (plugin_dir / "stamp.py").write_text(
        "def setup(broker):\n"
        "    from emqx_tpu.hooks import STOP_WITH\n"
        "    def stamp(msg):\n"
        "        msg.properties['user_property'] = [('via', 'plugin')]\n"
        "        return msg\n"
        "    cb = broker.hooks.add('message.publish', stamp)\n"
        "    class H:\n"
        "        def teardown(self, broker):\n"
        "            broker.hooks.delete('message.publish', cb)\n"
        "    return H()\n"
    )

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.plugins = ["stamp"]
        cfg.plugin_dir = str(plugin_dir)
        srv = BrokerServer(cfg)
        await srv.start()
        assert srv.broker.plugins.info() == [
            {"name": "stamp", "status": "running"}
        ]
        port = srv.listeners[0].port
        sub = TestClient(port, "s")
        await sub.connect()
        await sub.subscribe("p/#", qos=1)
        pub = TestClient(port, "p")
        await pub.connect()
        await pub.publish("p/x", b"hello", qos=1)
        pkt = await sub.recv_publish()
        assert ("via", "plugin") in pkt.properties.get("user_property", [])
        await pub.disconnect()
        await sub.disconnect()
        await srv.stop()

    run(t())


def test_dashboard_page():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.api.enable = True
        cfg.api.data_dir = tempfile.mkdtemp(dir=_MGMT_TMP.name)
        cfg.api.port = 0
        srv = BrokerServer(cfg)
        await srv.start()
        http, api = await auth_session(srv)
        async with http:
            async with http.get(api + "/dashboard") as r:
                text = await r.text()
            assert r.status == 200
            assert "emqx_tpu" in text and "connections" in text
            # the SPA drives these endpoints; verify its contract
            async with http.get(api + "/api/v5/stats") as r:
                stats = await r.json()
            assert "connections.count" in stats
            async with http.get(api + "/api/v5/nodes") as r:
                nodes = await r.json()
            assert nodes["data"][0]["node_status"] == "running"
            async with http.get(api + "/api/v5/clients") as r:
                clients = await r.json()
            assert "data" in clients
            async with http.get(api + "/api/v5/alarms") as r:
                alarms = await r.json()
            assert "data" in alarms
            async with http.get(api + "/api/v5/rules") as r:
                rules = await r.json()
            assert "data" in rules
        # anonymous fetch serves the SPA shell too (login is in-page)
        import aiohttp

        async with aiohttp.ClientSession() as anon:
            async with anon.get(api + "/dashboard") as r:
                text = await r.text()
            assert r.status == 200 and "/api/v5/login" in text
        await srv.stop()

    run(t())


def test_plugin_package_install_and_load(tmp_path):
    """Installable release packages (emqx_plugins ensure_installed):
    a <name>-<vsn>.tar.gz with release.json + sources installs into
    the plugin dir and loads by release name; unsafe member paths are
    rejected."""
    import io
    import json as _json
    import tarfile

    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.config import BrokerConfig
    from emqx_tpu.plugins import PluginManager

    def make_pkg(path, member_prefix="counter_pkg-1.0.0/"):
        with tarfile.open(path, "w:gz") as tf:
            def add(name, data):
                info = tarfile.TarInfo(member_prefix + name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
            add("release.json", _json.dumps({
                "name": "counter_pkg", "rel_vsn": "1.0.0",
                "description": "counts publishes",
            }).encode())
            add("counter_pkg.py", (
                "def setup(broker):\n"
                "    seen = []\n"
                "    broker.hooks.add('message.publish',\n"
                "                     lambda m: seen.append(m.topic) or m)\n"
                "    class H:\n"
                "        def teardown(self, broker):\n"
                "            seen.clear()\n"
                "    h = H(); h.seen = seen\n"
                "    return h\n"
            ).encode())

    pkg = tmp_path / "counter_pkg-1.0.0.tar.gz"
    make_pkg(str(pkg))
    broker = Broker(BrokerConfig())
    pm = PluginManager(broker, directory=str(tmp_path / "plugins"))
    os_rel = pm.install_package(str(pkg))
    assert os_rel == "counter_pkg-1.0.0"
    assert pm.load(os_rel)

    from emqx_tpu.message import Message

    broker.publish(Message(topic="pkg/x", payload=b"1"))
    handle = pm._loaded[os_rel]
    assert handle.seen == ["pkg/x"]
    assert pm.unload(os_rel)

    # path traversal is rejected
    import pytest as _pytest

    evil = tmp_path / "evil-1.tar.gz"
    with tarfile.open(str(evil), "w:gz") as tf:
        data = _json.dumps({"name": "evil", "rel_vsn": "1"}).encode()
        info = tarfile.TarInfo("evil-1/release.json")
        info.size = len(data)
        tf.addfile(info, io.BytesIO(data))
        bad = tarfile.TarInfo("../../outside.py")
        bad.size = 1
        tf.addfile(bad, io.BytesIO(b"x"))
    pm2 = PluginManager(broker, directory=str(tmp_path / "p2"))
    with _pytest.raises(ValueError):
        pm2.install_package(str(evil))
