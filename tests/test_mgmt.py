"""Observability surface: $SYS heartbeat topics, REST API, Prometheus
exposition (emqx_sys / emqx_management / emqx_prometheus parity at the
black-box level)."""

import asyncio
import tempfile

# auto-cleaned parent for per-test mgmt stores (finalized at interpreter exit)
_MGMT_TMP = tempfile.TemporaryDirectory(prefix="emqx-mgmt-")
import json

import aiohttp

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from api_helper import auth_session
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def make_server(sys_interval=3600.0):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.api.enable = True
    cfg.api.data_dir = tempfile.mkdtemp(dir=_MGMT_TMP.name)
    cfg.api.port = 0
    cfg.sys.interval = sys_interval
    return BrokerServer(cfg)


def test_sys_heartbeat_over_mqtt():
    async def t():
        srv = make_server(sys_interval=0.0)  # publish on every tick
        await srv.start()
        port = srv.listeners[0].port
        sub = TestClient(port, "mon")
        await sub.connect()
        await sub.subscribe("$SYS/#")
        srv.sys.tick()  # drive directly instead of waiting 1s
        seen = {}
        for _ in range(8):
            pkt = await sub.recv_publish()
            seen[pkt.topic.rsplit("/", 1)[-1]] = pkt.payload
        assert "version" in seen and b"emqx_tpu" in seen["version"]
        assert "uptime" in seen
        stats = json.loads(seen["stats"])
        assert stats["connections.count"] >= 1
        await sub.disconnect()
        await srv.stop()

    run(t())


def test_rest_clients_subscriptions_stats():
    async def t():
        srv = make_server()
        await srv.start()
        port = srv.listeners[0].port
        http, api = await auth_session(srv)

        c = TestClient(port, "dev-42")
        await c.connect()
        await c.subscribe("tele/+/up", qos=1)

        async with http:
            async with http.get(api + "/api/v5/clients") as r:
                data = await r.json()
            assert r.status == 200
            assert any(x["clientid"] == "dev-42" for x in data["data"])

            async with http.get(api + "/api/v5/clients/dev-42") as r:
                one = await r.json()
            assert one["connected"] is True

            async with http.get(api + "/api/v5/subscriptions") as r:
                subs = await r.json()
            assert {"clientid": "dev-42", "topic": "tele/+/up"} in subs["data"]

            async with http.get(api + "/api/v5/topics") as r:
                topics = await r.json()
            assert any(t["topic"] == "tele/+/up" for t in topics["data"])

            async with http.get(api + "/api/v5/stats") as r:
                stats = await r.json()
            assert stats["connections.count"] == 1

            # publish over REST, delivered over MQTT
            async with http.post(
                api + "/api/v5/publish",
                json={"topic": "tele/7/up", "payload": "ping", "qos": 1},
            ) as r:
                out = await r.json()
            assert out["delivered"] == 1
            pkt = await c.recv_publish()
            assert pkt.topic == "tele/7/up" and pkt.payload == b"ping"

            # kick over REST
            async with http.delete(api + "/api/v5/clients/dev-42") as r:
                assert r.status == 204
            await asyncio.sleep(0.05)
            async with http.get(api + "/api/v5/clients/dev-42") as r2:
                assert r2.status in (200, 404)

        await c.close()
        await srv.stop()

    run(t())


def test_rest_rules_crud():
    async def t():
        srv = make_server()
        await srv.start()
        http, api = await auth_session(srv)
        async with http:
            async with http.post(
                api + "/api/v5/rules",
                json={
                    "id": "r9",
                    "sql": 'SELECT * FROM "a/#" WHERE payload.x > 1',
                },
            ) as r:
                assert r.status == 201
            async with http.get(api + "/api/v5/rules") as r:
                rules = await r.json()
            assert rules["data"][0]["id"] == "r9"
            async with http.post(
                api + "/api/v5/rules", json={"id": "bad", "sql": "NOT SQL"}
            ) as r:
                assert r.status == 400
            async with http.delete(api + "/api/v5/rules/r9") as r:
                assert r.status == 204
            async with http.delete(api + "/api/v5/rules/r9") as r:
                assert r.status == 404
        await srv.stop()

    run(t())


def test_prometheus_exposition():
    async def t():
        srv = make_server()
        await srv.start()
        port = srv.listeners[0].port
        c = TestClient(port, "p")
        await c.connect()
        await c.publish("x/y", b"1", qos=1)
        http, api = await auth_session(srv)
        async with http:
            async with http.get(api + "/metrics") as r:
                text = await r.text()
        assert r.status == 200
        assert "# TYPE emqx_messages_received counter" in text
        assert "emqx_messages_received 1" in text
        assert "# TYPE emqx_connections_count gauge" in text
        assert "emqx_uptime_seconds" in text
        await c.disconnect()
        await srv.stop()

    run(t())


def test_telemetry_reporter():
    from aiohttp import web

    async def t():
        reports = []

        async def handle(request):
            reports.append(await request.json())
            return web.Response(status=200)

        app = web.Application()
        app.router.add_post("/t", handle)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        tport = runner.addresses[0][1]

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.telemetry_enable = True
        cfg.telemetry_url = f"http://127.0.0.1:{tport}/t"
        cfg.telemetry_interval = 0.0  # report on every tick
        srv = BrokerServer(cfg)
        await srv.start()
        assert srv.telemetry.tick()
        for _ in range(100):
            if reports:
                break
            await asyncio.sleep(0.02)
        assert reports and reports[0]["version"].startswith("emqx_tpu")
        assert "uuid" in reports[0] and reports[0]["cluster_size"] == 1
        # nothing sensitive leaves: only counts and names
        assert set(reports[0]) <= {
            "uuid", "version", "uptime", "connections", "subscriptions",
            "rules", "gateways", "cluster_size",
        }
        await srv.stop()
        await runner.cleanup()

    run(t())
