"""LTS storage layout (ds/lts.py): learned topic structures + bitmask
composite keys, differential-tested against the in-memory oracle (the
emqx_ds_storage_reference pattern) and benchmarked for the property
that justifies it — wildcard replay scans only overlapping structures
instead of every record (emqx_ds_lts.erl:100-143,
emqx_ds_bitmask_keymapper.erl:20-70)."""

import random
import time

import pytest

from emqx_tpu.ds import ReferenceStorage
from emqx_tpu.ds.lts import VAR_BITS, LtsIndex, LtsStorage, _overlaps
from emqx_tpu.message import Message

from test_ds import drain, make_msgs


# ----------------------------------------------------------- index unit

def test_overlap_matrix():
    cases = [
        ("a/b", "a/b", True),
        ("a/+", "a/b", True),
        ("a/#", "a/b/c", True),
        ("#", "x/y", True),
        ("a/b", "a/+", True),   # structure's var level
        ("a/b/c", "a/b", False),
        ("a/b", "a/b/c", False),
        ("x/+", "y/+", False),
    ]
    for f, p, want in cases:
        assert _overlaps(f.split("/"), p.split("/")) == want, (f, p)


def test_level_discovery_flips_to_varying():
    idx = LtsIndex(var_threshold=4)
    for i in range(10):
        idx.learn(["fleet", f"v{i}", "temp"])
    # after the threshold, new vehicle ids merge under '+'
    assert "fleet/+/temp" in idx._sids
    sid, varw = idx.learn(["fleet", "v999", "temp"])
    assert idx._patterns[sid] == "fleet/+/temp"
    assert varw == ["v999"]
    # low-variability structures stay concrete
    sid2, varw2 = idx.learn(["cfg", "global"])
    assert idx._patterns[sid2] == "cfg/global" and varw2 == []


def test_concrete_filter_maps_to_one_stream():
    idx = LtsIndex(var_threshold=4)
    keys = set()
    for i in range(50):
        keys.add(idx.key_of(f"fleet/v{i}/temp"))
    assert len(keys) > 1  # var hash spreads sub-streams
    shards = idx.shards_for_filter("fleet/v7/temp", keys)
    assert len(shards) == 1
    assert shards[0] == idx.key_of("fleet/v7/temp")
    # wildcard over the varying level: all of the structure's shards
    assert set(idx.shards_for_filter("fleet/+/temp", keys)) == keys
    # non-overlapping filter: nothing
    assert idx.shards_for_filter("grid/+/load", keys) == []


def test_index_json_roundtrip():
    idx = LtsIndex(var_threshold=3)
    for i in range(20):
        idx.learn(["a", f"x{i}", "b"])
    idx2 = LtsIndex.from_json(idx.to_json())
    assert idx2.key_of("a/x5/b") == idx.key_of("a/x5/b")
    assert idx2._patterns == idx._patterns


# ----------------------------------------------------- oracle equivalence

@pytest.mark.parametrize("seed", [0, 1, 2])
def test_lts_matches_reference_oracle(tmp_path, seed):
    rng = random.Random(seed)
    msgs = make_msgs(rng, 300)
    # plus a high-variability family that exercises the var flip
    t0 = 1_700_000_500.0
    for i in range(200):
        msgs.append(Message(
            topic=f"veh/v{i % 60}/s/{rng.choice(['t', 'p'])}",
            payload=f"vv-{i}".encode(),
            timestamp=t0 + i * 0.001,
        ))
    lts = LtsStorage(str(tmp_path / "lts"), var_threshold=8)
    oracle = ReferenceStorage(n_streams=8)
    for i in range(0, len(msgs), 41):
        batch = msgs[i: i + 41]
        lts.store_batch(batch)
        oracle.store_batch(batch)
    for flt in ("#", "fleet/#", "dev/+", "a/b", "+/+/x7", "nomatch/+",
                "veh/v7/s/t", "veh/+/s/t", "veh/v8/#", "veh/+/s/+"):
        assert drain(lts, flt) == drain(oracle, flt), flt
    lts.close()


def test_lts_crash_recovery_rebuilds_index(tmp_path):
    d = str(tmp_path / "ds")
    store = LtsStorage(d, var_threshold=4)
    msgs = [
        Message(topic=f"iot/d{i}/x", payload=str(i).encode(),
                timestamp=1_700_000_000.0 + i)
        for i in range(30)
    ]
    store.store_batch(msgs)
    store._log.sync()  # data durable, index NOT saved (crash window)
    store._log.close()

    store2 = LtsStorage(d, var_threshold=4)  # index rebuilt from log
    got = drain(store2, "iot/+/x")
    assert len(got) == 30
    # and new writes keep mapping consistently with the old ones
    store2.store_batch([Message(
        topic="iot/d5/x", payload=b"new", timestamp=1_700_000_100.0
    )])
    got2 = drain(store2, "iot/d5/x")
    assert (b"5" in dict((p, p) for _, p in got2)
            or len(got2) == 2)
    store2.close()


# --------------------------------------------------------- the property

def test_wildcard_replay_is_sublinear(tmp_path):
    """The layout's reason to exist: with 100k+ topics across several
    structures, replaying one structure's wildcard must NOT scan the
    other structures' records, and a concrete filter must touch ~1
    sub-stream.  The flat hash layout scans (and decodes) every record
    of a 2-level hash shard."""
    n_per_family = 40_000
    fams = ["veh/%d/t", "grid/%d/load", "app/%d/evt"]
    lts = LtsStorage(str(tmp_path / "big"), var_threshold=16)
    t0 = 1_700_000_000.0
    for f_i, fam in enumerate(fams):
        batch = [
            Message(topic=fam % i, payload=b"x",
                    timestamp=t0 + f_i * n_per_family + i)
            for i in range(n_per_family)
        ]
        lts.store_batch(batch)
    total = lts.stats()["records"]
    assert total == n_per_family * len(fams)  # 120k records

    # wildcard over ONE family: scanned streams hold only that family
    shards = lts.get_streams("veh/+/t")
    scanned = sum(
        lts._log.stream_count(s.shard) for s in shards
    )
    assert scanned == n_per_family  # not 120k: sub-linear vs flat scan

    # concrete topic: ~1/(2^VAR_BITS) of the family
    shards_c = lts.get_streams("veh/123/t")
    assert len(shards_c) == 1
    scanned_c = lts._log.stream_count(shards_c[0].shard)
    assert scanned_c <= max(4 * n_per_family / (1 << VAR_BITS), 64)

    # and the replay itself returns exactly the right record fast
    t1 = time.perf_counter()
    out = drain(lts, "veh/123/t", page=64)
    dt = time.perf_counter() - t1
    assert len(out) == 1
    assert dt < 1.0  # decodes dozens of records, not 120k
    lts.close()


def test_lts_sids_stable_across_gc_and_rebuild(tmp_path):
    """Review r5: stream keys bake structure ids in, so a crash-forced
    index rebuild AFTER gc reclaimed an early structure's records must
    not renumber the survivors — the persisted pattern registry is the
    sid ground truth, and replay must keep finding the surviving
    structures' records."""
    import os
    import time as _time

    d = str(tmp_path / "ds")
    store = LtsStorage(d, var_threshold=4, seg_bytes=512)
    t_old = 1_700_000_000.0
    t_new = 1_700_900_000.0
    # structure 0: old records only (will be GC'd wholesale)
    store.store_batch([
        Message(topic=f"old/x{i}/t", payload=b"o",
                timestamp=t_old + i)
        for i in range(20)
    ])
    # structure(s) for the survivors, written much later
    store.store_batch([
        Message(topic=f"new/y{i}/t", payload=b"n",
                timestamp=t_new + i)
        for i in range(20)
    ])
    store.sync()
    # reclaim everything older than the cutoff: structure "old/+/t"
    # loses ALL its records
    store.gc(int((t_old + 1000) * 1e6))
    # crash window: the log moved but the index count was not re-saved
    store._log.sync()
    store._log.close()
    idx_path = os.path.join(d, "lts_index.json")
    if os.path.exists(idx_path):
        os.remove(idx_path)  # worst case: trie cache gone entirely

    store2 = LtsStorage(d, var_threshold=4, seg_bytes=512)
    got = drain(store2, "new/+/t")
    assert len(got) == 20, len(got)  # survivors still replay
    # and new writes to the surviving structure join the same streams
    store2.store_batch([Message(
        topic="new/y3/t", payload=b"post", timestamp=t_new + 500,
    )])
    got2 = drain(store2, "new/y3/t")
    assert len(got2) == 2
    store2.close()
