"""Minimal asyncio MQTT test client speaking real bytes through the
repo codec — the role `emqtt` plays in the reference's client suites
(apps/emqx/test/emqx_client_SUITE.erl): black-box testing through an
actual socket."""

from __future__ import annotations

import asyncio
import itertools
from typing import List, Optional

from emqx_tpu.codec import mqtt as C


class TestClient:
    __test__ = False  # not a pytest class

    def __init__(
        self,
        port: int,
        client_id: str = "",
        version: int = C.MQTT_V5,
        host: str = "127.0.0.1",
    ):
        self.host, self.port = host, port
        self.client_id = client_id
        self.version = version
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.parser = C.StreamParser(version=version)
        self._pids = itertools.count(1)
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._pump: Optional[asyncio.Task] = None

    async def connect(
        self,
        clean_start: bool = True,
        keepalive: int = 60,
        username: Optional[str] = None,
        password: Optional[bytes] = None,
        will: Optional[C.Will] = None,
        properties: Optional[dict] = None,
    ) -> C.Connack:
        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port
        )
        self._pump = asyncio.get_running_loop().create_task(self._read_loop())
        await self.send(
            C.Connect(
                client_id=self.client_id,
                proto_ver=self.version,
                clean_start=clean_start,
                keepalive=keepalive,
                username=username,
                password=password,
                will=will,
                properties=properties or {},
            )
        )
        ack = await self.expect(C.CONNACK)
        return ack

    async def _read_loop(self) -> None:
        try:
            while True:
                data = await self.reader.read(65536)
                if not data:
                    break
                for pkt in self.parser.feed(data):
                    await self._inbox.put(pkt)
        except (ConnectionError, asyncio.CancelledError):
            pass
        await self._inbox.put(None)  # EOF marker

    async def send(self, pkt: C.Packet) -> None:
        self.writer.write(C.serialize(pkt, self.version))
        await self.writer.drain()

    async def recv(self, timeout: float = 2.0) -> Optional[C.Packet]:
        """Next packet, or None on EOF."""
        return await asyncio.wait_for(self._inbox.get(), timeout)

    async def expect(self, ptype: int, timeout: float = 2.0) -> C.Packet:
        """Next packet of the given type; auto-acks nothing, fails on
        EOF or a different packet type."""
        pkt = await self.recv(timeout)
        assert pkt is not None, "connection closed while waiting"
        assert pkt.type == ptype, f"expected type {ptype}, got {pkt!r}"
        return pkt

    async def subscribe(
        self, *filters, qos: int = 0, **subopts
    ) -> C.Suback:
        pid = next(self._pids)
        subs = [
            C.Subscription(topic_filter=f, qos=qos, **subopts)
            for f in filters
        ]
        await self.send(C.Subscribe(packet_id=pid, subscriptions=subs))
        ack = await self.expect(C.SUBACK)
        assert ack.packet_id == pid
        return ack

    async def unsubscribe(self, *filters) -> C.Unsuback:
        pid = next(self._pids)
        await self.send(
            C.Unsubscribe(packet_id=pid, topic_filters=list(filters))
        )
        ack = await self.expect(C.UNSUBACK)
        assert ack.packet_id == pid
        return ack

    async def publish(
        self,
        topic: str,
        payload: bytes = b"",
        qos: int = 0,
        retain: bool = False,
        properties: Optional[dict] = None,
        timeout: float = 2.0,
    ) -> Optional[C.Packet]:
        """Publish and complete the QoS handshake; returns the final
        ack (PUBACK/PUBCOMP) or None for QoS 0."""
        pid = next(self._pids) if qos else None
        await self.send(
            C.Publish(
                topic=topic,
                payload=payload,
                qos=qos,
                retain=retain,
                packet_id=pid,
                properties=properties or {},
            )
        )
        if qos == 0:
            return None
        if qos == 1:
            ack = await self.expect(C.PUBACK, timeout=timeout)
            assert ack.packet_id == pid
            return ack
        rec = await self.expect(C.PUBREC, timeout=timeout)
        assert rec.packet_id == pid
        await self.send(C.Pubrel(packet_id=pid))
        comp = await self.expect(C.PUBCOMP)
        assert comp.packet_id == pid
        return comp

    async def recv_publish(self, timeout: float = 2.0, ack: bool = True) -> C.Publish:
        """Wait for an inbound PUBLISH, completing its QoS handshake."""
        while True:
            pkt = await self.recv(timeout)
            assert pkt is not None, "connection closed"
            if pkt.type != C.PUBLISH:
                continue
            if ack and pkt.qos == 1:
                await self.send(C.Puback(packet_id=pkt.packet_id))
            elif ack and pkt.qos == 2:
                await self.send(C.Pubrec(packet_id=pkt.packet_id))
                rel = await self.expect(C.PUBREL)
                await self.send(C.Pubcomp(packet_id=rel.packet_id))
            return pkt

    async def ping(self) -> None:
        await self.send(C.Pingreq())
        await self.expect(C.PINGRESP)

    async def disconnect(
        self, reason_code: int = 0, properties: dict = None
    ) -> None:
        await self.send(
            C.Disconnect(
                reason_code=reason_code, properties=properties or {}
            )
        )
        await self.close()

    async def close(self) -> None:
        if self._pump is not None:
            self._pump.cancel()
        if self.writer is not None and not self.writer.is_closing():
            self.writer.close()
            try:
                await self.writer.wait_closed()
            except ConnectionError:
                pass
