"""UDP gateways: MQTT-SN and CoAP clients interoperating with MQTT
clients through the broker core (emqx_gateway_mqttsn /
emqx_gateway_coap parity)."""

import asyncio
import json
import struct

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.gateway import coap as CO
from emqx_tpu.gateway import mqttsn as SN
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


class UdpTestClient:
    """Raw datagram client with a frame queue."""

    def __init__(self, port, codec):
        self.port = port
        self.codec = codec
        self.frames: asyncio.Queue = asyncio.Queue()

    async def start(self):
        loop = asyncio.get_running_loop()
        client = self

        class _Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                frames, _ = client.codec.parse(
                    client.codec.initial_state(), data
                )
                for f in frames:
                    client.frames.put_nowait(f)

        self.transport, _ = await loop.create_datagram_endpoint(
            _Proto, remote_addr=("127.0.0.1", self.port)
        )
        return self

    def send(self, frame):
        self.transport.sendto(self.codec.serialize(frame))

    def send_raw(self, data: bytes):
        self.transport.sendto(data)

    async def expect(self, *types, timeout=3.0):
        while True:
            f = await asyncio.wait_for(self.frames.get(), timeout)
            kind = getattr(f, "msg_type", None)
            if kind is None:
                kind = f.type  # CoapMessage: match on message type
            if kind in types:
                return f

    def close(self):
        self.transport.close()


async def make_server(gateways):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.gateways = gateways
    srv = BrokerServer(cfg)
    await srv.start()
    return srv


# ------------------------------------------------------------- MQTT-SN


def sn_frame(t, **kw):
    return SN.SnFrame(t, **kw)


async def sn_connect(port, clientid, clean=True, will=None):
    c = await UdpTestClient(port, SN.SnCodec()).start()
    flags = SN.FLAG_CLEAN if clean else 0
    if will is not None:
        flags |= SN.FLAG_WILL
    c.send(sn_frame(SN.CONNECT, flags=flags, protocol_id=1, duration=60,
                    client_id=clientid))
    if will is not None:
        await c.expect(SN.WILLTOPICREQ)
        c.send(sn_frame(SN.WILLTOPIC, flags=will.get("flags", 0),
                        topic=will["topic"]))
        await c.expect(SN.WILLMSGREQ)
        c.send(sn_frame(SN.WILLMSG, data=will["msg"]))
    ack = await c.expect(SN.CONNACK)
    assert ack.rc == SN.RC_ACCEPTED
    return c


def test_mqttsn_pub_sub_roundtrip():
    async def t():
        srv = await make_server(
            [{"type": "mqttsn", "bind": "127.0.0.1", "port": 0}]
        )
        sport = srv.broker.gateways.get("mqttsn").port
        mport = srv.listeners[0].port

        sn = await sn_connect(sport, "sn1")
        # register a topic, publish QoS 1 to an MQTT subscriber
        m = TestClient(mport, "m1")
        await m.connect()
        await m.subscribe("sensors/#")

        sn.send(sn_frame(SN.REGISTER, topic_id=0, msg_id=1,
                         topic="sensors/temp"))
        rack = await sn.expect(SN.REGACK)
        assert rack.rc == SN.RC_ACCEPTED
        tid = rack.topic_id

        sn.send(sn_frame(SN.PUBLISH, flags=(1 << 5), topic_id=tid,
                         msg_id=2, data=b"21.5"))
        pack = await sn.expect(SN.PUBACK)
        assert pack.rc == SN.RC_ACCEPTED
        pub = await m.recv_publish()
        assert pub.topic == "sensors/temp" and pub.payload == b"21.5"

        # wildcard subscribe: MQTT publish flows back, REGISTER first
        sn.send(sn_frame(SN.SUBSCRIBE_SN, flags=0, msg_id=3,
                         topic="alerts/#"))
        sack = await sn.expect(SN.SUBACK)
        assert sack.rc == SN.RC_ACCEPTED

        await m.publish("alerts/fire", b"hot", qos=0)
        reg = await sn.expect(SN.REGISTER)
        assert reg.topic == "alerts/fire"
        sn.send(sn_frame(SN.REGACK, topic_id=reg.topic_id,
                         msg_id=reg.msg_id, rc=SN.RC_ACCEPTED))
        spub = await sn.expect(SN.PUBLISH)
        assert spub.topic_id == reg.topic_id and spub.data == b"hot"

        sn.send(sn_frame(SN.PINGREQ, client_id=""))
        await sn.expect(SN.PINGRESP)
        sn.close()
        await m.close()
        await srv.stop()

    run(t())


def test_mqttsn_short_topic_and_qos_neg1():
    async def t():
        srv = await make_server(
            [{"type": "mqttsn", "bind": "127.0.0.1", "port": 0,
              "predefined": {7: "pre/defined"}}]
        )
        gw = srv.broker.gateways.get("mqttsn")
        mport = srv.listeners[0].port
        m = TestClient(mport, "m2")
        await m.connect()
        await m.subscribe("ab", "pre/defined")

        sn = await sn_connect(gw.port, "sn2")
        # short topic name "ab" rides the topic_id field
        tid = struct.unpack(">H", b"ab")[0]
        sn.send(sn_frame(SN.PUBLISH,
                         flags=SN.TOPIC_SHORT, topic_id=tid,
                         msg_id=0, data=b"s"))
        pub = await m.recv_publish()
        assert pub.topic == "ab" and pub.payload == b"s"

        # QoS -1 publish without a connection, predefined topic
        anon = await UdpTestClient(gw.port, SN.SnCodec()).start()
        anon.send(sn_frame(SN.PUBLISH,
                           flags=(3 << 5) | SN.TOPIC_PREDEF,
                           topic_id=7, msg_id=0, data=b"fire"))
        pub = await m.recv_publish()
        assert pub.topic == "pre/defined" and pub.payload == b"fire"

        anon.close()
        sn.close()
        await m.close()
        await srv.stop()

    run(t())


def test_mqttsn_sleep_buffers_and_wakes():
    async def t():
        srv = await make_server(
            [{"type": "mqttsn", "bind": "127.0.0.1", "port": 0}]
        )
        gw = srv.broker.gateways.get("mqttsn")
        mport = srv.listeners[0].port

        sn = await sn_connect(gw.port, "sn3")
        sn.send(sn_frame(SN.SUBSCRIBE_SN, flags=0, msg_id=1,
                         topic="news/today"))
        sack = await sn.expect(SN.SUBACK)
        tid = sack.topic_id
        assert tid != 0  # concrete filter gets an id upfront

        # go to sleep; publishes are buffered, not delivered
        sn.send(sn_frame(SN.DISCONNECT, duration=60))
        await sn.expect(SN.DISCONNECT)

        m = TestClient(mport, "m3")
        await m.connect()
        await m.publish("news/today", b"zzz", qos=0)
        await asyncio.sleep(0.2)
        assert sn.frames.empty()

        # PINGREQ with client id wakes and flushes
        sn.send(sn_frame(SN.PINGREQ, client_id="sn3"))
        pub = await sn.expect(SN.PUBLISH)
        assert pub.topic_id == tid and pub.data == b"zzz"
        await sn.expect(SN.PINGRESP)

        sn.close()
        await m.close()
        await srv.stop()

    run(t())


def test_mqttsn_will_fires_on_drop():
    async def t():
        srv = await make_server(
            [{"type": "mqttsn", "bind": "127.0.0.1", "port": 0}]
        )
        gw = srv.broker.gateways.get("mqttsn")
        mport = srv.listeners[0].port
        m = TestClient(mport, "m4")
        await m.connect()
        await m.subscribe("wills/#")

        sn = await sn_connect(gw.port, "sn4",
                              will={"topic": "wills/sn4", "msg": b"gone"})
        # non-graceful loss (reaped as idle) publishes the will
        addr = next(iter(gw._channels))
        gw._drop_peer(addr, "idle_timeout")
        pub = await m.recv_publish()
        assert pub.topic == "wills/sn4" and pub.payload == b"gone"

        sn.close()
        await m.close()
        await srv.stop()

    run(t())


def test_mqttsn_advertise_broadcast():
    """The gateway ADVERTISEs itself periodically (spec §6.1): a
    listener socket on the advertise target receives gw_id+duration."""

    async def t():
        import socket as _socket

        loop = asyncio.get_running_loop()
        frames: asyncio.Queue = asyncio.Queue()

        class _Listener(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                got, _ = SN.SnCodec().parse(
                    SN.SnCodec().initial_state(), data
                )
                for f in got:
                    frames.put_nowait(f)

        transport, _ = await loop.create_datagram_endpoint(
            _Listener, local_addr=("127.0.0.1", 0)
        )
        adv_port = transport.get_extra_info("sockname")[1]

        # unicast loopback stands in for the broadcast segment
        srv = await make_server([{
            "type": "mqttsn", "bind": "127.0.0.1", "port": 0,
            "advertise_interval": 0.1,
            "broadcast_addr": "127.0.0.1",
            "advertise_port": adv_port,
        }])

        adv = await asyncio.wait_for(frames.get(), 3.0)
        assert adv.msg_type == SN.ADVERTISE
        assert adv.gw_id == SN.GATEWAY_ID
        # T_ADV is rounded UP: a 0.1s interval must not advertise 0
        # ("already stale") to conforming clients
        assert adv.duration == 1
        adv2 = await asyncio.wait_for(frames.get(), 3.0)  # periodic
        assert adv2.msg_type == SN.ADVERTISE
        transport.close()
        await srv.stop()

    run(t())


def test_mqttsn_malformed_datagram_is_ignored():
    async def t():
        srv = await make_server(
            [{"type": "mqttsn", "bind": "127.0.0.1", "port": 0}]
        )
        gw = srv.broker.gateways.get("mqttsn")
        raw = await UdpTestClient(gw.port, SN.SnCodec()).start()
        raw.send_raw(b"\x02\x0c")  # truncated PUBLISH body
        raw.send_raw(b"\xff")  # nonsense
        raw.send_raw(b"")  # empty
        await asyncio.sleep(0.1)
        # garbage must not register channels nor kill the gateway
        assert not gw._channels
        sn = await sn_connect(gw.port, "sn5")
        sn.close()
        await srv.stop()

    run(t())


# --------------------------------------------------------------- CoAP


def coap_msg(code, path, *, mtype=CO.CON, mid=1, token=b"\x01",
             queries=(), observe=None, payload=b"", block1=None):
    opts = [(CO.OPT_URI_PATH, seg.encode()) for seg in path.split("/")]
    opts += [(CO.OPT_URI_QUERY, q.encode()) for q in queries]
    if observe is not None:
        opts.append((CO.OPT_OBSERVE,
                     observe.to_bytes(1, "big") if observe else b""))
    if block1 is not None:
        num, more, szx = block1
        v = (num << 4) | (0x08 if more else 0) | szx
        opts.append((CO.OPT_BLOCK1,
                     v.to_bytes(max(1, (v.bit_length() + 7) // 8),
                                "big")))
    return CO.CoapMessage(mtype, code, mid, token, opts, payload)


def test_coap_publish_subscribe():
    async def t():
        srv = await make_server(
            [{"type": "coap", "bind": "127.0.0.1", "port": 0}]
        )
        gw = srv.broker.gateways.get("coap")
        mport = srv.listeners[0].port
        m = TestClient(mport, "cm1")
        await m.connect()
        await m.subscribe("co/up")

        c = await UdpTestClient(gw.port, CO.CoapCodec()).start()
        # PUT /ps/co/up publishes
        c.send(coap_msg(CO.PUT, "ps/co/up", mid=7,
                        queries=["clientid=coap1"], payload=b"hello"))
        ack = await c.expect(CO.ACK)
        assert ack.code == CO.CHANGED and ack.message_id == 7
        pub = await m.recv_publish()
        assert pub.topic == "co/up" and pub.payload == b"hello"

        # GET /ps/co/+ observe=0 subscribes (wildcard filter)
        c.send(coap_msg(CO.GET, "ps/co/+", mid=8, token=b"\x42",
                        observe=0))
        ack = await c.expect(CO.ACK)
        assert ack.code == CO.CONTENT

        await m.publish("co/down", b"notify", qos=0)
        note = await c.expect(CO.NON)
        assert note.code == CO.CONTENT
        assert note.token == b"\x42"
        assert note.payload == b"notify"
        assert note.observe == 1

        await m.publish("co/down", b"n2", qos=0)
        note = await c.expect(CO.NON)
        assert note.observe == 2  # sequence grows

        # observe=1 cancels
        c.send(coap_msg(CO.GET, "ps/co/+", mid=9, token=b"\x42",
                        observe=1))
        ack = await c.expect(CO.ACK)
        assert ack.code == CO.DELETED
        await m.publish("co/down", b"n3", qos=0)
        await asyncio.sleep(0.2)
        assert c.frames.empty()

        c.close()
        await m.close()
        await srv.stop()

    run(t())


def test_coap_block1_large_publish():
    """RFC 7959 Block1: a large payload arrives in 16-byte blocks,
    each non-final block gets 2.31 Continue, and the assembled whole
    is published once; out-of-order restarts get 4.08."""

    async def t():
        srv = await make_server(
            [{"type": "coap", "bind": "127.0.0.1", "port": 0}]
        )
        gw = srv.broker.gateways.get("coap")
        m = TestClient(srv.listeners[0].port, "cm-blk")
        await m.connect()
        await m.subscribe("co/big")

        c = await UdpTestClient(gw.port, CO.CoapCodec()).start()
        body = bytes(range(48))  # 3 blocks of 16 (szx=0)
        for num in range(3):
            more = num < 2
            c.send(coap_msg(
                CO.PUT, "ps/co/big", mid=20 + num, token=b"\x07",
                queries=["clientid=coapB"],
                payload=body[num * 16:(num + 1) * 16],
                block1=(num, more, 0),
            ))
            ack = await c.expect(CO.ACK)
            assert ack.code == (CO.CONTINUE if more else CO.CHANGED)
        pub = await m.recv_publish()
        assert pub.topic == "co/big" and pub.payload == body

        # a mid-transfer block with no transfer in flight -> 4.08
        c.send(coap_msg(
            CO.PUT, "ps/co/big", mid=30, token=b"\x08",
            queries=["clientid=coapB"], payload=b"x" * 16,
            block1=(2, True, 0),
        ))
        ack = await c.expect(CO.ACK)
        assert ack.code == CO.ENTITY_INCOMPLETE

        # retransmits (lost ACKs, RFC 7252 §4.2) must not abort the
        # transfer or double-publish
        body2 = bytes(range(32))
        c.send(coap_msg(CO.PUT, "ps/co/big", mid=40, token=b"\x09",
                        queries=["clientid=coapB"],
                        payload=body2[:16], block1=(0, True, 0)))
        assert (await c.expect(CO.ACK)).code == CO.CONTINUE
        # duplicate of block 0: re-ACKed, not treated as out-of-order
        c.send(coap_msg(CO.PUT, "ps/co/big", mid=40, token=b"\x09",
                        queries=["clientid=coapB"],
                        payload=body2[:16], block1=(0, True, 0)))
        assert (await c.expect(CO.ACK)).code == CO.CONTINUE
        c.send(coap_msg(CO.PUT, "ps/co/big", mid=41, token=b"\x09",
                        queries=["clientid=coapB"],
                        payload=body2[16:], block1=(1, False, 0)))
        assert (await c.expect(CO.ACK)).code == CO.CHANGED
        pub2 = await m.recv_publish()
        assert pub2.payload == body2
        # duplicate FINAL block: re-ACK CHANGED, no second publish
        c.send(coap_msg(CO.PUT, "ps/co/big", mid=41, token=b"\x09",
                        queries=["clientid=coapB"],
                        payload=body2[16:], block1=(1, False, 0)))
        assert (await c.expect(CO.ACK)).code == CO.CHANGED
        try:
            dup = await m.recv_publish(timeout=0.4)
            raise AssertionError(f"duplicate publish: {dup!r}")
        except asyncio.TimeoutError:
            pass

        c.close()
        await m.disconnect()
        await srv.stop()

    run(t())


def test_coap_not_found_and_garbage():
    async def t():
        srv = await make_server(
            [{"type": "coap", "bind": "127.0.0.1", "port": 0}]
        )
        gw = srv.broker.gateways.get("coap")
        c = await UdpTestClient(gw.port, CO.CoapCodec()).start()
        c.send_raw(b"\x40")  # short datagram
        c.send_raw(b"\xd0\x02")  # bad version bits
        c.send(coap_msg(CO.GET, "other/x", mid=3))
        rsp = await c.expect(CO.ACK)
        assert rsp.code == CO.NOT_FOUND
        assert len(gw._channels) == 1  # garbage registered nothing
        c.close()
        await srv.stop()

    run(t())


# -------------------------------------------------------------- LwM2M


def test_lwm2m_register_command_observe():
    """Register over POST /rd, drive a read command dn->device->up,
    observe with notifications, then deregister (emqx_gateway_lwm2m
    registration + dm-bridge parity)."""
    from emqx_tpu.gateway import lwm2m as LW

    async def t():
        srv = await make_server(
            [{"type": "lwm2m", "bind": "127.0.0.1", "port": 0}]
        )
        gw = srv.broker.gateways.get("lwm2m")
        m = TestClient(srv.listeners[0].port, "dm-app")
        await m.connect()
        await m.subscribe("lwm2m/ep-1/up/#", qos=0)

        dev = await UdpTestClient(gw.port, CO.CoapCodec()).start()
        # -------- register
        dev.send(coap_msg(
            CO.POST, "rd", mid=1, token=b"\x11",
            queries=["ep=ep-1", "lt=120", "lwm2m=1.0"],
            payload=b"</1/0>,</3/0>",
        ))
        ack = await dev.expect(CO.ACK)
        assert ack.code == CO.CREATED
        loc = [v for n, v in ack.options if n == LW.OPT_LOCATION_PATH]
        assert loc[0] == b"rd" and len(loc) == 2
        reg = await m.recv_publish()
        assert reg.topic == "lwm2m/ep-1/up/resp"
        body = json.loads(reg.payload)
        assert body["msgType"] == "register"
        assert body["data"]["objectList"] == ["/1/0", "/3/0"]

        # -------- read command: app -> dn topic -> device
        await m.publish("lwm2m/ep-1/dn/dm", json.dumps({
            "reqID": "42", "msgType": "read",
            "data": {"path": "/3/0/0"},
        }).encode())
        req = await dev.expect(CO.CON)
        assert req.code == CO.GET
        assert req.uri_path == ["3", "0", "0"]
        # device answers with the resource value
        dev.send_raw(CO.CoapCodec().serialize(CO.CoapMessage(
            CO.ACK, CO.CONTENT, req.message_id, req.token, [],
            b"emqx_tpu device",
        )))
        resp = await m.recv_publish()
        assert resp.topic == "lwm2m/ep-1/up/resp"
        body = json.loads(resp.payload)
        assert body["reqID"] == "42" and body["msgType"] == "read"
        assert body["data"]["code"] == "2.05"
        assert body["data"]["content"] == "emqx_tpu device"

        # -------- observe: first reply answers, later ones notify
        await m.publish("lwm2m/ep-1/dn/dm", json.dumps({
            "reqID": "43", "msgType": "observe",
            "data": {"path": "/3/0/1"},
        }).encode())
        req = await dev.expect(CO.CON)
        assert any(n == CO.OPT_OBSERVE for n, _ in req.options)
        dev.send_raw(CO.CoapCodec().serialize(CO.CoapMessage(
            CO.ACK, CO.CONTENT, req.message_id, req.token,
            [(CO.OPT_OBSERVE, b"\x01")], b"v1",
        )))
        first = await m.recv_publish()
        assert first.topic == "lwm2m/ep-1/up/resp"
        # an unsolicited notification on the same token
        dev.send_raw(CO.CoapCodec().serialize(CO.CoapMessage(
            CO.NON, CO.CONTENT, 999, req.token,
            [(CO.OPT_OBSERVE, b"\x02")], b"v2",
        )))
        note = await m.recv_publish()
        assert note.topic == "lwm2m/ep-1/up/notify"
        body = json.loads(note.payload)
        assert body["data"]["content"] == "v2"

        # -------- deregister
        dev.send(coap_msg(
            CO.DELETE, "rd/" + loc[1].decode(), mid=9, token=b"\x12",
        ))
        ack = await dev.expect(CO.ACK)
        assert ack.code == CO.DELETED

        dev.close()
        await m.disconnect()
        await srv.stop()

    run(t())


def test_lwm2m_tlv_codec_roundtrip():
    """OMA-TLV (LwM2M TS 6.4.3): decode the spec's Device-object
    example structure and round-trip our encoder."""
    from emqx_tpu.gateway.lwm2m import decode_tlv, encode_tlv

    # resource 0 = "Open Mobile Alliance" (string, 8-bit len field),
    # resource 1 = "Lightweight M2M Client", resource 9 = int 100
    manu = b"Open Mobile Alliance"
    model = b"Lightweight M2M Client"
    data = (
        bytes([0b11001000, 0, len(manu)]) + manu
        + bytes([0b11001000, 1, len(model)]) + model
        + bytes([0b11000001, 9]) + bytes([100])
    )
    entries = decode_tlv(data)
    assert entries[0]["id"] == 0
    assert entries[0]["value"]["str"] == "Open Mobile Alliance"
    assert entries[2]["value"]["int"] == 100

    # nested: object instance 0 wrapping a multiple resource
    nested = [{
        "kind": "obj_inst", "id": 0,
        "resources": [
            {"kind": "res", "id": 5, "value": {"int": -3}},
            {"kind": "multiple", "id": 6, "instances": [
                {"kind": "res_inst", "id": 0, "value": {"int": 1}},
                {"kind": "res_inst", "id": 1, "value": {"int": 5}},
            ]},
            {"kind": "res", "id": 7, "value": {"str": "hello"}},
        ],
    }]
    enc = encode_tlv(nested)
    dec = decode_tlv(enc)
    assert dec[0]["kind"] == "obj_inst" and dec[0]["id"] == 0
    rs = dec[0]["resources"]
    assert rs[0]["value"]["int"] == -3
    assert rs[1]["kind"] == "multiple"
    assert [i["value"]["int"] for i in rs[1]["instances"]] == [1, 5]
    assert rs[2]["value"]["str"] == "hello"

    # 16-bit ids and long values survive
    long_val = b"x" * 300
    enc2 = encode_tlv([{"kind": "res", "id": 500,
                        "value": {"hex": long_val.hex()}}])
    dec2 = decode_tlv(enc2)
    assert dec2[0]["id"] == 500
    assert bytes.fromhex(dec2[0]["value"]["hex"]) == long_val


def test_lwm2m_tlv_read_response_decodes():
    """A device answering a read with content-format 11542 crosses the
    dm bridge as structured TLV resources, and a {"tlv": ...} write
    value goes down as encoded TLV."""
    from emqx_tpu.gateway import lwm2m as LW

    async def t():
        srv = await make_server(
            [{"type": "lwm2m", "bind": "127.0.0.1", "port": 0}]
        )
        gw = srv.broker.gateways.get("lwm2m")
        m = TestClient(srv.listeners[0].port, "dm-app")
        await m.connect()
        await m.subscribe("lwm2m/ep-9/up/#", qos=0)

        dev = await UdpTestClient(gw.port, CO.CoapCodec()).start()
        dev.send(coap_msg(
            CO.POST, "rd", mid=1, token=b"\x21",
            queries=["ep=ep-9", "lt=120"],
            payload=b"</3/0>",
        ))
        await dev.expect(CO.ACK)
        await m.recv_publish()  # register uplink

        # dm commands a read; the device answers in OMA-TLV
        await m.publish("lwm2m/ep-9/dn/dm", json.dumps({
            "reqID": "r1", "msgType": "read",
            "data": {"path": "/3/0"},
        }).encode())
        req = await dev.expect(CO.CON)
        tlv = bytes([0b11000001, 9, 77])  # resource 9 = int 77
        dev.send_raw(CO.CoapCodec().serialize(CO.CoapMessage(
            CO.ACK, CO.CONTENT, req.message_id, req.token,
            [(CO.OPT_CONTENT_FORMAT,
              LW.TLV_CONTENT_FORMAT.to_bytes(2, "big"))],
            tlv,
        )))
        resp = await m.recv_publish()
        body = json.loads(resp.payload)
        content = body["data"]["content"]
        assert content["tlv"][0]["id"] == 9
        assert content["tlv"][0]["value"]["int"] == 77

        # structured write goes down as TLV with the CF option set
        await m.publish("lwm2m/ep-9/dn/dm", json.dumps({
            "reqID": "w1", "msgType": "write",
            "data": {"path": "/3/0/14", "value": {"tlv": [
                {"kind": "res", "id": 14, "value": {"str": "+02"}},
            ]}},
        }).encode())
        wreq = await dev.expect(CO.CON)
        cf = [v for n, v in wreq.options
              if n == CO.OPT_CONTENT_FORMAT]
        assert cf and int.from_bytes(cf[0], "big") == \
            LW.TLV_CONTENT_FORMAT
        assert LW.decode_tlv(wreq.payload)[0]["value"]["str"] == "+02"

        dev.close()
        await m.disconnect()
        await srv.stop()

    run(t())
