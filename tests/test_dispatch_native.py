"""Native window deliver (PR 5): GIL-released per-run packet assembly
+ block session bookkeeping.

The referee for the dispatch fast path: the native assembler
(`native/dispatchasm.cpp` via `ops.dispatchasm`) and the pure-Python
per-delivery fallback in `Session.deliver` must put bit-identical
bytes on every connection's wire under random qos / version / RAP /
subid / no_local / upgrade_qos mixes — decoded end-to-end through a
real `Channel` — and the whole suite must stay green with the `.so`
unavailable.  Plus the standalone bulk bookkeeping (block packet-id
allocator, `Inflight.insert_run`), the shared detached-window mqueue
bake, and the window-batched delivered sink."""

import random

import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.inflight import Inflight
from emqx_tpu.broker.session import Session, SubOpts
from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig
from emqx_tpu.message import Message
from emqx_tpu.ops import dispatchasm


def _broker():
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    return Broker(config=cfg)


class WireChannel(Channel):
    """Real Channel over a capturing transport (true wire bytes, true
    cork behavior), as in test_dispatch_fanout."""

    def __init__(self, broker, version=C.MQTT_V5):
        self.writes = []

        def send(pkts):
            self.writes.append(
                b"".join(C.serialize(p, self.version) for p in pkts)
            )

        super().__init__(broker, send=send, close=lambda r: None)
        self.version = version


def _force_fallback(monkeypatch):
    """Make ops.dispatchasm.load() return None (missing-.so shape)."""
    monkeypatch.setattr(dispatchasm, "_lib", None)
    monkeypatch.setattr(dispatchasm, "_lib_failed", True)


_native = dispatchasm.load()


# ------------------------------------------------ native/python parity


def _build_world(seed):
    """One randomized subscriber/publish world, returned as plain data
    so the native and fallback brokers are built identically."""
    rng = random.Random(seed)
    clients = []
    for i in range(10):
        subs = []
        for f in range(rng.randint(1, 3)):
            flt = rng.choice(["t/#", "t/+/x", f"t/{f}/x", "s/only"])
            subs.append({
                "flt": flt,
                "qos": rng.randint(0, 2),
                "rap": rng.random() < 0.4,
                "no_local": rng.random() < 0.3,
                "subid": rng.randint(1, 9)
                if rng.random() < 0.2 else None,
            })
        clients.append({
            "cid": f"c{i}",
            "version": rng.choice([C.MQTT_V4, C.MQTT_V5]),
            "upgrade": rng.random() < 0.3,
            "max_inflight": rng.choice([2, 4, 32]),
            "subs": subs,
        })
    windows = []
    for _ in range(4):
        win = []
        for _ in range(rng.randint(1, 12)):
            win.append({
                "topic": rng.choice(
                    ["t/1/x", "t/2/x", "t/0/x", "s/only", "t/deep/x"]
                ),
                "qos": rng.randint(0, 2),
                "retain": rng.random() < 0.3,
                "payload": bytes(
                    rng.randrange(256)
                    for _ in range(rng.randint(0, 200))
                ),
                "from": rng.choice(["c0", "c1", "pub"]),
            })
        windows.append(win)
    return clients, windows


def _run_world(clients, windows):
    b = _broker()
    chans = {}
    for c in clients:
        ch = WireChannel(b, version=c["version"])
        session, _ = b.cm.open_session(
            True, c["cid"], ch, max_inflight=c["max_inflight"]
        )
        session.upgrade_qos = c["upgrade"]
        for s in c["subs"]:
            opts = SubOpts(
                qos=s["qos"], retain_as_published=s["rap"],
                no_local=s["no_local"], subid=s["subid"],
            )
            session.subscribe(s["flt"], opts)
            b.subscribe(c["cid"], s["flt"], opts)
        chans[c["cid"]] = ch
    counts = []
    ts = 1.0e9  # fixed stamps: identical expiry math across runs
    for win in windows:
        msgs = [
            Message(
                topic=w["topic"], qos=w["qos"], retain=w["retain"],
                payload=w["payload"], from_client=w["from"],
                timestamp=ts,
            )
            for w in win
        ]
        counts.append(b.publish_many(msgs))
    wires = {cid: b"".join(ch.writes) for cid, ch in chans.items()}
    sent = {
        k: b.metrics.val(k)
        for k in ("messages.sent", "messages.qos0.sent",
                  "messages.qos1.sent", "messages.qos2.sent",
                  "packets.publish.sent", "messages.delivered")
    }
    inflights = {
        c["cid"]: sorted(
            (pid, e.qos) for pid, e in b.cm.lookup(c["cid"]).inflight.items()
        )
        for c in clients
    }
    return counts, wires, sent, inflights, {c["cid"]: c for c in clients}


@pytest.mark.skipif(_native is None, reason="native dispatchasm unavailable")
@pytest.mark.parametrize("seed", [1, 2, 7, 23])
def test_native_and_fallback_wire_is_bit_identical(seed, monkeypatch):
    """Property test: random qos/version/RAP/subid/no_local/
    upgrade_qos/inflight-pressure mixes through full broker windows —
    the native assembler and the per-delivery Python loop must produce
    the SAME per-connection byte stream, delivery counts, per-qos sent
    metrics, and inflight windows."""
    clients, windows = _build_world(seed)
    native = _run_world(clients, windows)
    _force_fallback(monkeypatch)
    fallback = _run_world(clients, windows)
    assert native[0] == fallback[0]  # delivery counts
    for cid in native[1]:
        assert native[1][cid] == fallback[1][cid], cid
    assert native[2] == fallback[2]  # per-qos sent metrics
    assert native[3] == fallback[3]  # (pid, qos) inflight windows
    # and the native byte stream decodes end-to-end through the codec
    for cid, wire in native[1].items():
        parser = C.StreamParser(version=native[4][cid]["version"])
        for pkt in parser.feed(wire):
            assert pkt.type == C.PUBLISH


@pytest.mark.skipif(_native is None, reason="native dispatchasm unavailable")
def test_native_path_actually_engages():
    """Guard against silently testing fallback-vs-fallback: a plain
    window must take the native path (assemble stage recorded, run
    arriving as ONE Raw blob)."""
    b = _broker()
    ch = WireChannel(b)
    session, _ = b.cm.open_session(True, "c1", ch)
    session.subscribe("t/#", SubOpts(qos=1))
    b.subscribe("c1", "t/#", SubOpts(qos=1))
    raws = []
    orig = ch._send

    def send(pkts):
        raws.extend(p for p in pkts if isinstance(p, C.Raw))
        orig(pkts)

    ch._send = send
    counts = b.publish_many(
        [Message(topic=f"t/{i}", qos=1) for i in range(8)]
    )
    assert counts == [1] * 8
    assert len(raws) == 1 and raws[0].n_packets == 8
    (win,) = b.profiler.windows(1)
    assert "assemble" in win["stages_us"]
    assert b.profiler.summary()["assemble"]["count"] >= 1
    # the blob decodes to the eight QoS1 publishes with fresh pids
    parser = C.StreamParser(version=C.MQTT_V5)
    pkts = list(parser.feed(b"".join(ch.writes)))
    assert [p.packet_id for p in pkts] == list(range(1, 9))


def test_missing_so_full_fallback(monkeypatch):
    """Force the ctypes load to fail: dispatch stays green on the
    per-delivery loop (the acceptance criterion's deleted-.so run)."""
    _force_fallback(monkeypatch)
    assert dispatchasm.load() is None
    b = _broker()
    ch = WireChannel(b)
    session, _ = b.cm.open_session(True, "c1", ch)
    session.subscribe("t/#", SubOpts(qos=1))
    b.subscribe("c1", "t/#", SubOpts(qos=1))
    assert b.publish_many(
        [Message(topic=f"t/{i}", qos=1) for i in range(4)]
    ) == [1] * 4
    assert len(ch.writes) == 1  # still ONE corked write per window
    parser = C.StreamParser(version=C.MQTT_V5)
    assert [p.packet_id for p in parser.feed(ch.writes[0])] == [1, 2, 3, 4]


def test_no_native_env_var_disables(monkeypatch):
    monkeypatch.setattr(dispatchasm, "_lib", None)
    monkeypatch.setattr(dispatchasm, "_lib_failed", False)
    monkeypatch.setenv("EMQX_TPU_NO_NATIVE_DISPATCH", "1")
    assert dispatchasm.load() is None


# ------------------------------------------- block session bookkeeping


def test_alloc_packet_ids_matches_sequential_semantics():
    """The block allocator must equal n sequential `_alloc_packet_id`
    calls (with interleaved inserts) for wraparound and in-use skips."""
    rng = random.Random(3)
    for _ in range(50):
        s_blk = Session("blk")
        s_seq = Session("seq")
        start = rng.choice([0, 1, 17, 65530, 65533, 65534])
        s_blk._next_pid = s_seq._next_pid = start
        in_use = rng.sample(range(1, 66), rng.randint(0, 8))
        for pid in in_use:
            s_blk.inflight.insert(pid, "x")
            s_seq.inflight.insert(pid, "x")
        n = rng.randint(1, 6)
        got = s_blk.alloc_packet_ids(n)
        want = []
        for _ in range(n):
            pid = s_seq._alloc_packet_id()
            s_seq.inflight.insert(pid, "y")  # sequential interleave
            want.append(pid)
        assert got == want, (start, in_use, n)
        assert s_blk._next_pid == s_seq._next_pid


def test_alloc_packet_ids_wraparound():
    s = Session("w")
    s._next_pid = 65533
    assert s.alloc_packet_ids(4) == [65534, 65535, 1, 2]


def test_alloc_packet_ids_skips_block_internal_ids():
    """Ids granted earlier in the same block are in use even though
    their inflight inserts land only after the whole allocation."""
    s = Session("b")
    s._next_pid = 65534
    s.inflight.insert(1, "x")
    assert s.alloc_packet_ids(3) == [65535, 2, 3]


def test_alloc_packet_ids_exhaustion():
    s = Session("full", max_inflight=0)
    for pid in range(1, 65536):
        s.inflight.insert(pid, "x")
    with pytest.raises(RuntimeError):
        s.alloc_packet_ids(1)


def test_inflight_insert_run():
    inf = Inflight(8)
    inf.insert_run([3, 1, 2], ["a", "b", "c"])
    assert [k for k, _ in inf.items()] == [3, 1, 2]  # order preserved
    assert inf.get(1) == "b"
    with pytest.raises(KeyError):
        inf.insert_run([5, 3], ["d", "e"])  # duplicate detected
    assert inf.get(5) == "d"  # entries before the dup landed (as with
    # sequential insert calls)


# ------------------------------------- shared detached-window mqueue bake


def _detached(b, cid, **kw):
    session, _ = b.cm.open_session(False, cid, object(), **kw)
    b.cm.disconnect(cid, b.cm.channel(cid))
    return session


def test_detached_window_shares_one_bake():
    """One queued copy per (msg, qos, subopts-signature) shared across
    every detached session in the window."""
    b = _broker()
    sessions = []
    for cid in ("d1", "d2", "d3"):
        s = _detached(b, cid, expiry_interval=300.0)
        s.subscribe("t", SubOpts(qos=1))
        b.subscribe(cid, "t", SubOpts(qos=1))
        sessions.append(s)
    assert b.publish(Message(topic="t", qos=1, payload=b"p")) == 3
    baked = [s.mqueue.pop() for s in sessions]
    assert baked[0] is baked[1] is baked[2]  # ONE bake for the window
    assert baked[0].qos == 1 and baked[0].payload == b"p"


def test_detached_bake_signature_separates_variants():
    """Different effective qos / RAP / subid must NOT share a bake."""
    b = _broker()
    s1 = _detached(b, "d1", expiry_interval=300.0)
    s1.subscribe("t", SubOpts(qos=1, retain_as_published=True))
    b.subscribe("d1", "t", SubOpts(qos=1, retain_as_published=True))
    s2 = _detached(b, "d2", expiry_interval=300.0)
    s2.subscribe("t", SubOpts(qos=2, subid=7))
    b.subscribe("d2", "t", SubOpts(qos=2, subid=7))
    assert b.publish(
        Message(topic="t", qos=2, retain=True, payload=b"p")
    ) == 2
    m1, m2 = s1.mqueue.pop(), s2.mqueue.pop()
    assert m1 is not m2
    assert (m1.qos, m1.retain) == (1, True)
    assert m2.qos == 2 and not m2.retain
    assert m2.properties["subscription_identifier"] == [7]


def test_detached_shared_bake_queue_full_accounting():
    """queue_full drops stay per-session even with a shared bake."""
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.mqtt.max_mqueue_len = 2
    b = Broker(config=cfg)
    s = _detached(b, "d1", expiry_interval=300.0)
    s.subscribe("t", SubOpts(qos=1))
    b.subscribe("d1", "t", SubOpts(qos=1))
    counts = b.publish_many(
        [Message(topic="t", qos=1, payload=bytes([i])) for i in range(4)]
    )
    assert counts == [1, 1, 1, 1]  # queued counts as delivered-to-session
    assert len(s.mqueue) == 2
    assert b.metrics.val("delivery.dropped.queue_full") == 2
    # survivors are the newest two (drop-oldest policy)
    assert [m.payload for m in s.mqueue] == [b"\x02", b"\x03"]


def test_detached_shared_bake_replication_payload_unchanged():
    """`replicate_queued` must carry the same wire dicts as the
    per-client bake did (one entry per session, identical content)."""
    b = _broker()
    calls = []

    class Ext:
        def match_remote(self, topics):
            return [set() for _ in topics]

        def replicate_queued(self, cid, wires):
            calls.append((cid, wires))

        def forward(self, msg, nodes):
            pass

    b.external = Ext()
    for cid in ("d1", "d2"):
        s = _detached(b, cid, expiry_interval=300.0)
        s.subscribe("t", SubOpts(qos=1))
        b.subscribe(cid, "t", SubOpts(qos=1))
    b.publish(Message(topic="t", qos=1, payload=b"z"))
    assert sorted(c for c, _ in calls) == ["d1", "d2"]
    (w1,), (w2,) = (w for _, w in calls)
    assert w1 == w2
    assert w1["topic"] == "t" and w1["qos"] == 1


# ----------------------------------------- window-batched delivered sink


def test_delivered_batch_sink_fires_once_per_window():
    b = _broker()
    for cid in ("c1", "c2"):
        ch = WireChannel(b)
        s, _ = b.cm.open_session(True, cid, ch)
        s.subscribe("t/#", SubOpts(qos=0))
        b.subscribe(cid, "t/#", SubOpts(qos=0))
    batches = []
    b.delivered_batch_sinks.append(lambda runs: batches.append(runs))
    hook_calls = []
    b.hooks.add(
        "message.delivered",
        lambda cid, ds: hook_calls.append((cid, len(ds))),
    )
    b.publish_many([Message(topic=f"t/{i}") for i in range(5)])
    # ONE sink call for the whole window, carrying both clients' runs
    assert len(batches) == 1
    assert sorted((c, len(d)) for c, d in batches[0]) == [
        ("c1", 5), ("c2", 5)
    ]
    # the in-process hook keeps its per-(window, client) signature
    assert sorted(hook_calls) == [("c1", 5), ("c2", 5)]


def test_exhook_client_registers_window_sink():
    pytest.importorskip("grpc")
    from emqx_tpu.exhook.client import ExhookClient

    b = _broker()
    client = ExhookClient(b, "t", "127.0.0.1:1")  # nothing listening
    client._channel = object()  # _register needs no live channel
    client._register(["message.delivered", "session.created"])
    assert client._delivered_window_sink in b.delivered_batch_sinks
    # no per-client hook registered for message.delivered
    assert not any(
        cb.fn is client._delivered_window_sink
        for cb in b.hooks.callbacks("message.delivered")
    )
    assert "message.delivered" in [n for n, _ in client._registered]
    client._channel = None
    client.stop()
    assert client._delivered_window_sink not in b.delivered_batch_sinks
    assert b.hooks.callbacks("session.created") == []
