"""Round-5 auth-surface backends: MongoDB (OP_MSG wire), LDAP (BER
simple bind), the TLS-PSK identity store, and the env-override + boot
config check plumbing."""

import asyncio
import struct

import pytest

from emqx_tpu.access import (ALLOW, AccessControl, ClientInfo, DENY,
                             IGNORE, PUBLISH)
from emqx_tpu.auth_db import hash_password
from emqx_tpu.auth_ldap import (LdapAuthenticator, bind_request,
                                parse_bind_response)
from emqx_tpu.auth_mongo import (MongoAuthenticator, MongoAuthorizer,
                                 MongoConnector, bson_decode,
                                 bson_encode)
from emqx_tpu.config import (BrokerConfig, apply_env_overrides,
                             check_config)
from emqx_tpu.psk import PskStore


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------------- mongodb

def test_bson_roundtrip():
    doc = {
        "find": "users", "limit": 1, "big": 1 << 40,
        "ok": 1.0, "flag": True, "none": None,
        "filter": {"username": "alice"},
        "arr": ["a", 2, {"x": False}],
    }
    enc = bson_encode(doc)
    dec, off = bson_decode(enc)
    assert off == len(enc)
    assert dec == doc


class FakeMongo:
    """OP_MSG server with a user and an acl collection."""

    def __init__(self):
        self.users = {}
        self.acl = {}
        self.port = 0
        self.server = None

    async def start(self):
        self.server = await asyncio.start_server(
            self._conn, "127.0.0.1", 0
        )
        self.port = self.server.sockets[0].getsockname()[1]

    async def stop(self):
        self.server.close()
        await self.server.wait_closed()

    async def _conn(self, r, w):
        try:
            while True:
                hdr = await r.readexactly(16)
                length, rid, _rto, opcode = struct.unpack("<iiii", hdr)
                payload = await r.readexactly(length - 16)
                doc, _ = bson_decode(payload, 5)
                coll = doc.get("find", "")
                uname = doc.get("filter", {}).get("username", "")
                if coll == "mqtt_user":
                    batch = (
                        [self.users[uname]] if uname in self.users
                        else []
                    )
                else:
                    batch = list(self.acl.get(uname, []))
                reply = bson_encode({
                    "cursor": {"firstBatch": batch, "id": 0,
                               "ns": f"mqtt.{coll}"},
                    "ok": 1.0,
                })
                body = struct.pack("<I", 0) + b"\x00" + reply
                w.write(struct.pack(
                    "<iiii", 16 + len(body), 99, rid, 2013
                ) + body)
                await w.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            w.close()


def test_mongo_authn_and_acl_prefetch():
    async def t():
        fm = FakeMongo()
        fm.users["alice"] = {
            "username": "alice",
            "password_hash": hash_password("s3cret", "sha256", "na"),
            "salt": "na",
            "is_superuser": False,
        }
        fm.acl["bob"] = [
            {"username": "bob", "permission": "allow",
             "action": "publish", "topics": ["ok/#"]},
            {"username": "bob", "permission": "deny",
             "action": "all", "topic": "#"},
        ]
        await fm.start()
        conn = MongoConnector("127.0.0.1", fm.port)
        authn = MongoAuthenticator(conn)

        d, _ = await authn.authenticate_async(
            ClientInfo(clientid="a", username="alice",
                       password=b"s3cret"))
        assert d == ALLOW
        d, _ = await authn.authenticate_async(
            ClientInfo(clientid="a", username="alice",
                       password=b"wrong"))
        assert d == DENY
        d, _ = await authn.authenticate_async(
            ClientInfo(clientid="a", username="nobody",
                       password=b"x"))
        assert d == IGNORE

        # authorizer through the access layer's prefetch cache
        ac = AccessControl(authz_default="deny")
        ac.db_authz_sources.append(MongoAuthorizer(conn))
        bob = ClientInfo(clientid="b", username="bob")
        await ac.prefetch_acl(bob)
        assert ac.authorize(bob, PUBLISH, "ok/topic")
        assert not ac.authorize(bob, PUBLISH, "other/topic")

        await conn.close()
        await fm.stop()

    run(t())


def test_mongo_commands_pipeline_on_one_connection():
    """PR 3 burn-down: commands no longer serialize on a lock held
    across the round-trip.  The server here collects TWO complete
    OP_MSG requests before answering either (impossible under the old
    lock) and answers in REVERSE order — replies must demultiplex by
    ``responseTo``, each caller seeing its own echoed document."""

    async def t():
        conns = []

        async def handler(r, w):
            conns.append(w)
            seen = []
            for _ in range(2):
                hdr = await r.readexactly(16)
                length, rid, _rto, _op = struct.unpack("<iiii", hdr)
                payload = await r.readexactly(length - 16)
                doc, _ = bson_decode(payload, 5)
                seen.append((rid, doc))
            for rid, doc in reversed(seen):
                reply = bson_encode({
                    "echo": doc.get("find", ""), "ok": 1.0,
                })
                body = struct.pack("<I", 0) + b"\x00" + reply
                w.write(struct.pack(
                    "<iiii", 16 + len(body), 99, rid, 2013
                ) + body)
            await w.drain()

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        conn = MongoConnector("127.0.0.1", port)
        r1, r2 = await asyncio.wait_for(
            asyncio.gather(
                conn.command({"find": "alpha"}),
                conn.command({"find": "beta"}),
            ),
            5.0,
        )
        assert r1["echo"] == "alpha" and r2["echo"] == "beta"
        assert len(conns) == 1  # both rode one pipelined connection
        await conn.close()
        server.close()
        await server.wait_closed()

    run(t())


# ---------------------------------------------------------------- ldap

def test_ber_bind_codec():
    req = bind_request(7, "uid=alice,dc=x", b"pw")
    assert req[0] == 0x30
    # craft a success BindResponse and parse it
    resp = bytes([0x30, 0x0C, 0x02, 0x01, 7, 0x61, 0x07,
                  0x0A, 0x01, 0x00, 0x04, 0x00, 0x04, 0x00])
    mid, code = parse_bind_response(resp)
    assert (mid, code) == (7, 0)


class FakeLdap:
    def __init__(self, accept):
        self.accept = accept  # dn -> password accepted
        self.port = 0
        self.server = None
        self.seen = []

    async def start(self):
        self.server = await asyncio.start_server(
            self._conn, "127.0.0.1", 0
        )
        self.port = self.server.sockets[0].getsockname()[1]

    async def stop(self):
        self.server.close()
        await self.server.wait_closed()

    async def _conn(self, r, w):
        try:
            data = await r.read(4096)
            # crude parse: find the DN (first 0x04 string) + password
            # ([0] context tag 0x80) inside the BindRequest
            i = data.index(0x60)
            j = data.index(0x04, i)
            dln = data[j + 1]
            dn = data[j + 2:j + 2 + dln].decode()
            k = data.index(0x80, j + 2 + dln)
            pln = data[k + 1]
            pw = data[k + 2:k + 2 + pln]
            self.seen.append((dn, pw))
            code = 0 if self.accept.get(dn) == pw else 49
            mid = data[4]  # messageID (single byte ids in tests)
            w.write(bytes([
                0x30, 0x0C, 0x02, 0x01, mid, 0x61, 0x07,
                0x0A, 0x01, code, 0x04, 0x00, 0x04, 0x00,
            ]))
            await w.drain()
        except Exception:
            pass
        finally:
            w.close()


def test_ldap_bind_auth():
    async def t():
        fl = FakeLdap({
            "uid=alice,ou=users,dc=example,dc=com": b"pw1",
        })
        await fl.start()
        ld = LdapAuthenticator("127.0.0.1", fl.port)
        d, _ = await ld.authenticate_async(
            ClientInfo(clientid="c", username="alice", password=b"pw1"))
        assert d == ALLOW
        d, _ = await ld.authenticate_async(
            ClientInfo(clientid="c", username="alice", password=b"no"))
        assert d == DENY
        # full chain: access control consumes the async provider
        ac = AccessControl(allow_anonymous=False)
        ac.authenticators.append(ld)
        assert ac.has_async_authn
        ok, _ = await ac.authenticate_async(
            ClientInfo(clientid="c", username="alice", password=b"pw1"))
        assert ok
        await fl.stop()

    run(t())


def test_ldap_dn_metacharacters_are_escaped():
    """RFC 4514 escaping closes the authorization-scope bypass: a
    username like 'x,ou=admins,...' must reach the directory as DATA
    inside uid=..., never as extra RDNs rewriting the bind DN."""
    from emqx_tpu.auth_ldap import escape_dn_value

    assert escape_dn_value("alice") == "alice"
    assert escape_dn_value("x,ou=admins") == "x\\,ou\\=admins"
    assert escape_dn_value("#lead ") == "\\#lead\\ "
    assert escape_dn_value(" a+b<c>d;e\"f\\g") == \
        "\\ a\\+b\\<c\\>d\\;e\\\"f\\\\g"
    assert escape_dn_value("n\x00ul") == "n\\00ul"

    async def t():
        evil = "bob,ou=admins,dc=example,dc=com"
        fl = FakeLdap({
            # the directory would accept the ADMIN entry's password:
            # reachable only if the DN arrives unescaped
            "uid=bob,ou=admins,dc=example,dc=com": b"adminpw",
        })
        await fl.start()
        ld = LdapAuthenticator("127.0.0.1", fl.port)
        d, _ = await ld.authenticate_async(ClientInfo(
            clientid="c", username=evil, password=b"adminpw",
        ))
        assert d == DENY  # the escaped DN does not match the admin DN
        seen_dn = fl.seen[0][0]
        assert seen_dn.startswith("uid=bob\\,ou\\=admins")
        assert seen_dn.endswith(",ou=users,dc=example,dc=com")
        await fl.stop()

    run(t())


# ----------------------------------------------------------------- psk

def test_psk_store_file_and_lookup(tmp_path):
    f = tmp_path / "psk.txt"
    f.write_text(
        "# fleet keys\n"
        "dev-1:6162636431323334\n"
        "dev-2:feedface\n"
        "badline\n"
        "dev-3:nothex\n"
    )
    store = PskStore(str(f))
    assert len(store) == 2
    assert store.lookup("dev-1") == b"abcd1234"
    assert store.lookup("dev-2") == bytes.fromhex("feedface")
    assert store.lookup("ghost") is None
    assert store.server_callback(None, b"dev-1") == b"abcd1234"
    assert store.server_callback(None, b"ghost") == b""
    store.insert("dev-9", b"k")
    f.write_text("dev-1:00ff\n")
    assert store.refresh() == 1  # reload replaces the table
    assert store.lookup("dev-9") is None

    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    # on 3.12 this reports the missing hookup instead of crashing
    attached = store.attach(ctx)
    assert attached == hasattr(ctx, "set_psk_server_callback")


# -------------------------------------------- env overrides + check

def test_env_overrides_and_boot_check():
    cfg = BrokerConfig()
    applied = apply_env_overrides(cfg, {
        "EMQX_TPU_MQTT__MAX_INFLIGHT": "64",
        "EMQX_TPU_MQTT__RETAIN_AVAILABLE": "false",
        "EMQX_TPU_DURABLE__LAYOUT": "hash",
        "EMQX_TPU_CLUSTER__ENABLE": "true",
        "UNRELATED": "x",
    })
    assert cfg.mqtt.max_inflight == 64
    assert cfg.mqtt.retain_available is False
    assert cfg.durable.layout == "hash"
    assert cfg.cluster["enable"] is True
    assert len(applied) == 4

    with pytest.raises(ValueError):
        apply_env_overrides(BrokerConfig(),
                            {"EMQX_TPU_MQTT__NO_SUCH_KEY": "1"})

    # the native-lib kill switches share the prefix but are runtime
    # flags, not config paths: a worker booted with one must not die
    applied = apply_env_overrides(BrokerConfig(), {
        "EMQX_TPU_NO_NATIVE_DISPATCH": "1",
        "EMQX_TPU_NO_NATIVE_SORT": "1",
    })
    assert applied == []

    assert check_config(BrokerConfig()) == []
    bad = BrokerConfig()
    bad.durable.layout = "bogus"
    bad.listeners[0].type = "quic"  # no certfile
    problems = check_config(bad)
    assert len(problems) == 2


def test_mongo_redials_after_connection_loss():
    """Pump teardown closes the transport, so a later command re-dials
    instead of stalling CONNECT-time auth to its timeout."""

    async def t():
        fm = FakeMongo()
        fm.users["alice"] = {"username": "alice",
                             "password_hash": "x", "salt": ""}
        await fm.start()
        conn = MongoConnector("127.0.0.1", fm.port)
        assert (await conn.find_one(
            "mqtt_user", {"username": "alice"}
        ))["username"] == "alice"
        first_w = conn._w
        await fm.stop()
        first_w.close()
        await asyncio.sleep(0.05)
        assert conn._w is None  # pump teardown reset the transport
        await fm.start()
        conn.port = fm.port
        row = await asyncio.wait_for(
            conn.find_one("mqtt_user", {"username": "alice"}), 5.0
        )
        assert row["username"] == "alice"
        await conn.close()
        await fm.stop()

    run(t())
