"""Live publish micro-batching: the broker's production path routes
through PublishBatcher (one device step per window) and the rule
engine's WHERE runs vectorized over each window — VERDICT r2 weak #1/#2
(the reference analogue: emqx_broker:publish per message at
emqx_broker.erl:244-253, amortized here per SURVEY §7)."""

import asyncio

import pytest

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.message import Message
from emqx_tpu.rules.engine import FunctionAction
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def make_server(**engine_kw):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    for k, v in engine_kw.items():
        setattr(cfg.engine, k, v)
    return BrokerServer(cfg)


def test_batcher_installed_by_default():
    async def t():
        srv = make_server()
        await srv.start()
        assert srv.broker.batcher is not None
        await srv.stop()
        assert srv.broker.batcher is None

    run(t())


def test_concurrent_publishers_one_window():
    """Many concurrent QoS1 publishes coalesce into batcher windows;
    every message is delivered and acked exactly once."""

    async def t():
        srv = make_server(batch_window_ms=5.0)
        await srv.start()
        port = srv.listeners[0].port
        sub = TestClient(port, "sub")
        await sub.connect()
        await sub.subscribe("load/#", qos=1)

        pubs = [TestClient(port, f"p{i}") for i in range(8)]
        for p in pubs:
            await p.connect()

        match_calls = [0]
        orig_match = srv.broker.publish_match_submit

        def counting_match(live, congested=False, rec=None):
            match_calls[0] += 1
            return orig_match(live, congested, rec)

        srv.broker.publish_match_submit = counting_match

        async def blast(p, i):
            for k in range(10):
                await p.publish(f"load/{i}/{k}", f"{i}:{k}".encode(), qos=1)

        await asyncio.gather(*(blast(p, i) for i, p in enumerate(pubs)))
        got = set()
        for _ in range(80):
            pkt = await sub.recv_publish()
            got.add(pkt.payload.decode())
        assert got == {f"{i}:{k}" for i in range(8) for k in range(10)}
        # the batcher actually batched: strictly fewer match steps than
        # messages (8 concurrent publishers with 5 ms windows coalesce)
        assert srv.broker.metrics.val("messages.publish") >= 80
        assert 0 < match_calls[0] < 80
        for p in pubs:
            await p.disconnect()
        await sub.disconnect()
        await srv.stop()

    run(t())


def test_rules_batched_where_over_live_path():
    """A compilable WHERE evaluates via PredicateProgram over the
    window; results equal the interpreter's per-message verdicts."""

    async def t():
        srv = make_server(batch_window_ms=5.0)
        await srv.start()
        port = srv.listeners[0].port
        hits = []
        rule = srv.broker.rules.add_rule(
            "r1",
            "SELECT payload.v AS v FROM \"t/#\" WHERE payload.v > 5",
            actions=[FunctionAction(fn=lambda sel, msg: hits.append(sel["v"]))],
        )
        assert rule.program is not None  # compiled, not interpreted

        pub = TestClient(port, "pub")
        await pub.connect()
        for v in range(10):
            await pub.publish("t/x", b'{"v": %d}' % v, qos=1)
        await pub.disconnect()
        await asyncio.sleep(0.05)
        assert sorted(hits) == [6, 7, 8, 9]
        assert rule.matched == 10 and rule.passed == 4 and rule.failed == 6
        await srv.stop()

    run(t())


def test_apply_batch_matches_interpreter():
    """apply_batch (vectorized WHERE) and apply (interpreter) agree on
    a mixed batch, including null/missing and string predicates."""
    from emqx_tpu.broker.broker import Broker

    payloads = [
        b'{"temp": 31, "site": "sf"}',
        b'{"temp": 12, "site": "la"}',
        b'{"temp": 40}',
        b"not json",
        b'{"temp": "hot", "site": "sf"}',
    ]
    sql = "SELECT * FROM \"m/#\" WHERE payload.temp > 20 and payload.site = 'sf'"

    def run_engine(batched):
        broker = Broker(BrokerConfig())
        got = []
        broker.rules.add_rule(
            "r",
            sql,
            actions=[FunctionAction(fn=lambda sel, msg: got.append(msg.payload))],
        )
        msgs = [Message(topic="m/a", payload=p, qos=1) for p in payloads]
        if batched:
            broker.rules.apply_batch([(m, ["r"]) for m in msgs])
        else:
            for m in msgs:
                broker.rules.apply(m, ["r"])
        return got

    assert run_engine(True) == run_engine(False) == [payloads[0]]


def test_batcher_failure_does_not_ack():
    """If routing raises, the QoS1 publish must NOT be acked (client
    retransmits); the connection is closed with an error instead."""

    async def t():
        srv = make_server(batch_window_ms=1.0)
        await srv.start()
        port = srv.listeners[0].port

        def boom(*a, **k):
            raise RuntimeError("injected")

        srv.broker.publish_match_submit = boom
        pub = TestClient(port, "pub")
        await pub.connect()
        with pytest.raises(Exception):
            await pub.publish("t/x", b"y", qos=1)
        await pub.close()
        await srv.stop()

    run(t())


def test_rate_limited_flooder_does_not_starve_others():
    """A listener with messages_rate throttles a flooding publisher via
    read-pausing while a well-behaved client on the same listener keeps
    its latency (emqx_limiter semantics: throttle, not disconnect)."""
    import time as _time

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0, messages_rate=50)]
        srv = BrokerServer(cfg)
        await srv.start()
        port = srv.listeners[0].port

        sub = TestClient(port, "watcher")
        await sub.connect()
        await sub.subscribe("flood/#")
        await sub.subscribe("calm/#")

        flooder = TestClient(port, "flood")
        await flooder.connect()

        async def blast():
            # fire-and-forget qos0 flood, ~10x over the budget
            for i in range(300):
                try:
                    await flooder.send(
                        __import__("emqx_tpu.codec.mqtt", fromlist=["x"])
                        .Publish(topic="flood/x", payload=b"f", qos=0)
                    )
                except ConnectionError:
                    return

        task = asyncio.get_running_loop().create_task(blast())
        await asyncio.sleep(0.3)

        calm = TestClient(port, "calm")
        await calm.connect()
        t0 = _time.perf_counter()
        await calm.publish("calm/ping", b"p", qos=1)
        calm_rtt = _time.perf_counter() - t0
        assert calm_rtt < 0.5  # not starved by the flood

        # the flooder is throttled: nowhere near 300 deliveries yet
        n = srv.broker.metrics.val("messages.received")
        assert n < 150, n
        assert srv.broker.metrics.val("connection.rate_limited") > 0
        task.cancel()
        await calm.disconnect()
        await sub.close()
        await flooder.close()
        await srv.stop()

    run(t())
