"""JT/T 808 gateway (gateway/jt808.py): framing/escaping/checksum,
register -> auth-code -> authenticate flow, location decoding to the
up topic, downlink text messages — written from the public JT/T
808-2013 spec (the emqx_gateway_jt808 role)."""

import asyncio
import json
import struct

import pytest

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.gateway.jt808 import (
    FLAG,
    Jt808Codec,
    Jt808Message,
    MSG_AUTH,
    MSG_GENERAL_ACK,
    MSG_HEARTBEAT,
    MSG_LOCATION,
    MSG_REGISTER,
    MSG_REGISTER_ACK,
    MSG_TEXT,
    MSG_UNREGISTER,
    decode_location,
)
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------------- codec

def test_jt808_codec_roundtrip_and_escaping():
    codec = Jt808Codec()
    # a body containing both escape-sensitive bytes
    m = Jt808Message(MSG_LOCATION, "013812345678", 7,
                     b"\x7e\x7d\x00data")
    wire = codec.serialize(m)
    assert wire[0] == FLAG and wire[-1] == FLAG
    assert b"\x7e" not in wire[1:-1]  # escaped payload
    frames, rest = codec.parse(codec.initial_state(), wire)
    assert rest == b"" and len(frames) == 1
    out = frames[0]
    assert (out.msg_id, out.phone, out.serial) == (
        MSG_LOCATION, "013812345678", 7
    )
    assert out.body == b"\x7e\x7d\x00data"

    # split delivery reassembles; checksum corruption raises
    half = len(wire) // 2
    frames, state = codec.parse(codec.initial_state(), wire[:half])
    assert frames == []
    frames, _ = codec.parse(state, wire[half:])
    assert len(frames) == 1
    bad = bytearray(wire)
    bad[-2] ^= 0xFF
    with pytest.raises(ValueError):
        codec.parse(codec.initial_state(), bytes(bad))


def test_jt808_location_decode():
    body = struct.pack(
        ">IIII", 0x00000001, 0x00000002,
        int(31.2304 * 1e6), int(121.4737 * 1e6),
    ) + struct.pack(">HHH", 15, 605, 90) + bytes.fromhex(
        "260731102530"
    )
    loc = decode_location(body)
    assert abs(loc["lat"] - 31.2304) < 1e-6
    assert abs(loc["lon"] - 121.4737) < 1e-6
    assert loc["speed_kmh"] == 60.5 and loc["direction"] == 90
    assert loc["time"] == "2026-07-31 10:25:30"


# --------------------------------------------------------------- e2e

class Terminal:
    def __init__(self, port, phone):
        self.port = port
        self.phone = phone
        self.codec = Jt808Codec()
        self.state = b""
        self.serial = 0

    async def connect(self):
        self.r, self.w = await asyncio.open_connection(
            "127.0.0.1", self.port
        )
        return self

    def send(self, msg_id, body=b""):
        self.serial += 1
        self.w.write(self.codec.serialize(Jt808Message(
            msg_id, self.phone, self.serial, body
        )))

    async def recv(self, timeout=3.0):
        while True:
            frames, self.state = self.codec.parse(
                self.state,
                await asyncio.wait_for(self.r.read(4096), timeout),
            )
            if frames:
                return frames[0]

    def close(self):
        self.w.close()


def test_jt808_register_auth_location_downlink():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.gateways = [
            {"type": "jt808", "bind": "127.0.0.1", "port": 0}
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        gw = srv.broker.gateways.get("jt808")

        app = TestClient(srv.listeners[0].port, "fleet-app")
        await app.connect()
        await app.subscribe("jt808/+/up", qos=1)

        term = await Terminal(gw.port, "013800001111").connect()

        # -------- location before auth is refused
        term.send(MSG_HEARTBEAT)
        ack = await term.recv()
        assert ack.msg_id == MSG_GENERAL_ACK
        assert ack.body[-1] == 1  # failure: not authenticated

        # -------- register mints an auth code (NO uplink publish yet:
        # pre-auth frames must not reach the broker, ADVICE #5)
        term.send(MSG_REGISTER, b"\x00\x1f\x00\x23" + b"M" * 12)
        rack = await term.recv()
        assert rack.msg_id == MSG_REGISTER_ACK
        r_serial, result = struct.unpack_from(">HB", rack.body, 0)
        assert result == 0
        auth_code = rack.body[3:]

        # -------- wrong auth code denied, right one accepted
        term.send(MSG_AUTH, b"wrong")
        ack = await term.recv()
        assert ack.body[-1] == 1
        term.send(MSG_AUTH, auth_code)
        ack = await term.recv()
        assert ack.msg_id == MSG_GENERAL_ACK and ack.body[-1] == 0
        # the FIRST uplink the app sees is the post-auth one — nothing
        # leaked from the pre-auth register/denied-auth frames
        auth_up = await app.recv_publish()
        assert auth_up.topic == "jt808/013800001111/up"
        assert json.loads(auth_up.payload)["type"] == "auth"
        assert srv.broker.metrics.val("gateway.jt808.preauth_drop") >= 1

        # -------- location report decodes to the up topic
        body = struct.pack(
            ">IIII", 0, 0, int(31.2 * 1e6), int(121.5 * 1e6)
        ) + struct.pack(">HHH", 10, 321, 180) + bytes.fromhex(
            "260731120000"
        )
        term.send(MSG_LOCATION, body)
        ack = await term.recv()
        assert ack.body[-1] == 0
        up = await app.recv_publish()
        loc = json.loads(up.payload)
        assert loc["type"] == "location"
        assert abs(loc["lat"] - 31.2) < 1e-6
        assert loc["speed_kmh"] == 32.1

        # -------- downlink text message frames to the terminal
        await app.publish("jt808/013800001111/dn", json.dumps({
            "text": "return to depot",
        }).encode(), qos=1)
        dn = await term.recv()
        assert dn.msg_id == MSG_TEXT
        assert dn.body[1:] == b"return to depot"

        term.close()
        await app.disconnect()
        await srv.stop()

    run(t())


def test_jt808_reregister_does_not_overwrite_auth_code():
    """A new connection re-registering an enrolled phone is refused
    (0x8100 result 3) and the victim's auth code survives; after the
    real terminal unregisters, a fresh register succeeds."""

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.gateways = [
            {"type": "jt808", "bind": "127.0.0.1", "port": 0}
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        gw = srv.broker.gateways.get("jt808")
        phone = "013800003333"

        victim = await Terminal(gw.port, phone).connect()
        victim.send(MSG_REGISTER, b"\x00\x01\x00\x01" + b"M" * 12)
        rack = await victim.recv()
        assert rack.body[2] == 0
        code = rack.body[3:]

        # attacker: same phone, new connection — refused, code intact
        thief = await Terminal(gw.port, phone).connect()
        thief.send(MSG_REGISTER, b"\x00\x01\x00\x01" + b"X" * 12)
        tack = await thief.recv()
        assert tack.msg_id == MSG_REGISTER_ACK
        assert tack.body[2] == 3  # already registered: no code minted
        assert tack.body[3:] == b""
        assert gw.auth_codes[phone] == code.decode()
        thief.close()

        # the victim's code still authenticates
        victim.send(MSG_AUTH, code)
        ack = await victim.recv()
        assert ack.msg_id == MSG_GENERAL_ACK and ack.body[-1] == 0

        # unregister frees the phone; a fresh register then succeeds
        victim.send(MSG_UNREGISTER)
        await victim.recv()
        fresh = await Terminal(gw.port, phone).connect()
        fresh.send(MSG_REGISTER, b"\x00\x01\x00\x01" + b"M" * 12)
        rack2 = await fresh.recv()
        assert rack2.body[2] == 0 and rack2.body[3:] != b""
        fresh.close()
        victim.close()
        await srv.stop()

    run(t())


def test_jt808_phone_mismatch_closes_connection():
    """One connection = one terminal: a frame carrying a different
    phone than the channel's pinned identity is refused and the
    connection closed (uplink-topic spoofing guard)."""

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.gateways = [
            {"type": "jt808", "bind": "127.0.0.1", "port": 0}
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        gw = srv.broker.gateways.get("jt808")

        term = await Terminal(gw.port, "013800002222").connect()
        term.send(MSG_REGISTER, b"\x00\x01\x00\x01" + b"M" * 12)
        await term.recv()  # register ack pins the phone
        # now claim a DIFFERENT phone on the same connection
        term.phone = "013800009999"
        term.send(MSG_HEARTBEAT)
        ack = await term.recv()
        assert ack.msg_id == MSG_GENERAL_ACK and ack.body[-1] == 1
        # connection is torn down
        data = await asyncio.wait_for(term.r.read(64), 3)
        while data:
            data = await asyncio.wait_for(term.r.read(64), 3)
        await srv.stop()

    run(t())
