"""Chaos: cluster-plane fault injection through the failpoint seams.

A failpoint-driven partition (every frame crossing the leader dropped)
must produce a raft re-election on the surviving majority, commits
must keep succeeding there, and after the fault clears every node
converges on the committed history — no acknowledged write is lost.
A lossy+slow link (probabilistic drops, injected RPC latency) must
degrade throughput, never acknowledged durability."""

import asyncio
import tempfile

import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.cluster import ClusterNode
from emqx_tpu.config import BrokerConfig


FAST = dict(
    heartbeat_interval=0.05, down_after=0.4, flush_interval=0.002,
    consensus="raft", raft_fsync=False,
)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


async def boot_cluster(n=3, prefix="chaos"):
    servers, nodes = [], []
    for i in range(n):
        cfg = BrokerConfig()
        cfg.listeners[0].port = 0
        srv = BrokerServer(cfg)
        await srv.start()
        node = ClusterNode(
            f"n{i}", srv.broker,
            raft_data_dir=tempfile.mkdtemp(prefix=f"{prefix}-n{i}-"),
            **FAST,
        )
        await node.transport.start()
        servers.append(srv)
        nodes.append(node)
    seeds = [(f"n{i}", "127.0.0.1", nodes[i].transport.port)
             for i in range(n)]
    for i, node in enumerate(nodes):
        await node.start(
            seeds=[s for j, s in enumerate(seeds) if j != i]
        )
    deadline = asyncio.get_event_loop().time() + 5
    while asyncio.get_event_loop().time() < deadline:
        if any(nd.raft_conf.role == "leader" for nd in nodes):
            break
        await asyncio.sleep(0.02)
    else:
        raise AssertionError("no raft_conf leader")
    return servers, nodes


async def shutdown(servers, nodes):
    for srv, node in zip(reversed(servers), reversed(nodes)):
        await node.stop()
        await srv.stop()


async def wait_leader_among(nodes, timeout=8.0):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        for n in nodes:
            if n.raft_conf.role == "leader":
                return n
        await asyncio.sleep(0.05)
    raise AssertionError("no leader among survivors after injection")


def test_injected_partition_reelects_and_preserves_acked_writes():
    async def t():
        servers, nodes = await boot_cluster(3)
        try:
            # an acknowledged pre-fault write reaches everyone
            await nodes[0].update_config_async("mqtt.max_qos_allowed", 2)
            await asyncio.sleep(0.3)
            assert all(
                n.broker.config.mqtt.max_qos_allowed == 2 for n in nodes
            )

            old = next(n for n in nodes if n.raft_conf.role == "leader")
            rest = [n for n in nodes if n is not old]
            old_term = old.raft_conf.term
            # drop EVERY cluster frame crossing the leader, both
            # directions — a failpoint partition instead of the
            # transport.blocked test hook
            fp.configure("cluster.transport.send", "drop",
                         match=old.name)

            # the survivors re-elect through the injected partition
            leader = await wait_leader_among(rest)
            assert leader.raft_conf.term > old_term

            # ...and keep committing: this ack is a quorum promise
            await asyncio.wait_for(
                leader.update_config_async("mqtt.max_inflight", 7),
                timeout=10.0,
            )
            await asyncio.sleep(0.3)
            other = next(n for n in rest if n is not leader)
            assert other.broker.config.mqtt.max_inflight == 7

            # heal: the old leader adopts the committed history; both
            # acked writes survive on every node
            fp.clear("cluster.transport.send")
            deadline = asyncio.get_event_loop().time() + 12
            while asyncio.get_event_loop().time() < deadline:
                if old.broker.config.mqtt.max_inflight == 7:
                    break
                await asyncio.sleep(0.2)
            for n in nodes:
                assert n.broker.config.mqtt.max_inflight == 7
                assert n.broker.config.mqtt.max_qos_allowed == 2
        finally:
            await shutdown(servers, nodes)

    run(t())


def test_lossy_slow_link_commits_every_acknowledged_write():
    """25% frame loss (seeded) + 10ms injected latency on every raft
    RPC: slower consensus, but every acknowledged write is durable on
    a majority and converges everywhere once the chaos clears."""

    async def t():
        servers, nodes = await boot_cluster(3, prefix="lossy")
        try:
            fp.configure("cluster.transport.send", "drop",
                         prob=0.25, seed=20260803)
            fp.configure("cluster.raft.rpc", "delay", delay=0.01)

            acked = []
            for v in (3, 5, 9):
                await asyncio.wait_for(
                    nodes[0].update_config_async("mqtt.max_inflight", v),
                    timeout=15.0,
                )
                acked.append(v)
            assert acked == [3, 5, 9]

            fp.clear()
            deadline = asyncio.get_event_loop().time() + 10
            while asyncio.get_event_loop().time() < deadline:
                if all(
                    n.broker.config.mqtt.max_inflight == 9
                    for n in nodes
                ):
                    break
                await asyncio.sleep(0.1)
            # the LAST acknowledged write is the converged state: no
            # acked write was lost or reordered away
            for n in nodes:
                assert n.broker.config.mqtt.max_inflight == 9
        finally:
            await shutdown(servers, nodes)

    run(t())


def test_raft_rpc_drop_forces_timeout_retry_path():
    """Dropping a bounded count of raft RPC replies exercises the
    submit retry loop without losing the proposal."""

    async def t():
        servers, nodes = await boot_cluster(3, prefix="rpcdrop")
        try:
            fp.configure("cluster.raft.rpc", "drop", times=4)
            await asyncio.wait_for(
                nodes[0].update_config_async("mqtt.max_awaiting_rel", 55),
                timeout=15.0,
            )
            await asyncio.sleep(0.5)
            assert [p for p in fp.list_points()][0]["fires"] >= 1
            for n in nodes:
                assert n.broker.config.mqtt.max_awaiting_rel == 55
        finally:
            await shutdown(servers, nodes)

    run(t())
