"""Crash-point property suite for the DS durability contract.

The tools/crashsim harness records a seeded persistent-session
workload's write trace (every append / fsync / metadata replace, via
the live seams), then for EVERY crash point — clean op-boundary cuts,
records torn mid-write at byte granularity, metadata renames landing
as old/tmp-partial/replaced-torn, and cross-file reorderings where a
sidecar write is lost under later appends — materializes the on-disk
state, boots fresh recovery on it, and asserts:

  * ZERO LOSS of any PUBACK-acked QoS>=1 message in `always` mode
    (acked == covered by a completed dslog_sync, the group-commit
    contract);
  * at-least-once replay of every record that physically survived the
    crash, in every mode (recovery never silently skips data it has);
  * store invariants: per-stream (ts, seq) strictly monotone, stream
    pruning (census / LTS structures) never hides a stream holding a
    surviving matching record;
  * no metadata load ever silently resets to empty — torn sidecars
    surface as counted corruption (the `ds_meta_corruption` path),
    with recovery falling back conservatively.
"""

import random

import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.config import BrokerConfig
from emqx_tpu.ds.persist import DurableSessions
from emqx_tpu.message import Message
from emqx_tpu import topic as T
from tools.crashsim import (
    CrashRecorder, materialize, sync_covered_index,
)

_FILTER_POOL = ("fam0/+/t", "fam1/#", "fam2/dev1/t", "+/dev2/t")


def _matches(topic: str, flt: str) -> bool:
    return T.match_words(T.words(topic), T.words(flt))


def run_workload(seed: int, base: str, mode: str):
    """Seeded persistent-session workload under the recorder.

    Checkpointed (detached) subscriber sessions + a QoS1 publisher
    whose topics the persistence gate captures; interleaved group
    fsyncs and metadata checkpoints; possibly an un-fsynced tail.
    Returns ``(ops, layout, sessions, captured)`` where ``captured``
    aligns 1:1 (in order) with the trace's append ops.
    """
    rng = random.Random(seed)
    layout = "lts" if seed % 2 else "hash"
    sessions = {
        f"sub{i}": sorted(rng.sample(
            _FILTER_POOL, rng.randint(1, 2)
        ))
        for i in range(rng.randint(1, 3))
    }
    t0 = 1_700_000_000.0
    captured = []
    with CrashRecorder() as rec:
        ds = DurableSessions(base, layout=layout, fsync=mode)
        for cid, flts in sessions.items():
            ds.save(
                cid, {f: {"qos": 1} for f in flts},
                expiry=1e9, now=t0,
            )
            for f in flts:
                ds.add_filter(f)
        t = t0 + 1.0
        for _phase in range(rng.randint(3, 5)):
            batch = []
            for _ in range(rng.randint(2, 6)):
                t += 0.001
                batch.append(Message(
                    topic=(
                        f"fam{rng.randint(0, 2)}/"
                        f"dev{rng.randint(0, 3)}/t"
                    ),
                    payload=bytes(
                        rng.getrandbits(8)
                        for _ in range(rng.randint(3, 40))
                    ),
                    qos=1,
                    timestamp=t,
                    from_client="pub",
                ))
            ds.persist(batch)
            captured.extend(
                m for m in batch if ds._gate.match(m.topic)
            )
            if rng.random() < 0.7:
                # the group-commit flush: in `always` mode the acks
                # for everything appended so far release HERE
                ds.gate.sync_now()
            if rng.random() < 0.3:
                ds.checkpoint_meta()
        if rng.random() < 0.5:
            ds.gate.sync_now()
    # close OUTSIDE the recorder: its final flush is not part of the
    # crashed trace
    ds.close()
    n_appends = sum(1 for op in rec.ops if op.kind == "append")
    assert n_appends == len(captured)
    return rec.ops, layout, sessions, captured


def _crash_states(ops):
    """Every clean cut, plus torn variants at append/meta ops."""
    for k in range(len(ops) + 1):
        yield k, None, "old"
        if k < len(ops):
            op = ops[k]
            if op.kind == "append":
                blob_len = 28 + len(op.data)
                for tb in (1, blob_len // 2, blob_len - 1):
                    yield k, tb, "old"
            elif op.kind == "meta":
                yield k, 7, "tmp-partial"
                if not op.fsynced:
                    # rename-persisted-but-content-torn is only a
                    # legal power-fail state when the write skipped
                    # the tmp fsync (never/interval metadata mode) —
                    # `always` fsyncs the staging file BEFORE the
                    # rename, which is exactly what rules it out
                    yield k, max(1, len(op.data) // 2), "replaced-torn"


def _check_recovery(out, layout, mode, sessions, acked, survived,
                    expect_meta_corruption=False):
    ds2 = DurableSessions(str(out), layout=layout, fsync=mode)
    try:
        all_mids = {m.mid for m in survived}
        for cid, flts in sessions.items():
            expected_acked = {
                m.mid for m in acked
                if any(_matches(m.topic, f) for f in flts)
            }
            expected_survived = {
                m.mid for m in survived
                if any(_matches(m.topic, f) for f in flts)
            }
            state = ds2.load(cid)
            if mode == "always":
                # the checkpoint save precedes (and in always mode
                # fsyncs before) every captured publish: acked
                # messages imply a bootable session
                assert state is not None or not expected_acked, cid
            if state is None:
                continue
            got = {m.mid for _flt, m in ds2.replay(state)}
            # ZERO acked loss (always mode), at-least-once in general
            if mode == "always":
                assert expected_acked <= got, (
                    cid, expected_acked - got
                )
            # recovery never silently skips surviving records
            assert expected_survived <= got, (
                cid, expected_survived - got
            )
            # and never invents messages
            assert got <= all_mids
        # store invariants: per-stream (ts, seq) strictly monotone
        logh = ds2.storage._log
        for shard in logh.streams():
            prev = (0, 0)
            for ts, seq, _payload in logh.scan(shard, 0):
                assert (ts, seq) > prev, shard
                prev = (ts, seq)
        # stream pruning never hides a surviving record's stream
        for m in survived:
            key = ds2.storage.stream_key(m.topic)
            shards = {
                s.shard for s in ds2.storage.get_streams(m.topic)
            }
            assert key in shards, m.topic
        if expect_meta_corruption:
            # the contract's "never silent" half: a torn sidecar is
            # COUNTED (alarm path), not absorbed as a fresh start
            assert ds2.corruption_counts.get("meta", 0) >= 1
    finally:
        ds2.close()


@pytest.mark.parametrize("seed,mode", [
    (11, "always"),
    (12, "always"),
    (13, "always"),
    (14, "always"),
    (15, "interval"),
    (16, "never"),
])
def test_crash_point_enumeration(tmp_path, seed, mode):
    base = tmp_path / "live"
    ops, layout, sessions, captured = run_workload(
        seed, str(base), mode
    )
    append_idx = [
        i for i, op in enumerate(ops) if op.kind == "append"
    ]
    n_states = 0
    for k, torn, variant in _crash_states(ops):
        out = tmp_path / f"crash-{n_states}"
        materialize(
            ops, k, src_root=str(base), out_root=str(out),
            torn_bytes=torn, meta_variant=variant,
        )
        # appends materialized whole: index < k (a torn record at k is
        # truncated away by recovery — it never acked)
        n_survived = sum(1 for i in append_idx if i < k)
        survived = captured[:n_survived]
        j = sync_covered_index(ops, k)
        acked = captured[:sum(1 for i in append_idx if i < j)]
        _check_recovery(
            out, layout, mode, sessions, acked, survived,
            expect_meta_corruption=(variant == "replaced-torn"),
        )
        n_states += 1
    assert n_states > len(ops)  # torn variants actually enumerated


def test_cross_file_reordering_loses_sidecar_not_data(tmp_path):
    """ALICE's reordering case: a sidecar write in the un-fsynced
    tail is lost while LATER log appends persist.  Recovery must
    still serve every surviving record (the sidecars are caches /
    progress — losing one may widen replay, never narrow it)."""
    base = tmp_path / "live"
    ops, layout, sessions, captured = run_workload(
        21, str(base), "interval"
    )
    meta_idx = [i for i, op in enumerate(ops) if op.kind == "meta"]
    append_idx = [
        i for i, op in enumerate(ops) if op.kind == "append"
    ]
    for n, mi in enumerate(meta_idx[1:]):  # keep the LAYOUT marker
        out = tmp_path / f"reorder-{n}"
        materialize(
            ops, len(ops), src_root=str(base), out_root=str(out),
            skip_meta_index=mi,
        )
        survived = captured[:len(append_idx)]
        # acked: interval mode doesn't gate acks on sync; assert only
        # the at-least-once half
        _check_recovery(
            out, layout, "interval", sessions, [], survived
        )


def test_durable_shared_sub_workload_crash_points(tmp_path):
    """A durable $share group (single member: the rendezvous split is
    total) through the same enumeration: group replay stays
    at-least-once at every crash point."""
    base = tmp_path / "live"
    rng = random.Random(31)
    t0 = 1_700_000_000.0
    flt = "$share/g/fam1/#"
    captured = []
    with CrashRecorder() as rec:
        ds = DurableSessions(str(base), layout="hash", fsync="always")
        ds.save("sA", {flt: {"qos": 1}}, expiry=1e9, now=t0)
        ds.add_filter("fam1/#")
        ds.shared_join(flt, "sA")
        t = t0 + 1.0
        for _ in range(10):
            t += 0.001
            m = Message(
                topic=f"fam1/dev{rng.randint(0, 3)}/t",
                payload=b"x" * rng.randint(3, 20),
                qos=1, timestamp=t, from_client="pub",
            )
            ds.persist([m])
            captured.append(m)
            if rng.random() < 0.5:
                ds.gate.sync_now()
    ds.close()
    append_idx = [
        i for i, op in enumerate(rec.ops) if op.kind == "append"
    ]
    for n, k in enumerate(range(len(rec.ops) + 1)):
        out = tmp_path / f"crash-{n}"
        materialize(
            rec.ops, k, src_root=str(base), out_root=str(out)
        )
        survived = captured[:sum(1 for i in append_idx if i < k)]
        j = sync_covered_index(rec.ops, k)
        acked = captured[:sum(1 for i in append_idx if i < j)]
        ds2 = DurableSessions(str(out), layout="hash", fsync="always")
        try:
            state = ds2.load("sA")
            assert state is not None or not acked
            if state is None:
                continue
            got = {m.mid for _f, m in ds2.replay(state)}
            assert {m.mid for m in acked} <= got
            assert {m.mid for m in survived} <= got
        finally:
            ds2.close()


def test_full_broker_boots_on_materialized_crash(tmp_path):
    """The tentpole's integration hop: a fresh BROKER boots on a
    materialized mid-trace crash state, restores the checkpoints, and
    replays the acked interval."""
    base = tmp_path / "live"
    ops, layout, sessions, captured = run_workload(
        11, str(base), "always"
    )
    append_idx = [
        i for i, op in enumerate(ops) if op.kind == "append"
    ]
    k = max(
        (i for i, op in enumerate(ops) if op.kind == "sync"),
        default=len(ops),
    )  # crash right after the last completed flush
    out = tmp_path / "crashed"
    materialize(ops, k + 1, src_root=str(base), out_root=str(out))
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.durable.enable = True
    cfg.durable.data_dir = str(out)
    cfg.durable.layout = layout
    cfg.durable.fsync = "always"
    b = Broker(config=cfg)
    try:
        acked = captured[:sum(1 for i in append_idx if i < k)]
        for cid, flts in sessions.items():
            assert b.durable.has_checkpoint(cid)
            state = b.durable.load(cid)
            got = {m.mid for _f, m in b.durable.replay(state)}
            expected = {
                m.mid for m in acked
                if any(_matches(m.topic, f) for f in flts)
            }
            assert expected <= got
    finally:
        b.shutdown()


def test_full_broker_alarms_on_torn_sidecar(tmp_path):
    """A replaced-but-torn sidecar at the crash point surfaces as the
    ds_meta_corruption $SYS alarm on broker boot — never a silent
    reset."""
    base = tmp_path / "live"
    ops, layout, _sessions, _captured = run_workload(
        12, str(base), "interval"
    )
    meta_idx = [i for i, op in enumerate(ops) if op.kind == "meta"]
    k = meta_idx[-1]
    out = tmp_path / "crashed"
    materialize(
        ops, k, src_root=str(base), out_root=str(out),
        torn_bytes=max(1, len(ops[k].data) // 2),
        meta_variant="replaced-torn",
    )
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.durable.enable = True
    cfg.durable.data_dir = str(out)
    cfg.durable.layout = layout
    b = Broker(config=cfg)
    try:
        names = {a.name for a in b.alarms.active()}
        assert "ds_meta_corruption" in names
        assert b.metrics.all()["ds.meta.corruption"] >= 1
    finally:
        b.shutdown()
