"""Cluster-SHARDED route index (cluster/sharded_routes.py): the
wildcard set partitioned by rendezvous hash across nodes — each node
indexes ~1/N of the cluster's filters and publish windows
scatter-gather — vs the reference's full per-node replica
(/root/reference/apps/emqx/src/emqx_router.erl:133-162)."""

import asyncio
import random

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.cluster import ClusterNode
from emqx_tpu.config import BrokerConfig
from emqx_tpu import topic as T
from mqtt_client import TestClient


FAST = dict(heartbeat_interval=0.05, down_after=0.3,
            flush_interval=0.002, sharded_routes=True)


def run(coro):
    return asyncio.run(coro)


async def start_node(name, seeds=()):
    cfg = BrokerConfig()
    cfg.listeners[0].port = 0
    srv = BrokerServer(cfg)
    await srv.start()
    node = ClusterNode(name, srv.broker, **FAST)
    await node.start(seeds=list(seeds))
    return srv, node


async def stop_node(srv, node):
    await node.stop()
    await srv.stop()


async def settle(t=0.08):
    await asyncio.sleep(t)


def test_filters_partition_across_owners():
    """Each filter lives in exactly ONE node's shard table, and the
    partition is roughly balanced — no node holds a full replica."""

    async def t():
        s1, n1 = await start_node("n1")
        s2, n2 = await start_node("n2", seeds=[("n1", "127.0.0.1", n1.port)])
        s3, n3 = await start_node("n3", seeds=[("n1", "127.0.0.1", n1.port)])
        nodes = [(s1, n1), (s2, n2), (s3, n3)]
        try:
            await settle(0.3)  # full mesh via gossip
            clients = []
            for i in range(60):
                srv, _ = nodes[i % 3]
                c = TestClient(srv.listeners[0].port, f"c{i}")
                await c.connect()
                await c.subscribe(f"fleet/{i}/+", qos=0)
                clients.append(c)
            await settle(0.3)
            counts = [len(n.shard.table) for _, n in nodes]
            assert sum(counts) == 60, counts  # exactly one owner each
            assert all(5 <= c <= 40 for c in counts), counts  # balanced-ish
            for c in clients:
                await c.disconnect()
        finally:
            for srv, n in reversed(nodes):
                await stop_node(srv, n)

    run(t())


def test_cross_node_pubsub_sharded():
    async def t():
        s1, n1 = await start_node("n1")
        s2, n2 = await start_node("n2", seeds=[("n1", "127.0.0.1", n1.port)])
        try:
            sub = TestClient(s1.listeners[0].port, "subA")
            await sub.connect()
            await sub.subscribe("fleet/+/temp", qos=1)
            await settle(0.2)

            pub = TestClient(s2.listeners[0].port, "pubB")
            await pub.connect()
            await pub.publish("fleet/v1/temp", b"22C", qos=1)
            msg = await sub.recv_publish(timeout=5)
            assert msg.topic == "fleet/v1/temp" and msg.payload == b"22C"
            # and the scatter actually ran (not just a flood fallback)
            await settle()
            assert (n2.shard.stats["scatter"] >= 1
                    or n2.shard.stats["flood"] >= 1)
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await stop_node(s2, n2)
            await stop_node(s1, n1)

    run(t())


def test_sharded_oracle_equivalence():
    """Random filters subscribed on random nodes, random topics
    published from every node: the delivered sets must equal the
    single-broker wildcard oracle."""

    async def t():
        s1, n1 = await start_node("n1")
        s2, n2 = await start_node("n2", seeds=[("n1", "127.0.0.1", n1.port)])
        s3, n3 = await start_node("n3", seeds=[("n1", "127.0.0.1", n1.port)])
        nodes = [(s1, n1), (s2, n2), (s3, n3)]
        rng = random.Random(7)
        try:
            await settle(0.3)
            words = ["a", "b", "c", "+"]
            filters = []
            subs = []
            for i in range(24):
                flt = "/".join(rng.choice(words) for _ in range(3))
                if rng.random() < 0.3:
                    flt += "/#"
                srv, _ = nodes[i % 3]
                c = TestClient(srv.listeners[0].port, f"s{i}")
                await c.connect()
                await c.subscribe(flt, qos=1)
                filters.append((f"s{i}", flt))
                subs.append(c)
            await settle(0.3)

            pubs = []
            for j, (srv, _) in enumerate(nodes):
                p = TestClient(srv.listeners[0].port, f"p{j}")
                await p.connect()
                pubs.append(p)
            topics = [
                "/".join(rng.choice(["a", "b", "c"]) for _ in range(3))
                for _ in range(15)
            ]
            expected = {cid: set() for cid, _ in filters}
            for k, t_ in enumerate(topics):
                p = pubs[k % 3]
                payload = f"m{k}".encode()
                await p.publish(t_, payload, qos=1)
                for cid, flt in filters:
                    if T.match(t_, flt):
                        expected[cid].add(payload)
            await settle(0.6)

            for c, (cid, flt) in zip(subs, filters):
                got = set()
                while True:
                    try:
                        m = await c.recv_publish(timeout=0.3)
                    except Exception:
                        break
                    got.add(bytes(m.payload))
                assert got == expected[cid], (cid, flt, got, expected[cid])
            for c in subs + pubs:
                await c.disconnect()
        finally:
            for srv, n in reversed(nodes):
                await stop_node(srv, n)

    run(t())


def test_owner_death_reshards():
    """Kill the owner of a filter: after the membership change +
    resync, publishes still reach the subscriber (the filter re-homes
    to a surviving owner)."""

    async def t():
        s1, n1 = await start_node("n1")
        s2, n2 = await start_node("n2", seeds=[("n1", "127.0.0.1", n1.port)])
        s3, n3 = await start_node("n3", seeds=[("n1", "127.0.0.1", n1.port)])
        try:
            await settle(0.3)
            sub = TestClient(s1.listeners[0].port, "subA")
            await sub.connect()
            await sub.subscribe("dead/owner/t", qos=1)
            await settle(0.3)
            # find the owner; if it's n1 (the subscriber's own node),
            # that is fine too — kill n3 then to exercise reshard
            owner = n1.shard.owner_of("dead/owner/t")
            victim = {"n1": (s3, n3), "n2": (s2, n2),
                      "n3": (s3, n3)}[owner]
            vs, vn = victim
            await stop_node(vs, vn)
            await settle(1.2)  # down_after + resync

            pub_srv = s2 if vn is n3 else s3
            pub = TestClient(pub_srv.listeners[0].port, "pubB")
            await pub.connect()
            await pub.publish("dead/owner/t", b"alive", qos=1)
            msg = await sub.recv_publish(timeout=5)
            assert msg.payload == b"alive"
            await sub.disconnect()
            await pub.disconnect()
        finally:
            for srv, n in [(s3, n3), (s2, n2), (s1, n1)]:
                if n is not vn:
                    await stop_node(srv, n)

    run(t())
