"""Partition-heal convergence (VERDICT r4 #8): split a 3-node raft
cluster 2/1, write on both sides, heal — the majority's acked writes
survive, the minority's writes fail LOUDLY (not silently), and after
the heal every node converges to the committed state.  The reference
gets the same guarantee from emqx_cluster_rpc's logged transactions
over mria (emqx_cluster_rpc.erl:26-54)."""

import asyncio
import tempfile

import pytest

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.cluster import ClusterNode
from emqx_tpu.config import BrokerConfig


FAST = dict(
    heartbeat_interval=0.05, down_after=0.4, flush_interval=0.002,
    consensus="raft", raft_fsync=False,
)


def run(coro):
    return asyncio.run(coro)


async def boot_cluster(n=3):
    servers, nodes = [], []
    for i in range(n):
        cfg = BrokerConfig()
        cfg.listeners[0].port = 0
        srv = BrokerServer(cfg)
        await srv.start()
        node = ClusterNode(
            f"n{i}", srv.broker,
            raft_data_dir=tempfile.mkdtemp(prefix=f"raftp-n{i}-"),
            **FAST,
        )
        await node.transport.start()  # learn the port before seeding
        servers.append(srv)
        nodes.append(node)
    seeds = [(f"n{i}", "127.0.0.1", nodes[i].transport.port)
             for i in range(n)]
    for i, node in enumerate(nodes):
        await node.start(
            seeds=[s for j, s in enumerate(seeds) if j != i]
        )
    deadline = asyncio.get_event_loop().time() + 5
    while asyncio.get_event_loop().time() < deadline:
        if any(nd.raft_conf.role == "leader" for nd in nodes):
            break
        await asyncio.sleep(0.02)
    else:
        raise AssertionError("no raft_conf leader")
    return servers, nodes


def partition(minority, majority):
    """Full bidirectional split: each side drops traffic to the other."""
    for n in minority:
        n.transport.blocked |= {m.name for m in majority}
    for m in majority:
        m.transport.blocked |= {n.name for n in minority}


async def wait_leader_among(nodes, timeout=6.0):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    names = {n.name for n in nodes}
    while loop.time() < deadline:
        for n in nodes:
            if n.raft_conf.role == "leader" and n.name in names:
                return n
        await asyncio.sleep(0.05)
    raise AssertionError("no leader among majority after split")


def test_partition_heal_config_and_registry_convergence():
    async def t():
        servers, nodes = await boot_cluster(3)
        na, nb, nc = nodes
        try:
            # committed write pre-partition reaches everyone
            await na.update_config_async("mqtt.max_qos_allowed", 2)
            await asyncio.sleep(0.3)
            assert nc.broker.config.mqtt.max_qos_allowed == 2

            # split: nc alone vs {na, nb}
            partition([nc], [na, nb])
            await asyncio.sleep(1.0)  # down detection + re-election

            # majority side still commits
            leader = await wait_leader_among([na, nb])
            await asyncio.wait_for(
                leader.update_config_async("mqtt.max_inflight", 7),
                timeout=10.0,
            )
            await asyncio.sleep(0.3)
            other = nb if leader is na else na
            assert other.broker.config.mqtt.max_inflight == 7

            # minority side CANNOT commit: the submit fails loudly
            with pytest.raises(Exception):
                await nc.update_config_async("mqtt.max_inflight", 99)

            # registry write on the majority during the split: the
            # client-ownership claim rides the same committed log
            leader.client_opened("part-client")
            await asyncio.sleep(0.4)

            # heal and converge: the minority adopts the COMMITTED
            # history; its failed write never resurfaces anywhere
            for n in nodes:
                n.transport.blocked.clear()
            deadline = asyncio.get_event_loop().time() + 12
            while asyncio.get_event_loop().time() < deadline:
                if (nc.broker.config.mqtt.max_inflight == 7
                        and nc.clients.get("part-client")
                        == leader.name):
                    break
                await asyncio.sleep(0.2)
            assert nc.broker.config.mqtt.max_inflight == 7  # not 99
            assert nc.clients.get("part-client") == leader.name
            assert na.broker.config.mqtt.max_inflight == 7
            assert nb.broker.config.mqtt.max_inflight == 7
        finally:
            for srv, node in zip(reversed(servers), reversed(nodes)):
                await node.stop()
                await srv.stop()

    run(t())


def test_partition_minority_keeps_serving_locally():
    """A minority node keeps serving ITS OWN clients during the split
    (availability for local work), while quorum-plane writes stall —
    and the local registry claim converges cluster-wide after heal via
    the raft log."""

    async def t():
        servers, nodes = await boot_cluster(3)
        na, nb, nc = nodes
        try:
            partition([nc], [na, nb])
            await asyncio.sleep(0.8)
            # local (optimistic) registry apply still works on nc
            nc.client_opened("loner")
            assert nc.clients.get("loner") == "nc" or \
                nc.clients.get("loner") == nc.name
            # heal: nc's claim reaches the majority via the post-heal
            # sync + retried log entries
            for n in nodes:
                n.transport.blocked.clear()
            deadline = asyncio.get_event_loop().time() + 6
            while asyncio.get_event_loop().time() < deadline:
                if na.clients.get("loner") == nc.name:
                    break
                await asyncio.sleep(0.1)
            assert na.clients.get("loner") == nc.name
        finally:
            for srv, node in zip(reversed(servers), reversed(nodes)):
                await node.stop()
                await srv.stop()

    run(t())


def test_replicant_role_serves_without_joining_quorum():
    """mria core/replicant split: a replicant joins the cluster, never
    enters the raft membership, forwards config writes to a core, and
    receives committed entries — and adding it does not change the
    cores' quorum size."""

    async def t():
        servers, nodes = await boot_cluster(3)
        na, nb, nc = nodes
        try:
            from emqx_tpu.broker.listener import BrokerServer
            from emqx_tpu.cluster import ClusterNode
            from emqx_tpu.config import BrokerConfig

            cfg = BrokerConfig()
            cfg.listeners[0].port = 0
            rsrv = BrokerServer(cfg)
            await rsrv.start()
            rep = ClusterNode(
                "rep1", rsrv.broker, role="replicant",
                heartbeat_interval=0.05, down_after=0.4,
                flush_interval=0.002,
            )
            await rep.start(seeds=[
                ("n0", "127.0.0.1", na.transport.port)
            ])
            await asyncio.sleep(0.8)  # gossip + sync + heartbeats

            # the replicant never enters any core's raft membership
            for core in nodes:
                assert "rep1" not in core.raft_conf.peers
                assert "rep1" not in core.raft_ds.peers
            assert rep.raft_conf is None  # no local consensus machinery

            # committed write on a core reaches the replicant
            await na.update_config_async("mqtt.max_inflight", 9)
            deadline = asyncio.get_event_loop().time() + 5
            while asyncio.get_event_loop().time() < deadline:
                if rsrv.broker.config.mqtt.max_inflight == 9:
                    break
                await asyncio.sleep(0.1)
            assert rsrv.broker.config.mqtt.max_inflight == 9

            # a write ORIGINATED on the replicant forwards to a core,
            # commits through the quorum, and lands everywhere
            await rep.update_config_async("mqtt.max_awaiting_rel", 55)
            await asyncio.sleep(0.5)
            assert na.broker.config.mqtt.max_awaiting_rel == 55
            assert nb.broker.config.mqtt.max_awaiting_rel == 55

            await rep.stop()
            await rsrv.stop()
        finally:
            for srv, node in zip(reversed(servers), reversed(nodes)):
                await node.stop()
                await srv.stop()

    run(t())
