"""MQTT codec tests: golden byte vectors, round-trip property tests
(parse(serialize(p)) == p over randomized packets — the
prop_emqx_frame.erl pattern), incremental-feed fragmentation, and
malformed-frame rejection."""

import random

import pytest

from emqx_tpu.codec import mqtt as m


def rt(pkt, ver=m.MQTT_V5):
    """serialize -> parse round trip through the stream parser."""
    data = m.serialize(pkt, ver)
    p = m.StreamParser(version=ver)
    out = list(p.feed(data))
    assert len(out) == 1
    return out[0]


# ---------------------------------------------------------------- golden

def test_pingreq_bytes():
    assert m.serialize(m.Pingreq()) == b"\xc0\x00"
    assert m.serialize(m.Pingresp()) == b"\xd0\x00"


def test_publish_qos0_v4_bytes():
    # DUP=0 QoS=0 RETAIN=1, topic "a/b", payload "hi"
    data = m.serialize(
        m.Publish(topic="a/b", payload=b"hi", retain=True), m.MQTT_V4
    )
    assert data == b"\x31\x07\x00\x03a/bhi"


def test_connect_v4_golden():
    pkt = m.Connect(client_id="cid", proto_ver=4, clean_start=True,
                    keepalive=30)
    data = m.serialize(pkt)
    out = rt(pkt)
    assert out.client_id == "cid" and out.proto_ver == 4
    assert data[0] == 0x10
    assert b"MQTT" in data


def test_varint_boundaries():
    for n in (0, 127, 128, 16383, 16384, 2097151, 2097152, 268435455):
        buf = m._varint(n)
        r = m._Reader(buf)
        assert r.varint() == n
    with pytest.raises(m.MqttError):
        m._varint(268435456)


# ------------------------------------------------------------ round trip

RNG = random.Random(7)


def rand_props(rng, publish=False):
    props = {}
    if rng.random() < 0.5:
        props["user_property"] = [("k", "v"), ("k2", "vv")]
    if rng.random() < 0.3:
        props["message_expiry_interval"] = rng.randint(0, 2**32 - 1)
    if publish and rng.random() < 0.3:
        props["subscription_identifier"] = [rng.randint(1, 1000)]
        props["content_type"] = "application/json"
    return props


def rand_publish(rng, ver):
    qos = rng.randint(0, 2)
    return m.Publish(
        topic=rng.choice(["a", "a/b/c", "dev/1/温度", "x/" + "y" * 100]),
        payload=bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 64))),
        qos=qos,
        retain=rng.random() < 0.5,
        dup=qos > 0 and rng.random() < 0.5,
        packet_id=rng.randint(1, 65535) if qos else None,
        properties=rand_props(rng, publish=True) if ver == 5 else {},
    )


@pytest.mark.parametrize("ver", [m.MQTT_V4, m.MQTT_V5])
def test_publish_roundtrip(ver):
    for _ in range(200):
        pkt = rand_publish(RNG, ver)
        assert rt(pkt, ver) == pkt


@pytest.mark.parametrize("ver", [m.MQTT_V3, m.MQTT_V4, m.MQTT_V5])
def test_connect_roundtrip(ver):
    for _ in range(100):
        will = None
        if RNG.random() < 0.5:
            will = m.Will(
                topic="will/t",
                payload=b"gone",
                qos=RNG.randint(0, 2),
                retain=RNG.random() < 0.5,
                properties={"will_delay_interval": 5} if ver == 5 else {},
            )
        pkt = m.Connect(
            client_id="c-" + str(RNG.randint(0, 999)),
            proto_ver=ver,
            proto_name="MQIsdp" if ver == 3 else "MQTT",
            clean_start=RNG.random() < 0.5,
            keepalive=RNG.randint(0, 65535),
            username="u" if RNG.random() < 0.5 else None,
            password=b"p" if RNG.random() < 0.5 else None,
            will=will,
            properties={"session_expiry_interval": 120} if ver == 5 else {},
        )
        if pkt.password is not None and pkt.username is None and ver != 5:
            pkt.password = None  # [MQTT-3.1.2-22]: password requires username
        assert rt(pkt) == pkt


@pytest.mark.parametrize("ver", [m.MQTT_V4, m.MQTT_V5])
def test_sub_unsub_roundtrip(ver):
    subs = [
        m.Subscription("a/+/b", qos=1),
        m.Subscription("$share/g/x/#", qos=2, no_local=ver == 5,
                       retain_as_published=ver == 5, retain_handling=2 if ver == 5 else 0),
    ]
    pkt = m.Subscribe(packet_id=10, subscriptions=subs)
    out = rt(pkt, ver)
    if ver == 5:
        assert out == pkt
    else:
        assert [s.topic_filter for s in out.subscriptions] == ["a/+/b", "$share/g/x/#"]
        assert [s.qos for s in out.subscriptions] == [1, 2]
    assert rt(m.Suback(packet_id=10, reason_codes=[0, 1, 0x80]), ver) == m.Suback(
        packet_id=10, reason_codes=[0, 1, 0x80]
    )
    un = m.Unsubscribe(packet_id=11, topic_filters=["a/+/b", "c"])
    assert rt(un, ver) == un


@pytest.mark.parametrize("cls", [m.Puback, m.Pubrec, m.Pubrel, m.Pubcomp])
@pytest.mark.parametrize("ver", [m.MQTT_V4, m.MQTT_V5])
def test_acks_roundtrip(cls, ver):
    pkt = cls(packet_id=77)
    assert rt(pkt, ver) == pkt
    if ver == 5:
        pkt = cls(packet_id=78, reason_code=0x10,
                  properties={"reason_string": "no one"})
        assert rt(pkt, ver) == pkt


def test_disconnect_auth_roundtrip():
    assert rt(m.Disconnect()) == m.Disconnect()
    d = m.Disconnect(reason_code=0x8E, properties={"reason_string": "bye"})
    assert rt(d) == d
    a = m.Auth(reason_code=0x18, properties={"authentication_method": "SCRAM"})
    assert rt(a) == a
    # v4 disconnect has an empty body
    assert m.serialize(m.Disconnect(), m.MQTT_V4) == b"\xe0\x00"


# ------------------------------------------------------- stream behavior

def test_byte_at_a_time_feed():
    pkts = [
        m.Connect(client_id="c1", proto_ver=5),
        m.Publish(topic="t/1", payload=b"x" * 300, qos=1, packet_id=5),
        m.Pingreq(),
    ]
    stream = b"".join(m.serialize(p) for p in pkts)
    parser = m.StreamParser()
    got = []
    for i in range(len(stream)):
        got += list(parser.feed(stream[i : i + 1]))
    assert got == pkts


def test_version_locked_from_connect():
    parser = m.StreamParser()
    c = m.Connect(client_id="c", proto_ver=4)
    pub = m.Publish(topic="t", payload=b"p")
    out = list(parser.feed(m.serialize(c) + m.serialize(pub, 4)))
    assert parser.version == 4
    assert out[1].topic == "t"


def test_max_packet_size_guard():
    parser = m.StreamParser(max_packet_size=64)
    big = m.serialize(m.Publish(topic="t", payload=b"z" * 200))
    with pytest.raises(m.MqttError):
        list(parser.feed(big))


@pytest.mark.parametrize(
    "raw",
    [
        b"\x00\x00",          # type 0
        b"\xc1\x00",          # PINGREQ with flags
        b"\x60\x02\x00\x01",  # PUBREL with flags 0 (must be 2)
        b"\x10\x02\x00\x00",  # CONNECT truncated body
        b"\x36\x03\x00\x01a", # qos3 publish
    ],
)
def test_malformed(raw):
    parser = m.StreamParser()
    with pytest.raises(m.MqttError):
        list(parser.feed(raw))


def test_unknown_property_rejected():
    # CONNACK v5 with property id 0x7F
    body = b"\x00\x00" + b"\x02\x7f\x00"
    raw = bytes([m.CONNACK << 4, len(body)]) + body
    with pytest.raises(m.MqttError):
        list(m.StreamParser().feed(raw))


def test_unconsumed_feed_still_buffers():
    # feed() must consume its chunk even if the iterator is dropped
    parser = m.StreamParser()
    ping = b"\xc0\x00"
    parser.feed(ping[:1])  # iterator discarded
    assert len(list(parser.feed(ping[1:]))) == 1


def test_password_without_username_rejected_v4():
    pkt = m.Connect(client_id="c", proto_ver=4, password=b"p")
    raw = m.serialize(pkt)
    with pytest.raises(m.MqttError):
        list(m.StreamParser().feed(raw))
    # v5 allows password without username
    pkt5 = m.Connect(client_id="c", proto_ver=5, password=b"p")
    assert rt(pkt5).password == b"p"


def test_many_frames_one_chunk():
    chunk = m.serialize(m.Pingreq()) * 5000
    got = list(m.StreamParser().feed(chunk))
    assert len(got) == 5000
