"""Message transformation + schema validation ahead of routing
(emqx_message_transformation / emqx_schema_validation parity)."""

import asyncio
import json

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.payload_pipeline import Transformation, Validation
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


async def make_server():
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    srv = BrokerServer(cfg)
    await srv.start()
    return srv


def test_schema_validation_drops_invalid():
    async def t():
        srv = await make_server()
        port = srv.listeners[0].port
        failures = []
        srv.broker.hooks.add(
            "schema.validation_failed",
            lambda msg, name, err: failures.append((name, err)),
        )
        srv.broker.pipeline.add_validation(
            Validation(
                name="temp-check",
                topics=["sensors/#"],
                schema={
                    "type": "object",
                    "properties": {"temp": {"type": "number"}},
                    "required": ["temp"],
                },
            )
        )
        sub = TestClient(port, "s")
        await sub.connect()
        await sub.subscribe("sensors/#", qos=1)
        pub = TestClient(port, "p")
        await pub.connect()
        await pub.publish("sensors/a", b'{"temp": 20.5}', qos=1)
        pkt = await sub.recv_publish()
        assert json.loads(pkt.payload)["temp"] == 20.5
        # invalid: dropped, hookpoint fired
        await pub.publish("sensors/a", b'{"temp": "hot"}', qos=1)
        await pub.publish("sensors/a", b"not json", qos=1)
        await pub.publish("other/a", b"not json", qos=1)  # not covered
        await asyncio.sleep(0.05)
        assert len(failures) == 2
        assert failures[0][0] == "temp-check"
        assert srv.broker.metrics.val("messages.validation_failed") == 2
        # the valid message was the only sensors/# delivery
        await pub.publish("sensors/a", b'{"temp": 1}', qos=1)
        pkt2 = await sub.recv_publish()
        assert json.loads(pkt2.payload)["temp"] == 1
        await pub.disconnect()
        await sub.disconnect()
        await srv.stop()

    run(t())


def test_transformation_rewrites_payload_and_topic():
    async def t():
        srv = await make_server()
        port = srv.listeners[0].port
        srv.broker.pipeline.add_transformation(
            Transformation(
                name="enrich",
                topics=["raw/#"],
                operations={
                    "topic": "cooked/${clientid}",
                    "payload.source": "${topic}",
                    "payload.unit": "celsius",
                },
            )
        )
        sub = TestClient(port, "s2")
        await sub.connect()
        await sub.subscribe("cooked/#", qos=1)
        pub = TestClient(port, "dev7")
        await pub.connect()
        await pub.publish("raw/x", b'{"v": 3}', qos=1)
        pkt = await sub.recv_publish()
        assert pkt.topic == "cooked/dev7"
        body = json.loads(pkt.payload)
        assert body == {"v": 3, "source": "raw/x", "unit": "celsius"}
        await pub.disconnect()
        await sub.disconnect()
        await srv.stop()

    run(t())


def test_transformation_then_validation_order():
    async def t():
        srv = await make_server()
        port = srv.listeners[0].port
        # the transformation injects the field validation requires
        srv.broker.pipeline.add_transformation(
            Transformation(
                name="default-temp",
                topics=["t/#"],
                operations={"payload.temp": 0},
            )
        )
        srv.broker.pipeline.add_validation(
            Validation(
                name="needs-temp",
                topics=["t/#"],
                schema={"type": "object", "required": ["temp"]},
            )
        )
        sub = TestClient(port, "s3")
        await sub.connect()
        await sub.subscribe("t/#", qos=1)
        pub = TestClient(port, "p3")
        await pub.connect()
        await pub.publish("t/1", b"{}", qos=1)  # temp injected -> passes
        pkt = await sub.recv_publish()
        assert json.loads(pkt.payload)["temp"] == 0
        await pub.disconnect()
        await sub.disconnect()
        await srv.stop()

    run(t())
