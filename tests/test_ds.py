"""Durable storage tests: native backend vs in-memory oracle
(differential, the emqx_ds_storage_reference pattern), crash recovery,
iterator value semantics, message codec round-trip."""

import random

import pytest

from emqx_tpu.ds import LocalStorage, ReferenceStorage
from emqx_tpu.ds.api import decode_message, encode_message
from emqx_tpu.message import Message


def make_msgs(rng, n, t0=1_700_000_000.0):
    msgs = []
    for i in range(n):
        depth = rng.randint(1, 4)
        topic = "/".join(
            rng.choice(["fleet", "dev", "a", "b", "x7"]) for _ in range(depth)
        )
        msgs.append(
            Message(
                topic=topic,
                payload=f"payload-{i}".encode(),
                qos=rng.randint(0, 2),
                retain=rng.random() < 0.1,
                from_client=f"c{i % 7}",
                timestamp=t0 + i * 0.001,
                properties={"user_property": [("k", str(i))]}
                if rng.random() < 0.3
                else {},
            )
        )
    return msgs


def drain(store, flt, start_us=0, page=7):
    """Replay every matching message via get_streams + paged next."""
    out = []
    for stream in store.get_streams(flt, start_us):
        it = store.make_iterator(stream, flt, start_us)
        while True:
            it, msgs = store.next(it, page)
            if not msgs:
                break
            out.extend(msgs)
    return sorted((m.topic, m.payload) for m in out)


@pytest.mark.parametrize("seed", [0, 1])
def test_local_matches_reference_oracle(tmp_path, seed):
    rng = random.Random(seed)
    msgs = make_msgs(rng, 300)
    local = LocalStorage(str(tmp_path / "ds"), n_streams=8)
    oracle = ReferenceStorage(n_streams=8)
    # interleave batches
    for i in range(0, len(msgs), 37):
        batch = msgs[i : i + 37]
        local.store_batch(batch)
        oracle.store_batch(batch)
    for flt in ("#", "fleet/#", "dev/+", "a/b", "+/+/x7", "nomatch/+"):
        assert drain(local, flt) == drain(oracle, flt), flt
    local.close()


def test_crash_recovery_reopen(tmp_path):
    d = str(tmp_path / "ds")
    rng = random.Random(42)
    msgs = make_msgs(rng, 100)
    store = LocalStorage(d, n_streams=4)
    store.store_batch(msgs, sync=True)
    before = drain(store, "#")
    assert len(before) == 100
    store.close()

    # reopen: log recovery rebuilds the index
    store2 = LocalStorage(d, n_streams=4)
    assert drain(store2, "#") == before
    store2.close()


def test_torn_tail_truncated(tmp_path):
    d = str(tmp_path / "ds")
    store = LocalStorage(d, n_streams=2)
    store.store_batch(make_msgs(random.Random(1), 20), sync=True)
    store.close()

    # corrupt the tail: append garbage bytes to the newest segment
    import glob
    import os

    seg = sorted(glob.glob(os.path.join(d, "seg-*.log")))[-1]
    with open(seg, "ab") as f:
        f.write(b"\x13\x00\x00\x00GARBAGE-NOT-A-RECORD")
    store2 = LocalStorage(d, n_streams=2)
    assert len(drain(store2, "#")) == 20  # garbage dropped, data intact
    # and appends still work after truncation
    store2.store_batch(make_msgs(random.Random(2), 5))
    assert len(drain(store2, "#")) == 25
    store2.close()


def test_iterator_resume_is_value_typed(tmp_path):
    """An IterRef serialized to JSON and restored must resume exactly
    (the persistent-session checkpoint requirement)."""
    from emqx_tpu.ds.api import IterRef

    store = LocalStorage(str(tmp_path / "ds"), n_streams=1)
    msgs = [
        Message(topic="s/1", payload=str(i).encode(), timestamp=1000.0 + i)
        for i in range(10)
    ]
    store.store_batch(msgs)
    [stream] = store.get_streams("s/1")
    it = store.make_iterator(stream, "s/1", 0)
    it, got1 = store.next(it, 4)
    token = it.to_json()  # checkpoint

    it2 = IterRef.from_json(token)
    it2, got2 = store.next(it2, 100)
    assert [m.payload for m in got1] == [b"0", b"1", b"2", b"3"]
    assert [m.payload for m in got2] == [str(i).encode() for i in range(4, 10)]
    store.close()


def test_start_time_filtering(tmp_path):
    store = LocalStorage(str(tmp_path / "ds"), n_streams=1)
    msgs = [
        Message(topic="t/x", payload=str(i).encode(), timestamp=100.0 + i)
        for i in range(10)
    ]
    store.store_batch(msgs)
    [stream] = store.get_streams("t/x")
    it = store.make_iterator(stream, "t/x", int(105.0 * 1e6))
    _, got = store.next(it, 100)
    assert [m.payload for m in got] == [str(i).encode() for i in range(5, 10)]
    store.close()


def test_message_codec_roundtrip():
    msg = Message(
        topic="a/b/c",
        payload=b"\x00\x01binary",
        qos=2,
        retain=True,
        from_client="client-1",
        from_username="user-1",
        properties={
            "message_expiry_interval": 60,
            "correlation_data": b"\xff\x00",
            "user_property": [("a", "b")],
        },
    )
    out = decode_message(encode_message(msg))
    assert out.topic == msg.topic
    assert out.payload == msg.payload
    assert out.qos == 2 and out.retain and not out.dup
    assert out.from_client == "client-1"
    assert out.from_username == "user-1"
    assert out.mid == msg.mid
    assert abs(out.timestamp - msg.timestamp) < 1e-6
    assert out.properties == msg.properties

    anon = Message(topic="t", payload=b"", from_username=None)
    assert decode_message(encode_message(anon)).from_username is None


def test_segment_rolling(tmp_path):
    """Small seg_bytes forces multiple segments; replay still ordered."""
    d = str(tmp_path / "ds")
    store = LocalStorage(d, n_streams=1, seg_bytes=2048)
    msgs = [
        Message(topic="r/s", payload=bytes(200), timestamp=1.0 + i)
        for i in range(50)
    ]
    store.store_batch(msgs, sync=True)
    import glob
    import os

    assert len(glob.glob(os.path.join(d, "seg-*.log"))) > 1
    [stream] = store.get_streams("r/s")
    it = store.make_iterator(stream, "r/s", 0)
    _, got = store.next(it, 1000)
    assert [m.timestamp for m in got] == [1.0 + i for i in range(50)]
    store.close()
    # recovery across segments
    store2 = LocalStorage(d, n_streams=1, seg_bytes=2048)
    assert len(drain(store2, "#")) == 50
    store2.close()


def test_gc_reclaims_old_segments(tmp_path):
    """Code-review r2: retention GC drops whole segments older than the
    cutoff and the data survives consistently."""
    d = str(tmp_path / "ds")
    store = LocalStorage(d, n_streams=1, seg_bytes=2048)
    old = [
        Message(topic="g/s", payload=bytes(300), timestamp=100.0 + i)
        for i in range(20)
    ]
    new = [
        Message(topic="g/s", payload=bytes(300), timestamp=5000.0 + i)
        for i in range(20)
    ]
    store.store_batch(old, sync=True)
    store.store_batch(new, sync=True)
    import glob
    import os

    n_seg_before = len(glob.glob(os.path.join(d, "seg-*.log")))
    assert n_seg_before > 2
    dropped = store.gc(int(1000.0 * 1e6))
    assert dropped > 0
    n_seg_after = len(glob.glob(os.path.join(d, "seg-*.log")))
    assert n_seg_after < n_seg_before
    # every new-era message still replays; the dropped ones are gone
    remaining = drain(store, "#")
    assert len(remaining) == 40 - dropped
    [stream] = store.get_streams("g/s")
    it = store.make_iterator(stream, "g/s", int(5000.0 * 1e6))
    _, got = store.next(it, 100)
    assert len(got) == 20
    store.close()
    # recovery after GC is clean
    store2 = LocalStorage(d, n_streams=1, seg_bytes=2048)
    it = store2.make_iterator(
        store2.get_streams("g/s")[0], "g/s", int(5000.0 * 1e6)
    )
    _, got2 = store2.next(it, 100)
    assert len(got2) == 20
    store2.close()


def test_stale_census_rebuilt(tmp_path):
    """Code-review r2: a census cache that disagrees with the log (crash
    after save) must be rebuilt, not trusted."""
    import json
    import os

    d = str(tmp_path / "ds")
    store = LocalStorage(d, n_streams=4)
    store.store_batch(
        [Message(topic="a/b", payload=b"1", timestamp=1.0)], sync=True
    )
    store.close()

    # simulate a crash AFTER census save but with extra appends: write
    # more data via a second handle, then restore the stale census file
    with open(os.path.join(d, "census.json")) as f:
        stale = f.read()
    store2 = LocalStorage(d, n_streams=4)
    store2.store_batch(
        [Message(topic="c/d", payload=b"2", timestamp=2.0)], sync=False
    )
    store2._log.sync()
    store2._log.close()
    with open(os.path.join(d, "census.json"), "w") as f:
        f.write(stale)  # stale: doesn't know about c/d

    store3 = LocalStorage(d, n_streams=4)
    # wildcard filter must find c/d even though the stale census lacked it
    assert ("c/d", b"2") in drain(store3, "c/+")
    store3.close()
