"""Raft-consensus cluster mode end to end (the VERDICT r3 quorum
criteria at the BROKER level): a 3-node cluster with consensus="raft"
streams QoS1 publishes into a detached persistent session, the
session's home/leader node is killed mid-stream, and every PUBACKed
message is delivered after the client reconnects elsewhere — plus
cluster config updates resolving deterministically through the
replicated log."""

import asyncio
import tempfile

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.cluster import ClusterNode
from emqx_tpu.config import BrokerConfig
from mqtt_client import TestClient


FAST = dict(
    heartbeat_interval=0.05, down_after=0.3, flush_interval=0.002,
    consensus="raft", raft_fsync=False,
)


def run(coro):
    return asyncio.run(coro)


async def start_node(name, seeds=(), durable=True):
    cfg = BrokerConfig()
    cfg.listeners[0].port = 0
    if durable:
        cfg.durable.enable = True
        cfg.durable.data_dir = tempfile.mkdtemp(prefix=f"raft-ds-{name}-")
    srv = BrokerServer(cfg)
    await srv.start()
    node = ClusterNode(
        name, srv.broker,
        raft_data_dir=tempfile.mkdtemp(prefix=f"raft-{name}-"),
        **FAST,
    )
    return srv, node


async def boot_cluster(n=3):
    servers, nodes = [], []
    for i in range(n):
        srv, node = await start_node(f"n{i}")
        await node.transport.start()  # learn the port before seeding
        servers.append(srv)
        nodes.append(node)
    seeds = [(f"n{i}", "127.0.0.1", nodes[i].transport.port)
             for i in range(n)]
    for i, node in enumerate(nodes):
        await node.start(
            seeds=[s for j, s in enumerate(seeds) if j != i]
        )
    # wait for both raft groups to elect
    for group in ("raft_conf", "raft_ds"):
        deadline = asyncio.get_event_loop().time() + 5
        while asyncio.get_event_loop().time() < deadline:
            if any(getattr(nd, group).role == "leader" for nd in nodes):
                break
            await asyncio.sleep(0.02)
        else:
            raise AssertionError(f"no {group} leader")
    return servers, nodes


def test_acked_qos1_survives_leader_kill():
    async def t():
        servers, nodes = await boot_cluster(3)
        killed = set()
        try:
            # a persistent subscriber parks a detached session on n0
            sub = TestClient(servers[0].listeners[0].port, "psub")
            await sub.connect(
                clean_start=False,
                properties={"session_expiry_interval": 300},
            )
            await sub.subscribe("jobs/#", qos=1)
            await sub.disconnect(
                properties={"session_expiry_interval": 300}
            )
            await asyncio.sleep(0.2)  # registry + checkpoint settle

            # stream acked QoS1 publishes from n1; kill the DS
            # leader's node mid-stream (often n0, the session's home)
            pub = TestClient(servers[1].listeners[0].port, "pp")
            await pub.connect()
            acked = []
            for i in range(30):
                await pub.publish(f"jobs/{i}", str(i).encode(), qos=1,
                                  timeout=15)
                acked.append(i)  # PUBACK received => quorum-committed
                if i == 14:
                    victim = next(
                        k for k, nd in enumerate(nodes)
                        if nd.raft_ds.role == "leader"
                    )
                    if victim == 1:  # keep the publisher's node alive
                        await pub.close()
                    killed.add(victim)
                    await nodes[victim].stop()
                    await servers[victim].stop()
                    if victim == 1:
                        alive = next(
                            k for k in range(3) if k not in killed
                        )
                        pub = TestClient(
                            servers[alive].listeners[0].port, "pp2"
                        )
                        await pub.connect()
                    # quorum survives: the stream continues below
            await pub.close()

            # reconnect the subscriber on a SURVIVING node that is not
            # the session's home: restore must come from the quorum
            # replicas
            target = next(
                k for k in (2, 1, 0)
                if k not in killed and k != 0
            )
            sub2 = TestClient(
                servers[target].listeners[0].port, "psub"
            )
            ack = await sub2.connect(clean_start=False)
            got = set()
            deadline = asyncio.get_event_loop().time() + 10
            while len(got) < len(acked) and \
                    asyncio.get_event_loop().time() < deadline:
                try:
                    m = await sub2.recv_publish(timeout=2)
                except asyncio.TimeoutError:
                    break
                got.add(int(m.payload))
            missing = [i for i in acked if i not in got]
            assert not missing, f"ACKED messages lost: {missing}"
            await sub2.close()
        finally:
            for k in range(3):
                if k not in killed:
                    await nodes[k].stop()
                    await servers[k].stop()

    run(t())


def test_conf_updates_converge_through_log():
    async def t():
        servers, nodes = await boot_cluster(3)
        try:
            # concurrent conflicting writes to one path from two nodes
            nodes[0].update_config("mqtt.max_qos_allowed", 1)
            nodes[1].update_config("mqtt.max_qos_allowed", 2)
            await asyncio.sleep(0.5)
            finals = {
                srv.broker.config.mqtt.max_qos_allowed
                for srv in servers
            }
            assert len(finals) == 1, finals  # deterministic winner
            # and a follower-originated update lands everywhere
            nodes[2].update_config("mqtt.max_inflight", 7)
            await asyncio.sleep(0.5)
            assert all(
                srv.broker.config.mqtt.max_inflight == 7
                for srv in servers
            )
        finally:
            for k in range(3):
                await nodes[k].stop()
                await servers[k].stop()

    run(t())
