"""Kafka producer bridge (emqx_tpu/kafka.py) against an in-repo fake
Kafka broker speaking the real wire protocol (Metadata v1 + Produce v3
with magic-2 record batches) — the reference's flagship integration
(/root/reference/apps/emqx_bridge_kafka/src/emqx_bridge_kafka.erl)
proven at the resource/buffer-worker depth: batching, partitioning,
retriable-error recovery, and backpressure."""

import asyncio
import struct

from emqx_tpu.kafka import (
    KafkaClient,
    KafkaProducerResource,
    crc32c,
    decode_batch_record_count,
    encode_record_batch,
    murmur2,
)
from emqx_tpu.resources import BufferWorker


def run(coro):
    return asyncio.run(coro)


def _string(s):
    if s is None:
        return struct.pack(">h", -1)
    b = s.encode()
    return struct.pack(">h", len(b)) + b


class FakeKafka:
    """Minimal broker: leader of every partition of every topic.
    Knobs: ``fail_partition`` (error code, n_times) injection and a
    ``stall_produce`` event to wedge produce handling."""

    def __init__(self, n_partitions=2):
        self.n_partitions = n_partitions
        self.server = None
        self.port = 0
        self.records = {}  # (topic, partition) -> [batch bytes]
        self.produce_count = 0
        self.fail = {}  # partition -> [error_code, remaining]
        self.stalled = False

    async def start(self):
        self.server = await asyncio.start_server(
            self._conn, "127.0.0.1", 0
        )
        self.port = self.server.sockets[0].getsockname()[1]

    async def stop(self):
        self.server.close()
        await self.server.wait_closed()

    def total_records(self):
        return sum(
            decode_batch_record_count(b)
            for batches in self.records.values()
            for b in batches
        )

    async def _conn(self, r, w):
        try:
            while True:
                raw = await r.readexactly(4)
                (size,) = struct.unpack(">i", raw)
                req = await r.readexactly(size)
                api, ver, corr = struct.unpack_from(">hhi", req, 0)
                off = 8
                (cl,) = struct.unpack_from(">h", req, off)
                off += 2 + max(cl, 0)
                if api == 3:
                    resp = self._metadata(req, off)
                elif api == 0:
                    if self.stalled:
                        await asyncio.sleep(30)
                        continue
                    resp = self._produce(req, off)
                else:
                    continue
                payload = struct.pack(">i", corr) + resp
                w.write(struct.pack(">i", len(payload)) + payload)
                await w.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            w.close()

    def _metadata(self, req, off):
        (n,) = struct.unpack_from(">i", req, off)
        off += 4
        topics = []
        for _ in range(n):
            (ln,) = struct.unpack_from(">h", req, off)
            off += 2
            topics.append(req[off:off + ln].decode())
            off += ln
        out = bytearray()
        out += struct.pack(">i", 1)  # one broker: us
        out += struct.pack(">i", 0) + _string("127.0.0.1")
        out += struct.pack(">i", self.port) + _string(None)
        out += struct.pack(">i", 0)  # controller
        out += struct.pack(">i", len(topics))
        for t in topics:
            out += struct.pack(">h", 0) + _string(t) + b"\x00"
            out += struct.pack(">i", self.n_partitions)
            for p in range(self.n_partitions):
                out += struct.pack(">h", 0)   # partition error
                out += struct.pack(">i", p)   # partition id
                out += struct.pack(">i", 0)   # leader = broker 0
                out += struct.pack(">ii", 1, 0)  # replicas [0]
                out += struct.pack(">ii", 1, 0)  # isr [0]
        return bytes(out)

    def _produce(self, req, off):
        self.produce_count += 1
        (tx,) = struct.unpack_from(">h", req, off)
        off += 2 + max(tx, 0)
        _acks, _tmo = struct.unpack_from(">hi", req, off)
        off += 6
        (n_topics,) = struct.unpack_from(">i", req, off)
        off += 4
        results = []
        for _ in range(n_topics):
            (ln,) = struct.unpack_from(">h", req, off)
            off += 2
            topic = req[off:off + ln].decode()
            off += ln
            (n_parts,) = struct.unpack_from(">i", req, off)
            off += 4
            parts = []
            for _ in range(n_parts):
                (pid,) = struct.unpack_from(">i", req, off)
                off += 4
                (blen,) = struct.unpack_from(">i", req, off)
                off += 4
                batch = req[off:off + blen]
                off += blen
                err = 0
                inj = self.fail.get(pid)
                if inj and inj[1] > 0:
                    err, inj[1] = inj[0], inj[1] - 1
                else:
                    self.records.setdefault(
                        (topic, pid), []
                    ).append(batch)
                parts.append((pid, err))
            results.append((topic, parts))
        out = bytearray()
        out += struct.pack(">i", len(results))
        for topic, parts in results:
            out += _string(topic)
            out += struct.pack(">i", len(parts))
            for pid, err in parts:
                out += struct.pack(">ihqq", pid, err, 0, -1)
        out += struct.pack(">i", 0)  # throttle
        return bytes(out)


# ----------------------------------------------------------- unit bits

def test_crc32c_vectors():
    # RFC 3720 test vectors
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes(32)) == 0x8A9136AA


def test_record_batch_shape():
    batch = encode_record_batch([(b"k1", b"v1"), (None, b"v2")])
    assert decode_batch_record_count(batch) == 2
    # crc covers attributes..end and must verify
    crc_off = 8 + 4 + 4 + 1
    (crc,) = struct.unpack_from(">I", batch, crc_off)
    assert crc == crc32c(batch[crc_off + 4:])
    # magic 2
    assert batch[8 + 4 + 4] == 2


def test_murmur2_is_stable_and_spreads():
    vals = {murmur2(f"key-{i}".encode()) % 8 for i in range(64)}
    assert len(vals) >= 4  # spreads over partitions
    assert murmur2(b"abc") == murmur2(b"abc")


# ------------------------------------------------------------- e2e path

def test_produce_end_to_end_with_keys():
    async def t():
        fk = FakeKafka(n_partitions=3)
        await fk.start()
        res = KafkaProducerResource(
            [("127.0.0.1", fk.port)], topic="mqtt-data"
        )
        worker = BufferWorker(res, health_interval=0.2)
        await worker.start()
        assert worker.status == "connected"
        for i in range(100):
            # half keyed (stable partition), half round-robin
            if i % 2:
                worker.enqueue((f"dev-{i % 5}", f"payload-{i}"))
            else:
                worker.enqueue(f"payload-{i}")
        deadline = asyncio.get_event_loop().time() + 5
        while asyncio.get_event_loop().time() < deadline:
            if fk.total_records() >= 100:
                break
            await asyncio.sleep(0.05)
        assert fk.total_records() == 100
        assert res.stats["produced"] == 100
        # all records of one key land in ONE partition
        key_part = murmur2(b"dev-1") % 3
        assert ("mqtt-data", key_part) in fk.records
        await worker.stop()
        await fk.stop()

    run(t())


def test_retriable_partition_error_recovers_without_loss():
    async def t():
        fk = FakeKafka(n_partitions=2)
        await fk.start()
        fk.fail[0] = [6, 2]  # NOT_LEADER twice for partition 0
        res = KafkaProducerResource(
            [("127.0.0.1", fk.port)], topic="t"
        )
        worker = BufferWorker(res, health_interval=0.1)
        await worker.start()
        for i in range(40):
            worker.enqueue((f"k{i % 8}", f"m{i}"))
        deadline = asyncio.get_event_loop().time() + 8
        while asyncio.get_event_loop().time() < deadline:
            if fk.total_records() >= 40:
                break
            await asyncio.sleep(0.05)
        # exactly-once per record at the fake: no loss, no duplicates
        assert fk.total_records() == 40
        assert res.stats["partition_retries"] > 0
        assert res.stats["abandoned"] == 0
        await worker.stop()
        await fk.stop()

    run(t())


def test_backpressure_bounded_buffer_drops_oldest():
    async def t():
        fk = FakeKafka(n_partitions=1)
        await fk.start()
        res = KafkaProducerResource([("127.0.0.1", fk.port)], topic="t")
        worker = BufferWorker(res, max_buffer=50, health_interval=0.2)
        await worker.start()
        fk.stalled = True  # sink wedged: buffer takes the pressure
        await asyncio.sleep(0.1)
        for i in range(300):
            worker.enqueue(f"m{i}")
        assert len(worker) <= 51  # bounded (one may be in flight)
        assert worker.stats["dropped"] >= 240
        fk.stalled = False
        # the stalled produce's connection is wedged ~30s; the worker's
        # retry path reconnects and drains the surviving tail
        deadline = asyncio.get_event_loop().time() + 10
        while asyncio.get_event_loop().time() < deadline:
            if fk.total_records() >= 40:
                break
            await asyncio.sleep(0.1)
        assert fk.total_records() >= 40
        await worker.stop()
        await fk.stop()

    run(t())


def test_rule_action_into_kafka():
    """Full path: MQTT publish -> rule SELECT -> SinkAction -> buffer
    worker -> Kafka record on the fake broker."""

    async def t():
        from emqx_tpu.broker.broker import Broker
        from emqx_tpu.config import BrokerConfig
        from emqx_tpu.message import Message
        from emqx_tpu.rules.engine import SinkAction

        fk = FakeKafka(n_partitions=2)
        await fk.start()
        broker = Broker(BrokerConfig())
        res = KafkaProducerResource(
            [("127.0.0.1", fk.port)], topic="rules-out"
        )
        await broker.resources.create("kafka0", res)
        broker.rules.add_rule(
            "r1",
            'SELECT payload, topic FROM "sensors/#"',
            [SinkAction(resource_id="kafka0")],
        )
        broker.publish(Message(topic="sensors/1/temp", payload=b"21.5"))
        deadline = asyncio.get_event_loop().time() + 5
        while asyncio.get_event_loop().time() < deadline:
            if fk.total_records() >= 1:
                break
            await asyncio.sleep(0.05)
        assert fk.total_records() == 1
        blob = b"".join(
            b for bs in fk.records.values() for b in bs
        )
        assert b"21.5" in blob and b"sensors/1/temp" in blob
        await broker.resources.stop_all()
        await fk.stop()

    run(t())


def test_config_declared_kafka_sink_boots():
    """cfg.sinks entry of type kafka starts with the broker server and
    is addressable from rules by id (the emqx_bridge boot path)."""

    async def t():
        from emqx_tpu.broker.listener import BrokerServer
        from emqx_tpu.config import BrokerConfig, ListenerConfig
        from emqx_tpu.message import Message
        from emqx_tpu.rules.engine import SinkAction

        fk = FakeKafka(n_partitions=1)
        await fk.start()
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.sinks = [{
            "id": "kbridge",
            "type": "kafka",
            "bootstrap": [["127.0.0.1", fk.port]],
            "topic": "boot-out",
        }]
        srv = BrokerServer(cfg)
        await srv.start()
        assert srv.broker.resources.get("kbridge") is not None
        srv.broker.rules.add_rule(
            "r1", 'SELECT payload FROM "b/#"',
            [SinkAction(resource_id="kbridge")],
        )
        srv.broker.publish(Message(topic="b/1", payload=b"hello"))
        deadline = asyncio.get_event_loop().time() + 5
        while asyncio.get_event_loop().time() < deadline:
            if fk.total_records() >= 1:
                break
            await asyncio.sleep(0.05)
        assert fk.total_records() == 1
        await srv.stop()
        await fk.stop()

    run(t())


def test_requests_pipeline_on_one_connection():
    """PR 3 burn-down: requests no longer serialize on a lock held
    across the full round-trip.  The server here collects TWO complete
    requests before answering either (impossible under the old lock —
    the second frame was only written after the first response), then
    answers in REVERSE order to prove responses demultiplex by
    correlation id, not arrival order."""

    async def t():
        conns = []

        async def handler(r, w):
            conns.append(w)
            corrs = []
            for _ in range(2):
                raw = await r.readexactly(4)
                (size,) = struct.unpack(">i", raw)
                req = await r.readexactly(size)
                _api, _ver, corr = struct.unpack_from(">hhi", req, 0)
                corrs.append(corr)
            for corr in reversed(corrs):
                payload = struct.pack(">ii", corr, corr)
                w.write(struct.pack(">i", len(payload)) + payload)
            await w.drain()

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        client = KafkaClient("127.0.0.1", port)
        r1, r2 = await asyncio.wait_for(
            asyncio.gather(
                client.request(0, 0, b""), client.request(0, 0, b"")
            ),
            5.0,
        )
        # each caller got ITS body back despite reversed responses
        assert struct.unpack(">i", r1)[0] == 1
        assert struct.unpack(">i", r2)[0] == 2
        assert len(conns) == 1  # both rode one pipelined connection
        client.close()
        server.close()
        await server.wait_closed()

    run(t())


def test_connection_loss_fails_pending_requests():
    """A dead connection must fail every in-flight future (the reader
    pump's teardown), not leave callers hanging until their timeout."""

    async def t():
        async def handler(r, w):
            await r.readexactly(4)  # swallow, never answer

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        client = KafkaClient("127.0.0.1", port)
        stuck = asyncio.ensure_future(
            client.request(0, 0, b"", timeout=30.0)
        )
        await asyncio.sleep(0.05)
        assert not stuck.done()
        client._w.close()  # connection dies under the pending request
        try:
            await asyncio.wait_for(stuck, 5.0)
            assert False, "expected the pending request to fail"
        except ConnectionError:
            pass
        client.close()
        server.close()
        await server.wait_closed()

    run(t())


def test_client_redials_after_connection_loss():
    """The reader pump tears the transport down with itself: after a
    server-side close, the NEXT request must re-dial and succeed
    instead of registering in an unpumped map and hanging."""

    async def t():
        fk = FakeKafka(n_partitions=1)
        await fk.start()
        client = KafkaClient("127.0.0.1", fk.port)
        assert (await client.metadata(["t"]))["topics"]["t"] == {0: 0}
        # kill the live connection server-side and let the pump die
        first_w = client._w
        fk.server.close()
        await fk.server.wait_closed()
        first_w.close()
        await asyncio.sleep(0.05)
        assert not client.connected  # pump teardown closed the writer
        await fk.start()  # server back (new port)
        client.port = fk.port
        md = await asyncio.wait_for(client.metadata(["t"]), 5.0)
        assert md["topics"]["t"] == {0: 0}
        client.close()
        await fk.stop()

    run(t())
