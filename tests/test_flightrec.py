"""Flight recorder (flightrec.py): the always-on black-box.

Covers the four ISSUE acceptance behaviors:

* the bounded ring + trigger debounce (a p99 storm mints ONE dump, not
  N — the storm rule) and the SLO sensor's delta-window semantics;
* torn-dump recovery: a dump file caught mid-replace by a crash
  (crashsim's meta materializer) self-identifies via the atomicio CRC
  wrapper and is SKIPPED-and-counted, never merged, never fatal;
* the recorder-armed dispatch path is bit-identical per connection to
  recorder-off (observability must not change behavior);
* the chaos scenario: an injected service loss while a worker is
  attached yields EXACTLY ONE correlated capture — the worker's ring
  and the (restarted) service's ring under the SAME trigger id, merged
  into one Perfetto timeline with distinct per-process tracks.
"""

import asyncio
import json
import os
import threading
import time

import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu import flightrec
from emqx_tpu.broker import shmring
from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.matchclient import ServiceMatchEngine
from emqx_tpu.broker.session import SubOpts
from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig, ListenerConfig, check_config
from emqx_tpu.message import Message
from emqx_tpu.metrics import Metrics
from emqx_tpu.observability import Histogram
from emqx_tpu.ops.matchsvc import MatchService
from tools.crashsim import CrashRecorder, materialize


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


def wait_until(cond, timeout=10.0, what="condition"):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < timeout, f"timeout: {what}"
        time.sleep(0.01)


# --------------------------------------------------- ring + debounce

def test_ring_bounded_and_ordered():
    r = flightrec.FlightRecorder(process_label="t", ring_size=64)
    for i in range(1000):
        r.record(flightrec.EV_RING, float(i))
    tid = r.trigger("manual", force=True)
    (doc,) = r.local_dumps(tid)
    events = [e for e in doc["events"] if e[1] == flightrec.EV_RING]
    # bounded: only the NEWEST ring_size survive, oldest -> newest
    assert len(events) <= 64
    vals = [e[2] for e in events]
    assert vals == sorted(vals) and vals[-1] == 999.0
    assert r.status()["events_recorded"] == 1001  # total, not resident
    r.stop()


def test_trigger_debounce_storm_mints_one_dump():
    m = Metrics()
    r = flightrec.FlightRecorder(
        process_label="t", ring_size=64, min_dump_interval=60.0,
        metrics=m,
    )
    ids = [r.trigger("slo_breach") for _ in range(10)]
    minted = [i for i in ids if i]
    assert len(minted) == 1
    st = r.status()
    assert st["triggers"] == 1
    assert st["triggers_suppressed"] == 9
    assert len(r.local_dumps()) == 1
    assert m.val("flight.triggers") == 1
    assert m.val("flight.triggers.suppressed") == 9
    # manual force bypasses the debounce (ctl flight dump)
    assert r.trigger("manual", force=True)
    assert len(r.local_dumps()) == 2
    r.stop()


class _FakeProf:
    """snapshots()-shaped stand-in: one e2e histogram."""

    def __init__(self):
        self.h = Histogram()

    def snapshots(self):
        return {"e2e": self.h.snapshot()}


def test_slo_breach_delta_window_one_dump_per_storm():
    prof = _FakeProf()
    r = flightrec.FlightRecorder(
        process_label="t", slo_p99_ms={"e2e": 1.0},
        min_dump_interval=60.0,
    )
    r.tick(profiler=prof)          # baseline snapshot: no prev delta
    assert not r.local_dumps()
    for _ in range(100):
        prof.h.record(50_000.0)    # 50 ms >> the 1 ms SLO
    r.tick(profiler=prof)          # breach over THIS interval
    assert r.status()["triggers"] == 1
    (doc,) = r.local_dumps()
    assert doc["reason"] == "slo_breach"
    assert any(n["kind"] == "slo_breach" and n["stage"] == "e2e"
               for n in doc["notes"])
    # the storm keeps breaching every tick; the debounce holds at one
    for _ in range(5):
        for _ in range(50):
            prof.h.record(50_000.0)
        r.tick(profiler=prof)
    st = r.status()
    assert st["triggers"] == 1 and st["triggers_suppressed"] >= 1
    # quiet interval (delta count == 0): no new breach recorded
    r.tick(profiler=prof)
    r.stop()


def test_config_validation():
    cfg = BrokerConfig()
    cfg.flight.slo_p99_ms = {"e2e": 5.0}
    assert not check_config(cfg)
    cfg.flight.slo_p99_ms = {"nope": 5.0}
    assert any("unknown profiler stage" in p for p in check_config(cfg))
    cfg.flight.slo_p99_ms = {"e2e": -1}
    assert any("must be > 0" in p for p in check_config(cfg))
    cfg.flight.slo_p99_ms = {}
    cfg.flight.ring_size = 8
    assert any("ring_size" in p for p in check_config(cfg))


# ------------------------------------------------- dump files + merge

def test_dump_files_collect_and_perfetto_merge(tmp_path):
    dump_dir = str(tmp_path / "flight")
    w = flightrec.FlightRecorder(
        process_label="w0", role="broker", dump_dir=dump_dir, pid=111)
    s = flightrec.FlightRecorder(
        process_label="matchsvc", role="matchsvc", dump_dir=dump_dir,
        pid=222)
    w.record(flightrec.EV_RING, 3.0, 4.0)
    s.record(flightrec.EV_SVC_WINDOW, 7.0)
    tid = w.trigger("manual", force=True)
    assert s.dump_remote(tid, "manual")
    assert s.dump_remote(tid, "manual") is False  # idempotent per id
    names = sorted(os.listdir(dump_dir))
    assert names == [
        flightrec.dump_filename(tid, "matchsvc", 222),
        flightrec.dump_filename(tid, "w0", 111),
    ]
    rows = flightrec.list_dump_ids(dump_dir)
    assert len(rows) == 1 and rows[0]["id"] == tid
    assert len(rows[0]["files"]) == 2
    docs, torn = flightrec.collect_dumps(w, tid)
    assert torn == 0
    assert {(d["role"], d["pid"]) for d in docs} == {
        ("broker", 111), ("matchsvc", 222)}
    trace = flightrec.merge_dumps(docs)
    evs = trace["traceEvents"]
    tracks = {e["args"]["name"]: e["pid"] for e in evs
              if e.get("name") == "process_name"}
    assert tracks == {"w0 [broker pid=111]": 111,
                      "matchsvc [matchsvc pid=222]": 222}
    by_pid = {e["pid"] for e in evs if e.get("ph") == "i"}
    assert by_pid == {111, 222}
    w.stop()
    s.stop()


def test_torn_dump_recovery_via_crashsim(tmp_path):
    """A crash mid-replace of the SECOND process's dump file: the
    surviving prefix is a torn document; collect_dumps counts it and
    merges from the intact process only — alarmed conservative
    recovery, never a parse crash, never a silent half-merge."""
    src = tmp_path / "live"
    src.mkdir()
    dump_dir = str(src / "flight")
    w = flightrec.FlightRecorder(
        process_label="w0", role="broker", dump_dir=dump_dir, pid=11)
    s = flightrec.FlightRecorder(
        process_label="matchsvc", role="matchsvc", dump_dir=dump_dir,
        pid=22)
    w.record(flightrec.EV_RING, 1.0)
    cr = CrashRecorder()
    with cr:
        tid = w.trigger("manual", force=True)
        assert s.dump_remote(tid, "manual")
    meta_idx = [i for i, op in enumerate(cr.ops) if op.kind == "meta"]
    assert len(meta_idx) == 2
    out = tmp_path / "crashed"
    # crash AT the service's dump write: rename persisted, data pages
    # torn at byte 40 — the atomicio CRC wrapper's detection case
    materialize(cr.ops, meta_idx[1], str(src), str(out),
                torn_bytes=40, meta_variant="replaced-torn")
    crashed = str(out / "flight")
    docs, torn = flightrec.collect_dumps(None, tid, dump_dir=crashed)
    assert torn == 1
    assert len(docs) == 1 and docs[0]["pid"] == 11
    trace = flightrec.merge_dumps(docs)
    assert any(e.get("name") == "process_name"
               for e in trace["traceEvents"])
    w.stop()
    s.stop()


# ------------------------------------- armed dispatch is bit-identical

def _fanout_wire(flight_on):
    """256-subscriber QoS1 fanout; returns {clientid: wire bytes}."""
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.flight.enable = flight_on
    cfg.flight.slo_p99_ms = {"e2e": 0.0001}  # hair trigger
    cfg.flight.min_dump_interval = 0.0
    b = Broker(config=cfg)
    wires = {}
    for i in range(256):
        cid = f"c{i}"
        wires[cid] = bytearray()

        def send(pkts, _w=wires[cid]):
            for p in pkts:
                _w += C.serialize(p, C.MQTT_V5)

        ch = Channel(b, send=send, close=lambda r: None)
        session, _ = b.cm.open_session(True, cid, ch, max_inflight=0)
        session.subscribe("fan/fl", SubOpts(qos=1))
        b.subscribe(cid, "fan/fl", SubOpts(qos=1))
    for w0 in range(0, 192, 64):
        msgs = [Message(topic="fan/fl", payload=b"x" * 64, qos=1,
                        timestamp=1000.0 + w0 + k)
                for k in range(64)]
        b.publish_many(msgs)
        # the 1 Hz tick path (SLO checks, samplers) between windows
        b.flight.tick(profiler=b.profiler)
    if flight_on:
        # a mid-run capture must not perturb the wire either
        assert b.flight.status()["triggers"] >= 1 or \
            b.flight.trigger("manual", force=True)
    b.flight.stop()
    return {k: bytes(v) for k, v in wires.items()}


def test_recorder_armed_dispatch_bit_identical():
    on = _fanout_wire(True)
    off = _fanout_wire(False)
    assert on.keys() == off.keys()
    for cid in on:
        assert on[cid] == off[cid], f"wire divergence for {cid}"
    # and the armed run actually recorded window events
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    b = Broker(config=cfg)
    assert b.profiler.flight is b.flight


# ------------------------------------------- cross-process chaos

class _SvcThread:
    """Real MatchService on a real unix socket in a daemon thread,
    with its own flight recorder (the service process's black box)."""

    def __init__(self, socket_path, flight=None):
        self.socket_path = socket_path
        self.flight = flight
        self.svc = None
        self._loop = None
        self._stop_ev = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        asyncio.run(self._main())

    async def _main(self):
        self._loop = asyncio.get_running_loop()
        self._stop_ev = asyncio.Event()
        self.svc = MatchService(
            self.socket_path, use_device=False, flight=self.flight)
        await self.svc.start()
        self._started.set()
        await self._stop_ev.wait()
        await self.svc.stop()

    def start(self):
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._thread.start()
        assert self._started.wait(10), "service failed to start"
        return self

    def stop(self):
        loop = self._loop
        if loop is not None and loop.is_running():
            loop.call_soon_threadsafe(self._stop_ev.set)
        self._thread.join(10)
        assert not self._thread.is_alive(), "service thread hung"


def _attach_engine(sock, **kw):
    kw.setdefault("reconnect_backoff", 0.05)
    eng = ServiceMatchEngine(sock, worker_id=0, **kw)
    wait_until(lambda: eng.attached, what="client attach")
    return eng


def test_chaos_service_restart_exactly_one_correlated_dump(tmp_path):
    sock = str(tmp_path / "svc.sock")
    dump_dir = str(tmp_path / "flight")
    svc1 = _SvcThread(sock, flight=flightrec.FlightRecorder(
        process_label="matchsvc", role="matchsvc", dump_dir=dump_dir,
        pid=501)).start()
    eng = _attach_engine(sock)
    wfl = flightrec.FlightRecorder(
        process_label="w0", role="broker", dump_dir=dump_dir, pid=401)
    eng.flight = wfl
    eng.metrics = Metrics()
    wfl.on_trigger = eng.flight_broadcast
    svc2 = None
    try:
        wfl.record(flightrec.EV_RING, 1.0, 2.0)
        # the injected anomaly: the service dies under an attached
        # worker (multicore.service.restart in production terms)
        svc1.stop()
        # wait for the dump FILE, not just the trigger counter: the
        # counter bumps before the reader thread finishes the write
        wait_until(
            lambda: sum(
                len(r["files"]) for r in flightrec.list_dump_ids(dump_dir)
            ) == 1,
            what="worker-side service_restart trigger + dump")
        assert wfl.status()["triggers"] == 1
        tid = wfl.status()["last_id"]
        assert "service-restart" in tid or "service_restart" in tid
        # worker's own dump is the only file; the broadcast is QUEUED
        # (the anomaly IS the lost connection)
        assert len(flightrec.list_dump_ids(dump_dir)) == 1
        # the restarted service re-attaches the worker, which flushes
        # the queued "dump now" line -> the service dumps THE SAME id
        svc2 = _SvcThread(sock, flight=flightrec.FlightRecorder(
            process_label="matchsvc", role="matchsvc",
            dump_dir=dump_dir, pid=502)).start()
        wait_until(lambda: eng.attached, what="re-attach")
        # wait on list_dump_ids, not os.listdir: the latter counts
        # atomicio's transient .tmp file before the rename lands
        wait_until(
            lambda: sum(
                len(r["files"]) for r in flightrec.list_dump_ids(dump_dir)
            ) == 2,
            what="service-side correlated dump")
        rows = flightrec.list_dump_ids(dump_dir)
        assert len(rows) == 1 and rows[0]["id"] == tid, rows
        assert len(rows[0]["files"]) == 2
        # exactly one: no second id minted anywhere, ever
        assert wfl.status()["triggers"] == 1
        docs, torn = flightrec.collect_dumps(wfl, tid)
        assert torn == 0
        assert {(d["role"], d["pid"]) for d in docs} == {
            ("broker", 401), ("matchsvc", 502)}
        trace = flightrec.merge_dumps(docs)
        names = {e["args"]["name"] for e in trace["traceEvents"]
                 if e.get("name") == "process_name"}
        assert names == {"w0 [broker pid=401]",
                         "matchsvc [matchsvc pid=502]"}
    finally:
        eng.close()
        wfl.stop()
        if svc2 is not None:
            svc2.stop()


def test_matchsvc_counters_histograms_and_pong(tmp_path):
    sock = str(tmp_path / "svc.sock")
    svc = _SvcThread(sock).start()
    eng = _attach_engine(sock)
    try:
        info = {}
        pending = eng.match_batch_submit(["a/b", "c/d"])
        eng.match_batch_finish(pending, info=info)
        assert info.get("path", "svc") == "svc"
        eng.poll_service()
        wait_until(
            lambda: eng.poll_service() and (
                (eng.service_info()["service"].get("stats") or {})
                .get("windows", 0) >= 1),
            what="pong carries service counters")
        remote = eng.service_info()["service"]
        assert remote["stats"]["topics"] >= 2
        assert remote["stats"]["errors"] == 0
        assert set(remote["hist"]) == {"unpack", "match", "decide",
                                       "pack"}
        assert remote["hist"]["match"]["count"] >= 1
        assert remote["flight"] == {}  # service ran without a recorder
        # worker-side ring occupancy surface rides the same info dict
        ring = eng.service_info()["ring"]
        assert ring["slots"] >= 1 and ring["free"] == ring["slots"]
        assert ring["high_watermark"] >= 1
    finally:
        eng.close()
        svc.stop()


def test_shmring_stats_name_full_and_oversize():
    ring = shmring.WindowRing.create(slots=2, slot_bytes=4096)
    try:
        a = ring.acquire()
        ring.acquire()
        st = ring.stats()
        assert st["in_flight"] == 2 and st["high_watermark"] == 2
        with pytest.raises(shmring.RingFull) as ei:
            ring.acquire()
        # the degrade path names WHICH ring and at what depth
        assert ring.stats()["name"] in str(ei.value)
        assert "all 2 slots" in str(ei.value)
        assert ring.stats()["full"] == 1
        with pytest.raises(ValueError) as ei:
            ring.write(a, epoch=1, seq=1,
                       kind=shmring.KIND_MATCH_REQ,
                       parts=(b"x" * 8192,))
        assert ring.stats()["name"] in str(ei.value)
        assert ring.stats()["oversize"] == 1
    finally:
        ring.close()


# ---------------------------------------------------------- REST

def test_rest_flight_surface(tmp_path):
    async def t():
        from api_helper import auth_session

        from emqx_tpu.broker.listener import BrokerServer

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.engine.use_device = False
        cfg.api.enable = True
        cfg.api.port = 0
        cfg.api.data_dir = str(tmp_path / "api")
        cfg.flight.dump_dir = str(tmp_path / "flight")
        srv = BrokerServer(cfg)
        await srv.start()
        try:
            http, api = await auth_session(srv)
            async with http:
                async with http.get(api + "/api/v5/flight") as r:
                    assert r.status == 200
                    info = await r.json()
                    assert info["status"]["armed"]
                    assert info["dumps"] == []
                async with http.post(api + "/api/v5/flight/dump") as r:
                    assert r.status == 200
                    tid = (await r.json())["id"]
                async with http.get(api + f"/api/v5/flight/{tid}") as r:
                    assert r.status == 200
                    doc = await r.json()
                    assert doc["id"] == tid and doc["torn"] == 0
                    assert doc["processes"][0]["role"] == "broker"
                    assert doc["trace"]["traceEvents"]
                async with http.get(api + "/api/v5/flight/nope") as r:
                    assert r.status == 404
                # the olp satellite: transitions ride /api/v5/olp
                async with http.get(api + "/api/v5/olp") as r:
                    assert "transitions" in await r.json()
                async with http.get(api + "/metrics") as r:
                    text = await r.text()
                    assert "emqx_flight_triggers" in text
        finally:
            await srv.stop()

    asyncio.run(t())
