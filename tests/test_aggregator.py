"""Record aggregation (emqx_connector_aggregator parity): rule output
batches into time-bucketed JSONL/CSV objects, flushed by record cap,
byte cap, or interval, delivered to batch sinks (incl. S3)."""

import asyncio
import json

from emqx_tpu.aggregator import Aggregator


def test_flush_by_record_cap_jsonl():
    out = []
    agg = Aggregator(lambda k, b: out.append((k, b)), name="tele",
                     interval_s=3600, max_records=3)
    agg.push([{"a": 1}, {"a": 2}])
    assert not out
    agg.push([{"a": 3}])
    assert len(out) == 1
    key, body = out[0]
    assert key.startswith("tele/") and key.endswith("/0.jsonl")
    rows = [json.loads(l) for l in body.decode().splitlines()]
    assert rows == [{"a": 1}, {"a": 2}, {"a": 3}]
    # next bucket gets the next sequence number
    agg.push([{"a": 4}, {"a": 5}, {"a": 6}])
    assert out[1][0].endswith("/1.jsonl")


def test_flush_by_interval_tick_and_csv_columns():
    out = []
    agg = Aggregator(lambda k, b: out.append((k, b)), name="csvagg",
                     container="csv", interval_s=10,
                     column_order=["ts", "topic"])
    agg.push([{"ts": 1, "topic": "a/b", "temp": 20}])
    agg.push([{"ts": 2, "topic": "a/c", "hum": 50}])
    assert not agg.tick(now=agg._bucket_start + 5)
    assert agg.tick(now=agg._bucket_start + 11)
    body = out[0][1].decode().splitlines()
    # fixed columns first, extras in first-seen order; missing -> empty
    assert body[0] == "ts,topic,temp,hum"
    assert body[1] == "1,a/b,20,"
    assert body[2] == "2,a/c,,50"


def test_rule_to_aggregator_to_s3(tmp_path):
    """Full path: SQL rule -> AggregateAction -> flush -> S3 object."""
    from aiohttp import web

    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.config import BrokerConfig, ListenerConfig
    from emqx_tpu.resources import BufferWorker
    from emqx_tpu.rules.engine import AggregateAction
    from emqx_tpu.s3 import S3Client, S3Sink
    from mqtt_client import TestClient
    from test_s3 import _verify_sigv4

    async def t():
        objects = {}

        async def handle(request):
            body = await request.read()
            if not _verify_sigv4("sk", request.headers, request.method,
                                 request.path, body):
                return web.Response(status=403)
            if request.method == "PUT":
                objects[request.path] = body
                return web.Response(status=200)
            return web.Response(status=404)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handle)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        srv = BrokerServer(cfg)
        await srv.start()
        broker = srv.broker

        worker = await broker.resources.create(
            "agg:s3",
            S3Sink(S3Client(f"http://127.0.0.1:{port}", "lake",
                            "AK", "sk", region="local")),
        )
        agg = Aggregator(worker.enqueue2 if hasattr(worker, "enqueue2")
                         else (lambda k, b: worker.enqueue((k, b))),
                         name="fleet", max_records=2, interval_s=3600)
        broker.aggregators.append(agg)
        broker.rules.add_rule(
            "r-agg",
            'SELECT payload.v as v, topic FROM "tele/#"',
            actions=[AggregateAction(aggregator=agg)],
        )

        c = TestClient(srv.listeners[0].port, "agg-pub")
        await c.connect()
        await c.publish("tele/d1", json.dumps({"v": 1}).encode())
        await c.publish("tele/d2", json.dumps({"v": 2}).encode())

        key = None
        for _ in range(100):
            hit = [k for k in objects if k.startswith("/lake/fleet/")]
            if hit:
                key = hit[0]
                break
            await asyncio.sleep(0.05)
        assert key, objects.keys()
        rows = [json.loads(l) for l in objects[key].decode().splitlines()]
        assert sorted(r["v"] for r in rows) == [1, 2]
        assert all(r["topic"].startswith("tele/") for r in rows)

        await c.disconnect()
        await srv.stop()
        await runner.cleanup()

    asyncio.run(t())
