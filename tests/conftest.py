"""Test configuration: force JAX onto a virtual 8-device CPU mesh so
sharding tests run without TPU hardware (mirrors the driver's
dryrun_multichip environment).

The container pre-imports jax via sitecustomize with JAX_PLATFORMS set
to the real TPU tunnel, so mutating os.environ alone is too late — the
config value must be updated as well (safe while no backend is
initialized).  Benchmarks (bench.py), not tests, use the real chip."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
