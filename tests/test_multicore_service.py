"""Multicore match service (layer-1/layer-2 split): the shared-memory
window ring, the wire codec, and the worker<->service protocol.

The correctness anchor is the REFEREE PROPERTY: a worker's windows
served by the shared service must be bit-identical to the same windows
served by a plain single-process ``MatchEngine`` — under sub/unsub
churn, rule fids, shared subscriptions, injected faults on every
``multicore.*`` failpoint seam, ring exhaustion, service crash, and
service restart.  Any ring trouble may change the PATH (svc →
host-fallback) but never the RESULT, and never leaks a ring slot.

Plus the hostile-schedule regressions for the handoff seams (racesim):
a late doorbell after a worker re-hello superseded its connection, a
service stop racing an in-flight window, and the resume-shard
invariant (a foreign-shard worker never checkpoints) under
disconnect/reconnect interleaving.
"""

import asyncio
import itertools
import os
import random
import socket
import threading
import time

import numpy as np
import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.broker import shmring
from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.matchclient import ServiceMatchEngine
from emqx_tpu.broker.multicore import PortReservation, free_ports
from emqx_tpu.broker.resume import shard_of
from emqx_tpu.broker.session import SubOpts
from emqx_tpu.config import BrokerConfig
from emqx_tpu.engine import MatchEngine
from emqx_tpu.message import Message
from emqx_tpu.ops import matchsvc as wire
from emqx_tpu.ops.matchsvc import MatchService
from tools.racesim import run_seeds


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


def wait_until(cond, timeout=10.0, what="condition"):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < timeout, f"timeout: {what}"
        time.sleep(0.01)


# ------------------------------------------------- in-process service

class SvcThread:
    """A real `MatchService` on a real unix socket, its event loop in
    a daemon thread — so the thread-based `ServiceMatchEngine` client
    talks to it exactly as a worker process would, without spawning
    processes (the cth-cluster pattern one layer down)."""

    def __init__(self, socket_path, engine_kw=None):
        self.socket_path = socket_path
        self.engine_kw = engine_kw
        self.svc = None
        self._loop = None
        self._stop_ev = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        asyncio.run(self._main())

    async def _main(self):
        self._loop = asyncio.get_running_loop()
        self._stop_ev = asyncio.Event()
        self.svc = MatchService(
            self.socket_path, use_device=False,
            engine_kw=self.engine_kw,
        )
        await self.svc.start()
        self._started.set()
        await self._stop_ev.wait()
        await self.svc.stop()

    def start(self):
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._thread.start()
        assert self._started.wait(10), "service failed to start"
        return self

    def stop(self):
        loop = self._loop
        if loop is not None and loop.is_running():
            loop.call_soon_threadsafe(self._stop_ev.set)
        self._thread.join(10)
        assert not self._thread.is_alive(), "service thread hung"


def _attach_engine(sock, **kw):
    kw.setdefault("reconnect_backoff", 0.05)
    eng = ServiceMatchEngine(sock, worker_id=0, **kw)
    wait_until(lambda: eng.attached, what="client attach")
    return eng


def _match_via(eng, topics):
    """One window through the submit/finish pipeline (the executor-
    thread path the broker batcher drives), returning (result, path)."""
    info = {}
    pending = eng.match_batch_submit(topics)
    out = eng.match_batch_finish(pending, info=info)
    return out, info.get("path", pending[0])


# ------------------------------------------------------ ring + ports

def test_port_reservation_holds_ports_until_release():
    """The TOCTOU fix: a reserved port stays BOUND (a rival bind
    fails) until its owner's release, then binds cleanly."""
    res = PortReservation(2)
    try:
        port = res.ports[0]
        rival = socket.socket()
        with pytest.raises(OSError):
            rival.bind(("127.0.0.1", port))
        rival.close()
        res.release(port)
        owner = socket.socket()
        owner.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        owner.bind(("127.0.0.1", port))  # the worker's real bind
        owner.close()
        assert len(set(res.ports)) == 2
    finally:
        res.release_all()
    # the compatibility probe still hands back distinct ports
    ports = free_ports(3)
    assert len(set(ports)) == 3


def test_ring_acquire_release_and_full():
    ring = shmring.WindowRing.create(slots=2, slot_bytes=4096)
    try:
        a, b = ring.acquire(), ring.acquire()
        assert {a, b} == {0, 1}
        with pytest.raises(shmring.RingFull):
            ring.acquire()
        ring.release(a)
        ring.release(a)  # double release is idempotent
        assert ring.free_slots() == 1
        assert ring.acquire() == a
        ring.release(a)
        ring.release(b)
    finally:
        ring.close()


def test_ring_write_read_roundtrip_and_stale_rejection():
    ring = shmring.WindowRing.create(slots=2, slot_bytes=4096)
    try:
        n = ring.write(0, epoch=3, seq=7, kind=shmring.KIND_MATCH_REQ,
                       parts=(b"abc", b"def"))
        assert n == 6
        kind, payload = ring.read(0, 3, 7)
        assert kind == shmring.KIND_MATCH_REQ and payload == b"abcdef"
        # a stale (epoch, seq) — a dead incarnation's leftover — is
        # rejected, never misread as the current window's response
        assert ring.read(0, 2, 7) is None
        assert ring.read(0, 3, 8) is None
        with pytest.raises(ValueError):
            ring.write(0, 3, 8, shmring.KIND_MATCH_REQ,
                       (b"x" * (ring.payload_capacity + 1),))
    finally:
        ring.close()


def test_ring_attach_sees_owner_writes():
    owner = shmring.WindowRing.create(slots=4, slot_bytes=4096)
    try:
        svc_side = shmring.WindowRing.attach(owner.name)
        assert (svc_side.slots, svc_side.slot_bytes) == (4, 4096)
        owner.write(2, 1, 5, shmring.KIND_MATCH_REQ, (b"hello",))
        assert svc_side.read(2, 1, 5) == (shmring.KIND_MATCH_REQ,
                                          b"hello")
        # response written back through the attached side, same slot
        svc_side.write(2, 1, 5, shmring.KIND_MATCH_RESP, (b"resp",))
        assert owner.read(2, 1, 5) == (shmring.KIND_MATCH_RESP, b"resp")
        svc_side.close()
    finally:
        owner.close()


# ------------------------------------------------------- wire codec

def test_wire_match_roundtrip():
    topics = ["a/b", "", "x/" + "y" * 300, "ünï/ço∂é"]
    payload = b"".join(wire.pack_match_req(topics, True))
    assert wire.unpack_match_req(payload) == (topics, True)

    id_sets = [[3, 1, 2], [], [7], list(range(50))]
    resp = b"".join(wire.pack_match_resp(id_sets))
    rows = wire.unpack_match_resp(resp)
    assert [sorted(int(x) for x in r) for r in rows] == [
        sorted(s) for s in id_sets
    ]


def test_wire_decide_roundtrip():
    rng = np.random.default_rng(0)
    r, n, b = 16, 40, 8
    cols = (
        rng.integers(0, 3, r).astype(np.int8),
        rng.random(r) < 0.3, rng.random(r) < 0.3, rng.random(r) < 0.1,
    )
    rows = (
        rng.integers(0, r, n).astype(np.int64),
        rng.integers(0, 50, n).astype(np.int64),
        rng.integers(0, b, n).astype(np.int64),
        rng.integers(0, 3, b).astype(np.int8),
        rng.random(b) < 0.5,
        rng.integers(-1, 50, b).astype(np.int32),
    )
    for send_cols in (cols, None):
        payload = b"".join(wire.pack_decide_req(send_cols, 9, *rows))
        got = wire.unpack_decide_req(payload)
        if send_cols is None:
            assert got[0] is None
        else:
            for mine, theirs in zip(cols, got[0]):
                np.testing.assert_array_equal(np.asarray(mine),
                                              np.asarray(theirs))
        assert got[1] == 9
        for mine, theirs in zip(rows, got[2:]):
            np.testing.assert_array_equal(np.asarray(mine),
                                          np.asarray(theirs))

    packed = rng.integers(0, 255, n).astype(np.uint8)
    for path in ("dev", "host"):
        out, p = wire.unpack_decide_resp(
            b"".join(wire.pack_decide_resp(packed, path))
        )
        np.testing.assert_array_equal(out, packed)
        assert p == path


# ----------------------------------------- the referee property

_FILTERS = ["t/#", "t/+/x", "t/1/x", "s/only", "$share/g1/t/+/x",
            "a/b/c", "a/+/c", "a/#", "+/b/#", "deep/" + "l/" * 8 + "#"]
_TOPICS = ["t/1/x", "t/2/x", "s/only", "a/b/c", "a/z/c", "q/b/r",
           "deep/" + "l/" * 8 + "end", "none/of/these", "t/zzz"]


def _random_churn(eng, referee, rng, rounds):
    """Apply the same random sub/unsub churn (client fids, rule-tuple
    fids, shared subs) to the service-backed engine and the referee."""
    live = []
    for k in range(rounds):
        if live and rng.random() < 0.35:
            fid = live.pop(rng.randrange(len(live)))
            assert eng.delete(fid) == referee.delete(fid)
        else:
            flt = rng.choice(_FILTERS)
            fid = (("rule", f"r{k}", 0) if rng.random() < 0.2
                   else f"c{k}")
            eng.insert(flt, fid)
            referee.insert(flt, fid)
            live.append(fid)
    return live


def test_service_match_bit_identical_to_referee(tmp_path):
    """THE acceptance gate: sharded dispatch through the service is
    bit-identical to the single-process referee, across random churn,
    with every undisturbed window actually served by the service."""
    sock = str(tmp_path / "svc.sock")
    svc = SvcThread(sock).start()
    eng = _attach_engine(sock)
    referee = MatchEngine(use_device=False)
    rng = random.Random(4242)
    try:
        for _ in range(8):
            _random_churn(eng, referee, rng, rounds=12)
            topics = [rng.choice(_TOPICS) for _ in range(6)]
            out, path = _match_via(eng, topics)
            assert path == "svc"
            assert out == referee.match_batch(topics)
            # the loop-thread sync path stays pinned to the mirror
            # and agrees too
            assert eng.match_batch(topics) == referee.match_batch(topics)
        assert eng.svc_stats["windows"] == 8
        assert eng.svc_stats["fallbacks"] == 0
        assert eng._ring.free_slots() == eng._ring.slots
    finally:
        eng.close()
        svc.stop()


def test_route_delete_propagates_to_service(tmp_path):
    sock = str(tmp_path / "svc.sock")
    svc = SvcThread(sock).start()
    eng = _attach_engine(sock)
    try:
        eng.insert("gone/#", "g1")
        eng.insert("kept/#", "k1")
        out, path = _match_via(eng, ["gone/x", "kept/x"])
        assert path == "svc" and out == [{"g1"}, {"k1"}]
        assert eng.delete("g1")
        out, path = _match_via(eng, ["gone/x", "kept/x"])
        assert path == "svc" and out == [set(), {"k1"}]
        # deleting again reports absent on both sides
        assert not eng.delete("g1")
    finally:
        eng.close()
        svc.stop()


def test_decide_over_ring_bit_identical(tmp_path):
    """The decide kernel through the ring (cols shipped on first rev,
    cache-hit on the second window) equals the local referee."""
    sock = str(tmp_path / "svc.sock")
    svc = SvcThread(sock).start()
    eng = _attach_engine(sock)
    referee = MatchEngine(use_device=False)
    rng = np.random.default_rng(7)
    r, n, b = 32, 200, 16
    cols = (
        rng.integers(0, 3, r).astype(np.int8),
        rng.random(r) < 0.3, rng.random(r) < 0.3, rng.random(r) < 0.1,
    )
    try:
        for i in range(2):  # window 2 exercises the cols cache hit
            args = (
                rng.integers(0, r, n), rng.integers(0, 50, n),
                rng.integers(0, b, n),
                rng.integers(0, 3, b).astype(np.int8),
                rng.random(b) < 0.5,
                rng.integers(-1, 50, b).astype(np.int32),
            )
            got = eng._ring_decide(cols, 5, *args)
            assert got is not None, f"ring decide window {i} fell back"
            want, _ = referee.decide_window(cols, 5, *args)
            np.testing.assert_array_equal(got[0], want)
        assert eng.svc_stats["decides"] == 2
        assert eng._cols_sent_rev == 5
        assert eng._ring.free_slots() == eng._ring.slots
    finally:
        eng.close()
        svc.stop()


# -------------------------------------------- chaos: failpoint seams

def test_submit_seam_drop_falls_back_bit_identical(tmp_path):
    sock = str(tmp_path / "svc.sock")
    svc = SvcThread(sock).start()
    eng = _attach_engine(sock)
    referee = MatchEngine(use_device=False)
    try:
        _random_churn(eng, referee, random.Random(1), rounds=10)
        fp.configure("multicore.ring.submit", "drop")
        pending = eng.match_batch_submit(_TOPICS)
        assert pending[0] != "svc"  # window degraded at submit
        assert eng.match_batch_finish(pending) == \
            referee.match_batch(_TOPICS)
        assert eng._ring.free_slots() == eng._ring.slots
        fp.clear()
        _, path = _match_via(eng, _TOPICS)  # seam disarmed: svc again
        assert path == "svc"
    finally:
        eng.close()
        svc.stop()


def test_complete_seam_error_falls_back_without_slot_leak(tmp_path):
    """An injected completion fault degrades the window to the mirror
    AND quarantines-then-drains its slot: the late completion from the
    (healthy) service returns it to the free list."""
    sock = str(tmp_path / "svc.sock")
    svc = SvcThread(sock).start()
    eng = _attach_engine(sock)
    referee = MatchEngine(use_device=False)
    try:
        _random_churn(eng, referee, random.Random(2), rounds=10)
        fp.configure("multicore.ring.complete", "error")
        info = {}
        pending = eng.match_batch_submit(_TOPICS)
        assert pending[0] == "svc"  # submit succeeded; completion fails
        out = eng.match_batch_finish(pending, info=info)
        assert info["path"] == "host-fallback"
        assert out == referee.match_batch(_TOPICS)
        assert eng.svc_stats["fallbacks"] == 1
        fp.clear()
        # the service still served the window; its late completion
        # doorbell releases the quarantined slot — no leak
        wait_until(
            lambda: eng._ring.free_slots() == eng._ring.slots,
            what="abandoned slot drained by late completion",
        )
        _, path = _match_via(eng, _TOPICS)
        assert path == "svc"
    finally:
        eng.close()
        svc.stop()


def test_ring_full_degrades_window_in_process(tmp_path):
    sock = str(tmp_path / "svc.sock")
    svc = SvcThread(sock).start()
    eng = _attach_engine(sock)
    try:
        eng.insert("t/#", "c0")
        held = [eng._ring.acquire() for _ in range(eng._ring.slots)]
        out, path = _match_via(eng, ["t/x"])
        assert path != "svc" and out == [{"c0"}]
        assert eng.svc_stats["ring_full"] >= 1
        for s in held:
            eng._ring.release(s)
        out, path = _match_via(eng, ["t/x"])
        assert path == "svc" and out == [{"c0"}]
    finally:
        eng.close()
        svc.stop()


def test_oversize_window_degrades_in_process(tmp_path):
    sock = str(tmp_path / "svc.sock")
    svc = SvcThread(sock).start()
    eng = _attach_engine(sock, ring_slot_bytes=2048)
    try:
        eng.insert("big/#", "c0")
        topics = ["big/" + "x" * 200 for _ in range(40)]  # > slot
        out, path = _match_via(eng, topics)
        assert path != "svc"
        assert out == [{"c0"}] * len(topics)
        assert eng._ring.free_slots() == eng._ring.slots
    finally:
        eng.close()
        svc.stop()


# ------------------------------------- service crash / restart loop

def test_service_crash_fallback_then_reattach(tmp_path):
    """The availability story end-to-end: service dies → every window
    still served correctly from the mirror; service returns → client
    re-attaches, REPLAYS its full route set (including churn applied
    while detached), and serves via the service again."""
    sock = str(tmp_path / "svc.sock")
    svc = SvcThread(sock).start()
    eng = _attach_engine(sock)
    referee = MatchEngine(use_device=False)
    rng = random.Random(3)
    try:
        _random_churn(eng, referee, rng, rounds=10)
        _, path = _match_via(eng, _TOPICS)
        assert path == "svc"

        svc.stop()  # crash
        wait_until(lambda: not eng.attached, what="detach on EOF")
        # churn lands ONLY on the mirror while detached — the replay
        # must carry it to the next incarnation
        _random_churn(eng, referee, rng, rounds=10)
        out, path = _match_via(eng, _TOPICS)
        assert path != "svc"
        assert out == referee.match_batch(_TOPICS)

        svc2 = SvcThread(sock).start()
        try:
            wait_until(lambda: eng.attached, what="re-attach")
            out, path = _match_via(eng, _TOPICS)
            assert path == "svc"
            assert out == referee.match_batch(_TOPICS)
            assert eng.svc_stats["reconnects"] >= 2
            assert eng._ring.free_slots() == eng._ring.slots
        finally:
            svc2.stop()
    finally:
        eng.close()


def test_restart_during_inflight_window(tmp_path):
    """The hostile handoff: the doorbell is lost (swallowed send), the
    service dies while the window waits — the window must degrade to
    the mirror and the slot must come back when the incarnation
    provably dies (EOF detach), never leaking."""
    sock = str(tmp_path / "svc.sock")
    svc = SvcThread(sock).start()
    eng = _attach_engine(sock, rpc_timeout=30.0)
    referee = MatchEngine(use_device=False)
    try:
        _random_churn(eng, referee, random.Random(5), rounds=8)
        eng._send = lambda obj: True  # doorbell eaten by the "crash"
        pending = eng.match_batch_submit(_TOPICS)
        assert pending[0] == "svc"
        killer = threading.Timer(0.3, svc.stop)
        killer.start()
        info = {}
        out = eng.match_batch_finish(pending, info=info)
        killer.join()
        assert info["path"] == "host-fallback"
        assert out == referee.match_batch(_TOPICS)
        wait_until(lambda: eng._ring.free_slots() == eng._ring.slots,
                   what="in-flight slot released on detach")
    finally:
        eng.close()


def test_timeout_quarantines_slot_then_reattach_drains(tmp_path):
    """A timed-out window QUARANTINES its slot (a hung service may
    still write there) instead of freeing it; the next epoch bump
    proves the old incarnation dead and drains the quarantine."""
    sock = str(tmp_path / "svc.sock")
    svc = SvcThread(sock).start()
    eng = _attach_engine(sock, rpc_timeout=0.2)
    referee = MatchEngine(use_device=False)
    try:
        _random_churn(eng, referee, random.Random(6), rounds=8)
        eng._send = lambda obj: True  # service never hears the bell
        info = {}
        out = eng.match_batch_finish(
            eng.match_batch_submit(_TOPICS), info=info
        )
        assert info["path"] == "host-fallback"
        assert out == referee.match_batch(_TOPICS)
        # the slot is quarantined, NOT freed: the service (which this
        # client cannot prove dead) may still write there
        assert eng._ring.free_slots() == eng._ring.slots - 1
        with eng._lk:
            assert len(eng._abandoned) == 1

        svc.stop()  # EOF: incarnation provably dead → quarantine drains
        wait_until(lambda: eng._ring.free_slots() == eng._ring.slots,
                   what="quarantine drained")
    finally:
        eng.close()


# ------------------------------------------- broker-level chaos

def _broker_with_service(sock):
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.multicore.service_socket = sock
    cfg.multicore.worker_id = 0
    cfg.multicore.n_workers = 1
    return Broker(config=cfg)


class FakeChannel:
    def __init__(self):
        self.sent = []
        self.closed = None

    def send_packets(self, pkts):
        self.sent.extend(pkts)

    def close(self, reason):
        self.closed = reason


def test_broker_delivers_through_service_and_through_faults(tmp_path):
    """A worker Broker wired to the service delivers identically with
    the service healthy, with every multicore seam erroring, and with
    the service gone — the CPU-fallback acceptance invariant."""
    sock = str(tmp_path / "svc.sock")
    svc = SvcThread(sock).start()
    b = _broker_with_service(sock)
    eng = b.router.engine
    assert isinstance(eng, ServiceMatchEngine)
    wait_until(lambda: eng.attached, what="broker engine attach")
    try:
        for i in range(4):
            ch = FakeChannel()
            s, _ = b.cm.open_session(True, f"c{i}", ch)
            opts = SubOpts(qos=1)
            s.subscribe(f"mc/{i}/#", opts)
            b.subscribe(f"c{i}", f"mc/{i}/#", opts)

        def publish_all():
            return b.publish_many([
                Message(topic=f"mc/{i}/v", qos=1, payload=b"d")
                for i in range(4)
            ])

        assert publish_all() == [1] * 4  # healthy: via the service
        assert eng.svc_stats["windows"] >= 1

        fp.configure("multicore.ring.submit", "error")
        assert publish_all() == [1] * 4  # seam error: host fallback
        fp.clear()
        fp.configure("multicore.ring.complete", "error")
        assert publish_all() == [1] * 4
        fp.clear()

        svc.stop()  # service gone entirely
        wait_until(lambda: not eng.attached, what="detach")
        assert publish_all() == [1] * 4

        svc2 = SvcThread(sock).start()
        try:
            wait_until(lambda: eng.attached, what="re-attach")
            before = eng.svc_stats["windows"]
            assert publish_all() == [1] * 4
            assert eng.svc_stats["windows"] > before
            info = b.node_info()
            assert info["multicore"]["service"]["attached"] is True
        finally:
            svc2.stop()
    finally:
        b.shutdown()  # also closes the engine + unlinks the ring


# --------------------------------------------- resume shard homes

def test_shard_of_is_stable_and_covers_all_shards():
    # cross-process stability is the point: pin the exact hash rule
    import zlib

    for cid in ("veh-1", "ünïcode", ""):
        assert shard_of(cid, 4) == \
            zlib.crc32(cid.encode("utf-8")) % 4
    assert shard_of("anything", 1) == 0
    assert shard_of("anything", 0) == 0
    hit = {shard_of(f"client-{i}", 4) for i in range(200)}
    assert hit == {0, 1, 2, 3}


def _durable_cfg(data_dir, shard_index=0, shard_count=1):
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.durable.enable = True
    cfg.durable.data_dir = str(data_dir)
    cfg.durable.resume.shard_index = shard_index
    cfg.durable.resume.shard_count = shard_count
    return cfg


def _connect_durable(b, cid):
    ch = FakeChannel()
    s, _ = b.cm.open_session(False, cid, ch, expiry_interval=3600.0)
    opts = SubOpts(qos=1)
    s.subscribe("t/#", opts)
    b.subscribe(cid, "t/#", opts)
    return ch


def test_foreign_shard_worker_never_checkpoints(tmp_path):
    """Split-brain prevention: only the client's home shard writes its
    checkpoint; a foreign-shard worker counts + skips, so no two
    workers ever hold rival checkpoints for one client."""
    cid = "veh-1"
    home = shard_of(cid, 2)
    b = Broker(config=_durable_cfg(tmp_path / "w_foreign",
                                   shard_index=1 - home, shard_count=2))
    ch = _connect_durable(b, cid)
    assert not b.resume_home_shard(cid)
    b.cm.disconnect(cid, ch)
    b.channel_disconnected(cid)
    assert not os.path.exists(b.durable._state_path(cid))
    assert b.metrics.val("session.resume.foreign_shard") == 1
    b.durable.close()

    b2 = Broker(config=_durable_cfg(tmp_path / "w_home",
                                    shard_index=home, shard_count=2))
    ch2 = _connect_durable(b2, cid)
    assert b2.resume_home_shard(cid)
    b2.cm.disconnect(cid, ch2)
    b2.channel_disconnected(cid)
    assert os.path.exists(b2.durable._state_path(cid))
    assert b2.metrics.val("session.resume.foreign_shard") == 0
    b2.durable.close()


# --------------------------------------- racesim: handoff seams

class _StubWriter:
    def __init__(self):
        self.lines = []

    def write(self, data):
        self.lines.append(data)

    def close(self):
        pass


def _supersede_workload():
    """A worker re-hellos (service restarted from ITS point of view)
    while a doorbell from the superseded connection is still in
    flight: the late doorbell must degrade to an error completion,
    never touch the closed ring, and the new incarnation must win."""

    async def main():
        svc = MatchService("unused.sock", use_device=False)
        r1 = shmring.WindowRing.create(slots=2, slot_bytes=4096)
        r2 = shmring.WindowRing.create(slots=2, slot_bytes=4096)
        try:
            w_old = await svc._handle_hello(
                {"worker": 0, "epoch": 1, "ring": r1.name},
                _StubWriter(),
            )
            svc._apply_routes(w_old, [[0, "t/#"]], ())
            slot = r1.acquire()
            r1.write(slot, 1, 1, shmring.KIND_MATCH_REQ,
                     wire.pack_match_req(["t/x"], False))

            async def supersede():
                await asyncio.sleep(0)
                await svc._handle_hello(
                    {"worker": 0, "epoch": 2, "ring": r2.name},
                    _StubWriter(),
                )

            async def late_doorbell():
                await asyncio.sleep(0)
                out = svc._serve_window(w_old, slot, 1)
                assert out["t"] in ("c", "e")

            await asyncio.gather(supersede(), late_doorbell())
            assert svc._workers[0].epoch == 2
            # the superseded connection's routes were dropped with it;
            # only worker-0 state from the LIVE incarnation remains
            assert svc._workers[0].fids == set()
        finally:
            for w in list(svc._workers.values()):
                svc._drop_worker(w)
            r1.close()
            r2.close()

    return main()


def test_race_late_doorbell_after_supersede():
    for o in run_seeds(_supersede_workload, seeds=range(12)):
        assert not o.failed, (o.label, o.error)


def _stop_race_workload():
    """`MatchService.stop` racing an in-flight window: whatever the
    interleaving, the window completes or errors cleanly and stop
    leaves the service empty (no routes, no workers, rings closed)."""

    async def main():
        svc = MatchService("unused.sock", use_device=False)
        ring = shmring.WindowRing.create(slots=2, slot_bytes=4096)
        try:
            w = await svc._handle_hello(
                {"worker": 0, "epoch": 1, "ring": ring.name},
                _StubWriter(),
            )
            svc._apply_routes(w, [[0, "a/#"], [1, "b/#"]], ())
            slot = ring.acquire()
            ring.write(slot, 1, 1, shmring.KIND_MATCH_REQ,
                       wire.pack_match_req(["a/x", "b/y"], False))

            async def serve():
                await asyncio.sleep(0)
                out = svc._serve_window(w, slot, 1)
                assert out["t"] in ("c", "e")

            async def stop():
                await asyncio.sleep(0)
                await svc.stop()

            await asyncio.gather(serve(), stop())
            assert not svc._workers
            assert len(svc.engine) == 0
        finally:
            ring.close()

    return main()


def test_race_stop_during_inflight_window():
    for o in run_seeds(_stop_race_workload, seeds=range(12)):
        assert not o.failed, (o.label, o.error)


_shard_dirs = itertools.count()


def _shard_rebalance_workload(base_dir):
    """Disconnect-checkpoint racing a takeover reconnect on a FOREIGN
    shard worker: under every interleaving the foreign worker must
    never write a checkpoint (the home worker owns the one canonical
    copy)."""
    cid = "veh-race"
    foreign = 1 - shard_of(cid, 2)

    async def main():
        data_dir = os.path.join(base_dir, f"run{next(_shard_dirs)}")
        b = Broker(config=_durable_cfg(data_dir, shard_index=foreign,
                                       shard_count=2))
        try:
            ch = _connect_durable(b, cid)

            async def disconnect():
                await asyncio.sleep(0)
                b.cm.disconnect(cid, ch)
                await asyncio.sleep(0)
                b.channel_disconnected(cid)

            async def takeover():
                await asyncio.sleep(0)
                ch2 = FakeChannel()
                b.cm.open_session(False, cid, ch2,
                                  expiry_interval=3600.0)

            await asyncio.gather(disconnect(), takeover())
            assert not os.path.exists(b.durable._state_path(cid))
        finally:
            b.durable.close()

    return main()


def test_race_foreign_shard_disconnect_vs_takeover(tmp_path):
    outs = run_seeds(lambda: _shard_rebalance_workload(str(tmp_path)),
                     seeds=range(10))
    for o in outs:
        assert not o.failed, (o.label, o.error)


# ---------------------------------------------- merged nodes view

def test_node_info_carries_multicore_and_shard_surface(tmp_path):
    cfg = _durable_cfg(tmp_path / "ds", shard_index=1, shard_count=3)
    cfg.multicore.n_workers = 3
    cfg.multicore.worker_id = 1
    b = Broker(config=cfg)
    info = b.node_info()
    assert info["node_status"] == "running"
    assert info["multicore"] == {"worker_id": 1, "n_workers": 3}
    assert "durability" in info
    import json as _json

    _json.dumps(info)  # JSON-safe for the mgmt surface
    b.durable.close()


def test_merged_nodes_view_across_cluster(tmp_path):
    """ANY worker's api answers for the whole pool: its /api/v5/nodes
    row set carries every peer's node_info over the cluster RPC."""
    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.cluster import ClusterNode
    from emqx_tpu.config import ListenerConfig

    async def t():
        servers, nodes = [], []
        try:
            for i in range(2):
                cfg = BrokerConfig()
                cfg.engine.use_device = False
                cfg.listeners = [ListenerConfig(port=0)]
                cfg.node_name = f"worker{i}"
                cfg.multicore.n_workers = 2
                cfg.multicore.worker_id = i
                srv = BrokerServer(cfg)
                await srv.start()
                seeds = [("worker0", "127.0.0.1", nodes[0].port)] \
                    if nodes else []
                node = ClusterNode(
                    f"worker{i}", srv.broker,
                    heartbeat_interval=0.05, down_after=1.0,
                )
                await node.start(seeds=seeds)
                servers.append(srv)
                nodes.append(node)
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if nodes[0].peers_alive():
                    break
                await asyncio.sleep(0.05)
            rows = [servers[0].broker.node_info()]
            rows += await nodes[0].fetch_node_infos()
            names = {r["node"] for r in rows}
            assert names == {"worker0", "worker1"}
            for r in rows:
                assert r["node_status"] == "running"
                assert r["multicore"]["n_workers"] == 2
            assert {r["multicore"]["worker_id"] for r in rows} == {0, 1}
        finally:
            for node in reversed(nodes):
                await node.stop()
            for srv in reversed(servers):
                await srv.stop()

    asyncio.run(t())
