"""Rule engine tests: SQL parse, interpreter eval, function library,
broker integration through the shared match step, republish actions,
and batched-predicate equivalence against the interpreter oracle."""

import json
import random

import numpy as np
import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.session import SubOpts
from emqx_tpu.message import Message
from emqx_tpu.rules.engine import (
    FunctionAction,
    RepublishAction,
    RuleEngine,
    render_template,
)
from emqx_tpu.rules.predicate import compile_where
from emqx_tpu.rules.runtime import build_env, eval_expr, eval_select, eval_where
from emqx_tpu.rules.sql import SqlError, parse_sql


# ------------------------------------------------------------------ parse


def test_parse_basic():
    q = parse_sql('SELECT payload.x AS x, clientid FROM "t/#" WHERE x > 10')
    assert [f.alias or f.expr for f in q.fields] == ["x", ("var", ("clientid",))]
    assert q.froms == ["t/#"]
    assert q.where == ("op", ">", ("var", ("x",)), ("lit", 10))


def test_parse_star_and_multi_from():
    q = parse_sql('SELECT * FROM "a/+", "b"')
    assert q.fields[0].star and q.froms == ["a/+", "b"]
    assert q.where is None


def test_parse_precedence():
    q = parse_sql('SELECT * FROM "t" WHERE a = 1 OR b = 2 AND c = 3')
    assert q.where[1] == "or"
    assert q.where[3][1] == "and"
    q2 = parse_sql('SELECT * FROM "t" WHERE (a = 1 OR b = 2) AND c = 3')
    assert q2.where[1] == "and"


def test_parse_arith_in_case():
    q = parse_sql(
        'SELECT CASE WHEN qos = 0 THEN \'low\' ELSE \'hi\' END AS lvl '
        'FROM "t" WHERE qos + 1 * 2 IN (1, 3) AND NOT retain'
    )
    assert q.fields[0].expr[0] == "case"
    assert q.where[2][0] == "in"
    # 1*2 binds tighter than +
    assert q.where[2][1] == (
        "op", "+", ("var", ("qos",)), ("op", "*", ("lit", 1), ("lit", 2))
    )


def test_parse_errors():
    for bad in (
        "SELECT",
        'SELECT * FROM',
        'SELECT * FROM "t" WHERE',
        'SELECT * FROM "t" trailing',
        'SELECT * FROM "t" WHERE a in 1',
    ):
        with pytest.raises(SqlError):
            parse_sql(bad)


# ------------------------------------------------------------------- eval


def _env(payload=None, **over):
    msg = Message(
        topic=over.pop("topic", "dev/d1/temp"),
        payload=json.dumps(payload).encode() if payload is not None else b"",
        qos=over.pop("qos", 1),
        retain=over.pop("retain", False),
        from_client=over.pop("clientid", "c1"),
        from_username=over.pop("username", "u1"),
    )
    return build_env(msg)


def test_eval_where_payload_fields():
    env = _env(payload={"temp": 31.5, "ok": True, "tags": {"site": "x"}})
    assert eval_where(parse_sql('SELECT * FROM "t" WHERE payload.temp > 30').where, env)
    assert not eval_where(
        parse_sql('SELECT * FROM "t" WHERE payload.temp > 32').where, env
    )
    assert eval_where(parse_sql('SELECT * FROM "t" WHERE payload.ok').where, env)
    assert eval_where(
        parse_sql("SELECT * FROM \"t\" WHERE payload.tags.site = 'x'").where, env
    )


def test_eval_where_missing_field_is_false_but_shortcircuits():
    env = _env(payload={"a": 1})
    w1 = parse_sql('SELECT * FROM "t" WHERE payload.missing > 1').where
    assert not eval_where(w1, env)
    w2 = parse_sql(
        'SELECT * FROM "t" WHERE payload.a = 1 OR payload.missing > 1'
    ).where
    assert eval_where(w2, env)
    # errors on the left poison the whole predicate
    w3 = parse_sql(
        'SELECT * FROM "t" WHERE payload.missing > 1 OR payload.a = 1'
    ).where
    assert not eval_where(w3, env)


def test_eval_select_aliases_and_star():
    env = _env(payload={"t": 7})
    sql = parse_sql(
        'SELECT payload.t * 2 AS doubled, clientid, upper(username) FROM "t"'
    )
    out = eval_select(sql, env)
    assert out == {"doubled": 14, "clientid": "c1", "upper": "U1"}
    star = eval_select(parse_sql('SELECT * FROM "t"'), env)
    assert star["topic"] == "dev/d1/temp" and star["qos"] == 1


def test_eval_funcs():
    env = _env(payload={"s": "Hello World", "xs": [1, 2, 3]})
    cases = {
        "lower(payload.s)": "hello world",
        "strlen(payload.s)": 11,
        "substr(payload.s, 6)": "World",
        "nth(2, payload.xs)": 2,
        "concat('a', 'b', 1)": "ab1",
        "topic(1, 'x')": "1/x",
        "abs(0 - 5)": 5,
        "round(3.7)": 4,
        "max(1, 2, 3)": 3,
        "json_encode(payload.xs)": "[1, 2, 3]",
        "is_str(payload.s)": True,
        "contains(3, payload.xs)": True,
        "split('a,b,c', ',')": ["a", "b", "c"],
        "md5('abc')": "900150983cd24fb0d6963f7d28e17f72",
    }
    for src, want in cases.items():
        got = eval_expr(parse_sql(f'SELECT {src} FROM "t"').fields[0].expr, env)
        assert got == want, (src, got, want)


def test_like_operator():
    env = _env(topic="dev/d1/temp")
    assert eval_where(
        parse_sql("SELECT * FROM \"t\" WHERE topic LIKE 'dev/%/temp'").where, env
    )
    assert not eval_where(
        parse_sql("SELECT * FROM \"t\" WHERE topic LIKE 'dev/_/xx'").where, env
    )


# ---------------------------------------------------------------- broker


def test_rule_fires_through_broker_match():
    b = Broker()
    hits = []
    b.rules.add_rule(
        "r1",
        'SELECT payload.v AS v, topic FROM "sensors/+/temp" WHERE payload.v > 100',
        actions=[FunctionAction(lambda sel, msg: hits.append(sel))],
    )
    b.publish(Message(topic="sensors/s1/temp", payload=b'{"v": 150}'))
    b.publish(Message(topic="sensors/s1/temp", payload=b'{"v": 50}'))
    b.publish(Message(topic="other", payload=b'{"v": 999}'))
    assert len(hits) == 1 and hits[0]["v"] == 150
    rule = b.rules.rules["r1"]
    assert rule.matched == 2 and rule.passed == 1 and rule.failed == 1
    assert b.metrics.val("rules.matched") == 1
    assert b.metrics.val("actions.success") == 1


def test_rule_and_subscription_share_match_step():
    b = Broker()
    from tests_fakes import FakeChannel  # local helper below

    ch = FakeChannel()
    session, _ = b.cm.open_session(True, "c1", ch)
    session.subscribe("sensors/+/temp", SubOpts(qos=0))
    b.subscribe("c1", "sensors/+/temp", SubOpts(qos=0))
    fired = []
    b.rules.add_rule(
        "r",
        'SELECT * FROM "sensors/#"',
        actions=[FunctionAction(lambda sel, msg: fired.append(sel))],
    )
    n = b.publish(Message(topic="sensors/a/temp", payload=b"{}"))
    assert n == 1  # subscriber delivery count excludes rule hits
    assert len(ch.sent) == 1 and len(fired) == 1


def test_republish_action_and_loop_cap():
    b = Broker()
    from tests_fakes import FakeChannel

    ch = FakeChannel()
    session, _ = b.cm.open_session(True, "c1", ch)
    session.subscribe("alerts/#", SubOpts(qos=0))
    b.subscribe("c1", "alerts/#", SubOpts(qos=0))
    b.rules.add_rule(
        "alert",
        'SELECT payload.v AS v, topic FROM "sensors/+" WHERE payload.v > 10',
        actions=[
            RepublishAction(topic="alerts/${topic}", payload='{"v": ${v}}')
        ],
    )
    b.publish(Message(topic="sensors/s9", payload=b'{"v": 42}'))
    assert len(ch.sent) == 1
    assert ch.sent[0].topic == "alerts/sensors/s9"
    assert json.loads(ch.sent[0].payload) == {"v": 42}

    # a self-triggering rule must stop at the depth cap, not recurse
    b2 = Broker()
    b2.rules.add_rule(
        "loop",
        'SELECT topic FROM "loop/#"',
        actions=[RepublishAction(topic="loop/x", payload="again")],
    )
    b2.publish(Message(topic="loop/x", payload=b"start"))
    r = b2.rules.rules["loop"]
    assert r.actions_failed == 1  # the cap converts the loop into a failure
    assert r.passed <= 9


def test_rule_remove_and_disable():
    b = Broker()
    fired = []
    b.rules.add_rule(
        "r", 'SELECT * FROM "t"', actions=[FunctionAction(lambda s, m: fired.append(1))]
    )
    b.publish(Message(topic="t"))
    b.rules.enable_rule("r", False)
    b.publish(Message(topic="t"))
    assert len(fired) == 1
    b.rules.enable_rule("r", True)
    b.rules.remove_rule("r")
    b.publish(Message(topic="t"))
    assert len(fired) == 1
    assert b.router.engine.match_batch(["t"])[0] == set()


def test_render_template():
    data = {"a": {"b": 2}, "s": "x", "f": 3.0, "flag": True}
    assert render_template("${a.b}/${s}/${f}/${flag}/${nope}", data) == (
        "2/x/3/true/undefined"
    )


# ------------------------------------------------- batched predicates


def _random_env(rng):
    payload = {}
    if rng.random() < 0.9:
        payload["a"] = rng.choice([rng.randint(-5, 5), rng.uniform(-5, 5)])
    if rng.random() < 0.7:
        payload["b"] = rng.randint(0, 3)
    if rng.random() < 0.6:
        payload["s"] = rng.choice(["x", "y", "z"])
    return build_env(
        Message(
            topic=rng.choice(["t/1", "t/2"]),
            payload=json.dumps(payload).encode(),
            qos=rng.randint(0, 2),
            retain=bool(rng.getrandbits(1)),
            from_client=rng.choice(["c1", "c2"]),
        )
    )


_PREDICATES = [
    "payload.a > 0",
    "payload.a > payload.b",
    "payload.a + 1 >= payload.b * 2",
    "payload.s = 'x'",
    "payload.s != 'y'",
    "qos = 2 AND retain = 1 OR payload.b = 0",
    "NOT (payload.a > 0) AND payload.b <= 2",
    "payload.a = 1 OR payload.missing > 1",
    "payload.missing > 1 OR payload.a = 1",
    "qos IN (1, 2)",
    "payload.s IN ('x', 'q')",
    "payload.a / payload.b > 1",
    "payload.a div 2 = 1",
    "payload.a mod 2 = 0",
    "payload.a - 0.5 < payload.b OR payload.s = 'z' AND qos > 0",
]


@pytest.mark.parametrize("src", _PREDICATES)
def test_predicate_batch_equivalence(src):
    where = parse_sql(f'SELECT * FROM "t" WHERE {src}').where
    prog = compile_where(where)
    assert prog is not None, f"should compile: {src}"
    rng = random.Random(hash(src) & 0xFFFF)
    envs = [_random_env(rng) for _ in range(256)]
    got = prog.eval_batch(envs)
    want = np.array([eval_where(where, e) for e in envs])
    assert got.dtype == bool
    mismatch = np.nonzero(got != want)[0]
    assert mismatch.size == 0, (
        src,
        [envs[i]["payload"] for i in mismatch[:3]],
        got[mismatch[:3]],
        want[mismatch[:3]],
    )


def test_predicate_batch_jax_path():
    where = parse_sql(
        'SELECT * FROM "t" WHERE payload.a > 0 AND qos IN (1, 2)'
    ).where
    prog = compile_where(where)
    rng = random.Random(3)
    envs = [_random_env(rng) for _ in range(64)]
    got = prog.eval_batch(envs, use_jax=True)
    want = np.array([eval_where(where, e) for e in envs])
    assert (got == want).all()


def test_predicate_unsupported_falls_back():
    for src in (
        "lower(clientid) = 'c1'",
        "CASE WHEN qos = 0 THEN true ELSE false END",
    ):
        where = parse_sql(f'SELECT * FROM "t" WHERE {src}').where
        assert compile_where(where) is None


def test_predicate_total_equality_with_compound_side():
    """Review r2: `payload.s != qos + 1` with a string var must stay
    True (equality is total; only the compound side carries errors)."""
    where = parse_sql('SELECT * FROM "t" WHERE payload.s != qos + 1').where
    prog = compile_where(where)
    env = build_env(Message(topic="t", payload=b'{"s": "abc"}', qos=1))
    assert eval_where(where, env) is True
    assert prog.eval_batch([env])[0]
    # and an erroring compound side still poisons both polarities
    where2 = parse_sql(
        'SELECT * FROM "t" WHERE payload.missing + 1 != 5'
    ).where
    prog2 = compile_where(where2)
    assert eval_where(where2, env) is False
    assert not prog2.eval_batch([env])[0]


def test_predicate_timestamp_precision():
    """Review r2: millisecond timestamps exceed float32; the batch
    path must not lose the comparison."""
    where = parse_sql(
        'SELECT * FROM "t" WHERE timestamp > 1753000000100'
    ).where
    prog = compile_where(where)
    env = build_env(Message(topic="t"))
    env["timestamp"] = 1753000000200
    env2 = build_env(Message(topic="t"))
    env2["timestamp"] = 1753000000000
    got = prog.eval_batch([env, env2], use_jax=True)
    assert got.tolist() == [True, False]


def test_add_rule_invalid_sql_keeps_old_rule():
    b = Broker()
    b.rules.add_rule("r1", 'SELECT * FROM "t/#"')
    with pytest.raises(SqlError):
        b.rules.add_rule("r1", "SELECT FROM")
    assert "r1" in b.rules.rules
    assert b.router.engine.match_batch(["t/x"])[0] == {("rule", "r1", 0)}
    with pytest.raises(ValueError):
        b.rules.add_rule("r1", 'SELECT * FROM "bad/#/mid"')
    assert "r1" in b.rules.rules


def test_rule_fids_do_not_inflate_subscription_stat():
    b = Broker()
    b.rules.add_rule("r1", 'SELECT * FROM "t/#"')
    assert b.info()["subscriptions"] == 0


def test_compiled_where_error_vs_undefined_matches_interpreter():
    """Code-review r2: a lookup ERROR (non-JSON payload) must make the
    compiled WHERE false, exactly like the interpreter — distinct from
    a merely-missing field (which is total inequality)."""
    from emqx_tpu.message import Message

    for sql in (
        'SELECT * FROM "t" WHERE payload.x != 1',
        "SELECT * FROM \"t\" WHERE payload.x != 'y'",
        'SELECT * FROM "t" WHERE payload.x = 1 OR qos = 1',
    ):
        w = parse_sql(sql).where
        prog = compile_where(w)
        assert prog is not None, sql
        envs = [
            build_env(Message(topic="t", payload=b"hello", qos=1)),  # error
            build_env(Message(topic="t", payload=b'{"a": 2}', qos=1)),  # undef
            build_env(Message(topic="t", payload=b'{"x": 1}', qos=1)),
        ]
        want = [eval_where(w, e) for e in envs]
        got = prog.eval_batch(envs).tolist()
        assert got == want, (sql, got, want)


def test_compiled_where_arith_precision_matches_interpreter():
    """Code-review r2: f32 arithmetic results must not diverge from the
    float64 interpreter (16777216 + 1 == 16777216 in f32)."""
    w = parse_sql('SELECT * FROM "t" WHERE payload.a + 1 > 16777216').where
    prog = compile_where(w)
    envs = [_env(payload={"a": 16777216})]
    want = [eval_where(w, e) for e in envs]
    got = prog.eval_batch(envs, use_jax=True).tolist()
    assert got == want == [True]


def test_like_bracket_literal():
    """Code-review r2: '[' in a LIKE pattern is a literal, not a
    character class."""
    assert eval_where(
        parse_sql("SELECT * FROM \"t\" WHERE topic LIKE 'a[0]%'").where,
        build_env(Message(topic="a[0]x", payload=b"", qos=0)),
    )
    assert not eval_where(
        parse_sql("SELECT * FROM \"t\" WHERE topic LIKE 'a[0]%'").where,
        build_env(Message(topic="a0x", payload=b"", qos=0)),
    )


def test_engine_tuple_fids_survive_rebuild():
    """Code-review r2: all-tuple fids must stay a 1-D object array, not
    broadcast into a 2-D array that breaks device matching."""
    from emqx_tpu.engine import MatchEngine

    eng = MatchEngine(use_device=True)
    for i in range(5):
        eng.insert(f"r/{i}/+", ("rule", "r1", i))
    eng.rebuild()
    assert eng.match("r/3/x") == {("rule", "r1", 3)}
