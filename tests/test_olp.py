"""Coordinated overload protection (olp.py): the broker-wide load
ladder with QoS-aware shedding, admission clamps, and
hysteresis-driven recovery.

Four layers of coverage:

  * the LEVEL MACHINE driven with synthetic signal traces (pure
    ``observe`` with injected clocks): monotone one-step-down,
    immediate (possibly multi-step) up, min-hold, exit-factor
    hysteresis under square-wave load, seeded random-trace properties;
  * the LADDER EFFECTS, each against its real subsystem: L1 resume
    parking / retained deferral + flush / window shrink / rebuild
    deferral, L2 shed-mask parity vs the scalar referee (bit-identical
    wires across scalar / host-columns / device-columns with shedding
    active), listener bucket clamps, CONNECT budget; L3 ingress QoS0
    drop and slow-subscriber force-close;
  * the satellites: per-connection outbound high-watermark (stub
    transport + a REAL paused-transport regression) and AlarmRegistry
    flap damping (square-wave churn bounds);
  * the CHAOS gates: a publish flood plus slow-subscriber storm
    through ladder-up → responsive control plane → ladder-down, with
    zero QoS1 loss for admitted traffic; kill-mid-shed via the
    ``olp.shed`` panic; ``olp.sample`` faults hold the level (FP301
    coverage for both new seams).
"""

import asyncio
import time

import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.broker.broker import Broker, PublishBatcher
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.session import SubOpts
from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig, check_config
from emqx_tpu.limiter import ConnectionLimiter
from emqx_tpu.message import Message
from emqx_tpu.metrics import Metrics
from emqx_tpu.ops import dispatchasm
from emqx_tpu.ops_guard import AlarmRegistry

_native = dispatchasm.load()


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


def _broker(enable=True, columns=True, **olp_kw):
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.olp.enable = enable
    # pin the REAL-machine signals inert so a loaded CI box can never
    # move the ladder under a test; loop_lag_ms (100/500/2000) is the
    # synthetic driver the tests inject through `observe`
    cfg.olp.sysmem = [0.999, 0.9995, 0.9999]
    cfg.olp.procmem = [0.97, 0.98, 0.99]
    cfg.olp.cpu = [1e6, 2e6, 3e6]
    cfg.olp.e2e_p99_ms = [1e6, 2e6, 3e6]
    cfg.olp.mqueue_backlog = [1e9, 2e9, 3e9]
    for k, v in olp_kw.items():
        setattr(cfg.olp, k, v)
    b = Broker(config=cfg)
    b._decide_columns = columns
    return b


def lift(b, level, now=None):
    """Drive the ladder to `level` with one synthetic loop-lag signal
    (thresholds 100/500/2000 ms by default)."""
    now = time.time() if now is None else now
    val = {0: 0.0, 1: 100.0, 2: 500.0, 3: 2000.0}[level]
    b.olp.observe({"loop_lag_ms": val}, now=now)
    assert b.olp.level == level
    return now


def settle(b, now):
    """Step the ladder all the way back to 0 (one held step at a
    time), returning the final injected clock."""
    while b.olp.level:
        now += float(b.olp.cfg.min_hold) + 0.01
        b.olp.observe({"loop_lag_ms": 0.0}, now=now)
    return now


class WireChannel(Channel):
    def __init__(self, broker, version=C.MQTT_V5):
        self.writes = []

        def send(pkts):
            self.writes.append(
                b"".join(C.serialize(p, self.version) for p in pkts)
            )

        super().__init__(broker, send=send, close=lambda r: None)
        self.version = version

    def wire(self) -> bytes:
        return b"".join(bytes(w) for w in self.writes)

    def packets(self):
        return list(
            C.StreamParser(version=self.version).feed(self.wire())
        )


# ============================================================ levels

def test_disabled_default_is_inert():
    b = _broker(enable=False)
    assert BrokerConfig().olp.enable is False  # ships off, like emqx
    assert b.olp.observe({"loop_lag_ms": 1e9}, now=time.time()) == 0
    assert b.olp.tick(time.time()) == 0
    assert b.olp.shed_qos0_mask is False
    assert b.olp.defer_admissions is False


def test_enter_levels_and_max_across_signals():
    b = _broker()
    now = time.time()
    assert b.olp.observe({"loop_lag_ms": 99.0}, now=now) == 0
    assert b.olp.observe({"loop_lag_ms": 100.0}, now=now) == 1
    # a second signal at a HIGHER level wins (max across signals),
    # and up-transitions may jump several levels at once
    assert b.olp.observe(
        {"loop_lag_ms": 100.0, "batcher_fill": 3.0}, now=now
    ) == 3
    assert b.olp.shed_qos0_mask and b.olp.shed_ingress_qos0
    assert b.olp.defer_admissions
    assert b.olp.window_cap_now == b.olp.cfg.window_cap


def test_down_steps_one_level_after_hold():
    b = _broker(min_hold=5.0)
    now = lift(b, 3)
    # inside the hold nothing steps down, however quiet the signals
    assert b.olp.observe({"loop_lag_ms": 0.0}, now=now + 1) == 3
    # past the hold: exactly ONE step per observe, each re-arming it
    assert b.olp.observe({"loop_lag_ms": 0.0}, now=now + 5.1) == 2
    assert b.olp.observe({"loop_lag_ms": 0.0}, now=now + 5.2) == 2
    assert b.olp.observe({"loop_lag_ms": 0.0}, now=now + 10.3) == 1
    assert b.olp.observe({"loop_lag_ms": 0.0}, now=now + 15.5) == 0
    assert not b.olp.defer_admissions and not b.olp.shed_qos0_mask
    assert b.olp.window_cap_now == 0


def test_exit_factor_hysteresis_square_wave():
    """A load signal square-waving between just-above-enter and
    just-below-enter-but-above-exit must cost ONE transition total —
    the ladder neither flaps nor steps down while the signal sits in
    the hysteresis band (enter * exit_factor .. enter)."""
    b = _broker(min_hold=2.0, exit_factor=0.8)
    t0 = time.time()
    changes = 0
    last = 0
    for i in range(100):
        val = 120.0 if i % 2 == 0 else 85.0  # L1 enter=100, exit=80
        lvl = b.olp.observe({"loop_lag_ms": val}, now=t0 + i)
        if lvl != last:
            changes += 1
            last = lvl
    assert last == 1 and changes == 1
    # dropping BELOW the exit threshold finally releases it
    assert b.olp.observe({"loop_lag_ms": 79.0}, now=t0 + 200) == 0


@pytest.mark.parametrize("seed", [3, 17, 29])
def test_seeded_trace_level_properties(seed):
    """Random signal walks: levels stay in [0, 3], down transitions
    are exactly one step, up transitions only when a signal is at or
    above its enter threshold, and a long quiet tail converges to 0."""
    import random

    rng = random.Random(seed)
    b = _broker(min_hold=3.0)
    t = time.time()
    prev = 0
    for _ in range(300):
        t += rng.uniform(0.2, 2.0)
        sig = {
            "loop_lag_ms": rng.choice([0, 50, 90, 120, 600, 2500]),
            "batcher_fill": rng.choice([0.0, 0.5, 0.9, 1.7]),
        }
        lvl = b.olp.observe(sig, now=t)
        assert 0 <= lvl <= 3
        if lvl < prev:
            assert lvl == prev - 1, "down must step one level"
        if lvl > prev:
            assert (
                sig["loop_lag_ms"] >= (100, 500, 2000)[lvl - 1]
                or sig["batcher_fill"] >= (0.75, 1.5, 3.0)[lvl - 1]
            )
        prev = lvl
    for _ in range(10):
        t += 5.0
        prev = b.olp.observe({"loop_lag_ms": 0.0}, now=t)
    assert prev == 0
    # every transition was recorded for the REST surface
    assert len(b.olp._transitions) >= 1


def test_overload_alarm_standing_and_damped():
    b = _broker(min_hold=1.0, alarm_min_reraise=10.0, alarm_hold=5.0)
    m = b.metrics
    now = lift(b, 1)
    assert m.val("alarms.activate") == 1
    active = {a.name: a for a in b.alarms.active()}
    assert active["overload"].details["level"] == 1
    # level change UPDATES the standing alarm; the re-raise publish is
    # damped inside min_reraise (no $SYS churn), details stay honest
    b.olp.observe({"loop_lag_ms": 600.0}, now=now + 1)
    assert b.olp.level == 2
    assert m.val("alarms.activate") == 1
    active = {a.name: a for a in b.alarms.active()}
    assert active["overload"].details["level"] == 2
    # recovery: the deactivate is HELD (hysteresis) — a re-raise
    # inside the hold cancels it silently
    now = settle(b, now + 1)
    assert any(a.name == "overload" for a in b.alarms.active())
    assert m.val("alarms.deactivate") == 0
    b.olp.observe({"loop_lag_ms": 2000.0}, now=now + 1)  # re-raise
    assert b.olp.level == 3
    b.alarms.tick(now + 100)  # pending deact was cancelled
    assert any(a.name == "overload" for a in b.alarms.active())
    assert m.val("alarms.deactivate") == 0
    # a QUIET recovery completes after the hold elapses un-cancelled
    now = settle(b, now + 1)
    b.alarms.tick(now + 5.1)
    assert not any(a.name == "overload" for a in b.alarms.active())
    assert m.val("alarms.deactivate") == 1


# ============================================== alarm flap damping

class _PubSpy:
    """Minimal broker stand-in for a standalone AlarmRegistry."""

    class _Cfg:
        node_name = "spy@local"

    def __init__(self):
        self.metrics = Metrics()
        self.config = self._Cfg()
        self.published = []

    def publish(self, msg):
        self.published.append(msg.topic)
        return 0


def test_alarm_registry_square_wave_damping():
    """The satellite acceptance: a square-wave condition (activate /
    deactivate alternating every second for a minute) produces a
    bounded number of $SYS publishes — one initial raise, damped
    re-raises at most every ``min_reraise``, and ONE deactivate once
    the wave stops."""
    spy = _PubSpy()
    reg = AlarmRegistry(spy)
    t0 = 1000.0
    for i in range(60):
        now = t0 + i
        if i % 2 == 0:
            reg.activate("sq", message="square", min_reraise=10.0,
                         now=now)
        else:
            reg.deactivate("sq", hold=5.0, now=now)
        reg.tick(now)
    # held deactivations were always cancelled by the next activate:
    # zero deactivate publishes during the wave, and activates are
    # bounded by ONE per min_reraise window (60s / 10s = 6 + slack)
    acts = [t for t in spy.published if t.endswith("alarms/activate")]
    deacts = [t for t in spy.published if t.endswith("alarms/deactivate")]
    assert deacts == []
    assert 1 <= len(acts) <= 7
    # wave over: the hold elapses un-cancelled and ONE deactivate ships
    reg.deactivate("sq", hold=5.0, now=t0 + 60)
    reg.tick(t0 + 66)
    deacts = [t for t in spy.published if t.endswith("alarms/deactivate")]
    assert len(deacts) == 1
    assert not any(a.name == "sq" for a in reg.active())
    # undamped (legacy defaults) still deactivates immediately
    reg.activate("legacy", now=t0 + 70)
    assert reg.deactivate("legacy", now=t0 + 70.5) is True


def test_alarm_update_refreshes_details_with_throttle():
    spy = _PubSpy()
    reg = AlarmRegistry(spy)
    reg.update("u", details={"v": 1}, min_reraise=10.0, now=100.0)
    reg.update("u", details={"v": 2}, min_reraise=10.0, now=101.0)
    a = {x.name: x for x in reg.active()}["u"]
    assert a.details == {"v": 2}  # details fresh, publish damped
    acts = [t for t in spy.published if t.endswith("alarms/activate")]
    assert len(acts) == 1
    reg.update("u", details={"v": 3}, min_reraise=10.0, now=111.0)
    acts = [t for t in spy.published if t.endswith("alarms/activate")]
    assert len(acts) == 2


def test_alarm_ttl_expiry_unchanged():
    spy = _PubSpy()
    reg = AlarmRegistry(spy)
    reg.activate("ttl", ttl=5.0, now=100.0)
    reg.tick(104.0)
    assert any(a.name == "ttl" for a in reg.active())
    reg.tick(106.0)
    assert not any(a.name == "ttl" for a in reg.active())


# ======================================================= L1 effects

def test_l1_parks_new_resume_admissions():
    from emqx_tpu.broker.resume import ResumeScheduler, _Job
    from emqx_tpu.config import ResumeConfig

    b = _broker()
    rs = ResumeScheduler(b, ResumeConfig(max_concurrent=4))
    assert rs._place(_Job("a", None, None)) == "active"
    now = lift(b, 1)
    assert rs._place(_Job("b", None, None)) == "parked"
    assert b.metrics.val("olp.deferred.resume") == 1
    rs._unpark()
    assert "b" not in rs._active  # stays parked while raised
    settle(b, now)
    rs._unpark()
    assert "b" in rs._active  # recovery drains the park FIFO


def test_l1_defers_retained_catchup_and_flushes_on_recovery():
    b = _broker(retained_flush_per_tick=16)
    b.publish(Message(topic="t/r", payload=b"keep", qos=1, retain=True))
    ch = WireChannel(b)
    s, _ = b.cm.open_session(True, "sub", ch)
    now = lift(b, 1)
    opts = SubOpts(qos=1)
    s.subscribe("t/#", opts)
    retained = b.subscribe("sub", "t/#", opts, defer_ok=True)
    assert retained == []  # deferred, not delivered
    assert b.metrics.val("olp.deferred.retained") == 1
    assert b.olp.info()["retained_deferred"] == 1
    # while raised, the tick flushes nothing
    b.olp.tick(now + 0.5)
    assert ch.writes == []
    # ladder back at 0: the tick replays the catch-up (retain bit set)
    now = settle(b, now)
    b.olp._last_tick = now  # keep the lag probe out of this test
    b.olp.tick(now + 1.0)
    pkts = [p for p in ch.packets() if p.type == C.PUBLISH]
    assert len(pkts) == 1
    assert pkts[0].payload == b"keep" and pkts[0].retain
    assert b.olp.info()["retained_deferred"] == 0


def test_l1_retained_flush_to_detached_session_drops_qos0():
    """The deferred-catch-up flush to a DETACHED session queues QoS>0
    only (exactly like `_queue_detached_run`): queueing best-effort
    QoS0 retained could evict admitted QoS>=1 backlog from the
    bounded mqueue — the zero-QoS>=1-loss invariant forbids it."""
    b = _broker()
    b.publish(Message(topic="t/q0", qos=0, payload=b"r0", retain=True))
    b.publish(Message(topic="t/q1", qos=1, payload=b"r1", retain=True))
    ch = WireChannel(b)
    s, _ = b.cm.open_session(True, "det", ch)
    s.expiry_interval = 3600.0
    now = lift(b, 1)
    opts = SubOpts(qos=1)
    s.subscribe("t/#", opts)
    assert b.subscribe("det", "t/#", opts, defer_ok=True) == []
    # the channel detaches before recovery
    b.cm.disconnect("det", ch)
    now = settle(b, now)
    b.olp._last_tick = now
    b.olp.tick(now + 1.0)
    # QoS1 retained queued for the reconnect; QoS0 dropped AND
    # counted (never silent) via the shared detached queue path
    assert [m.payload for m in s.mqueue] == [b"r1"]
    assert b.metrics.val("delivery.dropped") >= 1


def test_l1_retained_flush_respects_stall_gate():
    """The recovery flush must not pile the catch-up burst onto a
    subscriber still over its outbound watermark — it takes the same
    stalled queue path as live dispatch (QoS0 counted, QoS>0 parked
    on the mqueue for the retry-timer drain)."""
    b = _broker()
    b.config.mqtt.outbound_high_watermark = 1000
    b.publish(Message(topic="w/q0", qos=0, payload=b"r0", retain=True))
    b.publish(Message(topic="w/q1", qos=1, payload=b"r1", retain=True))
    ch = WireChannel(b)
    ch.transport_buffered = lambda: 10_000  # still stalled
    s, _ = b.cm.open_session(True, "stall", ch)
    now = lift(b, 1)
    opts = SubOpts(qos=1)
    s.subscribe("w/#", opts)
    assert b.subscribe("stall", "w/#", opts, defer_ok=True) == []
    now = settle(b, now)
    b.olp._last_tick = now
    b.olp.tick(now + 1)
    assert ch.writes == []  # nothing onto the overflowing buffer
    assert [m.payload for m in s.mqueue] == [b"r1"]  # parked
    assert s.out_parked
    assert b.metrics.val("delivery.dropped.out_buffer") == 1  # r0


def test_l1_retained_flush_paced_by_messages_and_chunks_jobs():
    """Recovery pacing counts MESSAGES, not jobs: one filter matching
    a big retained set chunks across ticks instead of stalling the
    loop with one giant burst at recovery."""
    b = _broker(retained_flush_per_tick=2)
    for i in range(5):
        b.publish(Message(topic=f"big/{i}", qos=1,
                          payload=b"r%d" % i, retain=True))
    ch = WireChannel(b)
    s, _ = b.cm.open_session(True, "chunky", ch)
    now = lift(b, 1)
    opts = SubOpts(qos=0)
    s.subscribe("big/#", opts)
    assert b.subscribe("chunky", "big/#", opts, defer_ok=True) == []
    now = settle(b, now)
    b.olp._last_tick = now
    seen = 0
    for k in range(1, 5):
        b.olp.tick(now + k)
        n = len([p for p in ch.packets() if p.type == C.PUBLISH])
        assert n - seen <= 2, "flush burst exceeded the pacing budget"
        seen = n
    assert seen == 5  # the whole job drained, two messages per tick
    assert b.olp.info()["retained_deferred"] == 0


def test_l1_retained_defer_cancelled_by_rh2_and_unsubscribe():
    """A re-subscribe with retain_handling=2 (or an unsubscribe)
    cancels a parked catch-up job — the flush must honor the CURRENT
    subscription options."""
    b = _broker()
    b.publish(Message(topic="c/x", qos=1, payload=b"keep", retain=True))
    ch = WireChannel(b)
    s, _ = b.cm.open_session(True, "cancels", ch)
    now = lift(b, 1)
    opts = SubOpts(qos=1)
    s.subscribe("c/#", opts)
    assert b.subscribe("cancels", "c/#", opts, defer_ok=True) == []
    assert b.olp.info()["retained_deferred"] == 1
    # re-subscribe with rh=2: "send no retained" — job cancelled
    opts2 = SubOpts(qos=1, retain_handling=2)
    s.subscribe("c/#", opts2)
    assert b.subscribe("cancels", "c/#", opts2, is_new_sub=False,
                       defer_ok=True) == []
    assert b.olp.info()["retained_deferred"] == 0
    now = settle(b, now)
    b.olp._last_tick = now
    b.olp.tick(now + 1)
    assert [p for p in ch.packets() if p.type == C.PUBLISH] == []
    # and the unsubscribe path cancels too
    lift(b, 1, now + 2)
    opts3 = SubOpts(qos=1)
    s.subscribe("c/#", opts3)
    b.subscribe("cancels", "c/#", opts3, defer_ok=True)
    assert b.olp.info()["retained_deferred"] == 1
    s.unsubscribe("c/#")
    b.unsubscribe("cancels", "c/#")
    assert b.olp.info()["retained_deferred"] == 0


def test_l1_resume_park_fifo_bounded_under_defer():
    """While the ladder defers admissions, `saturated` must bound on
    the park FIFO alone — active slots drain and are never refilled,
    so the old active-AND-parked condition would admit (and park)
    storms without ever answering server-busy."""
    from emqx_tpu.broker.resume import ResumeScheduler, _Job
    from emqx_tpu.config import ResumeConfig

    b = _broker()
    rs = ResumeScheduler(
        b, ResumeConfig(max_concurrent=4, park_queue_cap=2)
    )
    lift(b, 1)
    assert not rs.saturated()
    rs._place(_Job("a", None, None))
    rs._place(_Job("b", None, None))
    assert rs.saturated()  # park cap reached with EMPTY active slots


def test_l1_retained_defers_only_for_delivering_callers():
    """Callers that DISCARD the retained return (takeover import,
    auto-subscribe, gateway adapters — defer_ok=False, the default)
    must not park catch-up jobs: the flush would later deliver a
    retained burst those paths never produce."""
    b = _broker()
    b.publish(Message(topic="d/x", qos=1, payload=b"r", retain=True))
    ch = WireChannel(b)
    s, _ = b.cm.open_session(True, "importer", ch)
    lift(b, 1)
    opts = SubOpts(qos=1)
    s.subscribe("d/#", opts)
    # the import/auto-subscribe shape: no defer_ok, return discarded
    out = b.subscribe("importer", "d/#", opts)
    assert [m.payload for m in out] == [b"r"]  # inline, as at level 0
    assert b.olp.info()["retained_deferred"] == 0  # nothing parked


def test_l1_inline_replay_supersedes_parked_job():
    """A re-subscribe served INLINE (level back at 0) cancels the job
    a deferred earlier subscribe parked — delivering both would
    duplicate the retained burst."""
    b = _broker()
    b.publish(Message(topic="s/x", qos=1, payload=b"once", retain=True))
    ch = WireChannel(b)
    s, _ = b.cm.open_session(True, "resub", ch)
    now = lift(b, 1)
    opts = SubOpts(qos=1)
    s.subscribe("s/#", opts)
    assert b.subscribe("resub", "s/#", opts, defer_ok=True) == []
    assert b.olp.info()["retained_deferred"] == 1
    now = settle(b, now)
    # before the flush runs, the client re-subscribes: inline replay
    out = b.subscribe("resub", "s/#", opts, defer_ok=True)
    assert [m.payload for m in out] == [b"once"]
    assert b.olp.info()["retained_deferred"] == 0  # job cancelled
    b.olp._last_tick = now
    b.olp.tick(now + 1)
    assert ch.writes == []  # the flush delivers nothing extra


def test_l1_deferred_rebuild_kicked_at_recovery():
    """A rebuild deferred during the episode fires at ladder-down to
    0 even if no further mutation ever arrives (stable fleet)."""
    from emqx_tpu.engine import MatchEngine

    b = _broker()
    eng = MatchEngine(
        use_device=False, background_rebuild=True, rebuild_threshold=4
    )
    calls = []
    eng._start_background_rebuild = lambda: calls.append(1)
    eng.defer_rebuild = b.olp.defer_rebuild
    b.router.engine = eng  # the recovery kick targets this engine
    now = lift(b, 1)
    for i in range(6):
        eng.insert(f"kick/{i}/+", f"f{i}")
    assert calls == []
    settle(b, now)  # no mutation after this — the kick must fire
    assert calls == [1]


def test_l1_retained_chunk_snapshot_stable_under_mutation():
    """A chunked job's tail is a message SNAPSHOT: clearing one of
    the already-delivered retained topics between ticks must not make
    the subscriber skip (or re-receive) any of the rest."""
    b = _broker(retained_flush_per_tick=2)
    for i in range(5):
        b.publish(Message(topic=f"mut/{i}", qos=1,
                          payload=b"m%d" % i, retain=True))
    ch = WireChannel(b)
    s, _ = b.cm.open_session(True, "mut", ch)
    now = lift(b, 1)
    opts = SubOpts(qos=1)
    s.subscribe("mut/#", opts)
    assert b.subscribe("mut", "mut/#", opts, defer_ok=True) == []
    now = settle(b, now)
    b.olp._last_tick = now
    b.olp.tick(now + 1)  # first chunk: 2 delivered, tail snapshotted
    # clear an ALREADY-DELIVERED retained topic: an offset-based
    # resume over a fresh match would now skip one message
    b.publish(Message(topic="mut/0", qos=1, payload=b"", retain=True))
    b.olp.tick(now + 2)
    b.olp.tick(now + 3)
    got = sorted(
        p.payload for p in ch.packets()
        # the retained-CLEAR publish also delivers live (empty
        # payload) — the invariant is about the catch-up set
        if p.type == C.PUBLISH and p.payload
    )
    assert got == [b"m%d" % i for i in range(5)]  # none skipped/duped


def test_l1_retained_defer_dies_with_the_session():
    """Discarded/terminated sessions drop their parked catch-up jobs
    — dead clients must not exhaust retained_defer_cap and crowd out
    live subscribers."""
    b = _broker()
    b.publish(Message(topic="gone/x", qos=1, payload=b"r", retain=True))
    ch = WireChannel(b)
    s, _ = b.cm.open_session(True, "ghost", ch)
    lift(b, 1)
    opts = SubOpts(qos=1)
    s.subscribe("gone/#", opts)
    assert b.subscribe("ghost", "gone/#", opts, defer_ok=True) == []
    assert b.olp.info()["retained_deferred"] == 1
    b.cm.kick("ghost")  # discard path
    assert b.olp.info()["retained_deferred"] == 0


def test_l1_retained_defer_cap_counts_overflow():
    b = _broker(retained_defer_cap=1)
    lift(b, 1)
    assert b.olp.defer_retained("c1", "a/#") is True
    assert b.olp.defer_retained("c2", "b/#") is True  # over cap
    assert b.metrics.val("olp.deferred.retained") == 1
    assert b.metrics.val("olp.dropped.retained") == 1  # never silent


def test_l1_shrinks_batch_window():
    b = _broker(window_cap=128)
    batcher = PublishBatcher(b, batch_max=4096)
    base = batcher._window_limit()
    assert base > 128
    now = lift(b, 1)
    assert batcher._window_limit() == 128
    settle(b, now)
    assert batcher._window_limit() == base


def test_l1_defers_background_rebuild():
    from emqx_tpu.engine import MatchEngine

    b = _broker()
    eng = MatchEngine(
        use_device=False, background_rebuild=True, rebuild_threshold=4
    )
    calls = []
    eng._start_background_rebuild = lambda: calls.append(1)
    eng.defer_rebuild = b.olp.defer_rebuild
    now = lift(b, 1)
    for i in range(6):
        eng.insert(f"defer/{i}/+", f"f{i}")
    assert calls == []  # deferred while the ladder is raised
    assert b.metrics.val("olp.deferred.rebuild") >= 1
    settle(b, now)
    eng.insert("defer/x/+", "fx")  # first post-recovery delta fires it
    assert calls


# ======================================================= L2 effects

def _shed_world(seed):
    """Random world for the shed-parity property: mixed QoS subs,
    no_local, RAP, subid, upgrade_qos, v4/v5, shared groups."""
    import random

    rng = random.Random(seed)
    clients = []
    for i in range(10):
        subs = []
        for f in range(rng.randint(1, 3)):
            subs.append({
                "flt": rng.choice(
                    ["t/#", "t/+/x", f"t/{f}/x", "s/only",
                     "$share/g1/t/+/x"]
                ),
                "qos": rng.randint(0, 2),
                "rap": rng.random() < 0.4,
                "no_local": rng.random() < 0.3,
                "subid": rng.randint(1, 9)
                if rng.random() < 0.2 else None,
            })
        clients.append({
            "cid": f"c{i}",
            "version": rng.choice([C.MQTT_V4, C.MQTT_V5]),
            "upgrade": rng.random() < 0.3,
            "max_inflight": rng.choice([2, 4, 32]),
            "subs": subs,
        })
    windows = []
    for _ in range(3):
        windows.append([
            {
                "topic": rng.choice(
                    ["t/1/x", "t/2/x", "s/only", "t/deep/x"]
                ),
                "qos": rng.randint(0, 2),
                "retain": rng.random() < 0.3,
                "payload": bytes(
                    rng.randrange(256)
                    for _ in range(rng.randint(0, 150))
                ),
                "from": rng.choice(["c0", "c1", "pub"]),
            }
            for _ in range(rng.randint(1, 10))
        ])
    return clients, windows


def _run_shed_world(clients, windows, mode):
    b = _broker(columns=mode != "scalar")
    if mode in ("host", "dev"):
        b.router.engine.decide_force = mode
    b.router.shared._rng.seed(1234)
    lift(b, 2)
    chans = {}
    for c in clients:
        ch = WireChannel(b, version=c["version"])
        session, _ = b.cm.open_session(
            True, c["cid"], ch, max_inflight=c["max_inflight"]
        )
        session.upgrade_qos = c["upgrade"]
        for sub in c["subs"]:
            opts = SubOpts(
                qos=sub["qos"], retain_as_published=sub["rap"],
                no_local=sub["no_local"], subid=sub["subid"],
            )
            session.subscribe(sub["flt"], opts)
            b.subscribe(c["cid"], sub["flt"], opts)
        chans[c["cid"]] = ch
    counts = []
    for win in windows:
        msgs = [
            Message(
                topic=w["topic"], qos=w["qos"], retain=w["retain"],
                payload=w["payload"], from_client=w["from"],
                timestamp=1.0e9,
            )
            for w in win
        ]
        counts.append(b.publish_many(msgs))
    wires = {cid: ch.wire() for cid, ch in chans.items()}
    sent = {
        k: b.metrics.val(k)
        for k in ("messages.sent", "messages.qos0.sent",
                  "messages.qos1.sent", "messages.qos2.sent",
                  "delivery.dropped", "delivery.dropped.olp_shed")
    }
    inflights = {
        c["cid"]: sorted(
            (pid, e.qos)
            for pid, e in b.cm.lookup(c["cid"]).inflight.items()
        )
        for c in clients
    }
    return counts, wires, sent, inflights, chans, clients


@pytest.mark.parametrize("seed", [1, 5, 11, 23])
def test_l2_shed_mask_parity_vs_scalar_referee(seed):
    """With shedding active, the columns paths (host + device decide)
    must put bit-identical bytes on every wire as the scalar referee —
    and NO wire may carry a QoS0 PUBLISH (the shed contract), while
    QoS>=1 deliveries all survive (zero-loss invariant)."""
    clients, windows = _shed_world(seed)
    scalar = _run_shed_world(clients, windows, "scalar")
    host = _run_shed_world(clients, windows, "host")
    dev = _run_shed_world(clients, windows, "dev")
    for other, label in ((host, "host"), (dev, "dev")):
        assert scalar[0] == other[0], (label, "counts")
        for cid in scalar[1]:
            assert scalar[1][cid] == other[1][cid], (label, cid)
        assert scalar[2] == other[2], (label, "sent/shed metrics")
        assert scalar[3] == other[3], (label, "inflight")
    assert scalar[2]["messages.qos0.sent"] == 0
    # decoded frames: every delivered PUBLISH is QoS >= 1
    for cid, ch in scalar[4].items():
        for p in ch.packets():
            if p.type == C.PUBLISH:
                assert p.qos >= 1, (cid, "shed leak")


def test_l2_level0_identical_to_disabled():
    """OLP enabled at level 0 must be byte-identical to disabled —
    the steady-state-overhead contract's functional half."""
    clients, windows = _shed_world(42)

    def run_mode(enable):
        b = _broker(enable=enable)
        b.router.shared._rng.seed(99)
        chans = {}
        for c in clients:
            ch = WireChannel(b, version=c["version"])
            session, _ = b.cm.open_session(
                True, c["cid"], ch, max_inflight=c["max_inflight"]
            )
            session.upgrade_qos = c["upgrade"]
            for sub in c["subs"]:
                opts = SubOpts(
                    qos=sub["qos"], retain_as_published=sub["rap"],
                    no_local=sub["no_local"], subid=sub["subid"],
                )
                session.subscribe(sub["flt"], opts)
                b.subscribe(c["cid"], sub["flt"], opts)
            chans[c["cid"]] = ch
        for win in windows:
            b.publish_many([
                Message(topic=w["topic"], qos=w["qos"],
                        retain=w["retain"], payload=w["payload"],
                        from_client=w["from"], timestamp=1.0e9)
                for w in win
            ])
        return {cid: ch.wire() for cid, ch in chans.items()}

    on = run_mode(True)
    off = run_mode(False)
    assert on == off


def test_l2_clamps_shared_buckets_and_restores():
    b = _broker(limiter_clamp=0.5)
    lim = ConnectionLimiter(messages_rate=100.0, bytes_rate=1000.0,
                            shared=True)
    b.olp.clamp_targets.append(lim)
    now = lift(b, 2)
    assert lim.msg_bucket.rate == pytest.approx(50.0)
    assert lim.byte_bucket.rate == pytest.approx(500.0)
    # stepping down to 1 already unclamps (the clamp is an L2 edge)
    now += float(b.olp.cfg.min_hold) + 0.01
    b.olp.observe({"loop_lag_ms": 100.0}, now=now)
    assert b.olp.level == 1
    assert lim.msg_bucket.rate == pytest.approx(100.0)
    assert lim.byte_bucket.rate == pytest.approx(1000.0)


def _connect(b, cid, version=C.MQTT_V5):
    ch = WireChannel(b, version=version)
    ch.handle_in(C.Connect(client_id=cid, proto_ver=version))
    return ch


def test_l2_connect_budget_answers_server_busy():
    b = _broker(connect_budget=2.0)
    lift(b, 2)
    rcs = []
    for i in range(4):
        ch = _connect(b, f"burst{i}")
        connacks = [p for p in ch.packets() if p.type == C.CONNACK]
        assert len(connacks) == 1
        rcs.append(connacks[0].reason_code)
    assert rcs[:2] == [0, 0]
    assert rcs[2] == 0x89 and rcs[3] == 0x89  # server busy
    assert b.metrics.val("olp.refused.connect") == 2
    # refused clients never created session state
    assert b.cm.lookup("burst2") is None
    # at level 0 the budget does not apply
    now = settle(b, time.time())
    b.olp._cb_tokens = 0.0
    ch = _connect(b, "after")
    assert ch.packets()[0].reason_code == 0


def test_l2_connect_budget_v4_maps_to_server_unavailable():
    b = _broker(connect_budget=0.5)
    lift(b, 2)
    b.olp._cb_tokens = 0.0
    ch = _connect(b, "old", version=C.MQTT_V4)
    assert ch.packets()[0].reason_code == 3  # v3 server unavailable


# ======================================================= L3 effects

def test_l3_drops_qos0_at_publish_ingress():
    b = _broker()
    sub = WireChannel(b)
    s, _ = b.cm.open_session(True, "watcher", sub)
    opts = SubOpts(qos=1)
    s.subscribe("in/#", opts)
    b.subscribe("watcher", "in/#", opts)
    pub = _connect(b, "pub")
    lift(b, 3)
    pub.handle_in(C.Publish(topic="in/a", payload=b"q0", qos=0))
    assert b.metrics.val("olp.shed.publish_qos0") == 1
    assert b.metrics.val("messages.dropped.olp_shed") == 1
    assert sub.writes == []  # never routed
    # QoS1 still routes AND acks — zero loss for admitted traffic
    pub.handle_in(
        C.Publish(topic="in/a", payload=b"q1", qos=1, packet_id=7)
    )
    pubs = [p for p in sub.packets() if p.type == C.PUBLISH]
    assert [p.payload for p in pubs] == [b"q1"]
    acks = [p for p in pub.packets() if p.type == C.PUBACK]
    assert [p.packet_id for p in acks] == [7]


def test_l3_force_closes_slowest_subscribers():
    b = _broker(slow_kill_max=2)
    chans = {}
    for i in range(3):
        cid = f"slow{i}"
        ch = _connect(b, cid)
        chans[cid] = ch
        b.slow_subs.record(cid, "t/x", 1000.0 + i)
    lift(b, 3)
    assert b.metrics.val("olp.killed.slow_subs") == 2
    killed = [
        cid for cid, ch in chans.items()
        if any(p.type == C.DISCONNECT for p in ch.packets())
    ]
    assert len(killed) == 2
    for cid in killed:
        d = [p for p in chans[cid].packets()
             if p.type == C.DISCONNECT][0]
        assert d.reason_code == 0x89  # server busy, not a client fault


# ==================================== outbound high-watermark (sat 1)

@pytest.mark.parametrize("columns", [True, False])
def test_out_buffer_watermark_drops_qos0_queues_qos1(columns):
    cfg_wm = 1000
    b = _broker(enable=False, columns=columns)
    b.config.mqtt.outbound_high_watermark = cfg_wm
    stalled = WireChannel(b)
    stalled.transport_buffered = lambda: cfg_wm * 10  # past watermark
    healthy = WireChannel(b)
    for cid, ch in (("stalled", stalled), ("healthy", healthy)):
        s, _ = b.cm.open_session(True, cid, ch)
        for flt, q in (("w/q0", 0), ("w/q1", 1)):
            opts = SubOpts(qos=q)
            s.subscribe(flt, opts)
            b.subscribe(cid, flt, opts)
    counts = b.publish_many([
        Message(topic="w/q0", qos=0, payload=b"a", timestamp=1e9),
        Message(topic="w/q1", qos=1, payload=b"b", timestamp=1e9),
    ])
    # the healthy subscriber got both; the stalled one got NOTHING on
    # the wire — its QoS0 dropped (counted), its QoS1 queued
    assert [p.payload for p in healthy.packets()
            if p.type == C.PUBLISH] == [b"a", b"b"]
    assert stalled.writes == []
    assert b.metrics.val("delivery.dropped.out_buffer") == 1
    stalled_s = b.cm.lookup("stalled")
    assert len(stalled_s.mqueue) == 1
    assert list(stalled_s.mqueue)[0].payload == b"b"
    # the dropped QoS0 does NOT count as handled (detached-path
    # semantics); the queued QoS1 does
    assert counts == [1, 2]
    # no buddy replication for a live session's overflow
    # (replicate=False path) — nothing external here anyway


@pytest.mark.parametrize("columns", [True, False])
def test_out_buffer_watermark_respects_no_local(columns):
    """[MQTT-3.8.3-3] on the stalled path too: a stalled subscriber's
    OWN publishes must not be queued back to it (and must not count
    as out_buffer drops)."""
    b = _broker(enable=False, columns=columns)
    b.config.mqtt.outbound_high_watermark = 1000
    ch = WireChannel(b)
    ch.transport_buffered = lambda: 10_000
    s, _ = b.cm.open_session(True, "selfpub", ch)
    opts = SubOpts(qos=1, no_local=True)
    s.subscribe("nl/#", opts)
    b.subscribe("selfpub", "nl/#", opts)
    b.publish(Message(topic="nl/t", qos=1, payload=b"own",
                      from_client="selfpub", timestamp=1e9))
    b.publish(Message(topic="nl/t", qos=0, payload=b"own0",
                      from_client="selfpub", timestamp=1e9))
    assert len(s.mqueue) == 0 and not s.out_parked
    assert b.metrics.val("delivery.dropped.out_buffer") == 0


def test_alarm_published_deactivate_resets_damping():
    """A PUBLISHED deactivate must reset the re-raise damping: the
    next activation publishes even inside min_reraise — otherwise a
    flap could leave a live alarm looking cleared on $SYS for the
    rest of the overload episode."""
    spy = _PubSpy()
    reg = AlarmRegistry(spy)
    reg.activate("ov", min_reraise=30.0, now=100.0)
    reg.deactivate("ov", now=105.0)  # published deactivate
    reg.activate("ov", min_reraise=30.0, now=112.0)  # inside 30s
    acts = [t for t in spy.published if t.endswith("alarms/activate")]
    deacts = [t for t in spy.published
              if t.endswith("alarms/deactivate")]
    assert len(acts) == 2 and len(deacts) == 1
    assert any(a.name == "ov" for a in reg.active())


@pytest.mark.parametrize("columns", [True, False])
def test_out_buffer_parked_backlog_keeps_order_and_timer_drains(
    columns,
):
    """A watermark-parked QoS>0 backlog must not be overtaken by
    later deliveries once the buffer recovers (same-topic order), and
    the channel's retry timer must flush it even when the client owes
    no ack (the ack-driven dequeue alone never fires)."""
    from emqx_tpu.broker.channel import CONNECTED

    b = _broker(enable=False, columns=columns)
    b.config.mqtt.outbound_high_watermark = 1000
    buf = [10_000]
    ch = WireChannel(b)
    ch.transport_buffered = lambda: buf[0]
    s, _ = b.cm.open_session(True, "parked", ch)
    ch.state = CONNECTED
    ch.session = s
    opts = SubOpts(qos=1)
    s.subscribe("o/#", opts)
    b.subscribe("parked", "o/#", opts)
    b.publish(Message(topic="o/t", qos=1, payload=b"m1", timestamp=1e9))
    assert ch.writes == [] and s.out_parked
    buf[0] = 0  # the subscriber drained its buffer...
    b.publish(Message(topic="o/t", qos=1, payload=b"m2", timestamp=1e9))
    # ...but m2 must queue BEHIND the parked m1, not overtake it
    assert ch.writes == []
    assert [m.payload for m in s.mqueue] == [b"m1", b"m2"]
    ch.retry_deliveries()  # the 5 s timer: flushes in order
    pubs = [p for p in ch.packets() if p.type == C.PUBLISH]
    assert [p.payload for p in pubs] == [b"m1", b"m2"]
    assert not s.out_parked and len(s.mqueue) == 0
    # recovered: the next delivery rides the fast path again
    b.publish(Message(topic="o/t", qos=1, payload=b"m3", timestamp=1e9))
    pubs = [p for p in ch.packets() if p.type == C.PUBLISH]
    assert [p.payload for p in pubs] == [b"m1", b"m2", b"m3"]


def test_out_buffer_watermark_paused_transport():
    """The regression the satellite asks for: a REAL subscriber that
    stops reading.  Once the kernel+transport buffers pass the
    watermark, QoS0 deliveries drop (counted) instead of growing the
    write buffer without bound."""
    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.config import BrokerConfig, ListenerConfig

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.engine.batch_publish = False
        cfg.mqtt.outbound_high_watermark = 64 * 1024
        srv = BrokerServer(cfg)
        await srv.start()
        port = srv.listeners[0].port
        try:
            async def conn(cid):
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(C.serialize(
                    C.Connect(client_id=cid, proto_ver=C.MQTT_V5),
                    C.MQTT_V5,
                ))
                await w.drain()
                p = C.StreamParser(version=C.MQTT_V5)
                while True:
                    data = await r.read(1 << 16)
                    assert data
                    if any(pk.type == C.CONNACK for pk in p.feed(data)):
                        return r, w, p

            sr, sw, sp = await conn("sleeper")
            sw.write(C.serialize(C.Subscribe(
                packet_id=1,
                subscriptions=[C.Subscription("flood/#", qos=0)],
            ), C.MQTT_V5))
            await sw.drain()
            await asyncio.sleep(0.1)
            # the subscriber now STOPS reading; flood it with big
            # QoS0 payloads until the watermark trips
            payload = b"x" * 65536
            broker = srv.broker
            for i in range(400):
                broker.publish(Message(
                    topic="flood/a", qos=0, payload=payload,
                    timestamp=time.time(),
                ))
                if broker.metrics.val(
                    "delivery.dropped.out_buffer"
                ) > 0:
                    break
                if i % 16 == 15:
                    await asyncio.sleep(0)  # let writes hit the socket
            assert broker.metrics.val(
                "delivery.dropped.out_buffer"
            ) > 0, "watermark never tripped"
            # the broker is still responsive to a healthy client
            hr, hw, hp = await conn("healthy")
            hw.write(C.serialize(C.Pingreq(), C.MQTT_V5))
            await hw.drain()
            data = await asyncio.wait_for(hr.read(1 << 12), 5.0)
            assert any(
                pk.type == C.PINGRESP for pk in hp.feed(data)
            )
            hw.close()
            sw.close()
        finally:
            await srv.stop()

    run(t())


# ============================================== chaos: the new seams

def test_olp_sample_fault_holds_level():
    b = _broker(sample_interval=0.0001)
    now = lift(b, 2)
    fp.configure("olp.sample", "error")
    b.olp._last_tick = now
    b.olp.tick(now + 1.0)  # sample raises inside; guard holds level
    assert b.olp.level == 2
    fp.configure("olp.sample", "drop")
    b.olp.tick(now + 2.0)  # dropped round: level held too
    assert b.olp.level == 2
    fp.clear("olp.sample")
    # sampling recovers: idle signals walk the ladder down
    t = now + 3.0
    for _ in range(10):
        t += float(b.olp.cfg.min_hold) + 1.0
        b.olp._last_tick = t - 1.0  # keep the lag probe quiet
        b.olp.tick(t)
    assert b.olp.level == 0


def test_olp_shed_accounting_fault_still_counts():
    b = _broker()
    fp.configure("olp.shed", "error")
    b.olp.shed("refused.connect")  # must not raise
    assert b.metrics.val("olp.refused.connect") == 1  # fallback count
    fp.clear("olp.shed")
    b.olp.shed("refused.connect")
    assert b.metrics.val("olp.refused.connect") == 2
    assert b.olp._shed_totals["refused.connect"] == 1


def test_olp_shed_panic_kills_mid_shed_without_qos1_loss():
    """kill-mid-shed: a panic (process-death stand-in) fired inside
    the shed accounting of a CONNECT refusal flows through the
    channel — and the broker keeps serving admitted QoS1 traffic with
    nothing lost."""
    b = _broker(connect_budget=1.0)
    sub = WireChannel(b)
    s, _ = b.cm.open_session(True, "keeper", sub)
    opts = SubOpts(qos=1)
    s.subscribe("live/#", opts)
    b.subscribe("keeper", "live/#", opts)
    lift(b, 2)
    b.olp._cb_tokens = 0.0
    fp.configure("olp.shed", "panic", times=1)
    with pytest.raises(fp.FailpointPanic):
        _connect(b, "victim")
    # the broker survives: QoS1 publish still routes and delivers
    n = b.publish(Message(topic="live/x", qos=1, payload=b"ok",
                          timestamp=time.time()))
    assert n == 1
    assert [p.payload for p in sub.packets()
            if p.type == C.PUBLISH] == [b"ok"]


# ========================================== chaos: flood + slow subs

def test_chaos_flood_and_slow_sub_storm_ladder_cycle():
    """The acceptance chaos gate, scaled to CI: a QoS0 publish flood
    over capacity plus a slow subscriber drives the ladder up to L2+,
    the control plane stays responsive (PINGREQ round-trips during
    the flood), sheds are counted, every ACKED QoS1 publish is
    delivered (zero admitted-QoS>=1 loss), and once the flood stops
    the ladder steps back down to 0."""
    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.config import BrokerConfig, ListenerConfig

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.engine.batch_max = 128
        cfg.olp.enable = True
        cfg.olp.sample_interval = 0.05
        cfg.olp.min_hold = 0.3
        cfg.olp.exit_factor = 0.8
        cfg.olp.batcher_fill = [0.3, 0.6, 50.0]
        cfg.olp.loop_lag_ms = [1e6, 1e6, 1e6]  # pin to one signal
        cfg.olp.e2e_p99_ms = [1e6, 1e6, 1e6]
        cfg.olp.mqueue_backlog = [1e9, 1e9, 1e9]
        cfg.olp.sysmem = [0.999, 0.9995, 0.9999]
        cfg.olp.procmem = [0.97, 0.98, 0.99]
        cfg.olp.cpu = [1e6, 1e6, 1e6]
        cfg.olp.alarm_min_reraise = 0.0
        srv = BrokerServer(cfg)
        await srv.start()
        broker = srv.broker
        port = srv.listeners[0].port
        max_level = 0
        stop_sampler = asyncio.Event()

        async def sampler():
            nonlocal max_level
            while not stop_sampler.is_set():
                broker.olp.tick(time.time())
                max_level = max(max_level, broker.olp.level)
                await asyncio.sleep(0.02)

        async def conn(cid):
            r, w = await asyncio.open_connection("127.0.0.1", port)
            w.write(C.serialize(
                C.Connect(client_id=cid, proto_ver=C.MQTT_V5),
                C.MQTT_V5,
            ))
            await w.drain()
            p = C.StreamParser(version=C.MQTT_V5)
            while True:
                data = await r.read(1 << 16)
                assert data
                if any(pk.type == C.CONNACK for pk in p.feed(data)):
                    return r, w, p

        try:
            sam = asyncio.get_running_loop().create_task(sampler())
            # subscriber: acks QoS1 promptly, records payloads
            sr, sw, sp = await conn("subscriber")
            sw.write(C.serialize(C.Subscribe(
                packet_id=1,
                subscriptions=[C.Subscription("live/#", qos=1),
                               C.Subscription("flood/#", qos=0)],
            ), C.MQTT_V5))
            await sw.drain()
            got = set()
            sub_done = asyncio.Event()

            async def sub_loop():
                while True:
                    data = await sr.read(1 << 16)
                    if not data:
                        return
                    acks = []
                    for pk in sp.feed(data):
                        if pk.type == C.PUBLISH and \
                                pk.topic.startswith("live/"):
                            got.add(bytes(pk.payload))
                            if pk.qos:
                                acks.append(C.serialize(
                                    C.Puback(packet_id=pk.packet_id),
                                    C.MQTT_V5,
                                ))
                    if acks:
                        sw.write(b"".join(acks))
                    if sub_done.is_set():
                        return

            sub_task = asyncio.get_running_loop().create_task(
                sub_loop()
            )
            # slow subscriber: subscribes the flood, then stops reading
            zr, zw, zp = await conn("slowpoke")
            zw.write(C.serialize(C.Subscribe(
                packet_id=1,
                subscriptions=[C.Subscription("flood/#", qos=0)],
            ), C.MQTT_V5))
            await zw.drain()

            flood_on = True

            async def flooder(i):
                r, w, p = await conn(f"flood{i}")
                payload = b"f" * 512
                k = 0
                while flood_on:
                    burst = b"".join(
                        C.serialize(C.Publish(
                            topic=f"flood/{i}/{k + j}", qos=0,
                            payload=payload,
                        ), C.MQTT_V5)
                        for j in range(64)
                    )
                    k += 64
                    w.write(burst)
                    try:
                        await asyncio.wait_for(w.drain(), 1.0)
                    except asyncio.TimeoutError:
                        await asyncio.sleep(0.05)  # read-paused: good
                w.close()

            flooders = [
                asyncio.get_running_loop().create_task(flooder(i))
                for i in range(3)
            ]
            # steady QoS1 publisher: every ack'd seq must arrive
            pr, pw, pp = await conn("steady")
            acked = set()

            async def qos1_publish(seq):
                pw.write(C.serialize(C.Publish(
                    topic="live/x", qos=1, packet_id=(seq % 60000) + 1,
                    payload=b"s%d" % seq,
                ), C.MQTT_V5))
                await pw.drain()

            async def pub_reader():
                while not sub_done.is_set():
                    data = await pr.read(1 << 14)
                    if not data:
                        return
                    for pk in pp.feed(data):
                        if pk.type == C.PUBACK:
                            acked.add(pk.packet_id)

            pub_rd = asyncio.get_running_loop().create_task(
                pub_reader()
            )
            # control connection: PINGREQ must round-trip under flood
            cr, cw, cp = await conn("control")
            pings_ok = 0
            sent_seqs = []
            t_end = time.time() + 4.0
            seq = 0
            while time.time() < t_end:
                await qos1_publish(seq)
                sent_seqs.append(seq)
                seq += 1
                cw.write(C.serialize(C.Pingreq(), C.MQTT_V5))
                await cw.drain()
                try:
                    data = await asyncio.wait_for(cr.read(1 << 10), 5.0)
                    if any(pk.type == C.PINGRESP
                           for pk in cp.feed(data)):
                        pings_ok += 1
                except asyncio.TimeoutError:
                    pass
                await asyncio.sleep(0.1)
            flood_on = False
            await asyncio.gather(*flooders, return_exceptions=True)
            # ladder must have risen to shedding territory and shed
            assert max_level >= 2, f"ladder only reached {max_level}"
            assert pings_ok >= len(sent_seqs) - 2, "control starved"
            shed = (
                broker.metrics.val("delivery.dropped.olp_shed")
                + broker.metrics.val("delivery.dropped.out_buffer")
            )
            assert shed > 0, "flood never shed"
            # drain: every QoS1 the broker ACKED must reach the sub
            want = {b"s%d" % s for s in sent_seqs}
            deadline = time.time() + 10.0
            while time.time() < deadline:
                if want <= got:
                    break
                await asyncio.sleep(0.1)
            missing = want - got
            assert not missing, f"QoS1 loss: {sorted(missing)[:5]}"
            # recovery: load gone, the ladder steps back down to 0
            deadline = time.time() + 10.0
            while time.time() < deadline and broker.olp.level:
                await asyncio.sleep(0.1)
            assert broker.olp.level == 0, "ladder never recovered"
            assert broker.metrics.val("olp.level.changed") >= 2
            sub_done.set()
            stop_sampler.set()
            for w in (sw, zw, pw, cw):
                w.close()
            sub_task.cancel()
            pub_rd.cancel()
            await asyncio.gather(
                sub_task, pub_rd, return_exceptions=True
            )
            await asyncio.gather(sam, return_exceptions=True)
        finally:
            stop_sampler.set()
            await srv.stop()

    run(t())


# ================================================ surfaces / config

def test_check_config_rejects_bad_olp():
    cfg = BrokerConfig()
    cfg.olp.exit_factor = 1.5
    cfg.olp.loop_lag_ms = [500.0, 100.0, 2000.0]
    cfg.olp.limiter_clamp = 0.0
    cfg.olp.window_cap = 0
    cfg.mqtt.outbound_high_watermark = -1
    problems = "\n".join(check_config(cfg))
    assert "olp.exit_factor" in problems
    assert "olp.loop_lag_ms" in problems
    assert "olp.limiter_clamp" in problems
    assert "olp.window_cap" in problems
    assert "outbound_high_watermark" in problems


def test_olp_info_shape():
    b = _broker()
    now = lift(b, 2)
    b.olp.shed("refused.connect")
    info = b.olp.info()
    assert info["level"] == 2 and info["enable"] is True
    assert info["signals"]["loop_lag_ms"] == 500.0
    assert info["thresholds"]["loop_lag_ms"] == [100.0, 500.0, 2000.0]
    assert info["shed"] == {"refused.connect": 1}
    assert info["counters"]["olp.refused.connect"] == 1
    assert info["transitions"][-1]["to"] == 2
    assert info["clamped"] is True


def test_rest_and_ctl_olp(tmp_path):
    import tempfile

    from api_helper import auth_session
    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.config import ListenerConfig

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.api.enable = True
        cfg.api.data_dir = tempfile.mkdtemp(dir=str(tmp_path))
        cfg.api.port = 0
        cfg.olp.enable = True
        srv = BrokerServer(cfg)
        await srv.start()
        srv.broker.olp.observe(
            {"loop_lag_ms": 600.0}, now=time.time()
        )
        http, api = await auth_session(srv)
        try:
            async with http.get(api + "/api/v5/olp") as r:
                assert r.status == 200
                body = await r.json()
                assert body["level"] == 2
                assert "loop_lag_ms" in body["signals"]
                assert body["counters"]["olp.level.changed"] == 1
            async with http.get(api + "/api/v5/nodes") as r:
                nodes = await r.json()
                assert nodes["data"][0]["olp_level"] == 2

            from emqx_tpu.ctl import Ctl

            def drive_ctl():
                ctl = Ctl(api, user="admin:public")
                ctl.olp()
                ctl.status()

            await asyncio.get_running_loop().run_in_executor(
                None, drive_ctl
            )
        finally:
            await http.close()
            await srv.stop()

    run(t())
