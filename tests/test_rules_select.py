"""Batched SELECT lowering + compiled templates: referee equality.

The output half of the rule matrix (PR 20).  Three contracts:

  * compiled templates are BIT-identical to the pre-PR regex renderer
    (a verbatim copy of it is the fuzz oracle), in both scalar
    (`TemplateProgram.render`) and column (`render_rows`) form;
  * batched SELECT + window-shaped actions produce exactly the same
    per-(rule, action) output streams as the scalar interpreter
    referee (`select_force="scalar"`) over seeded random worlds
    mixing lowerable and degraded rules, templated and JSON sink
    payloads, aggregate pushes, malformed payloads and absent fields;
  * the arithmetic/typing edge cases the interpreter pins (int-ness
    through json.dumps, string ``+`` concat, div-by-zero -> None,
    error-vs-missing operands) hold through the compiled lane.
"""

import json
import random
import re

import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.config import BrokerConfig
from emqx_tpu.message import Message
from emqx_tpu.rules.engine import (
    AggregateAction, RuleEngine, SinkAction, render_template,
)
from emqx_tpu.rules.select import (
    TemplateProgram, build_select_stack, compile_select,
    compile_template, materialize_rows,
)
from emqx_tpu.rules.sql import parse_sql
from emqx_tpu.aggregator import Aggregator


# ------------------------------------------------ the pre-PR renderer
# (verbatim copy of the regex-walk render_template this PR replaced —
# the oracle the compiled form must match byte for byte)

_PLACEHOLDER = re.compile(r"\$\{([^}]+)\}")


def _old_render_template(template, data):
    def sub(m):
        cur = data
        for part in m.group(1).split("."):
            if isinstance(cur, dict) and part in cur:
                cur = cur[part]
            else:
                return "undefined"
        if isinstance(cur, bool):
            return "true" if cur else "false"
        if isinstance(cur, bytes):
            return cur.decode("utf-8", "replace")
        if isinstance(cur, float) and cur.is_integer():
            return str(int(cur))
        if isinstance(cur, (dict, list)):
            return json.dumps(cur)
        return str(cur)

    return _PLACEHOLDER.sub(sub, template)


_FUZZ_VALUES = [
    0, 1, -3, 2.5, 4.0, -0.0, True, False, None, "", "x", "a%sb",
    "100% done", b"raw\xffbytes", {"k": 1, "j": [1, "s"]}, [1, 2.5],
    {"nested": {"deep": True}},
]

# values legal INSIDE a dict/list a placeholder may resolve to — the
# old renderer json.dumps'es containers, so bytes may only appear as
# a leaf, never nested (that crashed the old renderer too)
_FUZZ_NESTED = [v for v in _FUZZ_VALUES if not isinstance(v, bytes)]

_FUZZ_KEYS = ["a", "b", "payload", "topic", "v", "s"]


def _fuzz_template(rng):
    parts = []
    for _ in range(rng.randint(0, 6)):
        kind = rng.random()
        if kind < 0.45:
            parts.append(rng.choice(
                ["lit ", "x%sy", "100%", "{", "}", "$", "${", "a.b ",
                 "", "plain-literal "]
            ))
        else:
            depth = rng.randint(1, 3)
            parts.append(
                "${" + ".".join(
                    rng.choice(_FUZZ_KEYS) for _ in range(depth)
                ) + "}"
            )
    return "".join(parts)


def _fuzz_data(rng, depth=0):
    d = {}
    for k in _FUZZ_KEYS:
        if rng.random() < 0.6:
            if depth < 2 and rng.random() < 0.3:
                d[k] = _fuzz_data(rng, depth + 1)
            elif depth:
                d[k] = rng.choice(_FUZZ_NESTED)
            else:
                d[k] = rng.choice(_FUZZ_VALUES)
    return d


@pytest.mark.parametrize("seed", [3, 11, 29, 57])
def test_compiled_template_matches_old_renderer_fuzz(seed):
    rng = random.Random(seed)
    for _ in range(400):
        tmpl = _fuzz_template(rng)
        data = _fuzz_data(rng)
        expect = _old_render_template(tmpl, data)
        prog = TemplateProgram(tmpl)
        assert prog.render(data) == expect, tmpl
        # the public entry point rides the cache
        assert render_template(tmpl, data) == expect, tmpl


@pytest.mark.parametrize("seed", [5, 17])
def test_render_rows_matches_per_row_render(seed):
    rng = random.Random(seed)
    for _ in range(120):
        tmpl = _fuzz_template(rng)
        prog = TemplateProgram(tmpl)
        rows = [_fuzz_data(rng) for _ in range(rng.randint(1, 7))]
        # column view: union of head keys, column per key
        heads = set()
        for part in prog.parts:
            if part.__class__ is not str:
                heads.add(part[0])
        cols = {
            h: [r.get(h) for r in rows]
            for h in heads
            if any(h in r for r in rows)
        }
        got = prog.render_rows(cols, len(rows))
        # render_rows reads missing-in-SOME-rows keys through the
        # column (None cells); mirror that view in the scalar twin
        twin = [
            {h: c[i] for h, c in cols.items()}
            for i in range(len(rows))
        ]
        assert got == [prog.render(t) for t in twin], tmpl


def test_compile_template_caches():
    a = compile_template("x ${v} y")
    b = compile_template("x ${v} y")
    assert a is b
    assert a.n_slots == 1


# ------------------------------------------- lowering unit behavior


def test_compile_select_covers_and_rejects():
    lowered = [
        "SELECT * FROM \"t/#\"",
        "SELECT payload.a AS a, topic FROM \"t/#\"",
        "SELECT payload.a + 1 AS b, 'k' AS lit FROM \"t/#\"",
        "SELECT payload.a * 2 + payload.b AS c FROM \"t/#\"",
        "SELECT payload.a div 2 AS d, payload.a mod 2 AS e "
        "FROM \"t/#\"",
        "SELECT -payload.a AS n FROM \"t/#\"",
    ]
    degraded = [
        "SELECT lower(payload.s) AS l FROM \"t/#\"",
        "SELECT CASE WHEN qos = 0 THEN 1 ELSE 2 END AS c "
        "FROM \"t/#\"",
        "SELECT payload.a > 1 AS cmp FROM \"t/#\"",
    ]
    for sql in lowered:
        assert compile_select(parse_sql(sql)) is not None, sql
    for sql in degraded:
        assert compile_select(parse_sql(sql)) is None, sql


def test_select_stack_appends_paths_after_base():
    base = [("payload", "w"), ("qos",)]
    stack = build_select_stack(
        [("r1", parse_sql(
            'SELECT payload.a AS a, qos FROM "t/#"'
        ))],
        base,
    )
    # base paths keep their indices; new SELECT paths strictly append
    assert stack.all_paths[:2] == (("payload", "w"), ("qos",))
    assert ("payload", "a") in stack.all_paths[2:]
    # qos reuses the base plane
    prog = stack.progs["r1"]
    qos_slot = dict(
        (p, k) for k, p in enumerate(prog.paths)
    )[("qos",)]
    assert stack.planes["r1"][qos_slot] == 1


# ----------------------------------- seeded-world referee equality


class FakeWorker:
    """Just enough of BufferWorker for the engine's sink handoff."""

    def __init__(self):
        self.queries = []

    def enqueue(self, q):
        self.queries.append(q)
        return True

    def enqueue_batch(self, qs):
        self.queries.extend(qs)
        return 0


_SELECTS = [
    "*",
    "payload.a AS a, topic",
    "payload.a + payload.b AS s, payload.a * 2 AS d, 'k' AS lit",
    "payload.s + '!' AS cat, clientid",
    "payload.a / payload.b AS q, payload.a mod 2 AS m",
    "payload.obj AS o, payload.a AS a",
    "payload.a AS x, payload.b AS x",  # duplicate alias
    "-payload.a AS neg, 7 AS seven",
    # degraded per rule (function call / CASE): scalar interpreter
    "lower(clientid) AS l, payload.a AS a",
    "CASE WHEN qos = 0 THEN 'q0' ELSE 'qn' END AS c",
]

_WHERES = [
    "payload.a >= 0", "payload.b > 0", "qos >= 0",
    "payload.s = 'x' OR payload.a < 2", "is_not_null(payload.a)",
]

_TEMPLATES = [
    None,  # JSON dump of the selected columns
    '{"t":"${topic}","a":${a}}',
    "v=${a} s=${s} cat=${cat} missing=${nope}",
    "${o} ${x} ${neg}",
]

_FILTERS = ["t/#", "t/+/x", "t/1/x", "t/2/#"]
_TOPICS = ["t/1/x", "t/2/x", "t/2/y", "q/none"]


def _world(seed):
    rng = random.Random(seed)
    rules = []
    for i in range(rng.randint(5, 10)):
        sel = rng.choice(_SELECTS)
        rules.append((
            f"r{i}",
            f'SELECT {sel} FROM "{rng.choice(_FILTERS)}" '
            f"WHERE {rng.choice(_WHERES)}",
            rng.choice(_TEMPLATES),
        ))
    windows = []
    for _ in range(6):
        win = []
        for _ in range(rng.randint(1, 12)):
            payload = {}
            if rng.random() < 0.85:
                payload["a"] = (
                    rng.randint(-5, 5) if rng.random() < 0.7
                    else round(rng.uniform(-5, 5), 2)
                )
            if rng.random() < 0.7:
                payload["b"] = rng.randint(0, 3)
            if rng.random() < 0.6:
                payload["s"] = rng.choice(["x", "y", "zz"])
            if rng.random() < 0.3:
                payload["obj"] = rng.choice(
                    [{"k": 1}, [1, 2], {"k": {"d": True}}]
                )
            body = json.dumps(payload).encode()
            if rng.random() < 0.08:
                body = b"not json {"
            win.append(Message(
                topic=rng.choice(_TOPICS), payload=body,
                qos=rng.randint(0, 2),
                retain=bool(rng.getrandbits(1)),
                from_client=rng.choice(["c1", "c2"]),
                timestamp=1.7e9,
            ))
        windows.append(win)
    return rules, windows


def _run_select_world(rules, windows, force):
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    b = Broker(config=cfg)
    b.rules.select_force = force
    sinks, aggs = {}, {}
    for rid, sql, tmpl in rules:
        sinks[rid] = FakeWorker()
        b.resources._workers[f"sink:{rid}"] = sinks[rid]
        records = []
        aggs[rid] = records
        agg = Aggregator(
            lambda k, body: None, interval_s=1e9, max_records=10**9
        )
        real_push = agg.push
        agg.push = lambda rs, _rp=real_push, _rec=records: (
            _rec.extend(rs), _rp(rs)
        )[1]
        b.rules.add_rule(rid, sql, actions=[
            SinkAction(f"sink:{rid}", payload=tmpl),
            AggregateAction(agg),
        ])
    for win in windows:
        b.publish_many([
            Message(
                topic=m.topic, payload=m.payload, qos=m.qos,
                retain=m.retain, from_client=m.from_client,
                timestamp=m.timestamp,
            )
            for m in win
        ])
    counters = {
        rid: (r.matched, r.passed, r.actions_success,
              r.actions_failed)
        for rid, r in b.rules.rules.items()
    }
    return (
        {rid: w.queries for rid, w in sinks.items()},
        aggs,
        counters,
        b.rules.stats(),
    )


@pytest.mark.parametrize("seed", [2, 9, 13, 31, 71])
def test_batched_select_bit_identical_to_scalar_referee(seed):
    """Per-(rule, action) sink query streams, aggregate record
    streams and action counters identical between the batched lane
    and the scalar interpreter referee, over worlds mixing lowered
    and degraded rules."""
    rules, windows = _world(seed)
    ref = _run_select_world(rules, windows, "scalar")
    bat = _run_select_world(rules, windows, "batched")
    assert ref[0] == bat[0], "sink query streams differ"
    assert ref[1] == bat[1], "aggregate record streams differ"
    assert ref[2] == bat[2], "rule counters differ"
    # the lanes really ran where they claim
    assert ref[3]["select_batched_rows"] == 0
    if bat[3]["select_lowered"] and any(
        n for n in ref[0].values()
    ):
        assert (
            bat[3]["select_batched_rows"] > 0
            or bat[3]["select_scalar_rows"] > 0
        )


def test_int_ness_and_arith_edges_through_batched_lane():
    """The typing contract: json.dumps(5) != json.dumps(5.0), string
    '+' concat, div-by-zero -> None field, missing operand -> None,
    lookup ERROR operand -> None — identical in both lanes."""
    rules = [(
        "r1",
        "SELECT payload.v * 2 + 1 AS v2, payload.s + '-t' AS cat, "
        'payload.v / payload.z AS dz, payload.v + payload.nope AS mn '
        'FROM "t/#" WHERE is_not_null(payload.v)',
        None,
    )]
    msgs = [
        Message(topic="t/a", payload=json.dumps(
            {"v": 2, "s": "x", "z": 0}
        ).encode()),
        Message(topic="t/a", payload=json.dumps(
            {"v": 2.0, "s": "y", "z": 2}
        ).encode()),
        Message(topic="t/a", payload=b"not json {"),
    ]
    ref = _run_select_world(rules, [msgs], "scalar")
    bat = _run_select_world(rules, [msgs], "batched")
    assert ref[0] == bat[0]
    q0 = json.loads(bat[0]["r1"][0])
    assert q0["v2"] == 5 and json.dumps(q0["v2"]) == "5"  # int stays
    assert q0["cat"] == "x-t"
    assert q0["dz"] is None  # div by zero
    assert q0["mn"] is None  # missing operand
    q1 = json.loads(bat[0]["r1"][1])
    assert q1["v2"] == 5.0 and json.dumps(q1["v2"]) == "5.0"


def test_select_force_and_ewma_breaker_stats():
    """select_force pins the lane; the cost-EWMA breaker state is
    visible in stats()."""
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    b = Broker(config=cfg)
    w = FakeWorker()
    b.resources._workers["s"] = w
    b.rules.add_rule(
        "r1", 'SELECT payload.a AS a FROM "t/#" WHERE payload.a > 0',
        actions=[SinkAction("s")],
    )
    msgs = [
        Message(topic="t/1", payload=b'{"a": 3}') for _ in range(4)
    ]
    b.rules.select_force = "scalar"
    b.publish_many(list(msgs))
    st = b.rules.stats()
    assert st["select_scalar_rows"] == 4
    assert st["select_batched_rows"] == 0
    b.rules.select_force = "batched"
    b.publish_many(list(msgs))
    st = b.rules.stats()
    assert st["select_batched_rows"] == 4
    assert st["select_lowered"] == 1
    assert "select_batch_disabled" in st
    assert "select_batched_us_ewma" in st
    assert len(w.queries) == 8
