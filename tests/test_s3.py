"""S3 client: SigV4 signing verified against the worked example from
the public signature spec, and the client + sink driven against a
local S3-compatible fake that checks the authorization header."""

import asyncio
import datetime
import hashlib
import hmac

from emqx_tpu.s3 import S3Client, S3Sink


def run(coro):
    return asyncio.run(coro)


def test_sigv4_shape_and_determinism():
    c = S3Client("https://s3.us-east-1.amazonaws.com", "bkt",
                 "AKIDEXAMPLE", "secret")
    now = datetime.datetime(2013, 5, 24, 0, 0, 0,
                            tzinfo=datetime.timezone.utc)
    url, headers = c.sign("PUT", "a/b c.txt", b"hello", now=now)
    assert url == "https://s3.us-east-1.amazonaws.com/bkt/a/b%20c.txt"
    assert headers["x-amz-date"] == "20130524T000000Z"
    assert headers["x-amz-content-sha256"] == hashlib.sha256(
        b"hello").hexdigest()
    auth = headers["authorization"]
    assert auth.startswith(
        "AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/20130524/us-east-1/"
        "s3/aws4_request, SignedHeaders=host;x-amz-content-sha256;"
        "x-amz-date, Signature="
    )
    # deterministic for fixed time + inputs
    _, headers2 = c.sign("PUT", "a/b c.txt", b"hello", now=now)
    assert headers2["authorization"] == auth


def _verify_sigv4(store_secret, request_headers, method, path, body):
    """Server-side re-derivation: recompute the signature from the
    request exactly as S3 does and compare."""
    auth = request_headers["authorization"]
    cred = auth.split("Credential=")[1].split(",")[0]
    access_key, datestamp, region, svc, _ = cred.split("/")
    amz_date = request_headers["x-amz-date"]
    payload_hash = hashlib.sha256(body).hexdigest()
    assert request_headers["x-amz-content-sha256"] == payload_hash
    headers = {
        "host": request_headers["host"],
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed = ";".join(sorted(headers))
    canonical = "\n".join([
        method, path, "",
        "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)),
        signed, payload_hash,
    ])
    scope = f"{datestamp}/{region}/{svc}/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest(),
    ])
    k = hmac.new(b"AWS4" + store_secret.encode(), datestamp.encode(),
                 hashlib.sha256).digest()
    for part in (region, svc, "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    want = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    return auth.endswith("Signature=" + want)


def test_put_get_delete_against_fake_s3():
    async def t():
        from aiohttp import web

        objects = {}

        async def handle(request):
            body = await request.read()
            ok = _verify_sigv4(
                "sekrit", request.headers, request.method,
                request.path, body,
            )
            if not ok:
                return web.Response(status=403, text="SignatureDoesNotMatch")
            key = request.path
            if request.method == "PUT":
                objects[key] = body
                return web.Response(status=200)
            if request.method == "GET":
                if key not in objects:
                    return web.Response(status=404)
                return web.Response(body=objects[key])
            if request.method == "DELETE":
                objects.pop(key, None)
                return web.Response(status=204)
            return web.Response(status=400)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handle)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        client = S3Client(f"http://127.0.0.1:{port}", "exports",
                          "AKID", "sekrit", region="local")
        await client.put_object("ft/dev1/readings.bin", b"\x01\x02\x03")
        got = await client.get_object("ft/dev1/readings.bin")
        assert got == b"\x01\x02\x03"
        await client.delete_object("ft/dev1/readings.bin")
        try:
            await client.get_object("ft/dev1/readings.bin")
            raise AssertionError("expected 404")
        except RuntimeError:
            pass

        # the sink through the buffered resource layer
        from emqx_tpu.resources import BufferWorker

        worker = BufferWorker(S3Sink(client), max_buffer=16)
        await worker.start()
        worker.enqueue(("rules/out.json", b'{"x":1}'))
        for _ in range(100):
            if "/exports/rules/out.json" in objects:
                break
            await asyncio.sleep(0.05)
        assert objects.get("/exports/rules/out.json") == b'{"x":1}'
        await worker.stop()
        await runner.cleanup()

    run(t())


def test_ft_s3_exporter_end_to_end(tmp_path):
    """Config-wired ft S3 export: a $file transfer assembled by the
    broker uploads to the (fake) S3 store as <fileid>/<name>."""
    import json

    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.config import BrokerConfig, ListenerConfig
    from mqtt_client import TestClient

    async def t():
        from aiohttp import web

        objects = {}

        async def handle(request):
            body = await request.read()
            if not _verify_sigv4("sek", request.headers, request.method,
                                 request.path, body):
                return web.Response(status=403)
            if request.method == "PUT":
                objects[request.path] = body
                return web.Response(status=200)
            if request.method == "GET":
                return (web.Response(body=objects[request.path])
                        if request.path in objects
                        else web.Response(status=404))
            return web.Response(status=400)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handle)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.ft.enable = True
        cfg.ft.storage_dir = str(tmp_path / "ft")
        cfg.ft.s3 = {
            "endpoint": f"http://127.0.0.1:{port}",
            "bucket": "uploads",
            "access_key": "AK",
            "secret_key": "sek",
            "region": "local",
        }
        srv = BrokerServer(cfg)
        await srv.start()

        c = TestClient(srv.listeners[0].port, "up2")
        await c.connect()
        await c.subscribe("$file/fx/response")
        data = b"abc123" * 100
        await c.publish("$file/fx/init", json.dumps(
            {"name": "cam.bin", "size": len(data)}).encode())
        assert json.loads((await c.recv_publish()).payload)["result"] == "ok"
        await c.publish("$file/fx/0", data)
        await c.publish("$file/fx/fin", b"")
        assert json.loads((await c.recv_publish()).payload)["result"] == "ok"

        for _ in range(100):
            if "/uploads/fx/cam.bin" in objects:
                break
            await asyncio.sleep(0.05)
        assert objects.get("/uploads/fx/cam.bin") == data

        await c.disconnect()
        await srv.stop()
        await runner.cleanup()

    run(t())
