"""racesim (tools/racesim) + the forced-interleaving sanitizer
(emqx_tpu.testing.interleave).

Three layers, mirroring the crashsim suite's shape:

  * harness properties — same seed => same schedule, a failing trace
    replays as a script, the preemption budget bounds overhead, the
    declared failpoint seams become yieldpoints;
  * reproduction — the canonical check-then-act race fails under the
    seeded sweep and the exhaustive small-schedule mode, the re-checked
    fix survives every one of the same schedules;
  * hostile-schedule regressions for the sites the RACE8xx burn-down
    fixed (ResumeScheduler stop/start, ClusterNode.stop's task-list
    swap, _take_parked's snapshot scan): the repaired shapes hold their
    invariants under adversarial interleaving, pinned so a future edit
    cannot quietly reintroduce the window.
"""

import asyncio

import pytest

from emqx_tpu import failpoints
from emqx_tpu.broker.resume import ResumeScheduler, _Job
from emqx_tpu.cluster.node import ClusterNode
from emqx_tpu.testing.interleave import (
    SchedulePolicy, drive, failpoint_yieldpoints,
)
from tools.racesim import run_exhaustive, run_schedule, run_seeds


# ------------------------------------------------------ toy workloads

class _CheckThenAct:
    """The canonical RACE801 shape: membership check and pop separated
    by an await, so two concurrent takers can both pass the check."""

    def __init__(self):
        self.pending = {"k": 1}

    async def take_racy(self):
        if "k" in self.pending:
            await asyncio.sleep(0)       # the window
            self.pending.pop("k")        # KeyError when raced

    async def take_fixed(self):
        if "k" in self.pending:
            await asyncio.sleep(0)
            self.pending.pop("k", None)  # act re-validates


def _racy_workload():
    async def main():
        obj = _CheckThenAct()
        await asyncio.gather(obj.take_racy(), obj.take_racy())
    return main()


def _fixed_workload():
    async def main():
        obj = _CheckThenAct()
        await asyncio.gather(obj.take_fixed(), obj.take_fixed())
    return main()


# -------------------------------------------------- harness properties

def test_same_seed_same_schedule():
    a = run_schedule(_racy_workload,
                     SchedulePolicy(mode="random", seed=42), label="a")
    b = run_schedule(_racy_workload,
                     SchedulePolicy(mode="random", seed=42), label="b")
    assert a.trace == b.trace
    assert a.trace, "no yieldpoints were exercised"
    assert type(a.error) is type(b.error)


def test_failing_trace_replays_as_script():
    outcomes = run_seeds(_racy_workload, seeds=range(8))
    failing = next(o for o in outcomes if o.failed)
    script = [n for _site, n in failing.trace]
    replay = run_schedule(
        _racy_workload, SchedulePolicy(mode="script", script=script),
        label="replay",
    )
    assert replay.failed
    assert type(replay.error) is type(failing.error)


def test_preemption_budget_bounds_overhead():
    async def main():
        for _ in range(50):
            await asyncio.sleep(0)

    policy = SchedulePolicy(mode="random", seed=1, prob=1.0,
                            max_preempts=2)
    asyncio.run(drive(main(), policy))
    assert sum(n for _site, n in policy.trace) <= 2
    assert len(policy.trace) >= 50  # every yieldpoint still consulted


def test_failpoint_seams_become_yieldpoints():
    policy = SchedulePolicy(mode="random", seed=3)

    async def main():
        await failpoints.evaluate_async("racesim.fixture.seam")

    with failpoint_yieldpoints(policy):
        asyncio.run(drive(main(), policy))
    assert any(site == "seam:racesim.fixture.seam"
               for site, _n in policy.trace)
    # the context restored the module seam hooks on exit
    assert not failpoints.enabled


# ----------------------------------------------------- reproduction

def test_seeded_sweep_reproduces_check_then_act():
    outcomes = run_seeds(_racy_workload, seeds=range(8))
    failing = [o for o in outcomes if o.failed]
    assert failing, "no seed reproduced the race"
    assert all(isinstance(o.error, KeyError) for o in failing)


def test_fixed_shape_survives_every_seed():
    outcomes = run_seeds(_fixed_workload, seeds=range(8))
    bad = [o for o in outcomes if o.failed]
    assert not bad, f"{bad[0].label}: {bad[0].error!r}"


def test_exhaustive_small_schedules():
    racy = run_exhaustive(_racy_workload, points=4)
    assert len(racy) == 16
    assert any(o.failed for o in racy)
    fixed = run_exhaustive(_fixed_workload, points=4)
    assert not any(o.failed for o in fixed)


@pytest.mark.slow
def test_exhaustive_large_schedule_space():
    """The real exhaustive mode: 2^10 schedules each way."""
    racy = run_exhaustive(_racy_workload, points=10)
    assert any(o.failed for o in racy)
    fixed = run_exhaustive(_fixed_workload, points=10)
    bad = [o for o in fixed if o.failed]
    assert not bad, f"{bad[0].label}: {bad[0].error!r}"


def test_targeted_mode_finds_fifo_assumption():
    """Forced preemption finds what the normal scheduler cannot: the
    watcher's 'one turn per yield' FIFO assumption holds under the
    undisturbed schedule and breaks once its awaits are widened."""

    def workload():
        async def main():
            counter = {"n": 0}

            async def ticker():
                for _ in range(6):
                    counter["n"] += 1
                    await asyncio.sleep(0)

            t = asyncio.get_running_loop().create_task(ticker())
            await asyncio.sleep(0)
            before = counter["n"]
            await asyncio.sleep(0)  # "exactly one turn" assumption
            assert counter["n"] - before <= 1, "FIFO assumption broken"
            await t
        return main()

    undisturbed = run_schedule(
        workload, SchedulePolicy(mode="script", script=()),
        label="undisturbed",
    )
    assert not undisturbed.failed, repr(undisturbed.error)

    # "main:" matches the driver sites of the outer coroutine only
    # (ticker's qualname continues "...main.<locals>.ticker")
    hostile = SchedulePolicy(mode="targeted", sites=("main:",),
                             seed=0, prob=1.0)
    out = run_schedule(workload, hostile, label="targeted")
    assert out.failed and isinstance(out.error, AssertionError)
    assert all(n == 0 for site, n in out.trace if "main:" not in site)


# ------------------------- hostile-schedule regressions (fixed sites)

class _Cfg:
    max_concurrent = 4
    park_queue_cap = 8


class _Olp:
    defer_admissions = False

    def shed(self, *a):
        pass


class _Metrics:
    def inc(self, *a, **k):
        pass


class _Broker:
    def __init__(self):
        self.olp = _Olp()
        self.metrics = _Metrics()


def _resume_stop_start_workload():
    async def main():
        sched = ResumeScheduler(_Broker(), _Cfg())
        await sched.start()
        await asyncio.sleep(0)  # let the drive task park on its event
        # a stop() and a start() racing: the start lands inside stop's
        # cancel window and must find the stopped state already
        # committed (running False, no task) — not a torn running=False
        # with the old task still registered, which made it no-op and
        # leave the scheduler dead
        await asyncio.gather(sched.stop(), sched.start())
        assert sched.running, "start() during stop() left it dead"
        assert sched._task is not None
        await sched.stop()
        assert not sched.running and sched._task is None
    return main()


def test_resume_scheduler_stop_start_race():
    outcomes = run_seeds(_resume_stop_start_workload, seeds=range(10))
    bad = [o for o in outcomes if o.failed]
    assert not bad, f"{bad[0].label}: {bad[0].error!r}"


def _node_stop_workload():
    async def main():
        node = object.__new__(ClusterNode)
        node._started = True
        node.raft_conf = None
        node.raft_ds = None

        class _Transport:
            async def stop(self):
                pass

        node.transport = _Transport()
        loop = asyncio.get_running_loop()
        old = loop.create_task(asyncio.sleep(30))
        node._tasks = [old]
        late = loop.create_task(asyncio.sleep(30))

        async def restarter():
            # a start() racing mid-stop: repopulates _tasks while
            # stop() is parked reaping the old generation
            node._tasks.append(late)

        await asyncio.gather(node.stop(), restarter())
        try:
            assert late in node._tasks, \
                "stop() dropped the racing start()'s task"
            assert not late.cancelled()
        finally:
            late.cancel()
            try:
                await late
            except asyncio.CancelledError:
                pass
        assert old.cancelled() or old.done()
    return main()


def test_cluster_node_stop_keeps_racing_starts_tasks():
    outcomes = run_seeds(_node_stop_workload, seeds=range(10))
    bad = [o for o in outcomes if o.failed]
    assert not bad, f"{bad[0].label}: {bad[0].error!r}"


def test_take_parked_scans_a_snapshot():
    sched = ResumeScheduler(_Broker(), _Cfg())
    jobs = [_Job(cid, object(), object()) for cid in ("a", "b", "c")]
    for j in jobs:
        sched._parked.append(j)
        sched._parked_ids.add(j.clientid)
    got = sched._take_parked("b")
    assert got is jobs[1]
    assert [j.clientid for j in sched._parked] == ["a", "c"]
    assert sched._parked_ids == {"a", "c"}
    assert sched._take_parked("zz") is None
