"""Native dslog recovery semantics, by direct byte surgery on segment
files: torn tails truncate (crash artifacts), interior CRC breaks
quarantine the suffix instead of silently destroying it, damaged or
empty segments never fail the open, and gc walks around a quarantined
segment.  Mirrors the tokdict suite's skip discipline: the tests only
run where the native lib builds."""

import os
import struct

import pytest

from emqx_tpu.ds.native import DsLog, load


def _lib():
    try:
        return load()
    except Exception:
        return None


pytestmark = pytest.mark.skipif(
    _lib() is None, reason="native dslog unavailable"
)

HDR = struct.Struct("<IIIQQ")  # len, crc32, stream, ts, seq
HDR_LEN = HDR.size  # 28


def parse_segment(path):
    """(offset, len, crc, stream, ts, seq, payload) per parseable
    record — the on-disk format documented in native/dslog.cpp."""
    with open(path, "rb") as f:
        data = f.read()
    recs = []
    off = 0
    while off + HDR_LEN <= len(data):
        ln, crc, stream, ts, seq = HDR.unpack_from(data, off)
        if ln > (128 << 20) or off + HDR_LEN + ln > len(data):
            break
        recs.append(
            (off, ln, crc, stream, ts, seq,
             data[off + HDR_LEN: off + HDR_LEN + ln])
        )
        off += HDR_LEN + ln
    return recs


def seg0(d):
    return os.path.join(d, "seg-000000.log")


def fill(d, n=6, stream=7, seg_bytes=0):
    log = DsLog(d, seg_bytes=seg_bytes)
    for i in range(n):
        log.append(stream, 1000 + i, b"payload-%03d" % i)
    log.sync()
    log.close()


def test_clean_reopen_serves_everything(tmp_path):
    d = str(tmp_path / "db")
    fill(d, n=6)
    log = DsLog(d)
    assert log.stream_count(7) == 6
    assert log.corrupt_records() == 0
    assert log.quarantined_count() == 0
    assert [p for _, _, p in log.scan(7, 0)] == [
        b"payload-%03d" % i for i in range(6)
    ]
    log.close()


def test_torn_tail_truncates(tmp_path):
    """A record cut mid-write by a crash is the normal torn-tail
    artifact: recovery truncates it away and raises no corruption."""
    d = str(tmp_path / "db")
    fill(d, n=5)
    recs = parse_segment(seg0(d))
    last_off = recs[-1][0]
    # cut the file mid-way through the last record's payload
    with open(seg0(d), "r+b") as f:
        f.truncate(last_off + HDR_LEN + 3)
    log = DsLog(d)
    assert log.stream_count(7) == 4
    assert log.corrupt_records() == 0
    assert log.quarantined_count() == 0
    # the partial record was truncated off the file itself
    assert os.path.getsize(seg0(d)) == last_off
    # appends continue in the SAME segment (no quarantine roll)
    log.append(7, 9000, b"after")
    log.sync()
    assert not os.path.exists(os.path.join(d, "seg-000001.log"))
    log.close()


def test_torn_header_at_eof_truncates(tmp_path):
    d = str(tmp_path / "db")
    fill(d, n=3)
    size = os.path.getsize(seg0(d))
    with open(seg0(d), "ab") as f:
        f.write(b"\x05\x00")  # 2 bytes of a header that never finished
    log = DsLog(d)
    assert log.stream_count(7) == 3
    assert log.corrupt_records() == 0
    assert os.path.getsize(seg0(d)) == size
    log.close()


def test_interior_payload_flip_quarantines(tmp_path):
    """An interior CRC break (bit flip with intact records after it)
    must quarantine the suffix — served prefix intact, file preserved
    byte-for-byte, corruption counted — never silently truncated (the
    pre-PR behavior destroyed the whole suffix)."""
    d = str(tmp_path / "db")
    fill(d, n=6)
    recs = parse_segment(seg0(d))
    size = os.path.getsize(seg0(d))
    victim = recs[2]
    with open(seg0(d), "r+b") as f:
        f.seek(victim[0] + HDR_LEN)  # first payload byte of record 2
        b = f.read(1)
        f.seek(victim[0] + HDR_LEN)
        f.write(bytes((b[0] ^ 0xFF,)))
    log = DsLog(d)
    # intact prefix serves; suffix quarantined
    assert log.stream_count(7) == 2
    assert [p for _, _, p in log.scan(7, 0)] == [
        b"payload-000", b"payload-001"
    ]
    assert log.corrupt_records() == 4  # records 2..5
    assert log.quarantined_count() == 1
    # forensics: the damaged file was NOT truncated
    assert os.path.getsize(seg0(d)) == size
    # appends roll past the quarantined segment into a fresh one
    log.append(7, 9000, b"after-quarantine")
    log.sync()
    assert os.path.exists(os.path.join(d, "seg-000001.log"))
    assert log.stream_count(7) == 3
    log.close()
    # and a second recovery keeps the same picture (idempotent)
    log = DsLog(d)
    assert log.stream_count(7) == 3
    assert log.corrupt_records() == 4
    assert [p for _, _, p in log.scan(7, 0)] == [
        b"payload-000", b"payload-001", b"after-quarantine"
    ]
    log.close()


def test_interior_header_flip_quarantines(tmp_path):
    """A flipped length field (implausible len with data after the
    header) is interior corruption, not a torn tail."""
    d = str(tmp_path / "db")
    fill(d, n=4)
    recs = parse_segment(seg0(d))
    with open(seg0(d), "r+b") as f:
        f.seek(recs[1][0])
        f.write(struct.pack("<I", 0xFFFFFFFF))
    log = DsLog(d)
    assert log.stream_count(7) == 1
    assert log.corrupt_records() >= 1
    assert log.quarantined_count() == 1
    log.close()


def test_empty_segment_survives_open(tmp_path):
    d = str(tmp_path / "db")
    os.makedirs(d)
    with open(seg0(d), "wb"):
        pass
    log = DsLog(d)
    assert log.corrupt_records() == 0
    log.append(3, 100, b"x")
    assert log.stream_count(3) == 1
    log.close()


def test_garbage_segment_survives_open(tmp_path):
    d = str(tmp_path / "db")
    os.makedirs(d)
    with open(seg0(d), "wb") as f:
        f.write(b"\xff" * 100)  # len field = 0xFFFFFFFF: implausible
    log = DsLog(d)
    assert log.quarantined_count() == 1
    assert log.corrupt_records() >= 1
    # appends land in a fresh segment, replay serves them
    log.append(3, 100, b"x")
    log.sync()
    assert os.path.exists(os.path.join(d, "seg-000001.log"))
    assert [p for _, _, p in log.scan(3, 0)] == [b"x"]
    log.close()


def test_gc_across_quarantined_segment(tmp_path):
    """gc reclaims old clean segments around a quarantined one; the
    quarantined segment itself is preserved (its suffix's timestamps
    are unknowable, so age-based reclaim never applies)."""
    d = str(tmp_path / "db")
    log = DsLog(d, seg_bytes=64)  # every record overflows a segment
    for i in range(4):
        log.append(1, 1000 + i, b"record-%d" % i + b"." * 60)
    log.sync()
    log.close()
    segs = sorted(
        n for n in os.listdir(d) if n.startswith("seg-")
    )
    assert len(segs) >= 3
    # corrupt segment 0's record interior?  A one-record segment's CRC
    # break is a torn tail (extent reaches EOF) — append garbage after
    # the record so the break is interior.
    with open(os.path.join(d, segs[0]), "r+b") as f:
        f.seek(HDR_LEN)
        f.write(b"\x00")  # flip payload of the only record
        f.seek(0, 2)
        f.write(b"\xee" * 8)  # trailing bytes: damage is interior
    log = DsLog(d, seg_bytes=64)
    assert log.quarantined_count() == 1
    reclaimed = log.gc(int(5000))  # cutoff beyond every record
    assert reclaimed >= 1
    # quarantined segment file survives the gc
    assert os.path.exists(os.path.join(d, segs[0]))
    # clean old segments (not current, not quarantined) were unlinked
    remaining = sorted(
        n for n in os.listdir(d) if n.startswith("seg-")
    )
    assert len(remaining) < len(segs) + 1
    log.close()


def test_quarantine_count_accumulates_across_segments(tmp_path):
    d = str(tmp_path / "db")
    log = DsLog(d, seg_bytes=64)
    for i in range(4):
        log.append(1, 1000 + i, b"rec-%d" % i + b"." * 60)
    log.sync()
    log.close()
    segs = sorted(n for n in os.listdir(d) if n.startswith("seg-"))
    for name in segs[:2]:
        path = os.path.join(d, name)
        with open(path, "r+b") as f:
            f.seek(HDR_LEN)
            f.write(b"\x00")
            f.seek(0, 2)
            f.write(b"\xee" * 8)
    log = DsLog(d, seg_bytes=64)
    assert log.quarantined_count() == 2
    assert log.corrupt_records() >= 2
    log.close()
