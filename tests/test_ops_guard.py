"""Operational guards: banned CONNECT, flapping ban, alarms over $SYS
and REST, slow-subscription tracking (emqx_banned / emqx_flapping /
emqx_alarm / emqx_slow_subs parity)."""

import asyncio
import tempfile

# auto-cleaned parent for per-test mgmt stores (finalized at interpreter exit)
_MGMT_TMP = tempfile.TemporaryDirectory(prefix="emqx-mgmt-")

import aiohttp

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.ops_guard import SlowSubs
from api_helper import auth_session
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def make_server(**kw):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.api.enable = True
    cfg.api.data_dir = tempfile.mkdtemp(dir=_MGMT_TMP.name)
    cfg.api.port = 0
    for k, v in kw.items():
        setattr(cfg, k, v)
    return BrokerServer(cfg)


def test_banned_client_rejected_at_connect():
    async def t():
        srv = make_server()
        await srv.start()
        port = srv.listeners[0].port
        srv.broker.banned.ban("clientid", "evil", reason="test")
        c = TestClient(port, "evil")
        ack = await c.connect()
        assert ack.reason_code == 0x8A  # banned
        await c.close()
        # expiry frees the ban
        srv.broker.banned.ban("clientid", "brief", seconds=-1)
        c2 = TestClient(port, "brief")
        ack2 = await c2.connect()
        assert ack2.reason_code == 0
        await c2.disconnect()
        await srv.stop()

    run(t())


def test_flapping_client_gets_banned():
    async def t():
        from emqx_tpu.config import FlappingConfig

        srv = make_server(
            flapping=FlappingConfig(max_count=3, window=10.0, ban_time=60.0)
        )
        await srv.start()
        port = srv.listeners[0].port
        for _ in range(3):
            c = TestClient(port, "flappy")
            await c.connect()
            await c.disconnect()
            await asyncio.sleep(0.02)
        c = TestClient(port, "flappy")
        ack = await c.connect()
        assert ack.reason_code == 0x8A  # banned for flapping
        await c.close()
        assert any(
            a.name.startswith("flapping/") for a in srv.broker.alarms.active()
        )
        await srv.stop()

    run(t())


def test_alarms_rest_and_sys():
    async def t():
        srv = make_server()
        await srv.start()
        port = srv.listeners[0].port
        mon = TestClient(port, "mon")
        await mon.connect()
        await mon.subscribe("$SYS/#")

        srv.broker.alarms.activate(
            "high_mem", details={"pct": 93}, message="memory high"
        )
        pkt = await mon.recv_publish()
        assert pkt.topic.endswith("/alarms/activate")
        assert b"high_mem" in pkt.payload

        http, api = await auth_session(srv)
        async with http:
            async with http.get(api + "/api/v5/alarms") as r:
                data = await r.json()
            assert data["data"][0]["name"] == "high_mem"
            async with http.delete(api + "/api/v5/alarms") as r:
                assert r.status == 204
            async with http.get(api + "/api/v5/alarms") as r:
                assert (await r.json())["data"] == []
            async with http.get(
                api + "/api/v5/alarms?activated=false"
            ) as r:
                hist = await r.json()
            assert hist["data"][0]["name"] == "high_mem"

        await mon.disconnect()
        await srv.stop()

    run(t())


def test_banned_rest_crud():
    async def t():
        srv = make_server()
        await srv.start()
        http, api = await auth_session(srv)
        async with http:
            async with http.post(
                api + "/api/v5/banned",
                json={"as": "peerhost", "who": "10.0.0.9", "seconds": 60},
            ) as r:
                assert r.status == 201
            async with http.get(api + "/api/v5/banned") as r:
                data = await r.json()
            assert data["data"][0]["who"] == "10.0.0.9"
            async with http.delete(
                api + "/api/v5/banned/peerhost/10.0.0.9"
            ) as r:
                assert r.status == 204
        await srv.stop()

    run(t())


def test_slow_subs_topk():
    ss = SlowSubs(top_k=2, threshold_ms=10.0)
    ss.record("a", "t/1", 5.0)  # below threshold: ignored
    ss.record("b", "t/2", 50.0)
    ss.record("c", "t/3", 500.0)
    ss.record("d", "t/4", 100.0)  # evicts the 50ms entry
    top = ss.top()
    assert [e["clientid"] for e in top] == ["c", "d"]
    assert top[0]["latency_ms"] == 500.0


def test_hierarchical_limiter_levels():
    """The tightest level bounds the connection: listener-aggregate
    and zone buckets throttle even when the per-connection bucket is
    unlimited (emqx_limiter's hierarchy, flattened)."""
    from emqx_tpu.limiter import ConnectionLimiter, HierarchicalLimiter

    listener_shared = ConnectionLimiter(messages_rate=10, messages_burst=10)
    conn_a = HierarchicalLimiter(None, listener_shared, None)
    conn_b = HierarchicalLimiter(
        ConnectionLimiter(messages_rate=1000), listener_shared, None
    )
    # the two connections drain the SHARED bucket together
    assert conn_a.consume(0, 5) == 0.0
    assert conn_b.consume(0, 5) == 0.0
    delay = conn_a.consume(0, 5)
    assert delay > 0.0  # shared bucket exhausted => pause owed
    # a zone bucket above both wins when tighter
    zone = ConnectionLimiter(bytes_rate=100, bytes_burst=100)
    c = HierarchicalLimiter(
        ConnectionLimiter(bytes_rate=10**9), None, zone
    )
    assert c.consume(100, 0) == 0.0
    assert c.consume(100, 0) > 0.0


def test_shared_bucket_debt_accumulates_across_consumers():
    """Aggregate enforcement: N connections hammering one SHARED
    bucket must queue behind its rate — the debt (and so the owed
    pause) keeps growing instead of saturating at one burst, which
    would let the combined rate scale with N."""
    from emqx_tpu.limiter import ConnectionLimiter

    shared = ConnectionLimiter(
        messages_rate=10, messages_burst=10, shared=True
    )
    delays = [shared.consume(0, 1) for _ in range(50)]
    # first burst-worth admitted free, then the wait grows linearly:
    # the 50th consumer owes ~(50-10)/10 = 4s, far beyond one burst
    assert delays[9] == 0.0
    assert delays[-1] > 3.0
    assert delays[-1] > delays[20] > delays[11]
    # a PRIVATE bucket keeps the one-burst debt cap (bounded pause)
    private = ConnectionLimiter(messages_rate=10, messages_burst=10)
    for _ in range(50):
        capped = private.consume(0, 1)
    assert capped <= 1.0 + 1e-6


def test_listener_hierarchy_over_socket():
    """End to end: a listener-aggregate message cap throttles two
    clients' combined publish rate via read-pausing."""
    import time as _time

    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.config import BrokerConfig, ListenerConfig
    from mqtt_client import TestClient

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(
            port=0, max_messages_rate=50, max_bytes_rate=0,
        )]
        srv = BrokerServer(cfg)
        await srv.start()
        port = srv.listeners[0].port
        c1 = TestClient(port, "l1")
        c2 = TestClient(port, "l2")
        await c1.connect()
        await c2.connect()
        t0 = _time.perf_counter()
        # 120 msgs over a 50/s shared cap (burst 50) => >= ~1.3s
        for i in range(60):
            await c1.publish("t/a", b"x", qos=1, timeout=10)
            await c2.publish("t/b", b"x", qos=1, timeout=10)
        elapsed = _time.perf_counter() - t0
        assert elapsed >= 1.0, f"shared cap not enforced ({elapsed:.2f}s)"
        await c1.close()
        await c2.close()
        await srv.stop()

    run(t())


def test_sysmon_samples_and_alarms():
    """emqx_os_mon / emqx_vm_mon role: gauges always land in stats;
    watermark breaches raise alarms with cpu hysteresis."""
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.config import BrokerConfig
    from emqx_tpu.sysmon import SysMonitor

    broker = Broker(BrokerConfig())
    mon = SysMonitor(broker, interval=0.0,
                     sysmem_high_watermark=2.0,  # never fires
                     procmem_high_watermark=2.0,
                     cpu_high_watermark=1e9,
                     cpu_low_watermark=1e9 - 1)
    out = mon.sample()
    stats = broker.stats.all()
    assert "vm.mem.rss_bytes" in stats and stats["vm.mem.rss_bytes"] > 0
    assert "os.cpu.load1_per_core_x1000" in stats
    assert not any(a.name == "high_sysmem"
                   for a in broker.alarms.active())

    # force every watermark under the observed readings: alarms fire
    mon2 = SysMonitor(broker, interval=0.0,
                      sysmem_high_watermark=0.0,
                      procmem_high_watermark=0.0,
                      cpu_high_watermark=-1.0,
                      cpu_low_watermark=-2.0)
    mon2.sample()
    names = {a.name for a in broker.alarms.active()}
    assert {"high_sysmem", "high_procmem", "high_cpu"} <= names

    # hysteresis: readings between low and high KEEP the cpu alarm
    mon3 = SysMonitor(broker, interval=0.0,
                      sysmem_high_watermark=2.0,
                      procmem_high_watermark=2.0,
                      cpu_high_watermark=1e9,
                      cpu_low_watermark=-1.0)
    mon3.sample()
    names = {a.name for a in broker.alarms.active()}
    assert "high_sysmem" not in names  # cleared (above-threshold gone)
    assert "high_cpu" in names         # still above LOW: alarm holds

    # dropping under the low watermark finally clears it
    mon4 = SysMonitor(broker, interval=0.0,
                      sysmem_high_watermark=2.0,
                      procmem_high_watermark=2.0,
                      cpu_high_watermark=1e9,
                      cpu_low_watermark=1e9 - 1)
    mon4.sample()
    assert "high_cpu" not in {a.name for a in broker.alarms.active()}
