"""Vectorized fan-out (PR 3): CSR expansion equivalence + batched-path
regression semantics.

The referee for the window dispatch rewrite: the CSR expansion must
equal the legacy per-filter walk under random sub/unsub churn, and the
delivery-guard / shared skip-dead / no-local / RAP semantics must
survive the batched path bit-identically — including the
single-encode wire bytes and the one-write-per-connection corked
flush."""

import random

import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.session import Session, SubOpts
from emqx_tpu.codec import mqtt as C
from emqx_tpu.message import Message
from emqx_tpu.router import Router


class FakeChannel:
    """Versionless channel stub (legacy per-packet encode path)."""

    def __init__(self):
        self.sent = []
        self.closed = None

    def send_packets(self, pkts):
        self.sent.extend(pkts)

    def close(self, reason):
        self.closed = reason


class WireChannel(Channel):
    """Real Channel over a capturing transport: counts writes and
    serializes every packet exactly as Connection._send_packets does,
    so tests see the true wire bytes and the real cork behavior."""

    def __init__(self, broker, version=C.MQTT_V5):
        self.writes = []
        self.packets = []

        def send(pkts):
            self.packets.extend(pkts)
            self.writes.append(
                b"".join(C.serialize(p, self.version) for p in pkts)
            )

        super().__init__(broker, send=send, close=lambda r: None)
        self.version = version


def _connect(broker, clientid, channel=None, clean_start=True,
             expiry=0.0):
    ch = channel if channel is not None else FakeChannel()
    session, _ = broker.cm.open_session(
        clean_start, clientid, ch, expiry_interval=expiry
    )
    return ch, session


# ------------------------------------------------ CSR property test


def _legacy_expand(router, matched):
    """The pre-PR3 per-filter walk, reconstructed per message."""
    out = []
    for fids in matched:
        per_msg = []
        rules = []
        shared = []
        for fid in fids:
            if isinstance(fid, tuple):
                rules.append(fid[1])
                continue
            for clientid, opts in router.subscribers(fid):
                per_msg.append((clientid, id(opts)))
            for group in router.shared.groups_for(fid):
                shared.append((fid, group))
        out.append((sorted(per_msg), sorted(rules), sorted(shared)))
    return out


def _csr_expand(router, matched):
    """The batched expansion, regrouped to the legacy shape."""
    msg_idx, rows, opts_rows, rules, shared = router.expand_window(
        matched
    )
    n = len(matched)
    per_msg = [[] for _ in range(n)]
    for i, row, slot in zip(
        msg_idx.tolist(), rows.tolist(), opts_rows.tolist()
    ):
        per_msg[i].append(
            (router.client_of_row(row), id(router.opts_at(slot)))
        )
    rule_by = [[] for _ in range(n)]
    for i, rids in rules:
        rule_by[i].extend(rids)
    shared_by = [[] for _ in range(n)]
    for i, real, group in shared:
        shared_by[i].append((real, group))
    return [
        (sorted(per_msg[i]), sorted(rule_by[i]), sorted(shared_by[i]))
        for i in range(n)
    ]


def test_csr_expansion_equals_legacy_walk_under_churn():
    """Property test: random subscribe/unsubscribe churn (direct +
    shared + option refreshes + full client cleanup) interleaved with
    window expansions — the CSR path and the legacy per-filter walk
    must agree on every (client, opts-identity) delivery, every rule
    hit, and every shared-group hit."""
    rng = random.Random(7)
    r = Router()
    clients = [f"c{i}" for i in range(24)]
    filters = [f"t/{i}" for i in range(12)] + ["t/+", "a/#", "$sys/x"]
    share_filters = [f"$share/g{i}/t/{i % 4}" for i in range(6)]
    live = set()
    for step in range(600):
        op = rng.random()
        cid = rng.choice(clients)
        if op < 0.45:
            flt = rng.choice(filters + share_filters)
            r.subscribe(cid, flt, SubOpts(qos=rng.randint(0, 2)))
            live.add((cid, flt))
        elif op < 0.70 and live:
            cid2, flt = rng.choice(sorted(live))
            r.unsubscribe(cid2, flt)
            live.discard((cid2, flt))
        elif op < 0.78:
            r.cleanup_client(cid)
            live = {(c, f) for (c, f) in live if c != cid}
        if step % 20 == 0:
            # a window of matched fid sets: real filters, absent
            # filters, raw int fids (bench-style), and rule tuples
            matched = []
            for _ in range(rng.randint(1, 6)):
                fids = set(rng.sample(filters, rng.randint(0, 4)))
                if rng.random() < 0.4:
                    fids.add(("rule", f"r{rng.randint(0, 3)}", 0))
                if rng.random() < 0.3:
                    fids.add(1_000_000_000 + rng.randint(0, 5))
                if rng.random() < 0.4:
                    sf = rng.choice(share_filters)
                    fids.add(sf.split("/", 2)[2])
                matched.append(fids)
            assert _csr_expand(r, matched) == _legacy_expand(r, matched)


def test_pure_rule_window_short_circuits_subscriber_expansion():
    """A window whose only hits are rule fids must reach the rule sink
    without touching the CSR (empty expansion arrays) and account each
    message as a no-subscriber drop — the PR3 satellite fix."""
    b = Broker()
    matched = [
        {("rule", "r1", 0)},
        {("rule", "r1", 1), ("rule", "r2", 1)},
    ]
    msg_idx, rows, opts_rows, rules, shared = b.router.expand_window(
        matched
    )
    assert len(rows) == 0 and len(msg_idx) == 0 and not shared
    assert [
        (i, sorted(ids)) for i, ids in sorted(rules)
    ] == [(0, ["r1"]), (1, ["r1", "r2"])]
    sink = []
    msgs = [Message(topic="x"), Message(topic="y")]
    counts = b._dispatch_window(msgs, matched, rule_sink=sink)
    assert counts == [0, 0]
    assert [sorted(ids) for _m, ids in sink] == [
        ["r1"], ["r1", "r2"]
    ]
    assert b.metrics.val("messages.dropped.no_subscribers") == 2


# -------------------------------------------- batched-path semantics


def test_delivery_guards_survive_batched_path():
    b = Broker()
    for cid in ("allowed", "denied"):
        ch, s = _connect(b, cid)
        s.subscribe("$link/+", SubOpts(qos=0))
        b.subscribe(cid, "$link/+", SubOpts(qos=0))
        s.subscribe("plain", SubOpts(qos=0))
        b.subscribe(cid, "plain", SubOpts(qos=0))
    chans = {cid: b.cm.channel(cid) for cid in ("allowed", "denied")}
    b.delivery_guards.append(
        lambda cid, msg: cid == "allowed"
    )
    counts = b.publish_many([
        Message(topic="$link/a"),
        Message(topic="plain"),
        Message(topic="$link/b"),
    ])
    # guards apply to $-topics only; 'plain' reaches both clients
    assert counts == [1, 2, 1]
    assert [p.topic for p in chans["allowed"].sent] == [
        "$link/a", "plain", "$link/b"
    ]
    assert [p.topic for p in chans["denied"].sent] == ["plain"]


def test_guard_denying_everyone_counts_no_subscribers():
    b = Broker()
    ch, s = _connect(b, "c1")
    s.subscribe("$link/x", SubOpts(qos=0))
    b.subscribe("c1", "$link/x", SubOpts(qos=0))
    b.delivery_guards.append(lambda cid, msg: False)
    assert b.publish(Message(topic="$link/x")) == 0
    assert b.metrics.val("messages.dropped.no_subscribers") == 1


def test_shared_pick_skips_dead_in_batched_window():
    """_shared_pick redispatch (skip-dead) semantics through the
    multi-message window path."""
    b = Broker(shared_strategy="round_robin")
    for cid in ("c1", "c2"):
        ch, s = _connect(b, cid)
        s.subscribe("$share/g/t", SubOpts(qos=0))
        b.subscribe(cid, "$share/g/t", SubOpts(qos=0))
    chans = {cid: b.cm.channel(cid) for cid in ("c1", "c2")}
    counts = b.publish_many([Message(topic="t") for _ in range(4)])
    assert counts == [1, 1, 1, 1]
    assert len(chans["c1"].sent) == 2 and len(chans["c2"].sent) == 2
    b.cm.kick("c1")
    counts = b.publish_many([Message(topic="t") for _ in range(3)])
    assert counts == [1, 1, 1]
    assert len(chans["c2"].sent) == 5


def test_no_local_and_rap_survive_batched_path():
    b = Broker()
    ch_nl, s_nl = _connect(b, "selfpub")
    s_nl.subscribe("t", SubOpts(qos=0, no_local=True))
    b.subscribe("selfpub", "t", SubOpts(qos=0, no_local=True))
    ch_rap, s_rap = _connect(b, "rap")
    s_rap.subscribe("t", SubOpts(qos=0, retain_as_published=True))
    b.subscribe("rap", "t", SubOpts(qos=0, retain_as_published=True))
    ch_plain, s_plain = _connect(b, "plain")
    s_plain.subscribe("t", SubOpts(qos=0))
    b.subscribe("plain", "t", SubOpts(qos=0))

    b.publish_many([
        Message(topic="t", payload=b"r", retain=True,
                from_client="selfpub"),
    ])
    # no_local: the publisher's own subscription is skipped
    # ([MQTT-3.8.3-3]) but still counts as a delivery target
    assert ch_nl.sent == []
    # retain-as-published: the RAP subscriber sees retain=1, the
    # plain subscriber retain=0 [MQTT-3.3.1-9]
    assert ch_rap.sent[0].retain is True
    assert ch_plain.sent[0].retain is False


def test_subscription_option_refresh_updates_csr():
    """A re-subscribe with new options must change what the CSR path
    delivers (the opts-table slot is replaced in place)."""
    b = Broker()
    ch, s = _connect(b, "c1")
    s.subscribe("t", SubOpts(qos=0))
    b.subscribe("c1", "t", SubOpts(qos=0))
    b.publish(Message(topic="t", qos=1))
    assert ch.sent[-1].qos == 0
    s.subscribe("t", SubOpts(qos=1))
    b.subscribe("c1", "t", SubOpts(qos=1), is_new_sub=False)
    b.publish(Message(topic="t", qos=1))
    assert ch.sent[-1].qos == 1


# ------------------------------------------------- single-encode wire


def _stripped(pkt):
    """Re-build the packet without its pre-rendered wire."""
    return C.Publish(
        topic=pkt.topic, payload=pkt.payload, qos=pkt.qos,
        retain=pkt.retain, dup=pkt.dup, packet_id=pkt.packet_id,
        properties=dict(pkt.properties),
    )


@pytest.mark.parametrize("version", [C.MQTT_V4, C.MQTT_V5])
def test_single_encode_is_bit_identical(version):
    """The DispatchEncoder's pre-rendered frames must equal a from-
    scratch serialize of the same packet — for QoS 0/1/2, RAP, large
    payloads (multi-byte varint), and v5 properties."""
    enc = C.DispatchEncoder()
    cases = [
        Message(topic="a/b", payload=b"x"),
        Message(topic="a/b", payload=b"y" * 500, retain=True),
        Message(topic="t/long/topic", payload=b"z" * 3,
                properties={"user_property": [("k", "v")]}
                if version == C.MQTT_V5 else {}),
    ]
    for msg in cases:
        for qos in (0, 1, 2):
            for rap in (False, True):
                opts = SubOpts(qos=qos, retain_as_published=rap)
                if qos == 0:
                    pkt = enc.publish_qos0(msg, opts, version)
                else:
                    pkt = enc.publish(msg, opts, qos, 0x1234, version)
                ver, wire = pkt._wire
                assert ver == version
                assert wire == C.serialize(_stripped(pkt), version)
                # and serialize() itself returns the cached frame for
                # the matching version, re-encodes for any other
                assert C.serialize(pkt, version) == wire
                other = C.MQTT_V4 if version == C.MQTT_V5 else C.MQTT_V5
                assert C.serialize(pkt, other) == C.serialize(
                    _stripped(pkt), other
                )


def test_session_deliver_uses_encoder_and_matches_legacy_wire():
    """A session delivering through the window encoder must put the
    same bytes on the wire as the legacy per-packet path, and QoS 0
    fan-out must share ONE packet object across subscribers."""
    msg = Message(topic="t", payload=b"hello")
    opts = SubOpts(qos=0)
    enc = C.DispatchEncoder()
    s1 = Session("a")
    s2 = Session("b")
    p1 = s1.deliver([(msg, opts)], encoder=enc, version=C.MQTT_V5)[0]
    p2 = s2.deliver([(msg, opts)], encoder=enc, version=C.MQTT_V5)[0]
    assert p1 is p2  # one shared frame for the whole fan-out
    legacy = Session("c").deliver([(msg, opts)])[0]
    assert C.serialize(p1, C.MQTT_V5) == C.serialize(legacy, C.MQTT_V5)
    # QoS>0: per-subscriber packet ids patched into the shared buffer
    mq = Message(topic="t", payload=b"hi", qos=1)
    q1 = Session("d").deliver(
        [(mq, SubOpts(qos=1))], encoder=enc, version=C.MQTT_V5
    )[0]
    lq = Session("e").deliver([(mq, SubOpts(qos=1))])[0]
    assert q1.packet_id == lq.packet_id == 1
    assert C.serialize(q1, C.MQTT_V5) == C.serialize(lq, C.MQTT_V5)


def test_subid_falls_back_to_per_packet_encode():
    """A subscription identifier is per-subscriber state: the encoder
    must NOT be used (no _wire) and the property must survive."""
    msg = Message(topic="t", payload=b"p")
    enc = C.DispatchEncoder()
    pkt = Session("a").deliver(
        [(msg, SubOpts(qos=0, subid=42))],
        encoder=enc, version=C.MQTT_V5,
    )[0]
    assert getattr(pkt, "_wire", None) is None
    assert pkt.properties["subscription_identifier"] == [42]


def test_end_to_end_wire_bytes_with_real_channel():
    """Full broker window through a real Channel: the captured wire
    must decode back to the published messages (v5 AND v3.1.1)."""
    b = Broker()
    ch5 = WireChannel(b, version=C.MQTT_V5)
    _connect(b, "v5", channel=ch5)
    ch4 = WireChannel(b, version=C.MQTT_V4)
    _connect(b, "v4", channel=ch4)
    for cid in ("v5", "v4"):
        sess = b.cm.lookup(cid)
        sess.subscribe("w/#", SubOpts(qos=0))
        b.subscribe(cid, "w/#", SubOpts(qos=0))
    msgs = [Message(topic=f"w/{i}", payload=bytes([i]) * i)
            for i in range(5)]
    counts = b.publish_many(msgs)
    assert counts == [2] * 5
    for ch, ver in ((ch5, C.MQTT_V5), (ch4, C.MQTT_V4)):
        # ONE corked write for the whole window per connection
        assert len(ch.writes) == 1
        parser = C.StreamParser(version=ver)
        decoded = list(parser.feed(ch.writes[0]))
        assert [p.topic for p in decoded] == [m.topic for m in msgs]
        assert [p.payload for p in decoded] == [m.payload for m in msgs]


# --------------------------------------------------- write coalescing


def test_channel_cork_buffers_and_flushes_once():
    b = Broker()
    ch = WireChannel(b)
    ch.cork()
    ch.send_packets([C.Publish(topic="a", payload=b"1")])
    ch.send_packets([C.Publish(topic="b", payload=b"2")])
    assert ch.writes == []  # buffered while corked
    ch.uncork()
    assert len(ch.writes) == 1
    assert [p.topic for p in ch.packets] == ["a", "b"]
    # nested cork scopes flush once, at the outermost uncork
    ch.cork()
    ch.cork()
    ch.send_packets([C.Publish(topic="c", payload=b"3")])
    ch.uncork()
    assert len(ch.writes) == 1
    ch.uncork()
    assert len(ch.writes) == 2


def test_cork_drops_buffer_on_shutdown():
    b = Broker()
    ch = WireChannel(b)
    ch.cork()
    ch.send_packets([C.Publish(topic="a", payload=b"1")])
    ch._shutdown("test")
    ch.uncork()
    assert ch.writes == []  # never flush past teardown


def test_window_coalesces_to_one_write_per_connection():
    b = Broker()
    ch = WireChannel(b)
    _connect(b, "sub", channel=ch)
    sess = b.cm.lookup("sub")
    sess.subscribe("t/#", SubOpts(qos=0))
    b.subscribe("sub", "t/#", SubOpts(qos=0))
    b.publish_many([Message(topic=f"t/{i}") for i in range(16)])
    assert len(ch.writes) == 1  # 16 deliveries, one transport write
    b.publish_many([Message(topic=f"t/{i}") for i in range(4)])
    assert len(ch.writes) == 2


# ------------------------------------------------ batched bookkeeping


def test_window_metrics_match_legacy_semantics():
    b = Broker()
    ch, s = _connect(b, "c1")
    s.subscribe("t", SubOpts(qos=0))
    b.subscribe("c1", "t", SubOpts(qos=0))
    counts = b.publish_many([
        Message(topic="t"),
        Message(topic="nobody"),
        Message(topic="t"),
    ])
    assert counts == [1, 0, 1]
    assert b.metrics.val("messages.delivered") == 2
    assert b.metrics.val("messages.dropped.no_subscribers") == 1
    assert b.metrics.val("messages.publish") == 3


def test_delivered_hook_fires_once_per_window_client():
    """Bookkeeping amortization: the message.delivered hook gets ONE
    call per (window, client) carrying every delivery, not one call
    per delivery."""
    b = Broker()
    ch, s = _connect(b, "c1")
    s.subscribe("t/#", SubOpts(qos=0))
    b.subscribe("c1", "t/#", SubOpts(qos=0))
    calls = []
    b.hooks.add(
        "message.delivered",
        lambda cid, deliveries: calls.append((cid, len(deliveries))),
    )
    b.publish_many([Message(topic=f"t/{i}") for i in range(5)])
    assert calls == [("c1", 5)]
