"""Failpoint framework (emqx_tpu/failpoints.py): registry semantics,
seeded determinism, hit windows, env/REST/ctl configuration surfaces,
the disabled-is-a-no-op guard the hot paths rely on, and the
BufferWorker retry/backoff + disconnect→replay satellite driven
through injection (no sleeps for correctness, deterministic seed)."""

import asyncio
import tempfile
import time

import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.resources import (
    CONNECTED, DISCONNECTED, BufferWorker, Resource,
)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


# ---------------------------------------------------------- registry

def test_actions_error_drop_duplicate_panic():
    fp.configure("t.err", "error")
    with pytest.raises(fp.FailpointError):
        fp.evaluate("t.err")
    # FailpointError IS a ConnectionError: seams recover through their
    # real transport-failure paths
    assert issubclass(fp.FailpointError, ConnectionError)
    assert fp.FailpointError("x").code() == "FAILPOINT"

    fp.configure("t.drop", "drop")
    assert fp.evaluate("t.drop") == "drop"
    fp.configure("t.dup", "duplicate")
    assert fp.evaluate("t.dup") == "duplicate"

    fp.configure("t.panic", "panic")
    with pytest.raises(fp.FailpointPanic):
        fp.evaluate("t.panic")
    # panic must NOT be absorbed by ordinary Exception recovery
    assert not issubclass(fp.FailpointPanic, Exception)

    with pytest.raises(ValueError):
        fp.configure("t.bad", "explode")


def test_delay_sync_and_async():
    fp.configure("t.delay", "delay", delay=0.05)
    t0 = time.monotonic()
    assert fp.evaluate("t.delay") is None
    assert time.monotonic() - t0 >= 0.045

    async def t():
        t0 = time.monotonic()
        assert await fp.evaluate_async("t.delay") is None
        assert time.monotonic() - t0 >= 0.045

    run(t())


def test_seeded_probability_is_reproducible():
    fp.configure("t.p", "drop", prob=0.4, seed=1234)
    a = [fp.evaluate("t.p") for _ in range(64)]
    fp.configure("t.p", "drop", prob=0.4, seed=1234)  # re-arm resets
    b = [fp.evaluate("t.p") for _ in range(64)]
    assert a == b
    fires = sum(1 for x in a if x == "drop")
    assert 0 < fires < 64  # actually probabilistic


def test_hit_count_windows_after_and_times():
    fp.configure("t.w", "drop", after=3, times=2)
    out = [fp.evaluate("t.w") for _ in range(8)]
    # first 3 hits skipped, then exactly 2 fires, then exhausted
    assert out == [None, None, None, "drop", "drop", None, None, None]
    info = fp.list_points()[0]
    assert info["hits"] == 8 and info["fires"] == 2


def test_match_substring_filter_on_key():
    fp.configure("t.m", "drop", match="n0")
    assert fp.evaluate("t.m", key="n0->n1") == "drop"
    assert fp.evaluate("t.m", key="n1->n0") == "drop"
    assert fp.evaluate("t.m", key="n1->n2") is None
    assert fp.evaluate("t.m") is None  # no key at the site


def test_env_spec_round_trip():
    n = fp.load_env(
        "engine.device_step=error;"
        "cluster.transport.send=drop,prob=0.25,seed=9,match=n2;"
        "cluster.raft.rpc=delay,delay=0.01,after=5,times=3"
    )
    assert n == 3 and fp.enabled
    by_name = {p["name"]: p for p in fp.list_points()}
    assert by_name["cluster.transport.send"]["prob"] == 0.25
    assert by_name["cluster.transport.send"]["match"] == "n2"
    assert by_name["cluster.raft.rpc"]["times"] == 3
    assert fp.load_env("") == 0  # unset env is a no-op
    with pytest.raises(ValueError):
        fp.parse_spec("name.only")
    with pytest.raises(ValueError):
        fp.parse_spec("a=error,bogus=1")
    fp.clear("engine.device_step")
    assert len(fp.list_points()) == 2
    fp.clear()
    assert fp.list_points() == [] and not fp.enabled


# ------------------------------------------------- disabled guard

def test_disabled_framework_is_a_noop_on_every_seam():
    """The guard the hot paths rely on: with nothing armed, every
    instrumented seam evaluates to None, counts nothing, and costs
    (far) less than a microsecond-scale budget per call — chaos hooks
    can never regress the disabled hot path."""
    assert fp.enabled is False
    for name in fp.SEAMS:
        assert fp.evaluate(name) is None
        assert run(fp.evaluate_async(name)) is None
    assert fp.list_points() == []  # nothing counted, nothing armed

    n = 200_000
    t0 = time.perf_counter()
    ev = fp.evaluate
    for _ in range(n):
        ev("engine.device_step")
    per_call = (time.perf_counter() - t0) / n
    # a disabled evaluate is one bool check; 5 µs/call is ~50x headroom
    # over any sane interpreter so this cannot flake, while still
    # catching an accidental lock/dict walk on the disabled path
    assert per_call < 5e-6, f"disabled failpoint costs {per_call:.2e}s"

    # armed-but-different-name is also a miss for every other seam
    fp.configure("only.this.one", "error")
    for name in fp.SEAMS:
        assert fp.evaluate(name) is None


def test_disabled_paths_behave_identically():
    """Instrumented code runs with the framework disabled exactly as
    if the seam were absent: a transport send and a replica store are
    bit-identical with and without a cleared registry."""
    from emqx_tpu.ds.replication import ReplicaStore

    store = ReplicaStore()
    store.store_checkpoint("c1", {"subs": {"a/b": {}}, "expiry": 60,
                                  "queued": []})
    store.append_messages("c1", [{"topic": "a/b", "mid": 1}])
    assert store.peek("c1")["queued"] == [{"topic": "a/b", "mid": 1}]

    # armed drop on the store seam: the same calls now lose the write
    fp.configure("ds.replication.store", "drop")
    store.store_checkpoint("c2", {"subs": {}, "expiry": 60})
    assert store.peek("c2") is None
    fp.clear()
    store.store_checkpoint("c2", {"subs": {}, "expiry": 60})
    assert store.peek("c2") is not None


# ------------------------------------------- resource buffer satellite

class CountingSink(Resource):
    """Sink that records delivered queries; failures come ONLY from
    the injected failpoint, so the retry path is deterministic."""

    def __init__(self):
        self.delivered = []

    async def on_query(self, query):
        self.delivered.append(query)

    async def health_check(self):
        return True


async def _drain(worker, sink, want, deadline=5.0):
    t0 = time.monotonic()
    while len(sink.delivered) < want:
        assert time.monotonic() - t0 < deadline, (
            f"delivered {len(sink.delivered)}/{want}"
        )
        await asyncio.sleep(0.005)


def test_buffer_worker_retry_backoff_through_failpoint():
    """First 3 drain attempts fail via injection: the worker retries
    with backoff, keeps the query at the buffer head, and delivers
    everything in order — no loss within buffer bounds."""

    async def t():
        sink = CountingSink()
        w = BufferWorker(sink, retry_base=0.005, retry_cap=0.02)
        w.name = "chaos-sink"
        fp.configure("resource.buffer.query", "error", times=3,
                     match="chaos-sink")
        await w.start()
        for i in range(5):
            w.enqueue(f"q{i}")
        await _drain(w, sink, 5)
        assert sink.delivered == [f"q{i}" for i in range(5)]
        assert w.stats["retried"] == 3
        assert w.stats["success"] == 5
        assert w.stats["dropped"] == 0 and w.stats["failed"] == 0
        assert w.status == CONNECTED
        await w.stop()

    run(t())


def test_buffer_worker_disconnect_then_replay():
    """A dead sink (every query errors) flips the worker to
    DISCONNECTED and buffers the backlog; clearing the injection
    replays the whole backlog in order and re-connects."""

    async def t():
        sink = CountingSink()
        w = BufferWorker(sink, retry_base=0.005, retry_cap=0.02)
        w.name = "outage-sink"
        fp.configure("resource.buffer.query", "error",
                     match="outage-sink")
        await w.start()
        for i in range(20):
            w.enqueue(i)
        t0 = time.monotonic()
        while not (w.status == DISCONNECTED and w.stats["retried"] >= 2):
            assert time.monotonic() - t0 < 5.0
            await asyncio.sleep(0.005)
        assert sink.delivered == [] and len(w) == 20
        fp.clear("resource.buffer.query")  # sink "comes back"
        await _drain(w, sink, 20)
        assert sink.delivered == list(range(20))
        assert w.status == CONNECTED and len(w) == 0
        await w.stop()

    run(t())


def test_buffer_worker_panic_is_not_absorbed():
    """An injected panic (BaseException) escapes the worker's
    except-Exception retry clause — the drain task dies the way a
    process would, instead of being silently retried."""

    async def t():
        sink = CountingSink()
        w = BufferWorker(sink, retry_base=0.005)
        w.name = "panic-sink"
        fp.configure("resource.buffer.query", "panic", times=1,
                     match="panic-sink")
        await w.start()
        w.enqueue("boom")
        for _ in range(100):
            if w._task.done():
                break
            await asyncio.sleep(0.005)
        assert w._task.done()
        with pytest.raises(fp.FailpointPanic):
            w._task.result()

    run(t())


# -------------------------------------------------- REST + ctl surface

def test_failpoints_rest_and_ctl(tmp_path):
    from api_helper import auth_session
    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.config import BrokerConfig, ListenerConfig

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.api.enable = True
        cfg.api.data_dir = tempfile.mkdtemp(dir=str(tmp_path))
        cfg.api.port = 0
        srv = BrokerServer(cfg)
        await srv.start()
        http, api = await auth_session(srv)
        try:
            async with http.get(api + "/api/v5/failpoints") as r:
                body = await r.json()
                assert r.status == 200
                assert body["enabled"] is False and body["data"] == []
                assert "engine.device_step" in body["seams"]
                assert body["engine_breaker"]["open"] is False

            async with http.put(
                api + "/api/v5/failpoints/cluster.transport.send",
                json={"action": "drop", "prob": 0.5, "seed": 7,
                      "match": "n0", "times": 10},
            ) as r:
                assert r.status == 200
                info = await r.json()
                assert info["action"] == "drop" and info["seed"] == 7
            assert fp.enabled

            async with http.put(
                api + "/api/v5/failpoints/x", json={"action": "nope"}
            ) as r:
                assert r.status == 400
            async with http.put(
                api + "/api/v5/failpoints/x",
                json={"action": "delay", "delay": "fast"},
            ) as r:
                assert r.status == 400  # bad numeric -> clean 400

            async with http.get(api + "/api/v5/failpoints") as r:
                body = await r.json()
                assert [p["name"] for p in body["data"]] == [
                    "cluster.transport.send"
                ]

            # the ctl CLI drives the same endpoints end to end
            from emqx_tpu.ctl import Ctl

            def drive_ctl():
                ctl = Ctl(api, user="admin:public")
                ctl.failpoints("set", "engine.device_step", "error",
                               "times=5")
                ctl.failpoints("list")
                ctl.failpoints("clear", "engine.device_step")

            await asyncio.get_running_loop().run_in_executor(
                None, drive_ctl
            )
            assert [p["name"] for p in fp.list_points()] == [
                "cluster.transport.send"
            ]

            async with http.delete(api + "/api/v5/failpoints/nope") as r:
                assert r.status == 404
            async with http.delete(api + "/api/v5/failpoints") as r:
                assert r.status == 204
            assert not fp.enabled
        finally:
            await http.close()
            await srv.stop()

    run(t())


# ------------------------------------- new seams (brokerlint FP301)
# ds.beamformer.poll / cluster.link.forward / s3.request — each seam
# is declared in tools/brokerlint/failpointrules.py:SEAM_FUNCS, so
# removing the evaluate call from the production function fails the
# tier-1 lint gate, and each gets one chaos test here.


def test_beamformer_poll_failpoint_drop_error_delay():
    """`drop` answers a poll empty immediately (the timeout shape,
    even though data IS available), `error` raises to the poller,
    `delay` injects long-poll latency — all keyed by shard."""
    from emqx_tpu.ds.api import IterRef, StreamRef
    from emqx_tpu.ds.beamformer import Beamformer

    class OneShotStorage:
        def next(self, it, n):
            return it, ["msg"]  # data is always there

    bf = Beamformer(OneShotStorage())
    it = IterRef(StreamRef(shard=3), "t/#")

    async def t():
        # baseline: data comes straight back
        _it2, msgs = await bf.poll(it, timeout=0.5)
        assert msgs == ["msg"]

        fp.configure("ds.beamformer.poll", "drop")
        _it2, msgs = await bf.poll(it, timeout=5.0)
        assert msgs == []  # dropped despite available data, no park

        # match filter partitions one shard: shard 3 matches, fires
        fp.configure("ds.beamformer.poll", "error", match="3")
        with pytest.raises(fp.FailpointError):
            await bf.poll(it, timeout=0.5)
        # a different shard's poll sails through
        other = IterRef(StreamRef(shard=7), "t/#")
        _it2, msgs = await bf.poll(other, timeout=0.5)
        assert msgs == ["msg"]

        fp.configure("ds.beamformer.poll", "delay", delay=0.05)
        t0 = time.monotonic()
        _it2, msgs = await bf.poll(it, timeout=5.0)
        assert msgs == ["msg"]
        assert time.monotonic() - t0 >= 0.045

    run(t())


def test_cluster_link_forward_failpoint_partitions_one_peer():
    """`drop` on cluster.link.forward loses the egress copy for the
    MATCHED peer cluster only — the other linked cluster still gets
    its wrapped message (a one-link partition)."""
    from emqx_tpu.cluster_link import MSG_PREFIX, LinkServer
    from emqx_tpu.message import Message

    class FakeMetrics:
        def __init__(self):
            self.counts = {}

        def inc(self, k, n=1):
            self.counts[k] = self.counts.get(k, 0) + n

    class FakeBroker:
        def __init__(self):
            self.metrics = FakeMetrics()
            self.published = []

        def publish(self, msg):
            self.published.append(msg)
            return 1

    broker = FakeBroker()
    srv = LinkServer(broker, "local", allowed={"east", "west"})
    srv.extern_routes = {"east": {"t/#"}, "west": {"t/#"}}

    msg = Message(topic="t/x", payload=b"hi")
    srv._on_publish(msg)
    assert sorted(m.topic for m in broker.published) == [
        MSG_PREFIX + "east", MSG_PREFIX + "west",
    ]

    broker.published.clear()
    fp.configure("cluster.link.forward", "drop", match="east")
    srv._on_publish(msg)
    assert [m.topic for m in broker.published] == [MSG_PREFIX + "west"]
    assert broker.metrics.counts.get("cluster_link.egress") == 3  # 2+1

    # unarmed again: both flow (the seam is behavior-free when clear)
    fp.clear()
    broker.published.clear()
    srv._on_publish(msg)
    assert len(broker.published) == 2


def test_s3_request_failpoint_rides_sink_health_path():
    """An injected s3.request fault is a ConnectionError: S3Sink's
    health probe reports down, and the resource layer's retry path
    sees the same exception shape a real S3 outage produces — without
    aiohttp ever being touched."""
    from emqx_tpu.s3 import S3Client, S3Sink

    client = S3Client("http://s3.test", "bkt", "ak", "sk")
    sink = S3Sink(client)

    async def t():
        fp.configure("s3.request", "error")
        with pytest.raises(fp.FailpointError):
            await client.put_object("k", b"v")
        assert await sink.health_check() is False

        # drop: the response never arrives — surfaced as the same
        # ConnectionError family the client timeout would raise
        fp.configure("s3.request", "drop")
        with pytest.raises(ConnectionError):
            await client.get_object("k")

        # match keys on "METHOD key": partition deletes only
        fp.configure("s3.request", "error", match="DELETE ")
        with pytest.raises(fp.FailpointError):
            await client.delete_object("k")

    run(t())
