"""Authenticated management-API session for tests: logs in with the
bootstrapped default admin and returns an aiohttp session that sends
the Bearer token on every request (the mgmt plane answers 401 without
it — emqx_mgmt_auth parity)."""

import aiohttp


async def auth_session(srv, username="admin", password="public"):
    """Returns (ClientSession with auth header, api base url)."""
    api = f"http://127.0.0.1:{srv.api.port}"
    async with aiohttp.ClientSession() as http:
        async with http.post(
            api + "/api/v5/login",
            json={"username": username, "password": password},
        ) as r:
            assert r.status == 200, await r.text()
            token = (await r.json())["token"]
    return (
        aiohttp.ClientSession(
            headers={"Authorization": f"Bearer {token}"}
        ),
        api,
    )
