"""Raft consensus (the `ra`/emqx_cluster_rpc quorum upgrade over
round-3's LWW): elections, quorum commit, the VERDICT's two
done-criteria — kill the leader mid-stream with ZERO acked-entry
loss, and concurrent conf updates resolving to one deterministic
winner on every node — plus log recovery from disk."""

import asyncio

import pytest

from emqx_tpu.cluster.raft import LEADER, NotLeader, RaftNode
from emqx_tpu.cluster.transport import NodeTransport


def run(coro):
    return asyncio.run(coro)


class Cluster:
    """N transports + raft nodes on loopback (the emqx_cth_cluster
    peer-nodes-in-one-host pattern)."""

    def __init__(self, n, data_dirs=None):
        self.names = [f"n{i}" for i in range(n)]
        self.data_dirs = data_dirs or [None] * n
        self.transports = {}
        self.rafts = {}
        self.applied = {name: [] for name in self.names}

    async def start(self, fast=True):
        for name in self.names:
            self.transports[name] = NodeTransport(name)
            await self.transports[name].start()
        for name in self.names:
            for other in self.names:
                if other != name:
                    self.transports[name].add_peer(
                        other, "127.0.0.1", self.transports[other].port
                    )
        for i, name in enumerate(self.names):
            peers = [p for p in self.names if p != name]
            r = RaftNode(
                name, peers, self.transports[name],
                apply_cb=(lambda nm: lambda idx, p:
                          self.applied[nm].append((idx, p)))(name),
                data_dir=self.data_dirs[i],
                election_timeout=(0.05, 0.12) if fast else (0.15, 0.3),
                heartbeat=0.02 if fast else 0.05,
                fsync=False,
            )
            self.rafts[name] = r
            r.start()

    async def stop(self):
        for r in self.rafts.values():
            await r.stop()
        for t in self.transports.values():
            await t.stop()

    async def leader(self, timeout=5.0):
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            leaders = [
                r for r in self.rafts.values()
                if r.role == LEADER and not r._stopped
            ]
            if len(leaders) == 1:
                return leaders[0]
            await asyncio.sleep(0.02)
        raise AssertionError("no (single) leader elected")

    async def kill(self, name):
        """Hard-stop a node: raft halted AND transport torn down (no
        goodbyes — the crash shape)."""
        await self.rafts[name].stop()
        await self.transports[name].stop()


def test_election_and_replication():
    async def t():
        c = Cluster(3)
        await c.start()
        leader = await c.leader()
        for i in range(20):
            await leader.propose({"op": i})
        await asyncio.sleep(0.2)  # followers learn commit via heartbeat
        for name in c.names:
            assert [p["op"] for _, p in c.applied[name]] == list(range(20))
        # every node applied in identical order with identical indexes
        assert len({tuple(map(str, c.applied[n])) for n in c.names}) == 1
        await c.stop()

    run(t())


def test_follower_submit_forwards_to_leader():
    async def t():
        c = Cluster(3)
        await c.start()
        leader = await c.leader()
        follower = next(
            r for r in c.rafts.values() if r.node != leader.node
        )
        idx = await follower.submit({"via": "follower"})
        assert idx >= 1
        await asyncio.sleep(0.2)
        assert any(
            p.get("via") == "follower" for _, p in c.applied[leader.node]
        )
        with pytest.raises(NotLeader):
            await follower.propose({"x": 1})
        await c.stop()

    run(t())


def test_leader_kill_mid_stream_zero_acked_loss():
    """The VERDICT's criterion: stream entries, kill the leader at a
    random point, verify EVERY acked entry survives on the remaining
    quorum (and the cluster keeps accepting writes)."""

    async def t():
        c = Cluster(3)
        await c.start()
        leader = await c.leader()
        acked = []
        for i in range(30):
            idx = await leader.submit({"seq": i})
            acked.append((idx, i))
            if i == 17:
                victim = leader.node
                await c.kill(victim)
                # the survivors elect a new leader; keep streaming
                leader = await c.leader()
        await asyncio.sleep(0.3)
        survivors = [n for n in c.names if n != victim]
        for name in survivors:
            seqs = [p["seq"] for _, p in c.applied[name]]
            # every ACKED seq is present, in ack order
            acked_seqs = [s for _, s in acked]
            assert [s for s in seqs if s in set(acked_seqs)] == acked_seqs, (
                name, seqs, acked_seqs
            )
        await c.stop()

    run(t())


def test_conf_conflict_deterministic_winner():
    """Two nodes race conflicting updates to ONE config path: all
    nodes apply both in the SAME committed order, so the final value
    is identical everywhere (emqx_cluster_rpc's logged-multicall
    semantics; round-3's per-path LWW could disagree)."""

    async def t():
        c = Cluster(3)
        await c.start()
        await c.leader()
        a, b = c.rafts["n0"], c.rafts["n1"]
        await asyncio.gather(
            a.submit({"path": "mqtt.max_qos", "value": 1}),
            b.submit({"path": "mqtt.max_qos", "value": 2}),
        )
        await asyncio.sleep(0.3)
        finals = set()
        for name in c.names:
            state = {}
            for _, p in c.applied[name]:
                state[p["path"]] = p["value"]
            finals.add(state["mqtt.max_qos"])
        assert len(finals) == 1, finals  # one deterministic winner
        # and the full logs are identical
        assert len({tuple(map(str, c.applied[n])) for n in c.names}) == 1
        await c.stop()

    run(t())


def test_lagging_node_catches_up():
    async def t():
        c = Cluster(3)
        await c.start()
        leader = await c.leader()
        lag = next(n for n in c.names if n != leader.node)
        # partition the laggard by tearing down its transport links
        for other in c.names:
            if other != lag:
                c.transports[other].drop_peer(lag)
                c.transports[lag].drop_peer(other)
                c.transports[other]._peer_addrs.pop(lag, None)
        addrs = {
            n: ("127.0.0.1", c.transports[n].port) for n in c.names
        }
        for i in range(10):
            await leader.submit({"seq": i})
        assert len(c.applied[lag]) == 0
        # heal the partition
        for other in c.names:
            if other != lag:
                c.transports[other].add_peer(lag, *addrs[lag])
        deadline = asyncio.get_event_loop().time() + 5
        while asyncio.get_event_loop().time() < deadline:
            if len(c.applied[lag]) == 10:
                break
            await asyncio.sleep(0.05)
        assert [p["seq"] for _, p in c.applied[lag]] == list(range(10))
        await c.stop()

    run(t())


def test_log_recovery_from_disk(tmp_path):
    """A restarted node recovers term/log from disk and rejoins with
    its entries intact (the reference's ra WAL role)."""

    async def t():
        dirs = [str(tmp_path / f"n{i}") for i in range(3)]
        c = Cluster(3, data_dirs=dirs)
        await c.start()
        leader = await c.leader()
        for i in range(7):
            await leader.submit({"seq": i})
        await asyncio.sleep(0.2)
        await c.stop()

        # full restart from the same dirs
        c2 = Cluster(3, data_dirs=dirs)
        await c2.start()
        leader2 = await c2.leader()
        # logs recovered: committed entries re-apply after new commits
        idx = await leader2.submit({"seq": 99})
        assert idx >= 8  # appended after the recovered entries
        await asyncio.sleep(0.3)
        for name in c2.names:
            seqs = [p["seq"] for _, p in c2.applied[name]]
            assert seqs[:7] == list(range(7)) and 99 in seqs, (name, seqs)
        await c2.stop()

    run(t())
