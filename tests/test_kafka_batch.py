"""Kafka magic-2 RecordBatch encoding (PR 20 satellite): crc32c
known-answer vectors, signed-varint/zigzag edges, multi-record
encode->decode round-trips, and corruption rejection.

`encode_record_batch` is what every windowed flush of the Kafka sink
puts on the wire; `decode_record_batch` is its crc-verified inverse,
so agreement here is agreement about the bytes a real broker sees."""

import struct

import pytest

from emqx_tpu.kafka import (
    _read_varint, _varint, _zigzag, crc32c,
    decode_batch_record_count, decode_record_batch,
    encode_record_batch, murmur2,
)


# ------------------------------------------------- crc32c vectors

# published CRC-32C (Castagnoli) check values: RFC 3720 appendix
# B.4 test patterns + the classic "123456789" check word
_CRC_VECTORS = [
    (b"", 0x00000000),
    (b"123456789", 0xE3069283),
    (b"\x00" * 32, 0x8A9136AA),
    (b"\xff" * 32, 0x62A8AB43),
    (bytes(range(32)), 0x46DD794E),
    (bytes(range(31, -1, -1)), 0x113FDB5C),
]


@pytest.mark.parametrize("data,expect", _CRC_VECTORS)
def test_crc32c_known_answers(data, expect):
    assert crc32c(data) == expect


def test_murmur2_known_partitioner_hashes():
    # signed 32-bit values from Apache Kafka's UtilsTest.testMurmur2,
    # masked to the unsigned form this implementation returns
    vectors = [
        (b"21", -973932308),
        (b"foobar", -790332482),
        (b"a-little-bit-long-string", -985981536),
        (b"a-little-bit-longer-string", -1486304829),
        (b"lkjh234lh9fiuh90y23oiuhsafujhadof229phr9h19h89h8",
         -58897971),
        (bytes([ord("a"), ord("b"), ord("c")]), 479470107),
    ]
    for data, signed in vectors:
        assert murmur2(data) == signed & 0xFFFFFFFF, data


# --------------------------------------------- varint/zigzag edges

_VARINT_EDGES = [
    0, -1, 1, -2, 2, 63, 64, -64, -65, 127, 128, -128,
    300, -300, 2**31 - 1, -(2**31), 2**62, -(2**62),
    2**63 - 1, -(2**63),
]


def test_zigzag_maps_sign_to_lsb():
    assert _zigzag(0) == 0
    assert _zigzag(-1) == 1
    assert _zigzag(1) == 2
    assert _zigzag(-2) == 3
    assert _zigzag(2**63 - 1) == 2**64 - 2
    assert _zigzag(-(2**63)) == 2**64 - 1


@pytest.mark.parametrize("n", _VARINT_EDGES)
def test_varint_round_trip(n):
    buf = _varint(n)
    got, pos = _read_varint(buf, 0)
    assert got == n
    assert pos == len(buf)


def test_varint_wire_bytes():
    # single byte up to zigzag 127; continuation bit beyond
    assert _varint(0) == b"\x00"
    assert _varint(-1) == b"\x01"
    assert _varint(63) == b"\x7e"
    assert _varint(64) == b"\x80\x01"  # first 2-byte value
    assert len(_varint(2**63 - 1)) == 10


def test_read_varint_sequence():
    buf = _varint(5) + _varint(-7) + _varint(1000)
    a, p = _read_varint(buf, 0)
    b, p = _read_varint(buf, p)
    c, p = _read_varint(buf, p)
    assert (a, b, c) == (5, -7, 1000)
    assert p == len(buf)


# ------------------------------------------------- batch round-trip

_RECORD_SETS = [
    [(None, b"solo")],
    [(b"k", b"v")],
    [(b"", b"")],  # empty (not None) key and empty value
    [(None, b"a"), (b"k1", b"bb"), (b"", b"ccc"), (None, b"")],
    [(b"key-%d" % i, b"x" * i) for i in range(17)],
    [(None, bytes(range(256)))],  # binary-safe values
]


@pytest.mark.parametrize("records", _RECORD_SETS)
def test_encode_decode_round_trip(records):
    batch = encode_record_batch(records, timestamp_ms=1_700_000_000_000)
    assert decode_record_batch(batch) == records
    assert decode_batch_record_count(batch) == len(records)


def test_batch_framing_fields():
    batch = encode_record_batch(
        [(b"k", b"v"), (None, b"w")], timestamp_ms=12345
    )
    # baseOffset, then batchLength covering the rest exactly
    assert struct.unpack_from(">q", batch, 0)[0] == 0
    (blen,) = struct.unpack_from(">i", batch, 8)
    assert blen == len(batch) - 12
    assert batch[16:17] == b"\x02"  # magic
    # crc covers attributes..records and verifies
    (crc,) = struct.unpack_from(">I", batch, 17)
    assert crc == crc32c(batch[21:])
    # firstTimestamp == maxTimestamp == the supplied stamp
    assert struct.unpack_from(">q", batch, 21 + 2 + 4)[0] == 12345


def test_decode_rejects_bad_magic():
    batch = bytearray(
        encode_record_batch([(b"k", b"v")], timestamp_ms=1)
    )
    batch[16] = 0x01
    with pytest.raises(ValueError, match="magic"):
        decode_record_batch(bytes(batch))


def test_decode_rejects_corrupt_payload():
    batch = bytearray(
        encode_record_batch([(b"key", b"value")], timestamp_ms=1)
    )
    batch[-3] ^= 0xFF  # flip a bit inside a record value
    with pytest.raises(ValueError, match="crc mismatch"):
        decode_record_batch(bytes(batch))


def test_decode_rejects_corrupt_crc_field():
    batch = bytearray(
        encode_record_batch([(b"key", b"value")], timestamp_ms=1)
    )
    batch[17] ^= 0xFF  # corrupt the stored crc itself
    with pytest.raises(ValueError, match="crc mismatch"):
        decode_record_batch(bytes(batch))


def test_count_agrees_for_large_batches():
    records = [(None, b"payload-%d" % i) for i in range(333)]
    batch = encode_record_batch(records, timestamp_ms=7)
    assert decode_batch_record_count(batch) == 333
    decoded = decode_record_batch(batch)
    assert len(decoded) == 333
    assert decoded[0] == (None, b"payload-0")
    assert decoded[-1] == (None, b"payload-332")
