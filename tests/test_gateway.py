"""STOMP gateway: a raw STOMP 1.2 client session against the broker
core, interoperating with MQTT clients (emqx_gateway + stomp parity)."""

import asyncio

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.gateway.stomp import StompCodec, StompFrame
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


class StompTestClient:
    def __init__(self, port: int):
        self.port = port
        self.codec = StompCodec()
        self.state = b""
        self.frames: asyncio.Queue = asyncio.Queue()

    async def connect(self, login=None, passcode=None):
        self.reader, self.writer = await asyncio.open_connection(
            "127.0.0.1", self.port
        )
        self._pump = asyncio.get_running_loop().create_task(self._read())
        headers = {"accept-version": "1.2", "host": "emqx"}
        if login:
            headers["login"] = login
        if passcode:
            headers["passcode"] = passcode
        await self.send(StompFrame("CONNECT", headers))
        frame = await self.expect("CONNECTED", "ERROR")
        return frame

    async def _read(self):
        try:
            while True:
                data = await self.reader.read(65536)
                if not data:
                    break
                frames, self.state = self.codec.parse(self.state, data)
                for f in frames:
                    await self.frames.put(f)
        except (ConnectionError, asyncio.CancelledError):
            pass

    async def send(self, frame: StompFrame):
        self.writer.write(self.codec.serialize(frame))
        await self.writer.drain()

    async def expect(self, *commands, timeout=3.0) -> StompFrame:
        frame = await asyncio.wait_for(self.frames.get(), timeout)
        assert frame.command in commands, (frame.command, frame.headers)
        return frame

    async def close(self):
        self._pump.cancel()
        self.writer.close()


async def make_server(**cfg_kw):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.gateways = [{"type": "stomp", "bind": "127.0.0.1", "port": 0}]
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    srv = BrokerServer(cfg)
    await srv.start()
    return srv


def test_stomp_send_subscribe_roundtrip():
    async def t():
        srv = await make_server()
        sport = srv.broker.gateways.get("stomp").port
        mport = srv.listeners[0].port

        s1 = StompTestClient(sport)
        ack = await s1.connect(login="alice")
        assert ack.command == "CONNECTED"
        assert ack.headers["version"] == "1.2"

        # STOMP subscribes with an MQTT wildcard destination
        await s1.send(
            StompFrame(
                "SUBSCRIBE",
                {"id": "0", "destination": "stocks/+", "receipt": "r1"},
            )
        )
        await s1.expect("RECEIPT")

        # MQTT publisher -> STOMP subscriber
        m = TestClient(mport, "mq")
        await m.connect()
        await m.publish("stocks/appl", b"190.5", qos=1)
        msg = await s1.expect("MESSAGE")
        assert msg.headers["destination"] == "stocks/appl"
        assert msg.headers["subscription"] == "0"
        assert msg.body == b"190.5"

        # STOMP SEND -> MQTT subscriber
        await m.subscribe("orders/#", qos=1)
        await s1.send(
            StompFrame(
                "SEND",
                {"destination": "orders/1", "receipt": "r2"},
                b"buy 100",
            )
        )
        await s1.expect("RECEIPT")
        pkt = await m.recv_publish()
        assert pkt.topic == "orders/1" and pkt.payload == b"buy 100"

        # the gateway session is visible to the broker's CM
        assert srv.broker.cm.lookup("stomp-alice") is not None

        await s1.send(StompFrame("DISCONNECT", {"receipt": "bye"}))
        await s1.expect("RECEIPT")
        await s1.close()
        await m.disconnect()
        await asyncio.sleep(0.05)
        assert srv.broker.cm.lookup("stomp-alice") is None
        await srv.stop()

    run(t())


def test_stomp_client_ack_mode():
    async def t():
        srv = await make_server()
        sport = srv.broker.gateways.get("stomp").port
        mport = srv.listeners[0].port

        s1 = StompTestClient(sport)
        await s1.connect(login="bob")
        await s1.send(
            StompFrame(
                "SUBSCRIBE",
                {"id": "7", "destination": "jobs/q", "ack": "client",
                 "receipt": "r"},
            )
        )
        await s1.expect("RECEIPT")

        m = TestClient(mport, "mq2")
        await m.connect()
        await m.publish("jobs/q", b"task-1", qos=1)
        msg = await s1.expect("MESSAGE")
        assert "ack" in msg.headers  # client-mode delivery carries an ack id
        session = srv.broker.cm.lookup("stomp-bob")
        assert len(session.inflight) == 1
        await s1.send(StompFrame("ACK", {"id": msg.headers["ack"]}))
        for _ in range(50):
            if len(session.inflight) == 0:
                break
            await asyncio.sleep(0.02)
        assert len(session.inflight) == 0  # settled by the STOMP ACK
        await s1.close()
        await m.disconnect()
        await srv.stop()

    run(t())


def test_stomp_codec_escapes_and_content_length():
    codec = StompCodec()
    frame = StompFrame(
        "SEND",
        {"destination": "a:b\nc", "receipt": "r\\1"},
        b"\x00binary\x00body",
    )
    frames, rest = codec.parse(b"", codec.serialize(frame))
    assert rest == b""
    f = frames[0]
    assert f.headers["destination"] == "a:b\nc"
    assert f.headers["receipt"] == "r\\1"
    assert f.body == b"\x00binary\x00body"
    # partial delivery reassembles
    blob = codec.serialize(frame)
    frames1, st = codec.parse(b"", blob[:10])
    assert frames1 == []
    frames2, st = codec.parse(st, blob[10:])
    assert len(frames2) == 1 and frames2[0].body == f.body
