"""Shared fakes for broker-level tests."""


class FakeChannel:
    def __init__(self):
        self.sent = []
        self.closed = None

    def send_packets(self, pkts):
        self.sent.extend(pkts)

    def close(self, reason):
        self.closed = reason
