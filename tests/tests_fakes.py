"""Shared fakes for broker-level tests."""


class FakeChannel:
    def __init__(self):
        self.sent = []
        self.closed = None

    def send_packets(self, pkts):
        self.sent.extend(pkts)

    def close(self, reason):
        self.closed = reason


def drain_folds(eng, timeout=15.0):
    """Wait until the engine has no fold in flight (shared test util)."""
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        t = eng._fold_thread
        if t is not None and t.is_alive():
            t.join(0.1)
        elif not eng._folding:
            return
    raise TimeoutError("fold never drained")
