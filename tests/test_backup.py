"""Data backup/restore (emqx_mgmt_data_backup parity): export a
node's config + retained + banned + rules + management-auth state as
one archive, wipe, and restore it into a FRESH node over the REST
API — then verify behavior, not just tables."""

import asyncio
import tempfile

import pytest

from emqx_tpu.backup import export_archive, import_archive
from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from api_helper import auth_session
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def make_server():
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.api.enable = True
    cfg.api.port = 0
    cfg.api.data_dir = tempfile.mkdtemp(prefix="emqx-mgmt-")
    return BrokerServer(cfg)


def test_round_trip_restores_wiped_node(tmp_path):
    async def t():
        # --- populate node A
        a = make_server()
        await a.start()
        broker = a.broker
        broker.apply_config("mqtt.max_qos_allowed", 1)
        broker.apply_config("auth.allow_anonymous", True)
        broker.banned.ban("clientid", "evil-1", seconds=3600,
                          reason="abuse")
        broker.rules.add_rule(
            "r-backup", 'SELECT * FROM "a/#"', description="test rule"
        )
        c = TestClient(a.listeners[0].port, "seed")
        await c.connect()
        await c.publish("cfg/a", b"A1", qos=1, retain=True)
        await c.publish("cfg/b", b"B1", qos=1, retain=True)
        await c.close()
        a.api.auth.add_admin("op2", "pw2", role="viewer")
        key, secret = a.api.auth.create_api_key("backup-key")

        path, manifest = export_archive(a, str(tmp_path))
        assert manifest["counts"]["retained"] == 2
        assert manifest["counts"]["banned"] == 1
        await a.stop()

        # --- fresh ("wiped") node B: nothing carried over
        b = make_server()
        await b.start()
        assert b.broker.config.mqtt.max_qos_allowed == 2
        assert not b.broker.banned.all()
        with open(path, "rb") as f:
            data = f.read()
        report = import_archive(b, data)
        assert not report["errors"], report["errors"]
        assert report["restored"]["retained"] == 2
        assert report["restored"]["banned"] == 1
        assert report["restored"]["rules"] == 1
        assert "listeners" in report["skipped"]  # reboot-only

        # BEHAVIOR: config applied, retained replay, ban enforced,
        # imported credentials authenticate
        assert b.broker.config.mqtt.max_qos_allowed == 1
        sub = TestClient(b.listeners[0].port, "s2")
        await sub.connect()
        await sub.subscribe("cfg/#", qos=1)
        got = {}
        for _ in range(2):
            m = await sub.recv_publish()
            got[m.topic] = m.payload
        assert got == {"cfg/a": b"A1", "cfg/b": b"B1"}
        await sub.close()

        banned_c = TestClient(b.listeners[0].port, "evil-1")
        ack = await banned_c.connect()
        assert ack.reason_code == 0x8A  # banned
        assert any(
            r.rule_id == "r-backup"
            for r in b.broker.rules.rules.values()
        )
        # imported admin + api key work against node B's API
        http, api = await auth_session(b, username="op2", password="pw2")
        async with http:
            async with http.get(api + "/api/v5/stats") as r:
                assert r.status == 200
        import base64
        basic = base64.b64encode(f"{key}:{secret}".encode()).decode()
        import aiohttp
        async with aiohttp.ClientSession(
            headers={"Authorization": f"Basic {basic}"}
        ) as keyed:
            async with keyed.get(
                f"http://127.0.0.1:{b.api.port}/api/v5/stats"
            ) as r:
                assert r.status == 200
        await b.stop()

    run(t())


def test_rest_export_import_flow():
    async def t():
        a = make_server()
        await a.start()
        c = TestClient(a.listeners[0].port, "seed")
        await c.connect()
        await c.publish("keep/x", b"1", qos=1, retain=True)
        await c.close()

        http, api = await auth_session(a)
        async with http:
            async with http.post(api + "/api/v5/data/export") as r:
                assert r.status == 201
                out = await r.json()
            name = out["filename"]
            async with http.get(
                api + f"/api/v5/data/export/{name}"
            ) as r:
                assert r.status == 200
                blob = await r.read()
            # path traversal in the download name is rejected
            async with http.get(
                api + "/api/v5/data/export/..%2F..%2Fetc%2Fpasswd"
            ) as r:
                assert r.status in (400, 404)
        await a.stop()

        b = make_server()
        await b.start()
        http2, api2 = await auth_session(b)
        async with http2:
            async with http2.post(
                api2 + "/api/v5/data/import", data=blob
            ) as r:
                assert r.status == 200
                report = await r.json()
            assert report["restored"]["retained"] == 1
            # garbage upload is a clean 400
            async with http2.post(
                api2 + "/api/v5/data/import", data=b"not-a-tar"
            ) as r:
                assert r.status == 400
        assert [m.payload for m in b.broker.retainer.match("keep/x")] \
            == [b"1"]
        await b.stop()

    run(t())


def test_import_rejects_newer_format(tmp_path):
    import io
    import json as _json
    import tarfile

    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tar:
        data = _json.dumps({"version": 99}).encode()
        info = tarfile.TarInfo("META.json")
        info.size = len(data)
        tar.addfile(info, io.BytesIO(data))

    async def t():
        b = make_server()
        await b.start()
        with pytest.raises(ValueError):
            import_archive(b, buf.getvalue())
        await b.stop()

    run(t())


def test_viewer_cannot_touch_backup_routes():
    async def t():
        a = make_server()
        await a.start()
        http, api = await auth_session(a)
        async with http:
            async with http.post(api + "/api/v5/users", json={
                "username": "v", "password": "p", "role": "viewer",
            }) as r:
                assert r.status == 201
            async with http.post(api + "/api/v5/data/export") as r:
                assert r.status == 201
                name = (await r.json())["filename"]
        viewer, api = await auth_session(a, username="v", password="p")
        async with viewer:
            # archives hold the full config incl. secrets: even the
            # GET download is administrator-only
            async with viewer.get(
                api + f"/api/v5/data/export/{name}"
            ) as r:
                assert r.status == 403
            async with viewer.post(api + "/api/v5/data/export") as r:
                assert r.status == 403
        await a.stop()

    run(t())
