"""Cluster linking: route-aware federation between two independent
brokers (emqx_cluster_link parity — routes sync first, only wanted
messages cross, origin tagging kills loops)."""

import asyncio

import pytest

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.cluster_link import filters_intersect
from emqx_tpu.config import BrokerConfig, ListenerConfig
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


@pytest.mark.parametrize(
    "a,b,want",
    [
        ("a/b", "a/b", True),
        ("a/b", "a/c", False),
        ("a/+", "a/b", True),
        ("a/#", "x/y", False),
        ("a/#", "a", True),
        ("a/#", "a/b/c", True),
        ("+/b", "a/+", True),
        ("a/+/c", "a/b/#", True),
        ("a/b/c", "a/b", False),
        ("#", "anything/at/all", True),
        ("a/+/x", "a/b/y", False),
    ],
)
def test_filters_intersect(a, b, want):
    assert filters_intersect(a, b) is want
    assert filters_intersect(b, a) is want


async def start_broker(name, links=()):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(bind="127.0.0.1", port=0)]
    cfg.cluster_name = name
    srv = BrokerServer(cfg)
    await srv.start()
    return srv


async def add_links(srv, links):
    from emqx_tpu.cluster_link import ClusterLinks

    srv.cluster_links = ClusterLinks(
        srv.broker, srv.broker.config.cluster_name, links
    )
    await srv.cluster_links.start()


async def settle(check, timeout=5.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if check():
            return True
        await asyncio.sleep(0.05)
    return False


def test_link_routes_then_messages_cross():
    async def t():
        east = await start_broker("east")
        west = await start_broker("west")
        # east pulls from west for sensor topics only; west configures
        # the symmetric link entry (that's what serves east's route ops)
        await add_links(east, [{
            "name": "west", "host": "127.0.0.1",
            "port": west.listeners[0].port,
            "topics": ["sensors/#"],
        }])
        await add_links(west, [{
            "name": "east", "host": "127.0.0.1",
            "port": east.listeners[0].port,
            "topics": [],
        }])

        # no local subscriber yet: west must see zero extern routes
        # for east even after the link connects
        agent = east.cluster_links.agents[0]
        assert await settle(lambda: agent.client.connected.is_set())
        await asyncio.sleep(0.2)
        assert not any(_extern(west).values())

        sub = TestClient(east.listeners[0].port, "e-sub")
        await sub.connect()
        await sub.subscribe("sensors/+/temp", qos=1)
        # the route op must arrive at west
        assert await settle(
            lambda: west.broker.hooks is not None and any(
                "sensors/+/temp" in fs
                for fs in _extern(west).values()
            )
        ), _extern(west)

        # a publish on west now crosses to the east subscriber
        pub = TestClient(west.listeners[0].port, "w-pub")
        await pub.connect()
        await pub.publish("sensors/s1/temp", b"19.5", qos=1)
        got = await sub.recv_publish()
        assert got.topic == "sensors/s1/temp" and got.payload == b"19.5"

        # topics outside the link allowlist never sync routes
        await sub.subscribe("billing/#")
        await asyncio.sleep(0.3)
        assert not any(
            "billing/#" in fs for fs in _extern(west).values()
        )

        # unsubscribe withdraws the route
        await sub.unsubscribe("sensors/+/temp")
        assert await settle(
            lambda: not any(
                "sensors/+/temp" in fs for fs in _extern(west).values()
            )
        )

        await pub.close()
        await sub.close()
        await east.stop()
        await west.stop()

    run(t())


def _extern(srv):
    cl = srv.cluster_links
    return cl.server.extern_routes if cl else {}


def test_bidirectional_links_no_loop():
    async def t():
        east = await start_broker("east")
        west = await start_broker("west")
        await add_links(east, [{
            "name": "west", "host": "127.0.0.1",
            "port": west.listeners[0].port, "topics": ["#"],
        }])
        await add_links(west, [{
            "name": "east", "host": "127.0.0.1",
            "port": east.listeners[0].port, "topics": ["#"],
        }])

        se = TestClient(east.listeners[0].port, "se")
        await se.connect()
        await se.subscribe("chat/#", qos=1)
        sw = TestClient(west.listeners[0].port, "sw")
        await sw.connect()
        await sw.subscribe("chat/#", qos=1)

        assert await settle(lambda: any(_extern(west).values()))
        assert await settle(lambda: any(_extern(east).values()))

        pub = TestClient(west.listeners[0].port, "wp")
        await pub.connect()
        await pub.publish("chat/hello", b"x", qos=1)

        got_w = await sw.recv_publish()
        got_e = await se.recv_publish()
        assert got_w.payload == got_e.payload == b"x"

        # loop check: neither side may see the message twice
        await asyncio.sleep(0.5)
        extra = 0
        for c in (se, sw):
            try:
                await asyncio.wait_for(c.recv_publish(), 0.2)
                extra += 1
            except asyncio.TimeoutError:
                pass
        assert extra == 0, "message echoed back across the link"

        await pub.close()
        await se.close()
        await sw.close()
        await east.stop()
        await west.stop()

    run(t())


def test_three_cluster_chain_no_reforward():
    """A link-imported message must never be re-exported (the
    reference's 'no gossip forwarding': forward/1 drops any message
    carrying a link origin) — in a 3-cluster mesh re-forwarding would
    duplicate deliveries or storm a cycle forever."""

    async def t():
        a = await start_broker("a")
        b = await start_broker("b")
        c = await start_broker("c")
        # full mesh: every cluster links to the other two
        async def mesh(me, peers):
            await add_links(me, [{
                "name": p.broker.config.cluster_name, "host": "127.0.0.1",
                "port": p.listeners[0].port, "topics": ["#"],
            } for p in peers])
        await mesh(a, (b, c))
        await mesh(b, (a, c))
        await mesh(c, (a, b))

        subs = []
        for srv, cid in ((a, "sa"), (b, "sb"), (c, "sc")):
            s = TestClient(srv.listeners[0].port, cid)
            await s.connect()
            await s.subscribe("news/#", qos=1)
            subs.append(s)
        # wait until every broker knows both peers want news/#
        for srv in (a, b, c):
            assert await settle(lambda srv=srv: sum(
                1 for fs in _extern(srv).values() if "news/#" in fs
            ) == 2), _extern(srv)

        pub = TestClient(a.listeners[0].port, "pa")
        await pub.connect()
        await pub.publish("news/x", b"once", qos=1)

        # each subscriber gets exactly one copy
        for s in subs:
            got = await s.recv_publish()
            assert got.payload == b"once"
        await asyncio.sleep(0.5)
        for s in subs:
            try:
                extra = await asyncio.wait_for(s.recv_publish(), 0.2)
                raise AssertionError(
                    f"duplicate delivery across the mesh: {extra.topic}"
                )
            except asyncio.TimeoutError:
                pass

        await pub.close()
        for s in subs:
            await s.close()
        for srv in (a, b, c):
            await srv.stop()

    run(t())


def test_route_op_requires_agent_identity():
    """Route ops published by a non-agent client for a configured peer
    name must be ignored, and $LINK/msg subscriptions are denied for
    anyone but that peer's agent — otherwise any local client could
    reset federation or siphon every forwarded publish past topic
    ACLs."""
    import json as _json

    async def t():
        east = await start_broker("east")
        await add_links(east, [{
            "name": "west", "host": "127.0.0.1",
            "port": 1, "topics": [],  # port 1: agent never connects
        }])

        evil = TestClient(east.listeners[0].port, "evil")
        await evil.connect()
        # 1. spoofed route op for the configured peer is ignored
        await evil.publish("$LINK/route/west", _json.dumps(
            {"op": "reset", "filters": ["#"]}
        ).encode(), qos=1)
        await asyncio.sleep(0.2)
        assert not _extern(east).get("west"), _extern(east)

        # 2. $LINK/msg subscription denied for a foreign client
        ack = await evil.subscribe("$LINK/msg/west", qos=1)
        assert ack.reason_codes[0] >= 0x80, ack.reason_codes
        ack = await evil.subscribe("$LINK/#", qos=1)
        assert ack.reason_codes[0] >= 0x80, ack.reason_codes

        # 3. the real agent identity is accepted for both
        agent = TestClient(east.listeners[0].port, "$link:west:east")
        await agent.connect()
        ack = await agent.subscribe("$LINK/msg/west", qos=1)
        assert ack.reason_codes[0] < 0x80, ack.reason_codes
        await agent.publish("$LINK/route/west", _json.dumps(
            {"op": "add", "filters": ["t/#"]}
        ).encode(), qos=1)
        assert await settle(
            lambda: "t/#" in _extern(east).get("west", ())
        )

        await evil.close()
        await agent.close()
        await east.stop()

    run(t())


def test_link_guard_allows_root_wildcards_blocks_share_bypass():
    """'#' can never match $-topics ([MQTT-4.7.2-1]) so it must be
    GRANTED; '$share/g/$LINK/msg/x' is the same siphon with a prefix
    and must be denied; imported messages on reserved topics drop."""

    async def t():
        east = await start_broker("east")
        await add_links(east, [{
            "name": "west", "host": "127.0.0.1", "port": 1, "topics": [],
        }])

        mon = TestClient(east.listeners[0].port, "monitor")
        await mon.connect()
        for ok_flt in ("#", "+/msg/x", "$SYS/#"):
            ack = await mon.subscribe(ok_flt, qos=1)
            assert ack.reason_codes[0] < 0x80, (ok_flt, ack.reason_codes)
        for bad_flt in ("$share/g/$LINK/msg/west", "$LINK/route/+",
                        "$LINK/msg/west"):
            ack = await mon.subscribe(bad_flt, qos=1)
            assert ack.reason_codes[0] >= 0x80, (bad_flt, ack.reason_codes)

        # imported wrapped message targeting a control topic is dropped
        from emqx_tpu.cluster_link import LinkServer  # noqa: F401
        from emqx_tpu.message import Message
        import json as _json
        srv = east.cluster_links.server
        srv._on_publish(Message(
            topic="$LINK/route/west",
            payload=_json.dumps(
                {"op": "reset", "filters": ["#"]}).encode(),
            from_client="$link:west:forged",
            headers={"cluster_origin": "elsewhere"},
        ))
        assert not srv.extern_routes.get("west")

        await mon.close()
        await east.stop()

    run(t())


def test_delivery_guard_blocks_hookless_subscriptions():
    """Subscriptions that never passed the client.subscribe hook
    (durable resume, takeover import, boot-window subscribes) must
    still get nothing: $LINK/msg delivery is pinned to the agent
    session at fan-out time."""

    async def t():
        east = await start_broker("east")
        await add_links(east, [{
            "name": "west", "host": "127.0.0.1", "port": 1, "topics": [],
        }])
        broker = east.broker

        # connect two clients; then force-install a $LINK/msg sub for
        # the evil one directly in the router (simulating a durable
        # restore that bypasses the subscribe hook)
        evil = TestClient(east.listeners[0].port, "evil")
        await evil.connect()
        await evil.subscribe("probe/ok", qos=1)  # liveness channel
        agent = TestClient(east.listeners[0].port, "$link:west:east")
        await agent.connect()
        ack = await agent.subscribe("$LINK/msg/west", qos=1)
        assert ack.reason_codes[0] < 0x80
        from emqx_tpu.broker.session import SubOpts
        broker.router.subscribe("evil", "$LINK/msg/west", SubOpts(qos=1))

        # a forwarded-bound publish: west wants t/#, someone publishes
        east.cluster_links.server.extern_routes["west"] = {"t/#"}
        pub = TestClient(east.listeners[0].port, "p")
        await pub.connect()
        await pub.publish("t/x", b"secret", qos=1)

        # the agent receives the wrapped copy; evil receives nothing
        got = await agent.recv_publish(timeout=3)
        assert got.topic == "$LINK/msg/west"
        await pub.publish("probe/ok", b"alive", qos=1)
        got = await evil.recv_publish(timeout=3)
        assert got.topic == "probe/ok", got  # NOT the $LINK copy

        for c in (evil, agent, pub):
            await c.close()
        await east.stop()

    run(t())


def test_forged_wrapped_publish_dropped():
    """A local client hand-publishing a wrapped payload on
    $LINK/msg/<peer> must be dropped — otherwise it would be unwrapped
    and injected into the remote cluster with forged topic/from_client,
    bypassing the remote side's ACLs."""
    import json as _json
    import base64 as _b64

    async def t():
        east = await start_broker("east")
        await add_links(east, [{
            "name": "west", "host": "127.0.0.1", "port": 1, "topics": [],
        }])
        agent = TestClient(east.listeners[0].port, "$link:west:east")
        await agent.connect()
        ack = await agent.subscribe("$LINK/msg/west", qos=1)
        assert ack.reason_codes[0] < 0x80

        forger = TestClient(east.listeners[0].port, "forger")
        await forger.connect()
        forged = _json.dumps({
            "t": "secret/cmd",
            "p": _b64.b64encode(b"pwn").decode(),
            "q": 1, "r": False, "o": "east", "c": "admin",
        }).encode()
        await forger.publish("$LINK/msg/west", forged, qos=1)
        try:
            got = await agent.recv_publish(timeout=0.8)
            raise AssertionError(
                f"forged wrapped publish delivered to agent: {got.topic}"
            )
        except asyncio.TimeoutError:
            pass

        # the legitimate egress path still flows (marker set internally)
        east.cluster_links.server.extern_routes["west"] = {"t/#"}
        await forger.publish("t/x", b"real", qos=1)
        got = await agent.recv_publish(timeout=3)
        assert got.topic == "$LINK/msg/west"

        await agent.close()
        await forger.close()
        await east.stop()

    run(t())


def test_cluster_name_with_colon_rejected():
    from emqx_tpu.cluster_link import ClusterLinks
    import pytest as _pytest

    class _B:  # ClusterLinks only touches broker at start()
        pass

    with _pytest.raises(ValueError):
        ClusterLinks(_B(), "eu:west", [{"name": "us"}])
    with _pytest.raises(ValueError):
        ClusterLinks(_B(), "eu", [{"name": "us:east"}])
