"""Cluster linking: route-aware federation between two independent
brokers (emqx_cluster_link parity — routes sync first, only wanted
messages cross, origin tagging kills loops)."""

import asyncio

import pytest

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.cluster_link import filters_intersect
from emqx_tpu.config import BrokerConfig, ListenerConfig
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


@pytest.mark.parametrize(
    "a,b,want",
    [
        ("a/b", "a/b", True),
        ("a/b", "a/c", False),
        ("a/+", "a/b", True),
        ("a/#", "x/y", False),
        ("a/#", "a", True),
        ("a/#", "a/b/c", True),
        ("+/b", "a/+", True),
        ("a/+/c", "a/b/#", True),
        ("a/b/c", "a/b", False),
        ("#", "anything/at/all", True),
        ("a/+/x", "a/b/y", False),
    ],
)
def test_filters_intersect(a, b, want):
    assert filters_intersect(a, b) is want
    assert filters_intersect(b, a) is want


async def start_broker(name, links=()):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(bind="127.0.0.1", port=0)]
    cfg.cluster_name = name
    srv = BrokerServer(cfg)
    await srv.start()
    return srv


async def add_links(srv, links):
    from emqx_tpu.cluster_link import ClusterLinks

    srv.cluster_links = ClusterLinks(
        srv.broker, srv.broker.config.cluster_name, links
    )
    await srv.cluster_links.start()


async def settle(check, timeout=5.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if check():
            return True
        await asyncio.sleep(0.05)
    return False


def test_link_routes_then_messages_cross():
    async def t():
        east = await start_broker("east")
        west = await start_broker("west")
        # east pulls from west for sensor topics only; west configures
        # the symmetric link entry (that's what serves east's route ops)
        await add_links(east, [{
            "name": "west", "host": "127.0.0.1",
            "port": west.listeners[0].port,
            "topics": ["sensors/#"],
        }])
        await add_links(west, [{
            "name": "east", "host": "127.0.0.1",
            "port": east.listeners[0].port,
            "topics": [],
        }])

        # no local subscriber yet: west must see zero extern routes
        # for east even after the link connects
        agent = east.cluster_links.agents[0]
        assert await settle(lambda: agent.client.connected.is_set())
        await asyncio.sleep(0.2)
        assert not any(_extern(west).values())

        sub = TestClient(east.listeners[0].port, "e-sub")
        await sub.connect()
        await sub.subscribe("sensors/+/temp", qos=1)
        # the route op must arrive at west
        assert await settle(
            lambda: west.broker.hooks is not None and any(
                "sensors/+/temp" in fs
                for fs in _extern(west).values()
            )
        ), _extern(west)

        # a publish on west now crosses to the east subscriber
        pub = TestClient(west.listeners[0].port, "w-pub")
        await pub.connect()
        await pub.publish("sensors/s1/temp", b"19.5", qos=1)
        got = await sub.recv_publish()
        assert got.topic == "sensors/s1/temp" and got.payload == b"19.5"

        # topics outside the link allowlist never sync routes
        await sub.subscribe("billing/#")
        await asyncio.sleep(0.3)
        assert not any(
            "billing/#" in fs for fs in _extern(west).values()
        )

        # unsubscribe withdraws the route
        await sub.unsubscribe("sensors/+/temp")
        assert await settle(
            lambda: not any(
                "sensors/+/temp" in fs for fs in _extern(west).values()
            )
        )

        await pub.close()
        await sub.close()
        await east.stop()
        await west.stop()

    run(t())


def _extern(srv):
    cl = srv.cluster_links
    return cl.server.extern_routes if cl else {}


def test_bidirectional_links_no_loop():
    async def t():
        east = await start_broker("east")
        west = await start_broker("west")
        await add_links(east, [{
            "name": "west", "host": "127.0.0.1",
            "port": west.listeners[0].port, "topics": ["#"],
        }])
        await add_links(west, [{
            "name": "east", "host": "127.0.0.1",
            "port": east.listeners[0].port, "topics": ["#"],
        }])

        se = TestClient(east.listeners[0].port, "se")
        await se.connect()
        await se.subscribe("chat/#", qos=1)
        sw = TestClient(west.listeners[0].port, "sw")
        await sw.connect()
        await sw.subscribe("chat/#", qos=1)

        assert await settle(lambda: any(_extern(west).values()))
        assert await settle(lambda: any(_extern(east).values()))

        pub = TestClient(west.listeners[0].port, "wp")
        await pub.connect()
        await pub.publish("chat/hello", b"x", qos=1)

        got_w = await sw.recv_publish()
        got_e = await se.recv_publish()
        assert got_w.payload == got_e.payload == b"x"

        # loop check: neither side may see the message twice
        await asyncio.sleep(0.5)
        extra = 0
        for c in (se, sw):
            try:
                await asyncio.wait_for(c.recv_publish(), 0.2)
                extra += 1
            except asyncio.TimeoutError:
                pass
        assert extra == 0, "message echoed back across the link"

        await pub.close()
        await se.close()
        await sw.close()
        await east.stop()
        await west.stop()

    run(t())
