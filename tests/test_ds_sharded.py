"""Million-durable-session store: the sharded segment-log layout, the
incremental metadata journals and their O(delta) recovery, generation-
pinned GC, and the cross-shard durability invariant.

Four claims under test:

  * SHARDING — messages partition by stream hash into independent
    shard stores (own segment chain, own metadata, own SyncGate);
    concrete filters route to one shard, corruption in one shard never
    widens to another, and the crash-point suite proves a crash
    BETWEEN two shards' fsyncs loses nothing acked (a window only acks
    after EVERY dirty shard flushed — the GateGroup barrier);
  * JOURNALED METADATA — census/LTS deltas append to a checksummed
    journal, snapshots are rewritten only by the fold, and a crash at
    ANY point of the fold (snapshot-then-truncate) is idempotent:
    replaying the stale journal over the new snapshot converges to the
    same state, and a re-fold produces the same snapshot;
  * O(delta) RECOVERY — reopen with intact metadata replays the
    journal and scans only from the watermark (no rebuild event);
    only a store with NO usable snapshot pays the full rebuild, which
    now runs in the background while reads serve unpruned;
  * GENERATION PINS — GC never reclaims a segment generation a live
    replay cursor still needs (seeded property enumeration).
"""

import glob
import json
import os
import random
import struct

import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu import topic as T
from emqx_tpu.ds import atomicio
from emqx_tpu.ds.api import StreamRef, stream_of
from emqx_tpu.ds.builtin_local import LocalStorage
from emqx_tpu.ds.journal import MetaJournal
from emqx_tpu.ds.native import load
from emqx_tpu.ds.persist import DurableSessions
from emqx_tpu.ds.sharded import ShardedStorage
from emqx_tpu.message import Message
from tools.crashsim import CrashRecorder, materialize


def _lib():
    try:
        return load()
    except Exception:
        return None


pytestmark = pytest.mark.skipif(
    _lib() is None, reason="native dslog unavailable"
)

HDR = struct.Struct("<IIIQQ")  # len, crc32, stream, ts, seq


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


def msg(topic, t, payload=b"x", qos=1):
    return Message(
        topic=topic, payload=payload, qos=qos, timestamp=t,
        from_client="pub",
    )


def drain(store, flt, start=0):
    out = []
    for s in store.get_streams(flt, start):
        it = store.make_iterator(s, flt, start)
        while True:
            it, batch = store.next(it, 64)
            if not batch:
                break
            out.extend(batch)
    return out


def _matches(topic, flt):
    return T.match_words(T.words(topic), T.words(flt))


# ----------------------------------------------------------- sharding


def test_shard_routing_and_roundtrip(tmp_path):
    """Concrete filters route to exactly one shard; wildcards fan out;
    every message round-trips through the shard that owns it."""
    st = ShardedStorage(str(tmp_path / "db"), n_shards=4, layout="hash")
    topics = [f"fam{i}/dev{j}/t" for i in range(3) for j in range(4)]
    msgs = [msg(t, 100.0 + i) for i, t in enumerate(topics)]
    counts = st.store_batch(msgs, sync=True)
    # the partition map matches the shard hash, and per-shard counts
    # sum to the batch (the owner marks each shard's gate from this)
    assert sum(counts.values()) == len(msgs)
    for idx in counts:
        assert 0 <= idx < 4
    assert counts == {
        s: sum(1 for t in topics if st.shard_for(t) == s)
        for s in set(map(st.shard_for, topics))
    }
    # concrete filter: all streams carry the owning shard's store tag
    for t in topics:
        streams = st.get_streams(t)
        assert streams, t
        assert {s.store for s in streams} == {st.shard_for(t)}
    # wildcard: fans out across every shard holding data
    wide = st.get_streams("#")
    assert {s.store for s in wide} == set(counts)
    got = {m.topic for m in drain(st, "#")}
    assert got == set(topics)
    # per-shard stats rows exist for every shard
    rows = st.shard_stats()
    assert [r["shard"] for r in rows] == [0, 1, 2, 3]
    st.close()


def test_stream_store_tag_serialization():
    """store == 0 serializes away (old checkpoints byte-identical);
    nonzero round-trips."""
    s0 = StreamRef(shard=3)
    assert "store" not in s0.to_json()
    assert StreamRef.from_json(s0.to_json()).store == 0
    s1 = StreamRef(shard=3, store=2)
    j = s1.to_json()
    assert j["store"] == 2
    assert StreamRef.from_json(j) == s1


def test_sharded_sessions_end_to_end(tmp_path):
    """DurableSessions over 4 shards: per-shard gates sync
    independently, sync_stats breaks down per shard, replay crosses
    shards, and the on-disk marker pins the shard count."""
    base = str(tmp_path / "ds")
    t0 = 1_700_000_000.0
    ds = DurableSessions(base, layout="hash", fsync="always", n_shards=4)
    try:
        ds.save("c1", {"fam/#": {"qos": 1}}, expiry=1e9, now=t0)
        ds.add_filter("fam/#")
        batch = [
            msg(f"fam/dev{i}/t", t0 + 1 + i * 0.001) for i in range(40)
        ]
        ds.persist(batch)
        ds.gate.sync_now()
        stats = ds.sync_stats()
        assert stats["shards"] == 4
        rows = stats["per_shard"]
        assert [r["shard"] for r in rows] == [0, 1, 2, 3]
        # exactly the shards that took appends flushed; none is dirty
        assert all(r["unsynced"] == 0 for r in rows)
        assert sum(r["sync_count"] for r in rows) >= 1
    finally:
        ds.close()
    # restart: boot-restored state replays across every shard; a
    # drifted config cannot re-route reads (the marker pins where
    # records LIVE)
    ds2 = DurableSessions(base, layout="hash", fsync="always", n_shards=2)
    try:
        assert ds2.n_shards == 4
        state = ds2.load("c1")
        got = {m.mid for _f, m in ds2.replay(state)}
        assert got == {m.mid for m in batch}
    finally:
        ds2.close()


def test_corruption_isolated_per_shard(tmp_path):
    """Byte surgery across shards: a torn tail in one shard truncates
    quietly THERE, an interior flip in another quarantines THERE — and
    neither touches the other shard's data."""
    base = str(tmp_path / "db")
    st = ShardedStorage(base, n_shards=2, layout="hash")
    topics = [f"fam{i}/dev{j}/t" for i in range(4) for j in range(4)]
    by_shard = {0: [], 1: []}
    t = 100.0
    for topic in topics:
        t += 0.001
        m = msg(topic, t, payload=b"p" * 64)
        by_shard[st.shard_for(topic)].append(m)
    assert by_shard[0] and by_shard[1]  # surgery needs both populated
    st.store_batch(
        [m for ms in by_shard.values() for m in ms], sync=True
    )
    st.close()

    def seg(shard):
        [p] = glob.glob(
            os.path.join(base, f"shard-{shard:02d}", "seg-*.log")
        )
        return p

    # shard 0: tear the last record mid-payload (crash artifact)
    with open(seg(0), "r+b") as f:
        f.truncate(os.path.getsize(seg(0)) - 20)
    # shard 1: flip one payload byte of the FIRST record (interior
    # break — records after it must quarantine, not vanish silently)
    with open(seg(1), "r+b") as f:
        f.seek(HDR.size + 2)
        b = f.read(1)
        f.seek(HDR.size + 2)
        f.write(bytes([b[0] ^ 0xFF]))

    st2 = ShardedStorage(base, n_shards=2, layout="hash")
    try:
        rows = {r["shard"]: r for r in st2.shard_stats()}
        # the torn tail is NOT corruption; the flip quarantines only
        # in its own shard
        assert rows[0]["corrupt_records"] == 0
        assert rows[0]["quarantined_segments"] == 0
        assert rows[1]["corrupt_records"] >= 1
        assert rows[1]["quarantined_segments"] == 1
        # ...and the facade rolls it up + forwarded the event
        assert st2.corruption_stats()["quarantined_segments"] == 1
        assert any(
            e["kind"] == "storage" for e in st2.corruption_events
        )
        # shard 0 serves everything but its torn final record
        got0 = {m.mid for m in drain(st2, "#") if
                st2.shard_for(m.topic) == 0}
        assert got0 == {m.mid for m in by_shard[0][:-1]}
        # shard 1's prefix (before the flipped record's suffix) intact:
        # the flip hit record 0, so the quarantine starts there — but
        # no OTHER shard lost anything to it
        assert len(drain(st2, "#")) >= len(by_shard[0]) - 1
    finally:
        st2.close()


def test_crash_between_shard_fsyncs_loses_nothing_acked(tmp_path):
    """The cross-shard invariant: a window only acks after EVERY dirty
    shard's fsync completed, so a crash landing between shard A's sync
    and shard B's sync must recover every acked message.  Enumerates
    every op-boundary cut of a seeded two-shard workload — the
    between-fsyncs cuts are in the enumeration by construction."""
    base = tmp_path / "live"
    rng = random.Random(42)
    t0 = 1_700_000_000.0
    batches = []          # (msgs, last_sync_op_index)
    with CrashRecorder() as rec:
        ds = DurableSessions(
            str(base), layout="hash", fsync="always", n_shards=2
        )
        ds.save("c1", {"fam/#": {"qos": 1}}, expiry=1e9, now=t0)
        ds.add_filter("fam/#")
        t = t0 + 1.0
        for _ in range(6):
            batch = []
            for _i in range(rng.randint(2, 5)):
                t += 0.001
                batch.append(msg(
                    f"fam/dev{rng.randint(0, 7)}/t", t,
                    payload=bytes(rng.getrandbits(8) for _ in range(12)),
                ))
            ds.persist(batch)
            # the group flush: one sync op PER DIRTY SHARD lands in
            # the trace; the ack for this window requires all of them
            ds.gate.sync_now()
            syncs = [i for i, op in enumerate(rec.ops)
                     if op.kind == "sync"]
            batches.append((batch, max(syncs)))
    ds.close()
    # the workload crossed both shards and produced multi-sync windows
    assert {op.path for op in rec.ops if op.kind == "sync"} >= {
        os.path.join(str(base), "messages", "shard-00"),
        os.path.join(str(base), "messages", "shard-01"),
    }
    for k in range(len(rec.ops) + 1):
        out = tmp_path / f"crash-{k}"
        materialize(rec.ops, k, src_root=str(base), out_root=str(out))
        acked = {
            m.mid for batch, last_sync in batches if last_sync < k
            for m in batch
        }
        ds2 = DurableSessions(
            str(out), layout="hash", fsync="always", n_shards=2
        )
        try:
            state = ds2.load("c1")
            assert state is not None or not acked
            if state is None:
                continue
            got = {m.mid for _f, m in ds2.replay(state)}
            assert acked <= got, (k, acked - got)
        finally:
            ds2.close()


# ------------------------------------------------- journaled metadata


def test_reopen_is_journal_replay_not_rebuild(tmp_path):
    """Intact snapshot + journal: reopen replays the journal and
    delta-scans from the watermark — no rebuild event fires, and the
    census still prunes."""
    d = str(tmp_path / "db")
    st = LocalStorage(d, n_streams=4)
    st.store_batch([msg("a/b/c", 100.0), msg("d/e/f", 101.0)], sync=True)
    # journal-only flush (no fold yet): snapshot absent, journal has
    # the deltas + watermark
    assert not os.path.exists(os.path.join(d, "census.json"))
    assert os.path.getsize(os.path.join(d, "census.journal")) > 0
    st.close()  # close folds: snapshot written, journal truncated
    assert os.path.exists(os.path.join(d, "census.json"))
    assert os.path.getsize(os.path.join(d, "census.journal")) == 0

    st2 = LocalStorage(d, n_streams=4)
    try:
        assert st2.rebuild_events == [] and not st2.rebuilding
        assert {m.topic for m in drain(st2, "#")} == {"a/b/c", "d/e/f"}
        # census pruning survived the reopen
        assert st2.get_streams("zzz/+/q") == []
    finally:
        st2.close()


def test_journal_covers_appends_after_snapshot(tmp_path):
    """Deltas that arrived AFTER the last fold live only in the
    journal; a reopen that ignored it (or a scan that ignored the
    watermark) would mis-prune."""
    d = str(tmp_path / "db")
    st = LocalStorage(d, n_streams=4)
    st.store_batch([msg("a/b/c", 100.0)], sync=True)
    st.save_meta_full()  # fold: snapshot holds a/b/c only
    st.store_batch([msg("x/y/z", 200.0)], sync=True)  # journal only
    st._log.close()  # simulate crash: no close-time fold
    st2 = LocalStorage(d, n_streams=4)
    try:
        assert st2.rebuild_events == []
        assert {m.topic for m in drain(st2, "#")} == {"a/b/c", "x/y/z"}
        assert st2.get_streams("x/y/z") != []
    finally:
        st2.close()


def test_fold_crash_idempotence(tmp_path):
    """Crash between the fold's snapshot write and its journal
    truncation: the stale journal replays over the new snapshot as a
    no-op, and a re-fold converges to the identical snapshot."""
    d = str(tmp_path / "db")
    st = LocalStorage(d, n_streams=4)
    st.store_batch(
        [msg(f"fam{i}/dev/t", 100.0 + i) for i in range(8)], sync=True
    )
    jpath = os.path.join(d, "census.journal")
    stale_journal = open(jpath, "rb").read()
    assert stale_journal  # the flush journaled deltas + watermark
    st.save_meta_full()  # the fold
    clean_snapshot = atomicio.load_json(
        os.path.join(d, "census.json")
    )
    st._log.close()
    # materialize the mid-fold crash: new snapshot, journal NOT yet
    # truncated
    with open(jpath, "wb") as f:
        f.write(stale_journal)

    st2 = LocalStorage(d, n_streams=4)
    try:
        assert st2.corruption_events == []
        assert st2.rebuild_events == []
        assert len(drain(st2, "#")) == 8
        st2.save_meta_full()  # the re-fold
    finally:
        st2.close()
    refolded = atomicio.load_json(os.path.join(d, "census.json"))
    assert refolded == clean_snapshot


def test_journal_torn_tail_recovers_silently(tmp_path):
    """A journal append cut mid-frame is the normal crash artifact:
    the valid prefix (and its watermark) applies, the delta scan
    covers the rest — correct census, no corruption event."""
    d = str(tmp_path / "db")
    st = LocalStorage(d, n_streams=4)
    st.store_batch([msg("a/b/c", 100.0)], sync=True)
    st.store_batch([msg("x/y/z", 200.0)], sync=True)  # second frameset
    jpath = os.path.join(d, "census.journal")
    st._log.close()
    with open(jpath, "r+b") as f:
        f.truncate(os.path.getsize(jpath) - 3)
    st2 = LocalStorage(d, n_streams=4)
    try:
        assert st2.corruption_events == []
        assert {m.topic for m in drain(st2, "#")} == {"a/b/c", "x/y/z"}
        assert st2.get_streams("x/y/z") != []
    finally:
        st2.close()


def test_journal_interior_break_alarms_not_silent(tmp_path):
    """A bit flip INSIDE the journal (valid frames after it) means a
    once-valid suffix is gone: the loader must count corruption (the
    alarm path) and still come out serving every record."""
    d = str(tmp_path / "db")
    st = LocalStorage(d, n_streams=4)
    st.store_batch([msg("a/b/c", 100.0)], sync=True)
    st.store_batch([msg("x/y/z", 200.0)], sync=True)
    jpath = os.path.join(d, "census.journal")
    st._log.close()
    with open(jpath, "r+b") as f:
        f.seek(9)  # payload of the first frame
        b = f.read(1)
        f.seek(9)
        f.write(bytes([b[0] ^ 0xFF]))
    st2 = LocalStorage(d, n_streams=4)
    try:
        assert any(
            e["kind"] == "meta" for e in st2.corruption_events
        )
        # conservative recovery: full correctness from the log
        st2.rebuild_now()
        assert {m.topic for m in drain(st2, "#")} == {"a/b/c", "x/y/z"}
    finally:
        st2.close()


def test_journal_append_chaos_error_drop_duplicate(tmp_path):
    """The ds.journal.append seam: an error keeps the deltas buffered
    for the next flush; a drop (lying disk) still recovers correct
    from the log; a duplicate replays idempotently."""
    d = str(tmp_path / "db")
    st = LocalStorage(d, n_streams=4)
    st.store_batch([msg("a/b/c", 100.0)], sync=False)
    st.sync_data()
    fp.configure("ds.journal.append", "error")
    with pytest.raises(ConnectionError):
        st.save_meta()
    fp.clear()
    st.save_meta()  # the retry lands the buffered deltas
    st._log.close()
    st2 = LocalStorage(d, n_streams=4)
    assert st2.get_streams("a/b/c") != []
    assert st2.rebuild_events == []
    st2.close()

    d2 = str(tmp_path / "db2")
    st = LocalStorage(d2, n_streams=4)
    st.store_batch([msg("a/b/c", 100.0)], sync=False)
    st.sync_data()
    fp.configure("ds.journal.append", "drop")
    st.save_meta()  # silently lost
    fp.clear()
    st._log.close()
    st2 = LocalStorage(d2, n_streams=4)
    st2.rebuild_now()  # no metadata at all -> background rebuild
    assert {m.topic for m in drain(st2, "#")} == {"a/b/c"}
    st2.close()

    d3 = str(tmp_path / "db3")
    st = LocalStorage(d3, n_streams=4)
    st.store_batch([msg("a/b/c", 100.0)], sync=False)
    st.sync_data()
    fp.configure("ds.journal.append", "duplicate")
    st.save_meta()
    fp.clear()
    st._log.close()
    st2 = LocalStorage(d3, n_streams=4)
    assert st2.corruption_events == []
    assert st2.get_streams("a/b/c") != []
    assert {m.topic for m in drain(st2, "#")} == {"a/b/c"}
    st2.close()


# ------------------------------------------------- background rebuild


def test_background_rebuild_serves_then_prunes(tmp_path):
    """A store with NO usable census serves unpruned DURING the
    background rebuild (progress + events surface it) and prunes once
    the scan lands."""
    d = str(tmp_path / "db")
    st = LocalStorage(d, n_streams=4)
    topics = [f"fam{i}/dev/t" for i in range(6)]
    st.store_batch([msg(t, 100.0 + i) for i, t in enumerate(topics)],
                   sync=True)
    st.close()
    os.remove(os.path.join(d, "census.json"))
    os.remove(os.path.join(d, "census.journal"))

    st2 = LocalStorage(d, n_streams=4)
    try:
        events = [e["event"] for e in st2.rebuild_events]
        assert events[0] == "start"
        # reads during (or after) the rebuild serve everything
        assert {m.topic for m in drain(st2, "#")} == set(topics)
        st2.rebuild_now()
        assert not st2.rebuilding
        assert [e["event"] for e in st2.rebuild_events][-1] == "done"
        prog = st2.rebuild_progress
        assert prog["scanned"] == prog["total"] > 0
        # the rebuilt census prunes again
        assert st2.get_streams("zzz/+/q") == []
        # appends racing the scan are merged, not lost
    finally:
        st2.close()
    # the close-time fold persisted the rebuilt census: next open is
    # a plain journal replay, no rebuild
    st3 = LocalStorage(d, n_streams=4)
    assert st3.rebuild_events == []
    st3.close()


def test_rebuild_merges_live_appends(tmp_path):
    """A topic first sighted WHILE the rebuild scan runs lands in the
    census (the worker merges the live list under the lock before
    declaring completion)."""
    d = str(tmp_path / "db")
    st = LocalStorage(d, n_streams=4)
    st.store_batch([msg("a/b/c", 100.0)], sync=True)
    st.close()
    os.remove(os.path.join(d, "census.json"))
    os.remove(os.path.join(d, "census.journal"))
    # foreground rebuild would finish before we can append; use the
    # background one and append immediately after open
    st2 = LocalStorage(d, n_streams=4)
    try:
        st2.store_batch([msg("x/y/z", 200.0)], sync=False)
        st2.sync_data()
        st2.rebuild_now()
        assert st2.get_streams("x/y/z") != []
        assert {m.topic for m in drain(st2, "#")} == {"a/b/c", "x/y/z"}
    finally:
        st2.close()


def test_broker_rebuild_alarm_lifecycle(tmp_path):
    """The ds_meta_rebuild alarm: raised when a boot-time census
    rebuild starts, cleared when it lands; the rebuild counter
    ticks."""
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.config import BrokerConfig

    base = str(tmp_path / "ds")
    ds = DurableSessions(base, layout="hash", fsync="always")
    ds.save("c1", {"fam/#": {"qos": 1}}, expiry=1e9,
            now=1_700_000_000.0)
    ds.add_filter("fam/#")
    ds.persist([msg(f"fam/d{i}/t", 1_700_000_001.0 + i)
                for i in range(8)])
    ds.gate.sync_now()
    ds.close()
    os.remove(os.path.join(base, "messages", "census.json"))
    jpath = os.path.join(base, "messages", "census.journal")
    if os.path.exists(jpath):
        os.remove(jpath)

    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.durable.enable = True
    cfg.durable.data_dir = base
    cfg.durable.layout = "hash"
    cfg.durable.fsync = "always"
    b = Broker(config=cfg)
    try:
        b.durable.rebuild_now()
        assert b.metrics.all()["ds.meta.rebuild"] >= 1
        # the done event cleared the alarm (events run inline: no loop)
        assert "ds_meta_rebuild" not in {
            a.name for a in b.alarms.active()
        }
        state = b.durable.load("c1")
        assert len(list(b.durable.replay(state))) == 8
    finally:
        b.shutdown()


# ----------------------------------------------------- generation GC


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_gc_never_reclaims_pinned_generation(tmp_path, seed):
    """Seeded property: for a random cursor into a multi-segment log,
    GC with that cursor's generation pin never reclaims a segment the
    cursor still needs — every record past the cursor stays
    readable — while an unpinned GC reclaims them all."""
    rng = random.Random(seed)
    d = str(tmp_path / "db")
    st = LocalStorage(d, n_streams=1, seg_bytes=2048)
    msgs = [
        msg("g/s", 100.0 + i, payload=bytes(rng.randint(100, 400)))
        for i in range(40)
    ]
    st.store_batch(msgs, sync=True)
    n_seg = len(glob.glob(os.path.join(d, "seg-*.log")))
    assert n_seg > 3  # the property needs a real segment chain
    [stream] = st.get_streams("g/s")
    # park a cursor at a random message boundary
    cut = rng.randint(5, len(msgs) - 5)
    cursor_ts = int(msgs[cut - 1].timestamp * 1e6)
    it = st.make_iterator(stream, "g/s", 0)
    got = []
    while len(got) < cut:
        it, batch = st.next(it, min(7, cut - len(got)))
        got.extend(batch)
    pin = st.seg_for(stream, it.ts, it.seq)
    assert pin >= 0
    # GC far in the future, pinned: generations >= pin survive
    dropped = st.gc(int(1e18), pin_floor=pin)
    segs_left = sorted(
        int(os.path.basename(p)[4:10]) for p in
        glob.glob(os.path.join(d, "seg-*.log"))
    )
    assert segs_left and min(segs_left) == pin
    assert dropped == len(msgs) - sum(
        1 for m in drain(st, "#")
    )
    # the cursor resumes losslessly: everything past it still reads
    rest = []
    while True:
        it, batch = st.next(it, 16)
        if not batch:
            break
        rest.extend(batch)
    assert [m.mid for m in rest] == [m.mid for m in msgs[cut:]]
    # release the pin: unpinned GC reclaims everything under cutoff
    assert st.gc(int(1e18)) > 0 or len(segs_left) == 1
    st.close()
    assert cursor_ts  # silence unused in skip configurations


def test_sessions_gc_honors_cursor_pins(tmp_path):
    """DurableSessions.gc derives per-shard floors from boot-state
    cursors: a detached session mid-replay keeps its remaining backlog
    through an aggressive retention pass."""
    base = str(tmp_path / "ds")
    t0 = 1_700_000_000.0
    ds = DurableSessions(base, layout="hash", fsync="always")
    ds.save("c1", {"g/#": {"qos": 1}}, expiry=1e9, now=t0)
    ds.add_filter("g/#")
    msgs = [msg("g/s/t", t0 + 1 + i, payload=bytes(300))
            for i in range(30)]
    ds.persist(msgs)
    ds.gate.sync_now()
    ds.close()

    # restart 1: replay a partial chunk, checkpoint the cursor
    # mid-backlog (replay_chunk advances the state's cursors in place)
    ds1 = DurableSessions(base, layout="hash", fsync="always")
    state = ds1.load("c1")
    got, _done = ds1.replay_chunk(state, 10)
    assert len(got) == 10
    ds1.save_state(state)
    ds1.close()

    # restart 2: an aggressive retention pass runs BEFORE the session
    # resumes — the cursor's generation pin must keep its backlog
    ds2 = DurableSessions(base, layout="hash", fsync="always")
    try:
        dropped = ds2.gc(int((t0 + 100) * 1e6))  # cutoff: everything
        state3 = ds2.load("c1")
        rest = [m.mid for _f, m in ds2.replay(state3)]
        expected = [m.mid for m in msgs[len(got):]]
        # the pinned generations kept every un-replayed message
        assert set(expected) <= set(rest)
        assert dropped >= 0
    finally:
        ds2.close()


def test_gc_reclaim_chaos(tmp_path):
    """The ds.gc.reclaim seam: error propagates (retention pass fails
    loudly, data intact), drop reclaims nothing, and a cleared seam
    reclaims normally."""
    d = str(tmp_path / "db")
    st = LocalStorage(d, n_streams=1, seg_bytes=2048)
    st.store_batch(
        [msg("g/s", 100.0 + i, payload=bytes(300)) for i in range(30)],
        sync=True,
    )
    n_before = len(glob.glob(os.path.join(d, "seg-*.log")))
    assert n_before > 2
    fp.configure("ds.gc.reclaim", "error")
    with pytest.raises(ConnectionError):
        st.gc(int(1e18))
    assert len(glob.glob(os.path.join(d, "seg-*.log"))) == n_before
    fp.configure("ds.gc.reclaim", "drop")
    assert st.gc(int(1e18)) == 0
    assert len(glob.glob(os.path.join(d, "seg-*.log"))) == n_before
    fp.clear()
    assert st.gc(int(1e18)) > 0
    assert len(glob.glob(os.path.join(d, "seg-*.log"))) < n_before
    st.close()
