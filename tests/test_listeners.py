"""WS and TLS listeners: full MQTT pub/sub roundtrips over ws:// and
mqtts:// (emqx_listeners.erl:430-447 transport parity)."""

import asyncio
import base64
import datetime
import os

import pytest

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.broker import ws as W
from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig, ListenerConfig
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


class WsTestClient(TestClient):
    """TestClient over a client-side websocket (masked frames)."""

    async def connect(self, **kw):
        r, w = await asyncio.open_connection(self.host, self.port)
        key = base64.b64encode(os.urandom(16)).decode()
        w.write(
            (
                f"GET /mqtt HTTP/1.1\r\nHost: {self.host}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n"
                "Sec-WebSocket-Protocol: mqtt\r\n\r\n"
            ).encode()
        )
        await w.drain()
        status = await r.readuntil(b"\r\n\r\n")
        assert b"101" in status.split(b"\r\n")[0], status
        assert b"Sec-WebSocket-Protocol: mqtt" in status

        class _ClientStream(W.WsServerStream):
            def write(self, data: bytes) -> None:  # clients mask
                if data and not self._w.is_closing():
                    self._w.write(
                        W.frame(W.OP_BINARY, data, mask=os.urandom(4))
                    )

        stream = _ClientStream(r, w)
        self.reader = stream
        self.writer = stream
        self._pump = asyncio.get_running_loop().create_task(
            self._read_loop()
        )
        await self.send(
            C.Connect(
                client_id=self.client_id,
                proto_ver=self.version,
                clean_start=kw.get("clean_start", True),
                keepalive=kw.get("keepalive", 60),
                properties=kw.get("properties") or {},
            )
        )
        return await self.expect(C.CONNACK)


def test_ws_pubsub_roundtrip():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [
            ListenerConfig(port=0),
            ListenerConfig(name="ws_default", type="ws", port=0),
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        tcp_port, ws_port = (lst.port for lst in srv.listeners)

        sub = WsTestClient(ws_port, "ws-sub")
        ack = await sub.connect()
        assert ack.reason_code == 0
        await sub.subscribe("web/#", qos=1)

        # cross-transport: publish over plain TCP, deliver over WS
        pub = TestClient(tcp_port, "tcp-pub")
        await pub.connect()
        await pub.publish("web/news", b"hello ws", qos=1)
        pkt = await sub.recv_publish()
        assert pkt.topic == "web/news" and pkt.payload == b"hello ws"

        # and WS -> TCP
        await pub.subscribe("from/ws")
        await sub.publish("from/ws", b"reverse", qos=1)
        pkt2 = await pub.recv_publish()
        assert pkt2.payload == b"reverse"

        await pub.disconnect()
        await sub.disconnect()
        await srv.stop()

    run(t())


def test_ws_rejects_plain_http():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(name="ws", type="ws", port=0)]
        srv = BrokerServer(cfg)
        await srv.start()
        r, w = await asyncio.open_connection(
            "127.0.0.1", srv.listeners[0].port
        )
        w.write(b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")
        await w.drain()
        resp = await r.read(64)
        assert b"400" in resp
        w.close()
        await srv.stop()

    run(t())


def _make_cert(tmp_path):
    """Self-signed localhost certificate via `cryptography`."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "localhost")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName("localhost")]),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    certfile = tmp_path / "cert.pem"
    keyfile = tmp_path / "key.pem"
    certfile.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    keyfile.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return str(certfile), str(keyfile)


def _make_pki(tmp_path):
    """CA + server cert + two client certs + a CRL revoking one
    (`cryptography`-built, no openssl CLI)."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    now = datetime.datetime.now(datetime.timezone.utc)

    def _name(cn):
        return x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])

    def _key():
        return rsa.generate_private_key(
            public_exponent=65537, key_size=2048
        )

    def _write(path, pem):
        (tmp_path / path).write_bytes(pem)
        return str(tmp_path / path)

    def _key_pem(key):
        return key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )

    ca_key = _key()
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(_name("test-ca")).issuer_name(_name("test-ca"))
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.BasicConstraints(ca=True, path_length=None),
            critical=True,
        )
        .sign(ca_key, hashes.SHA256())
    )

    def _issue(cn, san=None):
        key = _key()
        b = (
            x509.CertificateBuilder()
            .subject_name(_name(cn)).issuer_name(_name("test-ca"))
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
        )
        if san:
            b = b.add_extension(
                x509.SubjectAlternativeName([x509.DNSName(san)]),
                critical=False,
            )
        return key, b.sign(ca_key, hashes.SHA256())

    srv_key, srv_cert = _issue("localhost", san="localhost")
    good_key, good_cert = _issue("client-good")
    bad_key, bad_cert = _issue("client-revoked")

    crl = (
        x509.CertificateRevocationListBuilder()
        .issuer_name(_name("test-ca"))
        .last_update(now - datetime.timedelta(minutes=5))
        .next_update(now + datetime.timedelta(days=1))
        .add_revoked_certificate(
            x509.RevokedCertificateBuilder()
            .serial_number(bad_cert.serial_number)
            .revocation_date(now - datetime.timedelta(minutes=1))
            .build()
        )
        .sign(ca_key, hashes.SHA256())
    )
    enc = serialization.Encoding.PEM
    return {
        "ca": _write("ca.pem", ca_cert.public_bytes(enc)),
        "ca_key": _write("ca.key", _key_pem(ca_key)),
        "srv_cert": _write("srv.pem", srv_cert.public_bytes(enc)),
        "srv_key": _write("srv.key", _key_pem(srv_key)),
        "good_cert": _write("good.pem", good_cert.public_bytes(enc)),
        "good_key": _write("good.key", _key_pem(good_key)),
        "bad_cert": _write("bad.pem", bad_cert.public_bytes(enc)),
        "bad_key": _write("bad.key", _key_pem(bad_key)),
        "crl": _write("ca.crl", crl.public_bytes(enc)),
    }


async def _mtls_probe(port, ca, certfile, keyfile):
    """True if the broker ACCEPTS this client cert: under TLS 1.3 the
    server's verify verdict arrives AFTER the client handshake
    completes, so acceptance is probed by an MQTT CONNECT->CONNACK
    round trip (a revoked cert gets an alert/EOF instead)."""
    import ssl

    ctx = ssl.create_default_context(cafile=ca)
    ctx.check_hostname = False
    ctx.load_cert_chain(certfile, keyfile)
    try:
        r, w = await asyncio.open_connection(
            "127.0.0.1", port, ssl=ctx, server_hostname="localhost"
        )
    except (ssl.SSLError, ConnectionError):
        return False
    try:
        w.write(C.serialize(C.Connect(client_id="crl-probe")))
        await w.drain()
        data = await asyncio.wait_for(r.read(4), 5.0)
        return len(data) > 0 and data[0] >> 4 == 2  # CONNACK
    except (ssl.SSLError, ConnectionError, asyncio.TimeoutError):
        return False
    finally:
        w.close()


def test_tls_crl_rejects_revoked_client(tmp_path):
    """mTLS listener with a CRL (emqx_crl_cache role): a revoked
    client cert is rejected; an unrevoked one connects."""
    pki = _make_pki(tmp_path)

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [
            ListenerConfig(
                name="mtls", type="ssl", port=0,
                certfile=pki["srv_cert"], keyfile=pki["srv_key"],
                cacertfile=pki["ca"], verify=True,
                crlfile=pki["crl"],
            )
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        port = srv.listeners[0].port

        assert await _mtls_probe(port, pki["ca"], pki["good_cert"],
                                 pki["good_key"])
        assert not await _mtls_probe(port, pki["ca"], pki["bad_cert"],
                                     pki["bad_key"])
        await srv.stop()

    run(t())


def test_tls_crl_requires_verify(tmp_path):
    """crlfile without verify=true is a misconfiguration (no client
    cert requested -> nothing to revoke-check) and must fail loudly,
    not silently skip revocation."""
    import pytest

    pki = _make_pki(tmp_path)

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [
            ListenerConfig(
                name="mtls", type="ssl", port=0,
                certfile=pki["srv_cert"], keyfile=pki["srv_key"],
                cacertfile=pki["ca"], crlfile=pki["crl"],
            )
        ]
        srv = BrokerServer(cfg)
        with pytest.raises(ValueError, match="verify"):
            await srv.start()
        await srv.stop()

    run(t())


def test_tls_crl_hot_reload(tmp_path):
    """Revoking a cert by rewriting the CRL file takes effect on new
    handshakes after maybe_reload_crl, without a listener restart."""
    import os

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization

    pki = _make_pki(tmp_path)

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [
            ListenerConfig(
                name="mtls", type="ssl", port=0,
                certfile=pki["srv_cert"], keyfile=pki["srv_key"],
                cacertfile=pki["ca"], verify=True,
                crlfile=pki["crl"],
            )
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        lst = srv.listeners[0]
        port = lst.port

        # 'good' connects fine against the original CRL
        assert await _mtls_probe(port, pki["ca"], pki["good_cert"],
                                 pki["good_key"])

        # roll the CRL forward: now 'good' is revoked too
        from cryptography.x509.oid import NameOID

        now = datetime.datetime.now(datetime.timezone.utc)
        ca_name = x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, "test-ca")]
        )
        good = x509.load_pem_x509_certificate(
            open(pki["good_cert"], "rb").read()
        )
        bad = x509.load_pem_x509_certificate(
            open(pki["bad_cert"], "rb").read()
        )
        ca_key = serialization.load_pem_private_key(
            open(pki["ca_key"], "rb").read(), password=None
        )
        builder = (
            x509.CertificateRevocationListBuilder()
            .issuer_name(ca_name)
            .last_update(now)
            .next_update(now + datetime.timedelta(days=1))
        )
        for cert in (good, bad):
            builder = builder.add_revoked_certificate(
                x509.RevokedCertificateBuilder()
                .serial_number(cert.serial_number)
                .revocation_date(now)
                .build()
            )
        crl2 = builder.sign(ca_key, hashes.SHA256())
        with open(pki["crl"], "wb") as f:
            f.write(crl2.public_bytes(serialization.Encoding.PEM))
        os.utime(pki["crl"], (0, 10**10))  # force a new mtime
        assert lst.maybe_reload_crl()

        assert not await _mtls_probe(port, pki["ca"],
                                     pki["good_cert"],
                                     pki["good_key"])
        await srv.stop()

    run(t())


def test_tls_pubsub_roundtrip(tmp_path):
    import ssl

    certfile, keyfile = _make_cert(tmp_path)

    class TlsTestClient(TestClient):
        async def connect(self, **kw):
            ctx = ssl.create_default_context(cafile=certfile)
            ctx.check_hostname = False
            self.reader, self.writer = await asyncio.open_connection(
                self.host, self.port, ssl=ctx, server_hostname="localhost"
            )
            self._pump = asyncio.get_running_loop().create_task(
                self._read_loop()
            )
            await self.send(
                C.Connect(
                    client_id=self.client_id,
                    proto_ver=self.version,
                    clean_start=True,
                    keepalive=60,
                )
            )
            return await self.expect(C.CONNACK)

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [
            ListenerConfig(
                name="ssl",
                type="ssl",
                port=0,
                certfile=certfile,
                keyfile=keyfile,
            )
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        port = srv.listeners[0].port

        sub = TlsTestClient(port, "tls-sub")
        ack = await sub.connect()
        assert ack.reason_code == 0
        await sub.subscribe("sec/#", qos=1)
        pub = TlsTestClient(port, "tls-pub")
        await pub.connect()
        await pub.publish("sec/data", b"encrypted hi", qos=1)
        pkt = await sub.recv_publish()
        assert pkt.payload == b"encrypted hi"
        await pub.disconnect()
        await sub.disconnect()
        await srv.stop()

    run(t())
