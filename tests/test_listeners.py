"""WS and TLS listeners: full MQTT pub/sub roundtrips over ws:// and
mqtts:// (emqx_listeners.erl:430-447 transport parity)."""

import asyncio
import base64
import datetime
import os

import pytest

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.broker import ws as W
from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig, ListenerConfig
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


class WsTestClient(TestClient):
    """TestClient over a client-side websocket (masked frames)."""

    async def connect(self, **kw):
        r, w = await asyncio.open_connection(self.host, self.port)
        key = base64.b64encode(os.urandom(16)).decode()
        w.write(
            (
                f"GET /mqtt HTTP/1.1\r\nHost: {self.host}\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n"
                "Sec-WebSocket-Protocol: mqtt\r\n\r\n"
            ).encode()
        )
        await w.drain()
        status = await r.readuntil(b"\r\n\r\n")
        assert b"101" in status.split(b"\r\n")[0], status
        assert b"Sec-WebSocket-Protocol: mqtt" in status

        class _ClientStream(W.WsServerStream):
            def write(self, data: bytes) -> None:  # clients mask
                if data and not self._w.is_closing():
                    self._w.write(
                        W.frame(W.OP_BINARY, data, mask=os.urandom(4))
                    )

        stream = _ClientStream(r, w)
        self.reader = stream
        self.writer = stream
        self._pump = asyncio.get_running_loop().create_task(
            self._read_loop()
        )
        await self.send(
            C.Connect(
                client_id=self.client_id,
                proto_ver=self.version,
                clean_start=kw.get("clean_start", True),
                keepalive=kw.get("keepalive", 60),
                properties=kw.get("properties") or {},
            )
        )
        return await self.expect(C.CONNACK)


def test_ws_pubsub_roundtrip():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [
            ListenerConfig(port=0),
            ListenerConfig(name="ws_default", type="ws", port=0),
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        tcp_port, ws_port = (lst.port for lst in srv.listeners)

        sub = WsTestClient(ws_port, "ws-sub")
        ack = await sub.connect()
        assert ack.reason_code == 0
        await sub.subscribe("web/#", qos=1)

        # cross-transport: publish over plain TCP, deliver over WS
        pub = TestClient(tcp_port, "tcp-pub")
        await pub.connect()
        await pub.publish("web/news", b"hello ws", qos=1)
        pkt = await sub.recv_publish()
        assert pkt.topic == "web/news" and pkt.payload == b"hello ws"

        # and WS -> TCP
        await pub.subscribe("from/ws")
        await sub.publish("from/ws", b"reverse", qos=1)
        pkt2 = await pub.recv_publish()
        assert pkt2.payload == b"reverse"

        await pub.disconnect()
        await sub.disconnect()
        await srv.stop()

    run(t())


def test_ws_rejects_plain_http():
    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(name="ws", type="ws", port=0)]
        srv = BrokerServer(cfg)
        await srv.start()
        r, w = await asyncio.open_connection(
            "127.0.0.1", srv.listeners[0].port
        )
        w.write(b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")
        await w.drain()
        resp = await r.read(64)
        assert b"400" in resp
        w.close()
        await srv.stop()

    run(t())


def _make_cert(tmp_path):
    """Self-signed localhost certificate via `cryptography`."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "localhost")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName("localhost")]),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    certfile = tmp_path / "cert.pem"
    keyfile = tmp_path / "key.pem"
    certfile.write_bytes(cert.public_bytes(serialization.Encoding.PEM))
    keyfile.write_bytes(
        key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption(),
        )
    )
    return str(certfile), str(keyfile)


def test_tls_pubsub_roundtrip(tmp_path):
    import ssl

    certfile, keyfile = _make_cert(tmp_path)

    class TlsTestClient(TestClient):
        async def connect(self, **kw):
            ctx = ssl.create_default_context(cafile=certfile)
            ctx.check_hostname = False
            self.reader, self.writer = await asyncio.open_connection(
                self.host, self.port, ssl=ctx, server_hostname="localhost"
            )
            self._pump = asyncio.get_running_loop().create_task(
                self._read_loop()
            )
            await self.send(
                C.Connect(
                    client_id=self.client_id,
                    proto_ver=self.version,
                    clean_start=True,
                    keepalive=60,
                )
            )
            return await self.expect(C.CONNACK)

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [
            ListenerConfig(
                name="ssl",
                type="ssl",
                port=0,
                certfile=certfile,
                keyfile=keyfile,
            )
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        port = srv.listeners[0].port

        sub = TlsTestClient(port, "tls-sub")
        ack = await sub.connect()
        assert ack.reason_code == 0
        await sub.subscribe("sec/#", qos=1)
        pub = TlsTestClient(port, "tls-pub")
        await pub.connect()
        await pub.publish("sec/data", b"encrypted hi", qos=1)
        pkt = await sub.recv_publish()
        assert pkt.payload == b"encrypted hi"
        await pub.disconnect()
        await sub.disconnect()
        await srv.stop()

    run(t())
