"""Cluster peer transport over the in-repo QUIC stack.

The PSK cluster profile (integrity-authenticated plaintext, no
`cryptography` dependency) carries the SAME length-prefixed frames as
the TCP links: control + forward streams per peer, loss recovered by
quic/recovery.py's selective-ACK/PTO machinery at DATAGRAM
granularity.  Chaos here injects loss where it actually happens — the
``cluster.quic.send``/``cluster.quic.recv`` datagram seams — and
asserts the tentpole gates: zero QoS>=1 forwarded loss under seeded
1% loss with bounded p99, partition-then-heal replay, and
``transport_mode=auto``'s graceful TCP degradation + QUIC
re-promotion when the fault clears."""

import asyncio
import time

import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.cluster import ClusterNode
from emqx_tpu.cluster.transport import NodeTransport
from emqx_tpu.config import BrokerConfig
from mqtt_client import TestClient

FAST = dict(
    heartbeat_interval=0.05, down_after=5.0, flush_interval=0.002,
    consensus="lww", fwd_ack_timeout=0.2, fwd_backoff_max=0.8,
    fwd_probe_interval=0.2,
)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


async def start_node(name, seeds=(), mode="quic", **kw):
    cfg = BrokerConfig()
    cfg.listeners[0].port = 0
    cfg.node_name = name
    srv = BrokerServer(cfg)
    await srv.start()
    node = ClusterNode(
        name, srv.broker, transport_mode=mode, **{**FAST, **kw}
    )
    node.transport.quic_reprobe_interval = 0.4
    node.transport.quic_connect_timeout = 0.6
    await node.start(seeds=list(seeds))
    return srv, node


async def stop_node(srv, node):
    await node.stop()
    await srv.stop()


async def settle(cond, timeout=8.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.02)
    return False


# ------------------------------------------------ transport plumbing


def test_quic_link_cast_call_castbin_roundtrip():
    """The QuicPeerLink/QuicPeerEndpoint pair speaks the full RPC
    surface: JSON casts, calls with replies, and binary frames on the
    dedicated forward stream."""

    async def t():
        t1 = NodeTransport("n1", transport_mode="quic",
                           quic_psk=b"k" * 32)
        t2 = NodeTransport("n2", transport_mode="quic",
                           quic_psk=b"k" * 32)
        got = {"casts": [], "bins": []}

        async def on_echo(peer, obj):
            return {"peer": peer, "double": obj["n"] * 2}

        async def on_note(peer, obj):
            got["casts"].append((peer, obj["v"]))

        async def on_blob(peer, obj):
            got["bins"].append((peer, bytes(obj["_bin"])))

        t2.on("echo", on_echo)
        t2.on("note", on_note)
        t2.on("blob", on_blob)
        await t1.start()
        await t2.start()
        try:
            t1.add_peer("n2", "127.0.0.1", t2.port)
            assert await t1.cast("n2", {"type": "note", "v": 7})
            reply = await t1.call(
                "n2", {"type": "echo", "n": 21}, timeout=5.0
            )
            assert reply == {"peer": "n1", "double": 42}
            payload = bytes(range(256)) * 40  # several datagrams
            assert await t1.cast_bin("n2", "blob", payload)
            assert await settle(
                lambda: got["casts"] == [("n1", 7)]
                and got["bins"] == [("n1", payload)]
            )
            assert t1.stats["quic_sends"] >= 3
            assert t1.stats["tcp_sends"] == 0
        finally:
            await t1.stop()
            await t2.stop()

    run(t())


def test_quic_mode_cluster_end_to_end():
    """Full 2-node cluster over QUIC: route replication, window
    forwarding, acks — no TCP sends on the hot path."""

    async def t():
        sa, a = await start_node("a")
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            for i in range(60):
                await pub.publish(f"t/{i}", b"x" * 200, qos=1)
            got = set()
            for _ in range(60):
                got.add((await sub.recv_publish(timeout=8)).topic)
            assert got == {f"t/{i}" for i in range(60)}
            assert await settle(
                lambda: (st := a._fwd_out.get("b")) is not None
                and not st.inflight
            )
            assert a.transport.stats["quic_sends"] > 0
            assert a.forward_stats()["mode"] == "quic"
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_wrong_psk_peers_never_connect():
    """A peer with the wrong cluster secret fails the integrity tag
    on every packet: the handshake times out instead of admitting
    unauthenticated frames."""

    async def t():
        t1 = NodeTransport("n1", transport_mode="quic",
                           quic_psk=b"right" * 8)
        t2 = NodeTransport("n2", transport_mode="quic",
                           quic_psk=b"wrong" * 8)
        await t1.start()
        await t2.start()
        try:
            t1.quic_connect_timeout = 0.4
            t1.add_peer("n2", "127.0.0.1", t2.port)
            assert not await t1.cast("n2", {"type": "x"})
        finally:
            await t1.stop()
            await t2.stop()

    run(t())


# ------------------------------------------------------- chaos gates


def _lat_stats(lats):
    lats = sorted(lats)
    return (
        lats[len(lats) // 2],
        lats[min(len(lats) - 1, int(len(lats) * 0.99))],
    )


async def _forward_burst(sa, sb, n, tag):
    """Publish ``n`` QoS1 messages on node A, collect them on node
    B's subscriber, returning per-message e2e latencies (publish ->
    delivery) in seconds.  Streaming shape: the publisher does NOT
    stop-and-wait, so loss recovery runs under continuous traffic the
    way the real forward path does."""
    sub = TestClient(sb.listeners[0].port, f"s-{tag}")
    await sub.connect()
    await sub.subscribe(f"{tag}/#", qos=1)
    await asyncio.sleep(0.3)  # route propagation
    pub = TestClient(sa.listeners[0].port, f"p-{tag}")
    await pub.connect()
    sent_at = {}

    async def consume(got, lats):
        while len(got) < n:
            pkt = await sub.recv_publish(timeout=15)
            now = time.monotonic()
            if pkt.topic not in got:
                got.add(pkt.topic)
                lats.append(now - sent_at[pkt.topic])

    got, lats = set(), []
    eater = asyncio.get_running_loop().create_task(
        consume(got, lats)
    )
    for i in range(n):
        topic = f"{tag}/{i}"
        sent_at[topic] = time.monotonic()
        await pub.publish(topic, b"x" * 300, qos=1)
        if i % 16 == 15:
            await asyncio.sleep(0.005)
    await asyncio.wait_for(eater, timeout=30)
    await pub.disconnect()
    await sub.disconnect()
    assert got == {f"{tag}/{i}" for i in range(n)}, (
        f"lost {n - len(got)} QoS1 forwarded messages"
    )
    return lats


def test_one_percent_datagram_loss_zero_qos1_loss_bounded_p99():
    """THE loss gate: under seeded 1% datagram loss on BOTH quic
    seams, every QoS1 forwarded message arrives (duplicates only
    within at-least-once bounds — the dedup window keeps dispatch
    exactly-once) and the forwarded p99 stays <= 3x the lossless
    run's (floored at one PTO: sub-PTO lossless tails would make 3x
    an impossible bar for ANY loss-recovery design)."""

    async def t():
        sa, a = await start_node("a")
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            # lossless baseline
            base = await _forward_burst(sa, sb, 300, "clean")
            p50_0, p99_0 = _lat_stats(base)

            # seeded 1% loss, both directions, both seams
            fp.configure("cluster.quic.send", "drop", prob=0.01,
                         seed=20260804)
            fp.configure("cluster.quic.recv", "drop", prob=0.01,
                         seed=48062602)
            lossy = await _forward_burst(sa, sb, 300, "lossy")
            p50_1, p99_1 = _lat_stats(lossy)
            fired = sum(p["fires"] for p in fp.list_points())
            fp.clear()
            assert fired > 0, "chaos never fired"

            # receiver dispatched each window once (dups stayed on
            # the wire side of the dedup window)
            assert b.broker.metrics.val("messages.forward.received") \
                <= 600

            floor = 0.12  # one PTO + a scheduling slice
            bound = 3 * max(p99_0, floor)
            assert p99_1 <= bound, (
                f"p99 under 1% loss {p99_1 * 1000:.1f}ms exceeds "
                f"3x lossless ({p99_0 * 1000:.1f}ms, "
                f"bound {bound * 1000:.1f}ms); p50 "
                f"{p50_1 * 1000:.1f}/{p50_0 * 1000:.1f}ms"
            )
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_partition_then_heal_replays_over_quic():
    """A full bidirectional QUIC blackhole mid-burst: frames buffer
    in the replay window, and the heal replays them — zero QoS1
    loss, dedup'd dispatch."""

    async def t():
        sa, a = await start_node("a")
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            for i in range(10):
                await pub.publish(f"t/{i}", b"x", qos=1)
            got = set()
            for _ in range(10):
                got.add((await sub.recv_publish(timeout=8)).topic)

            # partition: every datagram both ways vanishes
            fp.configure("cluster.quic.send", "drop")
            for i in range(10, 30):
                await pub.publish(f"t/{i}", b"x", qos=1)
            assert await settle(
                lambda: (st := a._fwd_out.get("b")) is not None
                and st.inflight
            )
            await asyncio.sleep(0.4)  # frames sit out the partition
            assert len(got) == 10  # nothing crossed

            fp.clear("cluster.quic.send")  # heal
            while len(got) < 30:
                got.add((await sub.recv_publish(timeout=10)).topic)
            assert got == {f"t/{i}" for i in range(30)}
            assert await settle(
                lambda: not a._fwd_out["b"].inflight
            )
            assert b.broker.metrics.val("messages.forward.received") \
                == 30
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_auto_mode_degrades_to_tcp_and_repromotes():
    """THE degradation gate: with the QUIC handshake failpointed
    away, ``transport_mode=auto`` falls back to the TCP PeerLink with
    no forwarded loss; when the fault clears, the background probe
    re-promotes the peer to QUIC."""

    async def t():
        fp.configure("cluster.quic.send", "drop")  # QUIC blackholed
        sa, a = await start_node("a", mode="auto")
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)], mode="auto"
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            for i in range(20):
                await pub.publish(f"t/{i}", b"x", qos=1)
            got = set()
            for _ in range(20):
                got.add((await sub.recv_publish(timeout=8)).topic)
            assert got == {f"t/{i}" for i in range(20)}
            assert a.transport.stats["quic_demotions"] >= 1
            assert a.transport.stats["tcp_sends"] > 0
            quic_before = a.transport.stats["quic_sends"]

            # the fault clears: the background probe re-promotes
            fp.clear("cluster.quic.send")
            assert await settle(
                lambda: a.transport.stats["quic_promotions"] >= 1,
                timeout=10.0,
            )
            for i in range(20, 40):
                await pub.publish(f"t/{i}", b"x", qos=1)
            for _ in range(20):
                got.add((await sub.recv_publish(timeout=8)).topic)
            assert got == {f"t/{i}" for i in range(40)}
            assert await settle(
                lambda: a.transport.stats["quic_sends"] > quic_before
            )
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_established_link_blackhole_demotes_to_tcp(monkeypatch):
    """A peer that blackholes UDP AFTER the handshake must still
    degrade: sends into a UDP void 'succeed', so the deafness
    watchdog (data in flight, nothing heard) tears the link down,
    auto demotes to TCP, and the replay buffer delivers everything —
    no silent forever-spray at a dead address."""
    from emqx_tpu.cluster import quic_transport as qt

    monkeypatch.setattr(qt, "_DEAF_AFTER", 0.6)

    async def t():
        sa, a = await start_node("a", mode="auto")
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)], mode="auto"
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            await pub.publish("t/0", b"x", qos=1)
            assert (await sub.recv_publish(timeout=8)).topic == "t/0"
            assert a.transport.stats["quic_sends"] > 0  # established

            # NOW the network starts eating every QUIC datagram
            fp.configure("cluster.quic.send", "drop")
            got = set()
            for i in range(1, 15):
                await pub.publish(f"t/{i}", b"x", qos=1)
            # deafness watchdog fires, auto demotes, TCP replays
            while len(got) < 14:
                got.add((await sub.recv_publish(timeout=15)).topic)
            assert got == {f"t/{i}" for i in range(1, 15)}
            assert a.transport.stats["quic_demotions"] >= 1
            assert a.transport.stats["tcp_sends"] > 0
        finally:
            fp.clear()
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_quic_recv_error_resets_connection_and_recovers():
    """`cluster.quic.recv` error resets the inbound connection like a
    poisoned link; the dialer re-establishes and traffic resumes with
    zero QoS1 loss."""

    async def t():
        sa, a = await start_node("a")
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            fp.configure("cluster.quic.recv", "error", times=2)
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            for i in range(20):
                await pub.publish(f"t/{i}", b"x", qos=1)
            got = set()
            for _ in range(20):
                got.add((await sub.recv_publish(timeout=10)).topic)
            assert got == {f"t/{i}" for i in range(20)}
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())
