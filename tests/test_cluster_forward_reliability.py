"""Loss-proof cluster window forwarding: the at-least-once reliability
layer (sequenced frames, ack/replay, dedup, shed policy, per-peer
breaker) plus the transport-hardening satellites.

The chaos tests drive the REAL recovery paths through the failpoint
seams and the `transport.blocked` partition hook: a killed peer's
unacked windows replay after its restart with zero QoS>=1 loss, a
lost ack produces a dedup'd duplicate (never a double-dispatch), and
repeated failures trip a per-peer breaker that a background probe
re-closes."""

import asyncio
import json

import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.cluster import ClusterNode
from emqx_tpu.cluster.transport import (
    drain_frames, parse_frame, read_frame, _pack_bin, _pack_json,
)
from emqx_tpu.config import BrokerConfig
from emqx_tpu.message import Message
from mqtt_client import TestClient

FAST = dict(
    heartbeat_interval=0.05, down_after=5.0, flush_interval=0.002,
    consensus="lww", fwd_ack_timeout=0.15, fwd_backoff_max=0.6,
    fwd_probe_interval=0.15,
)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


async def start_node(name, seeds=(), tracing=False, port=0, **kw):
    cfg = BrokerConfig()
    cfg.listeners[0].port = 0
    cfg.node_name = name
    if tracing:
        cfg.tracing.enable = True
        cfg.tracing.sample_rate = 1.0
        cfg.tracing.seed = 5
    srv = BrokerServer(cfg)
    await srv.start()
    node = ClusterNode(name, srv.broker, port=port, **{**FAST, **kw})
    await node.start(seeds=list(seeds))
    return srv, node


async def stop_node(srv, node):
    await node.stop()
    await srv.stop()


async def settle(cond, timeout=6.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.02)
    return False


# --------------------------------------------- satellite: read_frame


def _feed(body: bytes):
    reader = asyncio.StreamReader()
    reader.feed_data(len(body).to_bytes(4, "big") + body)
    reader.feed_eof()
    return reader


def test_read_frame_zero_length_body_is_connection_error():
    async def t():
        with pytest.raises(ConnectionError):
            await read_frame(_feed(b""))

    run(t())


def test_read_frame_truncated_bin_header_is_connection_error():
    async def t():
        # format 1, declared type length 10, only 3 type bytes present
        with pytest.raises(ConnectionError):
            await read_frame(_feed(bytes([1, 10]) + b"abc"))

    run(t())


def test_read_frame_bad_type_utf8_is_connection_error():
    async def t():
        with pytest.raises(ConnectionError):
            await read_frame(_feed(bytes([1, 2, 0xFF, 0xFE])))

    run(t())


def test_read_frame_bad_json_is_connection_error():
    async def t():
        with pytest.raises(ConnectionError):
            await read_frame(_feed(bytes([0]) + b"{not json"))
        with pytest.raises(ConnectionError):
            await read_frame(_feed(bytes([0]) + b"[1,2]"))  # non-object

    run(t())


def test_read_frame_unknown_format_is_connection_error():
    async def t():
        with pytest.raises(ConnectionError):
            await read_frame(_feed(bytes([9]) + b"x"))

    run(t())


def test_parse_frame_good_frames_roundtrip():
    obj = parse_frame(_pack_json({"type": "hi", "n": 1})[4:])
    assert obj == {"type": "hi", "n": 1}
    obj = parse_frame(_pack_bin("fwd", b"\x00\x01")[4:])
    assert obj["type"] == "fwd" and obj["_bin"] == b"\x00\x01"


def test_drain_frames_partial_then_complete_and_malformed():
    buf = bytearray()
    frame = _pack_json({"type": "a"})
    buf += frame[:3]
    assert drain_frames(buf) == []
    buf += frame[3:] + _pack_bin("b", b"xy")
    out = drain_frames(buf)
    assert [o["type"] for o in out] == ["a", "b"]
    assert not buf
    # malformed body inside a complete frame raises
    buf += (1).to_bytes(4, "big") + bytes([9])
    with pytest.raises(ConnectionError):
        drain_frames(buf)


def test_malformed_frame_resets_link_not_server():
    """A peer feeding garbage gets ITS connection reset; the server
    keeps serving other peers."""

    async def t():
        s1, n1 = await start_node("srv")
        s2, n2 = await start_node(
            "good", seeds=[("srv", "127.0.0.1", n1.port)]
        )
        try:
            assert await settle(lambda: "good" in n1.peers_alive()
                                or "good" in n1._peers)
            # raw garbage peer: hello, then an empty frame body
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", n1.port
            )
            writer.write(_pack_json(
                {"type": "hello", "node": "evil", "ver": [3, 0]}
            ))
            writer.write((0).to_bytes(4, "big"))  # zero-length body
            await writer.drain()
            data = await reader.read(1)  # server closes our link
            assert data == b""
            writer.close()
            # the good peer still works: a heartbeat keeps flowing
            n1._last_seen["good"] = 0.0
            assert await settle(
                lambda: n1._last_seen.get("good", 0.0) > 0.0
            )
        finally:
            await stop_node(s2, n2)
            await stop_node(s1, n1)

    run(t())


# --------------------------------------------- ack/replay reliability


def test_link_loss_replays_unacked_windows():
    """Windows buffered while the peer is unreachable retransmit
    after the link heals: zero QoS1 loss, no duplicate dispatch."""

    async def t():
        sa, a = await start_node("a")
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            a.transport.blocked.add("b")  # the network eats everything
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            for i in range(40):
                await pub.publish(f"t/{i}", b"x", qos=1)
            # frames buffered, none delivered
            assert await settle(
                lambda: (st := a._fwd_out.get("b")) is not None
                and len(st.inflight) > 0
            )
            await asyncio.sleep(0.3)  # a few failed retx cycles
            a.transport.blocked.discard("b")
            got = set()
            for _ in range(40):
                pkt = await sub.recv_publish(timeout=8)
                got.add(pkt.topic)
            assert got == {f"t/{i}" for i in range(40)}
            assert await settle(
                lambda: not a._fwd_out["b"].inflight
            )
            assert a.broker.metrics.val("messages.forward.retx") > 0
            # dedup did its job: nothing dispatched twice
            assert b.broker.metrics.val("messages.forward.received") \
                == 40
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_lost_ack_duplicates_dedup_not_redispatched():
    """Chaos on the `cluster.forward.ack` seam: the first ack is
    dropped, the origin retransmits, the receiver re-acks WITHOUT
    re-dispatching — at-least-once stays at-least-once on the wire
    and exactly-once at dispatch."""

    async def t():
        sa, a = await start_node("a")
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            fp.configure("cluster.forward.ack", "drop", times=1)
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            for i in range(10):
                await pub.publish(f"t/{i}", b"x", qos=1)
            got = set()
            for _ in range(10):
                pkt = await sub.recv_publish(timeout=8)
                got.add(pkt.topic)
            assert len(got) == 10
            # the retransmit produced a duplicate frame, dedup'd
            assert await settle(
                lambda: b.broker.metrics.val("messages.forward.dup")
                > 0
            )
            assert await settle(lambda: not a._fwd_out["b"].inflight)
            # exactly-once dispatch: the duplicate never re-entered
            assert b.broker.metrics.val(
                "messages.forward.received") == 10
            assert [p["fires"] for p in fp.list_points()
                    if p["name"] == "cluster.forward.ack"] == [1]
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_overflow_sheds_qos0_frames_first():
    """A full replay buffer sheds QoS0-only frames before anything
    carrying QoS>=1, counting ``messages.forward.dropped``."""

    async def t():
        sa, a = await start_node("a", fwd_inflight_max=3)
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            assert await settle(lambda: "b" in a._peers)
            a.transport.blocked.add("b")

            def msgs(qos, tag, n=2):
                return [
                    Message(topic=f"{tag}/{i}", payload=b"x", qos=qos)
                    for i in range(n)
                ]

            # four frames into a 3-frame buffer: q0 frames shed first
            for qos, tag in ((0, "z0"), (1, "q1a"), (0, "z1"),
                             (1, "q1b")):
                for m in msgs(qos, tag):
                    a.forward(m, {"b"})
                await a._flush_forwards()
            st = a._fwd_out["b"]
            kept = [f.max_qos for f in st.inflight.values()]
            assert len(st.inflight) == 3
            assert kept.count(1) == 2  # both QoS1 frames survived
            assert a.broker.metrics.val(
                "messages.forward.dropped") == 2  # one q0 frame shed
            # push two more QoS1 frames: the last q0 goes, then the
            # OLDEST QoS1 makes room (bounded memory wins)
            for tag in ("q1c", "q1d"):
                for m in msgs(1, tag):
                    a.forward(m, {"b"})
                await a._flush_forwards()
            st = a._fwd_out["b"]
            assert all(
                f.max_qos == 1 for f in st.inflight.values()
            )
            assert a.broker.metrics.val(
                "messages.forward.dropped") == 6
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_breaker_trips_alarm_probes_and_recloses():
    """Repeated forward failures walk closed -> suspect -> open: an
    OPEN breaker parks frames and raises the $SYS alarm; the probe
    re-closes it when the peer heals and the backlog replays."""

    async def t():
        sa, a = await start_node(
            "a", fwd_suspect_threshold=1, fwd_breaker_threshold=2,
        )
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            a.transport.blocked.add("b")
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            for i in range(5):
                await pub.publish(f"t/{i}", b"x", qos=1)
            assert await settle(
                lambda: (st := a._fwd_out.get("b")) is not None
                and st.breaker_open
            )
            names = [al.name for al in a.broker.alarms.active()]
            assert "cluster_forward_breaker_b" in names
            assert a.broker.metrics.val(
                "cluster.forward.breaker.open") >= 1
            assert a.forward_stats()["peers"]["b"]["breaker"] == \
                "open"
            # heal: the background probe's frame gets acked and the
            # breaker re-closes; every window replays
            a.transport.blocked.discard("b")
            got = set()
            for _ in range(5):
                pkt = await sub.recv_publish(timeout=8)
                got.add(pkt.topic)
            assert got == {f"t/{i}" for i in range(5)}
            assert await settle(
                lambda: not a._fwd_out["b"].breaker_open
            )
            assert "cluster_forward_breaker_b" not in [
                al.name for al in a.broker.alarms.active()
            ]
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_kill_peer_mid_window_restart_zero_qos1_loss():
    """THE chaos gate: windows forwarded while the peer is dead
    replay to its restarted incarnation — zero QoS1 loss end to end,
    duplicates only within at-least-once bounds."""

    async def t():
        sa, a = await start_node("a")
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            # phase 1: live traffic flows
            for i in range(10):
                await pub.publish(f"t/{i}", b"x", qos=1)
            got = set()
            for _ in range(10):
                got.add((await sub.recv_publish(timeout=8)).topic)
            assert len(got) == 10

            # phase 2: KILL b mid-stream (no clean handshake — the
            # blocked hook plays the dead network while the process
            # restarts); the window keeps publishing into the outage
            cluster_port = b.port
            a.transport.blocked.add("b")
            await b.stop()
            await sb.stop()
            for i in range(10, 40):
                await pub.publish(f"t/{i}", b"x", qos=1)
            assert await settle(
                lambda: (st := a._fwd_out.get("b")) is not None
                and sum(f.n for f in st.inflight.values()) >= 30
            )

            # phase 3: b restarts at the same cluster address; its
            # subscriber reattaches FIRST, then the network heals —
            # every unacked window replays into the new incarnation
            sb2, b2 = await start_node(
                "b", seeds=[("a", "127.0.0.1", a.port)],
                port=cluster_port,
            )
            sub2 = TestClient(sb2.listeners[0].port, "s2")
            await sub2.connect()
            await sub2.subscribe("t/#", qos=1)
            assert await settle(
                lambda: b2.routes.nodes_for("t/#") != set()
                or True
            )
            a.transport.blocked.discard("b")
            got2 = set()
            try:
                while len(got2) < 30:
                    got2.add(
                        (await sub2.recv_publish(timeout=8)).topic
                    )
            except asyncio.TimeoutError:
                pass
            assert got2 == {f"t/{i}" for i in range(10, 40)}, (
                f"lost {30 - len(got2)} QoS1 forwarded messages"
            )
            assert await settle(lambda: not a._fwd_out["b"].inflight)
            await pub.disconnect()
            await sub2.disconnect()
            await stop_node(sb2, b2)
        finally:
            await stop_node(sa, a)

    run(t())


# ------------------------------------------------------- satellites


def test_departed_peer_buffers_reaped():
    """A peer removed from membership frees its pending buffers,
    replay state, and dedup window; the shed frames are counted."""

    async def t():
        sa, a = await start_node("a")
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            assert await settle(lambda: "b" in a._peers)
            a.transport.blocked.add("b")
            for i in range(6):
                a.forward(
                    Message(topic=f"t/{i}", payload=b"x", qos=1),
                    {"b"},
                )
            await a._flush_forwards()
            # plus a buffered-but-unflushed message
            a.forward(Message(topic="t/x", payload=b"x", qos=1),
                      {"b"})
            assert a._fwd_out["b"].inflight
            assert a._pending_fwd.get("b")
            a._fwd_in["b"] = [1, 0, set()]

            a.forget_peer("b")
            assert "b" not in a._peers
            assert "b" not in a._fwd_out
            assert "b" not in a._pending_fwd
            assert "b" not in a._fwd_in
            assert a.broker.metrics.val(
                "messages.forward.dropped") == 7
            # the retx loop has nothing left to drive
            await asyncio.sleep(0.25)
            assert "b" not in a._fwd_out
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_retx_loop_reaps_unknown_peer_state():
    """Defensive reap: replay state for a peer that silently left
    membership is dropped by the retx loop, not retained forever."""

    async def t():
        sa, a = await start_node("a")
        try:
            st = a._fwd_state("ghost")
            st.seq = 1
            from emqx_tpu.cluster.node import _FwdFrame

            st.inflight[1] = _FwdFrame(1, b"", 3, 1, ())
            assert await settle(lambda: "ghost" not in a._fwd_out)
            assert a.broker.metrics.val(
                "messages.forward.dropped") == 3
        finally:
            await stop_node(sa, a)

    run(t())


def test_forward_span_closes_on_task_crash():
    """Satellite regression (PR 8 invariant: a dropped leg still
    yields a CLOSED span): a forward task killed by an injected
    panic closes its ``message.forward`` spans ok=False."""

    async def t():
        sa, a = await start_node("a", tracing=True)
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            fp.configure("cluster.transport.send", "panic",
                         match="a->b")
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            await pub.publish("t/0", b"x", qos=1)

            def crashed_span():
                return [
                    s for s in a.broker.lifecycle.store.spans()
                    if s["name"] == "message.forward"
                    and s["attrs"].get("detail")
                    == "forward task crashed"
                ]

            assert await settle(lambda: bool(crashed_span()))
            s = crashed_span()[0]
            assert s["attrs"]["ok"] is False and s["end_ns"] > 0
            # the frame itself SURVIVED the crash: clear the fault
            # and the window still delivers (at-least-once held)
            fp.clear("cluster.transport.send")
            pkt = await sub.recv_publish(timeout=8)
            assert pkt.topic == "t/0"
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_ack_latency_and_retransmit_span_events():
    """A sampled forwarded message's span carries the ack latency and
    any retransmit events — a loss-induced p99 regression names its
    hop."""

    async def t():
        sa, a = await start_node("a", tracing=True)
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            # eat the first send(s) so the frame needs at least one
            # retransmit before it acks
            a.transport.blocked.add("b")
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            await pub.publish("t/0", b"x", qos=1)
            assert await settle(
                lambda: (st := a._fwd_out.get("b")) is not None
                and st.inflight
                and next(iter(st.inflight.values())).retx >= 1
            )
            a.transport.blocked.discard("b")
            pkt = await sub.recv_publish(timeout=8)
            assert pkt.topic == "t/0"

            def fwd_spans():
                return [
                    s for s in a.broker.lifecycle.store.spans()
                    if s["name"] == "message.forward"
                    and s["attrs"].get("ok") is True
                ]

            assert await settle(lambda: bool(fwd_spans()))
            s = fwd_spans()[0]
            assert s["attrs"]["ack_ms"] >= 0
            names = [e["name"] for e in s["events"]]
            assert "forward.acked" in names
            assert s["attrs"]["retx"] >= 1
            assert "forward.retransmit" in names
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())


def test_forward_stats_surface():
    """`ClusterNode.info()` (the /api/v5/nodes + ctl status payload)
    carries the reliability introspection."""

    async def t():
        sa, a = await start_node("a")
        sb, b = await start_node(
            "b", seeds=[("a", "127.0.0.1", a.port)]
        )
        try:
            sub = TestClient(sb.listeners[0].port, "s")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            assert await settle(
                lambda: a.routes.nodes_for("t/#") == {"b"}
            )
            pub = TestClient(sa.listeners[0].port, "p")
            await pub.connect()
            await pub.publish("t/0", b"x", qos=1)
            await sub.recv_publish(timeout=8)
            assert await settle(
                lambda: a.forward_stats()["peers"]
                .get("b", {}).get("acked_frames", 0) >= 1
            )
            info = a.info()
            assert info["forward"]["mode"] == "tcp"
            st = info["forward"]["peers"]["b"]
            assert st["breaker"] == "closed"
            assert st["unacked_frames"] == 0
            json.dumps(info)  # JSON-safe for the mgmt surface
            await pub.disconnect()
            await sub.disconnect()
        finally:
            await stop_node(sb, b)
            await stop_node(sa, a)

    run(t())
