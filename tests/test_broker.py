"""Broker-level tests (no sockets): publish routing, fan-out, shared
dispatch, retained replay, detached-session queueing, hooks, metrics."""

import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.session import SubOpts
from emqx_tpu.config import BrokerConfig
from emqx_tpu.hooks import STOP_WITH
from emqx_tpu.message import Message


class FakeChannel:
    def __init__(self):
        self.sent = []
        self.closed = None

    def send_packets(self, pkts):
        self.sent.extend(pkts)

    def close(self, reason):
        self.closed = reason


def _connect(broker, clientid, clean_start=True, expiry=0.0):
    ch = FakeChannel()
    session, present = broker.cm.open_session(
        clean_start, clientid, ch, expiry_interval=expiry
    )
    return ch, session


def test_publish_fanout_to_multiple_subscribers():
    b = Broker()
    ch1, s1 = _connect(b, "c1")
    ch2, s2 = _connect(b, "c2")
    s1.subscribe("a/+", SubOpts(qos=0))
    b.subscribe("c1", "a/+", SubOpts(qos=0))
    s2.subscribe("a/b", SubOpts(qos=1))
    b.subscribe("c2", "a/b", SubOpts(qos=1))

    n = b.publish(Message(topic="a/b", payload=b"hi", qos=1))
    assert n == 2
    assert len(ch1.sent) == 1 and ch1.sent[0].qos == 0
    assert len(ch2.sent) == 1 and ch2.sent[0].qos == 1
    assert b.metrics.val("messages.delivered") == 2


def test_publish_no_subscribers_drops():
    b = Broker()
    assert b.publish(Message(topic="nobody/home")) == 0
    assert b.metrics.val("messages.dropped.no_subscribers") == 1


def test_publish_many_batches_one_device_step():
    b = Broker()
    ch, s = _connect(b, "c1")
    for flt in ("a/+", "b/#"):
        s.subscribe(flt, SubOpts(qos=0))
        b.subscribe("c1", flt, SubOpts(qos=0))
    counts = b.publish_many(
        [
            Message(topic="a/x"),
            Message(topic="b/y/z"),
            Message(topic="c"),
        ]
    )
    assert counts == [1, 1, 0]
    assert len(ch.sent) == 2


def test_message_publish_hook_mutates_and_drops():
    b = Broker()
    ch, s = _connect(b, "c1")
    s.subscribe("t", SubOpts(qos=0))
    b.subscribe("c1", "t", SubOpts(qos=0))

    def rewrite(msg):
        if msg.topic == "drop/me":
            return STOP_WITH(None)
        return Message(
            topic=msg.topic, payload=msg.payload + b"!", qos=msg.qos,
            from_client=msg.from_client,
        )

    b.hooks.add("message.publish", rewrite)
    assert b.publish(Message(topic="drop/me")) == 0
    b.publish(Message(topic="t", payload=b"x"))
    assert ch.sent[0].payload == b"x!"


def test_shared_dispatch_picks_one_and_skips_dead():
    b = Broker(shared_strategy="round_robin")
    ch1, s1 = _connect(b, "c1")
    ch2, s2 = _connect(b, "c2")
    for cid, s in (("c1", s1), ("c2", s2)):
        s.subscribe("$share/g/t", SubOpts(qos=0))
        b.subscribe(cid, "$share/g/t", SubOpts(qos=0))

    for _ in range(4):
        assert b.publish(Message(topic="t")) == 1
    assert len(ch1.sent) == 2 and len(ch2.sent) == 2

    # kill c1: picks must redispatch to c2
    b.cm.kick("c1")
    for _ in range(2):
        assert b.publish(Message(topic="t")) == 1
    assert len(ch2.sent) == 4


def test_retained_replay_on_subscribe():
    b = Broker()
    b.publish(Message(topic="a/b", payload=b"keep", retain=True))
    assert b.metrics.val("messages.retained") == 1
    ch, s = _connect(b, "c1")
    opts = SubOpts(qos=1)
    s.subscribe("a/+", opts)
    retained = b.subscribe("c1", "a/+", opts)
    assert [m.topic for m in retained] == ["a/b"]
    # retain_handling=2 suppresses replay
    opts2 = SubOpts(qos=1, retain_handling=2)
    assert b.subscribe("c1", "x/+", opts2) == []
    # shared subs never replay retained
    assert b.subscribe("c1", "$share/g/a/+", SubOpts(qos=1)) == []


def test_detached_session_queues_qos1_drops_qos0():
    b = Broker()
    ch, s = _connect(b, "c1", clean_start=False, expiry=300.0)
    s.subscribe("t", SubOpts(qos=1))
    b.subscribe("c1", "t", SubOpts(qos=1))
    b.cm.disconnect("c1", ch)

    assert b.publish(Message(topic="t", qos=1)) == 1
    assert b.publish(Message(topic="t", qos=0)) == 0  # dropped
    assert len(s.mqueue) == 1
    assert b.metrics.val("delivery.dropped") == 1

    # reconnect: the queued message replays
    ch2, s2 = _connect(b, "c1", clean_start=False)
    assert s2 is s
    out = s2.resume()
    assert len(out) == 1 and out[0].topic == "t" and out[0].qos == 1


def test_subscription_count_stat():
    b = Broker()
    ch, s = _connect(b, "c1")
    b.subscribe("c1", "a/+", SubOpts())
    b.subscribe("c1", "b", SubOpts())
    assert b.stats.get("subscriptions.count") == 2
    b.unsubscribe("c1", "a/+")
    assert b.stats.get("subscriptions.count") == 1


def test_connected_queue_full_drop_is_counted():
    from emqx_tpu.config import MqttConfig

    cfg = BrokerConfig()
    cfg.mqtt.max_inflight = 1
    cfg.mqtt.max_mqueue_len = 1
    b = Broker(config=cfg)
    ch, s = _connect(b, "slow")
    s.subscribe("t", SubOpts(qos=1))
    b.subscribe("slow", "t", SubOpts(qos=1))
    # 1 inflight + 1 queued + 1 evicts the queued one
    for i in range(3):
        b.publish(Message(topic="t", payload=str(i).encode(), qos=1))
    assert b.metrics.val("delivery.dropped.queue_full") == 1
    assert b.metrics.val("delivery.dropped") == 1


def test_delayed_will_fires_and_reconnect_cancels():
    import time as _t

    b = Broker()
    watcher_ch, ws = _connect(b, "w")
    ws.subscribe("wills/#", SubOpts(qos=0))
    b.subscribe("w", "wills/#", SubOpts(qos=0))

    will = Message(topic="wills/c1", payload=b"gone")
    b.schedule_will("c1", will, 10.0)
    b.tick(now=_t.time() + 5)
    assert watcher_ch.sent == []
    b.tick(now=_t.time() + 11)
    assert len(watcher_ch.sent) == 1

    b.schedule_will("c2", Message(topic="wills/c2"), 10.0)
    b.cancel_will("c2")
    b.tick(now=_t.time() + 100)
    assert len(watcher_ch.sent) == 1
