"""MQTT over QUIC (emqx_tpu/quic + broker/quic_listener.py): the
listener class the reference ships via MsQuic
(emqx_listeners.erl:448, emqx_quic_connection.erl), here on the
from-scratch QUIC v1 / TLS 1.3 stack — handshake unit tests, loopback
transport tests, and CONNECT/SUB/PUB through a real broker."""

import asyncio
import datetime

import pytest

from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig, ListenerConfig


def run(coro):
    return asyncio.run(coro)


def make_cert(tmp_path):
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "localhost")]
    )
    cert = (
        x509.CertificateBuilder().subject_name(name).issuer_name(name)
        .public_key(key.public_key()).serial_number(1)
        .not_valid_before(datetime.datetime(2020, 1, 1))
        .not_valid_after(datetime.datetime(2040, 1, 1))
        .sign(key, hashes.SHA256())
    )
    certfile = tmp_path / "cert.pem"
    keyfile = tmp_path / "key.pem"
    certfile.write_bytes(
        cert.public_bytes(serialization.Encoding.PEM)
    )
    keyfile.write_bytes(key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    ))
    return str(certfile), str(keyfile), cert, key


def _der(cert):
    from cryptography.hazmat.primitives import serialization

    return cert.public_bytes(serialization.Encoding.DER)


def test_tls13_handshake_and_secrets(tmp_path):
    from emqx_tpu.quic.tls13 import Tls13

    _cf, _kf, cert, key = make_cert(tmp_path)
    srv = Tls13(True, quic_tp=b"\x01", cert_der=_der(cert), key=key)
    cli = Tls13(False, quic_tp=b"\x02")
    cli.client_hello()
    srv.feed(0, cli.take_out(0))
    cli.feed(0, srv.take_out(0))
    assert cli.handshake_secrets == srv.handshake_secrets
    cli.feed(2, srv.take_out(2))
    assert cli.complete
    srv.feed(2, cli.take_out(2))
    assert srv.complete
    assert cli.app_secrets == srv.app_secrets
    assert cli.negotiated_alpn == "mqtt"
    assert srv.peer_quic_tp == b"\x02"


def test_tls13_wrong_finished_rejected(tmp_path):
    from emqx_tpu.quic.tls13 import HandshakeError, Tls13

    _cf, _kf, cert, key = make_cert(tmp_path)
    srv = Tls13(True, quic_tp=b"", cert_der=_der(cert), key=key)
    cli = Tls13(False)
    cli.client_hello()
    srv.feed(0, cli.take_out(0))
    cli.feed(0, srv.take_out(0))
    cli.feed(2, srv.take_out(2))
    fin = cli.take_out(2)
    tampered = fin[:-1] + bytes([fin[-1] ^ 0xFF])
    with pytest.raises(HandshakeError):
        srv.feed(2, tampered)


def test_quic_initial_keys_rfc9001_vector():
    """RFC 9001 appendix A: client initial secrets for the published
    DCID 0x8394c8f03e515708."""
    from emqx_tpu.quic.connection import initial_keys

    ck, _sk = initial_keys(bytes.fromhex("8394c8f03e515708"))
    assert ck.iv.hex() == "fa044b2f42a3fd3b46fb255c"
    assert ck.hp.hex() == "9f50449e04a0e810283a1e9933adedd2"


def test_quic_loopback_streams(tmp_path):
    from emqx_tpu.quic.connection import QuicConnection

    _cf, _kf, cert, key = make_cert(tmp_path)
    srv = QuicConnection(True, cert_der=_der(cert), key=key)
    cli = QuicConnection(False)
    cli.connect()

    def pump(n=20):
        for _ in range(n):
            moved = False
            for d in cli.datagrams_to_send():
                srv.receive_datagram(d)
                moved = True
            for d in srv.datagrams_to_send():
                cli.receive_datagram(d)
                moved = True
            if not moved:
                return

    pump()
    assert cli.handshake_complete and srv.handshake_complete
    sid = cli.open_stream()
    cli.send_stream(sid, b"ping")
    pump()
    evs = [e for e in srv.events() if e[0] == "stream"]
    assert evs[0][1] == sid and evs[0][2] == b"ping"
    # bulk transfer splits across packets and reassembles in order
    cli.send_stream(sid, bytes(range(256)) * 200)  # 51200 bytes
    pump(100)
    got = b"".join(e[2] for e in srv.events() if e[0] == "stream")
    assert got == bytes(range(256)) * 200


def test_send_stream_acked_prefix_trimmed(tmp_path):
    """A long-lived connection must not retain every byte ever sent:
    the acked prefix of a send stream is trimmed (base-offset rebase),
    and PTO retransmission stays exact across the trim."""
    pytest.importorskip("cryptography")
    from emqx_tpu.quic.connection import QuicConnection

    _cf, _kf, cert, key = make_cert(tmp_path)
    srv = QuicConnection(True, cert_der=_der(cert), key=key)
    cli = QuicConnection(False)
    cli.connect()

    def pump(n=50):
        for _ in range(n):
            moved = False
            for d in cli.datagrams_to_send():
                srv.receive_datagram(d)
                moved = True
            for d in srv.datagrams_to_send():
                cli.receive_datagram(d)
                moved = True
            if not moved:
                return

    pump()
    assert cli.handshake_complete
    sid = cli.open_stream()
    payload = bytes(range(256)) * 400  # 102400 bytes
    cli.send_stream(sid, payload)
    pump(200)
    got = b"".join(e[2] for e in srv.events() if e[0] == "stream")
    assert got == payload
    st = cli._streams_out[sid]
    # the server acked the stream: the buffer holds only the unacked
    # tail, not the 100 KiB history
    assert st.base > 90_000
    assert len(st.data) < 8192
    # a PTO after the trim retransmits only real data (no corruption)
    cli.on_timeout()
    pump(50)
    cli.send_stream(sid, b"more-after-trim")
    pump(50)
    tail = b"".join(e[2] for e in srv.events() if e[0] == "stream")
    assert tail.endswith(b"more-after-trim")


def test_initial_flood_amplification_bounded(tmp_path):
    """RFC 9000 §8.1: a spoofed-source Initial (valid ClientHello,
    then silence) reflects at most 3x the received bytes — no
    timer-driven PTO stream of cert flights to the victim."""
    pytest.importorskip("cryptography")

    async def t():
        from emqx_tpu.broker.listener import BrokerServer
        from emqx_tpu.quic.connection import QuicConnection

        certfile, keyfile, _c, _k = make_cert(tmp_path)
        cfg = BrokerConfig()
        cfg.listeners = [
            ListenerConfig(port=0),
            ListenerConfig(name="q", type="quic", port=0,
                           bind="127.0.0.1", certfile=certfile,
                           keyfile=keyfile),
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        qport = srv.quic_listeners[0].port

        rx = []

        class _Spoof(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                rx.append(len(data))

        loop = asyncio.get_running_loop()
        transport, _ = await loop.create_datagram_endpoint(
            lambda: _Spoof(), remote_addr=("127.0.0.1", qport)
        )
        attacker = QuicConnection(False)
        attacker.connect()
        flights = attacker.datagrams_to_send()
        sent = sum(len(d) for d in flights)
        assert sent >= 1200
        for d in flights:
            transport.sendto(d)
        # four PTO periods of silence: the old listener re-sent the
        # full Initial+Handshake cert flight every 300ms
        await asyncio.sleep(1.3)
        reflected = sum(rx)
        assert reflected <= 3 * sent, (
            f"amplification {reflected}/{sent} exceeds 3x"
        )
        transport.close()
        await srv.stop()

    run(t())


def test_handshake_phase_bridges_bounded_per_source(tmp_path):
    """Half-open state is bounded: one source IP cannot mint unlimited
    handshake-phase conn+Channel bridges, and runt (sub-1200-byte)
    Initials never create state at all."""
    pytest.importorskip("cryptography")

    async def t():
        from emqx_tpu.broker.listener import BrokerServer
        from emqx_tpu.quic.connection import QuicConnection

        certfile, keyfile, _c, _k = make_cert(tmp_path)
        cfg = BrokerConfig()
        cfg.listeners = [
            ListenerConfig(port=0),
            ListenerConfig(name="q", type="quic", port=0,
                           bind="127.0.0.1", certfile=certfile,
                           keyfile=keyfile),
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        lst = srv.quic_listeners[0]
        qport = lst.port
        loop = asyncio.get_running_loop()
        transport, _ = await loop.create_datagram_endpoint(
            asyncio.DatagramProtocol,
            remote_addr=("127.0.0.1", qport),
        )
        cap = lst.MAX_HANDSHAKES_PER_SOURCE
        for _ in range(cap + 8):
            c = QuicConnection(False)
            c.connect()
            for d in c.datagrams_to_send():
                transport.sendto(d)
        # a runt "Initial" (long header, no 1200-byte padding)
        transport.sendto(b"\xc0\x00\x00\x00\x01\x08" + b"r" * 60)
        await asyncio.sleep(0.3)
        bridges = set(lst._by_cid.values())
        assert len(bridges) <= cap
        assert lst._hs_per_src.get("127.0.0.1", 0) <= cap
        transport.close()
        await srv.stop()

    run(t())


def test_mqtt_over_quic_end_to_end(tmp_path):
    """CONNECT / SUBSCRIBE / PUBLISH over a quic listener, cross-
    delivered to a TCP client — both directions."""

    async def t():
        from emqx_tpu.broker.listener import BrokerServer
        from emqx_tpu.broker.quic_listener import QuicClientTransport
        from mqtt_client import TestClient

        certfile, keyfile, _c, _k = make_cert(tmp_path)
        cfg = BrokerConfig()
        cfg.listeners = [
            ListenerConfig(port=0),
            ListenerConfig(name="quic_default", type="quic", port=0,
                           bind="127.0.0.1", certfile=certfile,
                           keyfile=keyfile),
        ]
        srv = BrokerServer(cfg)
        await srv.start()
        assert srv.quic_listeners, "quic listener did not start"
        qport = srv.quic_listeners[0].port

        qc = QuicClientTransport("127.0.0.1", qport)
        await qc.connect()
        parser = C.StreamParser(version=C.MQTT_V5)

        async def expect(ptype, timeout=5.0):
            deadline = asyncio.get_event_loop().time() + timeout
            while True:
                for pkt in parser.feed(await qc.read(
                    timeout=deadline - asyncio.get_event_loop().time()
                )):
                    assert pkt.type == ptype, pkt
                    return pkt

        qc.write(C.serialize(
            C.Connect(client_id="quic-dev", proto_ver=C.MQTT_V5),
            C.MQTT_V5,
        ))
        await expect(C.CONNACK)
        qc.write(C.serialize(C.Subscribe(
            packet_id=1,
            subscriptions=[C.Subscription(topic_filter="q/#", qos=0)],
        ), C.MQTT_V5))
        await expect(C.SUBACK)

        # TCP -> QUIC delivery
        tcp = TestClient(srv.listeners[0].port, "tcp-peer")
        await tcp.connect()
        await tcp.subscribe("from-quic/#", qos=0)
        await tcp.publish("q/hello", b"over-udp", qos=0)
        pkt = await expect(C.PUBLISH)
        assert pkt.topic == "q/hello" and pkt.payload == b"over-udp"

        # QUIC -> TCP delivery
        qc.write(C.serialize(C.Publish(
            topic="from-quic/x", payload=b"hi-tcp", qos=0,
        ), C.MQTT_V5))
        msg = await tcp.recv_publish(timeout=5)
        assert msg.topic == "from-quic/x" and msg.payload == b"hi-tcp"

        # the quic client appears in the connection census like any
        # other transport
        assert srv.broker.cm.channel("quic-dev") is not None

        qc.close()
        await tcp.disconnect()
        await srv.stop()

    run(t())


# ------------------------------------------------- selective-ack loss


def test_recovery_range_tracker():
    """Crypto-free unit test of the loss-recovery range arithmetic
    (quic/recovery.py is deliberately importable without the
    `cryptography` package)."""
    from emqx_tpu.quic.recovery import RangeTracker

    rt = RangeTracker()
    rt.add(0, 100)
    rt.add(200, 300)
    rt.add(100, 150)  # touching ranges merge
    assert rt.ranges == [(0, 150), (200, 300)]
    assert rt.contiguous_from(0) == 150
    assert rt.contiguous_from(150) == 150  # next byte unacked
    assert rt.missing_within(0, 400) == [(150, 200), (300, 400)]
    assert rt.missing_within(0, 120) == []
    rt.prune_below(140)
    assert rt.ranges == [(140, 150), (200, 300)]


def test_recovery_selective_ack_model():
    """An ack of LATER packet numbers must not imply earlier ones: the
    lost packet's ranges stay unacked, get declared lost at the
    3-packet threshold, and requeue only their unacked parts."""
    from emqx_tpu.quic.recovery import RecoverySpace, SentPacket

    sp = RecoverySpace()
    for pn in range(6):
        pkt = SentPacket()
        pkt.streams.append((0, pn * 1000, (pn + 1) * 1000))
        sp.record(pn, pkt)
    # packets 0 and 2..5 acked; packet 1 lost on the wire
    sp.on_ack_range(0, 0)
    acked = sp.on_ack_range(2, 5)
    assert len(acked) == 4
    lost = sp.detect_lost()  # cutoff = 5 - 3 = 2 -> pn 1
    assert [p.streams[0] for p in lost] == [(0, 1000, 2000)]
    assert sp.sent == {}  # nothing left in flight
    # crypto path: queued retx is re-filtered against later acks
    sp.crypto_acked.add(0, 40)
    sp.queue_crypto_retx([(0, 100)])
    assert sp.take_crypto_retx() == [(40, 100)]
    assert sp.take_crypto_retx() == []  # drained


def test_selective_loss_retransmitted(tmp_path):
    """ROADMAP open item: under selective loss (an earlier data packet
    lost, later ones acked) the lost stream bytes must be
    retransmitted from the ack stream alone — no PTO, no idle-timeout
    wedge.  The pre-selective-ack model treated an ack of the latest
    pn as cumulative and never resent them."""
    pytest.importorskip("cryptography")
    from emqx_tpu.quic.connection import QuicConnection

    _cf, _kf, cert, key = make_cert(tmp_path)
    srv = QuicConnection(True, cert_der=_der(cert), key=key)
    cli = QuicConnection(False)
    cli.connect()

    def pump(n=200):
        for _ in range(n):
            moved = False
            for d in cli.datagrams_to_send():
                srv.receive_datagram(d)
                moved = True
            for d in srv.datagrams_to_send():
                cli.receive_datagram(d)
                moved = True
            if not moved:
                return

    pump()
    assert cli.handshake_complete and srv.handshake_complete
    sid = cli.open_stream()

    payload = bytes(range(256)) * 200  # 51200 bytes, ~50 packets
    # eat the SECOND datagram of the flight: everything after it is
    # received and acked, the gap must be loss-detected + resent
    cli.send_stream(sid, payload)
    flight = cli.datagrams_to_send()
    assert len(flight) > 5
    for i, d in enumerate(flight):
        if i != 1:
            srv.receive_datagram(d)
    pump()
    got = b"".join(e[2] for e in srv.events() if e[0] == "stream")
    assert got == payload
    # and the sender's buffer trimmed through the recovered range
    st = cli._streams_out[sid]
    assert st.base == len(payload)
    assert st.data == b""

    # a second loss epoch on the same long-lived stream still works
    # (absolute offsets survive the base rebase)
    more = b"tail-after-recovery" * 500
    cli.send_stream(sid, more)
    flight = cli.datagrams_to_send()
    for i, d in enumerate(flight):
        if i != 0:
            srv.receive_datagram(d)
    pump()
    got2 = got + b"".join(
        e[2] for e in srv.events() if e[0] == "stream"
    )
    assert got2 == payload + more


def test_selective_loss_recovery_without_crypto(monkeypatch):
    """The connection-level recovery integration, runnable in the
    tier-1 environment (no `cryptography` package): AEAD and header
    protection are stubbed at the import boundary — passthrough
    ciphertext, identity HP mask — while the REAL packetizer, ack
    parser, recovery spaces, and stream buffers run end to end.  In
    environments with the real package this skips in favor of
    test_selective_loss_retransmitted (true crypto path)."""
    try:
        import cryptography  # noqa: F401
        pytest.skip("real cryptography present: the full-stack "
                    "selective-loss test covers this path")
    except ImportError:
        pass
    import sys
    import types

    def mod(name):
        m = types.ModuleType(name)
        monkeypatch.setitem(sys.modules, name, m)
        return m

    class FakeAESGCM:
        def __init__(self, key):
            pass

        def encrypt(self, nonce, data, aad):
            return data + b"\x00" * 16

        def decrypt(self, nonce, ct, aad):
            return ct[:-16]

    class _Enc:
        def update(self, data):
            return bytes(data)

    class FakeCipher:
        def __init__(self, alg, mode):
            pass

        def encryptor(self):
            return _Enc()

    mod("cryptography")
    mod("cryptography.hazmat")
    prims = mod("cryptography.hazmat.primitives")
    ciphers = mod("cryptography.hazmat.primitives.ciphers")
    aead = mod("cryptography.hazmat.primitives.ciphers.aead")
    aead.AESGCM = FakeAESGCM
    ciphers.Cipher = FakeCipher
    ciphers.algorithms = types.SimpleNamespace(
        AES=lambda key: None
    )
    ciphers.modes = types.SimpleNamespace(ECB=lambda: None)
    prims.hashes = types.SimpleNamespace()
    prims.serialization = types.SimpleNamespace()
    asym = mod("cryptography.hazmat.primitives.asymmetric")
    asym.ec = types.SimpleNamespace()
    x = mod("cryptography.hazmat.primitives.asymmetric.x25519")
    x.X25519PrivateKey = object
    x.X25519PublicKey = object

    # import against the stubs; evict cached copies both ways so other
    # tests never see a stub-built module
    for name in ("emqx_tpu.quic.connection", "emqx_tpu.quic.tls13"):
        monkeypatch.delitem(sys.modules, name, raising=False)
    import importlib

    conn_mod = importlib.import_module("emqx_tpu.quic.connection")
    try:
        _run_stubbed_loss_scenarios(conn_mod)
    finally:
        for name in ("emqx_tpu.quic.connection",
                     "emqx_tpu.quic.tls13"):
            sys.modules.pop(name, None)


def _run_stubbed_loss_scenarios(conn_mod):
    from emqx_tpu.quic.recovery import RecoverySpace

    class _FakeTls:
        complete = True
        handshake_secrets = None
        app_secrets = None

        def take_out(self, epoch):
            return b""

    def make_conn(is_server, scid, dcid):
        c = object.__new__(conn_mod.QuicConnection)
        c.is_server = is_server
        c.scid = scid
        c.dcid = dcid
        c.original_dcid = dcid
        c.tls = _FakeTls()
        k = conn_mod.Keys(b"\x11" * 32)
        c._keys = {0: (None, None), 2: (None, None), 3: (k, k)}
        c._pn = {0: 0, 2: 0, 3: 0}
        c._largest_recv = {0: -1, 2: -1, 3: -1}
        c._recv_pns = {0: set(), 2: set(), 3: set()}
        c._pn_floor = {0: 0, 2: 0, 3: 0}
        c._PN_WINDOW = 2048
        c._ack_due = {0: False, 2: False, 3: False}
        c._ack_every = 1
        c._ack_pending = {0: 0, 2: 0, 3: 0}
        c.max_stream_chunk = 1100
        c._crypto_out = {0: b"", 2: b"", 3: b""}
        c._crypto_sent = {0: 0, 2: 0, 3: 0}
        c._crypto_recv_off = {0: 0, 2: 0, 3: 0}
        c._crypto_chunks = {0: {}, 2: {}, 3: {}}
        c._streams_out = {}
        c._streams_sent = {}
        c._streams_in = {}
        c._events = []
        c.handshake_complete = True
        c._handshake_done_sent = True
        c._handshake_confirmed = True
        c.address_validated = True
        c.closed = False
        c.close_code = None
        c._out_datagrams = []
        c._next_stream_id = 0
        c._spaces = {0: RecoverySpace(), 2: RecoverySpace(),
                     3: RecoverySpace()}
        return c

    def pair():
        return (make_conn(False, b"C" * 8, b"S" * 8),
                make_conn(True, b"S" * 8, b"C" * 8))

    def pump(a, b, n=50, drop=None):
        for r in range(n):
            moved = False
            for i, d in enumerate(a.datagrams_to_send()):
                if drop is not None and drop(r, i):
                    continue
                b.receive_datagram(d)
                moved = True
            for d in b.datagrams_to_send():
                a.receive_datagram(d)
                moved = True
            if not moved:
                return

    def delivered(conn, sid=0):
        return b"".join(
            e[2] for e in conn.events() if e[0] == "stream"
        )

    payload = bytes(range(256)) * 20  # 5120 B, several packets

    # 1) no loss: plain delivery + full trim
    cli, srv = pair()
    cli.send_stream(0, payload)
    pump(cli, srv)
    assert delivered(srv) == payload

    # 2) selective loss, ack-driven: drop one mid-flight datagram;
    #    later acks trigger threshold loss detection + exact resend
    cli, srv = pair()
    cli.send_stream(0, payload)
    pump(cli, srv, drop=lambda r, i: r == 0 and i == 1)
    assert delivered(srv) == payload
    st = cli._streams_out[0]
    assert st.base == len(payload) and st.data == b""

    # 3) tail loss: no later acks exist — PTO requeues exactly the
    #    missing ranges
    cli, srv = pair()
    cli.send_stream(0, payload)
    flight = cli.datagrams_to_send()
    for d in flight[:-1]:
        srv.receive_datagram(d)
    pump(cli, srv)
    assert srv._streams_in[0].delivered < len(payload)
    cli.on_timeout()
    pump(cli, srv)
    assert srv._streams_in[0].delivered == len(payload)

    # 4) FIN lost: retransmitted after PTO
    cli, srv = pair()
    cli.send_stream(0, b"x" * 100, fin=True)
    cli.datagrams_to_send()  # whole flight eaten
    cli.on_timeout()
    pump(cli, srv)
    assert any(
        e[0] == "stream" and e[3] for e in srv.events()
    ), "FIN not retransmitted"
