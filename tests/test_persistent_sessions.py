"""Persistent sessions over durable storage: a session checkpointed at
disconnect survives a broker restart, and messages persisted while it
was away replay on reconnect (emqx_persistent_session_ds semantics at
the black-box level)."""

import asyncio

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def make_server(data_dir):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.durable.enable = True
    cfg.durable.data_dir = str(data_dir)
    return BrokerServer(cfg)


def test_session_survives_broker_restart(tmp_path):
    async def t():
        srv1 = make_server(tmp_path / "ds")
        await srv1.start()
        port = srv1.listeners[0].port

        c1 = TestClient(port, "veh-1")
        await c1.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        await c1.subscribe("cmd/veh-1/#", qos=1)
        await c1.disconnect()

        # messages arrive while the client is away; qos1 -> persisted
        pub = TestClient(port, "ctl")
        await pub.connect()
        for i in range(3):
            await pub.publish(f"cmd/veh-1/step{i}", f"go{i}".encode(), qos=1)
        await pub.disconnect()

        # broker restarts: all in-memory state is gone
        await srv1.stop()
        srv1.broker.durable.close()

        srv2 = make_server(tmp_path / "ds")
        await srv2.start()
        port2 = srv2.listeners[0].port
        c1b = TestClient(port2, "veh-1")
        ack = await c1b.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        assert ack.session_present  # rebuilt from the DS checkpoint
        got = []
        for _ in range(3):
            msg = await c1b.recv_publish(timeout=5)
            got.append((msg.topic, msg.payload, msg.qos))
        assert sorted(got) == [
            (f"cmd/veh-1/step{i}", f"go{i}".encode(), 1) for i in range(3)
        ]
        # subscription is live again, not just replayed
        pub2 = TestClient(port2, "ctl2")
        await pub2.connect()
        await pub2.publish("cmd/veh-1/live", b"now", qos=1)
        msg = await c1b.recv_publish(timeout=5)
        assert msg.payload == b"now"
        await pub2.disconnect()
        await c1b.disconnect()
        await srv2.stop()
        srv2.broker.durable.close()

    run(t())


def test_clean_start_discards_checkpoint(tmp_path):
    async def t():
        srv1 = make_server(tmp_path / "ds")
        await srv1.start()
        port = srv1.listeners[0].port
        c1 = TestClient(port, "dev-9")
        await c1.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        await c1.subscribe("q/#", qos=1)
        await c1.disconnect()
        await srv1.stop()
        srv1.broker.durable.close()

        srv2 = make_server(tmp_path / "ds")
        await srv2.start()
        c1b = TestClient(srv2.listeners[0].port, "dev-9")
        ack = await c1b.connect(clean_start=True)
        assert not ack.session_present
        # and a later clean_start=false reconnect finds nothing either
        await c1b.disconnect()
        c1c = TestClient(srv2.listeners[0].port, "dev-9")
        ack2 = await c1c.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        assert not ack2.session_present
        await c1c.disconnect()
        await srv2.stop()
        srv2.broker.durable.close()

    run(t())


def test_qos0_not_persisted_by_default(tmp_path):
    async def t():
        srv1 = make_server(tmp_path / "ds")
        await srv1.start()
        port = srv1.listeners[0].port
        c1 = TestClient(port, "s0")
        await c1.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        await c1.subscribe("ev/#", qos=1)
        await c1.disconnect()
        pub = TestClient(port, "p")
        await pub.connect()
        await pub.publish("ev/a", b"q0", qos=0)
        await pub.publish("ev/b", b"q1", qos=1)
        await pub.disconnect()
        await srv1.stop()
        srv1.broker.durable.close()

        srv2 = make_server(tmp_path / "ds")
        await srv2.start()
        c1b = TestClient(srv2.listeners[0].port, "s0")
        ack = await c1b.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        assert ack.session_present
        msg = await c1b.recv_publish(timeout=5)
        assert msg.payload == b"q1"  # only the QoS1 message survived
        try:
            extra = await c1b.recv(timeout=0.3)
            assert False, f"unexpected extra packet: {extra!r}"
        except asyncio.TimeoutError:
            pass
        await c1b.disconnect()
        await srv2.stop()
        srv2.broker.durable.close()

    run(t())


def test_expired_checkpoint_not_resumed(tmp_path):
    async def t():
        srv1 = make_server(tmp_path / "ds")
        await srv1.start()
        port = srv1.listeners[0].port
        c1 = TestClient(port, "exp-1")
        await c1.connect(
            clean_start=False,
            properties={"session_expiry_interval": 1},
        )
        await c1.subscribe("z/#", qos=1)
        await c1.disconnect()
        await srv1.stop()
        srv1.broker.durable.close()

        await asyncio.sleep(1.2)  # past the 1s expiry

        srv2 = make_server(tmp_path / "ds")
        await srv2.start()
        c1b = TestClient(srv2.listeners[0].port, "exp-1")
        ack = await c1b.connect(
            clean_start=False,
            properties={"session_expiry_interval": 1},
        )
        assert not ack.session_present
        await c1b.disconnect()
        await srv2.stop()
        srv2.broker.durable.close()

    run(t())


def test_gate_released_on_clean_start_discard(tmp_path):
    """Discarding a boot checkpoint (clean_start reconnect) must release
    the gate refs _load_states took, or the gate persists messages for a
    session that can never return."""

    async def t():
        srv1 = make_server(tmp_path / "ds")
        await srv1.start()
        c1 = TestClient(srv1.listeners[0].port, "leak-1")
        await c1.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        await c1.subscribe("leak/a/#", qos=1)
        await c1.disconnect()
        await srv1.stop()
        srv1.broker.durable.close()

        srv2 = make_server(tmp_path / "ds")
        await srv2.start()
        assert srv2.broker.durable._refs == {"leak/a/#": 1}
        c1b = TestClient(srv2.listeners[0].port, "leak-1")
        await c1b.connect(clean_start=True)
        assert srv2.broker.durable._refs == {}
        assert not srv2.broker.durable._gate.match("leak/a/x")
        await c1b.disconnect()
        await srv2.stop()
        srv2.broker.durable.close()

    run(t())


def test_gate_released_on_expiry_zero_disconnect(tmp_path):
    """An MQTT5 client that lowers session_expiry_interval to 0 at
    DISCONNECT terminates the session — the gate refs taken at subscribe
    time (expiry was >0 then) must be released."""

    async def t():
        srv = make_server(tmp_path / "ds")
        await srv.start()
        c = TestClient(srv.listeners[0].port, "zero-x")
        await c.connect(
            clean_start=False,
            properties={"session_expiry_interval": 3600},
        )
        await c.subscribe("zero/#", qos=1)
        assert srv.broker.durable._refs == {"zero/#": 1}
        await c.disconnect(properties={"session_expiry_interval": 0})
        await asyncio.sleep(0.05)
        assert srv.broker.durable._refs == {}
        await srv.stop()
        srv.broker.durable.close()

    run(t())


def test_remote_forwarded_message_is_persisted(tmp_path):
    """A message arriving via cluster forward must hit the local
    persistence gate: DS is node-local, so remote-origin messages for a
    local persistent session are stored here or nowhere."""
    from emqx_tpu.broker.broker import Broker
    from emqx_tpu.message import Message

    cfg = BrokerConfig()
    cfg.durable.enable = True
    cfg.durable.data_dir = str(tmp_path / "ds")
    broker = Broker(cfg)
    broker.durable.add_filter("far/#")
    n0 = broker.durable.storage.stats()["messages"]
    broker.dispatch_forwarded(
        Message(topic="far/away", payload=b"x", qos=1)
    )
    assert broker.durable.storage.stats()["messages"] == n0 + 1
    broker.shutdown()


def test_chunked_replay_checkpoints_iterators(tmp_path):
    """A crash mid-replay must resume from the persisted iterator
    cursors, not re-read the whole missed interval from the disconnect
    timestamp (the stream-progress persistence the reference keeps in
    its DS session tables)."""
    import time as _time

    from emqx_tpu.ds.persist import DurableSessions
    from emqx_tpu.message import Message

    ds0 = DurableSessions(str(tmp_path / "ds"), n_streams=4)
    ds0.add_filter("fleet/+/pos")
    ds0.save(
        "veh-9", {"fleet/+/pos": {"qos": 1}}, 3600.0,
        now=_time.time() - 10,
    )
    for i in range(40):
        ds0.persist([Message(topic=f"fleet/v{i % 4}/pos", qos=1,
                             payload=str(i).encode())])
    ds0.sync()
    ds0.close()

    # boot 1: checkpoint restored from disk, replay starts
    ds1 = DurableSessions(str(tmp_path / "ds"), n_streams=4)
    state = ds1.load("veh-9")
    first, done = ds1.replay_chunk(state, max_msgs=15)
    assert len(first) == 15 and not done
    ds1.save_state(state)  # the mid-replay checkpoint
    got_first = {m.payload for _, m in first}
    ds1.close()

    # "crash": a fresh instance reloads the checkpoint from disk and
    # resumes from the cursors
    ds2 = DurableSessions(str(tmp_path / "ds"), n_streams=4)
    state2 = ds2.load("veh-9")
    assert state2.iters is not None  # cursors survived
    rest = ds2.replay(state2)
    got_rest = {m.payload for _, m in rest}
    assert len(got_rest) + len(got_first) >= 40
    assert got_first | got_rest == {str(i).encode() for i in range(40)}
    # the resumed run re-reads at most the partially-consumed streams,
    # never the already-exhausted ones: overlap stays well under a
    # full re-read
    assert len(got_first & got_rest) < 15
    ds2.close()
