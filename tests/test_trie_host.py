"""HostTrie oracle tests: hand cases + randomized equivalence against the
brute-force word matcher (the property-test pattern the reference applies
to its matchers, e.g. emqx_trie_search semantics cases)."""

import random

import pytest

from emqx_tpu import topic as T
from emqx_tpu.ops.trie_host import HostTrie


def build(filters):
    t = HostTrie()
    for i, f in enumerate(filters):
        t.insert(f, i)
    return t


def ids(t, name):
    return t.match(name)


def test_basic_match():
    t = build(["a/b/c", "a/+/c", "a/#", "#", "x/y"])
    assert ids(t, "a/b/c") == {0, 1, 2, 3}
    assert ids(t, "a/z/c") == {1, 2, 3}
    assert ids(t, "a") == {2, 3}
    assert ids(t, "x/y") == {3, 4}
    assert ids(t, "q") == {3}


def test_dollar_exclusion():
    t = build(["#", "+/broker", "$SYS/#", "$SYS/+"])
    assert ids(t, "$SYS/broker") == {2, 3}
    assert ids(t, "other/broker") == {0, 1}
    assert ids(t, "$SYS") == {2}


def test_hash_parent():
    t = build(["sport/#"])
    assert ids(t, "sport") == {0}
    assert ids(t, "sport/tennis/x") == {0}
    assert ids(t, "sports") == set()


def test_empty_levels():
    t = build(["a/+/c", "+/b", "a/+", "#"])
    assert ids(t, "a//c") == {0, 3}
    assert ids(t, "/b") == {1, 3}
    assert ids(t, "a/") == {2, 3}


def test_delete_and_replace():
    t = HostTrie()
    t.insert("a/+", "s1")
    t.insert("a/#", "s2")
    assert t.match("a/b") == {"s1", "s2"}
    assert t.delete_id("s1")
    assert t.match("a/b") == {"s2"}
    assert not t.delete_id("s1")
    # replace same id with a new filter
    t.insert("c/d", "s2")
    assert t.match("a/b") == set()
    assert t.match("c/d") == {"s2"}
    assert len(t) == 1


def test_prune_keeps_shared_prefixes():
    t = HostTrie()
    t.insert("a/b/c", 1)
    t.insert("a/b", 2)
    t.delete_id(1)
    assert t.match("a/b") == {2}
    t.delete_id(2)
    assert t.match("a/b") == set()
    assert len(t._root.children) == 0


WORDS = ["a", "b", "c", "dev", "42", "", "$SYS", "$x", "longish-word"]


def rand_filter(rng):
    n = rng.randint(1, 6)
    ws = []
    for i in range(n):
        r = rng.random()
        if r < 0.2:
            ws.append("+")
        elif r < 0.3 and i == n - 1:
            ws.append("#")
        else:
            ws.append(rng.choice(WORDS))
    return "/".join(ws)


def rand_name(rng):
    n = rng.randint(1, 6)
    return "/".join(rng.choice(WORDS) for _ in range(n))


@pytest.mark.parametrize("seed", range(8))
def test_randomized_equivalence(seed):
    rng = random.Random(seed)
    filters = [rand_filter(rng) for _ in range(300)]
    t = build(filters)
    for _ in range(300):
        name = rand_name(rng)
        assert t.match(name) == t.match_brute(name), name


def test_randomized_with_deletions():
    rng = random.Random(99)
    t = HostTrie()
    alive = {}
    for step in range(2000):
        op = rng.random()
        if op < 0.55 or not alive:
            fid = rng.randint(0, 500)
            f = rand_filter(rng)
            t.insert(f, fid)
            alive[fid] = f
        else:
            fid = rng.choice(list(alive))
            assert t.delete_id(fid)
            del alive[fid]
        if step % 100 == 0:
            name = rand_name(rng)
            assert t.match(name) == t.match_brute(name)
    assert len(t) == len(alive)


# ---------------------------------------------------------------- native

def _native_or_skip():
    from emqx_tpu.ops.trie_native import NativeTrie, load

    if load() is None:
        import pytest

        pytest.skip("native hosttrie unavailable")
    return NativeTrie()


@pytest.mark.parametrize("seed", range(6))
def test_native_trie_equivalence(seed):
    """NativeTrie (C++) must agree with HostTrie (the Python oracle) on
    randomized insert/delete/match churn, including '$'-topics, empty
    levels, and fid reuse across different filters."""
    import random

    from emqx_tpu import topic as T
    from emqx_tpu.ops.trie_host import HostTrie

    rng = random.Random(7000 + seed)
    native = _native_or_skip()
    py = HostTrie()
    words = ["a", "b", "c", "dev", "x1", "", "$SYS", "+", "#"]
    live = set()
    for step in range(1500):
        op = rng.random()
        if op < 0.55 or not live:
            depth = rng.randint(1, 4)
            ws = [rng.choice(words) for _ in range(depth)]
            flt = "/".join(ws)
            try:
                T.validate_filter(flt)
            except ValueError:
                continue
            fid = rng.choice(
                ["s%d" % rng.randint(0, 300), rng.randint(0, 300),
                 ("rule", rng.randint(0, 50))]
            )
            native.insert(flt, fid)
            py.insert(flt, fid)
            live.add(fid)
        else:
            fid = rng.choice(sorted(live, key=str))
            assert native.delete_id(fid) == py.delete_id(fid)
            live.discard(fid)
        if step % 100 == 99:
            assert len(native) == len(py)
            for _ in range(30):
                depth = rng.randint(1, 5)
                t = "/".join(
                    rng.choice(["a", "b", "c", "dev", "x1", "", "$SYS", "q9"])
                    for _ in range(depth)
                )
                assert native.match(t) == py.match_words(T.words(t)), t


def test_native_trie_large_matchset_grows_buffer():
    native = _native_or_skip()
    for i in range(5000):
        native.insert("big/#", i)
    got = native.match("big/one/two")
    assert got == set(range(5000))


def test_make_trie_python_fallback(monkeypatch):
    """The Python HostTrie serves when the native lib is unavailable
    (kill switch or failed build) — the fallback path must survive
    the C++17 rewrite making the native trie available everywhere."""
    from emqx_tpu.ops import trie_native
    from emqx_tpu.ops.trie_host import HostTrie

    monkeypatch.setenv("EMQX_TPU_NO_NATIVE_TRIE", "1")
    t = trie_native.make_trie()
    assert isinstance(t, HostTrie)
    t.insert("a/+/c", "f1")
    t.insert("a/#", "f2")
    assert t.match("a/b/c") == {"f1", "f2"}
    monkeypatch.delenv("EMQX_TPU_NO_NATIVE_TRIE")
    if trie_native.load() is not None:
        assert not isinstance(trie_native.make_trie(), HostTrie)
