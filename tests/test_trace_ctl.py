"""Tracing, audit trail, and the ctl CLI (emqx_trace / emqx_audit /
emqx_ctl parity at the black-box level)."""

import asyncio
import tempfile

# auto-cleaned parent for per-test mgmt stores (finalized at interpreter exit)
_MGMT_TMP = tempfile.TemporaryDirectory(prefix="emqx-mgmt-")
import subprocess
import sys

import aiohttp

from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.config import BrokerConfig, ListenerConfig
from api_helper import auth_session
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def make_server(tmp_path):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.api.enable = True
    cfg.api.data_dir = tempfile.mkdtemp(dir=_MGMT_TMP.name)
    cfg.api.port = 0
    srv = BrokerServer(cfg)
    srv.broker.trace.directory = str(tmp_path / "trace")
    return srv


def test_trace_clientid_and_topic(tmp_path):
    async def t():
        srv = make_server(tmp_path)
        await srv.start()
        port = srv.listeners[0].port
        http, api = await auth_session(srv)

        async with http:
            async with http.post(
                api + "/api/v5/trace",
                json={"name": "t1", "type": "clientid", "match": "dev-1"},
            ) as r:
                assert r.status == 201
            async with http.post(
                api + "/api/v5/trace",
                json={"name": "t2", "type": "topic", "match": "sensors/#"},
            ) as r:
                assert r.status == 201

            c = TestClient(port, "dev-1")
            await c.connect()
            await c.subscribe("sensors/+/temp", qos=1)
            p = TestClient(port, "other")
            await p.connect()
            await p.publish("sensors/5/temp", b"21.5", qos=1)
            await c.recv_publish()
            await p.disconnect()
            await c.disconnect()
            await asyncio.sleep(0.05)

            async with http.get(api + "/api/v5/trace/t1/log") as r:
                log1 = await r.text()
            assert "client.connected" in log1 and "clientid=dev-1" in log1
            assert "session.subscribed" in log1
            async with http.get(api + "/api/v5/trace/t2/log") as r:
                log2 = await r.text()
            assert "message.publish" in log2
            assert "topic=sensors/5/temp" in log2

            async with http.get(api + "/api/v5/trace") as r:
                lst = await r.json()
            assert {t["name"] for t in lst["data"]} == {"t1", "t2"}
            async with http.delete(api + "/api/v5/trace/t1") as r:
                assert r.status == 204

            # mutations show up in the audit trail
            async with http.get(api + "/api/v5/audit") as r:
                audit = await r.json()
            paths = [(a["method"], a["path"]) for a in audit["data"]]
            assert ("POST", "/api/v5/trace") in paths
            assert ("DELETE", "/api/v5/trace/t1") in paths

        await srv.stop()

    run(t())


def test_ctl_cli_against_live_broker(tmp_path):
    async def t():
        srv = make_server(tmp_path)
        await srv.start()
        port = srv.listeners[0].port
        api = f"http://127.0.0.1:{srv.api.port}"
        c = TestClient(port, "cli-watch")
        await c.connect()
        await c.subscribe("cli/#", qos=1)

        def ctl(*args):
            out = subprocess.run(
                [sys.executable, "-m", "emqx_tpu.ctl", "--api", api, *args],
                capture_output=True,
                text=True,
                timeout=30,
                cwd="/root/repo",
            )
            assert out.returncode == 0, out.stderr
            return out.stdout

        loop = asyncio.get_running_loop()
        status = await loop.run_in_executor(None, ctl, "status")
        assert "is running" in status
        clients = await loop.run_in_executor(None, ctl, "clients")
        assert "cli-watch" in clients
        pub = await loop.run_in_executor(
            None, ctl, "publish", "cli/hello", "from-ctl"
        )
        assert "delivered to 1" in pub
        pkt = await c.recv_publish()
        assert pkt.payload == b"from-ctl"

        # elastic ops round trip: status -> start -> status -> stop
        reb = await loop.run_in_executor(None, ctl, "rebalance")
        assert "evacuation:" in reb and "purge:" in reb
        started = await loop.run_in_executor(
            None, ctl, "rebalance", "start"
        )
        assert "rebalance:" in started
        stopped = await loop.run_in_executor(
            None, ctl, "rebalance", "stop"
        )
        assert "stopped" in stopped
        purge = await loop.run_in_executor(
            None, ctl, "rebalance", "purge", "start"
        )
        assert "purge:" in purge

        await c.disconnect()
        await srv.stop()

    run(t())
