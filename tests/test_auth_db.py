"""Database auth backends (emqx_auth_mysql/postgresql/redis parity):
placeholder queries compile to prepared-statement parameters, the
full password-hashing suite (incl. bcrypt) verifies, ACL rows
evaluate with eq_/wildcard semantics, and a live broker prefetches a
client's ACL at CONNECT so publish/subscribe authorization never
waits on IO."""

import asyncio

import pytest

from emqx_tpu.access import ALLOW, DENY, IGNORE, ClientInfo, PUBLISH, SUBSCRIBE
from emqx_tpu.auth_db import (RedisAuthenticator, RedisAuthorizer,
                              SqlAuthenticator, SqlAuthorizer,
                              SqlConnector, compile_query,
                              evaluate_acl_rows, hash_password,
                              verify_password)


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------------- hashing

@pytest.mark.parametrize("algo", ["plain", "md5", "sha", "sha256",
                                  "sha512"])
@pytest.mark.parametrize("pos", ["prefix", "suffix"])
def test_simple_hash_suite(algo, pos):
    stored = hash_password("s3cret", algo, salt="NaCl", salt_position=pos)
    assert verify_password(b"s3cret", stored, algo, "NaCl", pos)
    assert not verify_password(b"wrong", stored, algo, "NaCl", pos)
    if algo != "plain":
        assert not verify_password(b"s3cret", stored, algo, "other", pos)


def test_pbkdf2_and_bcrypt():
    stored = hash_password("pw", "pbkdf2", salt="salty", iterations=1000)
    assert verify_password(b"pw", stored, "pbkdf2", "salty",
                           iterations=1000)
    assert not verify_password(b"pw", stored, "pbkdf2", "salty",
                               iterations=999)

    bc = hash_password("hello", "bcrypt")
    assert bc.startswith("$2")
    assert verify_password(b"hello", bc, "bcrypt")
    assert not verify_password(b"nope", bc, "bcrypt")
    # a stock bcrypt hash of "hello" verifies too (interop check)
    known = "$2b$10$N9qo8uLOickgx2ZMRZoMyeLsZqCYRq5JA..Ba2xizzVJebx3sdMuu"
    assert verify_password(b"hello", known, "bcrypt")


# ----------------------------------------------------------- templating

def test_compile_query_parameterizes_placeholders():
    sql, getters = compile_query(
        "SELECT h FROM u WHERE username = ${username} AND "
        "clientid = ${clientid} AND ip = ${peerhost}"
    )
    assert sql == ("SELECT h FROM u WHERE username = %s AND "
                   "clientid = %s AND ip = %s")
    c = ClientInfo(clientid="c1' OR 1=1 --", username="bob",
                   peerhost="10.0.0.9:5312")
    vals = [g(c) for g in getters]
    # injection text stays in the PARAMS, never in the SQL
    assert vals == ["bob", "c1' OR 1=1 --", "10.0.0.9"]

    sql_pg, _ = compile_query(
        "SELECT h FROM u WHERE username = ${username} AND c = %c",
        paramstyle="numeric",
    )
    assert sql_pg == "SELECT h FROM u WHERE username = $1 AND c = $2"


def test_acl_row_evaluation():
    c = ClientInfo(clientid="dev7", username="u1")
    rows = [
        {"permission": "deny", "action": "publish", "topic": "admin/#"},
        {"permission": "allow", "action": "all",
         "topic": "dev/${clientid}/#"},
        {"permission": "allow", "action": "subscribe",
         "topic": "eq t/+/literal"},
    ]
    assert evaluate_acl_rows(rows, c, PUBLISH, "admin/x") == DENY
    assert evaluate_acl_rows(rows, c, PUBLISH, "dev/dev7/up") == ALLOW
    assert evaluate_acl_rows(rows, c, PUBLISH, "dev/other/up") == IGNORE
    # 'eq ' pins the literal: no wildcard expansion
    assert evaluate_acl_rows(rows, c, SUBSCRIBE, "t/+/literal") == ALLOW
    assert evaluate_acl_rows(rows, c, SUBSCRIBE, "t/x/literal") == IGNORE


# ------------------------------------------------------------ providers

class FakeSql(SqlConnector):
    """In-memory connector: asserts parameterization and serves
    canned rows per (sql, params)."""

    def __init__(self, table):
        self.table = table  # username -> row dict
        self.acl = {}  # username -> rows
        self.queries = []

    async def query(self, sql, params):
        self.queries.append((sql, tuple(params)))
        assert "${" not in sql and "%u" not in sql  # compiled away
        who = params[0]
        if "password_hash" in sql:
            row = self.table.get(who)
            return [row] if row else []
        return list(self.acl.get(who, ()))


def test_sql_authenticator_against_fake():
    async def t():
        fake = FakeSql({
            "alice": {
                "password_hash": hash_password("pw", "sha256", "s1"),
                "salt": "s1",
                "is_superuser": 1,
            },
        })
        authn = SqlAuthenticator(fake, algorithm="sha256")
        d, meta = await authn.authenticate_async(
            ClientInfo(clientid="c", username="alice", password=b"pw"))
        assert d == ALLOW and meta["is_superuser"]
        d, _ = await authn.authenticate_async(
            ClientInfo(clientid="c", username="alice", password=b"no"))
        assert d == DENY
        d, _ = await authn.authenticate_async(
            ClientInfo(clientid="c", username="ghost", password=b"pw"))
        assert d == IGNORE  # unknown user falls through the chain
        # the default query carried the username as a bind param
        assert fake.queries[0][1] == ("alice",)

    run(t())


class FakeRedis:
    def __init__(self, hashes):
        self.hashes = hashes
        self.cmds = []

    async def cmd(self, *args):
        self.cmds.append(args)
        if args[0] == "HMGET":
            h = self.hashes.get(args[1], {})
            return [h.get(f) for f in args[2:]]
        if args[0] == "HGETALL":
            return dict(self.hashes.get(args[1], {}))
        raise AssertionError(args)

    async def close(self):
        pass


def test_redis_providers_against_fake():
    async def t():
        fake = FakeRedis({
            "mqtt_user:bob": {
                "password_hash": hash_password("pw", "sha256", "ns"),
                "salt": "ns",
                "is_superuser": "0",
            },
            "mqtt_acl:bob": {
                "tele/${clientid}/#": "publish",
                "cfg/#": "subscribe",
            },
        })
        authn = RedisAuthenticator(fake)
        d, meta = await authn.authenticate_async(
            ClientInfo(clientid="d1", username="bob", password=b"pw"))
        assert d == ALLOW and not meta["is_superuser"]
        d, _ = await authn.authenticate_async(
            ClientInfo(clientid="d1", username="bob", password=b"x"))
        assert d == DENY
        d, _ = await authn.authenticate_async(
            ClientInfo(clientid="d1", username="nobody", password=b"x"))
        assert d == IGNORE

        authz = RedisAuthorizer(fake)
        c = ClientInfo(clientid="d1", username="bob")
        assert await authz.authorize_async(c, PUBLISH, "tele/d1/up") \
            == ALLOW
        assert await authz.authorize_async(c, PUBLISH, "cfg/x") == IGNORE
        assert await authz.authorize_async(c, SUBSCRIBE, "cfg/x") \
            == ALLOW

    run(t())


def test_broker_prefetches_acl_at_connect():
    """End-to-end over a real socket: the ACL is fetched once at
    CONNECT; subscribe/publish authorization then runs sync off the
    cache (authz_default=deny makes the DB rows load-bearing)."""
    from emqx_tpu.broker.listener import BrokerServer
    from emqx_tpu.config import BrokerConfig, ListenerConfig
    from mqtt_client import TestClient

    async def t():
        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        cfg.auth.authz_default = "deny"
        srv = BrokerServer(cfg)
        await srv.start()
        fake = FakeSql({})
        fake.acl["carol"] = [
            {"permission": "allow", "action": "all",
             "topic": "room/${clientid}/#"},
        ]
        authz = SqlAuthorizer(fake)
        srv.broker.access.db_authz_sources.append(authz)

        c = TestClient(srv.listeners[0].port, "k9")
        await c.connect(username="carol")
        ack = await c.subscribe("room/k9/temp", qos=1)
        assert ack.reason_codes[0] < 0x80
        ack = await c.subscribe("other/t", qos=1)
        assert ack.reason_codes[0] >= 0x80  # not in the ACL: denied
        n_q = len(fake.queries)
        await c.publish("room/k9/temp", b"21", qos=0)
        got = await c.recv_publish()
        assert got.payload == b"21"
        # no further DB round-trips after CONNECT (cache hit path)
        assert len(fake.queries) == n_q
        await c.close()
        await srv.stop()

    run(t())


def test_ipv6_peerhost_and_percent_escaping():
    from emqx_tpu.auth_db import compile_query

    sql, getters = compile_query(
        "SELECT h FROM u WHERE t LIKE 'x/%' AND ip = ${peerhost}"
    )
    assert sql == "SELECT h FROM u WHERE t LIKE 'x/%%' AND ip = %s"
    c = ClientInfo(clientid="c", peerhost="2001:db8::7:51234")
    assert [g(c) for g in getters] == ["2001:db8::7"]


def test_acl_cache_eviction_spares_live_clients():
    from emqx_tpu.access import AccessControl

    ac = AccessControl(authz_default="deny")
    live = {"keep-1", "keep-2"}
    ac.is_live = lambda cid: cid in live
    for i in range(50):
        ac._acl_cache[f"dead-{i}"] = []
    ac._acl_cache["keep-1"] = [{"permission": "allow", "action": "all",
                               "topic": "#"}]
    ac._acl_cache["keep-2"] = []
    ac._evict_acl()
    assert "keep-1" in ac._acl_cache and "keep-2" in ac._acl_cache
    assert not any(k.startswith("dead-") for k in ac._acl_cache)
    # the surviving entry still authorizes
    assert ac.authorize(ClientInfo(clientid="keep-1"), PUBLISH, "t/x")
