"""Window-shaped sink egress (PR 20): micro-batch thresholds, circuit
breaker park/replay, olp flush deferral, manager alarm wiring, and the
chaos seams `resource.batch.flush` / `bridge.mqtt.send`.

The delivery contract under every injected fault is AT-LEAST-ONCE:
error/drop replays the parked window (nothing lost), duplicate
double-delivers (never consumes twice from the buffer)."""

import asyncio
import time

import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.bridge_mqtt import MqttEgressResource
from emqx_tpu.resources import (
    BufferWorker, Resource, ResourceManager,
)


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _clear_failpoints():
    fp.clear()
    yield
    fp.clear()


async def wait_until(cond, timeout=5.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        await asyncio.sleep(0.005)


class BatchSink(Resource):
    """Records every on_query/on_query_batch call; scriptable
    failures and partial consumes."""

    max_batch = 64

    def __init__(self):
        self.batches = []  # list of lists, one per batch call
        self.singles = []
        self.fail_next = 0  # raise on the next N delivery attempts
        self.healthy = True
        self.consume_limit = None  # partial-consume ceiling

    @property
    def received(self):
        out = list(self.singles)
        for b in self.batches:
            out.extend(b)
        return out

    async def on_query(self, query):
        if self.fail_next > 0:
            self.fail_next -= 1
            raise ConnectionError("sink down (scripted)")
        self.singles.append(query)

    async def on_query_batch(self, queries):
        if self.fail_next > 0:
            self.fail_next -= 1
            raise ConnectionError("sink down (scripted)")
        if self.consume_limit is not None:
            queries = queries[: self.consume_limit]
        self.batches.append(list(queries))
        return len(queries)

    async def health_check(self):
        return self.healthy


# ------------------------------------------------------- thresholds


def test_count_threshold_releases_before_age():
    async def t():
        sink = BatchSink()
        w = BufferWorker(
            sink, batch_records=4, batch_age=30.0, retry_base=0.01
        )
        await w.start()
        try:
            for i in range(4):
                w.enqueue(("q", i))
            await wait_until(
                lambda: len(sink.received) == 4, what="flush"
            )
            # one window, not four round-trips, and far before the
            # 30 s age budget
            assert sink.batches == [[("q", 0), ("q", 1),
                                     ("q", 2), ("q", 3)]]
            assert w.stats["batches"] == 1
            assert w.batch_hist.snapshot().count == 1
        finally:
            await w.stop()

    run(t())


def test_byte_threshold_releases_before_age():
    async def t():
        sink = BatchSink()
        w = BufferWorker(
            sink, batch_records=10_000, batch_bytes=64,
            batch_age=30.0, retry_base=0.01,
        )
        await w.start()
        try:
            w.enqueue(b"x" * 100)  # alone crosses 64 bytes
            await wait_until(
                lambda: len(sink.received) == 1, what="flush"
            )
            assert w.stats["batches"] == 1
        finally:
            await w.stop()

    run(t())


def test_age_threshold_flushes_partial_batch():
    async def t():
        sink = BatchSink()
        w = BufferWorker(
            sink, batch_records=1000, batch_age=0.03,
            retry_base=0.01,
        )
        await w.start()
        try:
            w.enqueue("a")
            w.enqueue("b")
            await asyncio.sleep(0.01)
            assert sink.received == []  # still lingering
            await wait_until(
                lambda: len(sink.received) == 2, what="age flush"
            )
            assert sink.batches == [["a", "b"]]
        finally:
            await w.stop()

    run(t())


def test_enqueue_batch_drop_oldest_and_edge_event():
    async def t():
        sink = BatchSink()
        sink.healthy = False
        w = BufferWorker(
            sink, max_buffer=5, batch_age=30.0, batch_records=1000
        )
        edges = []
        w.on_queue_full = edges.append
        dropped = w.enqueue_batch([f"q{i}" for i in range(8)])
        assert dropped == 3
        assert list(w._buf) == ["q3", "q4", "q5", "q6", "q7"]
        assert w.stats["dropped"] == 3
        assert w.stats["matched"] == 8
        # edge-triggered: ONE event per excursion, not per drop
        assert edges == [3]
        w.enqueue_batch(["q8"])
        assert edges == [3]
        assert w.enqueue_batch([]) == 0

    run(t())


# -------------------------------------------- breaker park + replay


def test_breaker_opens_parks_and_replays_on_probe():
    async def t():
        sink = BatchSink()
        w = BufferWorker(
            sink, batch_records=2, batch_age=0.005,
            breaker_threshold=3, retry_base=0.001,
            health_interval=0.02,
        )
        edges = []
        w.on_breaker_edge = edges.append
        await w.start()
        try:
            sink.fail_next = 10**9
            sink.healthy = False
            for i in range(6):
                w.enqueue(i)
            await wait_until(
                lambda: w.breaker_open, what="breaker open"
            )
            assert edges == [True]
            assert w.stats["breaker_opens"] == 1
            assert len(w) == 6  # everything parked, nothing dropped
            attempts_when_open = sink.fail_next
            await asyncio.sleep(0.05)
            # parked: the drain loop probes health, it does NOT keep
            # hammering the sink with deliveries
            assert sink.fail_next == attempts_when_open
            assert w.breaker_open
            # heal: the probe re-closes and the whole backlog replays
            sink.fail_next = 0
            sink.healthy = True
            await wait_until(
                lambda: len(sink.received) == 6, what="replay"
            )
            assert not w.breaker_open
            assert edges == [True, False]
            assert sorted(sink.received) == list(range(6))
            assert w.stats["dropped"] == 0 and w.stats["failed"] == 0
        finally:
            await w.stop()

    run(t())


def test_max_retries_drop_path_still_works_without_breaker():
    async def t():
        sink = BatchSink()
        sink.max_batch = 1  # scalar path
        w = BufferWorker(
            sink, max_retries=2, retry_base=0.001, retry_cap=0.002
        )
        await w.start()
        try:
            sink.fail_next = 10**9
            w.enqueue("doomed")
            await wait_until(
                lambda: w.stats["failed"] == 1, what="retry drop"
            )
            assert len(w) == 0
        finally:
            await w.stop()

    run(t())


# ------------------------------------------------ olp flush deferral


def test_defer_flush_stretches_age_linger():
    async def t():
        sink = BatchSink()
        defer = {"on": True}
        w = BufferWorker(
            sink, batch_records=1000, batch_age=0.04,
            defer_flush=lambda: defer["on"], retry_base=0.01,
        )
        noted = []
        w.on_flush_deferred = lambda: noted.append(1)
        await w.start()
        try:
            w.enqueue("held")
            await asyncio.sleep(0.08)  # past batch_age, inside 4x
            assert sink.received == []
            assert w.stats["flush_deferred"] == 1
            assert noted == [1]  # one event per pending batch
            defer["on"] = False  # ladder cleared -> flush promptly
            await wait_until(
                lambda: sink.received == ["held"], what="flush"
            )
            # stretched age is CAPPED: even a stuck ladder flushes
            w.enqueue("capped")
            defer["on"] = True
            await wait_until(
                lambda: "capped" in sink.received, timeout=1.0,
                what="capped flush",
            )
            assert w.stats["flush_deferred"] == 2
        finally:
            await w.stop()

    run(t())


# --------------------------------------------- manager hook wiring


class FakeAlarms:
    def __init__(self):
        self.active = {}
        self.log = []

    def activate(self, name, details=None, message=""):
        self.active[name] = message
        self.log.append(("activate", name))

    def deactivate(self, name):
        self.active.pop(name, None)
        self.log.append(("deactivate", name))


class FakeFlight:
    def __init__(self):
        self.edges = []
        self.notes = []

    def breaker_edge(self, opened, info):
        self.edges.append((opened, dict(info)))

    def note(self, kind, **fields):
        self.notes.append((kind, fields))


class FakeMetrics:
    def __init__(self):
        self.counts = {}

    def inc(self, name, by=1):
        self.counts[name] = self.counts.get(name, 0) + by


def test_manager_wires_breaker_alarm_flight_and_olp_counter():
    async def t():
        mgr = ResourceManager(alarms=FakeAlarms())
        mgr.flight = FakeFlight()
        mgr.metrics = FakeMetrics()
        sink = BatchSink()
        w = await mgr.create(
            "k1", sink, batch_records=2, batch_age=0.005,
            breaker_threshold=2, retry_base=0.001,
            health_interval=0.02, max_buffer=4,
        )
        try:
            sink.fail_next = 10**9
            sink.healthy = False
            w.enqueue("a")
            await wait_until(
                lambda: w.breaker_open, what="breaker open"
            )
            assert "sink_breaker:k1" in mgr.alarms.active
            assert mgr.flight.edges == [(True, {"sink": "k1"})]
            # queue-full excursion lands in the black box
            for i in range(9):
                w.enqueue(i)
            assert mgr.flight.notes[0][0] == "sink_queue_full"
            assert mgr.flight.notes[0][1]["sink"] == "k1"
            sink.fail_next = 0
            sink.healthy = True
            await wait_until(
                lambda: not w.breaker_open, what="breaker close"
            )
            assert "sink_breaker:k1" not in mgr.alarms.active
            assert mgr.flight.edges[-1] == (False, {"sink": "k1"})
            # info()/summary() expose the batch shape, JSON-safe
            import json as _j
            info = mgr.info()["k1"]
            _j.dumps(info)
            assert set(info["batch_size"]) == {
                "count", "p50", "p95", "p99"
            }
            assert mgr.summary()["sinks"] == 1
        finally:
            await mgr.stop_all()
        # removal cleared the down-alarm too
        assert "resource_down:k1" not in mgr.alarms.active

    run(t())


def test_manager_flush_deferred_counts_olp_metric():
    async def t():
        mgr = ResourceManager()
        mgr.metrics = FakeMetrics()

        class Olp:
            defer_sink_flush = True

        mgr.olp = Olp()
        sink = BatchSink()
        w = await mgr.create(
            "k2", sink, batch_records=1000, batch_age=0.02,
        )
        try:
            w.enqueue("x")
            await wait_until(
                lambda: sink.received == ["x"], what="capped flush"
            )
            assert (
                mgr.metrics.counts["olp.deferred.sink_flush"] == 1
            )
            assert w.stats["flush_deferred"] == 1
        finally:
            await mgr.stop_all()

    run(t())


# --------------------------------- chaos: resource.batch.flush seam


def test_chaos_batch_flush_error_retries_without_loss():
    async def t():
        sink = BatchSink()
        w = BufferWorker(
            sink, batch_records=4, batch_age=0.005,
            retry_base=0.001, retry_cap=0.002,
        )
        await w.start()
        try:
            fp.configure(
                "resource.batch.flush", "error", times=3
            )
            for i in range(4):
                w.enqueue(i)
            await wait_until(
                lambda: len(sink.received) == 4, what="delivery"
            )
            assert sink.received == [0, 1, 2, 3]
            assert w.stats["retried"] == 3
            assert w.stats["dropped"] == 0
            assert w.stats["failed"] == 0
        finally:
            await w.stop()

    run(t())


def test_chaos_batch_flush_drop_replays_whole_window():
    async def t():
        sink = BatchSink()
        w = BufferWorker(
            sink, batch_records=3, batch_age=0.005,
            retry_base=0.001,
        )
        await w.start()
        try:
            fp.configure("resource.batch.flush", "drop", times=1)
            for i in range(3):
                w.enqueue(i)
            await wait_until(
                lambda: len(sink.received) == 3, what="replay"
            )
            # the dropped flush never reached the sink; the replay
            # delivered the SAME window once — no loss, no dup
            assert sink.batches == [[0, 1, 2]]
            assert w.stats["retried"] == 1
        finally:
            await w.stop()

    run(t())


def test_chaos_batch_flush_duplicate_is_at_least_once():
    async def t():
        sink = BatchSink()
        w = BufferWorker(
            sink, batch_records=3, batch_age=0.005,
            retry_base=0.001,
        )
        await w.start()
        try:
            fp.configure(
                "resource.batch.flush", "duplicate", times=1
            )
            for i in range(3):
                w.enqueue(i)
            await wait_until(
                lambda: len(sink.batches) >= 2, what="dup delivery"
            )
            await asyncio.sleep(0.02)
            # delivered twice, but consumed from the buffer ONCE
            assert sink.batches == [[0, 1, 2], [0, 1, 2]]
            assert len(w) == 0
            assert w.stats["success"] == 3
            assert w.stats["dropped"] == 0
        finally:
            await w.stop()

    run(t())


def test_chaos_partial_consume_replays_tail():
    async def t():
        sink = BatchSink()
        w = BufferWorker(
            sink, batch_records=4, batch_age=0.005,
            retry_base=0.001,
        )
        await w.start()
        try:
            sink.consume_limit = 3  # sink takes 3 of the 4
            for i in range(4):
                w.enqueue(i)
            await wait_until(
                lambda: len(sink.batches) >= 1, what="first flush"
            )
            sink.consume_limit = None
            await wait_until(
                lambda: len(w) == 0, what="tail replay"
            )
            assert sink.batches[0] == [0, 1, 2]
            assert sink.batches[1] == [3]  # tail replayed, no loss
        finally:
            await w.stop()

    run(t())


# ------------------------------------ chaos: bridge.mqtt.send seam


class StubMqttClient:
    """Duck-typed MqttClient: records publishes, scriptable per-call
    failures, so the egress window semantics are tested without a
    socket."""

    def __init__(self, client_id="eg1"):
        self.client_id = client_id
        self.connected = asyncio.Event()
        self.connected.set()
        self.published = []
        self.fail_topics = set()

    async def publish(self, topic, payload, qos=0, retain=False):
        await asyncio.sleep(0)
        if topic in self.fail_topics:
            raise ConnectionError(f"publish {topic} failed")
        self.published.append((topic, payload, qos, retain))

    async def start(self):
        pass

    async def stop(self):
        pass


def _egress(client):
    res = MqttEgressResource.__new__(MqttEgressResource)
    res.client = client
    return res


def test_bridge_window_prefix_consume_and_replay():
    async def t():
        client = StubMqttClient()
        res = _egress(client)
        w = BufferWorker(
            res, batch_records=3, batch_age=0.005,
            retry_base=0.001,
        )
        await w.start()
        try:
            client.fail_topics.add("t/1")
            w.enqueue(("t/0", b"a", 1, False))
            w.enqueue(("t/1", b"b", 1, False))
            w.enqueue(("t/2", b"c", 1, False))
            await wait_until(
                lambda: len(client.published) >= 2,
                what="first window",
            )
            client.fail_topics.clear()
            await wait_until(lambda: len(w) == 0, what="replay")
            topics = [t for t, _, _, _ in client.published]
            # prefix consumed; the failed message and its tail
            # replayed — at-least-once, nothing lost
            assert topics.count("t/0") >= 1
            assert topics.count("t/1") == 1
            assert topics.count("t/2") >= 1
            assert w.stats["dropped"] == 0
        finally:
            await w.stop()

    run(t())


def test_bridge_send_chaos_drop_and_duplicate():
    async def t():
        client = StubMqttClient()
        res = _egress(client)
        w = BufferWorker(
            res, batch_records=2, batch_age=0.005,
            retry_base=0.001,
        )
        await w.start()
        try:
            fp.configure(
                "bridge.mqtt.send", "drop", times=1,
                match="eg1",
            )
            w.enqueue(("t/a", b"1", 0, False))
            w.enqueue(("t/b", b"2", 0, False))
            await wait_until(lambda: len(w) == 0, what="replay")
            topics = [t for t, _, _, _ in client.published]
            # drop claims 0 consumed -> worker replays; exactly one
            # real delivery
            assert topics == ["t/a", "t/b"]
            assert w.stats["retried"] == 1

            fp.clear()
            fp.configure(
                "bridge.mqtt.send", "duplicate", times=1,
                match="eg1",
            )
            client.published.clear()
            w.enqueue(("t/c", b"3", 0, False))
            w.enqueue(("t/d", b"4", 0, False))
            await wait_until(
                lambda: len(client.published) >= 4, what="dup"
            )
            topics = [t for t, _, _, _ in client.published]
            assert topics == ["t/c", "t/d", "t/c", "t/d"]
            assert len(w) == 0  # consumed once despite double send
        finally:
            await w.stop()

    run(t())


def test_bridge_send_chaos_keyed_to_other_client_is_inert():
    async def t():
        client = StubMqttClient(client_id="eg1")
        res = _egress(client)
        w = BufferWorker(
            res, batch_records=1, batch_age=0.005, retry_base=0.001
        )
        await w.start()
        try:
            fp.configure(
                "bridge.mqtt.send", "drop", match="other-bridge"
            )
            w.enqueue(("t/x", b"p", 0, False))
            await wait_until(lambda: len(w) == 0, what="send")
            assert [t for t, _, _, _ in client.published] == ["t/x"]
            assert w.stats["retried"] == 0
        finally:
            await w.stop()

    run(t())
