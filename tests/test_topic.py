"""Topic semantics tests — case set mirrors the reference's
emqx_topic_SUITE coverage (match/validate/words/$share)."""

import pytest

from emqx_tpu import topic as T


def test_words():
    assert T.words("a/b/c") == ("a", "b", "c")
    assert T.words("a//b") == ("a", "", "b")
    assert T.words("/a") == ("", "a")
    assert T.words("a/") == ("a", "")
    assert T.words("a") == ("a",)
    assert T.levels("a/b/c") == 3
    assert T.levels("/") == 2


MATCH_CASES = [
    # (name, filter, expected)
    ("a/b/c", "a/b/c", True),
    ("a/b/c", "a/+/c", True),
    ("a/b/c", "a/#", True),
    ("a/b/c", "#", True),
    ("a/b/c", "+/+/+", True),
    ("a/b/c", "a/b", False),
    ("a/b/c", "a/b/c/d", False),
    ("a/b/c", "a/+", False),
    ("a/b/c", "+", False),
    ("a/b/c", "b/+/c", False),
    # '#' matches the parent level itself
    ("sport", "sport/#", True),
    ("sport/tennis", "sport/#", True),
    ("sport", "sport/+", False),
    # '+' matches empty levels
    ("a//c", "a/+/c", True),
    ("/b", "+/b", True),
    ("/", "+/+", True),
    ("/", "#", True),
    ("a/", "a/+", True),
    # '$' topics: no root wildcard match
    ("$SYS/broker", "#", False),
    ("$SYS/broker", "+/broker", False),
    ("$SYS/broker", "$SYS/#", True),
    ("$SYS/broker", "$SYS/+", True),
    ("$SYS/a/b", "$SYS/+/b", True),
    ("$SYS", "#", False),
    # '$' deeper than root is ordinary
    ("a/$SYS/b", "a/+/b", True),
    ("a/$x", "a/#", True),
    # exactness
    ("a/B", "a/b", False),
    ("aa/b", "a/b", False),
]


@pytest.mark.parametrize("name,flt,exp", MATCH_CASES)
def test_match(name, flt, exp):
    assert T.match(name, flt) is exp


def test_is_wildcard():
    assert T.is_wildcard("a/+/b")
    assert T.is_wildcard("#")
    assert not T.is_wildcard("a/b")
    # '+' embedded in a word is not a wildcard level (it is invalid, but
    # wildcard detection is level-wise like emqx_topic:wildcard/1)
    assert not T.is_wildcard("a+b/c")


def test_validate_name():
    T.validate_name("a/b/c")
    T.validate_name("$SYS/x")
    with pytest.raises(ValueError):
        T.validate_name("a/+/b")
    with pytest.raises(ValueError):
        T.validate_name("a/#")
    with pytest.raises(ValueError):
        T.validate_name("")
    with pytest.raises(ValueError):
        T.validate_name("a\x00b")
    with pytest.raises(ValueError):
        T.validate_name("x" * 70000)


def test_validate_filter():
    T.validate_filter("a/+/b")
    T.validate_filter("a/#")
    T.validate_filter("#")
    T.validate_filter("+")
    T.validate_filter("/")
    with pytest.raises(ValueError):
        T.validate_filter("a/#/b")  # '#' not last
    with pytest.raises(ValueError):
        T.validate_filter("a/b#")  # '#' not whole level
    with pytest.raises(ValueError):
        T.validate_filter("a/b+/c")  # '+' not whole level
    with pytest.raises(ValueError):
        T.validate_filter("")


def test_share_parse():
    s = T.parse_share("$share/g1/a/b/+")
    assert s == T.SharedFilter("g1", "a/b/+")
    assert T.parse_share("a/b") is None
    assert T.real_topic("$share/g/t") == "t"
    assert T.real_topic("t/x") == "t/x"
    with pytest.raises(ValueError):
        T.parse_share("$share/g")  # no topic
    with pytest.raises(ValueError):
        T.parse_share("$share//t")  # empty group
    with pytest.raises(ValueError):
        T.parse_share("$share/g+/t")  # wildcard group
    with pytest.raises(ValueError):
        T.parse_share("$share/g/$share/h/t")  # nested


def test_validate_shared_filter():
    T.validate_filter("$share/group/a/+/b")
    with pytest.raises(ValueError):
        T.validate_filter("$share/gr/")
