"""Chaos: failure-driven device→host degradation of the match engine.

With a failpoint forcing 100% device-step errors, the broker must keep
delivering QoS1 traffic on the host path, trip the device-path circuit
breaker (raising the ``engine_device_path`` $SYS alarm), and — once the
fault clears — re-close the breaker via the background probe and
deactivate the alarm.  Engine-level tests pin the mechanics (trip
threshold, host fallback correctness, watchdog deadline, probe
re-close); the broker test asserts the end-to-end acceptance
invariant."""

import asyncio
import json
import time

import pytest

from emqx_tpu import failpoints as fp
from emqx_tpu.engine import MatchEngine


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def _clean_failpoints():
    fp.clear()
    yield
    fp.clear()


def make_engine(n=64, **kw):
    eng = MatchEngine(use_device=True, **kw)
    for i in range(n):
        eng.insert(f"dev/{i}/+", f"w{i}")
    eng.insert("exact/topic", "e0")
    eng.rebuild()
    return eng


def wait_until(cond, timeout=5.0, what="condition"):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < timeout, f"timeout: {what}"
        time.sleep(0.01)


# ----------------------------------------------------------- engine

def test_device_errors_fall_back_to_host_and_trip_breaker():
    eng = make_engine()
    trips, clears = [], []
    eng.on_breaker_trip = trips.append
    eng.on_breaker_clear = clears.append
    eng.breaker_threshold = 3
    eng.breaker_probe_interval = 3600.0  # no probe during this test

    fp.configure("engine.device_step", "error")
    for k in range(6):
        out = eng.match_batch([f"dev/{k}/x", "exact/topic", "none/y"])
        # every window is served EXACTLY on the host oracle
        assert out[0] == {f"w{k}"}
        assert out[1] == {"e0"}
        assert out[2] == set()
    assert eng.breaker_info()["open"] is True
    assert len(trips) == 1 and trips[0]["failures"] == 3
    # after the trip the device path is not attempted: the failpoint
    # stops firing and device_errors stays at the trip count
    errs = eng.breaker_info()["device_errors"]
    eng.match_batch(["dev/0/x"])
    assert eng.breaker_info()["device_errors"] == errs
    assert clears == []


def test_probe_recloses_breaker_after_fault_clears():
    eng = make_engine()
    clears = []
    eng.on_breaker_clear = clears.append
    eng.breaker_threshold = 2
    eng.breaker_probe_interval = 3600.0
    fp.configure("engine.device_step", "error")
    for _ in range(3):
        eng.match_batch(["dev/1/x"])
    assert eng.breaker_info()["open"]

    # fault persists: the probe fails and the breaker stays open
    eng.breaker_probe_interval = 0.0
    eng.match_batch(["dev/1/x"])  # host window schedules a probe
    wait_until(lambda: eng.breaker_info()["probes"] >= 1, what="probe")
    wait_until(lambda: not eng._brk_probing, what="probe done")
    assert eng.breaker_info()["open"]

    # fault clears: the next probe closes it and matching returns to
    # the device path
    fp.clear("engine.device_step")
    eng.match_batch(["dev/1/x"])
    wait_until(lambda: not eng.breaker_info()["open"], what="re-close")
    # the probe thread flips `open` BEFORE it runs the clear callback:
    # waiting on the flag alone races the callback (observed flaky
    # under load) — wait for the callback itself
    wait_until(lambda: len(clears) == 1, what="clear callback")
    assert eng.match_batch(["dev/2/x"])[0] == {"w2"}
    assert eng.breaker_info()["consecutive_failures"] == 0


def test_watchdog_deadline_counts_slow_windows():
    """A device window that RETURNS but blows the watchdog deadline is
    breaker food too — a wedged tunnel degrades to host-only without a
    single exception being raised."""
    eng = make_engine()
    eng.breaker_threshold = 2
    eng.breaker_probe_interval = 3600.0
    eng.breaker_deadline = 0.01
    fp.configure("engine.device_step", "delay", delay=0.05)
    out1 = eng.match_batch(["dev/3/x"])
    out2 = eng.match_batch(["dev/4/x"])
    assert out1[0] == {"w3"} and out2[0] == {"w4"}
    info = eng.breaker_info()
    assert info["slow_windows"] >= 2 and info["open"] is True


def test_insert_delete_keep_working_while_tripped():
    """Degraded mode is not read-only: churn lands in the host tiers
    and matches correctly while the breaker is open."""
    eng = make_engine()
    eng.breaker_threshold = 1
    eng.breaker_probe_interval = 3600.0
    fp.configure("engine.device_step", "error")
    eng.match_batch(["dev/0/x"])
    assert eng.breaker_info()["open"]
    eng.insert("new/+/sub", "n1")
    eng.delete("w5")
    out = eng.match_batch(["new/a/sub", "dev/5/x", "dev/6/x"])
    assert out[0] == {"n1"} and out[1] == set() and out[2] == {"w6"}


# ----------------------------------------------------------- broker

def test_broker_survives_total_device_failure_qos1():
    """The acceptance invariant: 100% device-step errors; QoS1 traffic
    keeps flowing (host path), the $SYS alarm raises on trip and
    clears after the probe re-closes the breaker."""

    async def t():
        from emqx_tpu.broker.listener import BrokerServer
        from emqx_tpu.config import BrokerConfig, ListenerConfig
        from mqtt_client import TestClient

        cfg = BrokerConfig()
        cfg.listeners = [ListenerConfig(port=0)]
        srv = BrokerServer(cfg)
        await srv.start()
        broker = srv.broker
        eng = broker.router.engine
        eng.use_device = True  # pin: every window attempts the device
        eng.breaker_threshold = 3
        eng.breaker_probe_interval = 3600.0
        port = srv.listeners[0].port

        mon = TestClient(port, "mon")
        await mon.connect()
        await mon.subscribe("$SYS/brokers/+/alarms/#")
        sub = TestClient(port, "sub")
        await sub.connect()
        await sub.subscribe("chaos/+/q", qos=1)
        # build the device automaton so the device path is live
        eng.rebuild()
        assert eng._aut is not None and eng._aut.n_nodes > 1

        fp.configure("engine.device_step", "error")
        for i in range(8):
            # QoS1 publish acks only after dispatch: delivery rides
            # the host fallback while every device window errors
            await pub_one(srv, port, i)
        got = set()
        for _ in range(8):
            pkt = await sub.recv_publish(timeout=5)
            got.add(pkt.topic)
        assert got == {f"chaos/{i}/q" for i in range(8)}

        # breaker tripped and the $SYS alarm is active + published
        assert eng.breaker_info()["open"] is True
        deadline = asyncio.get_event_loop().time() + 5
        while not any(
            a.name == "engine_device_path"
            for a in broker.alarms.active()
        ):
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.02)
        alarm_pkt = await mon.recv_publish(timeout=5)
        assert alarm_pkt.topic.endswith("/alarms/activate")
        assert json.loads(alarm_pkt.payload)["name"] == \
            "engine_device_path"
        assert broker.metrics.val("engine.breaker.trip") == 1

        # fault clears: probe re-closes, alarm deactivates, traffic
        # still exact
        fp.clear("engine.device_step")
        eng.breaker_probe_interval = 0.0
        await pub_one(srv, port, 8)
        deadline = asyncio.get_event_loop().time() + 5
        while eng.breaker_info()["open"]:
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.02)
        clear_pkt = await mon.recv_publish(timeout=5)
        assert clear_pkt.topic.endswith("/alarms/deactivate")
        assert not any(
            a.name == "engine_device_path"
            for a in broker.alarms.active()
        )
        pkt = await sub.recv_publish(timeout=5)
        assert pkt.topic == "chaos/8/q"
        assert broker.metrics.val("engine.breaker.clear") == 1

        await sub.disconnect()
        await mon.disconnect()
        await srv.stop()

    async def pub_one(srv, port, i):
        from mqtt_client import TestClient

        pub = TestClient(port, f"pub{i}")
        await pub.connect()
        await pub.publish(f"chaos/{i}/q", b"payload", qos=1)
        await pub.disconnect()

    run(t())
