"""Property tests: quic/recovery.py under adversarial ACK delivery.

The recovery model's contract, exercised with seeded reordered,
duplicated, and delayed ack ranges over randomized packetizations:

  * the contiguous-prefix watermark NEVER regresses;
  * no range is retransmitted after it was acked (a spurious-loss ack
    beats a queued retransmit);
  * PTO requeues EXACTLY the unacked ranges — nothing acked, nothing
    missing.

Crypto-free by design (recovery.py's whole point), so this runs in
the tier-1 environment."""

import random

from emqx_tpu.quic.recovery import (
    RangeTracker, RecoverySpace, SentPacket,
)


def _overlaps(a, b):
    return a[0] < b[1] and b[0] < a[1]


def _ranges_union_len(ranges):
    total = 0
    last = -1
    for s, e in sorted(ranges):
        s = max(s, last)
        if e > s:
            total += e - s
            last = e
    return total


# ------------------------------------------------------- RangeTracker


def test_range_tracker_matches_reference_set():
    """`add`/`contiguous_from`/`missing_within` agree with a byte-set
    reference model under random merges."""
    for seed in range(6):
        rng = random.Random(seed)
        rt = RangeTracker()
        ref = set()
        for _ in range(200):
            s = rng.randrange(0, 2000)
            e = s + rng.randrange(0, 60)
            rt.add(s, e)
            ref.update(range(s, e))
            # contiguous watermark from 0 == longest prefix in ref
            wm = rt.contiguous_from(0)
            expect = 0
            while expect in ref:
                expect += 1
            assert wm == expect
            # missing_within on a random window == ref complement
            lo = rng.randrange(0, 2000)
            hi = lo + rng.randrange(1, 200)
            missing = set()
            for ms, me in rt.missing_within(lo, hi):
                missing.update(range(ms, me))
            assert missing == {
                b for b in range(lo, hi) if b not in ref
            }
        # ranges stay sorted + disjoint
        for (s1, e1), (s2, e2) in zip(rt.ranges, rt.ranges[1:]):
            assert e1 < s2 and s1 < e1


def test_range_tracker_prune_below_keeps_tail_exact():
    rt = RangeTracker()
    rt.add(0, 10)
    rt.add(20, 30)
    rt.add(40, 50)
    rt.prune_below(25)
    assert rt.ranges == [(25, 30), (40, 50)]
    assert rt.missing_within(25, 50) == [(30, 40)]


# ---------------------------------------------- adversarial delivery


def _world(seed):
    """One seeded sender world: randomized packetization of a crypto
    stream, an adversarial ack schedule (reordered, duplicated, a
    delayed tail), interleaved threshold-loss + retransmission, then
    a PTO sweep.  Returns nothing — asserts the three invariants
    inline."""
    rng = random.Random(seed)
    space = RecoverySpace()
    total = 0
    next_pn = 0

    def send(ranges):
        nonlocal next_pn
        pkt = SentPacket()
        pkt.crypto.extend(ranges)
        space.record(next_pn, pkt)
        next_pn += 1
        return next_pn - 1

    # initial flight: contiguous stream in random-size packets
    pns = []
    while total < 20_000:
        n = rng.randrange(200, 1400)
        pns.append(send([(total, total + n)]))
        total += n

    # adversarial schedule: shuffle, duplicate ~20%, delay ~10% to
    # the very end, and never ack ~15% at all
    never = set(rng.sample(pns, len(pns) * 15 // 100))
    order = [pn for pn in pns if pn not in never]
    rng.shuffle(order)
    delayed = set(rng.sample(order, len(order) // 10))
    schedule = [pn for pn in order if pn not in delayed]
    schedule += [
        schedule[i]
        for i in rng.sample(range(len(schedule)), len(schedule) // 5)
    ]  # duplicates

    watermark = 0
    retransmitted = []  # (range, acked_snapshot) at queue time
    for i, pn in enumerate(schedule):
        space.on_ack_range(pn, pn)
        wm = space.crypto_acked.contiguous_from(0)
        assert wm >= watermark, "watermark regressed"
        watermark = wm
        if i % 7 == 3:
            # threshold loss detection + retransmission round
            lost = space.detect_lost()
            space.queue_crypto_retx(
                [r for p in lost for r in p.crypto]
            )
            for r in space.take_crypto_retx():
                # invariant: nothing acked is ever retransmitted
                for a in space.crypto_acked.ranges:
                    assert not _overlaps(r, a), (
                        f"acked range {a} retransmitted as {r}"
                    )
                retransmitted.append(r)
                send([r])  # the retransmit goes back in flight

    # delayed acks land AFTER loss declared them missing: the re-check
    # in take_crypto_retx must drop them (ack beats retransmit)
    for pn in delayed:
        space.on_ack_range(pn, pn)
        wm = space.crypto_acked.contiguous_from(0)
        assert wm >= watermark
        watermark = wm
    lost = space.detect_lost()
    space.queue_crypto_retx([r for p in lost for r in p.crypto])
    for r in space.take_crypto_retx():
        for a in space.crypto_acked.ranges:
            assert not _overlaps(r, a)
        send([r])

    # PTO sweep: requeued ranges must be EXACTLY the unacked bytes
    # still in flight — compare against the tracker's own complement
    lost = space.on_pto()
    assert not space.sent  # everything in flight was declared lost
    inflight_ranges = [r for p in lost for r in p.crypto]
    space.queue_crypto_retx(inflight_ranges)
    requeued = space.take_crypto_retx()
    expect = []
    for r in inflight_ranges:
        expect.extend(space.crypto_acked.missing_within(*r))
    assert _ranges_union_len(requeued) == _ranges_union_len(expect)
    for r in requeued:
        for a in space.crypto_acked.ranges:
            assert not _overlaps(r, a)
    # and the unacked tail is fully covered: requeued ∪ acked ⊇ every
    # byte the never-acked packets carried
    covered = RangeTracker()
    for s, e in requeued:
        covered.add(s, e)
    for a_s, a_e in space.crypto_acked.ranges:
        covered.add(a_s, a_e)
    assert covered.missing_within(0, total) == [], (
        "PTO requeue left a hole"
    )


def test_adversarial_ack_delivery_six_seeds():
    for seed in (1, 7, 42, 1337, 20260804, 9):
        _world(seed)


def test_duplicate_ack_is_idempotent():
    """Acking the same pn twice releases its record once and changes
    nothing the second time."""
    space = RecoverySpace()
    pkt = SentPacket()
    pkt.crypto.append((0, 100))
    space.record(0, pkt)
    assert len(space.on_ack_range(0, 0)) == 1
    assert space.on_ack_range(0, 0) == []
    assert space.crypto_acked.ranges == [(0, 100)]


def test_pto_then_late_ack_suppresses_retransmit():
    """A PTO declares a packet lost; its ack lands before the flush —
    the re-filter in take_crypto_retx must retransmit nothing."""
    space = RecoverySpace()
    pkt = SentPacket()
    pkt.crypto.append((0, 500))
    space.record(0, pkt)
    lost = space.on_pto()
    space.queue_crypto_retx([r for p in lost for r in p.crypto])
    space.crypto_acked.add(0, 500)  # the "spurious loss" ack arrives
    assert space.take_crypto_retx() == []
