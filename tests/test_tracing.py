"""End-to-end message-lifecycle tracing (PR 8): head-sampled trace
contexts through the batched hot path, across cluster links and
multicore-style worker hops.

The referees:
  * sampler/store units (seeded determinism, whole-trace FIFO
    eviction, message-id index hygiene);
  * local publish→dispatch spans cut from the window profiler's
    timestamps, queryable by trace id AND message id over REST;
  * the acceptance hop — a publish on node A delivered via cluster
    forward on node B yields ONE connected trace (B's dispatch span
    parents to A's forward span) and a merged Perfetto timeline with
    both nodes as distinct processes linked by a flow event; the same
    shape for worker-labeled nodes (the multicore hop rides the same
    inter-node transport);
  * chaos: with the cluster.link.forward failpoint eating egress,
    publisher-side traces still CLOSE and the bounded store never
    leaks (and spans never hold payload bytes);
  * the hot-path bargain: sampling off (rate=0) is byte-identical on
    every connection's wire vs. tracing disabled, adds zero store
    entries and zero per-message objects, and a paired A/B fanout-256
    run stays within noise.
"""

import asyncio
import json
import time

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.broker.session import SubOpts
from emqx_tpu.cluster import ClusterNode
from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig
from emqx_tpu.message import Message
from emqx_tpu import failpoints
from emqx_tpu.tracecontext import (
    TRACE_PROP,
    HeadSampler,
    TraceStore,
    chrome_trace,
    decode_ctx,
    encode_ctx,
    extract_strip,
    inject_props,
)
from mqtt_client import TestClient


def run(coro):
    return asyncio.run(coro)


def _cfg(enable=True, rate=1.0, filters=(), seed=7, store_max=512):
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.tracing.enable = enable
    cfg.tracing.sample_rate = rate
    cfg.tracing.topic_filters = list(filters)
    cfg.tracing.seed = seed
    cfg.tracing.store_max = store_max
    return cfg


class WireChannel(Channel):
    """Real Channel over a capturing transport (true wire bytes, true
    cork behavior), as in test_dispatch_native."""

    def __init__(self, broker, version=C.MQTT_V5):
        self.writes = []

        def send(pkts):
            self.writes.append(
                b"".join(C.serialize(p, self.version) for p in pkts)
            )

        super().__init__(broker, send=send, close=lambda r: None)
        self.version = version


def _fanout_broker(cfg, n_subs=3, flt="t/#", qos=0):
    b = Broker(config=cfg)
    chans = {}
    for i in range(n_subs):
        ch = WireChannel(b)
        cid = f"c{i}"
        session, _ = b.cm.open_session(True, cid, ch)
        session.subscribe(flt, SubOpts(qos=qos))
        b.subscribe(cid, flt, SubOpts(qos=qos))
        chans[cid] = ch
    return b, chans


# ------------------------------------------------------------ sampler


def test_sampler_rate_and_filters():
    off = HeadSampler(rate=0.0)
    assert not off.active
    assert not off.decide("t/x")
    always = HeadSampler(rate=1.0)
    assert always.decide("t/x")
    # rate-sampling skips $-reserved topics (broker plumbing)...
    assert not always.decide("$SYS/brokers")
    # ...but an explicit topic filter still pins them
    pinned = HeadSampler(rate=0.0, topic_filters=["$SYS/#", "fleet/+/t"])
    assert pinned.active
    assert pinned.decide("$SYS/brokers")
    assert pinned.decide("fleet/v9/t")
    assert not pinned.decide("fleet/v9/other")


def test_sampler_seeded_determinism():
    a = HeadSampler(rate=0.3, seed=42)
    b = HeadSampler(rate=0.3, seed=42)
    decisions_a = [a.decide(f"t/{i}") for i in range(200)]
    decisions_b = [b.decide(f"t/{i}") for i in range(200)]
    assert decisions_a == decisions_b
    assert any(decisions_a) and not all(decisions_a)
    assert a.span_id() == b.span_id()
    assert a.trace_id() == b.trace_id()


def test_context_codec_roundtrip_and_strip():
    props = {"user_property": [("k", "v")]}
    inject_props(props, "a" * 32, "b" * 16)
    assert (TRACE_PROP, encode_ctx("a" * 32, "b" * 16)) \
        in props["user_property"]
    # list-shaped pairs (the binary wire JSON round-trip) decode too
    props["user_property"] = [
        list(p) for p in props["user_property"]
    ]
    got = extract_strip(props)
    assert got == ("a" * 32, "b" * 16)
    # only the carrier pair is stripped; foreign pairs survive
    assert props["user_property"] == [["k", "v"]]
    # absent/foreign-only properties: untouched, None
    assert extract_strip(props) is None
    assert decode_ctx("junk") is None


def test_store_bounded_eviction_with_mid_index():
    store = TraceStore(max_traces=4)
    for i in range(10):
        store.add({
            "trace_id": f"{i:032x}", "span_id": f"{i:016x}",
            "parent_id": None, "name": "message.publish",
            "node": "n", "start_ns": i, "end_ns": i + 1,
            "mid": f"{i:08x}", "attrs": {"topic": "t"}, "events": [],
        })
    assert len(store) == 4
    assert store.stats["evicted"] == 6
    # evicted traces took their mid-index entries with them
    assert store.by_mid(f"{0:08x}") is None
    assert store.by_mid(f"{9:08x}") == f"{9:032x}"
    assert len(store.traces(100)) == 4
    store.clear()
    assert len(store) == 0 and store.spans() == []


# ----------------------------------------------------- local pipeline


def test_local_publish_spans_from_window_record():
    b, _ = _fanout_broker(_cfg(rate=1.0), n_subs=3)
    counts = b.publish_many(
        [Message(topic="t/1", payload=b"x") for _ in range(4)]
    )
    assert counts == [3, 3, 3, 3]
    spans = b.lifecycle.store.spans()
    assert len(spans) == 4  # one span per sampled message
    for s in spans:
        assert s["name"] == "message.publish"
        assert s["parent_id"] is None
        assert s["attrs"]["deliveries"] == 3
        assert s["attrs"]["n_clients"] == 3
        assert s["attrs"]["path"] == "host"
        assert s["end_ns"] > s["start_ns"]
        # stage events come from the EXISTING WindowRecord timestamps
        names = {e["name"] for e in s["events"]}
        assert {"stage.expand", "stage.deliver", "stage.flush"} <= names
        # spans carry ids and scalars only — never the message body
        assert "payload" not in json.dumps(s)
    # queryable by message id
    mid = spans[0]["mid"]
    assert b.lifecycle.store.by_mid(mid) == spans[0]["trace_id"]
    # distinct messages get distinct traces
    assert len({s["trace_id"] for s in spans}) == 4


def test_spans_emitted_with_profiler_disabled():
    cfg = _cfg(rate=1.0)
    cfg.profiler.enable = False
    b, _ = _fanout_broker(cfg, n_subs=1)
    assert b.publish_many([Message(topic="t/1")]) == [1]
    (span,) = b.lifecycle.store.spans()
    assert span["end_ns"] >= span["start_ns"] > 0
    assert span["events"] == []  # no flight record, no stage events


def test_topic_filter_pins_flow_at_rate_zero():
    b, _ = _fanout_broker(_cfg(rate=0.0, filters=["fleet/+/temp"]),
                          n_subs=1, flt="#")
    b.publish_many([
        Message(topic="fleet/v1/temp"),
        Message(topic="other/x"),
    ])
    spans = b.lifecycle.store.spans()
    assert [s["attrs"]["topic"] for s in spans] == ["fleet/v1/temp"]


def test_slow_subs_entry_links_trace_id():
    cfg = _cfg(rate=1.0)
    cfg.slow_subs.threshold_ms = 1.0
    b, _ = _fanout_broker(cfg, n_subs=1)
    stale = Message(topic="t/slow", timestamp=time.time() - 5.0)
    b.publish_many([stale])
    (entry,) = b.slow_subs.top()
    assert entry["topic"] == "t/slow"
    tid = entry["trace_id"]
    assert tid and b.lifecycle.store.get(tid)


def test_runtime_configure_flips_active():
    b, _ = _fanout_broker(_cfg(enable=False, rate=0.0), n_subs=1)
    assert not b.lifecycle.active
    b.publish_many([Message(topic="t/1")])
    assert b.lifecycle.store.spans() == []
    b.lifecycle.configure(enable=True, sample_rate=1.0)
    assert b.lifecycle.active
    b.publish_many([Message(topic="t/1")])
    assert len(b.lifecycle.store.spans()) == 1
    # rate back to 0: still ACTIVE (adopts upstream contexts) but no
    # fresh sampling
    b.lifecycle.configure(sample_rate=0.0)
    assert b.lifecycle.active and not b.lifecycle.sampler.active
    b.publish_many([Message(topic="t/1")])
    assert len(b.lifecycle.store.spans()) == 1
    b.lifecycle.configure(enable=False)
    assert not b.lifecycle.active


# ------------------------------------- unsampled hot path: zero cost


def _world_wires(cfg):
    """Deterministic multi-window fan-out run; returns per-connection
    wire bytes + delivery counts (the byte-identity referee)."""
    b, chans = _fanout_broker(cfg, n_subs=6, flt="t/#", qos=1)
    counts = []
    ts = 1.0e9  # fixed stamps: identical expiry/slow-sub math
    for w in range(4):
        counts.append(b.publish_many([
            Message(
                topic=f"t/{i}", qos=i % 3, retain=(i % 4 == 0),
                payload=bytes([w, i]) * (i + 1), from_client="pub",
                timestamp=ts,
                properties=(
                    {"user_property": [("app", "v")]} if i % 2 else {}
                ),
            )
            for i in range(8)
        ]))
    return b, counts, {cid: b"".join(ch.writes)
                       for cid, ch in chans.items()}


def test_rate_zero_is_byte_identical_and_stores_nothing():
    """Satellite: sampling OFF (enable=True, rate=0) must be
    byte-identical on every connection's wire vs. the tracer disabled
    outright, stamp no per-message context objects, and add zero trace
    store entries."""
    b_off, counts_off, wires_off = _world_wires(_cfg(enable=False))
    b_zero, counts_zero, wires_zero = _world_wires(
        _cfg(enable=True, rate=0.0)
    )
    assert counts_off == counts_zero
    assert wires_off == wires_zero
    for b in (b_off, b_zero):
        assert b.lifecycle.store.spans() == []
        assert len(b.lifecycle.store) == 0
    # and rate=1 still delivers the SAME bytes (context rides broker-
    # internal state, never the subscriber wire)
    _b1, counts_one, wires_one = _world_wires(_cfg(enable=True, rate=1.0))
    assert counts_off == counts_one
    assert wires_off == wires_one


def test_unsampled_messages_carry_no_context_objects():
    b, _ = _fanout_broker(_cfg(enable=True, rate=0.0), n_subs=1)
    msgs = [Message(topic=f"t/{i}") for i in range(16)]
    b.publish_many(msgs)
    assert all(getattr(m, "_trace_ctx", None) is None for m in msgs)
    # enabled+sampled stamps exactly one context per message
    b2, _ = _fanout_broker(_cfg(enable=True, rate=1.0), n_subs=1)
    msgs2 = [Message(topic=f"t/{i}") for i in range(4)]
    b2.publish_many(msgs2)
    assert all(m._trace_ctx is not None for m in msgs2)


def test_unsampled_overhead_within_noise_fanout_256():
    """Paired A/B at fanout-256 (PR 4's pattern): tracing enabled with
    rate=0 vs. disabled, interleaved runs, compare medians.  The
    unsampled path adds one bool + one attribute probe per window, so
    the bound is generous to stay robust on loaded CI boxes — the real
    referee for exact cost is the byte-identity + zero-allocation
    tests above."""
    import statistics

    def build(cfg):
        return _fanout_broker(cfg, n_subs=256, flt="t/#", qos=0)[0]

    base = build(_cfg(enable=False))
    traced = build(_cfg(enable=True, rate=0.0))
    msgs = [Message(topic="t/x", payload=b"p" * 64) for _ in range(16)]

    def one(b):
        t0 = time.perf_counter()
        b.publish_many(list(msgs))
        return time.perf_counter() - t0

    one(base), one(traced)  # warm both paths (encoder pools, caches)
    a, t = [], []
    for _ in range(7):  # interleaved: shared box noise hits both
        a.append(one(base))
        t.append(one(traced))
    assert statistics.median(t) <= statistics.median(a) * 1.5, (a, t)


# -------------------------------------------------- cluster-hop trace

FAST = dict(heartbeat_interval=0.05, down_after=0.25,
            flush_interval=0.002)


async def _start_node(name, seeds=(), rate=1.0):
    cfg = BrokerConfig()
    cfg.listeners[0].port = 0
    cfg.node_name = name
    cfg.tracing.enable = True
    cfg.tracing.sample_rate = rate
    cfg.tracing.seed = 3
    srv = BrokerServer(cfg)
    await srv.start()
    node = ClusterNode(name, srv.broker, **FAST)
    await node.start(seeds=list(seeds))
    return srv, node


async def _settle(check, timeout=5.0):
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if check():
            return True
        await asyncio.sleep(0.05)
    return False


def _hop_trace(name_a="nodeA", name_b="nodeB"):
    """Publish on A, deliver via cluster forward on B; returns both
    stores' spans after the hop settles."""

    async def t():
        s1, n1 = await _start_node(name_a)
        s2, n2 = await _start_node(
            name_b, seeds=[(name_a, "127.0.0.1", n1.port)]
        )
        try:
            sub = TestClient(s2.listeners[0].port, "subB")
            await sub.connect()
            await sub.subscribe("fleet/+/temp", qos=1)
            assert await _settle(
                lambda: n1.routes.nodes_for("fleet/+/temp") == {name_b}
            )
            pub = TestClient(s1.listeners[0].port, "pubA")
            await pub.connect()
            await pub.publish("fleet/v1/temp", b"22C", qos=1)
            m = await sub.recv_publish(timeout=5)
            assert m.payload == b"22C"
            # the internal carrier never reaches the subscriber wire
            assert TRACE_PROP not in str(m.properties)
            assert await _settle(
                lambda: any(
                    s["name"] == "message.dispatch"
                    for s in s2.broker.lifecycle.store.spans()
                )
            )
            await sub.disconnect()
            await pub.disconnect()
            return (s1.broker.lifecycle.store.spans(),
                    s2.broker.lifecycle.store.spans())
        finally:
            await n2.stop()
            await s2.stop()
            await n1.stop()
            await s1.stop()

    return run(t())


def test_cluster_hop_yields_one_connected_trace():
    """THE acceptance criterion: a publish on node A delivered via
    cluster forward on node B is ONE trace — B's dispatch span parents
    to A's forward span — queryable by trace id and message id on both
    sides."""
    a_spans, b_spans = _hop_trace()
    pub = [s for s in a_spans if s["name"] == "message.publish"]
    fwd = [s for s in a_spans if s["name"] == "message.forward"]
    disp = [s for s in b_spans if s["name"] == "message.dispatch"]
    assert pub and fwd and disp
    tid = pub[0]["trace_id"]
    assert fwd[0]["trace_id"] == tid and disp[0]["trace_id"] == tid
    # the connected-parentage chain: publish -> forward -> dispatch
    assert fwd[0]["parent_id"] == pub[0]["span_id"]
    assert disp[0]["parent_id"] == fwd[0]["span_id"]
    assert fwd[0]["attrs"]["ok"] is True
    assert fwd[0]["attrs"]["target"] == "nodeB"
    assert disp[0]["attrs"]["deliveries"] == 1
    # every span closed; same mid end to end
    for s in a_spans + b_spans:
        assert s["end_ns"] > 0
    assert disp[0]["mid"] == pub[0]["mid"]


def test_merged_perfetto_timeline_processes_and_flow():
    """Merged multi-node Perfetto export: both nodes as DISTINCT
    processes (explicit process_name metadata), the hop linked by a
    flow event pair, and every event timeline-valid."""
    a_spans, b_spans = _hop_trace()
    merged = chrome_trace(a_spans + b_spans)
    events = merged["traceEvents"]
    procs = {
        e["pid"]: e["args"]["name"]
        for e in events if e["name"] == "process_name"
    }
    assert len(procs) == 2
    assert {"emqx_tpu nodeA", "emqx_tpu nodeB"} == set(procs.values())
    for e in events:
        assert "ph" in e and "pid" in e and "tid" in e
        if e["ph"] in ("X", "i", "s", "f"):
            assert e["ts"] >= 0
        if e["ph"] == "X":
            assert e["dur"] > 0
    flows = [e for e in events if e["ph"] in ("s", "f")]
    assert len(flows) == 2
    s_ev = next(e for e in flows if e["ph"] == "s")
    f_ev = next(e for e in flows if e["ph"] == "f")
    assert s_ev["id"] == f_ev["id"]
    assert s_ev["pid"] != f_ev["pid"]  # the hop crosses processes


def test_multicore_worker_hop_same_trace_shape():
    """The multicore worker hop rides the SAME inter-node transport
    (workers cluster over loopback), so worker-labeled nodes produce
    the identical connected-trace + per-worker process tracks."""
    a_spans, b_spans = _hop_trace("worker0", "worker1")
    fwd = [s for s in a_spans if s["name"] == "message.forward"]
    disp = [s for s in b_spans if s["name"] == "message.dispatch"]
    assert disp[0]["parent_id"] == fwd[0]["span_id"]
    merged = chrome_trace(a_spans + b_spans)
    procs = {
        e["args"]["name"]
        for e in merged["traceEvents"] if e["name"] == "process_name"
    }
    assert procs == {"emqx_tpu worker0", "emqx_tpu worker1"}


def test_multicore_worker_configs_carry_tracing_and_api_ports():
    from emqx_tpu.broker.multicore import worker_configs

    cfgs = worker_configs(
        3, 1883,
        base_config={"api": {"enable": True}},
        tracing={"enable": True, "sample_rate": 0.05, "seed": 1},
    )
    api_ports = set()
    for i, cfg in enumerate(cfgs):
        assert cfg["tracing"] == {
            "enable": True, "sample_rate": 0.05, "seed": 1,
        }
        assert cfg["node_name"] == f"worker{i}"
        assert cfg["api"]["enable"] is True
        api_ports.add(cfg["api"]["port"])
    # every worker gets its OWN api port (they cannot share one)
    assert len(api_ports) == 3
    # and the tracing dict round-trips through the typed config
    from emqx_tpu.config import ConfigHandler

    handler = ConfigHandler.from_dict(cfgs[0])
    assert handler.root.tracing.enable is True
    assert handler.root.tracing.sample_rate == 0.05


# ----------------------------------------------------- link-drop chaos


def test_link_forward_drop_closes_traces_and_bounds_store():
    """Satellite chaos test: with the cluster.link.forward failpoint
    injecting drops, sampled traces on the publisher still CLOSE (the
    link.forward span ends on the drop path with ok=False and the
    failpoint fire attached), the bounded store never leaks, and no
    span holds message payload bytes."""
    from emqx_tpu.cluster_link import LinkServer

    cfg = _cfg(rate=1.0, store_max=16)
    b, _ = _fanout_broker(cfg, n_subs=1)
    server = LinkServer(b, "east", allowed={"west"})
    server.start()
    server.extern_routes["west"] = {"fleet/#"}
    payload = b"SECRET-PAYLOAD-BYTES" * 10
    try:
        failpoints.configure(
            "cluster.link.forward", "drop", prob=0.5, seed=11
        )
        for i in range(40):
            b.publish(Message(topic=f"fleet/{i}", payload=payload,
                              from_client="pub"))
        spans = b.lifecycle.store.spans()
        link = [s for s in spans if s["name"] == "link.forward"]
        dropped = [s for s in link if s["attrs"]["ok"] is False]
        sent = [s for s in link if s["attrs"]["ok"] is True]
        assert dropped and sent  # prob=0.5 seed=11: both outcomes
        for s in link:
            assert s["end_ns"] > 0  # every forward span CLOSED
        assert any(
            s["attrs"].get("detail") == "failpoint drop" for s in dropped
        )
        # store stays bounded under chaos (whole-trace eviction)
        assert len(b.lifecycle.store) <= 16
        # spans never hold message bodies alive
        assert b"SECRET" not in json.dumps(spans).encode()
    finally:
        failpoints.clear()
        server.stop()
    # the publisher-side publish spans closed too (local delivery)
    pubs = [s for s in b.lifecycle.store.spans()
            if s["name"] == "message.publish"]
    assert pubs and all(s["end_ns"] > 0 for s in pubs)


def test_link_wrap_carries_context_end_to_end():
    """The $LINK wrapper's trace field round-trips: the importing
    broker adopts the context (as a remote parent) and its local
    dispatch joins the SAME trace, parented to the link.forward
    span."""
    from emqx_tpu.cluster_link import _unwrap, _wrap

    src = Message(topic="fleet/1", payload=b"x", from_client="c")
    wrapped = _wrap(src, "east", trace=encode_ctx("a" * 32, "b" * 16))
    inner = _unwrap(wrapped)
    assert inner.headers["trace_ctx"] == encode_ctx("a" * 32, "b" * 16)
    assert inner.headers["cluster_origin"] == "east"
    # no trace field -> no header (sampling off adds nothing)
    assert "trace_ctx" not in _unwrap(_wrap(src, "east")).headers
    # importing broker ingress: same trace, parent = link.forward span
    b, _ = _fanout_broker(_cfg(rate=0.0), n_subs=1, flt="fleet/#")
    b.publish(inner)
    (span,) = b.lifecycle.store.spans()
    assert span["trace_id"] == "a" * 32
    assert span["parent_id"] == "b" * 16
    # a link import is a full local PUBLISH on the importing cluster
    # (hooks/retain run, unlike a node-forward's dispatch-only path),
    # so it keeps the publish span name — with the remote parent
    assert span["name"] == "message.publish"


def test_orphan_wires_strip_trace_carrier():
    """The quorum-orphan path stores wire dicts that later restore
    STRAIGHT into session mqueues (no broker ingress to strip the
    carrier) — strip_wire_trace_ctx must remove exactly the trace
    pair, tuple- or list-shaped, leaving foreign properties alone."""
    from emqx_tpu.cluster.node import msg_to_wire, strip_wire_trace_ctx
    from emqx_tpu.tracecontext import LifecycleTracer, TraceContext

    class _Cfg:
        enable, sample_rate, topic_filters = True, 1.0, ()
        store_max, seed = 16, 1

    lc = LifecycleTracer(_Cfg(), node="n")
    msg = Message(topic="t/1", payload=b"x",
                  properties={"user_property": [("app", "v")]})
    clone = lc.forward_copy(
        msg, TraceContext("a" * 32, "b" * 16), "peer"
    )
    wires = [msg_to_wire(clone), msg_to_wire(msg)]
    assert TRACE_PROP in json.dumps(wires)
    strip_wire_trace_ctx(wires)
    assert TRACE_PROP not in json.dumps(wires)
    # the foreign user property survived on both wires
    for w in wires:
        assert ["app", "v"] in [
            list(p) for p in w["properties"]["user_property"]
        ]


def test_failpoint_fires_attach_as_span_events():
    """A seam that fires INSIDE the window (the link-forward tap runs
    in the publish hook fold) lands on the sampled message's span as a
    ``failpoint.*`` event — chaos runs attribute an anomalous window
    to its fault without log correlation."""
    from emqx_tpu.cluster_link import LinkServer

    b, _ = _fanout_broker(_cfg(rate=1.0), n_subs=1)
    server = LinkServer(b, "east", allowed={"west"})
    server.start()
    server.extern_routes["west"] = {"t/#"}
    failpoints.configure("cluster.link.forward", "drop")
    try:
        b.publish(Message(topic="t/1", from_client="pub"))
    finally:
        failpoints.clear()
        server.stop()
    pub = [s for s in b.lifecycle.store.spans()
           if s["name"] == "message.publish"]
    fp = [e for s in pub for e in s["events"]
          if e["name"] == "failpoint.cluster.link.forward"]
    assert fp and fp[0]["attrs"]["action"] == "drop"


# ----------------------------------------------------- REST + ctl


async def _api_server(tmp_path):
    import tempfile

    cfg = _cfg(rate=1.0)
    cfg.listeners[0].port = 0
    cfg.api.enable = True
    cfg.api.port = 0
    cfg.api.data_dir = tempfile.mkdtemp(dir=str(tmp_path))
    srv = BrokerServer(cfg)
    await srv.start()
    return srv


def test_rest_tracing_surface(tmp_path):
    async def t():
        from api_helper import auth_session

        srv = await _api_server(tmp_path)
        try:
            port = srv.listeners[0].port
            sub = TestClient(port, "s1")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            pub = TestClient(port, "p1")
            await pub.connect()
            await pub.publish("t/hello", b"hi", qos=1)
            await sub.recv_publish()
            await asyncio.sleep(0.05)

            http, api = await auth_session(srv)
            async with http:
                async with http.get(api + "/api/v5/tracing") as r:
                    info = await r.json()
                    assert info["active"] and info["sample_rate"] == 1.0
                async with http.get(
                    api + "/api/v5/tracing/traces"
                ) as r:
                    traces = (await r.json())["data"]
                    assert traces and traces[0]["topic"] == "t/hello"
                tid = traces[0]["trace_id"]
                async with http.get(
                    api + f"/api/v5/tracing/traces/{tid}"
                ) as r:
                    spans = (await r.json())["spans"]
                    assert spans[0]["trace_id"] == tid
                mid = spans[0]["mid"]
                # lookup by MESSAGE id resolves to the same trace
                async with http.get(
                    api + f"/api/v5/tracing/messages/{mid}"
                ) as r:
                    assert (await r.json())["trace_id"] == tid
                async with http.get(
                    api + "/api/v5/tracing/messages/feedbeef"
                ) as r:
                    assert r.status == 404
                # perfetto export of the store
                async with http.get(
                    api + f"/api/v5/tracing/trace?trace_id={tid}"
                ) as r:
                    trace = await r.json()
                    assert any(
                        e["name"] == "message.publish"
                        for e in trace["traceEvents"]
                    )
                # raw span dump (the multi-node merge feed)
                async with http.get(
                    api + "/api/v5/tracing/spans"
                ) as r:
                    dump = await r.json()
                    assert dump["node"] and dump["data"]
                # runtime sampler update
                async with http.put(
                    api + "/api/v5/tracing",
                    json={"sample_rate": 0.0,
                          "topic_filters": ["dbg/#"]},
                ) as r:
                    info = await r.json()
                    assert info["sample_rate"] == 0.0
                    assert info["topic_filters"] == ["dbg/#"]
                    assert info["active"]  # filters keep it live
                async with http.put(
                    api + "/api/v5/tracing", json={"sample_rate": 7}
                ) as r:
                    assert r.status == 400
                # clear
                async with http.delete(api + "/api/v5/tracing") as r:
                    assert r.status == 204
                async with http.get(
                    api + "/api/v5/tracing/traces"
                ) as r:
                    assert (await r.json())["data"] == []
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await srv.stop()

    run(t())


def test_ctl_tracing_roundtrip(tmp_path):
    """Black-box ctl: status + traces + perfetto export through the
    real CLI subprocess against a live broker."""
    import subprocess
    import sys

    async def t():
        srv = await _api_server(tmp_path)
        try:
            port = srv.listeners[0].port
            sub = TestClient(port, "s1")
            await sub.connect()
            await sub.subscribe("t/#", qos=1)
            pub = TestClient(port, "p1")
            await pub.connect()
            await pub.publish("t/cli", b"x", qos=1)
            await sub.recv_publish()
            await asyncio.sleep(0.05)
            api = f"http://127.0.0.1:{srv.api.port}"

            def ctl(*args):
                out = subprocess.run(
                    [sys.executable, "-m", "emqx_tpu.ctl",
                     "--api", api, *args],
                    capture_output=True, text=True, timeout=30,
                    cwd="/root/repo",
                )
                assert out.returncode == 0, out.stderr
                return out.stdout

            loop = asyncio.get_running_loop()
            status = await loop.run_in_executor(
                None, ctl, "tracing", "status"
            )
            assert "ACTIVE" in status
            traces = await loop.run_in_executor(
                None, ctl, "tracing", "traces"
            )
            assert "t/cli" in traces
            out_path = str(tmp_path / "merged.json")
            perfetto = await loop.run_in_executor(
                None, ctl, "tracing", "perfetto", out_path
            )
            assert "wrote" in perfetto
            with open(out_path) as f:
                merged = json.load(f)
            assert any(
                e["name"] == "message.publish"
                for e in merged["traceEvents"]
            )
            await sub.disconnect()
            await pub.disconnect()
        finally:
            await srv.stop()

    run(t())


# ------------------------------------------- profiler process tracks


def test_profiler_trace_names_its_process():
    """Satellite: the window profiler's Chrome export carries explicit
    process metadata (real pid + node label), so merged multi-node /
    multi-worker profiler timelines keep each broker's tracks in its
    own process group instead of interleaving into one implicit row."""
    import os

    cfg = _cfg(rate=0.0)
    cfg.node_name = "workerX"
    b, _ = _fanout_broker(cfg, n_subs=1)
    b.publish_many([Message(topic="t/1")])
    trace = b.profiler.chrome_trace()
    procs = [e for e in trace["traceEvents"]
             if e["name"] == "process_name"]
    assert len(procs) == 1
    assert "workerX" in procs[0]["args"]["name"]
    assert procs[0]["pid"] == os.getpid()
    assert any(
        e["name"] == "process_sort_index" for e in trace["traceEvents"]
    )
    # every event rides the explicit pid
    assert all(e["pid"] == os.getpid() for e in trace["traceEvents"])
