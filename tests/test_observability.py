"""Window-pipeline profiler: histogram buckets/merge/percentiles,
flight-recorder ring + Chrome trace export, Prometheus text-format
round-trip of the full scrape, OTLP histogram datapoints, the $SYS
profiler summary, slow-subs expiry, and the PERF401 single-encode
gate over the instrumented dispatch path."""

import asyncio
import json
import re
import tempfile
import time

# auto-cleaned parent for per-test mgmt stores
_MGMT_TMP = tempfile.TemporaryDirectory(prefix="emqx-obs-")

import aiohttp

from emqx_tpu.broker.broker import Broker
from emqx_tpu.broker.channel import Channel
from emqx_tpu.broker.listener import BrokerServer
from emqx_tpu.broker.session import SubOpts
from emqx_tpu.codec import mqtt as C
from emqx_tpu.config import BrokerConfig, ListenerConfig
from emqx_tpu.message import Message
from emqx_tpu.observability import (
    BOUNDS, Histogram, HistogramSnapshot, N_BUCKETS, Profiler, prom_name,
)
from api_helper import auth_session


def run(coro):
    return asyncio.run(coro)


# ------------------------------------------------------------ histogram


def test_histogram_bucket_boundaries():
    """Bucket i holds integer values with bit_length i: v <= 2^i - 1
    and v > 2^(i-1) - 1 — the O(1) index must agree with the exported
    ``le`` bounds exactly."""
    h = Histogram()
    for v in (0, 1, 2, 3, 4, 7, 8, 1023, 1024):
        h.record(v)
    snap = h.snapshot()
    assert snap.count == 9
    assert snap.counts[0] == 1  # v=0
    assert snap.counts[1] == 1  # v=1
    assert snap.counts[2] == 2  # v=2,3
    assert snap.counts[3] == 2  # v=4,7
    assert snap.counts[4] == 1  # v=8
    assert snap.counts[10] == 1  # v=1023 <= 2^10-1
    assert snap.counts[11] == 1  # v=1024
    # every recorded value v in bucket i satisfies v <= BOUNDS[i]
    for i in range(N_BUCKETS - 1):
        assert BOUNDS[i] == (1 << i) - 1


def test_histogram_overflow_lands_in_last_bucket():
    h = Histogram()
    h.record(float(1 << 40))  # way past the largest finite bound
    h.record(-5.0)  # negative clamps into bucket 0, never IndexError
    snap = h.snapshot()
    assert snap.counts[N_BUCKETS - 1] == 1
    assert snap.counts[0] == 1


def test_histogram_merge():
    a, b = Histogram(), Histogram()
    for v in (1, 10, 100):
        a.record(v)
    for v in (1000, 10000):
        b.record(v)
    m = a.snapshot().merge(b.snapshot())
    assert m.count == 5
    assert m.sum == 1 + 10 + 100 + 1000 + 10000
    assert sum(m.counts) == 5
    # merge is per-bucket: the merged p99 sees b's large values
    assert m.percentile(99) > a.snapshot().percentile(99)


def test_histogram_percentiles_monotone_and_bounded():
    h = Histogram()
    h.record_many([100.0] * 50 + [1000.0] * 50)
    snap = h.snapshot()
    p50, p99 = snap.percentile(50), snap.percentile(99)
    assert p50 <= p99
    # 100 lives in (63, 127], 1000 in (511, 1023]
    assert 63 <= p50 <= 127
    assert 511 <= p99 <= 1023
    # empty histogram: 0.0, not a crash
    assert Histogram().snapshot().percentile(99) == 0.0


def test_histogram_record_many_bulk():
    h = Histogram()
    h.record_many([float(i) for i in range(64)])
    snap = h.snapshot()
    assert snap.count == 64
    assert snap.sum == sum(range(64))


# ------------------------------------------------------ flight recorder


def test_flight_recorder_ring_wraparound():
    prof = Profiler(ring_size=4)
    for i in range(10):
        rec = prof.begin(i + 1)
        rec.lap("prepare")
        prof.commit(rec)
    wins = prof.windows(100)
    assert len(wins) == 4  # ring capacity, not total committed
    assert [w["seq"] for w in wins] == [10, 9, 8, 7]  # newest first
    assert prof.summary()["prepare"]["count"] == 10  # histograms keep all


def test_window_record_spans_are_contiguous():
    prof = Profiler()
    rec = prof.begin(3, source="publish")
    rec.lap("prepare")
    time.sleep(0.002)
    rec.lap("expand")
    prof.commit(rec)
    spans = rec.spans
    assert [s[0] for s in spans] == ["prepare", "expand"]
    # offsets are monotone and each span starts where the prior ended
    assert spans[0][1] == 0.0 or spans[0][1] >= 0.0
    assert abs((spans[0][1] + spans[0][2]) - spans[1][1]) < 1e-9
    assert spans[1][2] >= 0.002


def test_profiler_disabled_is_noop():
    prof = Profiler(enabled=False)
    assert prof.begin(5) is None
    prof.stage("tokenize", 0.001)  # no-op, no crash
    prof.event("xla_compile", 0.5)
    assert prof.windows() == []
    assert prof.events() == []
    assert all(s.count == 0 for s in prof.snapshots().values())


def _fanout_broker(n_subs=3):
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    b = Broker(config=cfg)
    sink = []
    for i in range(n_subs):
        ch = Channel(b, send=lambda pkts: sink.append(pkts),
                     close=lambda r: None)
        cid = f"c{i}"
        session, _ = b.cm.open_session(True, cid, ch)
        session.subscribe("t/#", SubOpts(qos=0))
        b.subscribe(cid, "t/#", SubOpts(qos=0))
    return b, sink


def test_dispatch_window_records_stages_and_sizes():
    b, _sink = _fanout_broker(n_subs=3)
    counts = b.publish_many(
        [Message(topic="t/1", payload=b"x") for _ in range(4)]
    )
    assert counts == [3, 3, 3, 3]
    (win,) = b.profiler.windows(1)
    assert win["source"] == "publish"
    assert win["n_msgs"] == 4
    assert win["n_deliveries"] == 12
    assert win["n_clients"] == 3
    assert win["path"] == "host"
    assert win["breaker_open"] is False
    for stage in ("prepare", "match_submit", "match_wait",
                  "dispatch_wait", "expand", "deliver", "flush"):
        assert stage in win["stages_us"], win["stages_us"]
    assert len(win["e2e_ms"]) == 4  # one e2e sample per routed message
    # engine-internal tokenize stage histogrammed too
    assert b.profiler.summary()["tokenize"]["count"] >= 1


def test_profiler_disabled_broker_still_dispatches():
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.profiler.enable = False
    b = Broker(config=cfg)
    ch = Channel(b, send=lambda pkts: None, close=lambda r: None)
    session, _ = b.cm.open_session(True, "c0", ch)
    session.subscribe("t/#", SubOpts(qos=0))
    b.subscribe("c0", "t/#", SubOpts(qos=0))
    assert b.publish_many([Message(topic="t/1", payload=b"x")]) == [1]
    assert b.profiler.windows() == []


# --------------------------------------------------------- chrome trace


def test_chrome_trace_export_is_valid():
    """The flight-recorder export must be loadable Chrome trace-event
    JSON: required keys on every event, strictly paired + properly
    nested B/E events per track, monotone non-decreasing timestamps
    within each track, durations on X events."""
    b, _sink = _fanout_broker()
    for _ in range(3):
        b.publish_many([Message(topic="t/x", payload=b"p")] * 2)
    b.profiler.event("xla_compile", 0.25, nodes=4096)  # engine track
    trace = b.profiler.chrome_trace()
    events = trace["traceEvents"]
    assert events, "empty trace"
    assert json.loads(json.dumps(trace))  # JSON-serializable
    per_tid = {}
    for ev in events:
        assert ev["ph"] in ("B", "E", "X", "M"), ev
        assert "pid" in ev and "tid" in ev and "name" in ev
        if ev["ph"] == "M":
            continue
        assert isinstance(ev["ts"], (int, float))
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
            continue
        per_tid.setdefault(ev["tid"], []).append(ev)
    assert per_tid, "no B/E span events"
    for tid, evs in per_tid.items():
        stack = []
        last_ts = -1.0
        for ev in evs:
            assert ev["ts"] >= last_ts, f"ts not monotone on tid {tid}"
            last_ts = ev["ts"]
            if ev["ph"] == "B":
                stack.append(ev["name"])
            else:
                assert stack, f"E without B on tid {tid}: {ev}"
                assert stack.pop() == ev["name"], "mismatched B/E pair"
        assert not stack, f"unclosed B events on tid {tid}: {stack}"


def test_chrome_trace_window_limit():
    prof = Profiler(ring_size=16)
    for i in range(8):
        rec = prof.begin(1)
        rec.lap("prepare")
        prof.commit(rec)
    limited = prof.chrome_trace(limit=2)
    spans = [e for e in limited["traceEvents"] if e["ph"] == "B"]
    assert len(spans) == 2  # one "prepare" B per window, 2 windows


def test_flight_record_labels_device_fallback_honestly():
    """A device fault the engine degrades INTERNALLY (submit- or
    finish-side) must label the window 'host-fallback', never 'dev'
    or plain 'host' — the recorder exists to diagnose exactly these
    windows."""
    cfg = BrokerConfig()
    cfg.engine.use_device = True
    b = Broker(config=cfg)
    eng = b.router.engine
    for i in range(4):
        b.subscribe(f"w{i}", f"f/{i}/+", SubOpts(qos=0))
    eng.rebuild()  # device automaton exists -> device path chosen
    eng.breaker_threshold = 10_000  # keep the breaker closed

    # submit-side fault: kernel dispatch raises, window serves on host
    orig = eng._flat_dispatch
    eng._flat_dispatch = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("injected dispatch fault")
    )
    try:
        b.publish_many([Message(topic="f/0/x", payload=b"p")])
    finally:
        eng._flat_dispatch = orig
    (win,) = b.profiler.windows(1)
    assert win["path"] == "host-fallback", win

    # finish-side fault: result transfer raises inside the engine
    orig_res = eng._flat_result
    eng._flat_result = lambda tok: (_ for _ in ()).throw(
        RuntimeError("injected result fault")
    )
    try:
        b.publish_many([Message(topic="f/1/x", payload=b"p")])
    finally:
        eng._flat_result = orig_res
    (win,) = b.profiler.windows(1)
    assert win["path"] == "host-fallback", win

    # healthy window on the same broker: labeled dev
    b.publish_many([Message(topic="f/2/x", payload=b"p")])
    (win,) = b.profiler.windows(1)
    assert win["path"] == "dev", win


# ------------------------------------------- engine lifecycle events


def test_engine_fold_and_device_put_events():
    """A synchronous delta fold on the CPU backend must record
    delta_fold + device_put events (with transfer bytes) through the
    engine's profiler hook."""
    from emqx_tpu.engine import MatchEngine

    eng = MatchEngine(use_device=True, delta_aut_threshold=4,
                      rebuild_threshold=10_000)
    prof = Profiler()
    eng.profiler = prof
    eng._fold_async = False  # deterministic: fold inline on insert
    eng.insert_many([(f"a/{i}/+", i) for i in range(8)])
    kinds = {e["kind"] for e in prof.events()}
    assert "delta_fold" in kinds, prof.events()
    assert "device_put" in kinds
    dp = next(e for e in prof.events() if e["kind"] == "device_put")
    assert dp["bytes"] > 0
    assert prof.summary()["engine_delta_fold"]["count"] >= 1
    # and the stats() gauge surface is numeric-exportable
    stats = eng.stats()
    for key in ("base", "delta", "folded", "residual", "deep",
                "auto_host_windows", "auto_dev_windows",
                "breaker_open", "breaker_trips"):
        assert key in stats


# ------------------------------------------------- prometheus scrape


def _make_server(**cfg_kw):
    cfg = BrokerConfig()
    cfg.listeners = [ListenerConfig(port=0)]
    cfg.api.enable = True
    cfg.api.data_dir = tempfile.mkdtemp(dir=_MGMT_TMP.name)
    cfg.api.port = 0
    cfg.engine.use_device = False
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    return BrokerServer(cfg)


_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"  # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(?:,[a-zA-Z_][a-zA-Z0-9_]*='
    r'"[^"]*")*\})?'  # optional labels
    r" (-?[0-9.eE+-]+|NaN|\+Inf|-Inf)$"  # value
)


def _parse_prometheus(text):
    """Strict text-format parse: returns (types, samples) and raises
    AssertionError on anything a real parser would reject."""
    types = {}
    samples = []  # (family-resolved name, labels-str, value)
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert _NAME_RE.match(name), f"bad family name {name!r}"
            assert kind in ("counter", "gauge", "histogram", "summary")
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = kind
            continue
        if line.startswith("# HELP "):
            parts = line.split(" ", 3)
            assert len(parts) >= 3 and _NAME_RE.match(parts[2])
            continue
        assert not line.startswith("#"), f"unknown comment {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line {line!r}"
        samples.append((m.group(1), m.group(2) or "", m.group(3)))
    return types, samples


def test_prometheus_full_scrape_round_trips():
    async def t():
        srv = _make_server()
        await srv.start()
        # traffic through the REAL pipeline so histograms have samples
        b = srv.broker
        ch = Channel(b, send=lambda pkts: None, close=lambda r: None)
        session, _ = b.cm.open_session(True, "pm", ch)
        session.subscribe("p/#", SubOpts(qos=0))
        b.subscribe("pm", "p/#", SubOpts(qos=0))
        for _ in range(3):
            b.publish_many([Message(topic="p/t", payload=b"x")] * 4)
        # an extra-registry counter with a name that NEEDS sanitizing
        b.metrics.inc("5xx.responses-total")
        async with aiohttp.ClientSession() as http:
            async with http.get(
                f"http://127.0.0.1:{srv.api.port}/metrics"
            ) as r:
                assert r.status == 200
                text = await r.text()
        await srv.stop()
        return text

    text = run(t())
    types, samples = _parse_prometheus(text)
    # the pre-existing exposition contract
    assert types["emqx_messages_received"] == "counter"
    assert types["emqx_connections_count"] == "gauge"
    # sanitized: no family may start with a digit or carry a '-'
    assert "emqx__5xx_responses_total" in types or any(
        n.startswith("emqx_") and "5xx" in n for n in types
    )
    for name in types:
        assert _NAME_RE.match(name)
    # engine gauge surface (satellite: MatchEngine.stats() exported)
    for g in ("emqx_engine_base", "emqx_engine_delta",
              "emqx_engine_residual", "emqx_engine_deep",
              "emqx_engine_auto_host_windows",
              "emqx_engine_breaker_open"):
        assert types.get(g) == "gauge", f"missing engine gauge {g}"
    # >= 4 histogram families with _bucket/_sum/_count samples
    hist_fams = [n for n, k in types.items() if k == "histogram"]
    assert len(hist_fams) >= 4, hist_fams
    by_name = {}
    for name, labels, value in samples:
        by_name.setdefault(name, []).append((labels, value))
    sampled = 0
    for fam in hist_fams:
        buckets = by_name.get(fam + "_bucket", [])
        assert buckets, f"{fam}: no _bucket samples"
        # cumulative, ordered le, +Inf last and == _count
        les, counts = [], []
        for labels, value in buckets:
            m = re.search(r'le="([^"]+)"', labels)
            assert m, f"{fam}: bucket without le label"
            les.append(m.group(1))
            counts.append(int(value))
        assert les[-1] == "+Inf"
        finite = [float(le) for le in les[:-1]]
        assert finite == sorted(finite)
        assert counts == sorted(counts), f"{fam}: not cumulative"
        (_, count_v), = by_name[fam + "_count"]
        assert int(count_v) == counts[-1]
        assert fam + "_sum" in by_name
        sampled += int(count_v)
    assert sampled > 0, "no histogram recorded any sample"


def test_prometheus_one_type_line_per_family():
    async def t():
        srv = _make_server()
        await srv.start()
        async with aiohttp.ClientSession() as http:
            async with http.get(
                f"http://127.0.0.1:{srv.api.port}/metrics"
            ) as r:
                text = await r.text()
        await srv.stop()
        return text

    text = run(t())
    type_names = [
        line.split(" ", 3)[2]
        for line in text.splitlines()
        if line.startswith("# TYPE ")
    ]
    assert len(type_names) == len(set(type_names))
    help_names = [
        line.split(" ", 3)[2]
        for line in text.splitlines()
        if line.startswith("# HELP ")
    ]
    assert len(help_names) == len(set(help_names))


def test_prom_name_sanitizer():
    assert prom_name("emqx_a.b") == "emqx_a_b"
    assert prom_name("5xx_total") == "_5xx_total"
    assert prom_name("a-b/c d") == "a_b_c_d"
    assert _NAME_RE.match(prom_name(""))
    assert _NAME_RE.match(prom_name("emqx_ok_name"))


# ------------------------------------------------- profiler REST + ctl


def test_profiler_rest_endpoints():
    async def t():
        srv = _make_server()
        await srv.start()
        http, api = await auth_session(srv)
        async with http:
            # publish through the BATCHER (the server wires one): the
            # flight record must carry source=batcher + batch_wait
            async with http.post(
                api + "/api/v5/publish",
                json={"topic": "nope/t", "payload": "x"},
            ) as r:
                assert r.status == 200
            async with http.get(api + "/api/v5/profiler") as r:
                assert r.status == 200
                body = await r.json()
            assert body["enabled"] is True
            assert "histograms_us" in body and "engine" in body
            assert body["windows"], "no window records after a publish"
            win = body["windows"][0]
            assert win["source"] == "batcher"
            assert "batch_wait" in win["stages_us"]
            assert "prepare" in win["stages_us"]
            # trace endpoint returns Chrome trace JSON
            async with http.get(api + "/api/v5/profiler/trace") as r:
                assert r.status == 200
                trace = await r.json()
            assert any(
                e["ph"] == "B" for e in trace["traceEvents"]
            )
            async with http.get(
                api + "/api/v5/profiler/trace?windows=bogus"
            ) as r:
                assert r.status == 400
            # reset clears histograms + ring
            async with http.delete(api + "/api/v5/profiler") as r:
                assert r.status == 204
            async with http.get(api + "/api/v5/profiler") as r:
                body = await r.json()
            assert body["windows"] == []
        await srv.stop()

    run(t())


def test_ctl_profiler_commands(tmp_path):
    import subprocess
    import sys as _sys

    async def t():
        srv = _make_server()
        await srv.start()
        b = srv.broker
        ch = Channel(b, send=lambda pkts: None, close=lambda r: None)
        session, _ = b.cm.open_session(True, "cc", ch)
        session.subscribe("c/#", SubOpts(qos=0))
        b.subscribe("cc", "c/#", SubOpts(qos=0))
        b.publish_many([Message(topic="c/t", payload=b"x")] * 3)
        api = f"http://127.0.0.1:{srv.api.port}"

        def ctl(*args):
            out = subprocess.run(
                [_sys.executable, "-m", "emqx_tpu.ctl", "--api", api,
                 *args],
                capture_output=True, text=True, timeout=30,
                cwd="/root/repo",
            )
            assert out.returncode == 0, out.stderr
            return out.stdout

        loop = asyncio.get_running_loop()
        summary = await loop.run_in_executor(None, ctl, "profiler")
        assert "profiler on" in summary
        assert "deliver" in summary and "engine:" in summary
        trace_path = str(tmp_path / "trace.json")
        traced = await loop.run_in_executor(
            None, ctl, "profiler", "trace", trace_path
        )
        assert "perfetto" in traced
        with open(trace_path) as f:
            trace = json.load(f)
        assert trace["traceEvents"]
        reset = await loop.run_in_executor(
            None, ctl, "profiler", "reset"
        )
        assert "reset" in reset
        await srv.stop()

    run(t())


# ------------------------------------------------------- OTLP + $SYS


def test_otlp_payload_has_histograms_and_engine_gauges():
    from emqx_tpu.otel import OtelExporter

    b, _sink = _fanout_broker()
    b.publish_many([Message(topic="t/1", payload=b"x")] * 4)
    exp = OtelExporter(b, "http://127.0.0.1:9")  # never contacted
    payload = json.loads(exp.metrics_payload(time.time()))
    metrics = payload["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
    by_name = {m["name"]: m for m in metrics}
    hists = [m for m in metrics if "histogram" in m]
    assert len(hists) >= 4, [m["name"] for m in hists]
    for m in hists:
        (dp,) = m["histogram"]["dataPoints"]
        assert len(dp["bucketCounts"]) == len(dp["explicitBounds"]) + 1
        assert sum(int(c) for c in dp["bucketCounts"]) == int(dp["count"])
        assert m["histogram"]["aggregationTemporality"] == 2
    assert "emqx_engine_base" in by_name
    assert "gauge" in by_name["emqx_engine_base"]
    # float EWMA gauges export as asDouble once measured; absent until
    # then (None is skipped, not exported as 0)
    assert "emqx_engine_breaker_open" in by_name


def test_sys_heartbeat_includes_profiler_summary():
    from emqx_tpu.sys_topics import SysTopics

    b, _sink = _fanout_broker()
    b.publish_many([Message(topic="t/1", payload=b"x")] * 2)
    sys_t = SysTopics(b, node_name="n1")
    msgs = sys_t.heartbeat_messages()
    prof_msgs = [m for m in msgs if m.topic.endswith("/profiler")]
    assert len(prof_msgs) == 1
    body = json.loads(prof_msgs[0].payload)
    assert body["stages_us"]["deliver"]["count"] >= 1
    assert "p99" in body["stages_us"]["deliver"]
    assert "base" in body["engine"]
    # disabled profiler: no $SYS topic (and no stale zeros)
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.profiler.enable = False
    b2 = Broker(config=cfg)
    msgs2 = SysTopics(b2, node_name="n1").heartbeat_messages()
    assert not any(m.topic.endswith("/profiler") for m in msgs2)


# ------------------------------------------------- slow subs / config


def test_slow_subs_entry_expiry():
    from emqx_tpu.ops_guard import SlowSubs

    ss = SlowSubs(top_k=5, threshold_ms=10.0, expire_interval=30.0)
    ss.record("c1", "t", 50.0)
    ss.record("c2", "t", 80.0)
    now = time.time()
    assert ss.tick(now + 10) == 0
    assert len(ss.top()) == 2
    assert ss.tick(now + 31) == 2
    assert ss.top() == []
    # expire_interval <= 0 disables expiry
    ss2 = SlowSubs(expire_interval=0.0, threshold_ms=1.0)
    ss2.record("c", "t", 5.0)
    assert ss2.tick(time.time() + 1e6) == 0
    assert len(ss2.top()) == 1


def test_slow_subs_config_wiring():
    cfg = BrokerConfig()
    cfg.engine.use_device = False
    cfg.slow_subs.threshold_ms = 123.0
    cfg.slow_subs.top_k = 7
    cfg.slow_subs.expire_interval = 42.0
    b = Broker(config=cfg)
    assert b.slow_subs.threshold_ms == 123.0
    assert b.slow_subs.top_k == 7
    assert b.slow_subs.expire_interval == 42.0
    cfg2 = BrokerConfig()
    cfg2.engine.use_device = False
    cfg2.slow_subs.enable = False
    b2 = Broker(config=cfg2)
    b2.slow_subs.record("c", "t", 1e9)  # below an inf threshold
    assert b2.slow_subs.top() == []


def test_flapping_deque_window_trim():
    from emqx_tpu.ops_guard import BannedList, FlappingDetector

    banned = BannedList()
    fl = FlappingDetector(banned, max_count=3, window=60.0)
    assert not fl.on_disconnect("c1")
    assert not fl.on_disconnect("c1")
    assert fl.on_disconnect("c1")  # third strike inside the window
    assert banned.is_banned(clientid="c1")
    # hits outside the window are trimmed (deque popleft path)
    fl2 = FlappingDetector(banned, max_count=3, window=0.0)
    for _ in range(10):
        assert not fl2.on_disconnect("c2")  # every hit expires at once


# ------------------------------------------------- perf gate (PERF401)


def test_instrumented_dispatch_passes_perf_gate():
    """The profiler threading through _dispatch_window/_deliver_run/
    Session.deliver must not have introduced per-subscriber encode
    calls: the PERF401 single-encode gate stays clean over the
    instrumented hot path."""
    from tools.brokerlint import run_lint

    findings = [
        f for f in run_lint(["emqx_tpu/broker", "emqx_tpu/engine.py"])
        if f.rule == "PERF401"
    ]
    assert not findings, "\n".join(f.render() for f in findings)


def test_profiler_overhead_window_shape():
    """Overhead smoke: the always-on profiler adds a BOUNDED number of
    record objects per window (one WindowRecord + spans), and a 256-
    fanout window commits with all stages present — the accounting
    that backs the <5% dispatch-throughput acceptance bound."""
    b, sink = _fanout_broker(n_subs=64)
    n_before = len(b.profiler.windows(1000))
    for _ in range(5):
        b.publish_many([Message(topic="t/1", payload=b"x" * 64)] * 8)
    wins = b.profiler.windows(1000)
    assert len(wins) == n_before + 5  # exactly one record per window
    w = wins[0]
    assert w["n_deliveries"] == 8 * 64
    assert len(w["stages_us"]) <= 12  # spans bounded, not per-delivery
    # one transport write per subscriber per window (corked flush
    # unchanged by instrumentation)
    assert len(sink) >= 64
